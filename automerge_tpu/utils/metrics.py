"""Observability: structured span tracing, labeled metrics, stall watchdog.

The reference has no instrumentation at all (SURVEY.md §5 — no logging, no
timers anywhere in src/). The rebuild's first pass was a bare counter/timer
singleton; this module grows it into the subsystem the production posture
needs (ROADMAP north star; the r5 config-8 timeout died inside
`sharded_service.hashes` with nothing but a thread dump to explain it):

- a structured **span tracer**: nested spans per thread, a ring buffer of
  recently completed spans, wall-clock timing plus a device-side
  `jax.profiler.TraceAnnotation` (device time shows up in xprof captures
  when a profiler trace is active), all thread-safe;
- **cross-replica trace context**: every span carries a `trace_id`/`span_id`
  pair; a span opened under `adopt_context(ctx)` joins the remote trace
  instead of starting a fresh one, so a sync round's spans stitch across
  replicas (sync/connection.py stamps the context onto outgoing protocol
  messages, docs/OBSERVABILITY.md "Trace propagation");
  `merge_timeline({replica: spans})` folds per-replica span buffers into
  one causally-ordered timeline;
- **labeled counters / gauges / histograms**
  (`bump("engine_kernels_dispatched", kernel="apply_doc")`) with
  bounded-cardinality label values;
- a **stall watchdog** (`watchdog(name, budget_s)`): a background timer that
  logs a one-line diagnosis with every thread's active span stack when a
  traced region overruns its budget — the region keeps running, the
  operator gets the "where is it stuck" line the r5 hang never produced;
- **exporters**: `snapshot()` (flat, `json.dumps`-safe; bench.py embeds it
  in BENCH_*.json) and `prometheus()` (text exposition).

Metric naming scheme (docs/OBSERVABILITY.md)
--------------------------------------------
Canonical names are `<layer>_<noun>_<verb>`, where layer is one of:

- `core`   — interpretive/bulk host apply (core/opset.py, core/bulkload.py)
- `engine` — docs-major device engine + adaptive router (engine/)
- `rows`   — docs-minor streaming engine (engine/resident_rows.py)
- `sync`   — sync services, wire protocol, transports, log archive (sync/)
- `obs`    — this subsystem's own signals (watchdog / budget overruns)

Counters may end in a plural verb (`sync_frames_received`); span names are
`<layer>_<region>` and export as `<name>_s` (seconds) + `<name>_count`.
Every name used by the package is declared in the registries below — a
collection-time lint (tests/test_metrics_lint.py) rejects unregistered
literals. The pre-rename alias names the first release of the scheme kept
readable have been dropped; snapshots now carry canonical names only.

Usage:
    from automerge_tpu import metrics
    metrics.bump("sync_frames_received")
    with metrics.trace("rows_round_apply"):
        ...
    with metrics.watchdog("sync_hashes_fanout", budget_s=120.0):
        h = svc.hashes()
    metrics.snapshot()      # flat JSON-able dict (canonical keys only;
                            # plus ONE nested "perf" section when the
                            # performance plane recorded anything —
                            # numeric-delta consumers must skip dicts)
    metrics.prometheus()    # text exposition
    with metrics.adopt_context({"tid": ..., "sid": ...}):   # join a
        ...                 # remote peer's trace (sync/connection.py)
    metrics.merge_timeline({"a": spans_a, "b": spans_b})
"""

from __future__ import annotations

import binascii
import logging
import os
import re
import threading
import time
from collections import deque
from contextlib import contextmanager

log = logging.getLogger("automerge_tpu.metrics")

# How many completed spans the ring buffer retains. Small enough to never
# matter for memory, large enough to cover a whole sync round's nesting on
# a sharded fleet node.
SPAN_RING = 512

# ---------------------------------------------------------------------------
# metric name registries (the naming contract; see module docstring)

COUNTERS: dict[str, str] = {
    # core — host interpretive / bulk apply
    "core_changes_applied": "changes admitted by the host apply paths",
    "core_ops_applied": "ops inside admitted changes (host apply paths)",
    "core_diffs_emitted": "diff records produced by the interpretive apply",
    "core_bulk_fallbacks": "bulk builds that fell back to interpretive",
    # text span plane (core/textspans.py + engine/span_kernels.py):
    # batched text merging — span splices instead of per-op RGA inserts
    "sync_text_batches_merged":
        "change batches admitted through the span-granularity text plane "
        "(core/textspans.py)",
    "sync_text_spans_spliced":
        "contiguous element runs spliced into the visible-order index "
        "(one splice per run, not per op)",
    "sync_text_ops_sequential":
        "text ops from changes covering the local frontier (no "
        "concurrency checks paid)",
    "sync_text_ops_concurrent":
        "text ops replayed with per-pair concurrency checks (the only "
        "ops whose cost scales with divergence)",
    "engine_span_tables_packed":
        "span tables packed into the [ROWS, S_pad] lane layout "
        "(engine/pack.pack_spans)",
    "engine_span_merges":
        "batched span-table merge dispatches (engine/span_kernels.py) "
        "{backend=host|device}",
    # move plane (core/moves.py + engine/move_kernels.py): one-op
    # reparenting with deterministic cycle resolution (ISSUE 15)
    "core_moves_applied":
        "move ops admitted through the per-op interpretive path",
    "sync_move_batches_merged":
        "change batches admitted through the batched move plane (one "
        "winner+cycle resolution per touched realm)",
    "sync_move_ops_sequential":
        "move ops from changes covering the local frontier (classified "
        "at admission via admit_change_header)",
    "sync_move_ops_concurrent":
        "move ops concurrent with the local frontier (the only moves "
        "that can conflict or cycle)",
    "sync_move_cycles_dropped":
        "move candidates dropped by deterministic cycle resolution "
        "(losers become no-ops; the element falls back to its next "
        "candidate or base position)",
    "engine_move_tables_packed":
        "move-resolution realms packed into the node/candidate lane "
        "layout (engine/pack.pack_moves)",
    "engine_move_resolves":
        "batched move cycle-resolution dispatches "
        "(engine/move_kernels.py) {backend=host|device}",
    # engine — docs-major device engine + adaptive router
    "engine_docs_reconciled": "documents reconciled by the batched kernel",
    "engine_ops_reconciled": "ops reconciled by the batched kernel",
    "engine_bulk_built": "host-path documents built by the bulk loader",
    "engine_kernels_dispatched": "jitted kernel dispatches {kernel=...}",
    "engine_kernels_retraced":
        "jit compile-cache misses (retrace/compile) {kernel=...}",
    # dispatch-efficiency ledger (engine/dispatchledger.py — r17)
    "engine_dispatch_calls":
        "routed kernel calls recorded by the dispatch-efficiency ledger "
        "{family=...,backend=host|device} (engine/dispatchledger.py)",
    "engine_dispatch_ambient":
        "jitted dispatches observed with no routed call scope open "
        "(engine/dispatchledger.note_jit; counted so nothing escapes "
        "the amplification account)",
    # megabatch plane (engine/dispatch.py plan_round — r20)
    "engine_megabatch_rounds":
        "flush rounds executed through the fused multi-doc megabatch "
        "path (engine/dispatch.py apply_round_adaptive)",
    "engine_megabatch_docs":
        "documents whose reconcile rode a fused megabatch dispatch "
        "(engine/dispatch.py; lane sharing across independent docs)",
    "engine_megabatch_fallbacks":
        "rounds the cost model routed back to the per-doc path after "
        "planning buckets (engine/dispatch.py plan_round; padded wire "
        "would have exceeded the classic gather)",
    # rows — docs-minor streaming engine
    "rows_rounds_batched": "round frames through the vectorized admission",
    "rows_rounds_fallback": "round frames through the per-round fallback",
    "rows_dispatch_failed": "device dispatches that failed (host recovered)",
    "rows_log_rebuilt": "engine rebuilds replayed from the admitted log",
    "rows_engine_poisoned": "engines poisoned by an unrecoverable failure",
    "rows_horizon_truncated": "log prefixes truncated below the horizon",
    "rows_docs_compacted": "documents compacted in place",
    # sync — services, wire protocol, transports, log archive
    "sync_frames_sent": "columnar change frames sent",
    "sync_frames_received": "columnar change frames received",
    "sync_frame_bytes_sent": "payload bytes of columnar frames sent",
    "sync_frame_bytes_received": "payload bytes of columnar frames received",
    "sync_msgs_sent": "protocol messages written to a TCP transport",
    "sync_msgs_received": "protocol messages read from a TCP transport",
    "sync_wire_bytes_sent": "framed bytes written to a TCP transport",
    "sync_wire_bytes_received": "framed bytes read from a TCP transport",
    "sync_ops_ingested": "ops admitted through service round flushes",
    "sync_rounds_flushed": "coalesced service round flushes",
    # epoch-batched ingestion (sync/epochs.py): the lock-free admission
    # path and its snapshot read plane (sync/service.py)
    "sync_ops_buffered":
        "ingress ops appended to the epoch ingestion buffer "
        "(sync/epochs.py; no service lock on this path)",
    "sync_epochs_sealed":
        "ingestion epochs sealed into coalesced rounds (sync/epochs.py)",
    "sync_reads_cached":
        "clock_of/missing_changes served lock-free from the per-doc "
        "snapshot read cache (sync/service.py)",
    "sync_archive_cold_reads": "lagging-peer reads served from the archive",
    "sync_changes_archived": "changes moved into the log archive",
    "sync_archive_tail_repaired": "torn archive tails repaired on open",
    "sync_archive_tail_skipped": "torn archive tails skipped on read",
    "sync_archive_reads_cached":
        "archive cold reads served from the parsed-prefix cache "
        "(sync/logarchive.py; active-segment entries keyed by file "
        "size+mtime)",
    # segmented archive + snapshot shipping (r15 storage tier:
    # sync/logarchive.py segments, sync/snapshots.py images,
    # sync/service.py bootstrap — docs/INTERNALS.md "The storage tier")
    "sync_segments_sealed":
        "active archive segments sealed (rotated immutable + manifest "
        "entry committed) (sync/logarchive.py)",
    "sync_segments_adopted":
        "orphan sealed segments re-adopted into a manifest after a "
        "crash between the seal rename and the manifest commit "
        "(sync/logarchive.py)",
    "sync_segment_reads_cached":
        "sealed-segment reads served from the immutable per-segment "
        "parse cache — entries never invalidate, only LRU-evict "
        "(sync/logarchive.py)",
    "sync_segments_skipped":
        "sealed segments skipped by a clock-bounded tail read — the "
        "manifest clock range proved every record covered "
        "(sync/logarchive.py read_since; the segmented bootstrap/"
        "cold-read win)",
    "sync_snapshot_writes":
        "compacted doc-state snapshot images committed "
        "(sync/snapshots.py; write-temp-then-rename)",
    "sync_snapshot_bytes_written":
        "bytes of committed snapshot images (sync/snapshots.py)",
    "sync_snapshot_loads":
        "snapshot images decoded from disk (sync/snapshots.py; "
        "cache misses — cached loads don't re-decode)",
    "sync_snapshot_frames_sent":
        "snapshot images shipped to fresh joiners over the sync wire "
        "(sync/connection.py; the empty-clock subscribe answer)",
    "sync_snapshot_bytes_sent":
        "payload bytes of snapshot images shipped (sync/connection.py)",
    "sync_snapshot_frames_received":
        "snapshot images applied from the sync wire (sync/service.py "
        "apply_snapshot)",
    "sync_snapshot_bytes_received":
        "payload bytes of snapshot images applied (sync/service.py)",
    "sync_bootstrap_docs":
        "docs snapshot-booted: compacted image admitted + covered "
        "clock seeded (engine seed_clock; local and wire bootstraps)",
    "sync_bootstrap_fallbacks":
        "bootstraps that fell back to full-history replay/serving — "
        "no usable image, non-covering tail, or a non-empty doc "
        "(sync/service.py; disclosed so a silent snapshot regression "
        "shows up in ops metrics, not just in wall time)",
    "sync_metrics_pulls": "remote metrics snapshots served to peers",
    # lockprof (utils/lockprof.py): the contention plane. The `_total`
    # suffix is deliberate prometheus idiom for this one counter (it
    # exports as-is; the exporter adds no suffix to counters).
    "sync_lock_contended_total":
        "lock acquisitions that found the lock held {lock=...} "
        "(utils/lockprof.py)",
    "sync_ops_sampled":
        "ingress ops sampled by the op-lifecycle plane (utils/oplag.py; "
        "1 of every AMTPU_OPLAG_SAMPLE admissions)",
    "sync_audit_pulls": "convergence-audit digest requests served to peers",
    "sync_audits_completed":
        "convergence-audit rounds completed against a peer's digests",
    "sync_divergences_detected":
        "convergence-audit divergence reports (shard+doc isolated)",
    # transport loss accounting (sync/tcp.py): a message the sender gave
    # up on before the socket write — send failure or an injected fault
    # (utils/chaos.py). The fleet doctor reads this as the frame-loss
    # root-cause signal (perf/doctor.py).
    "sync_frames_dropped":
        "outgoing change-bearing messages dropped before the socket "
        "write (sync/tcp.py; transport failure or injected fault)",
    # per-connection traffic accounting (sync/connection.py + sync/tcp.py
    # + sync/docledger.py): protocol messages split by frame KIND
    # (advert/changes/frame/audit/metrics — frames.msg_kind), and the
    # delivered-change usefulness split the redundancy ratio reads off.
    # Per-DOC splits live in the bounded docledger snapshot section, not
    # in label space (doc ids are unbounded cardinality).
    "sync_conn_msgs_sent":
        "protocol messages sent by a Connection {kind=clock|changes|"
        "frame|audit:*|metrics:*} (sync/connection.py; transport-"
        "agnostic — counts in-process and TCP sends alike)",
    "sync_conn_msgs_received":
        "protocol messages received by a Connection {kind=...} "
        "(sync/connection.py)",
    "sync_conn_bytes_sent":
        "framed wire bytes written, split by message kind {kind=...} "
        "(sync/tcp.py send_frame; exact post-encode sizes)",
    "sync_conn_bytes_received":
        "framed wire bytes read, split by message kind {kind=...} "
        "(sync/tcp.py recv_frame)",
    "sync_conn_changes_delivered":
        "received changes that advanced (or will advance) the local "
        "frontier — NOT already covered by the local clock at delivery "
        "(sync/connection.py; the redundancy ratio's denominator)",
    "sync_conn_changes_duplicate":
        "received changes already covered by the local clock at "
        "delivery — wasted wire work the engine dedups away "
        "(sync/connection.py; the redundancy ratio's numerator)",
    # subscription layer (sync/connection.py InterestSet) + relay fabric
    # (sync/relay.py) + SLO-coupled admission shedding (sync/epochs.py
    # IngressGovernor): interest-based partial replication's control and
    # disclosure plane (docs/INTERNALS.md "Interest-based partial
    # replication")
    "sync_sub_adds":
        "interest entries (doc ids + prefixes) added to a peer's "
        "subscription via {'sub': ...} messages (sync/connection.py)",
    "sync_sub_removes":
        "interest entries removed from a peer's subscription "
        "(sync/connection.py; removed docs degrade to advert-only)",
    "sync_sub_backfills":
        "targeted late-subscribe backfills served — missing-suffix "
        "pushes through the missing_changes snapshot read plane, never "
        "a full-DocSet replay (sync/connection.py)",
    "sync_sub_frames_suppressed":
        "gossip events where interest filtering suppressed the "
        "change-frame channel toward a peer (sync/connection.py; the "
        "wire partial replication saves)",
    "sync_sub_resubscribes":
        "full-interest replays after a re-home (Connection."
        "resubscribe; sync/relay.py adoption path)",
    "sync_relay_sub_deduped":
        "upstream subscription entries a relay hub suppressed because "
        "its merged cover already held them (sync/relay.py; the "
        "dedup-upward half of the fan-out tree)",
    "sync_shed_delayed":
        "low-priority epoch-path ingresses delayed by the admission "
        "governor during a sustained converge-SLO breach "
        "(sync/epochs.IngressGovernor mode='delay')",
    "sync_shed_dropped":
        "low-priority ingresses shed (IngressShedError) by the "
        "admission governor (sync/epochs.IngressGovernor mode='shed')",
    "sync_shed_transitions":
        "admission-governor state transitions (open <-> shedding) "
        "(sync/epochs.IngressGovernor; each also a shed_transition "
        "flight-recorder event)",
    # tenant attribution plane (sync/tenantledger.py — r18): the
    # governor's shed/delay decisions split per tenant {tenant=...}
    # (bounded: the ledger tracks at most MAX_TENANTS identities)
    "sync_tenant_shed_delayed":
        "governor-delayed low-priority ingresses per tenant "
        "{tenant=...} (sync/tenantledger.py note_shed)",
    "sync_tenant_shed_dropped":
        "governor-shed (IngressShedError) ingresses per tenant "
        "{tenant=...} (sync/tenantledger.py note_shed)",
    "sync_tenant_overflow":
        "distinct tenant identities folded into the _overflow bucket "
        "past MAX_TENANTS (sync/tenantledger.py; disclosed truncation)",
    # per-doc convergence ledger (sync/docledger.py)
    "obs_doc_evictions":
        "tracked docs evicted from the ledger's top-K table into the "
        "aggregate bucket (sync/docledger.py; bounded-memory policy)",
    # obs — the observability subsystem's own signals
    "obs_watchdog_fired": "watchdog budget overruns {name=...}",
    "obs_budget_exceeded": "trace(budget_s=...) post-hoc overruns {name=...}",
    "obs_flightrec_dumps": "flight-recorder post-mortem dumps {reason=...}",
    # fleet health plane (perf/fleet.py, perf/slo.py, utils/chaos.py)
    "obs_chaos_injected":
        "chaos fault injections fired {fault=slow_apply|lock_hold|"
        "frame_drop|doc_stall|sub_flap|conn_kill|peer_hang|disk_stall|"
        "tenant_storm} (utils/chaos.py; inert unless AMTPU_CHAOS_* set)",
    "obs_fleet_stragglers_flagged":
        "straggler flags raised by the fleet collector {node=...} "
        "(perf/fleet.py; counted on the transition into flagged)",
    "obs_slo_breaches":
        "SLO verdict transitions into breach {slo=...} (perf/slo.py)",
    # remediation plane (perf/remediate.py + sync/tcp.py supervisor —
    # r13): every automated action, withhold, and recovery disclosed
    "obs_remed_actions":
        "remediation actions EXECUTED {action=quarantine|reconnect|"
        "re_bootstrap|governor_escalate|governor_relax} "
        "(perf/remediate.py; dry-run intentions never land here)",
    "obs_remed_skipped":
        "remediation actions withheld by a guardrail {reason=cooldown|"
        "budget|quorum|dry_run} (perf/remediate.py)",
    "obs_remed_recovered":
        "remediation episodes closed with the fleet back to SLO-green "
        "(perf/remediate.py; each also a remed_recovered event with "
        "the measured MTTR)",
    "obs_flightrec_suppressed":
        "flight-recorder dumps suppressed by the per-trigger-class "
        "cooldown {reason=...} (utils/flightrec.py; a dump storm is "
        "throttled, never unbounded)",
    # lock-order sanitizer (utils/locksan.py — r18): runtime checks of
    # the committed locks_manifest.json hierarchy, AMTPU_LOCKSAN=1
    "obs_locksan_order_violations_total":
        "lock acquisitions inverting a committed locks_manifest.json "
        "order edge {lock=...} (utils/locksan.py; each also a "
        "locksan_violation event; AMTPU_LOCKSAN=2 additionally raises)",
    "obs_locksan_long_holds_total":
        "outermost lock holds exceeding AMTPU_LOCKSAN_HOLD_S released "
        "while other threads were blocked on the same lock {lock=...} "
        "(utils/locksan.py; the r5 stall shape caught live)",
    "sync_reconnect_attempts":
        "socket (re)connection attempts by the reconnect supervisor "
        "(sync/tcp.SupervisedTcpClient; includes the refused ones)",
    "sync_reconnects":
        "successful reconnections after a transport death — generation "
        ">= 2 links brought back by the supervisor (sync/tcp.py)",
    "sync_reconnect_idle_kicks":
        "reconnects forced by the inbound-idle detector — a live socket "
        "whose PROCESSED inbound activity went quiet past "
        "idle_reconnect_s (sync/tcp.SupervisedTcpClient; the peer_hang "
        "fault's detection path)",
}

GAUGES: dict[str, str] = {
    "core_queue_depth": "causal queue depth after the latest apply batch",
    "core_queue_bytes":
        "approximate host bytes held by the causal queue {estimate}",
    # perfscope compile telemetry (utils/perfscope.py): XLA's answer per
    # compiled kernel variant, refreshed on each one-time analysis
    "engine_kernel_flops": "XLA cost_analysis flops {kernel=...}",
    "engine_kernel_bytes_accessed":
        "XLA cost_analysis bytes accessed {kernel=...}",
    "engine_kernel_hbm_bytes":
        "XLA memory_analysis section bytes {kernel=...,section="
        "argument|output|temp|alias|code}",
    # resident-state footprints (the memory picture a post-mortem needs)
    "engine_resident_bytes": "docs-major resident-state footprint (bytes)",
    "rows_resident_bytes": "rows-engine resident-state footprint (bytes)",
    "sync_shard_resident_bytes":
        "per-shard resident-state footprint {shard=...}",
    "sync_hashes_clean_shards":
        "shards served from the hash cache on the last fleet hash read",
    "sync_hashes_dirty_shards":
        "shards re-read (dirty since epoch) on the last fleet hash read",
    "obs_live_arrays_bytes": "sampled live jax-array footprint (bytes)",
    "obs_live_arrays_peak_bytes":
        "high-water mark of the live jax-array footprint since reset",
    # oplag (utils/oplag.py): rolling per-stage lag percentiles over the
    # sampled-op reservoir (refreshed every few samples; the exact
    # reservoir lives in the snapshot's nested "oplag" section)
    "sync_op_lag_p50_s":
        "rolling median sampled-op lag {stage=...} (utils/oplag.py)",
    "sync_op_lag_p99_s":
        "rolling p99 sampled-op lag {stage=...} (utils/oplag.py)",
    # fleet health plane (perf/fleet.py): per-node rollups the collector
    # refreshes every scrape tick — node labels are bounded by fleet size
    "obs_fleet_nodes_scraped":
        "nodes with a fresh snapshot on the last collector tick "
        "(perf/fleet.py)",
    "obs_fleet_scrape_age_s":
        "seconds since a node's last snapshot arrived {node=...} "
        "(perf/fleet.py)",
    "obs_fleet_converge_p99_s":
        "per-node converge-stage p99 at the last scrape {node=...} "
        "(perf/fleet.py)",
    "obs_fleet_round_flush_s":
        "per-node mean round-flush seconds over the scrape window "
        "{node=...} (perf/fleet.py)",
    "obs_fleet_straggler_score":
        "robust deviation score vs the fleet median {node=...} "
        "(perf/fleet.py; >= K sigma flags the node)",
    "obs_slo_ok":
        "current SLO verdict {slo=...} (perf/slo.py; 1 ok / 0 breach)",
    # per-doc convergence ledger (sync/docledger.py): doc-population
    # percentiles over the tracked top-K set, refreshed whenever the
    # ledger snapshot section is exported (no doc-id labels — unbounded)
    "obs_doc_tracked":
        "docs tracked exactly by the convergence ledger "
        "(sync/docledger.py; bounded at its top-K)",
    "obs_doc_lagging":
        "tracked docs currently behind some peer's advertised frontier "
        "(sync/docledger.py)",
    "obs_doc_converge_lag_p50_s":
        "median per-doc convergence lag over tracked docs, seconds "
        "behind the most-advanced peer advert (sync/docledger.py)",
    "obs_doc_converge_lag_p99_s":
        "p99 per-doc convergence lag over tracked docs "
        "(sync/docledger.py)",
    "obs_doc_converge_lag_max_s":
        "max per-doc convergence lag over tracked docs "
        "(sync/docledger.py)",
    "obs_doc_redundancy_ratio":
        "duplicate deliveries / useful deliveries since reset "
        "(sync/docledger.py; the full-mesh fan-out waste partial "
        "replication exists to shrink)",
    # subscription / relay / shedding plane (r12)
    "sync_relay_cover_docs":
        "entries (doc ids + prefixes) in a relay hub's merged "
        "downstream cover set {node=...} (sync/relay.py)",
    "sync_shed_active":
        "admission governor state: 1 while low-priority ingress is "
        "being delayed/shed, else 0 (sync/epochs.IngressGovernor)",
    # dispatch-efficiency ledger (engine/dispatchledger.py — r17):
    # window rollups over the per-round ring, refreshed on the fold
    # cadence (no kernel/bucket labels here — the full attribution lives
    # in the nested "dispatchledger" snapshot section)
    "obs_dispatch_amplification":
        "dispatches per dirty doc over the round window — the number "
        "fleet megabatching must divide (engine/dispatchledger.py)",
    "obs_dispatch_pad_waste_pct":
        "padded-lane fraction computed for nobody, percent, over the "
        "round window (engine/dispatchledger.py)",
    "obs_dispatch_per_round":
        "mean routed dispatches per flush round over the window "
        "(engine/dispatchledger.py)",
    "obs_dispatch_rounds_tracked":
        "flush rounds currently held in the dispatch ledger's bounded "
        "ring (engine/dispatchledger.py)",
    # tenant attribution plane (sync/tenantledger.py — r18): refreshed
    # on the ledger's mutation path every GAUGE_REFRESH records; tenant
    # labels are bounded by the ledger's MAX_TENANTS table
    "obs_tenant_tracked":
        "tenant identities tracked by the attribution ledger "
        "(sync/tenantledger.py; bounded at MAX_TENANTS)",
    "obs_tenant_ingress_share_pct":
        "tenant's share of all admitted changes {tenant=...} "
        "(sync/tenantledger.py; the tenant_hot doctor evidence)",
    "obs_tenant_converge_lag_p99_s":
        "p99 converge-lag restamp over the tenant's recent sample ring "
        "{tenant=...} (sync/tenantledger.py; the tenant_converge_p99 "
        "SLO family's per-node feed)",
    # trace plane (utils/tracer.py — r19): refreshed on the plane's
    # mutation path every GAUGE_REFRESH completions; stage-level detail
    # lives in the nested "traceplane" snapshot section (no stage or
    # doc labels here)
    "obs_trace_sampled":
        "changes stamped with a trace context at frontend finalize "
        "since reset (utils/tracer.py; the completeness denominator)",
    "obs_trace_completed":
        "traces completed at converged-hash visibility since reset "
        "(utils/tracer.py; stitched cross-process ones included)",
    "obs_trace_inflight":
        "sampled changes currently mid-lifecycle across the awaiting "
        "tables (utils/tracer.py; TTL-expired ones leave as expired)",
    "obs_trace_critical_path_p99_s":
        "p99 end-to-end critical path over the completed-trace ring "
        "(utils/tracer.py; the number ROADMAP #2's megabatching "
        "divides into stages)",
    # megabatch plane (engine/dispatchledger.py window — r20): achieved
    # fused-round occupancy over the ring window, refreshed with the
    # other obs_dispatch_* gauges
    "obs_megabatch_docs_per_dispatch":
        "docs served per fused dispatch over the megabatch rounds in "
        "the ledger window (engine/dispatchledger.py; the achieved "
        "number next to perf dispatch's projection)",
    "obs_megabatch_fill_pct":
        "percent of fused-dispatch doc-lane capacity actually occupied "
        "over the window's megabatch rounds (engine/dispatchledger.py)",
    # remediation plane (perf/remediate.py — r13)
    "obs_remed_quarantined":
        "nodes currently quarantined by the remediation engine "
        "(perf/fleet.py; excluded from straggler scoring, rollups and "
        "SLO membership until unquarantined)",
    "obs_remed_governor_stage":
        "admission-governor escalation ladder stage: 0 open / 1 delay "
        "/ 2 shed (perf/remediate.GovernorLadder)",
}

HISTOGRAMS: dict[str, str] = {
    "sync_round_seconds": "latency of coalesced service round flushes",
    # lockprof (utils/lockprof.py): per-lock contention profile. Named
    # with the `_s` unit suffix (the ISSUE-6 contract names) — they
    # export as `sync_lock_wait_s{lock=...}_{count,sum,min,max}`.
    "sync_lock_wait_s":
        "time spent waiting to acquire an instrumented lock {lock=...}",
    "sync_lock_hold_s":
        "outermost hold time of an instrumented lock {lock=...}",
    # oplag (utils/oplag.py): per-stage lag of sampled ops through the
    # admission -> flush -> wire -> peer-apply -> converged lifecycle
    "sync_op_lag_s":
        "sampled op-lifecycle stage lag {stage=causal_queue|buffer_wait|"
        "queue_wait|pack|dispatch|device_wait|flush|origin_total|wire|"
        "peer_apply|converge} (utils/oplag.py; docs/OBSERVABILITY.md)",
    "sync_commit_wait_s":
        "writer park from epoch-buffer append to its group-commit flush "
        "resolving (sync/epochs.py ticket wait — NOT a lock wait: the "
        "writer holds nothing while parked)",
    "obs_fleet_scrape_s":
        "wall seconds of one fleet-collector scrape tick (perf/fleet.py; "
        "the self-overhead the collector_overhead SLO bounds)",
    "obs_doc_ledger_s":
        "convergence-ledger self-time flushed per snapshot export "
        "(sync/docledger.py; sum/elapsed = the duty-cycle bound the "
        "config-12 perf-check gate holds under 2%)",
    "obs_dispatch_ledger_s":
        "dispatch-ledger self-time flushed per gauge refresh "
        "(engine/dispatchledger.py; sum/elapsed = the duty-cycle bound "
        "the config-17 perf-check gate holds under 2%)",
    "obs_tenant_ledger_s":
        "tenant-ledger self-time flushed per gauge refresh "
        "(sync/tenantledger.py; sum/elapsed = the duty-cycle bound the "
        "config-18 perf-check gate holds under 2%)",
    "obs_trace_ledger_s":
        "trace-plane self-time flushed per gauge refresh "
        "(utils/tracer.py; sum/elapsed = the duty-cycle bound the "
        "config-19 perf-check gate holds under 2%)",
    "obs_remed_tick_s":
        "remediation-engine per-tick wall cost (perf/remediate.py; "
        "p50/interval = the steady-state duty cycle bench config 14 "
        "bounds under 2%)",
    "sync_archive_fsync_s":
        "wall seconds of one storage-tier fsync — archive append, "
        "segment seal, manifest commit, snapshot write "
        "(sync/logarchive.py / sync/snapshots.py; the doctor's "
        "storage_stall evidence and the disk_stall chaos signature)",
    "sync_bootstrap_s":
        "wall seconds of one replica bootstrap — snapshot admission + "
        "clock seed + tail replay, or the full-replay fallback "
        "(sync/service.py bootstrap paths)",
}

SPANS: dict[str, str] = {
    "engine_reconcile": "from-scratch batched encode + reconcile kernel",
    "engine_dispatch": "adaptive-routed batch apply {backend=host|device}",
    "engine_resident_apply": "docs-major resident delta scatter + apply",
    "engine_hashes": "docs-major reconcile / hash read",
    "rows_round_apply": "rows-engine round-frame admission + dispatch",
    "rows_hashes": "rows-engine hash read (the readback barrier)",
    "sync_round_flush": "service coalesced-round flush {shard=...}",
    "sync_hashes": "service hash read, incl. read-triggered flush",
    "sync_hashes_fanout": "sharded service hash fan-out over all shards",
    "sync_msg_send": "one outgoing protocol message (trace-context root)",
    "sync_msg_serve": "serving one received protocol message",
    "sync_snapshot_write":
        "one doc's snapshot write: archived-prefix read + survivor "
        "join + crash-safe image commit (sync/service.write_snapshots)",
    "engine_kernel_compile":
        "attributed jit lower+compile wall time {kernel=...} "
        "(perfscope listener; timer-only, no span records)",
}

# The pre-rename alias names ("changes_applied", "wire_frames_received", …)
# the scheme migration kept readable for one release are GONE: bump()/
# trace() on them now registers as an unknown name and snapshot() emits
# canonical keys only. Kept as an (empty) table so extension code probing
# `metrics.ALIASES` keeps working.
ALIASES: dict[str, str] = {}

REGISTRY: dict[str, str] = {**COUNTERS, **GAUGES, **HISTOGRAMS, **SPANS}


def register(name: str, description: str, kind: str = "counter") -> None:
    """Register an extension metric name (plugins, tests, deployments).
    The collection-time lint accepts any registered name."""
    REGISTRY[name] = description
    {"counter": COUNTERS, "gauge": GAUGES, "histogram": HISTOGRAMS,
     "span": SPANS}[kind][name] = description


def _resolve(name: str) -> str:
    return ALIASES.get(name, name)


def _lk(labels: dict) -> tuple:
    """Canonical hashable label key (sorted (k, str(v)) pairs)."""
    if not labels:
        return ()
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _flat_key(name: str, lk: tuple) -> str:
    if not lk:
        return name
    return name + "{" + ",".join(f"{k}={v}" for k, v in lk) + "}"


def _new_id(nbytes: int) -> str:
    return binascii.hexlify(os.urandom(nbytes)).decode()


# Thread-local adopted trace context: (trace_id, parent_span_id) a remote
# peer shipped with a protocol message. Spans opened while it is set join
# the remote trace instead of starting their own (adopt_context()).
_tls = threading.local()


class _Span:
    __slots__ = ("name", "lk", "t0", "wall", "depth", "parent", "thread",
                 "trace_id", "span_id", "parent_sid", "tags")

    def __init__(self, name, lk, depth, parent, thread):
        self.name = name
        self.lk = lk
        self.t0 = time.perf_counter()
        self.wall = time.time()
        self.depth = depth
        self.parent = parent
        self.thread = thread
        if parent is not None:
            self.trace_id = parent.trace_id
            self.parent_sid = parent.span_id
        else:
            ctx = getattr(_tls, "ctx", None)
            self.trace_id = ctx[0] if ctx else _new_id(8)
            self.parent_sid = ctx[1] if ctx else None
        self.span_id = _new_id(4)
        self.tags = None


class _Metrics:
    """Thread-safe metrics store. Every public mutation takes self.lock —
    the sync/tcp layer calls in from socket reader threads concurrently
    with application threads."""

    def __init__(self):
        self.lock = threading.RLock()
        self.counters: dict[tuple, int] = {}
        self.gauges: dict[tuple, float] = {}
        self.timers: dict[tuple, float] = {}
        self.span_counts: dict[tuple, int] = {}
        # histogram summary: [count, sum, min, max]
        self.hists: dict[tuple, list] = {}
        self.spans: deque = deque(maxlen=SPAN_RING)
        # thread ident -> stack of active _Span (the watchdog's evidence)
        self.active: dict[int, list] = {}
        self.watchdog_events: list[dict] = []

    # -- primitives ---------------------------------------------------------

    def bump(self, _name: str, _n: int = 1, **labels) -> None:
        key = (_resolve(_name), _lk(labels))
        with self.lock:
            self.counters[key] = self.counters.get(key, 0) + _n

    def gauge(self, _name: str, _value: float, **labels) -> None:
        key = (_resolve(_name), _lk(labels))
        with self.lock:
            self.gauges[key] = _value

    def observe(self, _name: str, _value: float, **labels) -> None:
        key = (_resolve(_name), _lk(labels))
        with self.lock:
            h = self.hists.get(key)
            if h is None:
                self.hists[key] = [1, _value, _value, _value]
            else:
                h[0] += 1
                h[1] += _value
                h[2] = min(h[2], _value)
                h[3] = max(h[3], _value)

    def add_time(self, _name: str, _seconds: float, **labels) -> None:
        key = (_resolve(_name), _lk(labels))
        with self.lock:
            self.timers[key] = self.timers.get(key, 0.0) + _seconds

    # -- span stack ---------------------------------------------------------

    def push_span(self, name: str, lk: tuple, tags: dict | None = None
                  ) -> _Span:
        ident = threading.get_ident()
        with self.lock:
            stack = self.active.setdefault(ident, [])
            span = _Span(name, lk, len(stack),
                         stack[-1] if stack else None,
                         threading.current_thread().name)
            if tags:
                span.tags = dict(tags)
            stack.append(span)
        return span

    def pop_span(self, span: _Span, duration: float) -> None:
        ident = threading.get_ident()
        with self.lock:
            stack = self.active.get(ident)
            if stack is not None:
                for i in range(len(stack) - 1, -1, -1):
                    if stack[i] is span:
                        del stack[i]
                        break
                if not stack:
                    del self.active[ident]
            self.timers[(span.name, span.lk)] = (
                self.timers.get((span.name, span.lk), 0.0) + duration)
            ckey = (span.name, span.lk)
            self.span_counts[ckey] = self.span_counts.get(ckey, 0) + 1
            rec = {
                "name": span.name,
                "labels": dict(span.lk),
                "start": span.wall,
                "duration_s": round(duration, 6),
                "depth": span.depth,
                "parent": (span.parent.name
                           if span.parent is not None else None),
                "thread": span.thread,
                "trace_id": span.trace_id,
                "span_id": span.span_id,
                "parent_span_id": span.parent_sid,
            }
            if span.tags:
                rec["tags"] = span.tags
            self.spans.append(rec)

    def span_stacks(self) -> dict[str, list[str]]:
        """Active span stacks for every thread — `{"Thread-3":
        ["sync_round_flush(12.1s)", "rows_hashes(11.8s)"]}`. This is the
        watchdog's one-line diagnosis payload."""
        now = time.perf_counter()
        with self.lock:
            out = {}
            for stack in self.active.values():
                if stack:
                    out[stack[0].thread] = [
                        f"{_flat_key(s.name, s.lk)}({now - s.t0:.2f}s)"
                        for s in stack]
            return out

    # -- exporters ----------------------------------------------------------

    def snapshot(self) -> dict:
        """Flat, json.dumps-safe view: counters as-is, gauges as-is,
        timers as `<name>_s`, histograms as `<name>_{count,sum,min,max}`.
        Labeled series flatten to `name{k=v,...}` keys. Canonical names
        only — the pre-rename alias keys the scheme migration emitted for
        one release are gone."""
        with self.lock:
            out: dict = {}
            for (name, lk), v in self.counters.items():
                out[_flat_key(name, lk)] = v
            for (name, lk), v in self.gauges.items():
                out[_flat_key(name, lk)] = v
            for (name, lk), h in self.hists.items():
                base = _flat_key(name, lk)
                out[base + "_count"] = h[0]
                out[base + "_sum"] = round(h[1], 6)
                out[base + "_min"] = round(h[2], 6)
                out[base + "_max"] = round(h[3], 6)
            for (name, lk), v in self.span_counts.items():
                out[_flat_key(name, lk) + "_count"] = v
            for (name, lk), v in self.timers.items():
                out[_flat_key(name, lk) + "_s"] = round(v, 6)
        return out

    def prometheus(self, prefix: str = "amtpu_") -> str:
        """Prometheus text exposition (0.0.4). Counters export as
        `<prefix><name>`, span/timer totals as
        `<prefix><name>_seconds_total`, histograms as summary-style
        `_count`/`_sum` plus `_min`/`_max` gauges."""
        def san(name):
            return re.sub(r"[^a-zA-Z0-9_:]", "_", name)

        def esc(value):
            return (value.replace("\\", r"\\").replace('"', r'\"')
                    .replace("\n", r"\n"))

        def labelstr(lk):
            if not lk:
                return ""
            return "{" + ",".join(f'{san(k)}="{esc(v)}"'
                                  for k, v in lk) + "}"

        with self.lock:
            counters = sorted(self.counters.items())
            gauges = sorted(self.gauges.items())
            hists = sorted(self.hists.items())
            span_counts = sorted(self.span_counts.items())
            timers = sorted(self.timers.items())
        lines: list[str] = []
        typed: set[str] = set()

        def emit(name, kind, lk, value, help_=None):
            full = prefix + san(name)
            if full not in typed:
                typed.add(full)
                desc = help_ or REGISTRY.get(name)
                if desc:
                    lines.append(f"# HELP {full} {desc}")
                lines.append(f"# TYPE {full} {kind}")
            lines.append(f"{full}{labelstr(lk)} {value}")

        for (name, lk), v in counters:
            emit(name, "counter", lk, v)
        for (name, lk), v in gauges:
            emit(name, "gauge", lk, v)
        for (name, lk), h in hists:
            emit(name + "_count", "counter", lk, h[0],
                 help_=REGISTRY.get(name))
            emit(name + "_sum", "counter", lk, h[1])
            emit(name + "_min", "gauge", lk, h[2])
            emit(name + "_max", "gauge", lk, h[3])
        for (name, lk), v in span_counts:
            emit(name + "_count", "counter", lk, v,
                 help_=REGISTRY.get(name))
        for (name, lk), v in timers:
            emit(name + "_seconds_total", "counter", lk, v,
                 help_=REGISTRY.get(name))
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        with self.lock:
            self.counters.clear()
            self.gauges.clear()
            self.timers.clear()
            self.span_counts.clear()
            self.hists.clear()
            self.spans.clear()
            self.watchdog_events.clear()
            # active spans are NOT cleared: regions currently executing
            # still finish and record into the fresh store


_global = _Metrics()

# ---------------------------------------------------------------------------
# module-level API (the singleton surface every layer imports)


def bump(_name: str, _n: int = 1, **labels) -> None:
    _global.bump(_name, _n, **labels)


def gauge(_name: str, _value: float, **labels) -> None:
    _global.gauge(_name, _value, **labels)


def observe(_name: str, _value: float, **labels) -> None:
    _global.observe(_name, _value, **labels)


def add_time(_name: str, _seconds: float, **labels) -> None:
    _global.add_time(_name, _seconds, **labels)


# Extension snapshot sections: a subsystem that cannot live in utils/
# (the per-doc ledger is sync-layer code) registers a provider here and
# its nested section rides every snapshot() — and therefore every
# metrics-pull answer, flight-recorder dump, and bench config capture —
# without utils importing the owning package. Providers run OUTSIDE the
# metrics lock (they may bump their own gauges), must return a
# json.dumps-clean dict (or None/{} to skip), and must be PURE functions
# of their subsystem's state: no wall-clock reads at export time, so two
# back-to-back snapshots with no traffic in between compare equal.
_section_providers: dict[str, object] = {}


def register_snapshot_section(name: str, provider) -> None:
    """Register (or replace) a nested snapshot section provider.
    `provider()` is called by every snapshot(); a raising provider is
    skipped — telemetry must never take down the caller."""
    _section_providers[name] = provider


def snapshot() -> dict:
    """Flat metrics view plus — when the performance plane has recorded
    anything since the last reset — a nested `"perf"` section
    (utils/perfscope.py: per-kernel compile telemetry, phase rollup,
    memory footprint). The perf attach happens OUTSIDE the metrics lock:
    perfscope has its own lock and the two must never nest."""
    out = _global.snapshot()
    try:
        from . import perfscope
        perf = perfscope.perf_snapshot()
    except Exception:
        perf = None
    if perf:
        out["perf"] = perf
    try:    # the op-lifecycle lag percentiles (same nested-section rule)
        from . import oplag
        lag = oplag.lag_snapshot()
    except Exception:
        lag = None
    if lag:
        out["oplag"] = lag
    for name, provider in list(_section_providers.items()):
        try:
            sec = provider()
        except Exception:
            sec = None
        if sec:
            out[name] = sec
    return out


def prometheus(prefix: str = "amtpu_") -> str:
    return _global.prometheus(prefix=prefix)


def reset() -> None:
    _global.reset()
    try:
        from . import perfscope
        perfscope.reset()
    except Exception:
        pass
    try:
        from . import oplag
        oplag.reset()
    except Exception:
        pass
    # registered section providers observe the reset through their own
    # reset hook, if they installed one (sync/docledger.py: clears every
    # live ledger so a post-reset snapshot() is {} again)
    for hook in list(_section_reset_hooks):
        try:
            hook()
        except Exception:
            pass


_section_reset_hooks: list = []


def register_reset_hook(hook) -> None:
    """Subsystems whose snapshot section must clear on reset() (the
    per-config bench captures depend on it) register a zero-arg hook."""
    if hook not in _section_reset_hooks:
        _section_reset_hooks.append(hook)


def recent_spans() -> list[dict]:
    """Completed spans from the ring buffer, oldest first."""
    with _global.lock:
        return list(_global.spans)


def span_stacks() -> dict[str, list[str]]:
    return _global.span_stacks()


def watchdog_events() -> list[dict]:
    """Diagnoses recorded by fired watchdogs since the last reset()."""
    with _global.lock:
        return list(_global.watchdog_events)


# ---------------------------------------------------------------------------
# node identity (the fleet health plane's scrape naming)

_node_name: str | None = None
_node_name_read = False


def node_name() -> str | None:
    """This process's fleet node label, if any: AMTPU_NODE_NAME (read
    once) or whatever set_node_name() installed. A Connection serving a
    `{"metrics": "pull"}` stamps it on the answer, so a fleet collector
    (perf/fleet.py) names scraped peers by THEIR self-identity instead
    of guessing from socket order."""
    global _node_name, _node_name_read
    if not _node_name_read:
        _node_name_read = True
        _node_name = os.environ.get("AMTPU_NODE_NAME") or None
    return _node_name


def set_node_name(name: str | None) -> None:
    """Override (or with None: clear back to the env) the node label."""
    global _node_name, _node_name_read
    if name is None:
        _node_name_read = False
        _node_name = None
    else:
        _node_name_read = True
        _node_name = str(name)


# ---------------------------------------------------------------------------
# cross-replica trace context


def current_context() -> dict | None:
    """The calling thread's live trace context — `{"tid": ..., "sid": ...}`
    of its innermost active span, falling back to an adopted remote context
    — or None when nothing is being traced. Public surface for CUSTOM
    transports/embedders stamping the context onto their own envelopes;
    the built-in Connection does not use it (its sync_msg_send span IS the
    context it stamps — sync/connection.py:_send_traced)."""
    ident = threading.get_ident()
    with _global.lock:
        stack = _global.active.get(ident)
        if stack:
            return {"tid": stack[-1].trace_id, "sid": stack[-1].span_id}
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None:
        return {"tid": ctx[0], "sid": ctx[1]}
    return None


@contextmanager
def adopt_context(ctx: dict | None):
    """Join a remote trace: top-level spans opened by this thread inside
    the block record the remote `tid` as their trace id and the remote
    `sid` as their parent span, stitching the local serving work onto the
    peer's span tree. A None/invalid ctx is a no-op (untraced peers cost
    nothing). Nested adoptions restore the previous context on exit."""
    if not isinstance(ctx, dict) or not ctx.get("tid"):
        yield
        return
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = (str(ctx["tid"]), str(ctx["sid"]) if ctx.get("sid") else None)
    try:
        yield
    finally:
        _tls.ctx = prev


def _topo_trace(spans: list[dict]) -> list[dict]:
    """Causal order within one trace: parent before child (even when clock
    skew between replicas makes the child's start earlier), siblings by
    start time, orphans (parent span not captured in any buffer) as roots.
    Each span emits exactly once (the guard also breaks parent cycles a
    span-id collision could fabricate)."""
    by_sid = {s["span_id"]: s for s in spans if s.get("span_id")}
    children: dict[str | None, list[dict]] = {}
    for s in spans:
        p = s.get("parent_span_id")
        children.setdefault(p if p in by_sid else None, []).append(s)
    out: list[dict] = []
    emitted: set[int] = set()

    def walk(parent_sid):
        for s in sorted(children.get(parent_sid, []),
                        key=lambda s: s.get("start", 0.0)):
            if id(s) in emitted:
                continue
            emitted.add(id(s))
            out.append(s)
            if s.get("span_id"):
                walk(s["span_id"])
    walk(None)
    for s in spans:        # collision leftovers: never drop a span
        if id(s) not in emitted:
            out.append(s)
    return out


def merge_timeline(buffers: dict[str, list[dict]]) -> list[dict]:
    """Merge per-replica span buffers (each a `recent_spans()` list — local
    or pulled from a peer via the `{"metrics": "pull", "spans": true}`
    protocol message) into ONE causally-ordered timeline. Each output span
    gains a `"replica"` key; traces are ordered by their earliest span
    start, and within a trace parents precede children regardless of
    replica clock skew — the cross-node picture of a sync round the
    per-node ring buffers cannot show alone. A span present in several
    buffers (overlapping pulls, or an in-process "peer" sharing the
    store) is emitted once, under the first buffer that carried it."""
    spans: list[dict] = []
    seen: set = set()
    for replica, buf in buffers.items():
        for s in buf or []:
            key = (s.get("span_id"), s.get("name"), s.get("start"))
            if s.get("span_id") and key in seen:
                continue
            seen.add(key)
            t = dict(s)
            t["replica"] = replica
            spans.append(t)
    by_trace: dict[str, list[dict]] = {}
    loose: list[dict] = []
    for s in spans:
        tid = s.get("trace_id")
        (by_trace.setdefault(tid, []) if tid else loose).append(s)
    groups = [(_topo_trace(group)) for group in by_trace.values()]
    groups.extend([s] for s in loose)
    groups.sort(key=lambda g: min(s.get("start", 0.0) for s in g))
    return [s for g in groups for s in g]


_annotation_cls = None


def _device_annotation(name: str):
    """jax.profiler.TraceAnnotation(name) when the profiler is importable
    (device time then shows under `name` in xprof captures); None otherwise.
    The class lookup is cached — trace() sits on hot paths."""
    global _annotation_cls
    if _annotation_cls is None:
        try:
            import jax.profiler
            _annotation_cls = jax.profiler.TraceAnnotation
        except Exception:  # profiler unavailable on some backends
            _annotation_cls = False
    if _annotation_cls is False:
        return None
    try:
        return _annotation_cls(name)
    except Exception:
        return None


@contextmanager
def trace(name: str, budget_s: float | None = None,
          tags: dict | None = None, **labels):
    """Structured span: nests per thread, records wall seconds + a count
    even when the body raises, annotates device work for jax.profiler, and
    lands in the recent-span ring buffer. With budget_s, an overrun is
    flagged post-hoc (`obs_budget_exceeded{name=...}` + one warning line);
    for live stall detection of a possibly-hung region use watchdog().

    `tags` ride on the ring-buffer span record ONLY — unlike **labels they
    never become metric series keys, so unbounded values (round numbers,
    doc ids) are safe there and forbidden as labels."""
    name = _resolve(name)
    lk = _lk(labels)
    annotation = _device_annotation(_flat_key(name, lk))
    span = _global.push_span(name, lk, tags)
    t0 = time.perf_counter()
    try:
        if annotation is not None:
            with annotation:
                yield span
        else:
            yield span
    finally:
        duration = time.perf_counter() - t0
        _global.pop_span(span, duration)
        if budget_s is not None and duration > budget_s:
            bump("obs_budget_exceeded", name=name)
            log.warning(
                "span %r exceeded budget: %.3fs > %.3fs (labels %s)",
                name, duration, budget_s, dict(lk))


class _WatchdogMonitor:
    """One shared background checker for every active watchdog. A
    threading.Timer per watched region would spawn a thread per hashes()
    poll; this parks a single daemon thread on a condition variable and
    wakes it only at the earliest pending deadline. An idle checker (no
    pending deadlines for `linger_s`) EXITS instead of parking forever —
    thread hygiene between tests/services — and the next add() respawns
    it."""

    #: seconds an idle checker thread lingers before exiting (a steady
    #: stream of watchdogged regions reuses the thread; a one-off lets it
    #: die). Tests shrink this to assert hygiene quickly.
    linger_s = 0.5

    def __init__(self):
        self._cv = threading.Condition()
        self._entries: dict[int, tuple[float, object]] = {}
        self._thread: threading.Thread | None = None
        self._seq = 0

    def add(self, deadline: float, fire) -> int:
        with self._cv:
            self._seq += 1
            key = self._seq
            self._entries[key] = (deadline, fire)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="amtpu-watchdog", daemon=True)
                self._thread.start()
            self._cv.notify()
        return key

    def remove(self, key: int) -> None:
        with self._cv:
            self._entries.pop(key, None)
            self._cv.notify()

    def thread(self) -> threading.Thread | None:
        """The live checker thread, if any (hygiene tests join on it)."""
        with self._cv:
            return self._thread

    def _run(self) -> None:
        while True:
            with self._cv:
                now = time.perf_counter()
                due = [(k, f) for k, (d, f) in self._entries.items()
                       if d <= now]
                for k, _ in due:
                    del self._entries[k]
                if not due:
                    if self._entries:
                        nxt = min(d for d, _ in self._entries.values())
                        self._cv.wait(timeout=max(nxt - now, 0.001))
                    else:
                        self._cv.wait(timeout=self.linger_s)
                        if not self._entries:
                            # idle past the linger: exit; add() respawns.
                            # The _thread reset happens under the cv, so
                            # an add() racing this exit either sees the
                            # old thread (and its entry is caught by the
                            # empty-check above on the next loop) or
                            # spawns a fresh one.
                            self._thread = None
                            return
                    continue
            for _, fire in due:   # outside the cv: fire() takes other locks
                try:
                    fire()
                except Exception:
                    log.exception("watchdog fire failed")


_monitor = _WatchdogMonitor()


@contextmanager
def watchdog(name: str, budget_s: float, logger=None,
             tags: dict | None = None):
    """Stall watchdog around a traced region: the shared background checker
    fires once at budget_s if the block has not exited, logging a one-line
    diagnosis with every thread's active span stack (the "where is it
    stuck" line the r5 config-8 hang never produced), bumping
    obs_watchdog_fired{name=...}, and dumping the flight recorder
    (utils/flightrec.py) so the hang leaves a self-contained post-mortem
    file. The watched block itself runs inside trace(name, tags=tags), so
    the diagnosis always names at least the watched region. The region is
    never interrupted. budget_s <= 0 disables."""
    if budget_s is None or budget_s <= 0:
        with trace(name, tags=tags):
            yield
        return
    lg = logger or log
    t_start = time.perf_counter()

    def _fire():
        stacks = _global.span_stacks()
        desc = "; ".join(f"{t}: {' > '.join(s)}"
                         for t, s in sorted(stacks.items())) \
            or "no active spans"
        try:    # who holds what, not just which span stalled (lockprof)
            from . import lockprof
            holders = lockprof.holders_snapshot()
        except Exception:
            holders = {}
        hdesc = "; ".join(
            f"{n} held {h['held_s']:.2f}s by {h['thread']} ({h['site']})"
            for n, h in sorted(holders.items())) or "none"
        lg.warning(
            "watchdog %r: traced region still running after %.2fs "
            "(budget %.2fs); active spans: %s; lock holders: %s",
            name, time.perf_counter() - t_start, budget_s, desc, hdesc)
        bump("obs_watchdog_fired", name=name)
        with _global.lock:
            _global.watchdog_events.append({
                "name": name, "budget_s": budget_s,
                "elapsed_s": round(time.perf_counter() - t_start, 3),
                "spans": stacks, "lock_holders": holders,
                "at": time.time()})
        try:    # the stall post-mortem: one self-contained JSON file
            from . import flightrec
            flightrec.record("watchdog_fire", name=name,
                             budget_s=budget_s)
            flightrec.dump(f"watchdog:{name}")
        except Exception:
            log.exception("flight-recorder dump on watchdog fire failed")

    key = _monitor.add(t_start + budget_s, _fire)
    try:
        with trace(name, tags=tags):
            yield
    finally:
        _monitor.remove(key)


# ---------------------------------------------------------------------------
# jit dispatch accounting


def dispatch_jit(kernel: str, fn, *args, **kwargs):
    """Call a jitted function, counting the dispatch under
    `engine_kernels_dispatched{kernel=...}` and any compile-cache miss
    under `engine_kernels_retraced{kernel=...}`. A retrace storm on a hot
    kernel is the classic silent TPU perf cliff; this makes it a counter.

    Miss detection is exact since the perfscope rework: a jax.monitoring
    listener observes `/jax/core/compile/*` duration events and attributes
    them to this dispatch through a thread-local marker
    (utils/perfscope.py) — the old jit cache-size delta was thread-racy
    and misattributed concurrent dispatches. The same window records
    per-kernel compile wall time (`engine_kernel_compile{kernel=...}_s`)
    and triggers the one-time XLA cost/memory analysis per new kernel
    signature. Each dispatch also lands in the flight recorder's event
    ring, so a post-mortem dump shows the last kernels every thread
    pushed at the device before the hang."""
    from . import perfscope
    marker = perfscope.dispatch_begin(kernel, fn, args, kwargs)
    try:
        with perfscope.phase("dispatch"):
            return fn(*args, **kwargs)
    finally:
        retraced = perfscope.dispatch_end(marker)
        bump("engine_kernels_dispatched", kernel=kernel)
        if retraced:
            bump("engine_kernels_retraced", kernel=kernel)
        try:
            from . import flightrec
            flightrec.record("dispatch", kernel=kernel,
                             **({"retraced": True} if retraced else {}))
        except Exception:
            pass
        try:
            from ..engine import dispatchledger
            dispatchledger.note_jit(kernel, retraced)
        except Exception:
            pass
