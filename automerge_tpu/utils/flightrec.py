"""Flight recorder: a bounded ring of structured events + crash dumps.

The r5 config-8 hang taught the painful version of this lesson: a fleet
that dies under a watchdog/timeout leaves, at best, a thread dump — no
record of which peer it was talking to, which shard was mid-flush, or
which kernel was the last one pushed at the device. This module is the
always-on black box the post-mortem needs:

- **record(kind, **fields)** appends a structured event — frame send/recv
  (sync/tcp.py), round flushes (sync/service.py), hash fan-out progress
  (sync/sharded_service.py, engine hashes paths), kernel dispatches
  (metrics.dispatch_jit), watchdog fires — to an in-memory ring. Bounded
  (AMTPU_FLIGHTREC_EVENTS, default 2048 events) and cheap (one dict append
  under a lock), so it stays on in production.
- **dump(reason)** writes one self-contained JSON file: the last N events
  per thread, every thread's active span stack, recent completed spans,
  watchdog diagnoses, and the full metrics snapshot. Returns the path.
- **install()** arms automatic dumps on unhandled exceptions (sys and
  threading excepthooks) and SIGTERM; the stall watchdog
  (metrics.watchdog) dumps on fire without any installation.

So the config-8 class of hang now produces a file naming the stalled span,
its peer, and the last thing every thread did — instead of a bare
`Timeout!`. Schema documented in docs/OBSERVABILITY.md.

Env knobs: AMTPU_FLIGHTREC=0 disables recording entirely;
AMTPU_FLIGHTREC_DIR picks the dump directory (default: the system temp
dir); AMTPU_FLIGHTREC_EVENTS sizes the ring; AMTPU_FLIGHTREC_PER_THREAD
caps the per-thread event tail embedded in a dump (default 64);
AMTPU_FLIGHTREC_COOLDOWN_S (default 30, 0 disables) rate-limits
auto-pathed dumps PER TRIGGER CLASS — a watchdog firing every budget
window, or a remediation escalation loop, must not write an unbounded
dump storm to disk. The class is the reason string itself (reasons are
already class-shaped: "watchdog:<name>", "exception", "remed:<action>");
within the cooldown a repeat trigger returns the PREVIOUS dump path,
bumps `obs_flightrec_suppressed{reason=<class>}`, and writes nothing.
An explicit `path=` or `force=True` always dumps — a caller that names
a destination is deliberate, not a storm.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
import traceback
from collections import deque

log = logging.getLogger("automerge_tpu.flightrec")

_ENABLED = os.environ.get("AMTPU_FLIGHTREC", "1") != "0"
_RING = int(os.environ.get("AMTPU_FLIGHTREC_EVENTS", "2048"))
_PER_THREAD = int(os.environ.get("AMTPU_FLIGHTREC_PER_THREAD", "64"))
try:
    _COOLDOWN_S = float(os.environ.get("AMTPU_FLIGHTREC_COOLDOWN_S", "30"))
except ValueError:
    _COOLDOWN_S = 30.0

_lock = threading.Lock()
_events: deque = deque(maxlen=_RING)
_seq = 0
_dump_count = 0
_last_dump_path: str | None = None
# per-trigger-class dump dedup: reason -> (monotonic stamp, path written)
_dump_stamps: dict[str, tuple[float, str | None]] = {}

# Event-kind registry: every `record(kind, ...)` call site in the package
# must use a kind declared here (enforced statically — the graftlint
# registry pass, automerge_tpu/analysis/registry.py — the same way metric
# names are pinned to metrics.REGISTRY). Post-mortem readers can only
# interpret documented kinds; an undeclared kind is a breadcrumb nobody
# can follow. Extension code registers its kinds by inserting here (or
# suppresses the lint with a justification).
EVENT_KINDS: dict[str, str] = {
    "frame_send": "one protocol message written to a TCP socket "
                  "(sync/tcp.py; kind/doc/bytes)",
    "frame_recv": "one protocol message read from a TCP socket",
    "round_flush": "a coalesced service round entering the engine "
                   "(sync/service.py; shard/round/docs/ops)",
    "epoch_seal": "an ingestion epoch sealed into the pending round "
                  "(sync/service.py; shard/entries/ops — the group-"
                  "commit boundary of the epoch-buffered admission path)",
    "hash_read": "per-node converged hash-table read served "
                 "(sync/service.py; shard/docs)",
    "hash_shard": "sharded hash fan-out reaching shard k "
                  "(sync/sharded_service.py; the stall-progress trail)",
    "hash_epoch_check": "sharded fan-out probing shard k's dirty epoch "
                        "(takes the shard lock — a wedged shard stalls "
                        "HERE, inside the watchdog)",
    "hash_fanout_done": "sharded hash fan-out completed (round/shards/docs)",
    "engine_hash_readback": "docs-major engine device->host hash readback "
                            "barrier (engine/resident.py)",
    "rows_hash_readback": "rows engine device->host hash readback barrier "
                          "(engine/resident_rows.py)",
    "dispatch": "one jitted kernel dispatch (metrics.dispatch_jit; "
                "kernel, retraced flag)",
    "dispatch_round": "one flush round folded into the dispatch-"
                      "efficiency ledger (engine/dispatchledger.py; "
                      "round/docs/dispatches/amp)",
    "watchdog_fire": "a stall watchdog fired (metrics.watchdog; "
                     "name/budget_s)",
    "audit_state": "a convergence-audit digest round compared "
                   "(sync/audit.py; shards/mismatched)",
    "divergence": "a convergence divergence isolated to one doc "
                  "(sync/audit.py; shard/doc)",
    "oplag_admit": "a sampled op entered the lifecycle plane "
                   "(utils/oplag.py; id/doc — the provenance id every "
                   "later oplag_stage event of this op carries)",
    "oplag_stage": "one lifecycle stage of a sampled op completed "
                   "(utils/oplag.py; id/stage/s — admission queue wait, "
                   "flush, wire, peer apply, convergence)",
    # fleet health plane (perf/fleet.py, perf/slo.py, utils/chaos.py)
    "chaos_inject": "an injected chaos fault fired (utils/chaos.py; "
                    "fault/node — discloses every degradation so a chaos "
                    "post-mortem is never mistaken for an organic one)",
    "fleet_scrape": "one fleet-collector scrape tick (perf/fleet.py; "
                    "nodes/fresh/stragglers/s)",
    "straggler_flagged": "the fleet collector flagged a straggler "
                         "(perf/fleet.py; node/signal/score)",
    "slo_verdict": "an SLO verdict transition (perf/slo.py; "
                   "slo/ok/value/bound — recorded on CHANGE, so the ring "
                   "shows when health flipped, not a heartbeat)",
    # subscription / relay / shedding plane (sync/connection.py,
    # sync/relay.py, sync/epochs.py — r12)
    "sub_change": "a peer's interest set changed via a {'sub': ...} "
                  "message (sync/connection.py; added/prefixes/removed)",
    "relay_rehome": "a relay hub adopted an orphaned downstream "
                    "connection after its previous hub died "
                    "(sync/relay.py; node)",
    "shed_transition": "the admission governor flipped between open and "
                       "shedding (sync/epochs.IngressGovernor; "
                       "shedding/p99_s/bound_s/mode)",
    # remediation plane (perf/remediate.py, sync/tcp.SupervisedTcpClient
    # — r13)
    "remed_action": "a remediation action was executed — or, in dry-run, "
                    "would have been (perf/remediate.py; action/node/"
                    "dry_run/evidence; reconnects recorded by the "
                    "supervisor carry action=reconnect)",
    "remed_recovered": "a remediation episode closed: the fleet returned "
                       "to SLO-green with zero human action "
                       "(perf/remediate.py; mttr_s/actions)",
    # trace plane (utils/tracer.py — r19)
    "trace_exemplar": "a completed lifecycle trace set a new slowest-"
                      "critical-path high-water mark (utils/tracer.py; "
                      "tid/doc/role/crit_s/stages — the full waterfall "
                      "lives in the traceplane section's exemplars)",
    # race plane (utils/locksan.py — r18)
    "locksan_violation": "the runtime lock-order sanitizer flagged a "
                         "violation (utils/locksan.py; violation=order|"
                         "long-hold, lock/held/hold_s — order inversions "
                         "vs. the committed locks_manifest.json, and "
                         "over-threshold holds with waiters pending)",
}


def enabled() -> bool:
    return _ENABLED


def record(_kind: str, **fields) -> None:
    """Append one structured event to the ring. Field values should be
    small JSON-able scalars (doc ids and per-event values are fine here —
    the ring is bounded, unlike a metric label space)."""
    if not _ENABLED:
        return
    global _seq
    with _lock:
        _seq += 1
        _events.append({
            "seq": _seq,
            "t": time.time(),
            "thread": threading.current_thread().name,
            "kind": _kind,
            **fields,
        })


def events() -> list[dict]:
    """Ring contents, oldest first."""
    with _lock:
        return list(_events)


def reset() -> None:
    global _seq
    with _lock:
        _events.clear()
        _seq = 0
        _dump_stamps.clear()


def last_dump() -> str | None:
    """Path of the most recent dump() of this process, if any."""
    return _last_dump_path


def _dump_dir() -> str:
    d = os.environ.get("AMTPU_FLIGHTREC_DIR")
    if d:
        return d
    import tempfile
    return tempfile.gettempdir()


def _json_default(o):
    try:
        return int(o)          # numpy integers and friends
    except Exception:
        return repr(o)


def dump(reason: str, path: str | None = None,
         extra: dict | None = None, force: bool = False) -> str | None:
    """Write the post-mortem JSON: per-thread event tails, active span
    stacks, recent completed spans, watchdog diagnoses, and the metrics
    snapshot. Never raises (a broken dump must not mask the failure being
    dumped); returns the file path, or None when disabled or the write
    failed.

    Auto-pathed dumps are rate-limited per trigger class (the reason
    string): a repeat trigger within AMTPU_FLIGHTREC_COOLDOWN_S is
    suppressed — counted on `obs_flightrec_suppressed{reason=...}`,
    returning the class's previous path so callers embedding "the dump"
    in a report still point somewhere real. `last_dump()` is NOT
    updated by a suppressed call. `path=`/`force=True` bypass."""
    global _dump_count, _last_dump_path
    if not _ENABLED:
        return None
    try:
        from . import metrics

        rate_limited = path is None and not force and _COOLDOWN_S > 0
        if rate_limited:
            with _lock:
                prev = _dump_stamps.get(reason)
            # the stamp is written only AFTER a successful dump (below):
            # a failed or still-in-flight first write must not silence
            # the whole trigger class for a cooldown window — the rare
            # race of two threads passing this check together costs one
            # extra dump, the opposite bias costs the post-mortem
            if prev is not None \
                    and time.monotonic() - prev[0] < _COOLDOWN_S:
                # bounded label: the reason class, not the full string
                metrics.bump("obs_flightrec_suppressed",
                             reason=reason.split(":")[0])
                log.debug("flight-recorder dump suppressed (reason %s "
                          "within %.0fs cooldown)", reason, _COOLDOWN_S)
                return prev[1]

        with _lock:
            evs = list(_events)
            _dump_count += 1
            n = _dump_count
        threads: dict[str, list[dict]] = {}
        for e in evs:
            threads.setdefault(e["thread"], []).append(e)
        threads = {t: es[-_PER_THREAD:] for t, es in threads.items()}
        try:    # who currently holds which instrumented lock (lockprof)
            from . import lockprof
            lock_holders = lockprof.holders_snapshot()
        except Exception:
            lock_holders = {}
        try:    # the slowest in-flight lifecycle traces at fault time:
            #     a divergence capture shows what was mid-flight, not
            #     just the aggregate gauges (docs/OBSERVABILITY.md
            #     "Trace plane")
            from . import tracer
            inflight_traces = tracer.inflight_snapshot()
        except Exception:
            inflight_traces = []
        doc = {
            "reason": reason,
            "at": time.time(),
            "pid": os.getpid(),
            "argv": sys.argv,
            "span_stacks": metrics.span_stacks(),
            "lock_holders": lock_holders,
            "threads": threads,
            "recent_spans": metrics.recent_spans(),
            "watchdog_events": metrics.watchdog_events(),
            "inflight_traces": inflight_traces,
            "metrics": metrics.snapshot(),
        }
        if extra:
            doc["extra"] = extra
        if path is None:
            safe = "".join(c if c.isalnum() or c in "-_" else "-"
                           for c in reason)[:48]
            path = os.path.join(
                _dump_dir(),
                f"amtpu-flightrec-{os.getpid()}-{n:03d}-{safe}.json")
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, default=_json_default)
        _last_dump_path = path
        if rate_limited:
            with _lock:
                # stamped on SUCCESS only, carrying the path a later
                # suppressed repeat of this trigger class will return
                _dump_stamps[reason] = (time.monotonic(), path)
        # bounded label: the reason class, not the full reason string
        metrics.bump("obs_flightrec_dumps", reason=reason.split(":")[0])
        log.warning("flight recorder dumped to %s (reason: %s)",
                    path, reason)
        return path
    except Exception:
        log.exception("flight-recorder dump failed (reason: %s)", reason)
        return None


# ---------------------------------------------------------------------------
# automatic dump triggers: unhandled exceptions + SIGTERM


_installed = False
_prev_sys_hook = None
_prev_threading_hook = None
_prev_sigterm = None


def install(signals: bool = True, excepthooks: bool = True) -> None:
    """Arm automatic dumps: unhandled exceptions on any thread (sys and
    threading excepthooks, chained to the previous hooks) and SIGTERM
    (dump, then re-deliver so termination semantics are unchanged).
    Idempotent. Long-lived processes (bench workers, sync services) call
    this once at startup; libraries should not."""
    global _installed, _prev_sys_hook, _prev_threading_hook, _prev_sigterm
    if _installed or not _ENABLED:
        return
    _installed = True

    if excepthooks:
        _prev_sys_hook = sys.excepthook

        def _sys_hook(exc_type, exc, tb):
            dump("exception", extra={
                "exception": "".join(traceback.format_exception(
                    exc_type, exc, tb))[-8000:]})
            (_prev_sys_hook or sys.__excepthook__)(exc_type, exc, tb)

        sys.excepthook = _sys_hook

        _prev_threading_hook = threading.excepthook

        def _thread_hook(args):
            dump("thread-exception", extra={
                "thread": getattr(args.thread, "name", None),
                "exception": "".join(traceback.format_exception(
                    args.exc_type, args.exc_value,
                    args.exc_traceback))[-8000:]})
            (_prev_threading_hook or threading.__excepthook__)(args)

        threading.excepthook = _thread_hook

    if signals and threading.current_thread() is threading.main_thread():
        import signal as _signal
        try:
            _prev_sigterm = _signal.getsignal(_signal.SIGTERM)

            def _on_sigterm(signum, frame):
                dump("sigterm")
                if _prev_sigterm is _signal.SIG_IGN:
                    return          # the process chose to ignore SIGTERM;
                    #                 dumping must not turn that into death
                if callable(_prev_sigterm):
                    _prev_sigterm(signum, frame)
                else:               # SIG_DFL (or unknown): default death
                    _signal.signal(_signal.SIGTERM, _signal.SIG_DFL)
                    os.kill(os.getpid(), _signal.SIGTERM)

            _signal.signal(_signal.SIGTERM, _on_sigterm)
        except (ValueError, OSError):   # non-main interpreter contexts
            _prev_sigterm = None


def uninstall() -> None:
    """Restore the hooks install() replaced (tests; embedders shutting
    down cleanly)."""
    global _installed, _prev_sys_hook, _prev_threading_hook, _prev_sigterm
    if not _installed:
        return
    _installed = False
    if _prev_sys_hook is not None:
        sys.excepthook = _prev_sys_hook
        _prev_sys_hook = None
    if _prev_threading_hook is not None:
        threading.excepthook = _prev_threading_hook
        _prev_threading_hook = None
    if _prev_sigterm is not None:
        import signal as _signal
        try:
            _signal.signal(_signal.SIGTERM, _prev_sigterm)
        except (ValueError, OSError):
            pass
        _prev_sigterm = None
