"""Runtime lock-order sanitizer: the dynamic half of the race plane.

The static side (analysis/lock_discipline.py + locks_manifest.json)
commits the lock hierarchy as a reviewed DAG. This module checks the
same order on LIVE threads: with `AMTPU_LOCKSAN=1`, every named lock
acquisition (the lockprof wrappers call in via `note_acquire`/
`note_release`; plain locks adopt via the `named_lock()` factory)
is checked against the committed manifest edges, per thread:

- **order violation** — acquiring lock A while holding lock B when the
  manifest commits A -> B (A before B). Only *committed inversions*
  flag: an edge the manifest has never seen is `lock-manifest-drift`'s
  job at lint time, not a runtime judgement call.
- **long hold** — an outermost hold longer than `AMTPU_LOCKSAN_HOLD_S`
  (default 0.25s) released while other threads are blocked waiting on
  the same name — the r5 stall shape, caught in the act.

Disclosure, not crashing: violations bump
`obs_locksan_order_violations_total` / `obs_locksan_long_holds_total`,
record a `locksan_violation` flightrec event, and append to a bounded
in-process list readable via `violations()`. Strict mode
(`AMTPU_LOCKSAN=2`) additionally RAISES `LockOrderViolation` on an
order violation — for tests and storm harnesses, never production.

Inert when unset: `AMTPU_LOCKSAN` is read once and cached; the
disabled fast path in lockprof is a single module-attribute truth test
(`locksan.on`), and `named_lock()` returns a plain `threading.Lock`.
`_reload_for_tests()` re-reads the environment and clears all state
(manifest cache, per-thread stacks survive only as stale thread-locals
that reset on next use).

Lock-name resolution: the manifest's lock table maps runtime names
("service", "peer_send") to static identities ("EngineDocSet._lock").
Renamed locks resolve by longest manifest-name prefix
("service_shard3" -> "service"), so sharded renames keep their
identity. Names with no manifest entry get no order checking (but
still participate in hold-time accounting).
"""

from __future__ import annotations

import json
import os
import pathlib
import threading
import time

MANIFEST_NAME = "locks_manifest.json"
DEFAULT_HOLD_S = 0.25
_MAX_VIOLATIONS = 256

#: fast-path flag — lockprof reads this attribute on every acquire; it
#: is the "one cached check" of the disabled path.
on = False

_level: int | None = None
_hold_s: float | None = None
_manifest: tuple[dict, set] | None = None   # (name->id, committed edges)
_tls = threading.local()
_meta_lock = threading.Lock()    # guards _violations and _waiters (leaf
_violations: list[dict] = []     # lock: never held while acquiring
_waiters: dict[str, int] = {}    # another)


class LockOrderViolation(RuntimeError):
    """Raised in strict mode (AMTPU_LOCKSAN=2) on an order violation."""


# ---------------------------------------------------------------------------
# configuration


def level() -> int:
    """0 = inert, 1 = record, 2 = strict (raise on order violation)."""
    global _level, on
    if _level is None:
        raw = os.environ.get("AMTPU_LOCKSAN", "0").strip() or "0"
        try:
            _level = max(0, min(2, int(raw)))
        except ValueError:
            _level = 0
        on = _level >= 1
    return _level


def enabled() -> bool:
    return level() >= 1


def hold_threshold_s() -> float:
    global _hold_s
    if _hold_s is None:
        try:
            _hold_s = float(os.environ.get("AMTPU_LOCKSAN_HOLD_S",
                                           str(DEFAULT_HOLD_S)))
        except ValueError:
            _hold_s = DEFAULT_HOLD_S
    return _hold_s


def _reload_for_tests() -> None:
    """Re-read AMTPU_LOCKSAN* and drop every cache (tests flip the env
    var mid-process; production reads it once)."""
    global _level, _hold_s, _manifest, on
    _level = None
    _hold_s = None
    _manifest = None
    on = False
    level()
    reset()


def reset() -> None:
    """Clear recorded violations and waiter counts (test isolation)."""
    with _meta_lock:
        _violations.clear()
        _waiters.clear()


def violations() -> list[dict]:
    """Snapshot of recorded violations (bounded at _MAX_VIOLATIONS)."""
    with _meta_lock:
        return list(_violations)


# ---------------------------------------------------------------------------
# manifest


def _manifest_path() -> pathlib.Path:
    override = os.environ.get("AMTPU_LOCKSAN_MANIFEST")
    if override:
        return pathlib.Path(override)
    # automerge_tpu/utils/locksan.py -> the repo root
    return pathlib.Path(__file__).resolve().parents[2] / MANIFEST_NAME


def _load_manifest() -> tuple[dict, set]:
    global _manifest
    if _manifest is None:
        names: dict[str, str] = {}
        edges: set[tuple[str, str]] = set()
        try:
            data = json.loads(_manifest_path().read_text())
            for e in data.get("locks", []):
                if e.get("name"):
                    names[e["name"]] = e["id"]
            for e in data.get("order", []):
                edges.add((e["before"], e["after"]))
        except (OSError, ValueError):
            pass        # no manifest: order checking disarmed
        _manifest = (names, edges)
    return _manifest


def _resolve(name: str) -> str | None:
    """Runtime name -> manifest lock id; longest-prefix match absorbs
    renames like service -> service_shard<k>."""
    names, _ = _load_manifest()
    lid = names.get(name)
    if lid is not None:
        return lid
    best = None
    for n, i in names.items():
        if name.startswith(n) and (best is None or len(n) > len(best[0])):
            best = (n, i)
    return best[1] if best else None


# ---------------------------------------------------------------------------
# the per-thread held stack


def _stack() -> list:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s    # entries: [name, lock_id, t_acquired, depth]


def note_acquire(name: str) -> None:
    """Called by the lock wrapper AFTER an outermost acquire."""
    if not on:
        return
    stack = _stack()
    for entry in stack:
        if entry[0] == name:        # reentrant re-acquire through rename
            entry[3] += 1
            return
    lid = _resolve(name)
    _, edges = _load_manifest()
    if lid is not None:
        for held_name, held_id, _t0, _d in reversed(stack):
            if held_id is None or held_id == lid:
                continue
            if (lid, held_id) in edges:
                _disclose("order", lock=name, lock_id=lid,
                          held=held_name, held_id=held_id,
                          detail=(f"acquired {name} ({lid}) while "
                                  f"holding {held_name} ({held_id}); "
                                  f"{MANIFEST_NAME} commits "
                                  f"{lid} -> {held_id}"))
                break
    stack.append([name, lid, time.perf_counter(), 1])


def note_release(name: str) -> None:
    """Called by the lock wrapper BEFORE/AT an outermost release."""
    if not on:
        return
    stack = _stack()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i][0] != name:
            continue
        stack[i][3] -= 1
        if stack[i][3] > 0:
            return
        _n, lid, t0, _d = stack.pop(i)
        hold_s = time.perf_counter() - t0
        if hold_s >= hold_threshold_s():
            with _meta_lock:
                pending = _waiters.get(name, 0)
            if pending > 0:
                _disclose("long-hold", lock=name, lock_id=lid,
                          hold_s=round(hold_s, 4), waiters=pending,
                          detail=(f"held {name} for {hold_s:.3f}s with "
                                  f"{pending} thread(s) blocked on it"),
                          raise_strict=False)
        return


def note_wait(name: str) -> None:
    """A thread is about to block on `name` (contended acquire)."""
    if not on:
        return
    with _meta_lock:
        _waiters[name] = _waiters.get(name, 0) + 1


def note_wait_done(name: str) -> None:
    if not on:
        return
    with _meta_lock:
        n = _waiters.get(name, 0) - 1
        if n <= 0:
            _waiters.pop(name, None)
        else:
            _waiters[name] = n


# ---------------------------------------------------------------------------
# disclosure


def _disclose(kind: str, detail: str, raise_strict: bool = True,
              **fields) -> None:
    rec = {"kind": kind, "thread": threading.current_thread().name,
           "detail": detail, **fields}
    with _meta_lock:
        if len(_violations) < _MAX_VIOLATIONS:
            _violations.append(rec)
    # lazy imports: lockprof imports this module, and metrics/flightrec
    # sit above lockprof — the inert path must not pull them in either
    try:
        from . import metrics
        if kind == "order":
            metrics.bump("obs_locksan_order_violations_total",
                         lock=fields.get("lock", "?"))
        else:
            metrics.bump("obs_locksan_long_holds_total",
                         lock=fields.get("lock", "?"))
        from . import flightrec
        # the violation class rides as `violation` — a `kind` field
        # would clobber the event kind itself
        flightrec.record("locksan_violation", violation=kind, **{
            k: v for k, v in rec.items() if k not in ("kind",)})
    except Exception:
        pass        # a sanitizer must never take the process down
    if raise_strict and level() >= 2:
        raise LockOrderViolation(detail)


# ---------------------------------------------------------------------------
# the named-lock factory (for plain-threading.Lock adopters)


class _SanLock:
    """A `threading.Lock` wrapper that reports to the sanitizer. Only
    handed out by `named_lock()` when the sanitizer is on — the
    disabled path carries zero wrapper overhead."""

    __slots__ = ("name", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._lock.acquire(blocking=False):
            note_acquire(self.name)
            return True
        if not blocking:
            return False
        note_wait(self.name)
        try:
            got = (self._lock.acquire() if timeout is None or timeout < 0
                   else self._lock.acquire(timeout=timeout))
        finally:
            note_wait_done(self.name)
        if got:
            note_acquire(self.name)
        return got

    def release(self) -> None:
        note_release(self.name)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "_SanLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<_SanLock {self.name!r}>"


def named_lock(name: str):
    """A mutex carrying a sanitizer name. Inert (`AMTPU_LOCKSAN` unset):
    a plain `threading.Lock` — zero overhead, no wrapper. Enabled: a
    `_SanLock` that participates in order/hold checking. graftlint
    recognizes this factory exactly like the lockprof wrappers, so the
    lock keeps its class-qualified identity in the static analysis."""
    if level() >= 1:
        return _SanLock(name)
    return threading.Lock()


# arm at import: the lockprof fast path tests `locksan.on` directly and
# must see the env verdict without anyone ever calling level() — a
# process whose only named locks are lockprof wrappers would otherwise
# never arm under AMTPU_LOCKSAN=1
level()
