"""The trace plane: sampled end-to-end change lifecycle tracing with
cross-process span stitching (docs/OBSERVABILITY.md "Trace plane").

Every latency figure the other planes report is a single end-to-end
number — the docledger's convergence rings, the tenant plane's per-
tenant p99 — with no decomposition of *where* the time goes between a
client mutation and remote convergence. This module stamps a trace
context on a deterministically sampled change at frontend finalize
(``api._apply_new_change``) and records a bounded span at every stage
the change crosses:

    finalize         change construction + local apply (frontend)
    governor_delay   admission-governor park before epoch append
    queue_wait       epoch-buffer admission -> epoch seal
    coalesce_wait    epoch seal -> its flush round starting
    dispatch         the flush round's wall time (joined to the
                     dispatch ledger's folded round: amplification and
                     pad-waste ride the span's metadata)
    wire_serialize   columnar frame encode on the sending connection
    wire             socket send -> remote receive (wall-clock delta;
                     cross-host skew is disclosed, not corrected)
    remote_decode    frame decode on the receiving connection
    remote_admission frame apply under the receiver's apply lock
    visibility       admission -> the change's doc appearing in a
                     converged-hash read

Sampling is 1-in-N by ``zlib.crc32(f"{actor}:{seq}")`` so every process
— and both ends of a connection — make the same decision without
coordination. ``AMTPU_TRACE_SAMPLE`` unset (the default) keeps the
plane INERT: every hook reduces to one cached boolean check, and the
wire envelope carries no trace key (byte-identical frames — the bench
config-19 parity gate).

Cross-process stitching: the sending connection pops the doc's awaiting
traces and ships each one's accumulated spans inside the change-frame
envelope (``frames.TRACEPLANE_KEY``). The receiver records its own
spans RELATIVE TO THE ORIGIN's wall epoch and completes ONE trace whose
spans cover both processes — the single cross-process critical path the
fleet megabatching arc (ROADMAP #2) divides. Receivers record
unconditionally of their local rate: the sender paid the sampling
decision (the oplag precursor's contract).

House ledger contract (docledger/dispatchledger/tenantledger):

- bounded everything — the finalized handoff table, the per-doc
  awaiting tables, the completed ring — with DISCLOSED truncation
  (``dropped``/``expired`` counters, never silent loss);
- ``section()`` is PURE — no wall-clock reads, no lock ordering
  surprises — and rides ``metrics.register_snapshot_section`` so every
  snapshot consumer (fleet collector, doctor, bench detail) sees it;
- ``obs_trace_*`` gauges refresh on the MUTATION path (every
  GAUGE_REFRESH completions), never on export;
- ``self_seconds()`` duty accounting, gated in bench config 19 under
  the same 2% budget as the other ledgers.

In-flight traces that never complete (an unreachable peer, a doc with
no hash reader) expire after ``TTL_S`` and are counted ``expired`` —
the completeness gauge's honest denominator, never a leak.
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from collections import OrderedDict, deque

from . import flightrec, metrics

#: lifecycle stages, in critical-path order (the waterfall's row order)
STAGES = (
    "finalize", "governor_delay", "queue_wait", "coalesce_wait",
    "dispatch", "megabatch", "wire_serialize", "wire", "remote_decode",
    "remote_admission", "visibility",
)

#: completed-trace ring capacity (AMTPU_TRACE_RING)
DEFAULT_RING = 256
#: finalized-but-unadmitted handoff entries kept per thread
PENDING_MAX = 8
#: per-table cap on docs with awaiting traces (oldest doc retired first)
AWAIT_MAX = 256
#: traces shipped per wire header (a storm of sampled changes on one
#: doc must not balloon the envelope)
HEADER_MAX = 4
#: in-flight traces older than this are retired as expired
TTL_S = 10.0
#: refresh the obs_trace_* gauges every this many mutations
GAUGE_REFRESH = 16
#: slowest completed exemplars surfaced by section()/the CLI waterfall
EXEMPLARS = 4

_rate: int | None | bool = False     # False = not yet read from env
_rate_lock = threading.Lock()


def sample_rate() -> int | None:
    """1-in-N sampling rate from AMTPU_TRACE_SAMPLE, or None when the
    plane is disabled (unset/0/garbage — the default). Cached; tests
    override via set_sample_rate()."""
    global _rate
    r = _rate
    if r is False:
        with _rate_lock:
            if _rate is False:
                try:
                    n = int(os.environ.get("AMTPU_TRACE_SAMPLE", "0"))
                except ValueError:
                    n = 0
                _rate = n if n > 0 else None
            r = _rate
    return r


def set_sample_rate(n: int | None) -> None:
    """Override the sampling rate (tests, the bench, the smoke).
    ``None`` disables the plane."""
    global _rate
    with _rate_lock:
        _rate = n if (n is None or n > 0) else None


def _reload_for_tests() -> None:
    """Drop the cached rate so the next check re-reads the env."""
    global _rate
    with _rate_lock:
        _rate = False


def enabled() -> bool:
    return sample_rate() is not None


def sampled(actor: str, seq: int) -> bool:
    """The deterministic coordination-free sampling decision: every
    process hashes (actor, seq) the same way."""
    n = sample_rate()
    if n is None:
        return False
    return zlib.crc32(f"{actor}:{seq}".encode()) % n == 0


def _ring_cap() -> int:
    try:
        n = int(os.environ.get("AMTPU_TRACE_RING", str(DEFAULT_RING)))
    except ValueError:
        n = DEFAULT_RING
    return max(8, n)


class _Trace:
    """One sampled change's lifecycle. Mutated only under the plane
    lock after the thread-local finalize handoff."""

    __slots__ = ("tid", "actor", "seq", "doc", "t0_wall", "t0_perf",
                 "spans", "role", "origin", "meta", "born", "mark")

    def __init__(self, actor: str, seq: int):
        self.tid = f"{actor}.{seq}"
        self.actor = actor
        self.seq = seq
        self.doc: str | None = None
        self.t0_wall = time.time()
        self.t0_perf = time.perf_counter()
        self.spans: list[list] = []      # [stage, rel_start_s, dur_s]
        self.role = "origin"
        self.origin = metrics.node_name() or "local"
        self.meta: dict = {}
        self.born = self.t0_perf
        self.mark = 0.0                  # last stage boundary (perf)

    def rel(self, t_perf: float) -> float:
        """Origin-epoch-relative seconds for a local perf stamp. On the
        remote side t0_wall is the ORIGIN's wall epoch and t0_perf the
        local receive stamp re-based onto it (see wire_receive)."""
        return t_perf - self.t0_perf

    def span(self, stage: str, start_perf: float, end_perf: float):
        self.spans.append([stage, round(self.rel(start_perf), 6),
                           round(max(0.0, end_perf - start_perf), 6)])

    def to_dict(self) -> dict:
        crit = 0.0
        if self.spans:
            crit = max(s[1] + s[2] for s in self.spans)
        return {
            "tid": self.tid, "doc": self.doc, "actor": self.actor,
            "seq": self.seq, "role": self.role, "origin": self.origin,
            "stitched": self.role == "stitched",
            "crit_s": round(crit, 6),
            "spans": [list(s) for s in self.spans],
            "meta": dict(self.meta),
        }


class TracePlane:
    """Process-global trace registry: the finalize handoff, the doc-
    keyed awaiting tables for each deferred stage boundary, and the
    bounded completed ring."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tls = threading.local()
        # doc id -> [traces] parked between admission and round flush
        self._awaiting_flush: OrderedDict[str, list] = OrderedDict()
        # doc id -> [traces] parked between round flush and wire send
        # (a doc with no peer completes from here at hash visibility)
        self._awaiting_wire: OrderedDict[str, list] = OrderedDict()
        # doc id -> [traces] parked between remote admission and the
        # converged-hash read that makes the change visible
        self._awaiting_visible: OrderedDict[str, list] = OrderedDict()
        self._completed: deque = deque(maxlen=_ring_cap())
        self._sampled = 0        # origin-side sampled finalizes
        self._received = 0       # sender-stamped traces adopted here
        self._handed_off = 0     # traces shipped inside a wire header
        self._done = 0           # traces completed at visibility here
        self._stitched = 0       # ... of which carry both processes
        self._expired = 0        # TTL retirements (incompleteness)
        self._dropped = 0        # bounded-table overflow retirements
        self._mutations = 0
        self._self_s = 0.0
        self._self_s_flushed = 0.0
        self._worst_crit = 0.0

    # -- frontend finalize ------------------------------------------------

    def finalize_begin(self, actor: str, seq: int):
        """Called by api._apply_new_change BEFORE change construction.
        Returns the trace for the matching finalize_end, or None when
        the plane is off or (actor, seq) is not sampled."""
        if not sampled(actor, seq):
            return None
        t0 = time.perf_counter()
        tr = _Trace(actor, seq)
        with self._lock:
            self._sampled += 1
            self._self_s += time.perf_counter() - t0
        return tr

    def finalize_end(self, tr) -> None:
        """The change is constructed and locally applied: record the
        finalize span and park the trace on THIS thread for the service
        admission that follows (set_doc on the same thread claims it)."""
        if tr is None:
            return
        t = time.perf_counter()
        tr.span("finalize", tr.t0_perf, t)
        tr.mark = t
        pend = getattr(self._tls, "pending", None)
        if pend is None:
            pend = self._tls.pending = []
        pend.append(tr)
        if len(pend) > PENDING_MAX:      # bounded: oldest unclaimed out
            del pend[0]
            with self._lock:
                self._dropped += 1

    def origin_ingress(self, pairs) -> None:
        """Engine-service writers hand the service Change objects
        directly (bench storms, native ingest) — there is no frontend
        finalize to stamp them. Start the sampled ones' lifecycle at the
        service boundary instead (zero-length finalize). A frontend-
        finalized trace already pending on this thread keeps its real
        finalize span (dedup by trace id); applies running under
        remote_apply() (a connection receive) never originate — the
        sender's stitched context owns that lifecycle."""
        if not enabled() or getattr(self._tls, "remote", False):
            return
        pend = getattr(self._tls, "pending", None)
        have = {tr.tid for tr in pend} if pend else ()
        started = []
        for actor, seq in pairs:
            if not sampled(actor, seq) or f"{actor}.{seq}" in have:
                continue
            tr = _Trace(actor, seq)
            tr.span("finalize", tr.t0_perf, tr.t0_perf)
            tr.mark = tr.t0_perf
            started.append(tr)
        if not started:
            return
        if pend is None:
            pend = self._tls.pending = []
        pend.extend(started)
        with self._lock:
            self._sampled += len(started)
            if len(pend) > PENDING_MAX:
                self._dropped += len(pend) - PENDING_MAX
                del pend[:len(pend) - PENDING_MAX]

    def remote_apply(self):
        """Context manager a connection wraps around a received frame's
        apply: origin_ingress under it is a no-op, so a remote change is
        never double-traced as a local origin."""
        plane = self

        class _Remote:
            def __enter__(self):
                plane._tls.remote = True

            def __exit__(self, *exc):
                plane._tls.remote = False
                return False

        return _Remote()

    # -- service admission -> flush ---------------------------------------

    def admit(self, doc_id: str, delay_s: float = 0.0) -> None:
        """Service ingress admitted a frame for doc_id on this thread:
        claim the thread's finalized traces, bind the doc, record the
        governor park and open the queue_wait stage."""
        if not enabled():
            return
        pend = getattr(self._tls, "pending", None)
        if not pend:
            return
        t0 = time.perf_counter()
        traces, pend[:] = pend[:], []
        for tr in traces:
            tr.doc = doc_id
            if delay_s > 0.0:
                tr.span("governor_delay", t0 - delay_s, t0)
            tr.mark = t0
        with self._lock:
            self._park_locked(self._awaiting_flush, doc_id, traces)
            self._self_s += time.perf_counter() - t0

    def sealed(self, doc_ids) -> None:
        """Epoch seal boundary — STAMP ONLY (called under the service
        lock; recording is deferred to flush_round outside it)."""
        if not enabled() or not self._awaiting_flush:
            return
        t0 = time.perf_counter()
        with self._lock:
            for d in doc_ids:
                for tr in self._awaiting_flush.get(d, ()):
                    if "sealed" not in tr.meta:
                        tr.meta["sealed"] = t0
            self._self_s += time.perf_counter() - t0

    def flush_round(self, round_docs, round_no: int,
                    t_start: float, dur_s: float) -> None:
        """A coalesced flush round covering round_docs finished (called
        OUTSIDE the service lock, before handler gossip — every trace is
        parked in the awaiting-wire table before its doc's message
        leaves). Records queue_wait / coalesce_wait / dispatch and joins
        the dispatch ledger's folded round."""
        if not enabled() or not self._awaiting_flush or round_docs is None:
            return
        t0 = time.perf_counter()
        t_end = t_start + dur_s
        rd = self._round_join()
        with self._lock:
            for d in round_docs:
                traces = self._awaiting_flush.pop(d, None)
                if not traces:
                    continue
                for tr in traces:
                    t_seal = tr.meta.pop("sealed", t_start)
                    tr.span("queue_wait", tr.mark, t_seal)
                    tr.span("coalesce_wait", t_seal, t_start)
                    tr.span("dispatch", t_start, t_end)
                    tr.mark = t_end
                    if rd is not None:
                        tr.meta["round"] = rd.get("round", round_no)
                        if rd.get("amp") is not None:
                            tr.meta["amp"] = rd["amp"]
                        if rd.get("pad_waste_pct") is not None:
                            tr.meta["pad_waste_pct"] = rd["pad_waste_pct"]
                        mega = rd.get("mega")
                        if mega:
                            # this change rode a fused multi-doc round
                            # (engine/dispatch.py apply_round_adaptive);
                            # the span shadows "dispatch" — same window,
                            # tagged so `perf explain` can show which
                            # fused round carried the doc's ops
                            tr.span("megabatch", t_start, t_end)
                            tr.meta["mega_buckets"] = mega.get("buckets")
                            tr.meta["mega_docs"] = mega.get("docs")
                            if mega.get("pad_waste_pct") is not None:
                                tr.meta["mega_pad_waste_pct"] = (
                                    mega["pad_waste_pct"])
                    else:
                        tr.meta["round"] = round_no
                self._park_locked(self._awaiting_wire, d, traces)
            self._expire_locked(t0)
            self._self_s += time.perf_counter() - t0

    def _round_join(self) -> dict | None:
        """The dispatch-ledger join: the most recent folded round's
        amplification / pad-waste, when that ledger is on (lazy import —
        the engine must not become a hard dependency of the plane)."""
        try:
            from ..engine import dispatchledger
            if dispatchledger.enabled():
                return dispatchledger.last_round_summary()
        except Exception:
            pass
        return None

    # -- wire: stitching --------------------------------------------------

    def wire_header(self, doc_id: str, serialize_s: float = 0.0):
        """Pop doc_id's post-flush traces for the send path. Returns the
        JSON-able header the envelope carries (the sender's accumulated
        spans + the origin wall epoch), or None when nothing is awaiting
        — the unset/unsampled envelope stays byte-identical."""
        if not enabled() or not self._awaiting_wire:
            return None
        t0 = time.perf_counter()
        with self._lock:
            traces = self._awaiting_wire.pop(doc_id, None)
            if not traces:
                self._self_s += time.perf_counter() - t0
                return None
            if len(traces) > HEADER_MAX:
                self._dropped += len(traces) - HEADER_MAX
                traces = traces[-HEADER_MAX:]
            hdr = []
            for tr in traces:
                tr.span("wire_serialize", t0 - serialize_s, t0)
                hdr.append({
                    "tid": tr.tid, "actor": tr.actor, "seq": tr.seq,
                    "t0": round(tr.t0_wall, 6),
                    "sent": round(time.time(), 6),
                    "origin": tr.origin,
                    "spans": tr.spans,
                    "meta": tr.meta,
                })
                self._handed_off += 1
            self._mutations += 1
            if self._mutations % GAUGE_REFRESH == 0:
                self._refresh_gauges_locked()
            self._self_s += time.perf_counter() - t0
        return hdr

    def wire_receive(self, hdr, doc_id: str | None = None):
        """Adopt sender-stamped traces from a received envelope header.
        Records the wire span (wall-clock delta — same-host skew is
        noise, cross-host skew is disclosed in the docs, not corrected)
        and returns the trace list for remote_admitted(). Recording is
        UNCONDITIONAL of the local rate: the sender paid the sampling
        decision."""
        if not hdr:
            return None
        t0 = time.perf_counter()
        now_wall = time.time()
        out = []
        try:
            for h in hdr[:HEADER_MAX]:
                tr = _Trace(str(h["actor"]), int(h["seq"]))
                tr.doc = doc_id
                tr.role = "stitched"
                tr.origin = str(h.get("origin", "?"))
                tr.t0_wall = float(h["t0"])
                # re-base the local perf clock onto the origin's wall
                # epoch: rel(local perf stamp) continues the sender's
                # timeline (minus inter-host skew)
                tr.t0_perf = t0 - (now_wall - tr.t0_wall)
                tr.spans = [list(s) for s in h.get("spans", ())][:32]
                tr.meta = dict(h.get("meta") or {})
                sent = float(h.get("sent", now_wall))
                wire_start = t0 - max(0.0, now_wall - sent)
                tr.span("wire", wire_start, t0)
                tr.mark = t0
                out.append(tr)
        except (KeyError, TypeError, ValueError):
            # a malformed header from a peer must never break apply
            out = out or None
        if out:
            with self._lock:
                self._received += len(out)
                self._self_s += time.perf_counter() - t0
        return out

    def remote_admitted(self, traces, doc_id: str,
                        decode_s: float = 0.0,
                        admission_s: float = 0.0) -> None:
        """The received frame is decoded and applied: record both spans
        and park for the converged-hash visibility read."""
        if not traces:
            return
        t0 = time.perf_counter()
        t_admit0 = t0 - admission_s
        t_dec0 = t_admit0 - decode_s
        for tr in traces:
            tr.doc = tr.doc or doc_id
            tr.span("remote_decode", t_dec0, t_admit0)
            tr.span("remote_admission", t_admit0, t0)
            tr.mark = t0
        with self._lock:
            self._park_locked(self._awaiting_visible, doc_id, traces)
            self._self_s += time.perf_counter() - t0

    # -- completion -------------------------------------------------------

    def visible(self, doc_ids=None) -> None:
        """A converged-hash read covering doc_ids (None = all docs) just
        served: complete every awaiting trace with its visibility span.
        Origin-side traces whose doc never crossed a wire complete from
        the awaiting-wire table — first consumer (send or visibility)
        wins. NOT gated on the local rate: adopted remote traces must
        complete even on a receiver whose own sampling is unset (the
        sender paid the decision); when the plane was never touched both
        tables are empty and this is two attribute loads."""
        if not self._awaiting_visible and not self._awaiting_wire:
            return
        t0 = time.perf_counter()
        done = []
        with self._lock:
            for table in (self._awaiting_visible, self._awaiting_wire):
                docs = (list(table) if doc_ids is None
                        else [d for d in doc_ids if d in table])
                for d in docs:
                    for tr in table.pop(d, ()):
                        tr.span("visibility", tr.mark, t0)
                        if self._complete_locked(tr):
                            done.append(tr)
            self._expire_locked(t0)
            self._self_s += time.perf_counter() - t0
        # the exemplar event is emitted OUTSIDE the plane lock
        for tr in done:
            d = tr.to_dict()
            flightrec.record("trace_exemplar", tid=d["tid"],
                             doc=d["doc"], role=d["role"],
                             crit_s=d["crit_s"],
                             stages=len(d["spans"]))

    def _complete_locked(self, tr) -> bool:
        """Fold a finished trace into the ring; True when it is a new
        slowest exemplar (the caller emits the flightrec event)."""
        self._done += 1
        if tr.role == "stitched":
            self._stitched += 1
        crit = max((s[1] + s[2] for s in tr.spans), default=0.0)
        exemplar = crit >= self._worst_crit
        if exemplar:
            self._worst_crit = crit
        self._completed.append(tr.to_dict())
        self._mutations += 1
        if self._mutations % GAUGE_REFRESH == 0:
            self._refresh_gauges_locked()
        return exemplar

    # -- bounded-table plumbing -------------------------------------------

    def _park_locked(self, table, doc_id: str, traces) -> None:
        table.setdefault(doc_id, []).extend(traces)
        table.move_to_end(doc_id)
        while len(table) > AWAIT_MAX:
            _, lost = table.popitem(last=False)
            self._dropped += len(lost)
        self._mutations += 1
        if self._mutations % GAUGE_REFRESH == 0:
            self._refresh_gauges_locked()

    def _expire_locked(self, now_perf: float) -> None:
        """Retire in-flight traces past TTL_S — counted, not leaked."""
        for table in (self._awaiting_flush, self._awaiting_wire,
                      self._awaiting_visible):
            for d in list(table):
                traces = table[d]
                live = [t for t in traces if now_perf - t.born < TTL_S]
                if len(live) != len(traces):
                    self._expired += len(traces) - len(live)
                    if live:
                        table[d] = live
                    else:
                        del table[d]

    def _inflight_locked(self) -> int:
        return (sum(len(v) for v in self._awaiting_flush.values())
                + sum(len(v) for v in self._awaiting_wire.values())
                + sum(len(v) for v in self._awaiting_visible.values()))

    def _refresh_gauges_locked(self) -> None:
        metrics.gauge("obs_trace_sampled", self._sampled)
        metrics.gauge("obs_trace_completed", self._done)
        metrics.gauge("obs_trace_inflight", self._inflight_locked())
        crits = sorted(t["crit_s"] for t in self._completed)
        if crits:
            metrics.gauge("obs_trace_critical_path_p99_s",
                          crits[min(len(crits) - 1,
                                    int(0.99 * len(crits)))])
        delta = self._self_s - self._self_s_flushed
        if delta > 0:
            metrics.observe("obs_trace_ledger_s", delta)
            self._self_s_flushed = self._self_s

    # -- export ------------------------------------------------------------

    def self_seconds(self) -> float:
        with self._lock:
            return self._self_s

    def section(self) -> dict:
        """PURE snapshot: counts, per-stage latency rollups over the
        completed ring, and the slowest completed exemplars (full
        waterfalls). No wall-clock reads."""
        with self._lock:
            ring = list(self._completed)
            sec = {
                "label": metrics.node_name() or "local",
                "sample_rate": sample_rate(),
                "sampled": self._sampled,
                "received": self._received,
                "handed_off": self._handed_off,
                "completed": self._done,
                "stitched": self._stitched,
                "expired": self._expired,
                "dropped": self._dropped,
                "inflight": self._inflight_locked(),
                "ring": len(ring),
                "ring_cap": self._completed.maxlen,
                "truncated": self._done > len(ring),
                "self_s": round(self._self_s, 6),
            }
        stages: dict[str, list] = {}
        for t in ring:
            for st, _rel, dur in t["spans"]:
                stages.setdefault(st, []).append(dur)
        sec["stages"] = {
            st: {
                "count": len(ds),
                "sum_s": round(sum(ds), 6),
                "p50_s": round(_pct(sorted(ds), 0.50), 6),
                "p99_s": round(_pct(sorted(ds), 0.99), 6),
            }
            for st, ds in sorted(
                stages.items(),
                key=lambda kv: (STAGES.index(kv[0])
                                if kv[0] in STAGES else 99))
        }
        crits = sorted(t["crit_s"] for t in ring)
        sec["critical_path"] = {
            "count": len(crits),
            "p50_s": round(_pct(crits, 0.50), 6),
            "p99_s": round(_pct(crits, 0.99), 6),
            "max_s": round(crits[-1], 6) if crits else 0.0,
        }
        ex = sorted(ring, key=lambda t: t["crit_s"], reverse=True)
        sec["exemplars"] = [
            {k: v for k, v in t.items() if not str(k).startswith("_")}
            for t in ex[:EXEMPLARS]]
        return sec

    def inflight_snapshot(self, limit: int = 8) -> list[dict]:
        """The slowest (oldest) in-flight traces — the flight recorder
        embeds these in a post-mortem dump so a divergence capture shows
        what was mid-lifecycle at fault time."""
        if not enabled():
            return []
        with self._lock:
            live = []
            for table, where in ((self._awaiting_flush, "flush"),
                                 (self._awaiting_wire, "wire"),
                                 (self._awaiting_visible, "visible")):
                for traces in table.values():
                    for tr in traces:
                        d = tr.to_dict()
                        d["awaiting"] = where
                        live.append(d)
        live.sort(key=lambda d: d["crit_s"], reverse=True)
        return live[:limit]

    def reset(self) -> None:
        with self._lock:
            self._awaiting_flush.clear()
            self._awaiting_wire.clear()
            self._awaiting_visible.clear()
            self._completed = deque(maxlen=_ring_cap())
            self._sampled = self._received = self._handed_off = 0
            self._done = self._stitched = 0
            self._expired = self._dropped = self._mutations = 0
            self._self_s = self._self_s_flushed = 0.0
            self._worst_crit = 0.0
        self._tls = threading.local()


def _pct(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(q * len(sorted_vals)))]


_plane = TracePlane()

# module-level hooks (the call-site API — every one inert when the
# plane is off beyond the cached-rate check)
finalize_begin = _plane.finalize_begin
finalize_end = _plane.finalize_end
origin_ingress = _plane.origin_ingress
remote_apply = _plane.remote_apply
admit = _plane.admit
sealed = _plane.sealed
flush_round = _plane.flush_round
wire_header = _plane.wire_header
wire_receive = _plane.wire_receive
remote_admitted = _plane.remote_admitted
visible = _plane.visible
section = _plane.section
self_seconds = _plane.self_seconds
inflight_snapshot = _plane.inflight_snapshot
reset = _plane.reset


def snapshot_section() -> dict | None:
    """None when the plane is off AND untouched — an unset process's
    snapshot must stay byte-identical to the pre-plane shape (the
    test_metrics reset contract). A receiver with its own rate unset
    but adopted traces still exports (the sender paid the decision)."""
    sec = _plane.section()
    if (sec["sample_rate"] is None and not sec["sampled"]
            and not sec["received"] and not sec["completed"]
            and not sec["inflight"]):
        return None
    return {"nodes": {sec["label"]: sec}}


def _reset_all() -> None:
    _plane.reset()


metrics.register_snapshot_section("traceplane", snapshot_section)
metrics.register_reset_hook(_reset_all)
