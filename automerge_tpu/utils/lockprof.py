"""Lock-contention profiler: instrumented lock/condition wrappers.

ROADMAP item #1 wants the service restructured around lock-free
epoch-batched ingestion (the Jiffy design, arxiv 2102.01044) because the
host side is serialized on one service lock — but until this module the
repo could not SEE that serialization: phase attribution says where a
thread spends time once it holds the lock, and nothing says how long
every other thread queued behind it. This is the instrument the refactor
lands against:

- **InstrumentedLock / InstrumentedRLock** — drop-in `threading.Lock` /
  `RLock` replacements (``with``, acquire/release, locked) that record,
  per named lock:

  * `sync_lock_wait_s{lock=...}`  — histogram of time spent WAITING for
    the lock (contended acquisitions only pay a measurable wait; the
    uncontended fast path records ~0 via a non-blocking first try);
  * `sync_lock_hold_s{lock=...}`  — histogram of outermost hold time
    (reentrant re-acquisitions of an RLock by the owner neither wait nor
    count as separate holds);
  * `sync_lock_contended_total{lock=...}` — acquisitions that found the
    lock held by another thread.

  The label is the lock's NAME (bounded cardinality: "service",
  "service_shard<k>", "peer_send", "archive" — never a per-instance id).

- **holder attribution** — while held, each lock knows its holder
  (thread name + acquiring call site file:line + since-when). Every
  instrumented lock registers in a process-wide weak registry;
  `holders_snapshot()` walks it and returns the current-holder table,
  which `flightrec.dump()` embeds in every post-mortem and
  `metrics.watchdog` appends to its fire diagnosis — so a watchdog fire
  names WHO held WHAT, not just which span stalled.

- **InstrumentedCondition** — the same wait accounting for condition
  variables (`sync_lock_wait_s{lock=...}` on `cv.wait`); provided for
  completeness of the drop-in surface (the built-in adopters are plain
  locks).

Overhead: the uncontended path costs one non-blocking try-acquire, two
`perf_counter` reads, one `sys._getframe` peek, and two histogram
updates — low single-digit microseconds, always-on by design (the
adopted locks already sit under per-ingress metrics calls heavier than
this). The holder table lives ON the lock instance (one tuple store),
so concurrent locks never contend on profiler state.

Static analysis: the graftlint lock-discipline pass recognizes these
wrappers as lock factories (analysis/lock_discipline.py
``_LOCK_FACTORIES``), so an instrumented lock keeps its class-qualified
identity (`EngineDocSet._lock`) and keeps participating in ABBA /
blocking-call analysis instead of silently degrading to the merged
`*._lock` bucket.

Runtime sanitizer: with `AMTPU_LOCKSAN=1` (utils/locksan.py) every
outermost acquire/release also reports to the lock-order sanitizer,
which checks live acquisition order against the committed
`locks_manifest.json`. The disabled path costs one module-attribute
truth test per acquire.
"""

from __future__ import annotations

import sys
import threading
import time
import weakref

from . import locksan, metrics

# every live instrumented lock/condition (weak: a dropped service must
# not pin its locks in the holder table forever)
_registry: "weakref.WeakSet" = weakref.WeakSet()
_registry_lock = threading.Lock()


def _call_site(depth: int) -> tuple[str, int]:
    """(filename, lineno) of the acquiring frame, best-effort."""
    try:
        f = sys._getframe(depth)
        return f.f_code.co_filename, f.f_lineno
    except Exception:
        return "?", 0


class InstrumentedLock:
    """Named, profiled mutual exclusion. Drop-in for `threading.Lock`
    (`reentrant=True` for `threading.RLock` semantics)."""

    _REENTRANT = False

    def __init__(self, name: str):
        self.name = name
        self._lock = (threading.RLock() if self._REENTRANT
                      else threading.Lock())
        # (thread name, ident, filename, lineno, t_acquired) while held
        self._holder: tuple | None = None
        self._depth = 0          # reentrancy depth (owner-only mutation)
        self._owner: int | None = None
        with _registry_lock:
            _registry.add(self)

    def rename(self, name: str) -> None:
        """Change the metric label (ShardedEngineDocSet renames each
        shard's service lock to `service_shard<k>` after construction)."""
        self.name = name

    # -- lock protocol -------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1,
                _depth: int = 2) -> bool:
        me = threading.get_ident()
        if self._REENTRANT and self._owner == me:
            # reentrant re-acquire by the owner: no wait, no new hold
            self._lock.acquire()
            self._depth += 1
            return True
        wait_s = 0.0
        if self._lock.acquire(blocking=False):
            acquired = True
        else:
            metrics.bump("sync_lock_contended_total", lock=self.name)
            if not blocking:
                return False
            if locksan.on:
                locksan.note_wait(self.name)
            t0 = time.perf_counter()
            try:
                acquired = (self._lock.acquire()
                            if timeout is None or timeout < 0
                            else self._lock.acquire(timeout=timeout))
            finally:
                if locksan.on:
                    locksan.note_wait_done(self.name)
            wait_s = time.perf_counter() - t0
            if not acquired:
                metrics.observe("sync_lock_wait_s", wait_s, lock=self.name)
                return False
        self._owner = me
        self._depth = 1
        fn, ln = _call_site(_depth)
        self._holder = (threading.current_thread().name, me, fn, ln,
                        time.perf_counter())
        metrics.observe("sync_lock_wait_s", wait_s, lock=self.name)
        if locksan.on:
            # strict mode can raise here: the lock IS held at that point
            # (the sanitizer is a test/storm harness, not production)
            locksan.note_acquire(self.name)
        return True

    def release(self) -> None:
        if self._REENTRANT and self._owner == threading.get_ident() \
                and self._depth > 1:
            self._depth -= 1
            self._lock.release()
            return
        holder = self._holder
        self._holder = None
        self._owner = None
        self._depth = 0
        if locksan.on:
            locksan.note_release(self.name)
        self._lock.release()
        if holder is not None:
            metrics.observe("sync_lock_hold_s",
                            time.perf_counter() - holder[4], lock=self.name)

    def locked(self) -> bool:
        return self._holder is not None

    def __enter__(self) -> "InstrumentedLock":
        self.acquire(_depth=3)
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # -- holder attribution --------------------------------------------------

    def _release_save(self) -> int:
        """Release ALL recursion levels (threading.Condition's
        _release_save contract — a reentrantly-held lock must fully
        release before the owner parks on a condition, or the notifier
        deadlocks). Returns the depth to restore."""
        holder = self._holder
        depth = max(1, self._depth)
        self._holder = None
        self._owner = None
        self._depth = 0
        if locksan.on:
            locksan.note_release(self.name)
        for _ in range(depth):
            self._lock.release()
        if holder is not None:
            metrics.observe("sync_lock_hold_s",
                            time.perf_counter() - holder[4], lock=self.name)
        return depth

    def _acquire_restore(self, depth: int, _depth: int = 3) -> None:
        """Re-acquire to the saved recursion depth (one profiled
        outermost acquire + silent inner re-acquires)."""
        self.acquire(_depth=_depth + 1)
        for _ in range(depth - 1):
            self._lock.acquire()
        self._depth = depth

    def holder(self) -> dict | None:
        """Current holder `{thread, site, held_s}` or None. Racy by
        design (a diagnostic read must never take the lock it reports
        on); the tuple swap is atomic so the result is self-consistent."""
        h = self._holder
        if h is None:
            return None
        return {"thread": h[0], "site": f"{h[2]}:{h[3]}",
                "held_s": round(time.perf_counter() - h[4], 4)}

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name!r}>"


class InstrumentedRLock(InstrumentedLock):
    """Named, profiled reentrant lock (drop-in for `threading.RLock`)."""

    _REENTRANT = True


class InstrumentedCondition:
    """Condition variable over an instrumented (or plain) lock; `wait`
    time records under `sync_lock_wait_s{lock=<name>}` so a consumer
    parked on a condition shows up in the same contention table."""

    def __init__(self, name: str, lock: InstrumentedLock | None = None):
        self.name = name
        self._ilock = lock if lock is not None else InstrumentedRLock(name)
        # the condition owns a private inner mutex; the public protocol
        # routes through the instrumented lock so holds/waits all record
        self._cv = threading.Condition(threading.Lock())

    def acquire(self) -> bool:
        return self._ilock.acquire(_depth=3)

    def release(self) -> None:
        self._ilock.release()

    def __enter__(self):
        self._ilock.acquire(_depth=3)
        return self

    def __exit__(self, *exc) -> None:
        self._ilock.release()

    def wait(self, timeout: float | None = None) -> bool:
        """Release the instrumented lock (ALL recursion levels, matching
        threading.Condition's _release_save semantics — a reentrant
        holder must not park while still owning the lock), park,
        re-acquire to the saved depth. The parked time records as wait
        on this condition's name."""
        t0 = time.perf_counter()
        with self._cv:
            saved = self._ilock._release_save()
            notified = self._cv.wait(timeout=timeout)
        self._ilock._acquire_restore(saved)
        metrics.observe("sync_lock_wait_s", time.perf_counter() - t0,
                        lock=self.name)
        return notified

    def notify(self, n: int = 1) -> None:
        with self._cv:
            self._cv.notify(n)

    def notify_all(self) -> None:
        with self._cv:
            self._cv.notify_all()


def holders_snapshot() -> dict[str, dict]:
    """Current-holder table across every live instrumented lock:
    `{lock_name: {"thread": ..., "site": "file.py:123", "held_s": ...}}`.
    Only held locks appear. This is the table flightrec embeds in every
    post-mortem and the watchdog appends to its fire line — the "who held
    what" the r5 hang diagnosis lacked. Duplicate names (many peers share
    "peer_send") keep the longest-held entry — the interesting one."""
    with _registry_lock:
        locks = list(_registry)
    out: dict[str, dict] = {}
    for lk in locks:
        h = lk.holder()
        if h is None:
            continue
        prev = out.get(lk.name)
        if prev is None or h["held_s"] > prev["held_s"]:
            out[lk.name] = h
    return out
