"""UUID source with a swappable factory for deterministic tests.

Mirrors the behavior of /root/reference/src/uuid.js:1-12: `make_uuid()` returns
a fresh v4 UUID string; `set_factory` swaps the generator (used by tests to get
deterministic object IDs); `reset` restores the default.
"""

from __future__ import annotations

import uuid as _uuid
from typing import Callable


def _default_factory() -> str:
    return str(_uuid.uuid4())


_factory: Callable[[], str] = _default_factory


def make_uuid() -> str:
    return _factory()


def set_factory(factory: Callable[[], str]) -> None:
    global _factory
    _factory = factory


def reset() -> None:
    global _factory
    _factory = _default_factory
