"""Process-wide generational-GC pause with a refcount.

Burst allocation phases (coalesced ingress, bulk builds, round encodes)
trigger gen-2 collections that scan the WHOLE service heap — measured at
~2/3 of ingress cost on a 2K-doc node and ~4x the round cost on a
100K-doc fleet node. Python's gc enable/disable is process-global, so
independent pause sites on concurrent threads (two service nodes syncing
over Connections) would re-enable each other mid-burst if each tracked
its own was-enabled flag; this refcount makes nesting and concurrency
safe: GC re-enables only when the LAST pauser exits, and never if
something outside had already disabled it.
"""

from __future__ import annotations

import contextlib
import gc
import threading

_lock = threading.Lock()
_depth = 0
_we_disabled = False


@contextlib.contextmanager
def gc_paused():
    global _depth, _we_disabled
    with _lock:
        _depth += 1
        if _depth == 1:
            _we_disabled = gc.isenabled()
            if _we_disabled:
                gc.disable()
    try:
        yield
    finally:
        with _lock:
            _depth -= 1
            if _depth == 0 and _we_disabled:
                gc.enable()
                _we_disabled = False
