"""Chaos fault-injection hooks for the fleet health plane.

The collector/SLO/doctor stack (perf/fleet.py, perf/slo.py,
perf/doctor.py) claims it can flag the degraded node in a fleet and rank
the injected root cause first. That claim is only testable if the repo
can DEGRADE a node on purpose — this module is the fault injector, three
hooks matching the three failure classes the doctor distinguishes:

- **slow-apply** (`AMTPU_CHAOS_SLOW_APPLY_S=<seconds>`): every coalesced
  round flush of an affected rows service sleeps that long inside the
  flush window (sync/service.py `_flush_pending_locked`). Signature: the
  node's `sync_round_flush_s` per-round mean and oplag `flush` stage
  inflate; lock wait inflates only as a CONSEQUENCE of the long flush.
- **lock-hold** (`AMTPU_CHAOS_LOCK_HOLD_S=<seconds>`, period
  `AMTPU_CHAOS_LOCK_HOLD_EVERY_S`, default 0.2): a chaos holder thread
  (`amtpu-chaos-lockhold`, spawned by `EngineDocSet.__init__` via
  `maybe_lock_holder`) periodically acquires the service lock and sits
  on it. Signature: `sync_lock_wait_s{lock=service*}` and the holder
  table inflate while the round-flush wall itself stays normal — the
  separation the doctor's ranking leans on.
- **frame-drop** (`AMTPU_CHAOS_DROP_FRAMES=<probability>`): outgoing
  CHANGE-BEARING transport messages are dropped before the socket write
  (sync/tcp.py `_Peer._send`, counted as `sync_frames_dropped`).
  Telemetry/audit/clock messages are never dropped — chaos degrades the
  data plane, not the instruments observing it (a fault injector that
  blinds the collector proves nothing).
- **doc-stall** (`AMTPU_CHAOS_STALL_DOC=<doc_id>`): outgoing
  change-bearing messages for EXACTLY one doc are suppressed at the
  Connection layer (sync/connection.py `send_msg`) — the per-doc fault
  class the convergence ledger + `perf explain` must localize (bench
  config 12). Every other doc keeps syncing; the victim doc's clock
  keeps being advertised, so peers SEE the frontier they cannot reach.
- **sub-flap** (`AMTPU_CHAOS_SUB_FLAP_DOC=<doc_id>`, cadence
  `AMTPU_CHAOS_SUB_FLAP_EVERY`): subscribe/unsubscribe churn on one doc
  at the SUBSCRIBER side of an explicit-interest connection
  (sync/connection.py `_maybe_sub_flap`) — the interest-plane fault
  class; the victim doc's lag must come out of `perf explain` as
  doc_unsubscribed (with the churn noted from the ledger's sub_events
  lane), never as a transport stall.
- **conn-kill** (`AMTPU_CHAOS_CONN_KILL_AFTER=<n>`): tear down an
  ESTABLISHED peer socket mid-stream — the n-th outgoing transport
  message of an affected peer hard-closes the socket instead of being
  written (sync/tcp.py `_Peer._send`). Fires ONCE per node key, then
  stays inert until `reload()`: the fault under test is a single
  transport death, and the thing being proven is that the reconnect
  supervisor (sync/tcp.SupervisedTcpClient) brings the link back and
  `resubscribe()` backfills what the dead window missed — the
  remediation plane's acceptance input (bench config 14).
- **tenant-storm** (`AMTPU_CHAOS_TENANT_STORM=<tenant_id>`, multiplier
  `AMTPU_CHAOS_TENANT_STORM_X`, default 8): ONE tenant's epoch-path
  ingress rate is multiplied — every governed append whose doc resolves
  to the victim tenant (sync/tenantledger.py derivation) is re-appended
  x-1 extra times as un-waited entries (sync/service.py
  `_epoch_append`). Duplicate changes dedup at (actor, seq) admission,
  so document STATE stays byte-identical while the flush/dispatch planes
  pay the storm for real — the noisy-neighbor fault class the tenant
  attribution plane (`tenant_hot` doctor cause, bench config 18) must
  localize without the quiet tenants' telemetry degrading.
- **peer-hang** (`AMTPU_CHAOS_PEER_HANG_S=<seconds>`, onset
  `AMTPU_CHAOS_PEER_HANG_AFTER=<n>`, default 1): an accepted but
  UNRESPONSIVE peer — for that many seconds from the n-th eligible
  receive, an affected peer's transport reader swallows every incoming
  message unprocessed (sync/tcp.py `_Peer._read_loop`): the socket
  stays open and deliverable, but nothing is applied and nothing
  (metrics pulls included) is answered. The onset count lets a bench
  open the window mid-traffic instead of on the very first handshake
  message. The supervisor's idle detector is what must notice — a
  dead-quiet inbound link with a live socket — and force a reconnect
  whose resubscribe recovers the swallowed suffix.

Targeting: `AMTPU_CHAOS_NODE=<label>` restricts injection to services /
transports whose owner set `_chaos_node` to that label — needed when
several fleet nodes share one process (tests). Unset, every node in the
process is affected — which is exactly right for the bench's
one-peer-per-process fault-injection config (the parent sets the chaos
env only in the degraded peer's environment).

Inertness contract (tests/test_chaos.py): with no `AMTPU_CHAOS_*` set,
every hook is one cached attribute check and returns — zero metrics,
zero events, zero threads. `reload()` re-reads the env (tests flip knobs
per-case).

Every injection is disclosed: `obs_chaos_injected{fault=...}` counts it
and a `chaos_inject` flight-recorder event records it, so a post-mortem
from a chaos run can never be mistaken for an organic failure.
"""

from __future__ import annotations

import os
import random
import threading
import time

from . import flightrec, metrics

# The sleeps below are the PRODUCT of this module: slow-apply sleeps
# inside a held service lock by design (that is the fault being
# injected). The alias keeps graftlint's block-under-lock rule — which
# guards against ACCIDENTAL stalls — from flagging every product call
# site that can reach a deliberately-injected one; the injection is
# env-gated, disclosed via obs_chaos_injected, and off in production.
_sleep = time.sleep

#: default seconds between two chaos lock holds
DEFAULT_HOLD_EVERY_S = 0.2

#: default sub_flap cadence: one subscribe/unsubscribe toggle per this
#: many eligible received messages of the victim doc
DEFAULT_FLAP_EVERY = 4


class _Config:
    __slots__ = ("slow_apply_s", "lock_hold_s", "lock_hold_every_s",
                 "drop_frames", "stall_doc_id", "sub_flap_doc_id",
                 "sub_flap_every", "conn_kill_after", "peer_hang_s",
                 "peer_hang_after", "disk_stall_s", "tenant_storm_id",
                 "tenant_storm_x", "node", "any")

    def __init__(self):
        def _f(name, default=0.0):
            try:
                return float(os.environ.get(name, "") or default)
            except ValueError:
                return default
        self.slow_apply_s = max(0.0, _f("AMTPU_CHAOS_SLOW_APPLY_S"))
        self.lock_hold_s = max(0.0, _f("AMTPU_CHAOS_LOCK_HOLD_S"))
        self.lock_hold_every_s = max(
            0.001, _f("AMTPU_CHAOS_LOCK_HOLD_EVERY_S", DEFAULT_HOLD_EVERY_S))
        self.drop_frames = min(1.0, max(0.0, _f("AMTPU_CHAOS_DROP_FRAMES")))
        self.stall_doc_id = os.environ.get("AMTPU_CHAOS_STALL_DOC") or None
        self.sub_flap_doc_id = (os.environ.get("AMTPU_CHAOS_SUB_FLAP_DOC")
                                or None)
        self.sub_flap_every = max(
            1, int(_f("AMTPU_CHAOS_SUB_FLAP_EVERY", DEFAULT_FLAP_EVERY)))
        self.conn_kill_after = max(0, int(_f("AMTPU_CHAOS_CONN_KILL_AFTER")))
        self.peer_hang_s = max(0.0, _f("AMTPU_CHAOS_PEER_HANG_S"))
        self.peer_hang_after = max(1, int(_f("AMTPU_CHAOS_PEER_HANG_AFTER",
                                             1)))
        self.disk_stall_s = max(0.0, _f("AMTPU_CHAOS_DISK_STALL_S"))
        self.tenant_storm_id = (os.environ.get("AMTPU_CHAOS_TENANT_STORM")
                                or None)
        self.tenant_storm_x = max(2, int(_f("AMTPU_CHAOS_TENANT_STORM_X",
                                            8)))
        self.node = os.environ.get("AMTPU_CHAOS_NODE") or None
        self.any = bool(self.slow_apply_s or self.lock_hold_s
                        or self.drop_frames or self.stall_doc_id
                        or self.sub_flap_doc_id or self.conn_kill_after
                        or self.peer_hang_s or self.disk_stall_s
                        or self.tenant_storm_id)


_config: _Config | None = None


def _cfg() -> _Config:
    global _config
    c = _config
    if c is None:
        _config = c = _Config()
    return c


def reload() -> None:
    """Re-read the AMTPU_CHAOS_* env (tests flip knobs between cases;
    already-running lock holders are unaffected — stop them via their
    handle)."""
    global _config
    _config = None
    _flap_counts.clear()
    _kill_counts.clear()
    _hang_counts.clear()
    _hang_started.clear()


def enabled() -> bool:
    return _cfg().any


def _match(c: _Config, node: str | None) -> bool:
    """Targeting: with AMTPU_CHAOS_NODE set, only owners labeled with
    that exact node are affected; unset targets every node (the
    process-per-peer posture)."""
    return c.node is None or node == c.node


def _disclose(fault: str, node: str | None, **fields) -> None:
    metrics.bump("obs_chaos_injected", fault=fault)
    flightrec.record("chaos_inject", fault=fault, node=node, **fields)


def slow_apply(node: str | None = None) -> None:
    """Injection point inside a rows service's round flush: sleep
    AMTPU_CHAOS_SLOW_APPLY_S inside the flush window (and therefore
    under the service lock — the fault IS a slow engine apply)."""
    c = _cfg()
    if not c.slow_apply_s or not _match(c, node):
        return
    _disclose("slow_apply", node, s=c.slow_apply_s)
    _sleep(c.slow_apply_s)


def disk_stall(node: str | None = None) -> None:
    """Injection point in the storage tier's durability paths
    (`AMTPU_CHAOS_DISK_STALL_S=<seconds>`): every archive/seal/snapshot
    fsync (sync/logarchive.py `_fsync_file`, sync/snapshots.py write)
    sleeps that long first — a slow or overloaded disk. Signature: the
    node's `sync_archive_fsync_s` histogram inflates while round
    flushes and lock waits stay ordinary, which is what lets the doctor
    attribute slow-append/slow-bootstrap to `storage_stall` instead of
    the engine. Inert (one cached check) unless the knob is set; every
    injection is disclosed."""
    c = _cfg()
    if not c.disk_stall_s or not _match(c, node):
        return
    _disclose("disk_stall", node, s=c.disk_stall_s)
    _sleep(c.disk_stall_s)


def drop_frame(node: str | None = None, kind: str = "frame") -> bool:
    """True when the transport should drop this outgoing message.
    Only change-bearing kinds ("frame"/"changes") are ever dropped —
    metrics pulls, audit digests, and clock adverts always pass, so the
    health plane keeps observing the node it is degrading."""
    c = _cfg()
    if not c.drop_frames or not _match(c, node):
        return False
    if kind not in ("frame", "changes"):
        return False
    if random.random() >= c.drop_frames:
        return False
    _disclose("frame_drop", node, kind=kind)
    return True


def stall_doc(node: str | None, doc_id: str) -> bool:
    """True when outgoing change-bearing messages for EXACTLY this doc
    should be suppressed (`AMTPU_CHAOS_STALL_DOC=<doc_id>`): the per-doc
    stall the doc-granular observability plane must localize — every
    OTHER doc keeps syncing, clock adverts keep flowing, and only the
    victim doc's changes die at the sender. Transport-agnostic: the hook
    sits in Connection.send_msg, so in-process meshes degrade the same
    way TCP fleets do. Caller counts the drop (sync_frames_dropped +
    the ledger's per-doc drop lane)."""
    c = _cfg()
    if c.stall_doc_id is None or not _match(c, node):
        return False
    if doc_id != c.stall_doc_id:
        return False
    _disclose("doc_stall", node, doc=doc_id)
    return True


# per-(node, doc) eligible-event counters for the sub_flap cadence —
# cleared by reload() so per-case env flips restart the rhythm
_flap_counts: dict = {}


def sub_flap(node: str | None, doc_id: str) -> bool:
    """True when the subscriber-side connection should TOGGLE its
    subscription for exactly this doc (`AMTPU_CHAOS_SUB_FLAP_DOC=<doc>`,
    cadence `AMTPU_CHAOS_SUB_FLAP_EVERY`, default one toggle per 4
    eligible events): subscribe/unsubscribe churn — the interest-plane
    fault class whose induced lag `perf explain` must attribute as
    doc_unsubscribed-with-churn instead of flagging a stall. The hook
    sits in Connection's receive path and only fires on connections
    with an explicit local interest; every toggle is disclosed
    (obs_chaos_injected{fault=sub_flap} + a chaos_inject event)."""
    c = _cfg()
    if c.sub_flap_doc_id is None or not _match(c, node):
        return False
    if doc_id != c.sub_flap_doc_id:
        return False
    key = (node, doc_id)
    n = _flap_counts.get(key, 0) + 1
    _flap_counts[key] = n
    if n % c.sub_flap_every:
        return False
    _disclose("sub_flap", node, doc=doc_id)
    return True


# per-node outgoing-message counters for conn_kill; the sentinel -1
# marks "already fired" (one transport death per node key per reload)
_kill_counts: dict = {}

# per-node peer_hang state: receive count until onset, then the wall
# clock the window opened at; cleared by reload()
_hang_counts: dict = {}
_hang_started: dict = {}


def conn_kill(node: str | None = None) -> bool:
    """True exactly ONCE per node key, on the n-th eligible outgoing
    transport message (`AMTPU_CHAOS_CONN_KILL_AFTER=<n>`): the caller
    (sync/tcp.py `_Peer._send`) hard-closes the socket instead of
    writing — an established connection torn down mid-stream, the
    reconnect supervisor's acceptance input. Inert unset; fires once
    and then stays quiet until reload() (the fault under test is a
    single transport death, not flapping — churn is sub_flap's job)."""
    c = _cfg()
    if not c.conn_kill_after or not _match(c, node):
        return False
    n = _kill_counts.get(node, 0)
    if n < 0:
        return False            # already fired for this node key
    n += 1
    if n < c.conn_kill_after:
        _kill_counts[node] = n
        return False
    _kill_counts[node] = -1
    _disclose("conn_kill", node, after=c.conn_kill_after)
    return True


def peer_hang(node: str | None = None) -> bool:
    """True while the hang window is open (`AMTPU_CHAOS_PEER_HANG_S=
    <seconds>`, opening at the `AMTPU_CHAOS_PEER_HANG_AFTER`-th
    eligible receive — default 1, i.e. immediately): the caller
    (sync/tcp.py `_Peer._read_loop`) swallows the incoming message
    unprocessed — an accepted but unresponsive peer. The socket stays
    open and keeps delivering, so nothing times out at the transport;
    only an idle detector watching PROCESSED inbound activity
    (SupervisedTcpClient `idle_reconnect_s`) can tell this apart from a
    quiet link. Every swallow is disclosed."""
    c = _cfg()
    if not c.peer_hang_s or not _match(c, node):
        return False
    now = time.monotonic()
    started = _hang_started.get(node)
    if started is None:
        n = _hang_counts.get(node, 0) + 1
        _hang_counts[node] = n
        if n < c.peer_hang_after:
            return False        # window not open yet
        _hang_started[node] = started = now
    if now - started >= c.peer_hang_s:
        return False            # window expired: responsive again
    _disclose("peer_hang", node, s=c.peer_hang_s)
    return True


def tenant_storm(node: str | None, doc_id: str) -> int:
    """Extra ingress copies this epoch append should enqueue (0 = no
    storm): `AMTPU_CHAOS_TENANT_STORM=<tenant_id>` multiplies exactly
    that tenant's epoch-path ingress by `AMTPU_CHAOS_TENANT_STORM_X`
    (default 8, min 2) — the caller (sync/service.py `_epoch_append`)
    appends the batch x-1 additional times as un-waited entries.
    Duplicate changes dedup at (actor, seq) admission, so the storm
    costs real flush/dispatch/wire work without corrupting state. Inert
    (one cached check) unset; every fire is disclosed."""
    c = _cfg()
    if c.tenant_storm_id is None or not _match(c, node):
        return 0
    from ..sync.tenantledger import tenant_of
    tid = tenant_of(doc_id)
    if tid != c.tenant_storm_id:
        return 0
    _disclose("tenant_storm", node, tenant=tid, x=c.tenant_storm_x)
    return c.tenant_storm_x - 1


class LockHolder:
    """Chaos thread that periodically acquires a lock and sits on it for
    `hold_s` — the deliberate re-creation of the r5 stall class, scaled
    down. The lockprof holder table names this thread
    (`amtpu-chaos-lockhold`), so a doctor report on a chaos run shows
    exactly the who-held-what evidence a real stall would."""

    def __init__(self, lock, hold_s: float, every_s: float,
                 node: str | None = None):
        self._lock_ref = lock
        self.hold_s = hold_s
        self.every_s = every_s
        self.node = node
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="amtpu-chaos-lockhold", daemon=True)

    def start(self) -> "LockHolder":
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop and join (idempotent); waits out at most one hold."""
        self._stop.set()
        self._thread.join(timeout=10.0 + self.hold_s)

    def _loop(self) -> None:
        while not self._stop.wait(self.every_s):
            with self._lock_ref:
                _disclose("lock_hold", self.node, s=self.hold_s)
                _sleep(self.hold_s)


def maybe_lock_holder(lock, node: str | None = None) -> LockHolder | None:
    """Start a LockHolder against `lock` when AMTPU_CHAOS_LOCK_HOLD_S is
    set (and the node matches any AMTPU_CHAOS_NODE targeting). Returns
    the handle (caller owns stop()) or None when inert.

    sync/service.py calls this at service construction, so a process
    launched with the knob set degrades every service it hosts — the
    bench's degraded-peer subprocess needs no code of its own. In-process
    multi-node tests pass an explicit matching `node` label instead."""
    c = _cfg()
    if not c.lock_hold_s or not _match(c, node):
        return None
    return LockHolder(lock, c.lock_hold_s, c.lock_hold_every_s,
                      node=node).start()
