"""Lightweight persistent containers for the semantic core.

The reference keeps every piece of CRDT state in Immutable.js structures so that
old document snapshots stay valid after new changes are applied
(/root/reference/src/op_set.js:272-285). We get the same persistence guarantee
with two cheaper devices tuned for the actual mutation patterns:

- `AList`: an append-only shared-backing list view. Appending to the newest view
  is O(1) amortized (it extends the shared backing list in place); appending to
  an older view copies the prefix. Change histories, per-actor state lists and
  undo/redo stacks are append-mostly, so forks are rare and cheap.
- copy-on-write dicts, managed by the OpSet builder (one shallow copy per
  *batch* of changes rather than per op).
"""

from __future__ import annotations

from itertools import islice
from typing import Any, Iterator


class AList:
    """Persistent append-only list: views share one backing list.

    A view is (backing, length). `append` mutates the backing in place when the
    view is the newest one (length == len(backing)); otherwise it copies the
    visible prefix. Old views never observe appends made through newer views.
    """

    __slots__ = ("_backing", "_length")

    def __init__(self, backing: list | None = None, length: int | None = None):
        self._backing = backing if backing is not None else []
        self._length = length if length is not None else len(self._backing)

    def append(self, item: Any) -> "AList":
        if self._length == len(self._backing):
            self._backing.append(item)
            return AList(self._backing, self._length + 1)
        backing = self._backing[: self._length]
        backing.append(item)
        return AList(backing, self._length + 1)

    def extend(self, items) -> "AList":
        out = self
        for item in items:
            out = out.append(item)
        return out

    def __len__(self) -> int:
        return self._length

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return list(islice(self._backing, *idx.indices(self._length)))
        if idx < 0:
            idx += self._length
        if not 0 <= idx < self._length:
            raise IndexError(idx)
        return self._backing[idx]

    def __iter__(self) -> Iterator[Any]:
        return islice(iter(self._backing), self._length)

    def __repr__(self) -> str:
        return f"AList({list(self)!r})"


EMPTY_ALIST = AList([], 0)
