"""Lightweight persistent containers for the semantic core.

The reference keeps every piece of CRDT state in Immutable.js structures so that
old document snapshots stay valid after new changes are applied
(/root/reference/src/op_set.js:272-285). We get the same persistence guarantee
with two cheaper devices tuned for the actual mutation patterns:

- `AList`: an append-only shared-backing list view. Appending to the newest view
  is O(1) amortized (it extends the shared backing list in place); appending to
  an older view copies the prefix. Change histories, per-actor state lists and
  undo/redo stacks are append-mostly, so forks are rare and cheap.
- copy-on-write dicts, managed by the OpSet builder (one shallow copy per
  *batch* of changes rather than per op).
"""

from __future__ import annotations

from itertools import islice
from typing import Any, Iterator


class AList:
    """Persistent append-only list: views share one backing list.

    A view is (backing, length). `append` mutates the backing in place when the
    view is the newest one (length == len(backing)); otherwise it copies the
    visible prefix. Old views never observe appends made through newer views.
    """

    __slots__ = ("_backing", "_length")

    def __init__(self, backing: list | None = None, length: int | None = None):
        self._backing = backing if backing is not None else []
        self._length = length if length is not None else len(self._backing)

    def append(self, item: Any) -> "AList":
        if self._length == len(self._backing):
            self._backing.append(item)
            return AList(self._backing, self._length + 1)
        backing = self._backing[: self._length]
        backing.append(item)
        return AList(backing, self._length + 1)

    def extend(self, items) -> "AList":
        out = self
        for item in items:
            out = out.append(item)
        return out

    def __len__(self) -> int:
        return self._length

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return list(islice(self._backing, *idx.indices(self._length)))
        if idx < 0:
            idx += self._length
        if not 0 <= idx < self._length:
            raise IndexError(idx)
        return self._backing[idx]

    def __iter__(self) -> Iterator[Any]:
        return islice(iter(self._backing), self._length)

    def __repr__(self) -> str:
        return f"AList({list(self)!r})"


EMPTY_ALIST = AList([], 0)


# ---------------------------------------------------------------------------
# PMap: a persistent hash map (hash array mapped trie)

_SHIFT = 5
_MASK = 31

# node kinds (first tuple element)
_LEAF = 0       # (_LEAF, hash, key, value)
_COLL = 1       # (_COLL, hash, ((k, v), ...))
_BITMAP = 2     # (_BITMAP, bitmap, (child, ...))

_BM_ABSENT = object()   # _bm_set's "key was not present" old-value marker


def _bm_set(node, shift, h, key, value):
    """Returns (new_node, added, old_value) — `old_value` is _BM_ABSENT
    when the key was not present, so writers that need the displaced
    value (CowDict.__setitem__'s existed-in-base check) get it from the
    SAME walk instead of paying a second full lookup (the r16 keystroke
    profile's worst single overhead: every shared-mode write walked the
    overlay twice)."""
    if node is None:
        return (_LEAF, h, key, value), 1, _BM_ABSENT
    kind = node[0]
    if kind == _LEAF:
        nh, nk = node[1], node[2]
        if nh == h and nk == key:
            return (_LEAF, h, key, value), 0, node[3]
        if nh == h:
            return (_COLL, h, ((nk, node[3]), (key, value))), 1, _BM_ABSENT
        merged, _, _ = _bm_set(None, shift, nh, nk, node[3])
        wrapped = (_BITMAP, 1 << ((nh >> shift) & _MASK), (merged,))
        return _bm_set(wrapped, shift, h, key, value)
    if kind == _COLL:
        if node[1] == h:
            entries = node[2]
            for i, (k, _v) in enumerate(entries):
                if k == key:
                    return (_COLL, h, entries[:i] + ((key, value),)
                            + entries[i + 1:]), 0, entries[i][1]
            return (_COLL, h, entries + ((key, value),)), 1, _BM_ABSENT
        wrapped = (_BITMAP, 1 << ((node[1] >> shift) & _MASK), (node,))
        return _bm_set(wrapped, shift, h, key, value)
    bitmap, children = node[1], node[2]
    bit = 1 << ((h >> shift) & _MASK)
    idx = bin(bitmap & (bit - 1)).count("1")
    if bitmap & bit:
        child, added, old = _bm_set(children[idx], shift + _SHIFT, h, key,
                                    value)
        return (_BITMAP, bitmap,
                children[:idx] + (child,) + children[idx + 1:]), added, old
    leaf = (_LEAF, h, key, value)
    return (_BITMAP, bitmap | bit,
            children[:idx] + (leaf,) + children[idx:]), 1, _BM_ABSENT


def _bm_get(node, shift, h, key, default):
    while node is not None:
        kind = node[0]
        if kind == _LEAF:
            if node[1] == h and node[2] == key:
                return node[3]
            return default
        if kind == _COLL:
            if node[1] == h:
                for k, v in node[2]:
                    if k == key:
                        return v
            return default
        bit = 1 << ((h >> shift) & _MASK)
        if not node[1] & bit:
            return default
        idx = bin(node[1] & (bit - 1)).count("1")
        node = node[2][idx]
        shift += _SHIFT
    return default


def _bm_delete(node, shift, h, key):
    """Returns (new_node | None, removed: bool)."""
    if node is None:
        return None, False
    kind = node[0]
    if kind == _LEAF:
        if node[1] == h and node[2] == key:
            return None, True
        return node, False
    if kind == _COLL:
        if node[1] != h:
            return node, False
        entries = tuple(e for e in node[2] if e[0] != key)
        if len(entries) == len(node[2]):
            return node, False
        if len(entries) == 1:
            return (_LEAF, h, entries[0][0], entries[0][1]), True
        return (_COLL, h, entries), True
    bitmap, children = node[1], node[2]
    bit = 1 << ((h >> shift) & _MASK)
    if not bitmap & bit:
        return node, False
    idx = bin(bitmap & (bit - 1)).count("1")
    child, removed = _bm_delete(children[idx], shift + _SHIFT, h, key)
    if not removed:
        return node, False
    if child is None:
        rest = children[:idx] + children[idx + 1:]
        if not rest:
            return None, True
        if len(rest) == 1 and rest[0][0] != _BITMAP:
            return rest[0], True
        return (_BITMAP, bitmap & ~bit, rest), True
    return (_BITMAP, bitmap, children[:idx] + (child,) + children[idx + 1:]), \
        True


class PMap:
    """Persistent string-keyed hash map (HAMT, 32-way). `set`/`delete`
    return new maps sharing structure with the old — the device the
    reference gets from Immutable.js Map (used for the skip list's
    key->node index, src/skip_list.js). O(log32 n) per operation."""

    __slots__ = ("_root", "_size")

    def __init__(self, root=None, size=0):
        self._root = root
        self._size = size

    def get(self, key, default=None):
        return _bm_get(self._root, 0, hash(key) & 0xFFFFFFFF, key, default)

    def set(self, key, value) -> "PMap":
        root, added, _old = _bm_set(self._root, 0, hash(key) & 0xFFFFFFFF,
                                    key, value)
        return PMap(root, self._size + added)

    def set_lookup(self, key, value):
        """(new map, displaced value or the _BM_ABSENT marker) from ONE
        walk — the write-path twin of get() for callers that need the
        old value anyway (CowDict.__setitem__)."""
        root, added, old = _bm_set(self._root, 0, hash(key) & 0xFFFFFFFF,
                                   key, value)
        return PMap(root, self._size + added), old

    def delete(self, key) -> "PMap":
        root, removed = _bm_delete(self._root, 0, hash(key) & 0xFFFFFFFF, key)
        return PMap(root, self._size - removed) if removed else self

    def __len__(self) -> int:
        return self._size

    def __contains__(self, key) -> bool:
        sentinel = object()
        return self.get(key, sentinel) is not sentinel

    def items(self):
        def walk(node):
            if node is None:
                return
            kind = node[0]
            if kind == _LEAF:
                yield node[2], node[3]
            elif kind == _COLL:
                yield from node[2]
            else:
                for child in node[2]:
                    yield from walk(child)
        yield from walk(self._root)

    def __iter__(self):
        for k, _v in self.items():
            yield k


EMPTY_PMAP = PMap()


# ---------------------------------------------------------------------------
# CowDict: dict with O(1) copy-on-write snapshots

_DELETED = object()
_ABSENT = object()


class CowDict:
    """Dict-like map whose `copy()` is O(1): a shared plain-dict base plus a
    persistent PMap overlay. Fresh (never-copied) instances write straight
    into the base at dict speed; once copied, writers go to their own
    overlay (structure-shared, so siblings and ancestors are unaffected),
    and a large overlay is folded into a fresh base — amortized O(1).

    This is the role Immutable.js Map plays for the reference's per-object
    CRDT state (src/op_set.js:272-285): big sequence objects stop paying
    O(n) per change-batch snapshot. Iteration order: base insertion order,
    then overlay additions in hash order (callers that need sequence order
    use the element index, not this map).
    """

    __slots__ = ("_base", "_over", "_size", "_shared")

    def __init__(self, base: dict | None = None):
        self._base = {} if base is None else base
        self._over = EMPTY_PMAP
        self._size = len(self._base)
        self._shared = False

    def copy(self) -> "CowDict":
        self._shared = True
        out = CowDict.__new__(CowDict)
        out._base = self._base
        out._over = self._over
        out._size = self._size
        out._shared = True
        return out

    def _maybe_rebase(self) -> None:
        if len(self._over) <= max(512, len(self._base) // 4):
            return
        self.rebase()

    def rebase(self) -> None:
        """Fold the overlay into a PRIVATE base fork now (O(n)) so
        subsequent writes run at plain-dict speed. Sharing-safe: the old
        base is forked, never mutated, so sibling snapshots are
        unaffected. No-op when already owned. Callers with a large write
        burst pending (the span-merge plane, core/textspans.py) invoke
        this up front: one base fork beats thousands of persistent-overlay
        updates."""
        if not self._shared and not len(self._over):
            return
        base = dict(self._base)
        for k, v in self._over.items():
            if v is _DELETED:
                base.pop(k, None)
            else:
                base[k] = v
        self._base = base
        self._over = EMPTY_PMAP
        self._shared = False   # fresh base: in-place writes are safe again

    # -- reads -------------------------------------------------------------

    def get(self, key, default=None):
        over = self._over
        if over._size:
            # inlined PMap.get (this is the engine's hottest read: ~20
            # calls per keystroke through the apply path)
            v = _bm_get(over._root, 0, hash(key) & 0xFFFFFFFF, key,
                        _ABSENT)
            if v is not _ABSENT:
                return default if v is _DELETED else v
        v = self._base.get(key, _ABSENT)
        return default if v is _ABSENT else v

    def __getitem__(self, key):
        v = self.get(key, _DELETED)
        if v is _DELETED:
            raise KeyError(key)
        return v

    def __contains__(self, key) -> bool:
        return self.get(key, _DELETED) is not _DELETED

    def __len__(self) -> int:
        return self._size

    def __bool__(self) -> bool:
        return self._size > 0

    def items(self):
        over = self._over
        if not len(over):
            yield from self._base.items()
            return
        od = dict(over.items())
        for k, v in self._base.items():
            if k in od:
                w = od.pop(k)
                if w is not _DELETED:
                    yield k, w
            else:
                yield k, v
        for k, w in od.items():
            if w is not _DELETED:
                yield k, w

    def keys(self):
        for k, _v in self.items():
            yield k

    def values(self):
        for _k, v in self.items():
            yield v

    def __iter__(self):
        return self.keys()

    # -- writes ------------------------------------------------------------

    def __setitem__(self, key, value) -> None:
        if self._shared:
            # one overlay walk, not two: set_lookup returns the value it
            # displaced, and only a key absent from the overlay needs the
            # (plain-dict-cheap) base membership probe
            self._over, old = self._over.set_lookup(key, value)
            if old is _BM_ABSENT:
                existed = key in self._base
            else:
                existed = old is not _DELETED
            if not existed:
                self._size += 1
            self._maybe_rebase()
        else:
            if key not in self._base:
                self._size += 1
            self._base[key] = value

    def pop(self, key, *default):
        v = self.get(key, _DELETED)
        if v is _DELETED:
            if default:
                return default[0]
            raise KeyError(key)
        if self._shared:
            if key in self._base:
                self._over = self._over.set(key, _DELETED)
            else:
                self._over = self._over.delete(key)
            self._size -= 1
            self._maybe_rebase()
        else:
            del self._base[key]
            self._size -= 1
        return v

    def __delitem__(self, key) -> None:
        self.pop(key)

    def __eq__(self, other):
        if isinstance(other, CowDict):
            return dict(self.items()) == dict(other.items())
        if isinstance(other, dict):
            return dict(self.items()) == other
        return NotImplemented

    def __repr__(self) -> str:
        return f"CowDict({dict(self.items())!r})"
