"""Performance plane: compile telemetry, phase attribution, memory gauges.

PRs 1-3 made the repo observable for *liveness* (spans, watchdog, flight
recorder, convergence audit); this module is the matching *performance*
plane the ROADMAP north star ("as fast as the hardware allows") needs to
be checkable run over run:

- **compile telemetry** — `metrics.dispatch_jit` routes every jitted
  kernel call through `dispatch_begin()`/`dispatch_end()` here. Compile
  events are observed exactly via `jax.monitoring` duration listeners
  (the cpp jit cache fires `/jax/core/compile/*` events only on a real
  cache miss), attributed to the dispatching kernel through a
  thread-local marker stack — replacing the old `_cache_size()` delta,
  which was thread-racy and misattributed concurrent dispatches. On the
  first sighting of a (kernel, abstract-signature) pair the kernel is
  also analyzed ahead of the call: `fn.lower(...)` for XLA
  `cost_analysis()` flops/bytes and (mode `full`) an AOT
  `lowered.compile()` for `memory_analysis()` HBM sections. Results
  land as registered gauges (`engine_kernel_flops{kernel=...}`,
  `engine_kernel_hbm_bytes{kernel=...,section=...}`) and in the `perf`
  section of `metrics.snapshot()`.
- **phase attribution** — `phase(name)` accumulates wall time into one
  of the registered PHASES (pack → dispatch → device_wait → readback →
  host_materialize → sync_wire), so a run self-reports where its time
  went across layers. Phase names are lint-enforced (the graftlint
  registry pass) the same way metric names are.
- **memory gauges** — a throttled `jax.live_arrays()` sample maintains
  the live-array footprint and its high-water mark
  (`obs_live_arrays_bytes` / `obs_live_arrays_peak_bytes`); the engines
  publish their resident-state footprints (`rows_resident_bytes`,
  `engine_resident_bytes`, `sync_shard_resident_bytes{shard=...}`). All
  of it rides inside `metrics.snapshot()`, so every flight-recorder
  post-mortem embeds the memory picture at the time of the hang.

Analysis cost note: the AOT `lowered.compile()` used for
`memory_analysis()` duplicates the backend compile the jit call itself
pays, once per new kernel signature. The default mode is backend-aware
(`AMTPU_PERFSCOPE=auto`): full analysis everywhere except the tpu
backend, which gets the cheap trace-only cost analysis — remote compiles
on the tunnel are the repo's documented wedge hazard and must not be
doubled by a profiling nicety. `AMTPU_PERFSCOPE=full` forces HBM
sections on TPU too; `cost` forces trace-only; `0` disables signature
analysis entirely. Compile *observation* (counts + attributed wall time)
is listener-based and has no such cost — it stays on in every mode.

Locking discipline: the store lock guards only dict arithmetic. Metric
emission, jax calls, and the AOT analysis all run outside it, so this
module adds no lock-order edge against the metrics store (the
lock-discipline pass scans utils/).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from contextlib import contextmanager

log = logging.getLogger("automerge_tpu.perfscope")

#: Registered phase names for `phase()` — the cross-layer wall-time
#: rollup. The graftlint registry pass rejects unregistered literals at
#: phase() call sites, exactly like metric names (docs/OBSERVABILITY.md
#: "Performance plane").
PHASES: dict[str, str] = {
    "pack": "columnar batch/rows packing on the host (engine/pack.py)",
    "dispatch": "jitted kernel dispatch calls (metrics.dispatch_jit)",
    "device_wait": "explicit host barriers on in-flight device work "
                   "(block_until_ready)",
    "readback": "device->host readbacks (hash reads, the trusted barrier)",
    "host_materialize": "interpretive apply + snapshot materialization "
                        "(frontend/materialize.py)",
    "sync_wire": "wire encode/decode of sync frames (sync/frames.py)",
    "fleet_hashes": "fleet-wide convergence reads: the sharded hash "
                    "fan-out incl. per-shard dirty-lane reconciles "
                    "(sync/sharded_service.py)",
    "span_merge": "span-granularity text-merge placement: run placement "
                  "walks + ElemList splices (core/textspans.py)",
}

#: seconds between jax.live_arrays() footprint samples (the walk is
#: O(live arrays); dispatch sites sample opportunistically)
LIVE_SAMPLE_INTERVAL_S = 0.5

_UNATTRIBUTED = "(unattributed)"

_tls = threading.local()


def _analysis_mode() -> str:
    """"full" (cost + memory analysis) | "cost" | "off". The default is
    backend-aware: "full" everywhere EXCEPT the tpu backend, where the
    extra AOT backend compile would double remote-compile exposure on the
    tunnel — the repo's documented wedge hazard (bench.py r5 lore). Set
    AMTPU_PERFSCOPE=full explicitly to get HBM sections on TPU runs."""
    raw = os.environ.get("AMTPU_PERFSCOPE", "auto").strip().lower()
    if raw in ("0", "off", "none", "false"):
        return "off"
    if raw in ("cost", "full"):
        return raw
    try:
        import jax
        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    return "cost" if backend == "tpu" else "full"


class _KernelStats:
    __slots__ = ("dispatches", "compiles", "compile_s", "trace_s",
                 "lower_s", "signatures")

    def __init__(self):
        self.dispatches = 0
        self.compiles = 0        # dispatch windows that observed a compile
        self.compile_s = 0.0     # backend compile seconds
        self.trace_s = 0.0       # jaxpr trace seconds
        self.lower_s = 0.0       # jaxpr -> MLIR lowering seconds
        self.signatures: set = set()


class _Store:
    def __init__(self):
        self.lock = threading.Lock()
        self.kernels: dict[str, _KernelStats] = {}
        self.phases: dict[str, list] = {}     # name -> [seconds, count]
        self.live_bytes = 0
        self.live_peak = 0
        self._last_live = 0.0

    def kernel(self, name: str) -> _KernelStats:
        st = self.kernels.get(name)
        if st is None:
            st = self.kernels[name] = _KernelStats()
        return st


_store = _Store()

# Analysis results survive metrics.reset(): XLA's answer for a compiled
# kernel variant does not change between bench configs, and per-config
# snapshots must still carry cost/memory rows for kernels compiled in an
# earlier config. kernel -> {"cost": {...}|None, "memory": {...}|None}
_analysis_lock = threading.Lock()
_analysis: dict[str, dict] = {}


class _Marker:
    """Per-dispatch compile-event accumulator (thread-local; no lock)."""
    __slots__ = ("kernel", "events", "compile_s", "trace_s", "lower_s")

    def __init__(self, kernel: str):
        self.kernel = kernel
        self.events = 0
        self.compile_s = 0.0
        self.trace_s = 0.0
        self.lower_s = 0.0

    def note(self, event: str, seconds: float) -> None:
        self.events += 1
        if event.endswith("backend_compile_duration"):
            self.compile_s += seconds
        elif event.endswith("jaxpr_trace_duration"):
            self.trace_s += seconds
        else:
            self.lower_s += seconds


# ---------------------------------------------------------------------------
# jax.monitoring listener (compile-event ground truth)


_installed = False
_install_lock = threading.Lock()


def _on_event_duration(name: str, seconds: float, **kw) -> None:
    if not name.startswith("/jax/core/compile"):
        return
    if getattr(_tls, "suppress", False):
        return      # our own AOT analysis compile: not a product retrace
    stack = getattr(_tls, "stack", None)
    if stack:
        stack[-1].note(name, seconds)
        return
    # a compile outside any dispatch_jit window (e.g. bench's own jits):
    # still worth counting, under a reserved bucket
    with _store.lock:
        st = _store.kernel(_UNATTRIBUTED)
        if name.endswith("backend_compile_duration"):
            st.compiles += 1
            st.compile_s += seconds
        elif name.endswith("jaxpr_trace_duration"):
            st.trace_s += seconds
        else:
            st.lower_s += seconds


def ensure_installed() -> bool:
    """Register the jax.monitoring compile-duration listener (idempotent).
    Returns False when jax.monitoring is unavailable."""
    global _installed
    if _installed:
        return True
    with _install_lock:
        if _installed:
            return True
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(
                _on_event_duration)
        except Exception:
            return False
        _installed = True
    return True


# ---------------------------------------------------------------------------
# per-dispatch accounting (driven by metrics.dispatch_jit)


def _signature(args, kwargs) -> tuple:
    """Abstract call signature: shapes/dtypes for array-likes, values for
    hashable statics. Two calls with equal signatures hit the same jit
    cache entry (modulo weak types — close enough to gate the one-time
    analysis)."""
    def one(x):
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is not None and dtype is not None:
            return ("a", tuple(shape), str(dtype))
        try:
            hash(x)
            return ("s", x)
        except TypeError:
            return ("r", repr(x)[:80])
    return (tuple(one(a) for a in args),
            tuple((k, one(v)) for k, v in sorted(kwargs.items())))


_install_warned = False


def dispatch_begin(kernel: str, fn, args: tuple, kwargs: dict):
    """Open a dispatch window: arm the listener, run the one-time
    signature analysis when this (kernel, signature) is new, and push the
    attribution marker. Returns the marker for dispatch_end()."""
    global _install_warned
    if not ensure_installed() and not _install_warned:
        _install_warned = True
        log.warning(
            "jax.monitoring compile listener unavailable — retrace "
            "detection and compile telemetry are degraded to zero "
            "(engine_kernels_retraced will not fire on this process)")
    try:
        sig = _signature(args, kwargs)
    except Exception:
        sig = None
    if sig is not None:
        with _store.lock:
            st = _store.kernel(kernel)
            new = sig not in st.signatures
            if new:
                st.signatures.add(sig)
        if new:
            # BEFORE the real call: donated input buffers are still live
            _analyze(kernel, fn, args, kwargs)
    marker = _Marker(kernel)
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(marker)
    return marker


def dispatch_end(marker) -> bool:
    """Close a dispatch window. Folds the marker's compile events into the
    store and returns True when the dispatch compiled (a jit cache miss —
    the ground truth behind `engine_kernels_retraced`)."""
    stack = getattr(_tls, "stack", None)
    if stack is not None:
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is marker:
                del stack[i]
                break
    compiled = marker.events > 0
    with _store.lock:
        st = _store.kernel(marker.kernel)
        st.dispatches += 1
        if compiled:
            st.compiles += 1
            st.compile_s += marker.compile_s
            st.trace_s += marker.trace_s
            st.lower_s += marker.lower_s
    if compiled:
        from . import metrics
        metrics.add_time("engine_kernel_compile",
                         marker.compile_s + marker.trace_s + marker.lower_s,
                         kernel=marker.kernel)
    sample_live_arrays()
    return compiled


@contextmanager
def _suppressed():
    prev = getattr(_tls, "suppress", False)
    _tls.suppress = True
    try:
        yield
    finally:
        _tls.suppress = prev


def _memory_dict(stats) -> dict | None:
    out = {}
    for attr, section in (("argument_size_in_bytes", "argument"),
                          ("output_size_in_bytes", "output"),
                          ("temp_size_in_bytes", "temp"),
                          ("alias_size_in_bytes", "alias"),
                          ("generated_code_size_in_bytes", "code")):
        v = getattr(stats, attr, None)
        if v is not None:
            out[section] = int(v)
    return out or None


def _cost_dict(raw) -> dict | None:
    if isinstance(raw, (list, tuple)):
        raw = raw[0] if raw else None
    if not isinstance(raw, dict):
        return None
    out = {}
    for key, name in (("flops", "flops"),
                      ("bytes accessed", "bytes_accessed"),
                      ("transcendentals", "transcendentals")):
        v = raw.get(key)
        if isinstance(v, (int, float)) and v >= 0:
            out[name] = float(v)
    return out or None


def _analyze(kernel: str, fn, args: tuple, kwargs: dict) -> None:
    """One-time per (kernel, signature): XLA cost analysis from the traced
    lowering and (mode `full`) HBM section sizes from an AOT compile.
    Best-effort — a kernel that cannot be lowered out of band (non-jit
    callable, exotic statics) simply has no cost/memory rows."""
    mode = _analysis_mode()
    if mode == "off":
        return
    lower = getattr(fn, "lower", None)
    if not callable(lower):
        return
    cost = memory = None
    try:
        with _suppressed():
            lowered = lower(*args, **kwargs)
            try:
                cost = _cost_dict(lowered.cost_analysis())
            except Exception:
                cost = None
            if mode == "full":
                compiled = lowered.compile()
                try:
                    c2 = _cost_dict(compiled.cost_analysis())
                    if c2:
                        cost = c2   # post-optimization numbers when available
                except Exception:
                    pass
                try:
                    memory = _memory_dict(compiled.memory_analysis())
                except Exception:
                    memory = None
    except Exception as e:
        log.debug("perfscope analysis failed for %r: %r", kernel, e)
        return
    with _analysis_lock:
        entry = _analysis.setdefault(kernel, {})
        if cost:
            entry["cost"] = cost
        if memory:
            entry["memory"] = memory
    from . import metrics
    if cost:
        if "flops" in cost:
            metrics.gauge("engine_kernel_flops", cost["flops"],
                          kernel=kernel)
        if "bytes_accessed" in cost:
            metrics.gauge("engine_kernel_bytes_accessed",
                          cost["bytes_accessed"], kernel=kernel)
    if memory:
        for section, v in memory.items():
            metrics.gauge("engine_kernel_hbm_bytes", v, kernel=kernel,
                          section=section)


# ---------------------------------------------------------------------------
# phase attribution


@contextmanager
def phase(name: str):
    """Accumulate wall time under one of the registered PHASES. Cheap (two
    perf_counter reads + one locked dict update), safe to nest; phases are
    attribution, not a partition — overlapping phases both count."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        with _store.lock:
            e = _store.phases.get(name)
            if e is None:
                _store.phases[name] = [dt, 1]
            else:
                e[0] += dt
                e[1] += 1


def phase_totals() -> dict[str, float]:
    """Accumulated seconds per phase since the last reset — a cheap
    point-in-time read (one locked dict copy). The op-lifecycle plane
    (utils/oplag.py) snapshots this around a round flush and attributes
    the delta (pack/dispatch/device_wait) to the sampled ops that rode
    the round."""
    with _store.lock:
        return {n: e[0] for n, e in _store.phases.items()}


def phased(name: str):
    """Decorator form of phase() for whole-function attribution (the pack
    entry points in engine/pack.py). Same lint discipline: the name
    literal at the decoration site must be a registered PHASE."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            # wrapper plumbing: the literal is checked at @phased("...")
            # decoration sites, not here
            with phase(name):   # graftlint: disable=phase-dynamic
                return fn(*args, **kwargs)
        return wrapped
    return deco


# ---------------------------------------------------------------------------
# memory gauges


def sample_live_arrays(force: bool = False) -> int | None:
    """Throttled live-array footprint sample; maintains the high-water
    mark. Returns the sampled byte total (None when throttled or jax is
    unavailable)."""
    now = time.monotonic()
    with _store.lock:
        if not force and now - _store._last_live < LIVE_SAMPLE_INTERVAL_S:
            return None
        _store._last_live = now
    try:
        import jax
        total = sum(int(getattr(a, "nbytes", 0) or 0)
                    for a in jax.live_arrays())
    except Exception:
        return None
    with _store.lock:
        _store.live_bytes = total
        if total > _store.live_peak:
            _store.live_peak = total
        peak = _store.live_peak
    from . import metrics
    metrics.gauge("obs_live_arrays_bytes", total)
    metrics.gauge("obs_live_arrays_peak_bytes", peak)
    return total


# ---------------------------------------------------------------------------
# snapshot / reset


def perf_snapshot() -> dict | None:
    """The `perf` section `metrics.snapshot()` embeds: per-kernel compile
    telemetry (counts, attributed seconds, XLA cost, HBM sections),
    cross-layer phase rollup, and the live-array footprint. None when
    nothing has been recorded since the last reset (so an untouched
    process still snapshots to `{}`)."""
    with _store.lock:
        kernels = {
            k: {"dispatches": st.dispatches,
                "compiles": st.compiles,
                "compile_s": round(st.compile_s, 6),
                "trace_s": round(st.trace_s, 6),
                "lower_s": round(st.lower_s, 6)}
            for k, st in _store.kernels.items()
            # idle entries (kept across reset() only for their signature
            # memory) stay out of the per-run snapshot
            if st.dispatches or st.compiles or st.compile_s
            or st.trace_s or st.lower_s}
        if not kernels and not _store.phases and not _store.live_peak:
            return None
        phases = {n: {"s": round(s, 6), "count": c}
                  for n, (s, c) in _store.phases.items()}
        memory = None
        if _store.live_peak:
            memory = {"live_array_bytes": _store.live_bytes,
                      "live_array_peak_bytes": _store.live_peak}
    with _analysis_lock:
        for k, entry in _analysis.items():
            if k in kernels:
                if entry.get("cost"):
                    kernels[k]["cost"] = dict(entry["cost"])
                if entry.get("memory"):
                    kernels[k]["memory"] = dict(entry["memory"])
    out: dict = {"kernels": kernels}
    if phases:
        out["phases"] = phases
    if memory:
        out["memory"] = memory
    return out


def reset() -> None:
    """Clear per-run counters/phases/footprint (metrics.reset() calls
    this). The per-kernel signature sets and cached XLA analyses survive:
    the jit caches they mirror are process-lived, and clearing them would
    re-run the (compile-costed) analysis every bench config."""
    with _store.lock:
        for st in _store.kernels.values():
            st.dispatches = 0
            st.compiles = 0
            st.compile_s = 0.0
            st.trace_s = 0.0
            st.lower_s = 0.0
        _store.kernels = {k: st for k, st in _store.kernels.items()
                          if st.signatures}
        _store.phases.clear()
        _store.live_bytes = 0
        _store.live_peak = 0
        _store._last_live = 0.0
