"""Move operations: one-op reparenting with deterministic cycle resolution.

Kleppmann-style moves ("Extending JSON CRDTs with Move Operations",
arxiv 2311.14007) give this CRDT an op class the v0.8.0 reference cannot
express: relocating a map child object or a list element is ONE op
(`move {obj, key, value, elem?}`) instead of a delete + re-insert that
re-ships the whole subtree and duplicates it under concurrency.

Two *realms* share one resolution engine:

- the **map realm** — the document-wide object forest. A map move
  reparents child object `value` under map `obj` at key `key`. Parent
  edges come from each object's effective location op (`ObjState.loc`);
  objects never move-targeted keep the reference's link semantics bit
  for bit.
- a **list realm** per list/text object — its RGA insertion forest. A
  list move re-anchors element `value` after `key` with a fresh sibling
  counter `elem` (allocated like an insert, so destination-order ties
  break exactly like concurrent inserts). The element keeps its
  identity: concurrent set/del on it still apply.

**Semantics (the one definition, shared by every implementation):**

1. *Candidates.* Each moved node carries the antichain of its
   non-dominated move ops (a move causally covered by a later move of
   the same node is dead forever — the same monotone-domination argument
   that lets the snapshot compactor drop it, sync/snapshots.py) plus an
   undroppable *base* edge: the element's original `ins` (lists) or its
   minimum-stamp inbound `link` (maps).
2. *Winner.* Highest-priority candidate, priority =
   (lamport, actor) where lamport = sum of the op's change's full
   vector clock — a total order extending causality, so a causally-later
   move always beats everything it has seen, and concurrent moves
   tie-break on the actor exactly like the LWW rule everywhere else in
   this engine.
3. *Cycles.* Tentatively applying every winner can cycle the forest
   (concurrent `A->B` + `B->A`). Fixpoint: find the cycles, drop the
   minimum-priority move edge on each cycle (the highest-priority move
   survives), re-select winners (a dropped node falls back to its next
   candidate, ultimately its base edge), repeat. Drops are monotone so
   the loop terminates; the result is a pure function of the candidate
   SET — delivery order, batching, and replica cannot matter. A cycle
   with no droppable move edge (pre-existing concurrent cross-links, a
   wart this repo inherits from the reference) is left as-is.

The per-op interpretive path resolves with host walks (O(moved * depth)
per admission — the baseline bench config 16 measures). Batches of >=
MOVE_BATCH_MIN_OPS moves admit through the span-plane scaffolding
(`admit_change_header` classification, one resolution per batch) and
route the packed fixpoint through engine/move_kernels.py — numpy host,
jitted XLA, or the pallas pointer-doubling kernel, by measured cost
model (engine/dispatch.plan_moves).
"""

from __future__ import annotations

import os

from ..utils import metrics
from .change import Change
from .ids import HEAD, ROOT_ID
from .opset import (Builder, admit_change_header, get_path, get_previous,
                    patch_list, update_map_key)

#: below this many ops a batch keeps the per-op path (interactive moves
#: keep their per-op diff records); tests override to force the plane.
MOVE_BATCH_MIN_OPS = 32

#: moved-node count from which realm resolution routes through the packed
#: kernel triple instead of the host walk (AMTPU_MOVE_KERNEL_MIN overrides).
MOVE_KERNEL_MIN_NODES = 64


def op_priority(b, op) -> tuple[int, str, str]:
    """(lamport, actor, moved-id) priority of a stamped op: lamport is
    the sum of the op's change's full transitive clock — strictly
    monotone along causality — the actor string breaks concurrent ties
    with the same highest-wins convention as the LWW rule
    (op_set.js:201), and the moved id makes priorities UNIQUE even for
    two moves inside one change (cross-moving two nodes), which the
    cycle-drop rule needs for walk/kernel parity."""
    if not op.actor or not op.seq:
        # local op inside an open change block: previews as winning over
        # everything admitted (the commit re-applies it stamped)
        return 2 ** 62, op.actor or "", str(op.value)
    clock = b.states[op.actor][op.seq - 1][1]
    # the stored row holds the op's own actor at seq-1, so this sum is
    # the true vector-clock sum minus a constant 1: ordering-identical
    return sum(clock.values()), op.actor or "", str(op.value)


def covers(b, op_a, op_b) -> bool:
    """True when op_a's change causally covers op_b's change (op_b is
    dominated: dead forever as a location candidate)."""
    if not op_a.actor or not op_a.seq:
        return True   # local unstamped op: sees (and overrides) everything
    if not op_b.actor or not op_b.seq:
        return False
    if op_a.actor == op_b.actor:
        return op_a.seq > op_b.seq
    clock = b.states[op_a.actor][op_a.seq - 1][1]
    return clock.get(op_b.actor, 0) >= op_b.seq


# ---------------------------------------------------------------------------
# the resolution problem: realm-neutral packed form


class MoveProblem:
    """One realm's resolution working set: the dirty closure of nodes
    (every moved node, every candidate target, and all their ancestors up
    to the root), base parent edges, and per-node sorted candidates."""

    __slots__ = ("nodes", "index", "base", "cands", "moved")

    def __init__(self):
        self.nodes: list = []          # node keys, slot order
        self.index: dict = {}          # node key -> slot
        self.base: list[int] = []      # slot -> base parent slot (-1 root)
        self.cands: list[list] = []    # slot -> [(hi, lo, parent_slot, op)]
        self.moved: list[int] = []     # slots with >= 1 candidate

    def slot(self, key) -> int:
        s = self.index.get(key)
        if s is None:
            s = len(self.nodes)
            self.index[key] = s
            self.nodes.append(key)
            self.base.append(-1)
            self.cands.append([])
        return s


def _resolve_walk(p: MoveProblem) -> tuple[list[int], int]:
    """The host-walk fixpoint: returns (winner index per slot — equal to
    len(cands[slot]) when the base edge wins — aligned with p.nodes, and
    the number of cycle-dropped candidates). This is the SEMANTICS
    definition — engine/move_kernels implements the identical fixpoint
    over packed arrays (parity-pinned by tests/test_moves.py)."""
    n = len(p.nodes)
    ptr = [0] * n
    dropped = 0
    total = sum(len(c) for c in p.cands)
    for _round in range(total + 1):
        parent = [0] * n
        for i in range(n):
            c = p.cands[i]
            parent[i] = c[ptr[i]][2] if ptr[i] < len(c) else p.base[i]
        # cycle detection over the functional graph: iterative coloring
        state = [0] * n          # 0 unvisited, >0 walk id, -1 done
        to_drop: list[int] = []
        wid = 0
        for start in range(n):
            if state[start] != 0:
                continue
            wid += 1
            path = []
            x = start
            while x >= 0 and state[x] == 0:
                state[x] = wid
                path.append(x)
                x = parent[x]
            if x >= 0 and state[x] == wid:
                # fresh cycle: the path suffix from x. Drop its minimum-
                # priority move edge (all of them on an exact tie — two
                # moves of one change cross-moving two nodes — which is
                # deterministic too: ties drop together on every replica)
                cyc = path[path.index(x):]
                best = None
                for node in cyc:
                    if ptr[node] < len(p.cands[node]):
                        e = p.cands[node][ptr[node]][:2]
                        if best is None or e < best:
                            best = e
                if best is not None:
                    for node in cyc:
                        if (ptr[node] < len(p.cands[node])
                                and p.cands[node][ptr[node]][:2] == best):
                            to_drop.append(node)
            for node in path:
                state[node] = -1
        if not to_drop:
            break
        for node in to_drop:
            ptr[node] += 1
            dropped += 1
    return ptr, dropped


def _resolve_packed(p: MoveProblem) -> tuple[list[int], int]:
    """Route the identical fixpoint through the engine kernel triple
    (host numpy / XLA / pallas, by measured cost model)."""
    from ..engine.dispatch import resolve_moves_adaptive
    from ..engine.pack import pack_moves

    packed = pack_moves([p])
    _plan, out = resolve_moves_adaptive(packed)
    ptr = [int(v) for v in out["ptr"][0][:len(p.nodes)]]
    return ptr, int(out["dropped"][0])


def _kernel_min() -> int:
    try:
        return int(os.environ.get("AMTPU_MOVE_KERNEL_MIN",
                                  MOVE_KERNEL_MIN_NODES))
    except ValueError:  # pragma: no cover
        return MOVE_KERNEL_MIN_NODES


def resolve_problem(p: MoveProblem) -> tuple[list[int], int]:
    if len(p.moved) >= _kernel_min():
        return _resolve_packed(p)
    return _resolve_walk(p)


# ---------------------------------------------------------------------------
# map realm


def _map_base(child):
    """The child's undroppable base edge: its minimum-stamp inbound link
    (the op that first placed it — causally before every move of it, so
    the choice is delivery-order-independent)."""
    best = None
    best_key = None
    for ref in child.inbound:
        if ref.action != "link":
            continue
        key = (ref.actor or "", ref.seq or 0)
        if best is None or key < best_key:
            best, best_key = ref, key
    return best


def _map_candidates(b: Builder, child) -> list:
    out = []
    for ref in child.inbound:
        if ref.action == "move":
            hi, a, v = op_priority(b, ref)
            out.append((hi, (a, v), ref))
    # stable sort, then reverse slices of equal keys keep REGISTRATION
    # order among exact ties (two moves of one change): the later op of
    # the change must rank first, and registration replaced same-stamp
    # earlier ops already, so ties here are cross-node only
    out.sort(key=lambda t: (t[0], t[1]), reverse=True)
    return out


def _effective_parent_map(b: Builder, oid: str) -> str | None:
    obj = b.by_object.get(oid)
    if obj is None or not obj.inbound:
        return None
    if obj.loc is not None:
        return obj.loc.obj
    ref = next(iter(obj.inbound))
    return ref.obj


def _build_map_problem(b: Builder) -> MoveProblem:
    p = MoveProblem()
    packed: dict[str, tuple] = {}
    frontier: list[str | None] = []
    for oid in b.moved_objs:
        child = b.by_object.get(oid)
        if child is None:
            continue
        cands = _map_candidates(b, child)
        base = _map_base(child)
        packed[oid] = (base, cands)
        p.moved.append(p.slot(oid))
        frontier.extend(op.obj for (_h, _l, op) in cands)
        if base is not None:
            frontier.append(base.obj)
    # closure: every target and every ancestor chain up to the root
    while frontier:
        oid = frontier.pop()
        if oid is None or oid == ROOT_ID or oid in p.index:
            continue
        p.slot(oid)
        frontier.append(_effective_parent_map(b, oid))
    # fill edges (closure complete: slot() below never adds a node)
    n = len(p.nodes)
    for s in range(n):
        oid = p.nodes[s]

        def pslot(target):
            return -1 if target is None or target == ROOT_ID \
                else p.index[target]

        entry = packed.get(oid)
        if entry is not None:
            base, cands = entry
            p.base[s] = pslot(base.obj) if base is not None else -1
            p.cands[s] = [(hi, lo, pslot(op.obj), op)
                          for (hi, lo, op) in cands]
        else:
            p.base[s] = pslot(_effective_parent_map(b, oid))
    assert len(p.nodes) == n
    return p


def _place_map_child(b: Builder, child_id: str, new_op,
                     touched: list) -> None:
    """Materialize one map child's effective location: remove every
    non-effective location op from its field, install `new_op` at its
    destination field (with the standard causal-overwrite split), stamp
    `loc`. Appends affected (obj, key) pairs to `touched`; diff emission
    happens AFTER the whole realm is placed (get_path must never walk a
    half-updated forest)."""
    child = b.obj(child_id)
    old = child.loc
    if old is new_op:
        return
    # single-location sweep: once a child is move-managed, exactly its
    # EFFECTIVE op may present it — every other inbound location op
    # (the base link, losing candidates, a stale previous winner) leaves
    # its field. Pure function of the candidate set, so delivery order
    # cannot matter.
    for ref in child.inbound:
        if ref is new_op:
            continue
        holder = b.by_object.get(ref.obj)
        if holder is not None and ref in holder.fields.get(ref.key, ()):
            hmut = b.obj(ref.obj)
            hmut.fields[ref.key] = tuple(
                o for o in hmut.fields[ref.key] if o is not ref)
            touched.append((ref.obj, ref.key))
    child.loc = new_op
    touched.append((new_op.obj, new_op.key))
    dest = b.obj(new_op.obj)
    prior = dest.fields.get(new_op.key, ())
    if new_op in prior:
        return
    # a location op causally covered by an assign already at the key is
    # suppressed — the overwrite wins, and any-order replay agrees
    # because apply_assign strips it the same way
    if any(covers(b, other, new_op) for other in prior):
        return
    overwritten = [o for o in prior if covers(b, new_op, o)]
    remaining = [o for o in prior if not covers(b, new_op, o)]
    for dead in overwritten:
        if dead.action == "link":
            b.obj(dead.value).inbound.pop(dead, None)
        # dead MOVE ops stay in their child's inbound: they remain
        # resolution candidates (visibility is what the field holds)
    remaining.append(new_op)
    remaining.sort(key=lambda o: o.actor or "", reverse=True)
    dest.fields[new_op.key] = tuple(remaining)


#: reserved ObjState.moves key holding the realm's drop count at its
#: previous resolution: the metric reports the positive DELTA, so a
#: standing cycle counts once, not once per later unrelated admission
#: (element ids are "actor:n" and map keys never start with \x00, so
#: the key cannot collide)
_DROPS_KEY = "\x00cycle_drops"


def _bump_drops(b: Builder, holder_oid: str, dropped: int) -> None:
    holder = b.obj(holder_oid)
    prev = holder.moves.get(_DROPS_KEY, 0)
    if dropped > prev:
        metrics.bump("sync_move_cycles_dropped", dropped - prev)
    if dropped != prev:
        holder.moves[_DROPS_KEY] = dropped


def _resolve_map_realm(b: Builder, emit: bool,
                       touched: set | None = None,
                       pre_pairs: list | None = None) -> list[dict]:
    if not b.moved_objs:
        return []
    p = _build_map_problem(b)
    ptr, dropped = resolve_problem(p)
    _bump_drops(b, ROOT_ID, int(dropped))
    # pre_pairs: (obj, key) fields the REGISTRATION step stripped
    # (domination pruning of superseded location ops) — they need diff
    # records too or incremental caches go stale on chained moves
    keys: list[tuple[str, str]] = list(pre_pairs or ())
    for s in p.moved:
        oid = p.nodes[s]
        child = b.by_object.get(oid)
        if child is None:
            continue
        cands = p.cands[s]
        if ptr[s] < len(cands):
            winner = cands[ptr[s]][3]
        else:
            winner = _map_base(child)
        if winner is None:
            continue
        _place_map_child(b, oid, winner, keys)
    diffs: list[dict] = []
    seen: set = set()
    for pair in keys:
        if pair in seen:
            continue
        seen.add(pair)
        if touched is not None:
            touched.add(pair[0])
        if emit:
            diffs.extend(update_map_key(b, pair[0], pair[1]))
    return diffs


# ---------------------------------------------------------------------------
# list realm
#
# Node space: each element contributes its PLACED spot (plain eid — where
# its winning op puts it) and, once moved, a GHOST spot (eid + suffix —
# its original ins position, which its unaware siblings keep anchoring
# at). Ghost edges are undroppable ins edges; candidates attach to placed
# spots only. Cycles arise when placement-aware anchoring loops (E typed
# after moved D, then D moved after E) and resolve exactly like map-realm
# cycles.


def _list_candidates(b: Builder, entry):
    out = []
    for op in entry.cands:
        hi, a, v = op_priority(b, op)
        out.append((hi, (a, v), op))
    out.sort(key=lambda t: (t[0], t[1]), reverse=True)
    return out


def _build_list_problem(b: Builder, oid: str) -> MoveProblem:
    from .opset import GHOST_SUFFIX, anchored_at_placed, strip_ghost

    obj = b.by_object[oid]
    p = MoveProblem()
    packed: dict[str, list] = {}

    def anchor_of(_eid: str, via_op) -> str | None:
        # PROSPECTIVE spot split: resolution runs before placement, so
        # the split keys on candidate existence, not on the currently
        # installed winner (when the winner ends up being the base, both
        # spots converge on the same position and the distinction is
        # harmless)
        anchor = via_op.key
        if anchor == HEAD:
            return None
        if anchor not in obj.moves:
            return anchor
        if anchored_at_placed(b, obj, via_op, anchor):
            return anchor
        return anchor + GHOST_SUFFIX

    frontier: list[str | None] = []
    for eid, entry in obj.moves.items():
        if eid == _DROPS_KEY:
            continue
        cands = _list_candidates(b, entry)
        packed[eid] = cands
        p.moved.append(p.slot(eid))
        frontier.append(eid + GHOST_SUFFIX)
        frontier.append(anchor_of(eid, entry.base))
        frontier.extend(anchor_of(eid, op) for (_h, _l, op) in cands)
    while frontier:
        key = frontier.pop()
        if key is None or key == HEAD or key in p.index:
            continue
        p.slot(key)
        bare = strip_ghost(key)
        entry = obj.moves.get(bare)
        if entry is not None:
            if key == bare and bare not in packed:
                # a moved element reached as an anchor: its candidates
                # (and their chains) shape the forest too
                cands = _list_candidates(b, entry)
                packed[bare] = cands
                p.moved.append(p.index[bare])
                frontier.append(bare + GHOST_SUFFIX)
                frontier.extend(anchor_of(bare, op)
                                for (_h, _l, op) in cands)
            frontier.append(anchor_of(bare, entry.base))
        else:
            ins = obj.insertion.get(bare)
            if ins is not None:
                frontier.append(anchor_of(bare, ins))
    n = len(p.nodes)

    def pslot(key):
        return -1 if key is None or key == HEAD else p.index[key]

    for s in range(n):
        key = p.nodes[s]
        bare = strip_ghost(key)
        entry = obj.moves.get(bare)
        if entry is not None:
            base_slot = pslot(anchor_of(bare, entry.base))
            p.base[s] = base_slot
            if key == bare:
                p.cands[s] = [(hi, lo, pslot(anchor_of(bare, op)), op)
                              for (hi, lo, op) in packed[bare]]
        else:
            ins = obj.insertion.get(bare)
            p.base[s] = pslot(anchor_of(bare, ins)) if ins is not None \
                else -1
    assert len(p.nodes) == n
    return p


def _place_list_elem(b: Builder, oid: str, eid: str, new_op,
                     emit: bool) -> list:
    """Re-place one element. The original ins never leaves the insertion
    tree (it is the ghost — siblings anchored at it keep their
    positions); the winning move op joins its destination bucket. The
    visible index updates incrementally (remove + insert, the same
    records a delete + re-add would emit) unless placement-aware
    followers exist, in which case the whole index rebuilds."""
    from .opset import rebuild_elem_ids

    obj = b.obj(oid)
    entry = obj.moves[eid]
    old = obj.insertion.get(eid)
    if old is new_op:
        return []
    if old is not entry.base:
        sibs = obj.following.get(old.key, ())
        obj.following[old.key] = tuple(o for o in sibs if o is not old)
    if new_op is not entry.base \
            and new_op not in obj.following.get(new_op.key, ()):
        obj.following[new_op.key] = \
            obj.following.get(new_op.key, ()) + (new_op,)
    obj.insertion[eid] = new_op
    if not emit:
        b._deferred_seqs.add(oid)
        return []
    if entry.followers:
        # siblings track this element's placement: their flat positions
        # shift with it, so rebuild the index wholesale (rare — requires
        # conflicting concurrent moves under placement-aware anchors)
        rebuild_elem_ids(obj, state=b)
        b._elem_copied.add(oid)
        kind = "text" if obj.init_action == "makeText" else "list"
        return [{"action": "batch", "type": kind, "obj": oid,
                 "path": get_path(b, oid)}]
    diffs: list[dict] = []
    elems = b.elem_ids_mut(oid)
    ops = obj.fields.get(eid, ())
    idx = elems.index_of(eid)
    if idx >= 0:
        diffs.extend(patch_list(b, oid, idx, "remove", None))
    if ops:
        prev = get_previous(b, oid, eid)
        at = -1
        while prev is not None:
            at = elems.index_of(prev)
            if at >= 0:
                break
            prev = get_previous(b, oid, prev)
        diffs.extend(patch_list(b, oid, at + 1, "insert", ops))
    return diffs


def _resolve_list_realm(b: Builder, oid: str, emit: bool) -> list[dict]:
    obj = b.by_object.get(oid)
    if obj is None or not obj.moves:
        return []
    p = _build_list_problem(b, oid)
    ptr, dropped = resolve_problem(p)
    _bump_drops(b, oid, int(dropped))
    diffs: list[dict] = []
    for s in p.moved:
        eid = p.nodes[s]
        cands = p.cands[s]
        if ptr[s] < len(cands):
            winner = cands[ptr[s]][3]
        else:
            winner = b.by_object[oid].moves[eid].base
        diffs.extend(_place_list_elem(b, oid, eid, winner, emit))
    return diffs


# ---------------------------------------------------------------------------
# per-op application (called from opset.apply_op)


def apply_move(b: Builder, op, emit: bool = True) -> list[dict]:
    """Apply one stamped move op: candidate registration with monotone
    domination pruning, then a realm resolution pass (host walks at this
    granularity — the batched plane amortizes resolution per batch)."""
    dest = b.by_object.get(op.obj)
    if dest is None:
        raise ValueError(f"Modification of unknown object {op.obj}")
    metrics.bump("core_moves_applied")
    if dest.is_sequence:
        _register_list_move(b, op)
        return _resolve_list_realm(b, op.obj, emit)
    stripped: list = []
    _register_map_move(b, op, stripped)
    return _resolve_map_realm(b, emit, pre_pairs=stripped)


def _register_map_move(b: Builder, op, stripped: list | None = None) -> None:
    child_id = op.value
    child = b.by_object.get(child_id)
    if not isinstance(child_id, str) or child is None:
        raise ValueError(f"Move of unknown object {child_id!r}")
    if child_id == ROOT_ID:
        raise ValueError("Cannot move the root object")
    child = b.obj(child_id)
    # monotone domination: candidates causally covered by this move are
    # dead forever (they can never win nor serve as a cycle fallback —
    # the base link below every chain is kept separately). A same-change
    # earlier move of the same child is replaced too: last op wins.
    for ref in [r for r in child.inbound if r.action == "move"]:
        if covers(b, op, ref) or (ref.actor == op.actor
                                  and ref.seq == op.seq):
            child.inbound.pop(ref, None)
            holder = b.by_object.get(ref.obj)
            if holder is not None and ref in holder.fields.get(ref.key, ()):
                hmut = b.obj(ref.obj)
                hmut.fields[ref.key] = tuple(
                    o for o in hmut.fields[ref.key] if o is not ref)
                if stripped is not None:
                    stripped.append((ref.obj, ref.key))
            if child.loc is ref:
                child.loc = None
    child.inbound[op] = None
    b.moved_objs.add(child_id)


def _register_list_move(b: Builder, op) -> None:
    from .opset import MoveEntry, anchored_at_placed

    oid = op.obj
    obj = b.obj(oid)
    eid = op.value
    ins = obj.insertion.get(eid)
    if ins is None:
        raise ValueError(f"Move of unknown list element {eid!r}")
    if op.key != HEAD and op.key not in obj.insertion:
        raise ValueError(f"Move anchored at unknown element {op.key!r}")
    if op.elem is None:
        raise ValueError("List move requires a destination elem counter")
    entry = obj.moves.get(eid)
    if entry is None:
        # first move of this element: the current insertion op IS its
        # original ins (nothing else can have replaced it yet)
        entry = MoveEntry(ins)
    else:
        entry = entry.copy()
    entry.cands = tuple(
        c for c in entry.cands
        if not covers(b, op, c)
        and not (c.actor == op.actor and c.seq == op.seq)) + (op,)
    if op.seq:  # local preview ops re-apply stamped at commit
        q = entry.stamps.get(op.actor)
        if q is None or op.seq < q:
            entry.stamps[op.actor] = op.seq
    obj.moves[eid] = entry
    # this move is itself a sibling op of its anchor: if it tracks the
    # anchor's placement, flag the anchor (winner changes there must
    # reposition this element too)
    if op.key != HEAD:
        aentry = obj.moves.get(op.key)
        if aentry is not None and not aentry.followers \
                and anchored_at_placed(b, obj, op, op.key):
            aentry = aentry.copy()
            aentry.followers = True
            obj.moves[op.key] = aentry
    if op.elem > obj.max_elem:
        obj.max_elem = op.elem


# ---------------------------------------------------------------------------
# the batched admission plane (the span-plane scaffolding, move-shaped)


def _scan(b: Builder, changes: list) -> int | None:
    """Eligibility: every change causally ready in batch order,
    duplicate-free, pure-move ops on existing containers with resolvable
    targets. Mutates nothing; None falls back to the generic path."""
    total = 0
    clock = dict(b.clock)
    for change in changes:
        if not isinstance(change, Change):
            return None
        actor, seq = change.actor, change.seq
        if seq != clock.get(actor, 0) + 1:
            return None
        for a, s in change.deps.items():
            if a != actor and clock.get(a, 0) < s:
                return None
        for op in change.ops:
            if op.action != "move":
                return None
            dest = b.by_object.get(op.obj)
            if dest is None:
                return None
            if dest.is_sequence:
                if (op.value not in dest.insertion or op.elem is None
                        or (op.key != HEAD
                            and op.key not in dest.insertion)):
                    return None
            else:
                child = b.by_object.get(op.value)
                if child is None or op.value == ROOT_ID:
                    return None
            total += 1
        clock[actor] = seq
    return total if total >= MOVE_BATCH_MIN_OPS else None


def try_apply_move_batch(b: Builder, changes: list) -> list[dict] | None:
    """Admit an all-move batch with ONE resolution pass per touched realm
    (winner selection + cycle fixpoint over the union), classifying each
    change sequential-vs-concurrent through admit_change_header exactly
    like the text span plane. Emits one coarse ``{"action": "batch"}``
    record per touched container (frontend/materialize.update_cache folds
    per object); callers needing per-op records must not opt in. Returns
    None when ineligible — the scan mutates nothing, so falling back to
    the per-op path is always safe."""
    if _scan(b, changes) is None:
        return None
    seq_ops = conc_ops = 0
    list_realms: set[str] = set()
    map_realm = False
    stripped: list = []
    for change in changes:
        prev_frontier = b.deps  # admit_change_header rebinds, not mutates
        all_deps = admit_change_header(b, change)
        sequential = True
        for a, s in prev_frontier.items():
            if all_deps.get(a, 0) < s:
                sequential = False
                break
        actor, seq = change.actor, change.seq
        for op in change.ops:
            stamped = op.stamped(actor, seq)
            dest = b.by_object[stamped.obj]
            if dest.is_sequence:
                _register_list_move(b, stamped)
                list_realms.add(stamped.obj)
            else:
                _register_map_move(b, stamped, stripped)
                map_realm = True
        if sequential:
            seq_ops += len(change.ops)
        else:
            conc_ops += len(change.ops)

    touched: set[str] = set()
    touched.update(obj for (obj, _key) in stripped)
    if map_realm:
        _resolve_map_realm(b, emit=False, touched=touched)
    for oid in list_realms:
        _resolve_list_realm(b, oid, emit=False)
        touched.add(oid)
    # emit=False deferred the visible-index maintenance; coarse records +
    # one rebuild per touched list keep materialization exact
    from .opset import rebuild_elem_ids
    for oid in b._deferred_seqs:
        obj = b.by_object.get(oid)
        if obj is not None:
            rebuild_elem_ids(obj, state=b)
    b._deferred_seqs.clear()
    diffs: list[dict] = []
    for oid in touched:
        obj = b.by_object.get(oid)
        kind = ("text" if obj is not None and obj.init_action == "makeText"
                else "list" if obj is not None and obj.is_sequence
                else "map")
        diffs.append({"action": "batch", "type": kind, "obj": oid,
                      "path": get_path(b, oid)})

    metrics.bump("sync_move_batches_merged")
    if seq_ops:
        metrics.bump("sync_move_ops_sequential", seq_ops)
    if conc_ops:
        metrics.bump("sync_move_ops_concurrent", conc_ops)
    return diffs
