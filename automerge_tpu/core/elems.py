"""Visible-element order index for lists and text.

The reference maintains this index as a persistent order-statistic skip list
(/root/reference/src/skip_list.js) giving O(log n) key<->index queries. The
TPU-native design replaces rank queries with tombstone bitmaps + prefix scans
in the columnar engine (automerge_tpu/engine/kernels.py); this host-side
structure only serves the interactive single-document frontend, where a flat
array with a position dictionary is simpler and fast enough (O(n) worst-case
updates, O(1) lookups). The public surface mirrors the skip list's:
insert_index / set_value / remove_index / index_of / key_of / get_value
(/root/reference/src/skip_list.js:169-327).

Persistence contract: instances are immutable-by-discipline; the OpSet builder
copies an ElemList before mutating it (copy-on-first-touch per change batch).
"""

from __future__ import annotations

from typing import Any, Iterator


class ElemList:
    __slots__ = ("keys", "values", "_pos")

    def __init__(self, keys: list[str] | None = None, values: list[Any] | None = None,
                 pos: dict[str, int] | None = None):
        self.keys = keys if keys is not None else []
        self.values = values if values is not None else []
        if pos is None:
            pos = {k: i for i, k in enumerate(self.keys)}
        self._pos = pos

    def copy(self) -> "ElemList":
        return ElemList(list(self.keys), list(self.values), dict(self._pos))

    def __len__(self) -> int:
        return len(self.keys)

    def insert_index(self, index: int, key: str, value: Any) -> None:
        self.keys.insert(index, key)
        self.values.insert(index, value)
        pos = self._pos
        for i in range(index, len(self.keys)):
            pos[self.keys[i]] = i

    def remove_index(self, index: int) -> None:
        key = self.keys.pop(index)
        self.values.pop(index)
        pos = self._pos
        del pos[key]
        for i in range(index, len(self.keys)):
            pos[self.keys[i]] = i

    def set_value(self, key: str, value: Any) -> None:
        self.values[self._pos[key]] = value

    def get_value(self, key: str) -> Any:
        return self.values[self._pos[key]]

    def index_of(self, key: str) -> int:
        """Index of `key` among visible elements, or -1."""
        return self._pos.get(key, -1)

    def key_of(self, index: int) -> str | None:
        """Element ID at `index`, or None if out of range."""
        if 0 <= index < len(self.keys):
            return self.keys[index]
        return None

    def __iter__(self) -> Iterator[str]:
        return iter(self.keys)

    def __repr__(self) -> str:
        return f"ElemList({list(zip(self.keys, self.values))!r})"
