"""Visible-element order index for lists and text.

The reference maintains this index as a persistent order-statistic skip list
(/root/reference/src/skip_list.js) giving O(log n) key<->index queries with
O(1) snapshots via structural sharing. The TPU-native design replaces rank
queries with tombstone bitmaps + prefix scans in the columnar engine
(automerge_tpu/engine/kernels.py); this host-side structure serves the
interactive single-document frontend, where it must stay responsive on
100K+-element live documents (VERDICT r2 #4).

Design: a persistent chunked sequence. Elements live in immutable chunks
(tuples of ~CHUNK keys/values) referenced from a per-instance top-level
list. An edit path-copies one chunk and rebuilds the top list:
O(CHUNK + n/CHUNK) — O(sqrt n) with the default chunk size at interactive
document scales — while `copy()` is O(1) (children share chunks and key
maps; the source is never mutated after being copied, per the builder's
discipline below). Old snapshots remain fully queryable, exactly like the
reference's skip list.

The key -> chunk-id map is layered for cheap bulk builds: a shared plain
dict base (built in one O(n) pass by the bulk loader) plus a persistent
HAMT overlay (utils/persist.PMap) carrying edits since the base, rebased
into a fresh dict when it grows past a fraction of the base — amortized
O(1) per edit, never mutating a structure another snapshot can see.

The public surface mirrors the skip list's: insert_index / set_value /
remove_index / index_of / key_of / get_value
(/root/reference/src/skip_list.js:169-327).

Persistence contract: instances are immutable-by-discipline; the OpSet
builder copies an ElemList before mutating it (copy-on-first-touch per
change batch), and never mutates an instance after copying it.
"""

from __future__ import annotations

from typing import Any, Iterator

from ..utils.persist import CowDict

# Split threshold; chunks split into two halves of CHUNK each. 256 keeps
# both terms of the O(CHUNK + n/CHUNK) edit cost in the low microseconds
# up to ~1M elements.
CHUNK = 256


class ElemList:
    __slots__ = ("_ids", "_keys", "_vals", "_kmap", "_pos",
                 "_cum", "_next_id", "_flat_k", "_flat_v")

    def __init__(self, keys: list[str] | None = None,
                 values: list[Any] | None = None):
        # top-level parallel lists: chunk ids, key tuples, value tuples
        self._ids: list[int] = []
        self._keys: list[tuple] = []
        self._vals: list[tuple] = []
        self._kmap = CowDict()           # key -> chunk id (O(1) snapshots)
        self._pos: dict[int, int] | None = None   # chunk id -> top index
        self._cum: list[int] | None = None        # cumulative sizes
        self._flat_k: list[str] | None = None     # cached flat key list
        self._flat_v: list[Any] | None = None     # cached flat value list
        self._next_id = 0
        if keys:
            values = values if values is not None else [None] * len(keys)
            kmap = self._kmap
            for lo in range(0, len(keys), CHUNK):
                cid = self._next_id
                self._next_id += 1
                ck = tuple(keys[lo:lo + CHUNK])
                self._ids.append(cid)
                self._keys.append(ck)
                self._vals.append(tuple(values[lo:lo + CHUNK]))
                for k in ck:
                    kmap[k] = cid   # fresh CowDict: plain-dict speed

    # -- key map -----------------------------------------------------------

    def _kget(self, key: str):
        return self._kmap.get(key)

    def _kset(self, key: str, cid: int) -> None:
        self._kmap[key] = cid

    def _kdel(self, key: str) -> None:
        self._kmap.pop(key, None)

    # -- snapshots ---------------------------------------------------------

    def copy(self) -> "ElemList":
        """O(1): shares every chunk, the key map (copy-on-write), and the
        caches; the top-level lists are un-shared on first mutation. (The
        flat-array predecessor copied all n entries here — the dominant
        cost of interactive editing at scale.)"""
        out = ElemList()
        out._ids = self._ids
        out._keys = self._keys
        out._vals = self._vals
        out._kmap = self._kmap.copy()
        out._pos = self._pos
        out._cum = self._cum
        out._flat_k = self._flat_k
        out._flat_v = self._flat_v
        out._next_id = self._next_id
        return out

    def _own_top(self) -> None:
        """Un-share the top-level lists before an in-place top mutation.
        Chunks themselves are immutable tuples, never edited in place."""
        self._ids = list(self._ids)
        self._keys = list(self._keys)
        self._vals = list(self._vals)

    # -- caches ------------------------------------------------------------

    def _ensure_caches(self) -> None:
        if self._pos is None:
            self._pos = {cid: i for i, cid in enumerate(self._ids)}
        if self._cum is None:
            cum = []
            total = 0
            for ck in self._keys:
                cum.append(total)
                total += len(ck)
            self._cum = cum

    def _locate_rank(self, index: int) -> tuple[int, int]:
        """(top position, offset) of global rank `index`."""
        self._ensure_caches()
        cum = self._cum
        lo, hi = 0, len(cum) - 1
        while lo < hi:   # rightmost chunk with cum <= index
            mid = (lo + hi + 1) // 2
            if cum[mid] <= index:
                lo = mid
            else:
                hi = mid - 1
        return lo, index - cum[lo]

    # -- queries -----------------------------------------------------------

    def __len__(self) -> int:
        if self._cum is not None:
            return (self._cum[-1] + len(self._keys[-1])) if self._keys else 0
        return sum(len(ck) for ck in self._keys)

    def index_of(self, key: str) -> int:
        """Index of `key` among visible elements, or -1."""
        cid = self._kget(key)
        if cid is None:
            return -1
        self._ensure_caches()
        p = self._pos.get(cid)
        if p is None:
            return -1
        try:
            off = self._keys[p].index(key)
        except ValueError:
            return -1
        return self._cum[p] + off

    def key_of(self, index: int) -> str | None:
        """Element ID at `index`, or None if out of range."""
        if index < 0 or not self._keys or index >= len(self):
            return None
        p, off = self._locate_rank(index)
        return self._keys[p][off]

    def value_at(self, index: int):
        """Value at visible rank `index` (raises IndexError out of range)."""
        if index < 0 or not self._keys or index >= len(self):
            raise IndexError(index)
        p, off = self._locate_rank(index)
        return self._vals[p][off]

    def get_value(self, key: str) -> Any:
        cid = self._kget(key)
        if cid is None:
            raise KeyError(key)
        self._ensure_caches()
        p = self._pos[cid]
        return self._vals[p][self._keys[p].index(key)]

    # -- mutations (only between copy() and commit) ------------------------

    def insert_index(self, index: int, key: str, value: Any) -> None:
        self._own_top()
        if not self._keys:
            cid = self._next_id
            self._next_id += 1
            self._ids.append(cid)
            self._keys.append((key,))
            self._vals.append((value,))
            self._kset(key, cid)
            self._pos = None
            self._cum = None
            self._flat_k = None
            self._flat_v = None
            return
        if index >= len(self):
            p = len(self._keys) - 1
            off = len(self._keys[p])
        else:
            p, off = self._locate_rank(index)
        ck, cv = self._keys[p], self._vals[p]
        nk = ck[:off] + (key,) + ck[off:]
        nv = cv[:off] + (value,) + cv[off:]
        cid = self._ids[p]
        self._kset(key, cid)
        if len(nk) <= 2 * CHUNK:
            self._keys[p] = nk
            self._vals[p] = nv
            # common case: chunk set unchanged — shift the rank cache
            # incrementally instead of invalidating (a keystroke would
            # otherwise pay a full O(chunks) rebuild on its next read)
            if self._cum is not None:
                cum = self._cum = list(self._cum)
                for i in range(p + 1, len(cum)):
                    cum[i] += 1
        else:
            # split: left half keeps the id (most keys stay mapped),
            # right half gets a fresh id and remaps its keys
            half = len(nk) // 2
            rid = self._next_id
            self._next_id += 1
            self._keys[p:p + 1] = [nk[:half], nk[half:]]
            self._vals[p:p + 1] = [nv[:half], nv[half:]]
            self._ids[p:p + 1] = [cid, rid]
            for k in nk[half:]:
                self._kset(k, rid)
            self._pos = None
            self._cum = None
        self._flat_k = None
        self._flat_v = None

    def remove_index(self, index: int) -> None:
        p, off = self._locate_rank(index)
        self._own_top()
        ck, cv = self._keys[p], self._vals[p]
        self._kdel(ck[off])
        nk = ck[:off] + ck[off + 1:]
        if nk:
            self._keys[p] = nk
            self._vals[p] = cv[:off] + cv[off + 1:]
            if self._cum is not None:  # chunk set unchanged: shift ranks
                cum = self._cum = list(self._cum)
                for i in range(p + 1, len(cum)):
                    cum[i] -= 1
        else:
            del self._ids[p], self._keys[p], self._vals[p]
            self._pos = None
            self._cum = None
        self._flat_k = None
        self._flat_v = None

    def set_value(self, key: str, value: Any) -> None:
        cid = self._kget(key)
        if cid is None:
            raise KeyError(key)
        self._ensure_caches()
        p = self._pos[cid]
        off = self._keys[p].index(key)
        self._own_top()
        cv = self._vals[p]
        self._vals[p] = cv[:off] + (value,) + cv[off + 1:]
        self._flat_v = None

    # -- iteration ---------------------------------------------------------

    @property
    def keys(self) -> list[str]:
        """Flat visible-key list (materialized once per version, cached —
        callers iterate it like the old flat attribute; do not mutate)."""
        if self._flat_k is None:
            out: list[str] = []
            for ck in self._keys:
                out.extend(ck)
            self._flat_k = out
        return self._flat_k

    @property
    def values(self) -> list[Any]:
        """Flat value list (cached like `keys`; do not mutate)."""
        if self._flat_v is None:
            out: list[Any] = []
            for cv in self._vals:
                out.extend(cv)
            self._flat_v = out
        return self._flat_v

    def __iter__(self) -> Iterator[str]:
        for ck in self._keys:
            yield from ck

    def __repr__(self) -> str:
        return f"ElemList({list(zip(self.keys, self.values))!r})"
