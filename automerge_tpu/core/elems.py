"""Visible-element order index for lists and text.

The reference maintains this index as a persistent order-statistic skip list
(/root/reference/src/skip_list.js) giving O(log n) key<->index queries with
O(1) snapshots via structural sharing. The TPU-native design replaces rank
queries with tombstone bitmaps + prefix scans in the columnar engine
(automerge_tpu/engine/kernels.py); this host-side structure serves the
interactive single-document frontend, where it must stay responsive on
100K+-element live documents (VERDICT r2 #4).

Design: a persistent chunked sequence. Elements live in immutable chunks
(tuples of ~CHUNK keys/values) referenced from a per-instance top-level
list. An edit path-copies one chunk and rebuilds the top list:
O(CHUNK + n/CHUNK) — O(sqrt n) with the default chunk size at interactive
document scales — while `copy()` is O(1) (children share chunks and key
maps; the source is never mutated after being copied, per the builder's
discipline below). Old snapshots remain fully queryable, exactly like the
reference's skip list.

The key -> chunk-id map is layered for cheap bulk builds: a shared plain
dict base (built in one O(n) pass by the bulk loader) plus a persistent
HAMT overlay (utils/persist.PMap) carrying edits since the base, rebased
into a fresh dict when it grows past a fraction of the base — amortized
O(1) per edit, never mutating a structure another snapshot can see.

The public surface mirrors the skip list's: insert_index / set_value /
remove_index / index_of / key_of / get_value
(/root/reference/src/skip_list.js:169-327).

Persistence contract: instances are immutable-by-discipline; the OpSet
builder copies an ElemList before mutating it (copy-on-first-touch per
change batch), and never mutates an instance after copying it.
"""

from __future__ import annotations

from typing import Any, Iterator

import numpy as np

from ..utils.persist import CowDict

# Split threshold; chunks split into two halves of CHUNK each. 256 keeps
# both terms of the O(CHUNK + n/CHUNK) edit cost in the low microseconds
# up to ~1M elements.
CHUNK = 256


class ElemList:
    __slots__ = ("_ids", "_keys", "_vals", "_kmap", "_pos",
                 "_cum", "_next_id", "_flat_k", "_flat_v", "_owned")

    def __init__(self, keys: list[str] | None = None,
                 values: list[Any] | None = None):
        # top-level parallel lists: chunk ids, key tuples, value tuples
        self._ids: list[int] = []
        self._keys: list[tuple] = []
        self._vals: list[tuple] = []
        self._kmap = CowDict()           # key -> chunk id (O(1) snapshots)
        self._pos: dict[int, int] | None = None   # chunk id -> top index
        self._cum: list[int] | None = None        # cumulative sizes
        self._flat_k: list[str] | None = None     # cached flat key list
        self._flat_v: list[Any] | None = None     # cached flat value list
        self._next_id = 0
        self._owned = True               # top lists private to this instance
        if keys:
            values = values if values is not None else [None] * len(keys)
            kmap = self._kmap
            for lo in range(0, len(keys), CHUNK):
                cid = self._next_id
                self._next_id += 1
                ck = tuple(keys[lo:lo + CHUNK])
                self._ids.append(cid)
                self._keys.append(ck)
                self._vals.append(tuple(values[lo:lo + CHUNK]))
                for k in ck:
                    kmap[k] = cid   # fresh CowDict: plain-dict speed

    # -- key map -----------------------------------------------------------

    def _kget(self, key: str):
        return self._kmap.get(key)

    def _kset(self, key: str, cid: int) -> None:
        self._kmap[key] = cid

    def _kdel(self, key: str) -> None:
        self._kmap.pop(key, None)

    # -- snapshots ---------------------------------------------------------

    def copy(self) -> "ElemList":
        """O(1): shares every chunk, the key map (copy-on-write), and the
        caches; the top-level lists are un-shared on first mutation. (The
        flat-array predecessor copied all n entries here — the dominant
        cost of interactive editing at scale.)"""
        out = ElemList()
        out._ids = self._ids
        out._keys = self._keys
        out._vals = self._vals
        out._kmap = self._kmap.copy()
        out._pos = self._pos
        out._cum = self._cum
        out._flat_k = self._flat_k
        out._flat_v = self._flat_v
        out._next_id = self._next_id
        # BOTH sides lose top-list ownership: the child shares the parent's
        # lists until its first mutation, and the parent must no longer
        # mutate them in place either (never happens under the builder's
        # copy-before-mutate discipline, but keep the invariant airtight)
        self._owned = False
        out._owned = False
        return out

    def _own_top(self) -> None:
        """Un-share the top-level lists before an in-place top mutation
        (once per copy: a batch of edits pays ONE three-list fork, not one
        per edit). Chunks themselves are immutable tuples, never edited in
        place."""
        if self._owned:
            return
        self._ids = list(self._ids)
        self._keys = list(self._keys)
        self._vals = list(self._vals)
        self._owned = True

    # -- caches ------------------------------------------------------------

    def _ensure_caches(self) -> None:
        # C-speed rebuilds: dict(zip) + numpy cumsum, not Python loops —
        # interactive keystrokes patch `_cum` with vectorized shifts
        # (keystroke latency must stay flat in document length: the old
        # per-edit O(chunks) Python patch loop was the r8 flatness
        # regression), and the span-merge plane interleaves queries with
        # splices, so a long document rebuilds these once per placed span
        if self._pos is None:
            self._pos = dict(zip(self._ids, range(len(self._ids))))
        if self._cum is None:
            n = len(self._keys)
            cum = np.zeros(n, np.int64)
            if n > 1:
                np.cumsum(np.fromiter(map(len, self._keys[:-1]),
                                      np.int64, n - 1), out=cum[1:])
            self._cum = cum

    def _locate_rank(self, index: int) -> tuple[int, int]:
        """(top position, offset) of global rank `index`."""
        self._ensure_caches()
        cum = self._cum
        p = int(np.searchsorted(cum, index, side="right")) - 1
        return p, index - int(cum[p])

    # -- queries -----------------------------------------------------------

    def __len__(self) -> int:
        if self._cum is not None:
            return (int(self._cum[-1]) + len(self._keys[-1])) \
                if self._keys else 0
        return sum(len(ck) for ck in self._keys)

    def index_of(self, key: str) -> int:
        """Index of `key` among visible elements, or -1."""
        cid = self._kget(key)
        if cid is None:
            return -1
        self._ensure_caches()
        p = self._pos.get(cid)
        if p is None:
            return -1
        try:
            off = self._keys[p].index(key)
        except ValueError:
            return -1
        return int(self._cum[p]) + off

    def key_of(self, index: int) -> str | None:
        """Element ID at `index`, or None if out of range."""
        if index < 0 or not self._keys or index >= len(self):
            return None
        p, off = self._locate_rank(index)
        return self._keys[p][off]

    def value_at(self, index: int):
        """Value at visible rank `index` (raises IndexError out of range)."""
        if index < 0 or not self._keys or index >= len(self):
            raise IndexError(index)
        p, off = self._locate_rank(index)
        return self._vals[p][off]

    def get_value(self, key: str) -> Any:
        cid = self._kget(key)
        if cid is None:
            raise KeyError(key)
        self._ensure_caches()
        p = self._pos[cid]
        return self._vals[p][self._keys[p].index(key)]

    # -- mutations (only between copy() and commit) ------------------------

    def insert_index(self, index: int, key: str, value: Any) -> None:
        self._own_top()
        if not self._keys:
            cid = self._next_id
            self._next_id += 1
            self._ids.append(cid)
            self._keys.append((key,))
            self._vals.append((value,))
            self._kset(key, cid)
            self._pos = None
            self._cum = None
            self._flat_k = None
            self._flat_v = None
            return
        if index >= len(self):
            p = len(self._keys) - 1
            off = len(self._keys[p])
        else:
            p, off = self._locate_rank(index)
        ck, cv = self._keys[p], self._vals[p]
        nk = ck[:off] + (key,) + ck[off:]
        nv = cv[:off] + (value,) + cv[off:]
        cid = self._ids[p]
        self._kset(key, cid)
        if len(nk) <= 2 * CHUNK:
            self._keys[p] = nk
            self._vals[p] = nv
            # common case: chunk set unchanged — shift the rank cache
            # with one vectorized add instead of invalidating (a
            # keystroke must neither rebuild O(chunks) caches nor pay an
            # O(chunks) Python patch loop: flat in document length)
            if self._cum is not None:
                cum = self._cum = self._cum.copy()
                cum[p + 1:] += 1
        else:
            # split: left half keeps the id (most keys stay mapped),
            # right half gets a fresh id and remaps its keys
            half = len(nk) // 2
            rid = self._next_id
            self._next_id += 1
            self._keys[p:p + 1] = [nk[:half], nk[half:]]
            self._vals[p:p + 1] = [nv[:half], nv[half:]]
            self._ids[p:p + 1] = [cid, rid]
            for k in nk[half:]:
                self._kset(k, rid)
            self._pos = None
            self._cum = None
        self._flat_k = None
        self._flat_v = None

    def own_kmap(self) -> None:
        """Force the key map into owned (plain-dict) mode: one O(n) base
        fork now, dict-speed writes afterwards. The span-merge plane
        (core/textspans.py) calls this before a write burst large enough
        that per-key persistent-overlay updates would dominate the merge;
        sharing-safe (the shared base is forked, never mutated)."""
        self._kmap.rebase()

    def splice_insert(self, index: int, keys: list[str],
                      values: list[Any]) -> None:
        """Insert len(keys) consecutive elements at `index` in ONE splice:
        O(k + chunks) instead of k per-op insert_index calls at
        O(CHUNK + chunks) each. This is the span-splice primitive of the
        batched text-merge plane (core/textspans.py): the run lands as
        freshly-built chunks between the two halves of the split chunk,
        and only the SMALLER surviving half remaps its keys (the larger
        half keeps the split chunk's id) — key-map writes per splice are
        k + min(off, CHUNK - off), not k + CHUNK."""
        k = len(keys)
        if k == 0:
            return
        if k == 1:
            self.insert_index(index, keys[0], values[0])
            return
        self._own_top()
        if not self._keys:
            p = 0
            old_id = None
            head_k = head_v = tail_k = tail_v = ()
        else:
            if index >= len(self):
                p = len(self._keys) - 1
                off = len(self._keys[p])
            else:
                p, off = self._locate_rank(index)
            ck, cv = self._keys[p], self._vals[p]
            old_id = self._ids[p]
            head_k, head_v = ck[:off], cv[:off]
            tail_k, tail_v = ck[off:], cv[off:]
        new_ids, new_keys, new_vals = [], [], []

        def piece(pk, pv, cid):
            if not pk:
                return
            if cid is None:
                cid = self._next_id
                self._next_id += 1
                for kk in pk:
                    self._kset(kk, cid)
            new_ids.append(cid)
            new_keys.append(pk)
            new_vals.append(pv)

        # the larger surviving half keeps the split chunk's id
        head_keeps = len(head_k) >= len(tail_k)
        piece(head_k, head_v, old_id if head_keeps else None)
        for lo in range(0, k, CHUNK):
            cid = self._next_id
            self._next_id += 1
            nk = tuple(keys[lo:lo + CHUNK])
            new_ids.append(cid)
            new_keys.append(nk)
            new_vals.append(tuple(values[lo:lo + CHUNK]))
            for kk in nk:
                self._kset(kk, cid)
        piece(tail_k, tail_v, None if head_keeps else old_id)
        had_chunks = bool(self._keys)
        if had_chunks:
            self._ids[p:p + 1] = new_ids
            self._keys[p:p + 1] = new_keys
            self._vals[p:p + 1] = new_vals
        else:
            self._ids, self._keys, self._vals = new_ids, new_keys, new_vals
        # rank-cache maintenance: patch `_cum` with three vectorized
        # segments instead of invalidating — the span plane alternates
        # placement queries with splices, and a full O(chunks) rebuild
        # per splice was the dominant merge cost at 1M characters.
        # `_pos` genuinely changes for every chunk after p (the top list
        # shifted), so it is rebuilt lazily at C speed by _ensure_caches.
        if self._cum is not None and had_chunks:
            m = len(new_ids)
            sizes = np.fromiter(map(len, new_keys), np.int64, m)
            mid = np.zeros(m, np.int64)
            np.cumsum(sizes[:-1], out=mid[1:])
            self._cum = np.concatenate(
                [self._cum[:p], self._cum[p] + mid,
                 self._cum[p + 1:] + k])
        else:
            self._cum = None
        self._pos = None
        self._flat_k = None
        self._flat_v = None

    def remove_index(self, index: int) -> None:
        p, off = self._locate_rank(index)
        self._own_top()
        ck, cv = self._keys[p], self._vals[p]
        self._kdel(ck[off])
        nk = ck[:off] + ck[off + 1:]
        if nk:
            self._keys[p] = nk
            self._vals[p] = cv[:off] + cv[off + 1:]
            if self._cum is not None:  # chunk set unchanged: shift ranks
                cum = self._cum = self._cum.copy()
                cum[p + 1:] -= 1
        else:
            del self._ids[p], self._keys[p], self._vals[p]
            self._pos = None
            self._cum = None
        self._flat_k = None
        self._flat_v = None

    def set_value(self, key: str, value: Any) -> None:
        cid = self._kget(key)
        if cid is None:
            raise KeyError(key)
        self._ensure_caches()
        p = self._pos[cid]
        off = self._keys[p].index(key)
        self._own_top()
        cv = self._vals[p]
        self._vals[p] = cv[:off] + (value,) + cv[off + 1:]
        self._flat_v = None

    # -- iteration ---------------------------------------------------------

    @property
    def keys(self) -> list[str]:
        """Flat visible-key list (materialized once per version, cached —
        callers iterate it like the old flat attribute; do not mutate)."""
        if self._flat_k is None:
            out: list[str] = []
            for ck in self._keys:
                out.extend(ck)
            self._flat_k = out
        return self._flat_k

    @property
    def values(self) -> list[Any]:
        """Flat value list (cached like `keys`; do not mutate)."""
        if self._flat_v is None:
            out: list[Any] = []
            for cv in self._vals:
                out.extend(cv)
            self._flat_v = out
        return self._flat_v

    def __iter__(self) -> Iterator[str]:
        for ck in self._keys:
            yield from ck

    def __repr__(self) -> str:
        return f"ElemList({list(zip(self.keys, self.values))!r})"
