"""Bulk loader: reconstruct an OpSet from a saved change log without the
per-op interpretive loop.

The interpretive path (`opset.add_changes`) replays a log change by change:
every list edit pays an index-resolution + visible-index update against the
CURRENT state, so loading an n-edit text history costs O(n^2) — the exact
cost profile the reference pays through its skip list, made worse by the
flat-array ElemList (VERDICT r1 weak #4). This module is the engine-style
answer (VERDICT r1 next #7: "route load() of large docs through the
engine"): parse the JSON with the native wire codec (no per-op Python
dicts), validate causal order vectorized, compute field survivors with the
same order-independent domination rule the device kernels use
(engine/kernels.py:field_states, op_set.js:179-209), linearize each list
ONCE with the native RGA linearizer, and bulk-build the final ObjState
tables. Per-op Python work is reduced to allocating the Op/Change records
the interactive OpSet state must contain anyway.

The result is bit-equivalent to interpretive application (asserted by
tests/test_bulkload.py over random traces, including the follow-up
behavior of documents edited after loading). Anything the fast path cannot
prove it handles exactly — out-of-order logs, duplicate deliveries,
unknown dependencies, dangling parents — raises BulkUnsupported and the
caller falls back to the interpretive path, which reproduces the
reference's behavior (queueing, idempotent drops, errors) faithfully.
"""

from __future__ import annotations

import numpy as np

from .change import Change, Op
from .elems import ElemList
from .ids import HEAD, ROOT_ID, make_elem_id
from .opset import Link, ObjState, OpSet
from ..utils import metrics
from ..utils.persist import AList

# Below this many changes the interpretive path wins (fixed numpy/native
# overheads dominate); load() also uses it as the routing threshold.
BULK_MIN_CHANGES = 64


class BulkUnsupported(Exception):
    """The log needs the general interpretive path (not an error)."""


def try_bulk_load(data: str, max_version: int | None = None) -> OpSet | None:
    """OpSet from a JSON save payload via the native parser + vectorized
    state build; None when the fast path does not apply (caller falls back
    to interpretive replay). `max_version` is the caller's supported save
    format version: a canonical payload declaring a higher one falls back
    so the interpretive path can raise its version error."""
    from ..native.wire import parse_changes_json

    arr = _changes_array_slice(data, max_version)
    if arr is None:
        return None
    try:
        cols = parse_changes_json(arr)
    except ValueError:
        return None  # malformed for the native parser: let json.loads decide
    if cols is None or cols.n_changes < BULK_MIN_CHANGES:
        return None
    return try_bulk_build(cols)


def try_bulk_build(cols) -> OpSet | None:
    """build_opset with the GC pause and the observable-fallback contract;
    None when the log needs the interpretive path. Shared by load() and the
    adaptive dispatcher (engine/dispatch.py)."""
    # The build allocates hundreds of thousands of long-lived records; the
    # cyclic GC's generational scans over that growing heap cost ~35% of the
    # build at 64K changes. Nothing here creates cycles — pause it.
    from ..utils.gcpause import gc_paused
    with gc_paused():
        try:
            return build_opset(cols)
        except BulkUnsupported:
            return None
        except KeyError:
            # structural reference the fast path didn't expect (e.g. op on
            # an object created by a queued change): interpretive path
            # handles it. Counted so an unexpected fallback (a fast-path
            # bug demoted to a perf regression) is observable rather than
            # silent.
            metrics.bump("core_bulk_fallbacks")
            return None


_CANON_RE = None


def _changes_array_slice(data: str, max_version: int | None) -> str | None:
    """The JSON array of changes inside a save payload: either the payload
    itself (bare list) or the value of the "changes" key in OUR canonical
    save shape '{"automerge_tpu": N, "changes": [...]}'. Any other dict
    shape returns None — the fast path must engage only where it is
    provably behavior-equivalent to the interpretive fallback (a nested
    "changes" key elsewhere, or an unknown version, must get the fallback's
    semantics, including its errors)."""
    global _CANON_RE
    s = data.lstrip()
    if s.startswith("["):
        return s
    if not s.startswith("{"):
        return None
    if _CANON_RE is None:
        import re
        _CANON_RE = re.compile(
            r'\{\s*"automerge_tpu"\s*:\s*(\d+)\s*,\s*"changes"\s*:\s*\[')
    m = _CANON_RE.match(s)
    if not m:
        return None
    if max_version is not None and int(m.group(1)) > max_version:
        return None
    b = m.end() - 1
    e = s.rfind("]")
    if e <= b or s[e + 1:].strip() != "}":
        return None
    return s[b:e + 1]


def build_opset(cols) -> OpSet:
    """Build the OpSet for a causally-ordered, duplicate-free change log
    given as native wire columns. Raises BulkUnsupported otherwise."""
    from ..storage import _ACTIONS

    act_idx = {a: i for i, a in enumerate(_ACTIONS)}
    i_ins, i_set, i_del, i_link = (act_idx["ins"], act_idx["set"],
                                   act_idx["del"], act_idx["link"])
    make_codes = (act_idx["makeMap"], act_idx["makeList"], act_idx["makeText"])
    if (np.asarray(cols.op_action) == act_idx["move"]).any():
        # the move plane's resolution (winner + cycle fixpoint,
        # core/moves.py) has no vectorized from-scratch formulation here
        # yet; the interpretive path owns those semantics
        raise BulkUnsupported("log contains move ops")

    n_ch = cols.n_changes
    actors = cols.actors
    objects_tab = cols.objects
    keys_tab = cols.keys
    ch_actor = np.asarray(cols.change_actor, np.int64)
    ch_seq = np.asarray(cols.change_seq, np.int64)

    # ------------------------------------------------------------------
    # 1. header validation (vectorized): per-actor seqs must run 1..k in
    # application order; every dep must name an earlier change.
    order = np.argsort(ch_actor, kind="stable")
    sa = ch_actor[order]
    within = np.empty(n_ch, np.int64)
    within[order] = np.arange(n_ch) - np.searchsorted(sa, sa)
    if not (ch_seq == within + 1).all():
        raise BulkUnsupported("non-contiguous or duplicated sequence numbers")

    key = ch_actor << 32 | ch_seq
    d_actor = np.asarray(cols.deps_actor, np.int64)
    d_seq = np.asarray(cols.deps_seq, np.int64)
    d_off = np.asarray(cols.deps_off, np.int64)
    dep_owner = np.repeat(np.arange(n_ch), np.diff(d_off))
    if len(d_actor):
        if (d_seq <= 0).any():
            raise BulkUnsupported("dependency with non-positive seq")
        dkey = d_actor << 32 | d_seq
        sort_key = np.argsort(key, kind="stable")
        skey = key[sort_key]
        pos = np.searchsorted(skey, dkey)
        ok = (pos < n_ch) & (skey[np.minimum(pos, n_ch - 1)] == dkey)
        if not ok.all():
            raise BulkUnsupported("dependency on a change not in the log")
        dep_app = sort_key[pos]
        if not (dep_app < dep_owner).all():
            raise BulkUnsupported("log is not in causal order")

    # ------------------------------------------------------------------
    # 2. per-change transitive clocks (op_set.js:29-37) + deps frontier;
    # dicts are actor-string keyed, exactly what OpSet.states stores.
    dep_lists: list[list[tuple[int, int]]] = [[] for _ in range(n_ch)]
    for own, da, ds in zip(dep_owner.tolist(), d_actor.tolist(),
                           d_seq.tolist()):
        dep_lists[own].append((da, ds))
    idx_of_change: dict[int, int] = {}  # (actor<<32|seq) -> change index
    all_deps: list[dict] = [None] * n_ch  # type: ignore[list-item]
    frontier: dict[str, int] = {}
    last_of_actor: dict[int, int] = {}
    ch_actor_l = ch_actor.tolist()
    ch_seq_l = ch_seq.tolist()
    for i in range(n_ch):
        a, s = ch_actor_l[i], ch_seq_l[i]
        astr = actors[a]
        if s > 1:
            full = dict(all_deps[last_of_actor[a]])
            full[astr] = s - 1
        else:
            full = {}
        for (da, ds) in dep_lists[i]:
            dstr = actors[da]
            if da != a or ds != s - 1:
                prev = all_deps[idx_of_change[da << 32 | ds]]
                if prev:
                    for k2, v2 in prev.items():
                        if v2 > full.get(k2, 0):
                            full[k2] = v2
                if ds > full.get(dstr, 0):
                    full[dstr] = ds
        all_deps[i] = full
        idx_of_change[a << 32 | s] = i
        last_of_actor[a] = i
        stale = [k2 for k2, v2 in frontier.items() if v2 <= full.get(k2, 0)]
        for k2 in stale:
            del frontier[k2]
        frontier[astr] = s

    # ------------------------------------------------------------------
    # 3. flat op table + per-op stamps (plain lists: numpy scalar indexing
    # inside the per-op loops costs ~3x list indexing)
    op_off = np.asarray(cols.op_off, np.int64)
    op_off_l = op_off.tolist()
    op_change_l = np.repeat(np.arange(n_ch), np.diff(op_off)).tolist()
    op_action_l = np.asarray(cols.op_action, np.int64).tolist()
    op_obj_l = np.asarray(cols.op_obj, np.int64).tolist()
    op_key_l = np.asarray(cols.op_key, np.int64).tolist()
    op_elem_l = np.asarray(cols.op_elem, np.int64).tolist()
    n_ops = len(op_action_l)

    # history Changes (unstamped ops, as parsed — what save/getChanges and
    # the idempotent-redelivery equality check compare against). Op records
    # are built with __new__ + direct slot stores: this loop allocates one
    # object per op in the log and is the bulk path's floor.
    from ..native.wire import (V_BIGINT, V_DOUBLE, V_FALSE, V_INT, V_STR,
                               V_TRUE)
    op_vtag_l = np.asarray(cols.op_vtag, np.int64).tolist()
    op_vint_l = np.asarray(cols.op_vint, np.int64).tolist()
    op_vdbl_l = np.asarray(cols.op_vdbl, np.float64).tolist()
    op_vstr_l = np.asarray(cols.op_vstr, np.int64).tolist()
    strings_tab = cols.strings
    hist_ops: list[Op] = [None] * n_ops  # type: ignore[list-item]
    new_op = Op.__new__
    for j in range(n_ops):
        code = op_action_l[j]
        kj = op_key_l[j]
        ej = op_elem_l[j]
        value = None
        if code == i_set or code == i_link:
            tag = op_vtag_l[j]
            if tag == V_INT:
                value = op_vint_l[j]
            elif tag == V_STR:
                value = strings_tab[op_vstr_l[j]]
            elif tag == V_DOUBLE:
                value = op_vdbl_l[j]
            elif tag == V_TRUE:
                value = True
            elif tag == V_FALSE:
                value = False
            elif tag == V_BIGINT:
                value = int(strings_tab[op_vstr_l[j]])
        op = new_op(Op)
        op.action = _ACTIONS[code]
        op.obj = objects_tab[op_obj_l[j]]
        op.key = keys_tab[kj] if kj >= 0 else None
        op.value = value
        op.elem = ej if ej >= 0 else None
        op.actor = None
        op.seq = None
        hist_ops[j] = op
    change_msg_l = np.asarray(cols.change_msg, np.int64).tolist()
    history: list[Change] = []
    for i in range(n_ch):
        msg = (cols.messages[change_msg_l[i]]
               if change_msg_l[i] >= 0 else None)
        deps = {actors[da]: ds for (da, ds) in dep_lists[i]}
        history.append(Change(
            actors[ch_actor_l[i]], ch_seq_l[i], deps,
            hist_ops[op_off_l[i]:op_off_l[i + 1]], msg))

    # ------------------------------------------------------------------
    # 4. objects
    by_object: dict[str, ObjState] = {ROOT_ID: ObjState("makeMap")}
    make_set = set(make_codes)
    for j in range(n_ops):
        if op_action_l[j] in make_set:
            obj_id = objects_tab[op_obj_l[j]]
            if obj_id in by_object:
                raise BulkUnsupported("duplicate object creation")
            obj = ObjState(_ACTIONS[op_action_l[j]])
            if obj.is_sequence:
                # build at plain-dict speed; wrapped back into CowDict
                # after the per-op loops (CowDict(base) wraps, no copy)
                obj.fields = {}
                obj.following = {}
                obj.insertion = {}
            by_object[obj_id] = obj

    def _stamp(src, actor, seq, _new=Op.__new__, _op=Op):
        o = _new(_op)
        o.action = src.action
        o.obj = src.obj
        o.key = src.key
        o.value = src.value
        o.elem = src.elem
        o.actor = actor
        o.seq = seq
        return o

    # ------------------------------------------------------------------
    # 5. ins ops: following / insertion / max_elem (tombstones included)
    for j in range(n_ops):
        if op_action_l[j] != i_ins:
            continue
        ci = op_change_l[j]
        op = _stamp(hist_ops[j], actors[ch_actor_l[ci]], ch_seq_l[ci])
        obj = by_object[op.obj]
        eid = f"{op.actor}:{op.elem}"  # make_elem_id, inlined
        insertion = obj.insertion
        if op.key != HEAD and op.key not in insertion:
            raise BulkUnsupported("insert after unknown parent element")
        if eid in insertion:
            raise BulkUnsupported("duplicate list element ID")
        following = obj.following
        following[op.key] = following.get(op.key, ()) + (op,)
        if op.elem > obj.max_elem:
            obj.max_elem = op.elem
        insertion[eid] = op

    # ------------------------------------------------------------------
    # 6. assign ops: per-field survivor analysis. Same order-independent
    # rule as the device kernels (engine/kernels.py:field_states): op i is
    # overwritten iff some same-field op j from a different change causally
    # knows it (clock_j[actor_i] >= seq_i); survivors sort actor-descending
    # for the LWW winner (op_set.js:201); del survivors erase but are not
    # stored (op_set.js:184-199).
    op_action_arr = np.asarray(op_action_l, np.int64)
    op_obj_arr = np.asarray(op_obj_l, np.int64)
    op_key_arr = np.asarray(op_key_l, np.int64)
    asg = np.nonzero((op_action_arr == i_set) | (op_action_arr == i_del)
                     | (op_action_arr == i_link))[0]
    inbound_adds: list[tuple[int, str, Op]] = []
    if len(asg):
        fid = op_obj_arr[asg] << 32 | (op_key_arr[asg] & 0xFFFFFFFF)
        forder = np.argsort(fid, kind="stable")  # field-grouped, app order
        f_sorted = fid[forder]
        bounds = np.nonzero(np.r_[True, f_sorted[1:] != f_sorted[:-1]])[0]
        bounds_l = np.r_[bounds, len(f_sorted)].tolist()
        grouped = asg[forder].tolist()  # op idx, field-grouped, app order
        ranges = [(grouped[bounds_l[g]], bounds_l[g], bounds_l[g + 1])
                  for g in range(len(bounds_l) - 1)]
        ranges.sort()  # fields in first-assignment order

        # Dense per-change clock matrix for the vectorized domination test
        # (built once, only when some field has >1 op): dominated_i iff a
        # DIFFERENT change in the group causally knows op i —
        # clock[ci_j, actor_i] >= seq_i. Replaces the O(g^2) Python double
        # loop that dominated the LWW-storm build (many ops per field).
        # Below this group size the plain Python domination loop beats the
        # numpy path's setup cost (tombstone/text logs: 2-3 ops per key);
        # above it, one dense comparison wins (LWW storms: 40+ per key).
        # ONE constant for both the branch and the clock_mat build gate —
        # the numpy branch requires the matrix.
        SMALL_GROUP = 8
        clock_mat = None
        if any(hi - lo > SMALL_GROUP for (_j0, lo, hi) in ranges):
            actor_code = {a: c for c, a in enumerate(actors)}
            clock_mat = np.zeros((n_ch, len(actors)), np.int64)
            for i2, d in enumerate(all_deps):
                if d:
                    for astr2, v2 in d.items():
                        clock_mat[i2, actor_code[astr2]] = v2

        for (j0, lo, hi) in ranges:
            op0 = hist_ops[j0]
            obj = by_object[op0.obj]
            key_str = op0.key
            if obj.is_sequence and key_str not in obj.insertion:
                # interpretive path raises "Missing index entry" here;
                # fall back so the error surface is identical
                raise BulkUnsupported("assignment to unknown list element")
            if hi - lo == 1:
                ci = op_change_l[j0]
                if op_action_l[j0] == i_del:
                    obj.fields[key_str] = ()
                    continue
                op = _stamp(op0, actors[ch_actor_l[ci]], ch_seq_l[ci])
                obj.fields[key_str] = (op,)
                if op.action == "link":
                    inbound_adds.append((j0, op.value, op))
                continue
            # multi-op field: pairwise domination over the group. Two
            # regimes: small groups (the common tombstone/text shape, 2-3
            # ops per element key) stay on the plain loop — numpy setup
            # costs more than it saves there; big groups (LWW storms,
            # 40+ concurrent sets per key) go through one dense numpy
            # comparison against the per-change clock matrix.
            g = hi - lo
            idxs = grouped[lo:hi]
            remaining = []
            if g <= SMALL_GROUP:
                metas = []
                for j in idxs:
                    ci = op_change_l[j]
                    metas.append((j, ci, actors[ch_actor_l[ci]],
                                  ch_seq_l[ci]))
                for (j, ci, astr, s) in metas:
                    dominated = False
                    for (_j2, ci2, _a2, _s2) in metas:
                        if ci2 != ci and all_deps[ci2].get(astr, 0) >= s:
                            dominated = True
                            break
                    if dominated or op_action_l[j] == i_del:
                        continue
                    op = _stamp(hist_ops[j], astr, s)
                    remaining.append(op)
                    if op.action == "link":
                        inbound_adds.append((j, op.value, op))
            else:
                cis = np.fromiter((op_change_l[j] for j in idxs),
                                  np.int64, g)
                cis_l = cis.tolist()
                seqs = np.fromiter((ch_seq_l[ci] for ci in cis_l),
                                   np.int64, g)
                acts = np.fromiter((ch_actor_l[ci] for ci in cis_l),
                                   np.int64, g)
                vals = clock_mat[cis][:, acts]            # [j, i]
                dom = ((vals >= seqs[None, :])
                       & (cis[:, None] != cis[None, :])).any(axis=0)
                actions = np.fromiter((op_action_l[j] for j in idxs),
                                      np.int64, g)
                for x in np.nonzero(~dom & (actions != i_del))[0].tolist():
                    j = idxs[x]
                    op = _stamp(hist_ops[j], actors[acts[x]],
                                int(seqs[x]))
                    remaining.append(op)
                    if op.action == "link":
                        inbound_adds.append((j, op.value, op))
            remaining.sort(key=lambda o: o.actor or "", reverse=True)
            obj.fields[key_str] = tuple(remaining)
        # inbound links in application order (get_path reads the first)
        inbound_adds.sort(key=lambda t: t[0])
        for (_j, target, op) in inbound_adds:
            if target not in by_object:
                raise BulkUnsupported("link to unknown object")
            by_object[target].inbound[op] = None

    # ------------------------------------------------------------------
    # 7. list order: one native RGA linearization per sequence object,
    # then a bulk ElemList build of the visible elements (shared with the
    # no-diff interpretive load: opset.rebuild_elem_ids).

    # seal the plain-dict sequence state back into CowDicts (wrap, no copy)
    from ..utils.persist import CowDict
    for obj in by_object.values():
        if obj.is_sequence:
            obj.fields = CowDict(obj.fields)
            obj.following = CowDict(obj.following)
            obj.insertion = CowDict(obj.insertion)

    from .opset import rebuild_elem_ids

    actor_rank = {a: r for r, a in enumerate(sorted(set(actors)))}
    for obj in by_object.values():
        if obj.is_sequence:
            rebuild_elem_ids(obj, actor_rank)

    # ------------------------------------------------------------------
    # 8. states / clock / frontier / history
    states: dict[str, list] = {}
    for i in range(n_ch):
        states.setdefault(history[i].actor, []).append(
            (history[i], all_deps[i]))
    clock = {actors[a]: int(c) for a, c in
             zip(*np.unique(ch_actor, return_counts=True))}

    metrics.bump("core_changes_applied", n_ch)
    metrics.bump("core_ops_applied", n_ops)
    return OpSet(states={a: AList(v) for a, v in states.items()},
                 by_object=by_object, clock=clock, deps=frontier,
                 queue=(), history=AList(history))
