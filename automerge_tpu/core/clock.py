"""Vector clock helpers.

Clocks are plain dicts mapping actorId -> highest applied sequence number.
Semantics mirror the reference: `less_or_equal` is the partial order used to
detect divergence (/root/reference/src/automerge.js:264-268,
src/connection.js:7-11), `union` is the element-wise max merge used by the sync
protocol (src/connection.js:16-19).

In the columnar engine the same operations become masked integer compare-reduces
over `[n_docs, n_actors]` int32 matrices (see automerge_tpu/engine/causal.py).
"""

from __future__ import annotations

from typing import Mapping


def less_or_equal(clock1: Mapping[str, int], clock2: Mapping[str, int]) -> bool:
    """True iff every component of clock1 is <= the matching component of clock2."""
    for actor in set(clock1) | set(clock2):
        if clock1.get(actor, 0) > clock2.get(actor, 0):
            return False
    return True


def union(clock1: Mapping[str, int], clock2: Mapping[str, int]) -> dict[str, int]:
    """Element-wise max of two clocks."""
    out = dict(clock1)
    for actor, seq in clock2.items():
        if seq > out.get(actor, 0):
            out[actor] = seq
    return out


def equal(clock1: Mapping[str, int], clock2: Mapping[str, int]) -> bool:
    """Clock equality, treating absent entries as 0."""
    for actor in set(clock1) | set(clock2):
        if clock1.get(actor, 0) != clock2.get(actor, 0):
            return False
    return True
