"""Identifier scheme.

- The root object has a fixed all-zeros UUID (/root/reference/src/op_set.js:3,
  INTERNALS.md:124-126).
- Every other map/list/text object gets a fresh v4 UUID at creation time
  (/root/reference/src/automerge.js:41).
- List element IDs are `actorId + ':' + elem` where `elem` is a per-list
  Lamport counter (/root/reference/src/op_set.js:84, INTERNALS.md:133-162).
  Actor IDs may themselves contain ':' in principle, so parsing splits on the
  *last* colon (the reference uses the greedy regex /^(.*):(\\d+)$/,
  op_set.js:352).
"""

from __future__ import annotations

ROOT_ID = "00000000-0000-0000-0000-000000000000"
HEAD = "_head"


def make_elem_id(actor: str, elem: int) -> str:
    return f"{actor}:{elem}"


def parse_elem_id(elem_id: str) -> tuple[str, int] | None:
    """Return (actor, elem) or None if `elem_id` is not a valid element ID."""
    if not elem_id:
        return None
    actor, sep, num = elem_id.rpartition(":")
    if not sep or not num.isdigit():
        return None
    return actor, int(num)
