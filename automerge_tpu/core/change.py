"""Operation and change records — the wire-level "ISA" of the CRDT.

The operation vocabulary matches the reference exactly
(/root/reference/INTERNALS.md:117-194): `makeMap`, `makeList`, `makeText`,
`ins {obj, key: prevElemId|'_head', elem}`, `set {obj, key, value}`,
`link {obj, key, value: objectId}`, `del {obj, key}`.

A change is `{actor, seq, deps, message?, ops[]}` (INTERNALS.md:104-115, built
at /root/reference/src/auto_api.js:28-33). `deps` is the pruned dependency
frontier, not a full vector clock; full clocks are reconstructed via
`transitive_deps` (src/op_set.js:29-37).

Ops inside a change carry no actor/seq; they are stamped with the change's
(actor, seq) at application time (src/op_set.js:239). Ops stored in per-field
state *do* carry their stamp, which is what concurrency detection keys on.
"""

from __future__ import annotations

from typing import Any, Iterable, Mapping

MAKE_ACTIONS = ("makeMap", "makeList", "makeText")
ASSIGN_ACTIONS = ("set", "del", "link")
# `move` (r16) reparents a map child object or repositions a list element
# as ONE op: {obj: destination container, key: dest key (map) / dest anchor
# elemId or '_head' (list), value: moved object id (map) / moved elemId
# (list), elem: dest sibling-order counter (list moves only)}. Concurrent
# moves of one element resolve by priority; cycles resolve deterministically
# (core/moves.py). The reference has no equivalent — a reparent there is a
# delete + re-insert of the whole subtree.
ALL_ACTIONS = MAKE_ACTIONS + ("ins",) + ASSIGN_ACTIONS + ("move",)


class Op:
    __slots__ = ("action", "obj", "key", "value", "elem", "actor", "seq")

    def __init__(self, action: str, obj: str, key: str | None = None,
                 value: Any = None, elem: int | None = None,
                 actor: str | None = None, seq: int | None = None):
        self.action = action
        self.obj = obj
        self.key = key
        self.value = value
        self.elem = elem
        self.actor = actor
        self.seq = seq

    def stamped(self, actor: str, seq: int | None) -> "Op":
        """Copy of this op carrying the applying change's (actor, seq)."""
        return Op(self.action, self.obj, self.key, self.value, self.elem, actor, seq)

    def stripped(self) -> "Op":
        """Copy without actor/seq — the form stored in undo histories
        (/root/reference/src/automerge.js:14, auto_api.js:89)."""
        if self.actor is None and self.seq is None:
            return self
        return Op(self.action, self.obj, self.key, self.value, self.elem)

    def _key_tuple(self):
        value = self.value
        if isinstance(value, (dict, list)):  # unhashable payloads: compare by repr
            value = repr(value)
        return (self.action, self.obj, self.key, value, self.elem, self.actor, self.seq)

    def __eq__(self, other):
        if not isinstance(other, Op):
            return NotImplemented
        return (self.action == other.action and self.obj == other.obj
                and self.key == other.key and self.value == other.value
                and self.elem == other.elem and self.actor == other.actor
                and self.seq == other.seq)

    def __hash__(self):
        return hash(self._key_tuple())

    def __repr__(self):
        parts = [f"action={self.action!r}", f"obj={self.obj!r}"]
        for name in ("key", "value", "elem", "actor", "seq"):
            val = getattr(self, name)
            if val is not None:
                parts.append(f"{name}={val!r}")
        return f"Op({', '.join(parts)})"

    def to_dict(self) -> dict:
        out: dict[str, Any] = {"action": self.action, "obj": self.obj}
        if self.key is not None:
            out["key"] = self.key
        if self.action in ("set", "link", "move"):
            out["value"] = self.value
        if self.elem is not None:
            out["elem"] = self.elem
        return out

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "Op":
        return Op(d["action"], d["obj"], d.get("key"), d.get("value"), d.get("elem"))


class Change:
    __slots__ = ("actor", "seq", "deps", "message", "ops")

    def __init__(self, actor: str, seq: int, deps: Mapping[str, int],
                 ops: Iterable[Op], message: str | None = None):
        self.actor = actor
        self.seq = seq
        self.deps = dict(deps)
        self.message = message
        self.ops = tuple(ops)

    def __eq__(self, other):
        if not isinstance(other, Change):
            return NotImplemented
        return (self.actor == other.actor and self.seq == other.seq
                and self.deps == other.deps and self.message == other.message
                and self.ops == other.ops)

    def __hash__(self):
        return hash((self.actor, self.seq, tuple(sorted(self.deps.items())),
                     self.message, self.ops))

    def __repr__(self):
        return (f"Change(actor={self.actor!r}, seq={self.seq}, deps={self.deps!r}, "
                f"message={self.message!r}, ops={list(self.ops)!r})")

    def to_dict(self) -> dict:
        out: dict[str, Any] = {
            "actor": self.actor,
            "seq": self.seq,
            "deps": dict(self.deps),
            "ops": [op.to_dict() for op in self.ops],
        }
        if self.message is not None:
            out["message"] = self.message
        return out

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "Change":
        return Change(d["actor"], d["seq"], d.get("deps", {}),
                      [Op.from_dict(o) for o in d.get("ops", [])],
                      d.get("message"))


def coerce_change(c) -> Change:
    """Accept either a Change or a plain dict (the JSON wire form)."""
    if isinstance(c, Change):
        return c
    return Change.from_dict(c)
