"""The CRDT semantic core: causally-ordered op application, LWW conflict
resolution, RGA list ordering, and diff emission.

This is the host-side *oracle* engine. Its semantics mirror the reference's
OpSet (/root/reference/src/op_set.js) operation for operation; conformance
targets (each covered by a test in tests/):

- LWW winner among concurrent assigns = highest actorId (op_set.js:201,425);
  losers are retained as conflicts keyed by actor (op_set.js:428-434).
- Concurrent inserts at one position are ordered by Lamport (elem, actor)
  descending, so each actor's runs do not interleave (op_set.js:343-362).
- Delete vs concurrent assign: the assign wins — deletion only removes ops
  causally prior to it (op_set.js:184-199).
- Out-of-order changes buffer in a causal queue until ready (op_set.js:254-270);
  duplicate deliveries are idempotent no-ops; reusing an (actor, seq) with
  different content is an error (op_set.js:227-232).

The batched/columnar TPU execution path lives in automerge_tpu/engine/ and is
checked against this engine for byte-identical convergence (state hashing).

Persistence model: `OpSet` instances are immutable. Mutation happens through a
`Builder` that shallow-copies the top-level containers once per *batch* of
changes and copies per-object state on first touch, so old document snapshots
remain valid (the reference achieves the same with Immutable.js throughout,
op_set.js:272-285).
"""

from __future__ import annotations

from typing import Any, Iterator

from ..utils import metrics, oplag
from ..utils.persist import AList, CowDict, EMPTY_ALIST
from .change import Change, Op
from .ids import HEAD, ROOT_ID, make_elem_id, parse_elem_id
from .elems import ElemList


class Link:
    """Marker for a link value inside an ElemList (points at a child object)."""

    __slots__ = ("obj",)

    def __init__(self, obj: str):
        self.obj = obj

    def __eq__(self, other):
        return isinstance(other, Link) and self.obj == other.obj

    def __hash__(self):
        return hash(("__link__", self.obj))

    def __repr__(self):
        return f"Link({self.obj!r})"


class ObjState:
    """Per-object CRDT state (the reference's byObject entry, op_set.js:63-93).

    - fields: key/elemId -> tuple of surviving assign ops, winner first
    - following: parent elemId -> tuple of 'ins' ops inserted after it
    - insertion: elemId -> the 'ins' op that created it
    - inbound: ordered set (dict keys) of 'link' ops pointing at this object
    - max_elem: per-list Lamport counter for element IDs
    - elem_ids: visible-element order index (lists/text only)
    """

    __slots__ = ("init_action", "fields", "following", "insertion", "inbound",
                 "max_elem", "elem_ids", "moves", "loc")

    def __init__(self, init_action: str):
        self.init_action = init_action
        seq = init_action in ("makeList", "makeText")
        # Sequence objects grow with document length (one fields/insertion
        # entry per element, tombstones included); CowDict makes their
        # per-change-batch snapshot O(1) instead of O(n) — the role
        # Immutable.js Map plays in op_set.js:272-285. Plain maps stay
        # dicts: small, and their key enumeration order is user-visible.
        self.fields: dict[str, tuple[Op, ...]] = CowDict() if seq else {}
        self.following: dict[str, tuple[Op, ...]] = CowDict() if seq else {}
        self.insertion: dict[str, Op] = CowDict() if seq else {}
        self.inbound: dict[Op, None] = {}
        self.max_elem = 0
        self.elem_ids: ElemList | None = ElemList() if seq else None
        # move plane (core/moves.py): per moved list element its
        # (base ins op, non-dominated move candidates); per moved map
        # child its resolved effective location op. Empty/None for every
        # object no move has ever targeted — the reference semantics are
        # untouched until the first move arrives.
        self.moves: dict[str, tuple] = {}
        self.loc: Op | None = None

    def copy(self) -> "ObjState":
        out = ObjState.__new__(ObjState)
        out.init_action = self.init_action
        out.fields = self.fields.copy()
        out.following = self.following.copy()
        out.insertion = self.insertion.copy()
        out.inbound = dict(self.inbound)
        out.max_elem = self.max_elem
        out.elem_ids = self.elem_ids  # copied lazily by Builder.elem_ids_mut
        out.moves = dict(self.moves) if self.moves else {}
        out.loc = self.loc
        return out

    @property
    def is_sequence(self) -> bool:
        return self.init_action in ("makeList", "makeText")


class MoveEntry:
    """Per-moved-list-element move-plane state (one per ObjState.moves
    entry): the original ins (the undroppable base edge and the ghost
    spot's identity), the non-dominated move candidates, the per-actor
    MINIMUM move seq ever seen (`stamps` — what anchored_at_placed tests
    against; additions are monotone and already-admitted siblings can
    never cover a later-arriving move, so the ghost/placed split never
    flips), and whether any sibling op follows the placed spot (the flag
    that forces a full index rebuild when the winner changes)."""

    __slots__ = ("base", "cands", "stamps", "followers")

    def __init__(self, base: Op, cands: tuple = (),
                 stamps: dict | None = None, followers: bool = False):
        self.base = base
        self.cands = cands
        self.stamps = stamps if stamps is not None else {}
        self.followers = followers

    def copy(self) -> "MoveEntry":
        return MoveEntry(self.base, self.cands, dict(self.stamps),
                         self.followers)


class Builder:
    """Copy-on-write working state for applying a batch of changes."""

    __slots__ = ("states", "by_object", "clock", "deps", "queue", "history",
                 "moved_objs", "_touched", "_elem_copied", "_deferred_seqs")

    def __init__(self, opset: "OpSet"):
        self.states: dict[str, AList] = dict(opset.states)
        self.by_object: dict[str, ObjState] = dict(opset.by_object)
        self.clock: dict[str, int] = dict(opset.clock)
        self.deps: dict[str, int] = dict(opset.deps)
        self.queue: list[Change] = list(opset.queue)
        self.history: AList = opset.history
        self.moved_objs: set[str] = set(opset.moved_objs)
        self._touched: set[str] = set()
        self._elem_copied: set[str] = set()
        # sequence objects whose elem_ids maintenance was deferred by a
        # no-diff apply (add_changes(emit_diffs=False)); rebuilt once at
        # the end of the batch
        self._deferred_seqs: set[str] = set()

    def obj(self, object_id: str) -> ObjState:
        """Object state for mutation (copied on first touch in this batch)."""
        obj = self.by_object[object_id]
        if object_id not in self._touched:
            obj = obj.copy()
            self.by_object[object_id] = obj
            self._touched.add(object_id)
        return obj

    def elem_ids_mut(self, object_id: str) -> ElemList:
        obj = self.obj(object_id)
        if object_id not in self._elem_copied:
            obj.elem_ids = obj.elem_ids.copy()
            self._elem_copied.add(object_id)
        return obj.elem_ids


# ---------------------------------------------------------------------------
# Causality (op_set.js:7-37)

def is_concurrent(state, op1: Op, op2: Op) -> bool:
    """True if neither stamped op causally precedes the other (op_set.js:7-16).

    Ops lacking a (actor, seq) stamp — i.e. local ops inside an open change
    block — are never concurrent with anything: prior ops are treated as
    overwritten by the local edit.
    """
    a1, s1, a2, s2 = op1.actor, op1.seq, op2.actor, op2.seq
    if not a1 or not a2 or not s1 or not s2:
        return False
    clock1 = state.states[a1][s1 - 1][1]
    clock2 = state.states[a2][s2 - 1][1]
    return clock1.get(a2, 0) < s2 and clock2.get(a1, 0) < s1


def causally_ready(state, change: Change) -> bool:
    """True if every causal predecessor of `change` has been applied
    (op_set.js:20-27)."""
    if state.clock.get(change.actor, 0) < change.seq - 1:
        return False
    for actor, seq in change.deps.items():
        if actor != change.actor and state.clock.get(actor, 0) < seq:
            return False
    return True


def transitive_deps(state, base_deps: dict[str, int]) -> dict[str, int]:
    """Expand a dependency frontier into a full vector clock (op_set.js:29-37).

    Unknown (actor, seq) entries — possible when computing missing changes
    against a peer that is ahead of us — contribute only themselves.
    """
    out: dict[str, int] = {}
    for actor, seq in base_deps.items():
        if seq <= 0:
            continue
        entries = state.states.get(actor)
        if entries is not None and seq - 1 < len(entries):
            for dep_actor, dep_seq in entries[seq - 1][1].items():
                if dep_seq > out.get(dep_actor, 0):
                    out[dep_actor] = dep_seq
        out[actor] = seq
    return out


# ---------------------------------------------------------------------------
# Paths and RGA traversal (op_set.js:43-60, 343-397)
#
# Ghost spots (the move plane, core/moves.py): a moved-away list element
# leaves its original `ins` in the insertion tree as an invisible GHOST —
# elements anchored at it keep their positions (the anchor relation is an
# ordering artifact, not containment), while the element itself is placed
# by its winning move op. A sibling op that causally KNOWS some move of
# its anchor (`anchored_at_placed`) follows the anchor's placed spot
# instead — that predicate is decidable at the sibling's admission
# (causal delivery: any move it covers has already arrived) and never
# flips afterwards, so positions are stable and delivery-order-free.
# Traversal walks spot-qualified ids: `eid` is the element's placed spot,
# `eid + GHOST_SUFFIX` its ghost. Ghost ids never appear in elem_ids,
# diffs, or on the wire.

GHOST_SUFFIX = "\x00g"


def is_ghost(key: str) -> bool:
    return key.endswith(GHOST_SUFFIX)


def strip_ghost(key: str) -> str:
    return key[:-len(GHOST_SUFFIX)] if key.endswith(GHOST_SUFFIX) else key


def moved_away(obj, eid: str) -> bool:
    """True when `eid`'s effective placement is a move op (its original
    ins spot is a ghost)."""
    if not obj.moves or eid not in obj.moves:
        return False
    placed = obj.insertion.get(eid)
    return placed is not None and placed.action == "move"


def anchored_at_placed(state, obj, sib_op, anchor_eid: str) -> bool:
    """True when sibling op `sib_op` (ins or move) anchored at
    `anchor_eid` follows the anchor's PLACED spot: it causally covers at
    least one move of the anchor. Stable from the op's admission on."""
    entry = obj.moves.get(anchor_eid)
    if entry is None:
        return False
    actor, seq = sib_op.actor, sib_op.seq
    if not actor or not seq:
        return True  # local unstamped op: sees the current placement
    clock = None
    for a, q in entry.stamps.items():
        if a == actor:
            if seq > q:
                return True
            continue
        if clock is None:
            clock = state.states[actor][seq - 1][1]
        if clock.get(a, 0) >= q:
            return True
    return False


def spot_of(state, obj, anchor_key: str, via_op) -> str:
    """Spot-qualified id of `via_op`'s anchor: the placed spot when the
    op causally follows the anchor's relocation, else the ghost spot."""
    if anchor_key == HEAD or not moved_away(obj, anchor_key):
        return anchor_key
    if anchored_at_placed(state, obj, via_op, anchor_key):
        return anchor_key
    return anchor_key + GHOST_SUFFIX

def get_path(state, object_id: str) -> list | None:
    """Path from the root to `object_id` (string keys for maps, integer
    indexes for lists), or None if unreachable (op_set.js:43-60)."""
    path: list = []
    while object_id != ROOT_ID:
        obj = state.by_object.get(object_id)
        if obj is None or not obj.inbound:
            return None
        # a move-targeted object's position is its RESOLVED location
        # (core/moves.py); everything else keeps first-inbound semantics
        ref = obj.loc if obj.loc is not None else next(iter(obj.inbound))
        object_id = ref.obj
        parent = state.by_object[object_id]
        if parent.is_sequence:
            index = parent.elem_ids.index_of(ref.key)
            if index < 0:
                return None
            path.insert(0, index)
        else:
            path.insert(0, ref.key)
    return path


def get_parent(state, object_id: str, key: str) -> str | None:
    """Spot-qualified anchor after which `key` sits, or None for the head
    (op_set.js:336-341). A ghost spot's anchor comes from the element's
    original ins; a placed spot's from its effective placement op."""
    if key == HEAD:
        return None
    obj = state.by_object[object_id]
    if is_ghost(key):
        entry = obj.moves.get(strip_ghost(key))
        if entry is None:
            raise TypeError(f"Missing move entry for ghost {key!r}")
        op = entry.base
    else:
        op = obj.insertion.get(key)
        if op is None:
            raise TypeError(f"Missing index entry for list element {key}")
    if op.key == HEAD:
        return HEAD
    return spot_of(state, obj, op.key, op)


def insertions_after(state, object_id: str, parent_id: str,
                     child_id: str | None = None) -> list[str]:
    """Element IDs inserted directly after `parent_id`, in Lamport-descending
    (elem, actor) order; if `child_id` is given, only those ordered before it
    (op_set.js:351-362)."""
    obj = state.by_object[object_id]
    anchor = strip_ghost(parent_id) if parent_id else parent_id
    ops = [op for op in obj.following.get(anchor, ())
           if op.action == "ins" or op.action == "move"]
    if parent_id and obj.moves and moved_away(obj, anchor):
        # the anchor element has a ghost and a placed spot: each sibling
        # op belongs to exactly one of them (anchored_at_placed is stable
        # from its admission, so this split never flips)
        want_placed = not is_ghost(parent_id)
        ops = [op for op in ops
               if anchored_at_placed(state, obj, op, anchor) == want_placed]
    if child_id:
        # a moved child bound compares by its PLACEMENT op's stamp, not
        # by the stamp embedded in its id; a ghost bound by its ins
        cid = strip_ghost(child_id)
        placed = (obj.moves[cid].base if is_ghost(child_id)
                  else obj.insertion.get(cid))
        if placed is not None and (placed.action == "move"
                                   or is_ghost(child_id)):
            child_elem, child_actor = placed.elem, placed.actor
        else:
            child_actor, child_elem = parse_elem_id(cid)
        ops = [op for op in ops
               if (op.elem, op.actor) < (child_elem, child_actor)]
    ops.sort(key=lambda op: (op.elem, op.actor), reverse=True)
    out = []
    for op in ops:
        if op.action == "move":
            out.append(op.value)          # the element at its placed spot
        else:
            eid = make_elem_id(op.actor, op.elem)
            out.append(eid + GHOST_SUFFIX if moved_away(obj, eid) else eid)
    return out


def get_next(state, object_id: str, key: str) -> str | None:
    """Successor of `key` in RGA document order (op_set.js:364-376)."""
    children = insertions_after(state, object_id, key)
    if children:
        return children[0]
    while True:
        ancestor = get_parent(state, object_id, key)
        if ancestor is None:
            return None
        siblings = insertions_after(state, object_id, ancestor, key)
        if siblings:
            return siblings[0]
        key = ancestor


def get_previous(state, object_id: str, key: str) -> str | None:
    """Predecessor of `key` in RGA document order, or None at the head
    (op_set.js:380-397)."""
    parent_id = get_parent(state, object_id, key)
    children = insertions_after(state, object_id, parent_id if parent_id is not None else HEAD)
    if children and children[0] == key:
        return None if (parent_id is None or parent_id == HEAD) else parent_id

    prev_id = None
    for child in children:
        if child == key:
            break
        prev_id = child
    while True:
        children = insertions_after(state, object_id, prev_id)
        if not children:
            return prev_id
        prev_id = children[-1]


def iter_list_elem_ids(state, object_id: str) -> Iterator[str]:
    """All element IDs of a list/text object in RGA document order (including
    deleted ones). Iterative preorder walk of the insertion tree — sequential
    text insertions form a chain as deep as the document, so recursion is not
    an option (the columnar engine linearizes the same tree with a sort-based
    kernel instead, see engine/kernels.py)."""
    stack = [iter(insertions_after(state, object_id, HEAD))]
    while stack:
        nxt = next(stack[-1], None)
        if nxt is None:
            stack.pop()
            continue
        yield nxt
        stack.append(iter(insertions_after(state, object_id, nxt)))


# ---------------------------------------------------------------------------
# Op application (op_set.js:63-252)

def _type_of(obj: ObjState) -> str:
    if obj.init_action == "makeText":
        return "text"
    if obj.init_action == "makeList":
        return "list"
    return "map"


def _conflict_records(ops: tuple[Op, ...]) -> list[dict]:
    """Conflict (loser) records for a multi-op field (op_set.js:95-103)."""
    out = []
    for op in ops[1:]:
        record: dict[str, Any] = {"actor": op.actor, "value": op.value}
        if op.action in ("link", "move"):
            record["link"] = True  # a map move's value IS a child object id
        out.append(record)
    return out


def apply_make(b: Builder, op: Op) -> list[dict]:
    object_id = op.obj
    if object_id in b.by_object:
        raise ValueError(f"Duplicate creation of object {object_id}")
    obj = ObjState(op.action)
    b.by_object[object_id] = obj
    b._touched.add(object_id)
    b._elem_copied.add(object_id)
    return [{"action": "create", "type": _type_of(obj), "obj": object_id}]


def apply_insert(b: Builder, op: Op) -> list[dict]:
    object_id = op.obj
    elem_id = make_elem_id(op.actor, op.elem)
    if object_id not in b.by_object:
        raise ValueError(f"Modification of unknown object {object_id}")
    obj = b.obj(object_id)
    if elem_id in obj.insertion:
        raise ValueError(f"Duplicate list element ID {elem_id}")
    obj.following[op.key] = obj.following.get(op.key, ()) + (op,)
    obj.max_elem = max(op.elem, obj.max_elem)
    obj.insertion[elem_id] = op
    if obj.moves:
        entry = obj.moves.get(op.key)
        if entry is not None and anchored_at_placed(b, obj, op, op.key):
            # this insert tracks the anchor's placement: a future winner
            # change must reposition it too (full-index rebuild path)
            if not entry.followers:
                entry = entry.copy()
                entry.followers = True
                obj.moves[op.key] = entry
    return []


def patch_list(b: Builder, object_id: str, index: int, action: str,
               ops: tuple[Op, ...] | None) -> list[dict]:
    obj = b.by_object[object_id]
    first = ops[0] if ops else None
    value = first.value if first is not None else None
    edit: dict[str, Any] = {"action": action, "type": _type_of(obj),
                            "obj": object_id, "index": index,
                            "path": get_path(b, object_id)}
    if first is not None and first.action == "link":
        edit["link"] = True
        value = Link(first.value)

    elem_ids = b.elem_ids_mut(object_id)
    if action == "insert":
        elem_ids.insert_index(index, first.key, value)
        edit["value"] = first.value
    elif action == "set":
        elem_ids.set_value(first.key, value)
        edit["value"] = first.value
    elif action == "remove":
        elem_ids.remove_index(index)
    else:
        raise ValueError(f"Unknown action type: {action}")

    if ops is not None and len(ops) > 1:
        edit["conflicts"] = _conflict_records(ops)
    return [edit]


def update_list_element(b: Builder, object_id: str, elem_id: str) -> list[dict]:
    obj = b.by_object[object_id]
    ops = obj.fields.get(elem_id, ())
    index = obj.elem_ids.index_of(elem_id)

    if index >= 0:
        if not ops:
            return patch_list(b, object_id, index, "remove", None)
        return patch_list(b, object_id, index, "set", ops)

    if not ops:
        return []  # deleting a non-existent element is a no-op

    # Find the closest visible predecessor element (op_set.js:146-156).
    prev_id = elem_id
    while True:
        index = -1
        prev_id = get_previous(b, object_id, prev_id)
        if prev_id is None:
            break
        index = obj.elem_ids.index_of(prev_id)
        if index >= 0:
            break
    return patch_list(b, object_id, index + 1, "insert", ops)


def update_map_key(b: Builder, object_id: str, key: str) -> list[dict]:
    ops = b.by_object[object_id].fields.get(key, ())
    edit: dict[str, Any] = {"action": "", "type": "map", "obj": object_id,
                            "key": key, "path": get_path(b, object_id)}
    if not ops:
        edit["action"] = "remove"
    else:
        edit["action"] = "set"
        edit["value"] = ops[0].value
        if ops[0].action in ("link", "move"):
            edit["link"] = True
        if len(ops) > 1:
            edit["conflicts"] = _conflict_records(ops)
    return [edit]


def apply_assign(b: Builder, op: Op, emit: bool = True) -> list[dict]:
    object_id = op.obj
    if object_id not in b.by_object:
        raise ValueError(f"Modification of unknown object {object_id}")
    obj = b.obj(object_id)

    prior = obj.fields.get(op.key, ())
    overwritten, remaining = [], []
    for prior_op in prior:
        (remaining if is_concurrent(b, prior_op, op) else overwritten).append(prior_op)

    # Overwritten links disappear from the target's inbound index.
    for dead in overwritten:
        if dead.action == "link":
            target = b.obj(dead.value)
            target.inbound.pop(dead, None)

    if op.action == "link":
        if op.value not in b.by_object:
            raise ValueError(f"Link to unknown object {op.value}")
        b.obj(op.value).inbound[op] = None
    if op.action != "del":
        remaining.append(op)

    # Survivors sorted by actor descending: the highest actor wins LWW
    # (op_set.js:201; winner read at op_set.js:425).
    remaining.sort(key=lambda o: o.actor or "", reverse=True)
    obj.fields[op.key] = tuple(remaining)

    # single-location rule for move-managed children (core/moves.py): a
    # link to a child whose position is move-resolved registers as a
    # potential base edge (inbound) but must not ALSO present the child
    # beside its effective location
    if op.action == "link" and op.value in b.moved_objs:
        child = b.by_object[op.value]
        if child.loc is not None and child.loc is not op:
            obj.fields[op.key] = tuple(
                o for o in obj.fields[op.key] if o is not op)

    if not emit:
        # No-diff mode (from-scratch loads): edit records have no consumer
        # and elem_ids maintenance — the per-op O(sqrt n) index work — is
        # deferred to one rebuild_elem_ids pass at end of batch. The
        # reference cannot skip this (its frontends are diff-driven,
        # op_set.js:105-129); ours materializes from state.
        if obj.is_sequence:
            b._deferred_seqs.add(object_id)
        return _NO_DIFFS
    if obj.is_sequence:
        return update_list_element(b, object_id, op.key)
    return update_map_key(b, object_id, op.key)


# immutable empty sentinel: returned (never mutated) by the no-diff
# apply paths so emit=False costs zero allocations per op
_NO_DIFFS: tuple = ()


def _queue_gauges(b: "Builder") -> None:
    """Causal-queue gauges after a batch (THE one definition — every
    add_changes exit path reports them): a growing depth means peers are
    delivering out of causal order (or a dep will never arrive); bytes
    are a coarse per-change host-object estimate (header + per-op
    records — exact sizeof walks would cost more than the queue is
    worth)."""
    metrics.gauge("core_queue_depth", len(b.queue))
    metrics.gauge("core_queue_bytes",
                  sum(120 + 80 * len(c.ops) for c in b.queue))


def rebuild_elem_ids(obj: "ObjState", actor_rank: dict | None = None,
                     state=None) -> None:
    """Rebuild a sequence object's visible-element index from its insertion
    tree in one pass: native RGA linearization over every insertion (the
    same algorithm the incremental path applies per-op), then a bulk
    ElemList build of the visible elements (those with surviving field
    ops), winner value first. Shared by the bulk loader (core/bulkload.py
    step 7) and the no-diff interpretive load (add_changes(emit_diffs=
    False)); O(n) total instead of O(ops * sqrt n) incremental upkeep."""
    import numpy as np

    from ..native.linearize import linearize_host

    # iterate (eid, op) pairs: a moved element's effective op carries the
    # MOVE stamp for ordering while the dict key keeps its identity
    ins_items = list(obj.insertion.items())
    n = len(ins_items)
    if n == 0:
        obj.elem_ids = ElemList()
        return
    if obj.moves:
        # moved lists have ghost/placed spot splits the native linearizer
        # cannot see (and can violate its parent.elem < child.elem
        # invariant): rebuild by walking the insertion tree in document
        # order instead — same O(n log n), no invariant needed. The walk
        # needs the states table for the anchored_at_placed predicate.
        if state is None:
            raise ValueError("rebuilding a moved list requires state")
        _rebuild_by_walk(obj, state)
        return
    if actor_rank is None:
        # ranks need only be order-isomorphic to the actor strings for
        # sibling comparisons within this object
        actor_rank = {a: r for r, a in enumerate(
            sorted({op.actor for _eid, op in ins_items}))}
    slot_of = {eid: s for s, (eid, _op) in enumerate(ins_items)}
    elem = np.fromiter((op.elem for _e, op in ins_items), np.int32, n)
    arank = np.fromiter((actor_rank[op.actor] for _e, op in ins_items),
                        np.int32, n)
    parent = np.fromiter(
        ((-1 if op.key == HEAD else slot_of[op.key])
         for _e, op in ins_items),
        np.int32, n)
    pos = linearize_host(np.ones(n, bool), elem, arank, parent)
    keys_v, values_v = [], []
    fields_get = obj.fields.get
    for s in np.argsort(pos, kind="stable").tolist():
        eid = ins_items[s][0]
        fops = fields_get(eid)
        if not fops:
            continue
        first = fops[0]
        keys_v.append(eid)
        values_v.append(Link(first.value) if first.action == "link"
                        else first.value)
    obj.elem_ids = ElemList(keys_v, values_v)


def _rebuild_by_walk(obj: "ObjState", state) -> None:
    """Visible-index rebuild by insertion-tree walk (move-aware twin of
    the linearize_host path above). Ghost spots yield no entry — their
    ids are not fields keys — but their subtrees are walked through."""
    keys_v, values_v = [], []
    fields_get = obj.fields.get
    for eid in iter_list_elem_ids(_ObjView(obj, state), "_"):
        fops = fields_get(eid)
        if not fops:
            continue
        first = fops[0]
        keys_v.append(eid)
        values_v.append(Link(first.value) if first.action == "link"
                        else first.value)
    obj.elem_ids = ElemList(keys_v, values_v)


class _ObjView:
    """Minimal state adapter so the RGA traversal helpers accept a bare
    ObjState (rebuilds run outside any Builder)."""
    __slots__ = ("by_object", "states")

    def __init__(self, obj, state=None):
        self.by_object = {"_": obj}
        self.states = state.states if state is not None else {}


def apply_op(b: Builder, op: Op, emit: bool = True) -> list[dict]:
    action = op.action
    if action in ("makeMap", "makeList", "makeText"):
        made = apply_make(b, op)
        return made if emit else _NO_DIFFS
    if action == "ins":
        return apply_insert(b, op)
    if action in ("set", "del", "link"):
        return apply_assign(b, op, emit)
    if action == "move":
        from .moves import apply_move
        return apply_move(b, op, emit)
    raise ValueError(f"Unknown operation type {action}")


def admit_change_header(b: Builder, change: Change) -> dict | None:
    """The op-independent half of applying one causally-ready change:
    duplicate-delivery check, transitive-clock computation, states/clock/
    deps/history bookkeeping (op_set.js:224-241, 243-248). Returns the
    change's full vector clock, or None for an idempotent re-delivery.
    Shared by the per-op path below and the batched text-merge plane
    (core/textspans.py), so both admit changes bit-identically."""
    actor, seq = change.actor, change.seq
    prior = b.states.get(actor, EMPTY_ALIST)
    if seq <= len(prior):
        if prior[seq - 1][0] != change:
            raise ValueError(f"Inconsistent reuse of sequence number {seq} by {actor}")
        return None  # idempotent re-delivery

    base = dict(change.deps)
    base[actor] = seq - 1
    all_deps = transitive_deps(b, base)
    b.states[actor] = prior.append((change, all_deps))
    b.deps = {a: s for a, s in b.deps.items() if s > all_deps.get(a, 0)}
    b.deps[actor] = seq
    b.clock[actor] = seq
    b.history = b.history.append(change)
    metrics.bump("core_changes_applied")
    metrics.bump("core_ops_applied", len(change.ops))
    # op-lifecycle plane: a change that sat causally-unready in the
    # queue records its dependency-wait here (no-op unless it was parked
    # — one unlocked empty-table check on the common path)
    oplag.queue_admitted(actor, seq)
    return all_deps


def apply_change(b: Builder, change: Change, emit: bool = True) -> list[dict]:
    """Apply one causally-ready change (op_set.js:224-252)."""
    actor, seq = change.actor, change.seq
    # ops apply against the PRE-admission states view only through the
    # stamped clocks, which admit_change_header has already appended —
    # exactly the order the reference applies them in (op_set.js:224-241)
    if admit_change_header(b, change) is None:
        return []  # idempotent re-delivery

    diffs: list[dict] = []
    for op in change.ops:
        d = apply_op(b, op.stamped(actor, seq), emit)
        if d:
            diffs.extend(d)
    metrics.bump("core_diffs_emitted", len(diffs))
    return diffs


def apply_queued_ops(b: Builder, emit: bool = True) -> list[dict]:
    """Fixpoint drain of the causal queue (op_set.js:254-270)."""
    diffs: list[dict] = []
    while True:
        leftover: list[Change] = []
        progressed = False
        for change in b.queue:
            if causally_ready(b, change):
                diffs.extend(apply_change(b, change, emit))
                progressed = True
            else:
                leftover.append(change)
        b.queue = leftover
        if not progressed or not leftover:
            return diffs


# ---------------------------------------------------------------------------
# Read queries (op_set.js:332-479)

def valid_field_name(key) -> bool:
    return isinstance(key, str) and key != "" and not key.startswith("_")


def get_field_ops(state, object_id: str, key: str) -> tuple[Op, ...]:
    obj = state.by_object.get(object_id)
    if obj is None:
        return ()
    return obj.fields.get(key, ())


def get_object_fields(state, object_id: str) -> list[str]:
    """Present field names of a map object, in field-creation order."""
    obj = state.by_object[object_id]
    return [key for key, ops in obj.fields.items()
            if valid_field_name(key) and ops]


def list_length(state, object_id: str) -> int:
    return len(state.by_object[object_id].elem_ids)


# ---------------------------------------------------------------------------
# The persistent OpSet

class OpSet:
    """Immutable CRDT state for one document (op_set.js:272-285).

    undo_pos / undo_stack / redo_stack live here (as in the reference) but are
    maintained by the change-assembly layer (automerge_tpu/api.py),
    mirroring auto_api.js:41-111.
    """

    __slots__ = ("states", "by_object", "clock", "deps", "queue", "history",
                 "moved_objs", "undo_pos", "undo_stack", "redo_stack")

    def __init__(self, states, by_object, clock, deps, queue, history,
                 undo_pos=0, undo_stack=(), redo_stack=(),
                 moved_objs=frozenset()):
        self.states = states          # actor -> AList[(Change, all_deps)]
        self.by_object = by_object    # objectId -> ObjState
        self.clock = clock            # actor -> seq
        self.deps = deps              # pruned dependency frontier
        self.queue = queue            # tuple of causally-unready changes
        self.history = history        # AList[Change], application order
        self.moved_objs = moved_objs  # map-realm children with move cands
        self.undo_pos = undo_pos
        self.undo_stack = undo_stack  # tuple of tuples of undo Ops
        self.redo_stack = redo_stack

    @staticmethod
    def init() -> "OpSet":
        return OpSet(states={}, by_object={ROOT_ID: ObjState("makeMap")},
                     clock={}, deps={}, queue=(), history=EMPTY_ALIST)

    def thaw(self) -> Builder:
        return Builder(self)

    def freeze(self, b: Builder, undo_pos=None, undo_stack=None,
               redo_stack=None) -> "OpSet":
        return OpSet(states=b.states, by_object=b.by_object, clock=b.clock,
                     deps=b.deps, queue=tuple(b.queue), history=b.history,
                     moved_objs=frozenset(b.moved_objs),
                     undo_pos=self.undo_pos if undo_pos is None else undo_pos,
                     undo_stack=self.undo_stack if undo_stack is None else undo_stack,
                     redo_stack=self.redo_stack if redo_stack is None else redo_stack)

    def replace_undo(self, undo_pos=None, undo_stack=None, redo_stack=None) -> "OpSet":
        return OpSet(states=self.states, by_object=self.by_object,
                     clock=self.clock, deps=self.deps, queue=self.queue,
                     history=self.history, moved_objs=self.moved_objs,
                     undo_pos=self.undo_pos if undo_pos is None else undo_pos,
                     undo_stack=self.undo_stack if undo_stack is None else undo_stack,
                     redo_stack=self.redo_stack if redo_stack is None else redo_stack)

    # -- change ingestion ---------------------------------------------------

    def add_change(self, change: Change) -> tuple["OpSet", list[dict]]:
        return self.add_changes([change])

    def add_changes(self, changes, emit_diffs: bool = True,
                    text_batch: bool = False,
                    move_batch: bool = False) -> tuple["OpSet", list[dict]]:
        """Queue + causally apply a batch of changes (op_set.js:294-297).

        emit_diffs=False is the from-scratch-load fast path: no edit
        records are produced (returns an empty diff list) and sequence
        index maintenance is deferred to ONE rebuild per touched list at
        the end of the batch. State is bit-identical to the emitting path
        — pinned by tests/test_nodiff_apply.py.

        text_batch=True offers the batch to the span-granularity text
        merge plane (core/textspans.py) first: a large all-text batch is
        admitted with visible-order maintenance at SPAN granularity (one
        placement + splice per contiguous run instead of per op) and
        returns ONE coarse diff per touched object ({"action": "batch"})
        instead of per-op edits — callers that fold diffs per object
        (frontend/materialize.update_cache) are unaffected; callers that
        need per-op edit records must not opt in. State is bit-identical
        to the per-op path (tests/test_textspans.py)."""
        if text_batch and emit_diffs and not self.queue:
            from .textspans import TEXT_BATCH_MIN_OPS, try_apply_text_batch
            changes = list(changes)
            # pre-thaw gate: a below-threshold batch (every interactive
            # keystroke takes this path) must not pay a Builder
            # construction just to be rejected by the scan
            if sum(len(c.ops) for c in changes
                   if isinstance(c, Change)) >= TEXT_BATCH_MIN_OPS:
                b = self.thaw()
                batch_diffs = try_apply_text_batch(b, changes)
                if batch_diffs is not None:
                    _queue_gauges(b)
                    return self.freeze(b), batch_diffs
                # ineligible: fall through on a FRESH builder (the scan
                # phase mutates nothing, but a clean thaw keeps that
                # contract local)
        if move_batch and emit_diffs and not self.queue:
            # the move twin of the text plane: an all-move batch admits
            # with ONE winner+cycle resolution per touched realm
            # (core/moves.py), kernel-routed above the size threshold
            from .moves import MOVE_BATCH_MIN_OPS, try_apply_move_batch
            changes = list(changes)
            if sum(len(c.ops) for c in changes
                   if isinstance(c, Change)) >= MOVE_BATCH_MIN_OPS:
                b = self.thaw()
                batch_diffs = try_apply_move_batch(b, changes)
                if batch_diffs is not None:
                    _queue_gauges(b)
                    return self.freeze(b), batch_diffs
        b = self.thaw()
        diffs: list[dict] = []
        for change in changes:
            b.queue.append(change)
            d = apply_queued_ops(b, emit_diffs)
            if d:
                diffs.extend(d)
        if b._deferred_seqs:
            for oid in b._deferred_seqs:
                obj = b.by_object.get(oid)
                if obj is not None:
                    rebuild_elem_ids(obj, state=b)
            b._deferred_seqs.clear()
        _queue_gauges(b)
        # op-lifecycle plane: mark when parking began (one locked batch
        # call; 1/N hash-sampled inside)
        if b.queue:
            oplag.queue_park_batch([(c.actor, c.seq) for c in b.queue])
        return self.freeze(b), diffs

    # -- change-graph queries (op_set.js:299-330) ---------------------------

    def get_missing_changes(self, have_deps: dict[str, int]) -> list[Change]:
        all_deps = transitive_deps(self, have_deps)
        out: list[Change] = []
        for actor, entries in self.states.items():
            skip = all_deps.get(actor, 0)
            for i in range(skip, len(entries)):
                out.append(entries[i][0])
        return out

    def get_changes_for_actor(self, for_actor: str, after_seq: int = 0) -> list[Change]:
        entries = self.states.get(for_actor, EMPTY_ALIST)
        return [entries[i][0] for i in range(after_seq, len(entries))]

    def get_missing_deps(self) -> dict[str, int]:
        missing: dict[str, int] = {}
        for change in self.queue:
            deps = dict(change.deps)
            deps[change.actor] = change.seq - 1
            for actor, seq in deps.items():
                if self.clock.get(actor, 0) < seq:
                    missing[actor] = max(seq, missing.get(actor, 0))
        return missing
