"""Batched text merging at span granularity (the eg-walker shape).

The generic ingestion path (core/opset.py) applies every op of every
incoming change through the per-op RGA machinery: each insert pays an
index-resolution walk plus an O(CHUNK + chunks) element-index update, and
each op emits a diff record — so merging a remote history into a long text
costs per-op work in the *document*, not in the *divergence*. Eg-walker
("Collaborative Text Editing with Eg-walker: Better, Faster, Smaller",
arxiv 2409.14252) shows the winning shape for collaborative text: replay
on merge over the causal graph, touch only the spans that are actually
concurrent, and keep the working state run-length encoded.

This module is that shape for our OpSet. For an eligible batch (all ops
are ins/set/del on existing makeText objects, causally ready in order,
nothing queued):

- **Region split.** Each change is classified against the local causal
  frontier at its admission point: a *sequential* change (its transitive
  clock covers the frontier — a single writer streaming, or a peer that
  is strictly ahead) skips every per-pair concurrency check outright:
  all prior field ops are causally dominated by construction. Only
  *concurrent* changes replay through `is_concurrent`.

- **RLE span splices.** Consecutive inserts that chain (each op's parent
  is the previous op's element — the typing/paste shape) are segmented
  into runs at admission time. The visible-order index is then updated
  with ONE placement walk and ONE `ElemList.splice_insert` per run
  instead of per op, so order maintenance costs O(spans), not O(ops).

- **Placement invariant.** A run splices immediately after its closest
  *already-placed* document-order predecessor (a `get_previous` walk that
  skips tombstones and not-yet-placed batch elements). Because every run
  placed later inserts after *its own* closest placed predecessor, placed
  elements are always in correct relative document order regardless of
  placement sequence — the property tests/test_textspans.py pins against
  per-op replay under hypothesis.

The CRDT tables themselves (fields / following / insertion / clocks /
history) are maintained bit-identically to the per-op path — the batch
plane only changes *how the visible-order index is maintained* and *what
diff records are emitted* (one coarse ``{"action": "batch"}`` record per
touched object; frontend/materialize.update_cache folds per object, so
the materialization is unaffected). Callers that need per-op edit records
must not opt in (`OpSet.add_changes(text_batch=...)`).

The device-side twin of this plane — span tables packed into the
``[ROWS, k_pad]`` lane layout with a batched merge-order kernel — lives
in engine/span_kernels.py.
"""

from __future__ import annotations

from ..utils import metrics, perfscope
from .change import Change
from .elems import CHUNK
from .ids import HEAD, make_elem_id
from .opset import (Builder, Link, admit_change_header, get_path,
                    get_previous, is_concurrent)

# Below this many ops the per-op path's constants win (and small batches
# are what interactive editing sends — they keep their per-op diff
# records). Tests override this to force the span plane on tiny batches.
TEXT_BATCH_MIN_OPS = 48

_TEXT_ACTIONS = frozenset(("ins", "set", "del"))


class _ObjBatch:
    """Per-object working state of one batched apply."""

    __slots__ = ("obj", "runs", "run_of", "last_ins", "dirty", "new")

    def __init__(self, obj, batch_ops: int = 0):
        self.obj = obj
        self.runs: list[list[str]] = []   # contiguous new-element runs
        self.run_of: dict[str, int] = {}  # new elem id -> run index
        self.last_ins: str | None = None  # chain-extension anchor
        self.dirty: set = set()           # assigned PRE-batch elem keys
        self.new: set = set()             # elem ids inserted this batch
        # Big batches fork the object's CRDT-table CowDicts up front
        # (fields/following/insertion write per op): one O(n) base fork
        # beats per-op persistent-overlay updates — same crossover
        # reasoning as ElemList.own_kmap in _place_object below.
        if batch_ops > max(1024, len(obj.fields) // 256):
            for table in (obj.fields, obj.following, obj.insertion):
                rebase = getattr(table, "rebase", None)
                if rebase is not None:
                    rebase()


def _scan(b: Builder, changes: list) -> dict | None:
    """Pre-mutation eligibility check: every change must be causally ready
    in sequence, duplicate-free, and composed purely of ins/set/del ops on
    existing makeText objects with resolvable parents/targets. Returns the
    per-object op counts when eligible (they size the copy-on-write
    ownership decision per object); anything else returns None and the
    generic path keeps its exact semantics (queueing, idempotent drops,
    error surfaces)."""
    total_ops = 0
    obj_ops: dict[str, int] = {}
    clock = dict(b.clock)
    known: dict[str, object] = {}
    new_elems: dict[str, set] = {}
    for change in changes:
        if not isinstance(change, Change):
            return None
        actor, seq = change.actor, change.seq
        if seq != clock.get(actor, 0) + 1:
            return None  # duplicate or gap: generic semantics own those
        for a, s in change.deps.items():
            if a != actor and clock.get(a, 0) < s:
                return None  # not causally ready in batch order
        for op in change.ops:
            if op.action not in _TEXT_ACTIONS:
                return None
            oid = op.obj
            obj = known.get(oid)
            if obj is None:
                obj = b.by_object.get(oid)
                if obj is None or obj.init_action != "makeText":
                    return None
                known[oid] = obj
                new_elems[oid] = set()
            new = new_elems[oid]
            if op.action == "ins":
                if op.elem is None or op.key is None:
                    return None
                eid = f"{actor}:{op.elem}"
                if eid in new or eid in obj.insertion:
                    return None  # duplicate elem id: per-op error path
                if (op.key != HEAD and op.key not in new
                        and op.key not in obj.insertion):
                    return None  # unknown parent element
                new.add(eid)
            else:
                key = op.key
                if (not isinstance(key, str)
                        or (key not in new and key not in obj.insertion)):
                    return None  # unknown element: per-op error path
            total_ops += 1
            obj_ops[oid] = obj_ops.get(oid, 0) + 1
        clock[actor] = seq
    return obj_ops if total_ops >= TEXT_BATCH_MIN_OPS else None


def _admit_ins(ob: _ObjBatch, op) -> None:
    """apply_insert's table maintenance + run segmentation. An insert
    extends the current run iff its parent is the immediately previously
    admitted element — no other sibling can have been admitted between
    two consecutive ops, so the chain is contiguous in document order at
    placement time (later runs splice INTO earlier blocks)."""
    obj = ob.obj
    eid = make_elem_id(op.actor, op.elem)
    obj.following[op.key] = obj.following.get(op.key, ()) + (op,)
    if op.elem > obj.max_elem:
        obj.max_elem = op.elem
    obj.insertion[eid] = op
    if ob.last_ins is not None and op.key == ob.last_ins:
        r = ob.run_of[ob.last_ins]
        ob.runs[r].append(eid)
    else:
        r = len(ob.runs)
        ob.runs.append([eid])
    ob.run_of[eid] = r
    ob.last_ins = eid
    ob.new.add(eid)


def _admit_assign(b: Builder, ob: _ObjBatch, op, sequential: bool) -> None:
    """apply_assign's survivor analysis without diff emission or per-op
    index maintenance. A sequential change causally knows every prior op
    on the field, so the whole per-pair `is_concurrent` join collapses to
    'everything prior is overwritten'."""
    obj = ob.obj
    key = op.key
    prior = obj.fields.get(key, ())
    if sequential or not prior:
        for prior_op in prior:
            if prior_op.action == "link":
                b.obj(prior_op.value).inbound.pop(prior_op, None)
        remaining = () if op.action == "del" else (op,)
    else:
        overwritten, rem = [], []
        for prior_op in prior:
            (rem if is_concurrent(b, prior_op, op)
             else overwritten).append(prior_op)
        for dead in overwritten:
            if dead.action == "link":
                b.obj(dead.value).inbound.pop(dead, None)
        if op.action != "del":
            rem.append(op)
        rem.sort(key=lambda o: o.actor or "", reverse=True)
        remaining = tuple(rem)
    obj.fields[key] = remaining
    if key not in ob.new:
        ob.dirty.add(key)


def _winner_value(fops):
    first = fops[0]
    return Link(first.value) if first.action == "link" else first.value


def _placed_predecessor_index(b: Builder, oid: str, elems, eid: str) -> int:
    """Visible index of the closest document-order predecessor of `eid`
    that is already in the element index (skipping tombstones and
    not-yet-placed batch elements), or -1 at the head."""
    prev = get_previous(b, oid, eid)
    while prev is not None:
        idx = elems.index_of(prev)
        if idx >= 0:
            return idx
        prev = get_previous(b, oid, prev)
    return -1


def _place_object(b: Builder, oid: str, ob: _ObjBatch) -> int:
    """Fold one object's batch into its visible-order index: one splice
    per run, then the dirty (pre-batch) keys — value rewrites, removals,
    and resurrections (a concurrent set outliving a delete). Returns the
    number of spans spliced."""
    fields_get = ob.obj.fields.get
    elems = b.elem_ids_mut(oid)
    # Key-map mode choice: every splice writes k + min-half-of-a-chunk
    # keys and every removal one, each a persistent-overlay update on a
    # copied index (~20us) — a big merge is better off forking the key
    # map's base dict ONCE (~0.05us/key) and writing at dict speed. The
    # crossover on the measuring host is ~n/400 writes; n//256 with a
    # 1024 floor keeps small interactive batches off the O(n) fork.
    est_writes = (len(ob.new) + (CHUNK // 2) * len(ob.runs)
                  + len(ob.dirty))
    if est_writes > max(1024, len(elems) // 256):
        elems.own_kmap()
    spans = 0
    for run in ob.runs:
        vis_keys: list[str] = []
        vis_vals: list = []
        for eid in run:
            fops = fields_get(eid)
            if fops:
                vis_keys.append(eid)
                vis_vals.append(_winner_value(fops))
        if not vis_keys:
            continue  # inserted and deleted within the batch: tombstones
        at = _placed_predecessor_index(b, oid, elems, run[0]) + 1
        elems.splice_insert(at, vis_keys, vis_vals)
        spans += 1
    for key in ob.dirty:
        fops = fields_get(key)
        idx = elems.index_of(key)
        if fops:
            val = _winner_value(fops)
            if idx >= 0:
                elems.set_value(key, val)
            else:
                # resurrection: place like a single-element run
                at = _placed_predecessor_index(b, oid, elems, key) + 1
                elems.insert_index(at, key, val)
                spans += 1
        elif idx >= 0:
            elems.remove_index(idx)
    return spans


def try_apply_text_batch(b: Builder, changes: list) -> list[dict] | None:
    """Admit a batch of changes through the span plane. Returns one coarse
    diff per touched object, or None when the batch needs the generic
    per-op path (the scan phase mutates nothing, so falling back is
    always safe)."""
    obj_ops = _scan(b, changes)
    if obj_ops is None:
        return None

    per_obj: dict[str, _ObjBatch] = {}
    seq_ops = conc_ops = 0
    for change in changes:
        prev_frontier = b.deps  # admit_change_header rebinds, not mutates
        all_deps = admit_change_header(b, change)
        # _scan rejected duplicates, so all_deps is never None here
        sequential = True
        for a, s in prev_frontier.items():
            if all_deps.get(a, 0) < s:
                sequential = False
                break
        actor, seq = change.actor, change.seq
        for op in change.ops:
            stamped = op.stamped(actor, seq)
            ob = per_obj.get(stamped.obj)
            if ob is None:
                ob = per_obj[stamped.obj] = _ObjBatch(
                    b.obj(stamped.obj), obj_ops[stamped.obj])
            if stamped.action == "ins":
                _admit_ins(ob, stamped)
            else:
                _admit_assign(b, ob, stamped, sequential)
        if sequential:
            seq_ops += len(change.ops)
        else:
            conc_ops += len(change.ops)

    diffs: list[dict] = []
    spans = 0
    with perfscope.phase("span_merge"):
        for oid, ob in per_obj.items():
            spans += _place_object(b, oid, ob)
            diffs.append({"action": "batch", "type": "text", "obj": oid,
                          "path": get_path(b, oid)})

    metrics.bump("sync_text_batches_merged")
    metrics.bump("sync_text_spans_spliced", spans)
    if seq_ops:
        metrics.bump("sync_text_ops_sequential", seq_ops)
    if conc_ops:
        metrics.bump("sync_text_ops_concurrent", conc_ops)
    return diffs


# ---------------------------------------------------------------------------
# RLE span extraction (the engine wire shape)

def merge_table(base_spans, blocks) -> list[tuple]:
    """Assemble one document's merge span table — the 7-tuple rows
    engine/pack.pack_spans ships — from its region split.

    `base_spans` is the RLE of the common history in document order,
    ALREADY split at every concurrent anchor gap and deletion boundary:
    (origin, start_id, vis_len) rows, vis_len=0 for a tombstone run (a
    region the merge deletes). `blocks` are the concurrent subtree
    blocks, each (gap, prio_elem, prio_actor, runs): `gap` is the index
    of the base span the block anchors AFTER (-1 for the head gap),
    (prio_elem, prio_actor) the RGA sibling priority of the block's head
    element against the other blocks in the same gap, and `runs` the
    block's RLE spans flattened in side-local document order (one side's
    spans in one gap stay contiguous — they are one insertion subtree).

    The merged document order is exactly
    ``lexsort(slot, -prio_elem, -prio_actor, block_seq)`` over the
    returned rows (engine/span_kernels.merge_spans): the table size is
    O(touched regions + concurrent spans), never O(document)."""
    rows = []
    for i, (origin, start, vis) in enumerate(base_spans):
        rows.append((origin, start, vis, 2 * i, 0, 0, i))
    for (gap, pelem, pactor, runs) in blocks:
        for j, (origin, start, vis) in enumerate(runs):
            rows.append((origin, start, vis, 2 * gap + 1, pelem, pactor, j))
    return rows


def rle_runs(keys):
    """Maximal runs of consecutively-numbered same-origin elem ids, in
    order: yields (actor, start_elem, length, start_index). The ONE
    definition of the run-boundary rule — spans_of_elems and both
    Text.spans() paths consume it, so lazy and eager views cannot
    drift."""
    cur_actor: str | None = None
    cur_start = cur_len = cur_at = 0
    prev_elem = -2
    at = 0
    for key in keys:
        i = key.rindex(":")
        actor, elem = key[:i], int(key[i + 1:])
        if actor == cur_actor and elem == prev_elem + 1:
            cur_len += 1
        else:
            if cur_actor is not None:
                yield cur_actor, cur_start, cur_len, cur_at
            cur_actor, cur_start, cur_len, cur_at = actor, elem, 1, at
        prev_elem = elem
        at += 1
    if cur_actor is not None:
        yield cur_actor, cur_start, cur_len, cur_at


def spans_of_elems(elems, insertion) -> list[tuple[str, int, int]]:
    """Run-length encode a visible element index: maximal runs of
    consecutive (actor, elem) ids in document order compress to
    (actor, start_elem, length) triples — the host form of the span rows
    engine/pack.pack_spans ships to the device, and what Text.spans()
    surfaces to the frontend. `insertion` is accepted for signature parity
    with future tombstone-carrying span tables; visibility is what the
    element index already encodes."""
    return [(a, s, n) for a, s, n, _ in rle_runs(elems.keys)]
