from .ids import ROOT_ID, HEAD, make_elem_id, parse_elem_id
from .change import Op, Change
from .opset import OpSet

__all__ = ["ROOT_ID", "HEAD", "make_elem_id", "parse_elem_id", "Op", "Change", "OpSet"]
