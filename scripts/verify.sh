#!/usr/bin/env bash
# verify.sh — the one command a builder runs before claiming "tier-1 green".
#
# Stage 1: static analysis (fast fail): graftlint runs the registry,
#          jit-hygiene, and lock-discipline passes against the committed
#          analysis_baseline.json (docs/ANALYSIS.md). A new finding — an
#          unregistered metric/span/event name, a host sync or retrace
#          hazard in jit-reachable code, a lock-order inversion or a
#          blocking call under a lock — fails the build regardless of
#          what else passes.
# Stage 2: the tier-1 pytest line EXACTLY as ROADMAP.md specifies it,
#          including the DOTS_PASSED count the driver compares against the
#          seed. Keep this in sync with ROADMAP.md "Tier-1 verify".
#
# Usage: scripts/verify.sh   (or: make verify)
set -u
cd "$(dirname "$0")/.."

echo "== stage 1/2: static analysis (graftlint) =="
JAX_PLATFORMS=cpu python -m automerge_tpu.analysis || exit $?

echo "== stage 2/2: tier-1 suite (ROADMAP.md) =="
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
exit $rc
