#!/usr/bin/env bash
# verify.sh — the one command a builder runs before claiming "tier-1 green".
#
# Stage 1: static analysis (fast fail): graftlint runs the registry,
#          jit-hygiene, lock-discipline, and race passes against the
#          committed analysis_baseline.json (docs/ANALYSIS.md). A new
#          finding — an unregistered metric/span/event name or
#          undocumented AMTPU_* knob, a host sync or retrace hazard in
#          jit-reachable code, a lock-order inversion or a blocking
#          call under a lock, a cross-thread unlocked write or an
#          undeclared lock-free read (the race plane, checked against
#          the committed locks_manifest.json) — fails the build
#          regardless of what else passes.
# Stage 2: perf report (INFORMATIONAL): the bench-history trajectory the
#          regression gate reads, plus the contention & convergence-lag
#          section (per-lock wait/hold, sampled op-lag stages — the
#          baseline ROADMAP #1's ingestion refactor lands against), the
#          perf-doctor post-mortem over the last bench detail (ranked
#          root causes per config — docs/OBSERVABILITY.md "Fleet
#          health"), the per-doc `perf explain` post-mortem beside
#          it (one view set per captured config, incl. config 13's
#          relay-tree run — docs/OBSERVABILITY.md "Partial replication,
#          relay fan-out & shedding"), and the chaos-recovery smoke:
#          one conn_kill injected into a supervised TCP link, recovery
#          (reconnect + reconverge, zero human action) asserted in
#          seconds (docs/OBSERVABILITY.md "Remediation plane"; the
#          full 4-class MTTR proof is bench config 14 under `make
#          perfcheck`), and the bootstrap smoke: a deep-history doc is
#          compacted into a snapshot image and a fresh replica
#          cold-boots from snapshot + archived tail with byte-equal
#          converged hashes (docs/INTERNALS.md "The storage tier";
#          the fleet-scale gate is bench config 15 under `make
#          perfcheck`), and the move smoke: a concurrent cycle storm
#          (A->B + B->A reparents, conflicting list reorders) on two
#          services in both delivery orders, convergence + cycle-drop +
#          host/XLA/pallas resolution parity asserted (docs/INTERNALS.md
#          "The move plane"; the fleet-scale gate is bench config 16
#          under `make perfcheck`), and the dispatch smoke: a short
#          eager-pinned traffic round proves the dispatch-efficiency
#          ledger accounts every routed call (amplification, padding
#          waste, megabatch projection — docs/OBSERVABILITY.md
#          "Dispatch-efficiency ledger"; the fleet-scale gate is bench
#          config 17 under `make perfcheck`), and the tenant smoke: a
#          three-tenant namespaced traffic round proves the tenant
#          attribution plane tracks every tenant's ingress/dispatch
#          shares with the shares summing back to the fleet totals
#          (docs/OBSERVABILITY.md "Tenant attribution plane"; the
#          fleet-scale gate is bench config 18 under `make
#          perfcheck`), and the race smoke: a threaded sync storm run
#          twice — sanitizer off, then under AMTPU_LOCKSAN=1 — with
#          zero lock-order/long-hold violations and sanitizer overhead
#          < 5% asserted (docs/ANALYSIS.md "The runtime lock-order
#          sanitizer"), and the trace smoke: a two-service TCP fleet
#          under forced sampling proves sampled lifecycles complete as
#          stitched cross-process waterfalls with the plane's duty
#          cycle under budget (docs/OBSERVABILITY.md "Trace plane";
#          the fleet-scale gate is bench config 19 under `make
#          perfcheck`). Never fails verify — a CPU-only
#          image or a missing/empty history must not block the build
#          (TUNNEL_DIAGNOSIS.md: TPU absence is an environment fact, not
#          a code defect). Run `make perfcheck` for the enforcing gate.
# Stage 3: the tier-1 pytest line EXACTLY as ROADMAP.md specifies it,
#          including the DOTS_PASSED count the driver compares against the
#          seed. Keep this in sync with ROADMAP.md "Tier-1 verify".
#
# Usage: scripts/verify.sh   (or: make verify)
set -u
cd "$(dirname "$0")/.."

echo "== stage 1/3: static analysis (graftlint) =="
JAX_PLATFORMS=cpu python -m automerge_tpu.analysis || exit $?

echo "== stage 2/3: perf report + contention (informational) =="
JAX_PLATFORMS=cpu python -m automerge_tpu.perf report \
    || echo "perf report unavailable (informational stage — not a failure)"
JAX_PLATFORMS=cpu python -m automerge_tpu.perf contention \
    || echo "contention report unavailable (informational — not a failure)"
JAX_PLATFORMS=cpu python -m automerge_tpu.perf doctor --post-mortem BENCH_DETAIL.json \
    || echo "perf doctor unavailable (informational — not a failure)"
JAX_PLATFORMS=cpu python -m automerge_tpu.perf explain --post-mortem BENCH_DETAIL.json \
    || echo "perf explain unavailable (informational — not a failure)"
JAX_PLATFORMS=cpu python -m automerge_tpu.perf remediate --smoke \
    || echo "chaos-recovery smoke FAILED (informational here; enforced by tests + perf check)"
JAX_PLATFORMS=cpu python -m automerge_tpu.perf bootstrap --smoke \
    || echo "bootstrap smoke FAILED (informational here; enforced by tests + perf check)"
JAX_PLATFORMS=cpu python -m automerge_tpu.perf move --smoke \
    || echo "move smoke FAILED (informational here; enforced by tests + perf check)"
JAX_PLATFORMS=cpu python -m automerge_tpu.perf dispatch --smoke \
    || echo "dispatch smoke FAILED (informational here; enforced by tests + perf check)"
JAX_PLATFORMS=cpu python -m automerge_tpu.perf tenant --smoke \
    || echo "tenant smoke FAILED (informational here; enforced by tests + perf check)"
JAX_PLATFORMS=cpu python -m automerge_tpu.perf race --smoke \
    || echo "race smoke FAILED (informational here; enforced by tests + the locksan suite)"
JAX_PLATFORMS=cpu python -m automerge_tpu.perf trace --smoke \
    || echo "trace smoke FAILED (informational here; enforced by tests + perf check)"
JAX_PLATFORMS=cpu python -m automerge_tpu.perf megabatch --smoke \
    || echo "megabatch smoke FAILED (informational here; enforced by tests + perf check)"

echo "== stage 3/3: tier-1 suite (ROADMAP.md) =="
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
exit $rc
