"""A measured cost model of the REFERENCE's per-op apply path, in Python.

BASELINE.md's target is "≥50× single-threaded JS throughput", but no Node
runtime exists in this image, so bench.py grades against this repo's own
interpretive oracle. VERDICT r2 #6 asks for a calibration that anchors that
stand-in: this module re-creates the reference backend's per-op
*data-structure traffic* — persistent-map path updates, per-pair
concurrency checks against transitive clocks, survivor recompute — exactly
as `/root/reference/src/op_set.js` performs it, using this repo's HAMT
(`utils.persist.PMap`, the same shape Immutable.js Map is) as the
persistent-map primitive, and measures it in-image on the SAME traces the
bench runs.

Modeled, with sources:
- opSet as nested persistent maps; applyChange stamps ops and appends to
  `states[actor]` with transitive deps (op_set.js:224-241, 29-38)
- applyOp / applyAssign: field-op partition into concurrent/overwritten
  via per-pair isConcurrent (two `states` lookups + clock compare each,
  op_set.js:7-16, 184-201), survivor sort by actor desc, setIn of the new
  field-op list (op_set.js:201-202)
- applyMake / applyInsert bookkeeping maps (`_init`, `_inbound`,
  `_following`, `_insertion`, `_maxElem`; op_set.js:63-93)
- updateMapKey edit-record build incl. getPath walk (op_set.js:160-176,
  44-60)
- applyQueuedOps fixpoint queue scan (op_set.js:250-266)
- clock/deps maintenance (op_set.js:243-248)

DELIBERATELY OMITTED, each a real cost the reference pays that this model
does not charge (so the model under-counts the reference):
- the FreezeAPI frontend folding every diff into materialized snapshots
  with path-copying to the root (freeze_api.js:148-186)
- undo-stack assembly per local change (auto_api.js:41-68)
- skip-list index maintenance for list elements (skip_list.js)
- JSON wire parse of incoming changes
- Immutable.js's per-access overhead for `op.get('…')` on EVERY field
  read (ops here are plain dicts read with native attribute access)

The resulting structure_factor = refmodel_time / oracle_time therefore
LOWER-BOUNDS how much more per-op work the reference's architecture does
than this repo's oracle, in the same language and on the same interpreter.
Language speed (V8 JIT vs CPython) is a separate multiplier the image
cannot measure; BASELINE.md states the resulting bounds.
"""

from __future__ import annotations

import time

from automerge_tpu.utils.persist import AList, PMap

_E = PMap()


def _pm(d: dict) -> PMap:
    m = _E
    for k, v in d.items():
        m = m.set(k, v)
    return m


def _init_opset() -> PMap:
    # op_set.js:268-281. history/states use AList (persistent append-only
    # views) so growth costs what Immutable.js List.push costs — amortized
    # O(1), not O(n) tuple copies that would OVER-count the reference.
    return _pm({
        "states": _E, "byObject": _E.set("00000000-0000-0000-0000-000000000000", _E),
        "clock": _E, "deps": _E, "history": AList(), "queue": (),
    })


ROOT = "00000000-0000-0000-0000-000000000000"


def _transitive_deps(opset: PMap, base: dict) -> PMap:
    # op_set.js:29-38
    deps = _E
    for actor, seq in base.items():
        if seq <= 0:
            continue
        trans = opset.get("states").get(actor)
        if trans is not None and len(trans) >= seq:
            for a, s in trans[seq - 1]["allDeps"].items():
                if s > deps.get(a, 0):
                    deps = deps.set(a, s)
        if seq > deps.get(actor, 0):
            deps = deps.set(actor, seq)
    return deps


def _is_concurrent(opset: PMap, op1: dict, op2: dict) -> bool:
    # op_set.js:7-16 — two states lookups + two clock reads per PAIR
    a1, s1 = op1.get("actor"), op1.get("seq")
    a2, s2 = op2.get("actor"), op2.get("seq")
    if not a1 or not a2 or not s1 or not s2:
        return False
    c1 = opset.get("states").get(a1)[s1 - 1]["allDeps"]
    c2 = opset.get("states").get(a2)[s2 - 1]["allDeps"]
    return c1.get(a2, 0) < s2 and c2.get(a1, 0) < s1


def _get_path(opset: PMap, object_id: str):
    # op_set.js:44-60 — walk _inbound links to the root
    path = []
    by_object = opset.get("byObject")
    while object_id != ROOT:
        ref = by_object.get(object_id).get("_inbound")
        if not ref:
            return None
        ref = next(iter(ref))
        object_id = ref["obj"]
        if by_object.get(object_id).get("_init")["action"] == "makeList":
            path.insert(0, ref.get("elem", 0))
        else:
            path.insert(0, ref["key"])
    return path


def _update_map_key(opset: PMap, object_id: str, key: str):
    # op_set.js:160-176
    ops = opset.get("byObject").get(object_id).get(key, ())
    edit = {"action": "", "type": "map", "obj": object_id, "key": key,
            "path": _get_path(opset, object_id)}
    if not ops:
        edit["action"] = "remove"
    else:
        edit["action"] = "set"
        edit["value"] = ops[0].get("value")
        if ops[0]["action"] == "link":
            edit["link"] = True
        if len(ops) > 1:
            edit["conflicts"] = [
                {"actor": o["actor"], "value": o.get("value")}
                for o in ops[1:]]
    return opset, [edit]


def _apply_assign(opset: PMap, op: dict):
    # op_set.js:179-209
    object_id = op["obj"]
    obj = opset.get("byObject").get(object_id)
    if obj is None:
        raise KeyError(object_id)
    obj.get("_init")  # objType lookup (op_set.js:181)
    prior = obj.get(op["key"], ())
    # ONE isConcurrent per pair, like the reference's groupBy
    # (op_set.js:184-187)
    flags = [_is_concurrent(opset, o, op) for o in prior]
    overwritten = tuple(o for o, c in zip(prior, flags) if not c)
    remaining = tuple(o for o, c in zip(prior, flags) if c)
    for o in overwritten:
        if o["action"] == "link":
            tgt = opset.get("byObject").get(o["value"])
            opset = opset.set("byObject", opset.get("byObject").set(
                o["value"], tgt.set("_inbound",
                                    tuple(x for x in tgt.get("_inbound", ())
                                          if x is not o))))
    if op["action"] == "link":
        tgt = opset.get("byObject").get(op["value"])
        opset = opset.set("byObject", opset.get("byObject").set(
            op["value"], tgt.set("_inbound",
                                 tgt.get("_inbound", ()) + (op,))))
    if op["action"] != "del":
        remaining = remaining + (op,)
    remaining = tuple(sorted(remaining, key=lambda o: o["actor"],
                             reverse=True))
    opset = opset.set("byObject", opset.get("byObject").set(
        object_id, obj.set(op["key"], remaining)))
    return _update_map_key(opset, object_id, op["key"])


def _apply_make(opset: PMap, op: dict):
    # op_set.js:63-78 (list bookkeeping modeled as empty maps, no skip list)
    obj = _pm({"_init": op, "_inbound": ()})
    if op["action"] in ("makeList", "makeText"):
        obj = obj.set("_elemIds", None)
    opset = opset.set("byObject",
                      opset.get("byObject").set(op["obj"], obj))
    return opset, [{"action": "create", "obj": op["obj"]}]


def _apply_insert(opset: PMap, op: dict):
    # op_set.js:82-93
    object_id = op["obj"]
    elem_id = f"{op['actor']}:{op['elem']}"
    obj = opset.get("byObject").get(object_id)
    following = obj.get("_following", _E)
    following = following.set(op["key"],
                              following.get(op["key"], ()) + (op,))
    obj = (obj.set("_following", following)
              .set("_maxElem", max(op["elem"], obj.get("_maxElem", 0)))
              .set("_insertion", obj.get("_insertion", _E).set(elem_id, op)))
    return opset.set("byObject",
                     opset.get("byObject").set(object_id, obj)), []


def _apply_op(opset: PMap, op: dict):
    a = op["action"]
    if a in ("makeMap", "makeList", "makeText"):
        return _apply_make(opset, op)
    if a == "ins":
        return _apply_insert(opset, op)
    return _apply_assign(opset, op)


def _causally_ready(opset: PMap, change) -> bool:
    # op_set.js:20-27
    deps = dict(change.deps)
    deps[change.actor] = change.seq - 1
    return all(opset.get("clock").get(a, 0) >= s for a, s in deps.items())


def _apply_change(opset: PMap, change):
    # op_set.js:224-248
    actor, seq = change.actor, change.seq
    prior = opset.get("states").get(actor)
    if prior is None:
        prior = AList()
    if seq <= len(prior):
        return opset, []
    base = dict(change.deps)
    base[actor] = seq - 1
    all_deps = _transitive_deps(opset, base).set(actor, seq)
    opset = opset.set("states", opset.get("states").set(
        actor, prior.append({"allDeps": all_deps})))
    diffs = []
    for op in change.ops:
        stamped = {"action": op.action, "obj": op.obj, "actor": actor,
                   "seq": seq}
        if op.key is not None:
            stamped["key"] = op.key
        if op.elem is not None:
            stamped["elem"] = op.elem
        if op.value is not None:
            stamped["value"] = op.value
        opset, d = _apply_op(opset, stamped)
        diffs.extend(d)
    deps = _E
    for a, s in opset.get("deps").items():
        if s > all_deps.get(a, 0):
            deps = deps.set(a, s)
    deps = deps.set(actor, seq)
    opset = (opset.set("deps", deps)
                  .set("clock", opset.get("clock").set(actor, seq))
                  .set("history", opset.get("history").append(change)))
    return opset, diffs


def apply_changes(opset: PMap, changes):
    # addChange + applyQueuedOps fixpoint (op_set.js:250-266, 287-291)
    queue = opset.get("queue") + tuple(changes)
    diffs = []
    while True:
        still = ()
        progressed = False
        for change in queue:
            if _causally_ready(opset, change):
                opset, d = _apply_change(opset, change)
                diffs.extend(d)
                progressed = True
            else:
                still = still + (change,)
        queue = still
        if not progressed or not queue:
            break
    return opset.set("queue", queue), diffs


def run_refmodel(doc_changes) -> float:
    """Seconds to apply every doc's change set through the reference-model
    backend (from scratch, per doc — what the JS reference does on merge)."""
    t0 = time.perf_counter()
    for changes in doc_changes:
        opset = _init_opset()
        opset, _diffs = apply_changes(opset, changes)
    return time.perf_counter() - t0
