"""A measured cost model of the REFERENCE's per-op apply path, in Python.

BASELINE.md's target is "≥50× single-threaded JS throughput", but no Node
runtime exists in this image, so bench.py grades against this repo's own
interpretive oracle. VERDICT r2 #6 asks for a calibration that anchors that
stand-in: this module re-creates the reference backend's per-op
*data-structure traffic* — persistent-map path updates, per-pair
concurrency checks against transitive clocks, survivor recompute — exactly
as `/root/reference/src/op_set.js` performs it, using this repo's HAMT
(`utils.persist.PMap`, the same shape Immutable.js Map is) as the
persistent-map primitive, and measures it in-image on the SAME traces the
bench runs.

Modeled, with sources:
- opSet as nested persistent maps; applyChange stamps ops and appends to
  `states[actor]` with transitive deps (op_set.js:224-241, 29-38)
- applyOp / applyAssign: field-op partition into concurrent/overwritten
  via per-pair isConcurrent (two `states` lookups + clock compare each,
  op_set.js:7-16, 184-201), survivor sort by actor desc, setIn of the new
  field-op list (op_set.js:201-202)
- applyMake / applyInsert bookkeeping maps (`_init`, `_inbound`,
  `_following`, `_insertion`, `_maxElem`; op_set.js:63-93)
- updateMapKey edit-record build incl. getPath walk (op_set.js:160-176,
  44-60)
- applyQueuedOps fixpoint queue scan (op_set.js:250-266)
- clock/deps maintenance (op_set.js:243-248)

Round 8 adds the piece VERDICT r5 weak #3 called out as missing: the
reference's **skip-list element index** (skip_list.js) and the list/text
half of its edit-record pipeline (updateListElement, op_set.js:131-158,
incl. the getPrevious RGA walk op_set.js:336-397). Text and list ops now
pay what v0.8.0 pays per op: persistent-map bookkeeping + an O(log n)
indexed skip-list update + the closest-visible-predecessor walk — so
configs 6/7 grade against the SHIPPED reference's architecture, not the
2017 pre-skip-list frontend.

DELIBERATELY OMITTED, each a real cost the reference pays that this model
does not charge (so the model under-counts the reference):
- the FreezeAPI frontend folding every diff into materialized snapshots
  with path-copying to the root (freeze_api.js:148-186)
- undo-stack assembly per local change (auto_api.js:41-68)
- the skip list's own Immutable.js path-copying (this model's skip list
  is mutable: node splices are O(level), not O(level) map copies)
- JSON wire parse of incoming changes
- Immutable.js's per-access overhead for `op.get('…')` on EVERY field
  read (ops here are plain dicts read with native attribute access)

The resulting structure_factor = refmodel_time / oracle_time therefore
LOWER-BOUNDS how much more per-op work the reference's architecture does
than this repo's oracle, in the same language and on the same interpreter.
Language speed (V8 JIT vs CPython) is a separate multiplier the image
cannot measure; BASELINE.md states the resulting bounds.
"""

from __future__ import annotations

import random
import time

from automerge_tpu.utils.persist import AList, PMap

_E = PMap()

HEAD = "_head"


class _SkipNode:
    __slots__ = ("key", "value", "level", "prev_key", "next_key",
                 "prev_count", "next_count")

    def __init__(self, key, value, level):
        self.key = key
        self.value = value
        self.level = level
        self.prev_key = [None] * level
        self.next_key = [None] * level
        self.prev_count = [0] * level
        self.next_count = [None] * level


class SkipList:
    """The reference's indexed skip list (skip_list.js): doubly-linked
    nodes at every level with per-link widths, so `index_of` (rank of a
    key), `key_at` (key at rank) and `insert_after` are all O(log n)
    expected. Level draws use a seeded RNG (p = 1/2, the classic Pugh
    parameters skip_list.js uses) so oracle runs are reproducible."""

    def __init__(self, seed: int = 0):
        self._head = _SkipNode(None, None, 1)
        self._head.next_count = [None]
        self._nodes: dict = {}
        self._rng = random.Random(seed)

    def __len__(self) -> int:
        return len(self._nodes)

    def __contains__(self, key) -> bool:
        return key in self._nodes

    def _node(self, key) -> _SkipNode:
        return self._head if key is None else self._nodes[key]

    def _random_level(self) -> int:
        level = 1
        while level < 32 and self._rng.random() < 0.5:
            level += 1
        return level

    def index_of(self, key) -> int:
        """Rank of `key` (0-based), or -1 when absent: climb left from the
        node, accumulating link widths (skip_list.js indexOf)."""
        node = self._nodes.get(key)
        if node is None:
            return -1
        i = 0
        cur = node
        while cur is not self._head:
            top = cur.level - 1
            i += cur.prev_count[top]
            cur = self._node(cur.prev_key[top])
        return i - 1

    def key_at(self, index: int):
        """Key at rank `index` (top-down descent over link widths)."""
        if not 0 <= index < len(self._nodes):
            return None
        cur, pos = self._head, -1
        for lvl in range(self._head.level - 1, -1, -1):
            while (cur.next_key[lvl] is not None
                   and pos + cur.next_count[lvl] <= index):
                pos += cur.next_count[lvl]
                cur = self._nodes[cur.next_key[lvl]]
            if pos == index:
                return cur.key
        return cur.key

    def value_at(self, index: int):
        return self._nodes[self.key_at(index)].value

    def set_value(self, key, value) -> None:
        self._nodes[key].value = value

    def _pre_walk(self, start: _SkipNode, start_dist: int, level: int):
        """(node, distance) of the last node of height > `level` at or
        before `start`, where `start_dist` is start's distance to the
        position being spliced."""
        cur, d = start, start_dist
        while cur.level < level + 1:
            top = cur.level - 1
            d += cur.prev_count[top]
            cur = self._node(cur.prev_key[top])
        return cur, d

    def insert_after(self, pred_key, key, value) -> None:
        """Insert `key` immediately after `pred_key` (None = head), the
        skip_list.js insertAfter splice: per-level width maintenance up
        the node's height, width increments on the spanning links above."""
        if key in self._nodes:
            raise KeyError(f"duplicate key {key}")
        new_level = self._random_level()
        if new_level > self._head.level:
            for _ in range(self._head.level, new_level):
                self._head.prev_key.append(None)
                self._head.prev_count.append(0)
                self._head.next_key.append(None)
                self._head.next_count.append(None)
            self._head.level = new_level
        node = _SkipNode(key, value, new_level)
        self._nodes[key] = node
        cur, d = self._node(pred_key), 1
        for lvl in range(new_level):
            cur, d = self._pre_walk(cur, d, lvl)
            nxt_key = cur.next_key[lvl]
            node.prev_key[lvl] = cur.key
            node.prev_count[lvl] = d
            node.next_key[lvl] = nxt_key
            cur.next_key[lvl] = key
            if nxt_key is not None:
                nxt = self._nodes[nxt_key]
                node.next_count[lvl] = nxt.prev_count[lvl] - d + 1
                nxt.prev_key[lvl] = key
                nxt.prev_count[lvl] = node.next_count[lvl]
            cur.next_count[lvl] = d
        # widen the taller spanning links crossing the insertion point
        for lvl in range(new_level, self._head.level):
            cur, d = self._pre_walk(cur, d, lvl)
            if cur.next_key[lvl] is not None:
                cur.next_count[lvl] += 1
                self._nodes[cur.next_key[lvl]].prev_count[lvl] += 1

    def remove(self, key) -> None:
        """Unsplice `key` (skip_list.js removeKey): per-level width merge
        at the node's height, width decrements on spanning links above."""
        node = self._nodes.pop(key)
        for lvl in range(node.level):
            pre = self._node(node.prev_key[lvl])
            nxt_key = node.next_key[lvl]
            pre.next_key[lvl] = nxt_key
            if nxt_key is not None:
                nxt = self._nodes[nxt_key]
                merged = node.prev_count[lvl] + nxt.prev_count[lvl] - 1
                pre.next_count[lvl] = merged
                nxt.prev_key[lvl] = node.prev_key[lvl]
                nxt.prev_count[lvl] = merged
            else:
                pre.next_count[lvl] = None
        cur, d = self._node(node.prev_key[node.level - 1]), 0
        for lvl in range(node.level, self._head.level):
            cur, d = self._pre_walk(cur, d, lvl)
            if cur.next_key[lvl] is not None:
                cur.next_count[lvl] -= 1
                self._nodes[cur.next_key[lvl]].prev_count[lvl] -= 1

    def to_list(self) -> list:
        """Values in order (model verification only — not a modeled cost)."""
        out = []
        cur = self._head
        while cur.next_key[0] is not None:
            cur = self._nodes[cur.next_key[0]]
            out.append(cur.value)
        return out


def _pm(d: dict) -> PMap:
    m = _E
    for k, v in d.items():
        m = m.set(k, v)
    return m


def _init_opset() -> PMap:
    # op_set.js:268-281. history/states use AList (persistent append-only
    # views) so growth costs what Immutable.js List.push costs — amortized
    # O(1), not O(n) tuple copies that would OVER-count the reference.
    return _pm({
        "states": _E, "byObject": _E.set("00000000-0000-0000-0000-000000000000", _E),
        "clock": _E, "deps": _E, "history": AList(), "queue": (),
    })


ROOT = "00000000-0000-0000-0000-000000000000"


def _transitive_deps(opset: PMap, base: dict) -> PMap:
    # op_set.js:29-38
    deps = _E
    for actor, seq in base.items():
        if seq <= 0:
            continue
        trans = opset.get("states").get(actor)
        if trans is not None and len(trans) >= seq:
            for a, s in trans[seq - 1]["allDeps"].items():
                if s > deps.get(a, 0):
                    deps = deps.set(a, s)
        if seq > deps.get(actor, 0):
            deps = deps.set(actor, seq)
    return deps


def _is_concurrent(opset: PMap, op1: dict, op2: dict) -> bool:
    # op_set.js:7-16 — two states lookups + two clock reads per PAIR
    a1, s1 = op1.get("actor"), op1.get("seq")
    a2, s2 = op2.get("actor"), op2.get("seq")
    if not a1 or not a2 or not s1 or not s2:
        return False
    c1 = opset.get("states").get(a1)[s1 - 1]["allDeps"]
    c2 = opset.get("states").get(a2)[s2 - 1]["allDeps"]
    return c1.get(a2, 0) < s2 and c2.get(a1, 0) < s1


def _get_path(opset: PMap, object_id: str):
    # op_set.js:44-60 — walk _inbound links to the root; a sequence
    # parent contributes the child's index via the skip list's indexOf
    path = []
    by_object = opset.get("byObject")
    while object_id != ROOT:
        ref = by_object.get(object_id).get("_inbound")
        if not ref:
            return None
        ref = next(iter(ref))
        object_id = ref["obj"]
        parent = by_object.get(object_id)
        init = parent.get("_init")  # the root has no _init and is a map
        if init is not None and init["action"] in ("makeList", "makeText"):
            path.insert(0, parent.get("_elemIds").index_of(ref["key"]))
        else:
            path.insert(0, ref["key"])
    return path


def _get_parent(opset: PMap, object_id: str, key: str):
    # op_set.js:336-341
    if key == HEAD:
        return None
    ins = opset.get("byObject").get(object_id).get("_insertion").get(key)
    if ins is None:
        raise KeyError(key)
    return ins["key"]


def _insertions_after(opset: PMap, object_id: str, parent_id,
                      child_id=None):
    # op_set.js:351-362 — children in Lamport-descending (elem, actor)
    child = None
    if child_id is not None:
        i = child_id.rindex(":")
        child = (int(child_id[i + 1:]), child_id[:i])
    obj = opset.get("byObject").get(object_id)
    ops = [op for op in obj.get("_following", _E).get(
        parent_id if parent_id is not None else HEAD, ())
        if op["action"] == "ins"]
    if child is not None:
        ops = [op for op in ops if (op["elem"], op["actor"]) < child]
    ops.sort(key=lambda op: (op["elem"], op["actor"]), reverse=True)
    return [f"{op['actor']}:{op['elem']}" for op in ops]


def _get_previous(opset: PMap, object_id: str, key: str):
    # op_set.js:380-397 — predecessor in RGA document order
    parent_id = _get_parent(opset, object_id, key)
    children = _insertions_after(opset, object_id, parent_id)
    if children and children[0] == key:
        return None if (parent_id is None or parent_id == HEAD) \
            else parent_id
    prev_id = None
    for child in children:
        if child == key:
            break
        prev_id = child
    while True:
        children = _insertions_after(opset, object_id, prev_id)
        if not children:
            return prev_id
        prev_id = children[-1]


def _update_list_element(opset: PMap, object_id: str, elem_id: str):
    # op_set.js:131-158 — the skip-list half of the edit pipeline: an
    # indexed-order update per op (indexOf / insertAfter / removeKey all
    # O(log n)) plus the closest-visible-predecessor walk on fresh inserts
    obj = opset.get("byObject").get(object_id)
    ops = obj.get(elem_id, ())
    sl: SkipList = obj.get("_elemIds")
    index = sl.index_of(elem_id)
    edit = {"type": "list", "obj": object_id,
            "path": _get_path(opset, object_id)}
    if index >= 0:
        if not ops:
            sl.remove(elem_id)
            edit.update(action="remove", index=index)
        else:
            sl.set_value(elem_id, ops[0].get("value"))
            edit.update(action="set", index=index,
                        value=ops[0].get("value"))
            if len(ops) > 1:
                edit["conflicts"] = [
                    {"actor": o["actor"], "value": o.get("value")}
                    for o in ops[1:]]
        return opset, [edit]
    if not ops:
        return opset, []  # deleting an absent element is a no-op
    # closest visible predecessor (op_set.js:146-156)
    prev_id = elem_id
    while True:
        index = -1
        prev_id = _get_previous(opset, object_id, prev_id)
        if prev_id is None:
            break
        index = sl.index_of(prev_id)
        if index >= 0:
            break
    sl.insert_after(prev_id if index >= 0 else None, elem_id,
                    ops[0].get("value"))
    edit.update(action="insert", index=index + 1,
                value=ops[0].get("value"))
    return opset, [edit]


def _update_map_key(opset: PMap, object_id: str, key: str):
    # op_set.js:160-176
    ops = opset.get("byObject").get(object_id).get(key, ())
    edit = {"action": "", "type": "map", "obj": object_id, "key": key,
            "path": _get_path(opset, object_id)}
    if not ops:
        edit["action"] = "remove"
    else:
        edit["action"] = "set"
        edit["value"] = ops[0].get("value")
        if ops[0]["action"] == "link":
            edit["link"] = True
        if len(ops) > 1:
            edit["conflicts"] = [
                {"actor": o["actor"], "value": o.get("value")}
                for o in ops[1:]]
    return opset, [edit]


def _apply_assign(opset: PMap, op: dict):
    # op_set.js:179-209
    object_id = op["obj"]
    obj = opset.get("byObject").get(object_id)
    if obj is None:
        raise KeyError(object_id)
    obj.get("_init")  # objType lookup (op_set.js:181)
    prior = obj.get(op["key"], ())
    # ONE isConcurrent per pair, like the reference's groupBy
    # (op_set.js:184-187)
    flags = [_is_concurrent(opset, o, op) for o in prior]
    overwritten = tuple(o for o, c in zip(prior, flags) if not c)
    remaining = tuple(o for o, c in zip(prior, flags) if c)
    for o in overwritten:
        if o["action"] == "link":
            tgt = opset.get("byObject").get(o["value"])
            opset = opset.set("byObject", opset.get("byObject").set(
                o["value"], tgt.set("_inbound",
                                    tuple(x for x in tgt.get("_inbound", ())
                                          if x is not o))))
    if op["action"] == "link":
        tgt = opset.get("byObject").get(op["value"])
        opset = opset.set("byObject", opset.get("byObject").set(
            op["value"], tgt.set("_inbound",
                                 tgt.get("_inbound", ()) + (op,))))
    if op["action"] != "del":
        remaining = remaining + (op,)
    remaining = tuple(sorted(remaining, key=lambda o: o["actor"],
                             reverse=True))
    opset = opset.set("byObject", opset.get("byObject").set(
        object_id, obj.set(op["key"], remaining)))
    init = obj.get("_init")  # the root has no _init and is a map
    if init is not None and init["action"] in ("makeList", "makeText"):
        return _update_list_element(opset, object_id, op["key"])
    return _update_map_key(opset, object_id, op["key"])


def _apply_make(opset: PMap, op: dict):
    # op_set.js:63-78; sequence objects carry the indexed skip list
    obj = _pm({"_init": op, "_inbound": ()})
    if op["action"] in ("makeList", "makeText"):
        obj = obj.set("_elemIds", SkipList())
    opset = opset.set("byObject",
                      opset.get("byObject").set(op["obj"], obj))
    return opset, [{"action": "create", "obj": op["obj"]}]


def _apply_insert(opset: PMap, op: dict):
    # op_set.js:82-93
    object_id = op["obj"]
    elem_id = f"{op['actor']}:{op['elem']}"
    obj = opset.get("byObject").get(object_id)
    following = obj.get("_following", _E)
    following = following.set(op["key"],
                              following.get(op["key"], ()) + (op,))
    obj = (obj.set("_following", following)
              .set("_maxElem", max(op["elem"], obj.get("_maxElem", 0)))
              .set("_insertion", obj.get("_insertion", _E).set(elem_id, op)))
    return opset.set("byObject",
                     opset.get("byObject").set(object_id, obj)), []


def _apply_op(opset: PMap, op: dict):
    a = op["action"]
    if a in ("makeMap", "makeList", "makeText"):
        return _apply_make(opset, op)
    if a == "ins":
        return _apply_insert(opset, op)
    return _apply_assign(opset, op)


def _causally_ready(opset: PMap, change) -> bool:
    # op_set.js:20-27
    deps = dict(change.deps)
    deps[change.actor] = change.seq - 1
    return all(opset.get("clock").get(a, 0) >= s for a, s in deps.items())


def _apply_change(opset: PMap, change):
    # op_set.js:224-248
    actor, seq = change.actor, change.seq
    prior = opset.get("states").get(actor)
    if prior is None:
        prior = AList()
    if seq <= len(prior):
        return opset, []
    base = dict(change.deps)
    base[actor] = seq - 1
    all_deps = _transitive_deps(opset, base).set(actor, seq)
    opset = opset.set("states", opset.get("states").set(
        actor, prior.append({"allDeps": all_deps})))
    diffs = []
    for op in change.ops:
        stamped = {"action": op.action, "obj": op.obj, "actor": actor,
                   "seq": seq}
        if op.key is not None:
            stamped["key"] = op.key
        if op.elem is not None:
            stamped["elem"] = op.elem
        if op.value is not None:
            stamped["value"] = op.value
        opset, d = _apply_op(opset, stamped)
        diffs.extend(d)
    deps = _E
    for a, s in opset.get("deps").items():
        if s > all_deps.get(a, 0):
            deps = deps.set(a, s)
    deps = deps.set(actor, seq)
    opset = (opset.set("deps", deps)
                  .set("clock", opset.get("clock").set(actor, seq))
                  .set("history", opset.get("history").append(change)))
    return opset, diffs


def apply_changes(opset: PMap, changes):
    # addChange + applyQueuedOps fixpoint (op_set.js:250-266, 287-291)
    queue = opset.get("queue") + tuple(changes)
    diffs = []
    while True:
        still = ()
        progressed = False
        for change in queue:
            if _causally_ready(opset, change):
                opset, d = _apply_change(opset, change)
                diffs.extend(d)
                progressed = True
            else:
                still = still + (change,)
        queue = still
        if not progressed or not queue:
            break
    return opset.set("queue", queue), diffs


def run_refmodel(doc_changes) -> float:
    """Seconds to apply every doc's change set through the reference-model
    backend (from scratch, per doc — what the JS reference does on merge)."""
    t0 = time.perf_counter()
    for changes in doc_changes:
        opset = _init_opset()
        opset, _diffs = apply_changes(opset, changes)
    return time.perf_counter() - t0


# ---------------------------------------------------------------------------
# interactive keystrokes (bench config 7's oracle side)


class _RawOp:
    __slots__ = ("action", "obj", "key", "elem", "value")

    def __init__(self, action, obj, key=None, elem=None, value=None):
        self.action = action
        self.obj = obj
        self.key = key
        self.elem = elem
        self.value = value


class _RawChange:
    __slots__ = ("actor", "seq", "deps", "ops")

    def __init__(self, actor, seq, deps, ops):
        self.actor = actor
        self.seq = seq
        self.deps = deps
        self.ops = ops


def find_text_object(opset: PMap) -> str:
    """Object id of the first makeText object (model verification)."""
    for oid, obj in opset.get("byObject").items():
        if oid != ROOT and obj.get("_init")["action"] == "makeText":
            return oid
    raise KeyError("no text object")


def text_of(opset: PMap, object_id: str) -> str:
    """Visible text via the skip list (model verification only)."""
    sl = opset.get("byObject").get(object_id).get("_elemIds")
    return "".join(str(v) for v in sl.to_list())


def keystroke_change(opset: PMap, object_id: str, actor: str, seq: int,
                     kind: str, pos: int, ch=None) -> _RawChange:
    """One interactive keystroke as the reference frontend would issue it:
    position -> element id through the skip list (key_at, O(log n)), then
    an ins+set (or del) change ready for `apply_changes`. Build cost is
    part of the per-keystroke pipeline and belongs inside the timed
    region."""
    obj = opset.get("byObject").get(object_id)
    sl: SkipList = obj.get("_elemIds")
    if kind == "ins":
        parent = sl.key_at(pos - 1) if pos > 0 else HEAD
        elem = obj.get("_maxElem", 0) + 1
        eid = f"{actor}:{elem}"
        ops = [_RawOp("ins", object_id, key=parent, elem=elem),
               _RawOp("set", object_id, key=eid, value=ch)]
    else:
        ops = [_RawOp("del", object_id, key=sl.key_at(pos))]
    # a local change depends on everything the frontend has seen — the
    # current deps frontier, minus the writer itself (change format)
    deps = {a: s for a, s in opset.get("deps").items() if a != actor}
    return _RawChange(actor, seq, deps, ops)
