"""Shim: the roofline probe now lives in `automerge_tpu.perf.roofline`
(run `python -m automerge_tpu.perf roofline`; this script stays for the
tunnel-recovery hook and muscle memory). Behavior — flags, the
`--interpret-smoke` contract pinned by tests/test_roofline_smoke.py, the
ROOFLINE.json output — is unchanged."""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)) or ".")

from automerge_tpu.perf.roofline import main  # noqa: E402

if __name__ == "__main__":
    main()
