"""Race-plane tests: thread-root discovery, the three race rules on
known-racy / known-safe / declared-lock-free fixtures, the lock-order
manifest round-trip + cycle detection, the manifest drift rules, the
env-knob registry rule, and the no-new-findings check on the repo."""

import json
import pathlib
import textwrap

from automerge_tpu.analysis import load_project
from automerge_tpu.analysis.core import run_analysis
from automerge_tpu.analysis.flow import (MANIFEST_NAME, LocksManifest,
                                         build_manifest, find_cycle,
                                         lock_graph)
from automerge_tpu.analysis.lock_discipline import LockDisciplinePass
from automerge_tpu.analysis.races import RacePass
from automerge_tpu.analysis.registry import RegistryConformancePass
from automerge_tpu.analysis.threadmap import thread_map

ROOT = pathlib.Path(__file__).resolve().parent.parent

RACY = '''\
    import threading

    class Node:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0
            self.items = []
            self._thread = threading.Thread(target=self._loop)

        def start(self):
            self._thread.start()

        def _loop(self):
            while True:
                self.count += 1
                self.items.append(1)

        def poke(self):
            self.count = 0
            self.items.append(2)
    '''

SAFE = '''\
    import threading

    class Node:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0
            self.items = []
            self._thread = threading.Thread(target=self._loop)

        def start(self):
            self._thread.start()

        def _loop(self):
            while True:
                with self._lock:
                    self.count += 1
                    self.items.append(1)

        def poke(self):
            with self._lock:
                self.count = 0
                self.items.append(2)
    '''

PEEK = '''\
    import threading

    class Node:
        def __init__(self):
            self._lock = threading.Lock()
            self.stamp = 0
            self._thread = threading.Thread(target=self._loop)

        def start(self):
            self._thread.start()

        def _loop(self):
            while True:
                with self._lock:
                    self.stamp += 1

        def snapshot(self):
            return self.stamp
    '''


def _write(tmp_path, source, rel="automerge_tpu/sync/fix.py"):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))


def _races(tmp_path, source, rel="automerge_tpu/sync/fix.py"):
    _write(tmp_path, source, rel)
    return RacePass().run(load_project(tmp_path))


def _rules(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# thread-root discovery


def test_threadmap_discovers_thread_roots(tmp_path):
    _write(tmp_path, RACY)
    tm = thread_map(load_project(tmp_path),
                    ("automerge_tpu/sync/",))
    assert "thread:fix.Node._loop" in tm.roots
    assert "main" in tm.roots


def test_threadmap_sites_carry_roots_and_holds(tmp_path):
    _write(tmp_path, SAFE)
    tm = thread_map(load_project(tmp_path),
                    ("automerge_tpu/sync/",))
    slot = tm.attr_table()["Node.count"]
    roots = {r for _s, ctx in slot["write"] for r in ctx}
    assert "thread:fix.Node._loop" in roots and "main" in roots
    for _s, ctx in slot["write"]:
        for held in ctx.values():
            assert any("_lock" in h for h in held)


# ---------------------------------------------------------------------------
# the race rules


def test_unlocked_shared_writes_flagged(tmp_path):
    findings = _races(tmp_path, RACY)
    rules = _rules(findings)
    assert "shared-write-unlocked" in rules      # Node.count
    assert "shared-mutate-aliased" in rules      # Node.items
    by_rule = {f.rule: f for f in findings}
    assert "Node.count" in by_rule["shared-write-unlocked"].message
    assert "Node.items" in by_rule["shared-mutate-aliased"].message
    # one finding per attribute, not one per site
    assert rules.count("shared-write-unlocked") == 1


def test_consistently_locked_writes_clean(tmp_path):
    assert _races(tmp_path, SAFE) == []


def test_lockfree_read_needs_declaration(tmp_path):
    findings = _races(tmp_path, PEEK)
    assert _rules(findings) == ["lockfree-undeclared"]
    assert "Node.stamp" in findings[0].message


def test_declared_lockfree_suppresses(tmp_path):
    (tmp_path / MANIFEST_NAME).write_text(json.dumps({
        "version": 1, "locks": [], "order": [],
        "lockfree": [{"attr": "Node.stamp",
                      "justification": "LWW stamp, test fixture"}]}))
    assert _races(tmp_path, PEEK) == []


def test_stale_lockfree_declaration_flagged(tmp_path):
    (tmp_path / MANIFEST_NAME).write_text(json.dumps({
        "version": 1, "locks": [], "order": [],
        "lockfree": [{"attr": "Node.stamp", "justification": "used"},
                     {"attr": "Node.gone", "justification": "unused"}]}))
    findings = _races(tmp_path, PEEK)
    assert _rules(findings) == ["lockfree-stale"]
    assert "Node.gone" in findings[0].message


# ---------------------------------------------------------------------------
# manifest round-trip + cycles


NESTED = '''\
    import threading

    class Node:
        def __init__(self):
            self._lock = threading.Lock()
            self._log_lock = threading.Lock()

        def a_then_b(self):
            with self._lock:
                with self._log_lock:
                    pass
    '''


def test_manifest_roundtrip(tmp_path):
    _write(tmp_path, NESTED)
    project = load_project(tmp_path)
    manifest = build_manifest(project)
    path = tmp_path / MANIFEST_NAME
    manifest.save(path)
    back = LocksManifest.load(path)
    assert back is not None
    assert back.order_edges() == manifest.order_edges()
    assert ("Node._lock", "Node._log_lock") in back.order_edges()


def test_manifest_carries_lockfree_on_rebuild(tmp_path):
    _write(tmp_path, NESTED)
    project = load_project(tmp_path)
    prior = LocksManifest(
        lockfree=[{"attr": "X.y", "justification": "kept"}])
    manifest = build_manifest(project, prior)
    assert manifest.lockfree_attrs() == {"X.y": "kept"}


def test_find_cycle():
    assert find_cycle({("a", "b"), ("b", "c")}) is None
    cyc = find_cycle({("a", "b"), ("b", "c"), ("c", "a")})
    assert cyc is not None and len(set(cyc) & {"a", "b", "c"}) == 3


def test_manifest_drift_and_stale(tmp_path):
    _write(tmp_path, NESTED)
    # manifest missing the observed edge, carrying a phantom one
    (tmp_path / MANIFEST_NAME).write_text(json.dumps({
        "version": 1, "locks": [],
        "order": [{"before": "P._a", "after": "P._b", "site": "x"}],
        "lockfree": []}))
    findings = LockDisciplinePass().run(load_project(tmp_path))
    rules = _rules(findings)
    assert "lock-manifest-drift" in rules
    assert "lock-manifest-stale" in rules


def test_manifest_cycle_fails(tmp_path):
    _write(tmp_path, NESTED)
    (tmp_path / MANIFEST_NAME).write_text(json.dumps({
        "version": 1, "locks": [],
        "order": [
            {"before": "Node._lock", "after": "Node._log_lock",
             "site": "x"},
            {"before": "Node._log_lock", "after": "Node._lock",
             "site": "y"}],
        "lockfree": []}))
    findings = LockDisciplinePass().run(load_project(tmp_path))
    assert "lock-order-cycle" in _rules(findings)


def test_no_manifest_no_drift_rules(tmp_path):
    _write(tmp_path, NESTED)
    findings = LockDisciplinePass().run(load_project(tmp_path))
    assert not any(r.startswith("lock-manifest") for r in _rules(findings))


# ---------------------------------------------------------------------------
# env-knob registry rule


KNOB_READER = '''\
    import os

    RATE = os.environ.get("AMTPU_FIXTURE_RATE", "1")
    MODE = os.getenv("AMTPU_FIXTURE_MODE")
    '''


def _knob_doc(tmp_path, body):
    doc = tmp_path / "docs" / "OBSERVABILITY.md"
    doc.parent.mkdir(parents=True, exist_ok=True)
    doc.write_text(body)


def test_undocumented_knob_flagged(tmp_path):
    _write(tmp_path, KNOB_READER, rel="automerge_tpu/utils/fix.py")
    _knob_doc(tmp_path, "## Environment knobs\n\n"
                        "| `AMTPU_FIXTURE_RATE` | 1 | rate |\n")
    findings = [f for f in
                RegistryConformancePass().run(load_project(tmp_path))
                if f.rule == "env-knob-undocumented"]
    assert len(findings) == 1
    assert "AMTPU_FIXTURE_MODE" in findings[0].message


def test_documented_knobs_clean(tmp_path):
    _write(tmp_path, KNOB_READER, rel="automerge_tpu/utils/fix.py")
    _knob_doc(tmp_path, "## Environment knobs\n\n"
                        "| `AMTPU_FIXTURE_RATE` | 1 | rate |\n"
                        "| `AMTPU_FIXTURE_MODE` | unset | mode |\n")
    findings = RegistryConformancePass().run(load_project(tmp_path))
    assert "env-knob-undocumented" not in _rules(findings)


def test_knob_rule_disarmed_without_doc(tmp_path):
    _write(tmp_path, KNOB_READER, rel="automerge_tpu/utils/fix.py")
    findings = RegistryConformancePass().run(load_project(tmp_path))
    assert "env-knob-undocumented" not in _rules(findings)


# ---------------------------------------------------------------------------
# the repo itself


def test_repo_race_findings_all_baselined():
    """The committed manifest + fixes keep the full suite green: any
    new race finding in the repo fails here first."""
    report = run_analysis(ROOT, ROOT / "analysis_baseline.json")
    assert [f.render() for f in report.new] == []


def test_repo_lock_graph_matches_committed_manifest():
    project = load_project(ROOT)
    observed = set(lock_graph(project))
    manifest = LocksManifest.load(ROOT / MANIFEST_NAME)
    assert manifest is not None
    committed = manifest.order_edges()
    assert observed == committed
    assert find_cycle(committed) is None
