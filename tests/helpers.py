"""Shared test helpers (analog of /root/reference/test/helpers.js)."""

import automerge_tpu as am


def equals_one_of(actual, *expected):
    """Assert `actual` deep-equals one of `expected` — used where the outcome
    legitimately depends on actor-ID ordering, followed by an assertion that
    all replicas agree."""
    for candidate in expected:
        if am.equals(actual, candidate):
            return
    raise AssertionError(f"{actual!r} is none of {expected!r}")


def counter_uuids(prefix=""):
    """Deterministic uuid factory: prefix1, prefix2, ..."""
    state = {"n": 0}

    def factory():
        state["n"] += 1
        return f"{prefix}{state['n']:04d}"
    return factory
