"""Observability: counters, structured spans, watchdog, exporters."""

import json
import logging
import threading
import time

import pytest

import automerge_tpu as am
from automerge_tpu import metrics


def test_counters_track_applied_changes():
    metrics.reset()
    s = am.change(am.init(), lambda d: d.__setitem__("a", 1))
    s = am.change(s, lambda d: am.assign(d, {"b": 2, "c": 3}))
    snap = metrics.snapshot()
    assert snap["core_changes_applied"] == 2
    assert snap["core_ops_applied"] == 3
    assert snap["core_diffs_emitted"] >= 3


def test_engine_counters():
    metrics.reset()
    from automerge_tpu.engine.batchdoc import apply_batch
    s = am.change(am.init("A"), lambda d: d.__setitem__("x", 1))
    apply_batch([s._doc.opset.get_missing_changes({})])
    snap = metrics.snapshot()
    assert snap["engine_docs_reconciled"] == 1
    assert snap["engine_ops_reconciled"] == 1
    assert snap["engine_reconcile_count"] == 1
    assert snap["engine_reconcile_s"] > 0


def test_trace_context_manager():
    metrics.reset()
    with metrics.trace("custom_phase"):
        pass
    snap = metrics.snapshot()
    assert snap["custom_phase_count"] == 1
    assert "custom_phase_s" in snap


def test_reset():
    metrics.reset()
    am.change(am.init(), lambda d: d.__setitem__("a", 1))
    metrics.reset()
    assert metrics.snapshot() == {}


# -- structured tracer ------------------------------------------------------


def test_trace_records_timing_on_exception():
    metrics.reset()
    with pytest.raises(ValueError):
        with metrics.trace("failing_phase"):
            raise ValueError("boom")
    snap = metrics.snapshot()
    assert snap["failing_phase_count"] == 1
    assert "failing_phase_s" in snap


def test_span_nesting_records_depth_and_parent():
    metrics.reset()
    with metrics.trace("outer"):
        with metrics.trace("inner"):
            stacks = metrics.span_stacks()
    spans = {s["name"]: s for s in metrics.recent_spans()}
    assert spans["inner"]["depth"] == 1
    assert spans["inner"]["parent"] == "outer"
    assert spans["outer"]["depth"] == 0
    assert spans["outer"]["parent"] is None
    # while both were active, the stack showed the nesting
    (stack,) = stacks.values()
    assert stack[0].startswith("outer(") and stack[1].startswith("inner(")


def test_labeled_counters_and_spans():
    metrics.reset()
    metrics.bump("engine_kernels_dispatched", kernel="apply_doc")
    metrics.bump("engine_kernels_dispatched", 2, kernel="apply_final")
    with metrics.trace("sync_round_flush", shard="3"):
        pass
    snap = metrics.snapshot()
    assert snap["engine_kernels_dispatched{kernel=apply_doc}"] == 1
    assert snap["engine_kernels_dispatched{kernel=apply_final}"] == 2
    assert snap["sync_round_flush{shard=3}_count"] == 1
    assert "sync_round_flush{shard=3}_s" in snap


def test_trace_budget_post_hoc_flag():
    metrics.reset()
    with metrics.trace("slow_span", budget_s=0.0001):
        time.sleep(0.01)
    snap = metrics.snapshot()
    assert snap["obs_budget_exceeded{name=slow_span}"] == 1


def test_watchdog_fires_with_span_stack_diagnosis(caplog):
    metrics.reset()
    with caplog.at_level(logging.WARNING, "automerge_tpu.metrics"):
        with metrics.watchdog("stuck_region", budget_s=0.05):
            with metrics.trace("rows_hashes"):
                time.sleep(0.3)
    snap = metrics.snapshot()
    assert snap["obs_watchdog_fired{name=stuck_region}"] == 1
    (event,) = metrics.watchdog_events()
    assert event["name"] == "stuck_region"
    # the diagnosis names the active span stack, watched region included
    (stack,) = event["spans"].values()
    assert stack[0].startswith("stuck_region(")
    assert stack[1].startswith("rows_hashes(")
    assert any("watchdog 'stuck_region'" in r.getMessage()
               and "rows_hashes(" in r.getMessage()
               for r in caplog.records)


def test_watchdog_quiet_inside_budget():
    metrics.reset()
    with metrics.watchdog("fast_region", budget_s=30.0):
        pass
    assert metrics.watchdog_events() == []
    assert "obs_watchdog_fired{name=fast_region}" not in metrics.snapshot()


# -- exporters --------------------------------------------------------------


def test_snapshot_roundtrips_through_json():
    metrics.reset()
    s = am.change(am.init(), lambda d: d.__setitem__("a", 1))
    am.merge(am.init("other"), s)
    metrics.bump("engine_kernels_dispatched", kernel="apply_doc")
    metrics.observe("sync_round_seconds", 0.25)
    with metrics.trace("outer"):
        pass
    snap = metrics.snapshot()
    assert json.loads(json.dumps(snap)) == snap


def test_prometheus_exposition():
    metrics.reset()
    metrics.bump("sync_frames_received", 3)
    metrics.bump("engine_kernels_dispatched", kernel="apply_doc")
    metrics.gauge("core_queue_depth", 2)
    metrics.observe("sync_round_seconds", 0.5)
    with metrics.trace("engine_reconcile"):
        pass
    text = metrics.prometheus()
    assert "# TYPE amtpu_sync_frames_received counter" in text
    assert "amtpu_sync_frames_received 3" in text
    assert 'amtpu_engine_kernels_dispatched{kernel="apply_doc"} 1' in text
    assert "# TYPE amtpu_core_queue_depth gauge" in text
    assert "amtpu_sync_round_seconds_count 1" in text
    assert "amtpu_sync_round_seconds_sum 0.5" in text
    assert "amtpu_engine_reconcile_seconds_total" in text


def test_pre_rename_alias_names_are_gone():
    """The one-release alias window is over: snapshots carry canonical
    names only, and the alias table is empty (extension code probing
    metrics.ALIASES keeps working, it just finds nothing)."""
    metrics.reset()
    assert metrics.ALIASES == {}
    metrics.bump("sync_frames_received")
    snap = metrics.snapshot()
    assert snap["sync_frames_received"] == 1
    assert "wire_frames_received" not in snap


# -- thread safety ----------------------------------------------------------


def test_thread_safety_under_concurrent_bump_and_trace():
    metrics.reset()
    n_threads, n_iter = 8, 300
    barrier = threading.Barrier(n_threads)

    def worker(k):
        barrier.wait()
        for _ in range(n_iter):
            metrics.bump("core_changes_applied")
            metrics.bump("engine_kernels_dispatched", kernel=f"k{k % 2}")
            with metrics.trace("sync_round_flush", shard=str(k % 2)):
                metrics.observe("sync_round_seconds", 0.001)

    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = metrics.snapshot()
    total = n_threads * n_iter
    assert snap["core_changes_applied"] == total
    assert (snap["engine_kernels_dispatched{kernel=k0}"]
            + snap["engine_kernels_dispatched{kernel=k1}"]) == total
    assert (snap["sync_round_flush{shard=0}_count"]
            + snap["sync_round_flush{shard=1}_count"]) == total
    assert snap["sync_round_seconds_count"] == total
    assert not metrics.span_stacks()   # every span popped


def test_metrics_pull_message_roundtrip():
    """The METRICS message type: a peer pulls this node's snapshot over the
    ordinary Connection protocol."""
    from automerge_tpu.sync.connection import Connection
    from automerge_tpu.sync.docset import DocSet

    metrics.reset()
    metrics.bump("core_changes_applied", 7)
    a_out, b_out = [], []
    conn_a = Connection(DocSet(), a_out.append)
    conn_b = Connection(DocSet(), b_out.append)
    conn_a.request_metrics()
    (pull,) = a_out
    assert pull["metrics"] == "pull"
    assert "trace" in pull            # cross-replica trace context header
    conn_b.receive_msg(pull)          # serves its snapshot
    (resp,) = b_out
    assert resp["metrics"] == "snapshot"
    conn_a.receive_msg(resp)
    assert conn_a.peer_metrics["core_changes_applied"] == 7
    assert metrics.snapshot()["sync_metrics_pulls"] == 1
