"""Observability counters and trace hooks."""

import automerge_tpu as am
from automerge_tpu import metrics


def test_counters_track_applied_changes():
    metrics.reset()
    s = am.change(am.init(), lambda d: d.__setitem__("a", 1))
    s = am.change(s, lambda d: am.assign(d, {"b": 2, "c": 3}))
    snap = metrics.snapshot()
    assert snap["changes_applied"] == 2
    assert snap["ops_applied"] == 3
    assert snap["diffs_emitted"] >= 3


def test_engine_counters():
    metrics.reset()
    from automerge_tpu.engine.batchdoc import apply_batch
    s = am.change(am.init("A"), lambda d: d.__setitem__("x", 1))
    apply_batch([s._doc.opset.get_missing_changes({})])
    snap = metrics.snapshot()
    assert snap["engine_docs_reconciled"] == 1
    assert snap["engine_ops_reconciled"] == 1
    assert snap["engine_reconcile_count"] == 1
    assert snap["engine_reconcile_s"] > 0


def test_trace_context_manager():
    metrics.reset()
    with metrics.trace("custom_phase"):
        pass
    snap = metrics.snapshot()
    assert snap["custom_phase_count"] == 1
    assert "custom_phase_s" in snap


def test_reset():
    metrics.reset()
    am.change(am.init(), lambda d: d.__setitem__("a", 1))
    metrics.reset()
    assert metrics.snapshot() == {}
