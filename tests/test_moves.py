"""The move plane (ISSUE 15 / r16): move-as-atom reparenting for maps and
lists with deterministic batched cycle resolution.

Pins, in rough dependency order:
- map/list move semantics through the interpretive core (winner rule,
  cycle survivor determinism, ghost anchoring, fallback chains);
- delivery-order independence (the whole point of a CRDT op class) via
  seeded storms and a hypothesis driver over random two-writer programs;
- walk/host/XLA/pallas resolution parity on packed realms;
- the batched admission plane == the per-op path, including the
  kernel-routed configuration;
- wire/storage ride-along (JSON, binary, columnar frames, the native
  C++ parse) and engine-hash convergence across services;
- a two-service fleet storm with a green ConvergenceAuditor;
- the frontend proxy API;
- the experimental_dense non-CPU import guard (ROADMAP carried debt).
"""

import json
import random

import numpy as np
import pytest

try:
    from hypothesis import given, settings
    from hypothesis import strategies as st
    HAVE_HYPOTHESIS = True
except ImportError:   # the seeded fallback driver below still runs
    HAVE_HYPOTHESIS = False

import automerge_tpu.api as am
from automerge_tpu.core.change import Change, Op
from automerge_tpu.core.ids import ROOT_ID
from automerge_tpu.core.moves import (MoveProblem, _resolve_walk,
                                      try_apply_move_batch)
from automerge_tpu.core.opset import OpSet
from automerge_tpu.frontend.materialize import materialize_root


def mat(opset):
    return materialize_root("t", opset)


def mat_j(opset):
    return json.dumps(mat(opset), sort_keys=True, default=str)


def base_doc():
    """root { k0..k4: maps f0..f4, L: [v1..v5] } in one change by A."""
    ops = []
    for i in range(5):
        ops.append(Op("makeMap", f"f{i}"))
        ops.append(Op("link", ROOT_ID, key=f"k{i}", value=f"f{i}"))
    ops.append(Op("makeList", "L"))
    ops.append(Op("link", ROOT_ID, key="L", value="L"))
    prev = "_head"
    for e in range(1, 6):
        ops.append(Op("ins", "L", key=prev, elem=e))
        ops.append(Op("set", "L", key=f"A:{e}", value=f"v{e}"))
        prev = f"A:{e}"
    chs = [Change("A", 1, {}, ops)]
    opset, _ = OpSet.init().add_changes(chs)
    return opset, chs


# ---------------------------------------------------------------------------
# map realm semantics


def test_map_move_reparents_and_empties_old_location():
    opset, _ = base_doc()
    out, diffs = opset.add_changes([Change("A", 2, {}, [
        Op("move", "f1", key="sub", value="f0")])])
    m = mat(out)
    assert "k0" not in m
    assert m["k1"]["sub"] == {}
    # both the removal and the placement emitted standard map records
    acts = {(d["action"], d["obj"]) for d in diffs}
    assert ("remove", ROOT_ID) in acts
    assert ("set", "f1") in acts


def test_map_move_chain_latest_wins():
    opset, _ = base_doc()
    out, _ = opset.add_changes([
        Change("A", 2, {}, [Op("move", "f1", key="s", value="f0")]),
        Change("A", 3, {}, [Op("move", "f2", key="s", value="f0")])])
    m = mat(out)
    assert "k0" not in m and "s" not in m["k1"]
    assert m["k2"]["s"] == {}


def test_concurrent_map_moves_same_child_highest_actor_wins_both_orders():
    opset, _ = base_doc()
    mb = Change("B", 1, {"A": 1}, [Op("move", "f1", key="b", value="f0")])
    mc = Change("C", 1, {"A": 1}, [Op("move", "f2", key="c", value="f0")])
    r1, _ = opset.add_changes([mb])
    r1, _ = r1.add_changes([mc])
    r2, _ = opset.add_changes([mc])
    r2, _ = r2.add_changes([mb])
    assert mat_j(r1) == mat_j(r2)
    m = mat(r1)
    assert m["k2"]["c"] == {}          # C > B
    assert "b" not in m["k1"] and "k0" not in m


def test_concurrent_cycle_survivor_deterministic_both_orders():
    opset, _ = base_doc()
    # B: f0 under f1; C: f1 under f0 — a 2-cycle. C wins (higher actor),
    # B's move drops, f0 falls back to its base link at root.k0.
    mb = Change("B", 1, {"A": 1}, [Op("move", "f1", key="in", value="f0")])
    mc = Change("C", 1, {"A": 1}, [Op("move", "f0", key="in", value="f1")])
    r1, _ = opset.add_changes([mb])
    r1, _ = r1.add_changes([mc])
    r2, _ = opset.add_changes([mc])
    r2, _ = r2.add_changes([mb])
    assert mat_j(r1) == mat_j(r2)
    m = mat(r1)
    assert m["k0"] == {"in": {}}       # f1 lives under f0
    assert "k1" not in m               # f1 moved away from root
    # never duplicated, never orphaned: f1 appears exactly once
    assert mat_j(r1).count('"in"') == 1


def test_three_cycle_resolves_deterministically():
    opset, _ = base_doc()
    moves = [Change("B", 1, {"A": 1},
                    [Op("move", "f1", key="m", value="f0")]),
             Change("C", 1, {"A": 1},
                    [Op("move", "f2", key="m", value="f1")]),
             Change("D", 1, {"A": 1},
                    [Op("move", "f0", key="m", value="f2")])]
    mats = set()
    for order in ([0, 1, 2], [2, 1, 0], [1, 0, 2]):
        cur = opset
        for i in order:
            cur, _ = cur.add_changes([moves[i]])
        mats.add(mat_j(cur))
    assert len(mats) == 1
    # the minimum-priority edge (actor B) dropped; its child is back at
    # the base link
    assert "k0" in mat(cur)


def test_move_wins_over_concurrent_dest_overwrite_rules():
    opset, _ = base_doc()
    # a causally-LATER set at the destination key kills the placement
    out, _ = opset.add_changes([
        Change("A", 2, {}, [Op("move", "f1", key="s", value="f0")]),
        Change("A", 3, {}, [Op("set", "f1", key="s", value=7)])])
    m = mat(out)
    assert m["k1"]["s"] == 7
    assert "k0" not in m               # the child stays gone (rm -rf)


def test_moved_child_keeps_concurrent_interior_edits():
    # the delete+reinsert emulation LOSES concurrent interior edits; the
    # move op must keep them — the capability headline
    opset, _ = base_doc()
    mv = Change("B", 1, {"A": 1}, [Op("move", "f1", key="s", value="f0")])
    ed = Change("C", 1, {"A": 1}, [Op("set", "f0", key="x", value=42)])
    r1, _ = opset.add_changes([mv])
    r1, _ = r1.add_changes([ed])
    r2, _ = opset.add_changes([ed])
    r2, _ = r2.add_changes([mv])
    assert mat_j(r1) == mat_j(r2)
    assert mat(r1)["k1"]["s"] == {"x": 42}


# ---------------------------------------------------------------------------
# list realm semantics


def test_list_move_to_head_and_ghost_anchoring():
    opset, _ = base_doc()
    out, _ = opset.add_changes([Change("A", 2, {}, [
        Op("move", "L", key="_head", value="A:3", elem=9)])])
    assert list(mat(out)["L"]) == ["v3", "v1", "v2", "v4", "v5"]
    # ghost semantics: elements anchored after the moved one do NOT ride
    # along (the anchor relation is ordering, not containment)
    out2, _ = opset.add_changes([Change("A", 2, {}, [
        Op("move", "L", key="A:3", value="A:2", elem=9)])])
    assert list(mat(out2)["L"]) == ["v1", "v3", "v2", "v4", "v5"]


def test_concurrent_list_moves_same_element_converge_both_orders():
    opset, _ = base_doc()
    mb = Change("B", 1, {"A": 1},
                [Op("move", "L", key="_head", value="A:2", elem=9)])
    mc = Change("C", 1, {"A": 1},
                [Op("move", "L", key="A:5", value="A:2", elem=9)])
    r1, _ = opset.add_changes([mb])
    r1, _ = r1.add_changes([mc])
    r2, _ = opset.add_changes([mc])
    r2, _ = r2.add_changes([mb])
    l1, l2 = list(mat(r1)["L"]), list(mat(r2)["L"])
    assert l1 == l2 == ["v1", "v3", "v4", "v5", "v2"]   # C wins


def test_placement_aware_follower_rides_the_next_move():
    opset, _ = base_doc()
    # move v2 after v5, then type w right after it, then move v2 to the
    # head: the placement-aware insert follows
    cur, _ = opset.add_changes([Change("A", 2, {}, [
        Op("move", "L", key="A:5", value="A:2", elem=9)])])
    cur, _ = cur.add_changes([Change("A", 3, {}, [
        Op("ins", "L", key="A:2", elem=10),
        Op("set", "L", key="A:10", value="w")])])
    assert list(mat(cur)["L"]) == ["v1", "v3", "v4", "v5", "v2", "w"]
    cur, _ = cur.add_changes([Change("A", 4, {}, [
        Op("move", "L", key="_head", value="A:2", elem=11)])])
    assert list(mat(cur)["L"]) == ["v2", "w", "v1", "v3", "v4", "v5"]


def test_move_of_tombstone_and_concurrent_resurrection():
    opset, _ = base_doc()
    # B deletes v2 while C moves it to the head: the concurrent move
    # repositions the tombstone; a concurrent set resurrects it THERE
    dl = Change("B", 1, {"A": 1}, [Op("del", "L", key="A:2")])
    mv = Change("C", 1, {"A": 1},
                [Op("move", "L", key="_head", value="A:2", elem=9)])
    rs = Change("D", 1, {"A": 1}, [Op("set", "L", key="A:2", value="R")])
    orders = [(dl, mv, rs), (rs, mv, dl), (mv, dl, rs)]
    mats = set()
    for chs in orders:
        cur = opset
        for c in chs:
            cur, _ = cur.add_changes([c])
        mats.add(mat_j(cur))
    assert len(mats) == 1
    assert list(mat(cur)["L"]) == ["R", "v1", "v3", "v4", "v5"]


def test_move_validation_errors():
    opset, _ = base_doc()
    with pytest.raises(ValueError):
        opset.add_changes([Change("A", 2, {}, [
            Op("move", "L", key="_head", value="A:99", elem=9)])])
    with pytest.raises(ValueError):
        opset.add_changes([Change("A", 2, {}, [
            Op("move", "L", key="A:77", value="A:2", elem=9)])])
    with pytest.raises(ValueError):
        opset.add_changes([Change("A", 2, {}, [
            Op("move", "f0", key="x", value="nosuch")])])
    with pytest.raises(ValueError):
        opset.add_changes([Change("A", 2, {}, [
            Op("move", "f0", key="x", value=ROOT_ID)])])


# ---------------------------------------------------------------------------
# delivery-order independence: seeded + hypothesis drivers


def _storm(rng, actor, k, elem_base):
    chs = []
    deps = {"A": 1}
    ec = elem_base
    seq = 0
    for _ in range(k):
        if rng.random() < 0.5:
            child = f"f{rng.randrange(5)}"
            dest = f"f{rng.randrange(5)}"
            if dest == child:
                dest = ROOT_ID
            op = Op("move", dest, key=f"m{rng.randrange(3)}", value=child)
        else:
            e = rng.randrange(1, 6)
            a = rng.randrange(0, 6)
            anchor = "_head" if a == 0 else f"A:{a}"
            if anchor == f"A:{e}":
                anchor = "_head"
            ec += 1
            op = Op("move", "L", key=anchor, value=f"A:{e}", elem=ec)
        seq += 1
        chs.append(Change(actor, seq, dict(deps), [op]))
        deps = {actor: seq}
    return chs


@pytest.mark.parametrize("seed", [0, 1, 7, 23])
def test_two_writer_storm_three_delivery_orders_converge(seed):
    rng = random.Random(seed)
    opset, _ = base_doc()
    sb = _storm(rng, "B", rng.randrange(2, 6), 100)
    sc = _storm(rng, "C", rng.randrange(2, 6), 200)
    r1 = opset
    for c in sb + sc:
        r1, _ = r1.add_changes([c])
    r2 = opset
    for c in sc + sb:
        r2, _ = r2.add_changes([c])
    mix, ib, ic = [], 0, 0
    while ib < len(sb) or ic < len(sc):
        if ib < len(sb) and (ic >= len(sc) or rng.random() < 0.5):
            mix.append(sb[ib]); ib += 1
        else:
            mix.append(sc[ic]); ic += 1
    r3 = opset
    for c in mix:
        r3, _ = r3.add_changes([c])
    assert mat_j(r1) == mat_j(r2) == mat_j(r3)


def _check_storm_converges(seed):
    rng = random.Random(seed)
    opset, _ = base_doc()
    sb = _storm(rng, "B", rng.randrange(1, 5), 100)
    sc = _storm(rng, "C", rng.randrange(1, 5), 200)
    r1 = opset
    for c in sb + sc:
        r1, _ = r1.add_changes([c])
    r2 = opset
    for c in sc + sb:
        r2, _ = r2.add_changes([c])
    assert mat_j(r1) == mat_j(r2)


if HAVE_HYPOTHESIS:
    @settings(max_examples=25, deadline=None)
    @given(st.integers(0, 10**9))
    def test_hypothesis_move_storms_converge(seed):
        _check_storm_converges(seed)
else:
    @pytest.mark.parametrize("seed", list(range(1000, 1025)))
    def test_hypothesis_move_storms_converge(seed):
        _check_storm_converges(seed)


# ---------------------------------------------------------------------------
# kernel parity: walk == host numpy == XLA == pallas(interpret)


def _rand_problem(rng, n_nodes, n_moves):
    p = MoveProblem()
    for i in range(n_nodes):
        p.slot(f"n{i}")
    for s in range(n_nodes):
        p.base[s] = rng.randrange(-1, s) if s else -1
    prios = rng.sample(range(10_000), n_moves)
    by_node = {}
    for m in range(n_moves):
        s = rng.randrange(n_nodes)
        by_node.setdefault(s, []).append(
            (prios[m] // 40, ("a%02d" % (prios[m] % 40), "v"),
             rng.randrange(-1, n_nodes)))
    for s, cl in by_node.items():
        cl.sort(key=lambda t: (t[0], t[1]), reverse=True)
        p.cands[s] = [(hi, lo, tgt, None) for (hi, lo, tgt) in cl]
        p.moved.append(s)
    return p


def test_kernel_triple_parity_on_random_realms():
    from automerge_tpu.engine.move_kernels import (
        pack_moves, resolve_moves, resolve_moves_host,
        resolve_moves_pallas)

    rng = random.Random(4242)
    probs = [_rand_problem(rng, rng.randrange(2, 48), rng.randrange(0, 40))
             for _ in range(20)]
    packed = pack_moves(probs)
    host = resolve_moves_host(packed)
    xla = {k: np.asarray(v) for k, v in
           resolve_moves(packed["nodes"], packed["cands"]).items()}
    pls = resolve_moves_pallas(packed, interpret=True)
    for i, p in enumerate(probs):
        ptr_walk, dropped_walk = _resolve_walk(p)
        n = len(p.nodes)
        assert list(host["ptr"][i][:n]) == ptr_walk
        assert int(host["dropped"][i]) == dropped_walk
    for k in ("ptr", "parent", "dropped"):
        assert (host[k] == xla[k]).all(), k
        assert (host[k] == pls[k]).all(), k
    assert (host["hash"] == xla["hash"]).all()
    assert (host["hash"] == pls["hash"]).all()


def test_kernel_drops_min_priority_edge_per_cycle():
    from automerge_tpu.engine.move_kernels import (pack_moves,
                                                   resolve_moves_host)
    p = MoveProblem()
    for i in range(4):
        p.slot(i)
        p.base[i] = -1
    # 0 -> 1 (prio 9) and 1 -> 0 (prio 5): cycle; the prio-5 edge drops
    p.cands[0] = [(9, ("b", "x"), 1, None)]
    p.cands[1] = [(5, ("a", "y"), 0, None)]
    p.moved = [0, 1]
    out = resolve_moves_host(pack_moves([p]))
    assert list(out["ptr"][0][:4]) == [0, 1, 0, 0]
    assert int(out["dropped"][0]) == 1
    assert out["resolved"][0][:4].all()
    ptr_walk, dropped = _resolve_walk(p)
    assert ptr_walk == [0, 1, 0, 0] and dropped == 1


def test_pallas_node_cap_is_loud():
    from automerge_tpu.engine.move_kernels import (PALLAS_MAX_NODES,
                                                   move_round_pallas)
    n = PALLAS_MAX_NODES * 2
    nodes = np.zeros((1, 4, n), np.int32)
    cands = np.zeros((1, 3, 128), np.int32)
    ptr = np.zeros((1, n), np.int32)
    with pytest.raises(ValueError, match="caps at"):
        move_round_pallas(nodes, cands, ptr, interpret=True)


# ---------------------------------------------------------------------------
# the batched admission plane


def _concurrent_storm(n_objs, k, writers=5):
    ops = []
    for i in range(n_objs):
        ops.append(Op("makeMap", f"o{i:04d}"))
        ops.append(Op("link", ROOT_ID, key=f"o{i:04d}", value=f"o{i:04d}"))
    base, _ = OpSet.init().add_changes([Change("A", 1, {}, ops)])
    rng = random.Random(99)
    movers = rng.sample(range(n_objs), k)
    chs = []
    wseq = {}
    for j, m in enumerate(movers):
        dst = rng.randrange(n_objs)
        while dst == m:
            dst = rng.randrange(n_objs)
        w = f"w{j % writers}"
        s = wseq.get(w, 0) + 1
        wseq[w] = s
        deps = {"A": 1}
        if s > 1:
            deps[w] = s - 1
        chs.append(Change(w, s, deps,
                          [Op("move", f"o{dst:04d}", key=f"s{j}",
                              value=f"o{m:04d}")]))
    return base, chs


def test_move_batch_plane_equals_per_op_path(monkeypatch):
    base, chs = _concurrent_storm(48, 40)
    perop = base
    for c in chs:
        perop, _ = perop.add_changes([c])
    batched, diffs = base.add_changes(chs, move_batch=True)
    assert diffs and diffs[0]["action"] == "batch"
    assert mat_j(perop) == mat_j(batched)
    # kernel-routed configuration resolves identically
    monkeypatch.setenv("AMTPU_MOVE_KERNEL_MIN", "4")
    routed, _ = base.add_changes(chs, move_batch=True)
    assert mat_j(routed) == mat_j(perop)


def test_move_batch_classifies_sequential_vs_concurrent():
    from automerge_tpu.utils import metrics
    base, chs = _concurrent_storm(40, 34)
    snap0 = metrics.snapshot()
    out, _ = base.add_changes(chs, move_batch=True)
    snap = metrics.snapshot()
    conc = (snap.get("sync_move_ops_concurrent", 0)
            - snap0.get("sync_move_ops_concurrent", 0))
    seqn = (snap.get("sync_move_ops_sequential", 0)
            - snap0.get("sync_move_ops_sequential", 0))
    # first change of each writer set covers the frontier only for the
    # very first one; everything else is cross-writer concurrent
    assert seqn >= 1
    assert conc + seqn == 34


def test_move_batch_falls_back_on_mixed_ops():
    base, chs = _concurrent_storm(40, 34)
    mixed = chs + [Change("z", 1, {"A": 1},
                          [Op("set", "o0000", key="p", value=1)])]
    out, diffs = base.add_changes(mixed, move_batch=True)
    # ineligible batch fell through to the generic path: per-op records
    assert all(d.get("action") != "batch" for d in diffs)
    perop = base
    for c in mixed:
        perop, _ = perop.add_changes([c])
    assert mat_j(out) == mat_j(perop)


# ---------------------------------------------------------------------------
# wire / storage / engine ride-along


def test_wire_and_storage_roundtrips_with_moves():
    from automerge_tpu.native.wire import (changes_to_columns,
                                           parse_changes_json)
    from automerge_tpu.sync.frames import bytes_to_columns, columns_to_bytes

    opset, chs = base_doc()
    mv = [Change("A", 2, {}, [Op("move", "f1", key="s", value="f0"),
                              Op("move", "L", key="_head", value="A:3",
                                 elem=9)])]
    all_chs = chs + mv
    # columnar frame roundtrip
    cols = bytes_to_columns(columns_to_bytes(changes_to_columns(all_chs)))
    assert cols.to_changes() == all_chs
    # native C++ JSON parse agrees with the Python object form
    raw = json.dumps([c.to_dict() for c in all_chs])
    ncols = parse_changes_json(raw)
    if ncols is not None:
        assert ncols.to_changes() == all_chs
    # api save/load (JSON) preserves semantics
    r1, _ = OpSet.init().add_changes(all_chs)
    d = am.init("x")
    from automerge_tpu.frontend.materialize import apply_changes_to_doc
    d = apply_changes_to_doc(d, d._doc.opset, all_chs, incremental=False)
    r2 = am.load(am.save(d), "y")
    assert am.inspect(r2) == mat(r1)


def test_binary_storage_roundtrip_with_moves():
    from automerge_tpu.storage import load_binary, save_binary
    d = am.init("u")
    d = am.change(d, lambda x: x.update({"a": {"n": 1}, "b": {},
                                         "l": [1, 2, 3]}))
    d = am.change(d, lambda x: x["a"] if False else x.move("a", x["b"]))
    d = am.change(d, lambda x: x["l"].move(2, 0))
    r = load_binary(save_binary(d), "v")
    assert am.inspect(r) == am.inspect(d) == {
        "b": {"a": {"n": 1}}, "l": [3, 1, 2]}


def test_engine_rows_hash_convergence_with_moves():
    from automerge_tpu.engine.resident_rows import ResidentRowsDocSet
    opset, chs = base_doc()
    sb = _storm(random.Random(5), "B", 4, 100)
    sc = _storm(random.Random(6), "C", 4, 200)
    e1 = ResidentRowsDocSet(["d"])
    e1.apply_rounds([{"d": chs + sb + sc}])
    e2 = ResidentRowsDocSet(["d"])
    e2.apply_rounds([{"d": chs}, {"d": sc}, {"d": sb}])
    assert e1.hashes()[0] == e2.hashes()[0]


def test_bulk_build_refuses_moves_and_falls_back():
    from automerge_tpu.core.bulkload import try_bulk_build
    from automerge_tpu.native.wire import changes_to_columns
    opset, chs = base_doc()
    mv = [Change("A", 2, {}, [Op("move", "f1", key="s", value="f0")])]
    assert try_bulk_build(changes_to_columns(chs + mv)) is None
    # and load() still yields correct state via the interpretive fallback
    text = json.dumps([c.to_dict() for c in chs + mv])
    d = am.load(text, "z")
    assert am.inspect(d)["k1"]["s"] == {}


def test_two_service_fleet_move_storm_auditor_green():
    from automerge_tpu.sync.audit import ConvergenceAuditor
    from automerge_tpu.sync.connection import Connection
    from automerge_tpu.sync.service import EngineDocSet

    sa, sb = EngineDocSet(backend="rows"), EngineDocSet(backend="rows")
    qa, qb = [], []
    ca = Connection(sa, qa.append, wire="columnar")
    cb = Connection(sb, qb.append, wire="columnar")
    ca.open()
    cb.open()

    def pump():
        for _ in range(150):
            moved = False
            while qa:
                cb.receive_msg(qa.pop(0)); moved = True
            while qb:
                ca.receive_msg(qb.pop(0)); moved = True
            if not moved:
                return

    opset, chs = base_doc()
    sa.apply_changes("d", chs)
    pump()
    for c in _storm(random.Random(11), "B", 6, 100):
        sa.apply_changes("d", [c])
    for c in _storm(random.Random(12), "C", 6, 200):
        sb.apply_changes("d", [c])
    pump()
    assert sa.hashes() == sb.hashes()
    assert sa.materialize("d") == sb.materialize("d")
    aud = ConvergenceAuditor(sa, ca, period_s=0)
    aud.audit_once()
    pump()
    assert aud.rounds_clean == 1 and aud.divergences == []
    ca.close()
    cb.close()


# ---------------------------------------------------------------------------
# frontend API


def test_proxy_move_map_and_list():
    d = am.init("u1")
    d = am.change(d, lambda x: x.update(
        {"tree": {"a": {"f": 1}, "b": {}}, "l": ["a", "b", "c", "d"]}))
    d = am.change(d, lambda x: x["tree"].move("a", x["tree"]["b"]))
    d = am.change(d, lambda x: x["l"].move(3, 1))
    assert am.inspect(d) == {"tree": {"b": {"a": {"f": 1}}},
                             "l": ["a", "d", "b", "c"]}


def test_proxy_move_refuses_local_cycle_and_bad_args():
    d = am.init("u1")
    d = am.change(d, lambda x: x.update({"a": {"b": {}}}))
    with pytest.raises(ValueError, match="own subtree"):
        am.change(d, lambda x: x["a"].move("b", x["a"]["b"]))
    with pytest.raises(TypeError):
        am.change(d, lambda x: x["a"].move("b", "not-a-proxy"))
    d2 = am.change(d, lambda x: x.__setitem__("l", [1, 2]))
    with pytest.raises(IndexError):
        am.change(d2, lambda x: x["l"].move(0, 5))


def test_move_merges_across_replicas_through_api():
    d = am.init("u1")
    d = am.change(d, lambda x: x.update({"a": {"n": 1}, "b": {}}))
    e = am.merge(am.init("u2"), d)
    d = am.change(d, lambda x: x.move("a", x["b"]))
    e = am.change(e, lambda x: x["a"].__setitem__("n", 5))
    d2 = am.merge(d, e)
    e2 = am.merge(e, d)
    assert am.inspect(d2) == am.inspect(e2) == {"b": {"a": {"n": 5}}}


# ---------------------------------------------------------------------------
# experimental_dense guard (ROADMAP carried debt)


def test_experimental_dense_refuses_non_cpu_backend(monkeypatch):
    import importlib
    import sys

    import jax

    import automerge_tpu.engine.experimental_dense as xd
    # importable on CPU (the product state of this image)
    assert hasattr(xd, "reconcile_dense") or hasattr(xd, "dense_cost")
    monkeypatch.delenv("AMTPU_ALLOW_DENSE_ON_DEVICE", raising=False)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    sys.modules.pop("automerge_tpu.engine.experimental_dense")
    try:
        with pytest.raises(NotImplementedError, match="quarantined"):
            importlib.import_module(
                "automerge_tpu.engine.experimental_dense")
        # the opt-in env knob lets a hardware-validation session through
        monkeypatch.setenv("AMTPU_ALLOW_DENSE_ON_DEVICE", "1")
        mod = importlib.import_module(
            "automerge_tpu.engine.experimental_dense")
        assert hasattr(mod, "dense_cost")
    finally:
        sys.modules.pop("automerge_tpu.engine.experimental_dense", None)
    monkeypatch.undo()
    importlib.import_module("automerge_tpu.engine.experimental_dense")


# ---------------------------------------------------------------------------
# post-review regression pins (r16 review findings, all applied)


def test_move_batch_plane_list_realm(monkeypatch):
    """Review find #1: an all-LIST-move batch crashed the deferred index
    rebuild (rebuild_elem_ids without state). Pin the list-realm batch
    against the per-op path, walk- and kernel-routed."""
    ops = [Op("makeList", "L"), Op("link", ROOT_ID, key="l", value="L")]
    prev = "_head"
    for e in range(1, 13):
        ops.append(Op("ins", "L", key=prev, elem=e))
        ops.append(Op("set", "L", key=f"A:{e}", value=f"v{e}"))
        prev = f"A:{e}"
    base, _ = OpSet.init().add_changes([Change("A", 1, {}, ops)])
    rng = random.Random(17)
    chs = []
    wseq = {}
    ec = 100
    for j in range(40):
        w = f"w{j % 4}"
        s = wseq.get(w, 0) + 1
        wseq[w] = s
        deps = {"A": 1}
        if s > 1:
            deps[w] = s - 1
        e = rng.randrange(1, 13)
        a = rng.randrange(0, 13)
        anchor = "_head" if a == 0 else f"A:{a}"
        if anchor == f"A:{e}":
            anchor = "_head"
        ec += 1
        chs.append(Change(w, s, deps,
                          [Op("move", "L", key=anchor, value=f"A:{e}",
                              elem=ec)]))
    perop = base
    for c in chs:
        perop, _ = perop.add_changes([c])
    batched, diffs = base.add_changes(chs, move_batch=True)
    assert diffs and diffs[0]["action"] == "batch"
    assert mat_j(batched) == mat_j(perop)
    monkeypatch.setenv("AMTPU_MOVE_KERNEL_MIN", "4")
    routed, _ = base.add_changes(chs, move_batch=True)
    assert mat_j(routed) == mat_j(perop)


def test_local_preview_move_survives_kernel_routing(monkeypatch):
    """Review find #2: a local unstamped move previews with a 2^62
    priority sentinel, which overflowed the int32 pack lanes once the
    realm was big enough to route through the kernels. Priorities now
    rank-compress at pack time."""
    monkeypatch.setenv("AMTPU_MOVE_KERNEL_MIN", "1")
    d = am.init("u")
    d = am.change(d, lambda x: x.update(
        {f"o{i}": {} for i in range(4)} | {"dest": {}}))
    for i in range(3):
        d = am.change(d, lambda x, i=i: x.move(f"o{i}", x["dest"]))
    assert set(am.inspect(d)["dest"]) == {"o0", "o1", "o2"}


def test_move_undo_redo_roundtrip():
    """Review find #3: moves recorded no undo ops — can_undo lied and
    undo silently kept the move applied."""
    d = am.init("u")
    d = am.change(d, lambda x: x.update({"a": {"n": 1}, "b": {},
                                         "l": ["x", "y", "z"]}))
    d = am.change(d, lambda x: x.move("a", x["b"]))
    assert am.inspect(d) == {"b": {"a": {"n": 1}}, "l": ["x", "y", "z"]}
    d = am.undo(d)
    assert am.inspect(d) == {"a": {"n": 1}, "b": {}, "l": ["x", "y", "z"]}
    d = am.redo(d)
    assert am.inspect(d) == {"b": {"a": {"n": 1}}, "l": ["x", "y", "z"]}
    d = am.change(d, lambda x: x["l"].move(2, 0))
    assert am.inspect(d)["l"] == ["z", "x", "y"]
    d = am.undo(d)
    assert am.inspect(d)["l"] == ["x", "y", "z"]
    d = am.redo(d)
    assert am.inspect(d)["l"] == ["z", "x", "y"]


def test_cycle_drop_metric_counts_once_not_per_admission():
    """Review find #4: a standing resolved cycle re-counted on every
    later unrelated admission; the metric now reports the DELTA vs the
    realm's previous resolution."""
    from automerge_tpu.utils import metrics
    opset, _ = base_doc()
    snap0 = metrics.snapshot().get("sync_move_cycles_dropped", 0)
    cur, _ = opset.add_changes([Change("B", 1, {"A": 1}, [
        Op("move", "f1", key="in", value="f0")])])
    cur, _ = cur.add_changes([Change("C", 1, {"A": 1}, [
        Op("move", "f0", key="in", value="f1")])])
    after_cycle = metrics.snapshot().get("sync_move_cycles_dropped", 0)
    assert after_cycle - snap0 == 1
    for k in range(3):   # unrelated move traffic over the same realm
        cur, _ = cur.add_changes([Change("D", k + 1,
                                         {"A": 1} if k == 0 else {"D": k},
                                         [Op("move", "f3", key=f"m{k}",
                                             value="f4")])])
    assert metrics.snapshot().get("sync_move_cycles_dropped", 0) \
        == after_cycle
