"""Cross-replica trace propagation: spans opened on one replica stitch to
the serving spans on its peers (ISSUE 2 acceptance: one sync round = one
stitched trace), and per-replica span buffers merge into one causally-
ordered timeline."""

from automerge_tpu import metrics
from automerge_tpu.core.change import Change, Op
from automerge_tpu.core.ids import ROOT_ID
from automerge_tpu.native.wire import changes_to_columns
from automerge_tpu.sync.connection import Connection
from automerge_tpu.sync.frames import pack_trace, unpack_trace
from automerge_tpu.sync.service import EngineDocSet


def _cols(actor, seq, key, value):
    return changes_to_columns([Change(
        actor=actor, seq=seq, deps={},
        ops=[Op("set", ROOT_ID, key=key, value=value)])])


def _pump(qa, ca, qb, cb, rounds=30):
    """Drain both in-memory queues until quiescent."""
    for _ in range(rounds):
        moved = False
        while qa:
            cb.receive_msg(qa.pop(0))
            moved = True
        while qb:
            ca.receive_msg(qb.pop(0))
            moved = True
        if not moved:
            return


def _pair():
    ea, eb = EngineDocSet(backend="rows"), EngineDocSet(backend="rows")
    qa, qb = [], []
    ca = Connection(ea, qa.append, wire="columnar")
    cb = Connection(eb, qb.append, wire="columnar")
    ca.open()
    cb.open()
    _pump(qa, ca, qb, cb)
    return ea, eb, qa, ca, qb, cb


# -- wire header ------------------------------------------------------------


def test_trace_header_roundtrip():
    ctx = {"tid": "aabbccdd00112233", "sid": "deadbeef"}
    assert unpack_trace(pack_trace(ctx)) == ctx
    # malformed / foreign values never break message handling
    assert unpack_trace(None) is None
    assert unpack_trace("") is None
    assert unpack_trace(7) is None
    assert unpack_trace("tidonly") == {"tid": "tidonly", "sid": None}


# -- the acceptance path: one sync round, one trace -------------------------


def test_sync_round_stitches_client_and_server_spans():
    """The ISSUE acceptance: after one sync round between two replicas,
    the sending replica's span and the receiving replica's serving span
    share a trace id, with the serve span parented under the send span."""
    metrics.reset()
    ea, eb, qa, ca, qb, cb = _pair()
    ea.apply_columns("doc1", _cols("A", 1, "x", 1))
    _pump(qa, ca, qb, cb)
    assert eb.hashes()["doc1"] == ea.hashes()["doc1"]

    spans = metrics.recent_spans()
    sends = {s["span_id"]: s for s in spans if s["name"] == "sync_msg_send"}
    serves = [s for s in spans if s["name"] == "sync_msg_serve"]
    assert sends and serves
    stitched = [s for s in serves if s.get("parent_span_id") in sends]
    assert stitched, (sends, serves)
    for s in stitched:
        parent = sends[s["parent_span_id"]]
        assert s["trace_id"] == parent["trace_id"]


def test_relay_chain_is_one_trace():
    """A change propagating A -> B -> C keeps ONE trace id end to end:
    B's relay send happens inside its serve span, so it inherits the
    trace A started."""
    metrics.reset()
    ea, eb = EngineDocSet(backend="rows"), EngineDocSet(backend="rows")
    ec = EngineDocSet(backend="rows")
    q_ab, q_ba, q_bc, q_cb = [], [], [], []
    c_ab = Connection(ea, q_ab.append, wire="columnar")   # a's link to b
    c_ba = Connection(eb, q_ba.append, wire="columnar")   # b's link to a
    c_bc = Connection(eb, q_bc.append, wire="columnar")   # b's link to c
    c_cb = Connection(ec, q_cb.append, wire="columnar")   # c's link to b
    for c in (c_ab, c_ba, c_bc, c_cb):
        c.open()

    def pump():
        for _ in range(40):
            moved = False
            for q, dst in ((q_ab, c_ba), (q_ba, c_ab),
                           (q_bc, c_cb), (q_cb, c_bc)):
                while q:
                    dst.receive_msg(q.pop(0))
                    moved = True
            if not moved:
                return

    pump()
    metrics.reset()   # only the round below matters
    ea.apply_columns("relay", _cols("A", 1, "k", 42))
    pump()
    assert ec.hashes().get("relay") == ea.hashes()["relay"]
    spans = metrics.recent_spans()
    # the serving spans on B and C (and the relay sends between) all carry
    # the trace the originating send started
    serves = [s for s in spans if s["name"] == "sync_msg_serve"]
    tid_counts: dict[str, int] = {}
    for s in serves:
        tid_counts[s["trace_id"]] = tid_counts.get(s["trace_id"], 0) + 1
    # at least one trace spans multiple serves (B's serve + C's serve)
    assert max(tid_counts.values()) >= 2, tid_counts


def test_round_flush_spans_carry_round_tags():
    """service.py tags each flush span with the node's round number (a
    span-record tag, not a metric label)."""
    metrics.reset()
    svc = EngineDocSet(backend="rows")
    svc.apply_columns("d", _cols("A", 1, "x", 1))
    svc.apply_columns("d", _cols("A", 2, "x", 2))
    rounds = [s["tags"]["round"] for s in metrics.recent_spans()
              if s["name"] == "sync_round_flush"]
    assert rounds == [1, 2]


# -- remote span pull + merged timeline -------------------------------------


def test_remote_span_pull_and_merged_timeline():
    metrics.reset()
    ea, eb, qa, ca, qb, cb = _pair()
    ea.apply_columns("doc1", _cols("A", 1, "x", 1))
    _pump(qa, ca, qb, cb)
    ca.request_metrics(spans=True)
    _pump(qa, ca, qb, cb)
    assert ca.peer_metrics is not None
    assert ca.peer_spans, "peer did not ship its span ring"
    timeline = metrics.merge_timeline({
        "local": metrics.recent_spans(), "peer": ca.peer_spans})
    assert all("replica" in s for s in timeline)
    # at least one trace in the merged timeline has spans from a send
    # and its serve (the stitched cross-replica round)
    by_tid: dict[str, set] = {}
    for s in timeline:
        by_tid.setdefault(s["trace_id"], set()).add(s["name"])
    assert any({"sync_msg_send", "sync_msg_serve"} <= names
               for names in by_tid.values())


def test_merge_timeline_orders_parent_before_child_despite_clock_skew():
    """Causal order beats timestamps: a child span whose replica clock
    reads EARLIER than its parent's still sorts after the parent."""
    parent = {"name": "sync_msg_send", "trace_id": "t1", "span_id": "p1",
              "parent_span_id": None, "start": 100.0}
    child = {"name": "sync_msg_serve", "trace_id": "t1", "span_id": "c1",
             "parent_span_id": "p1", "start": 99.0}   # skewed clock
    other = {"name": "rows_hashes", "trace_id": "t2", "span_id": "x1",
             "parent_span_id": None, "start": 50.0}
    out = metrics.merge_timeline({"a": [parent], "b": [child, other]})
    names = [(s["trace_id"], s["span_id"]) for s in out]
    assert names.index(("t1", "p1")) < names.index(("t1", "c1"))
    # traces order by earliest start: t2 (50.0) first
    assert names[0] == ("t2", "x1")
    assert out[0]["replica"] == "b"


def test_merge_timeline_dedups_overlapping_buffers():
    """A span present in several buffers (overlapping pulls; an
    in-process peer sharing the store) must emit exactly once — the
    duplicate-parent walk used to duplicate whole subtrees
    exponentially."""
    parent = {"name": "sync_msg_send", "trace_id": "t1", "span_id": "p1",
              "parent_span_id": None, "start": 1.0}
    child = {"name": "sync_msg_serve", "trace_id": "t1", "span_id": "c1",
             "parent_span_id": "p1", "start": 2.0}
    grand = {"name": "sync_round_flush", "trace_id": "t1", "span_id": "g1",
             "parent_span_id": "c1", "start": 3.0}
    buf = [parent, child, grand]
    out = metrics.merge_timeline({"a": buf, "b": list(buf)})
    assert len(out) == 3
    assert [s["span_id"] for s in out] == ["p1", "c1", "g1"]
    assert all(s["replica"] == "a" for s in out)


def test_adopt_context_noop_for_untraced_peer():
    metrics.reset()
    with metrics.adopt_context(None):
        with metrics.trace("sync_msg_serve") as s:
            assert s.parent_sid is None
    ctx = metrics.current_context()
    assert ctx is None
