"""bench_history.jsonl + the perf regression gate
(automerge_tpu/perf/history.py and the `python -m automerge_tpu.perf`
CLI contract). Pure host tests — no jax dispatch work."""

import json
import os
import pathlib
import subprocess
import sys

import pytest

from automerge_tpu.perf import history

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _rec(value, backend="cpu", source="test", compiles=None, configs=None):
    out = {"schema": 1, "at": 0.0, "source": source, "backend": backend,
           "value": value, "unit": "ops/sec", "vs_baseline": 1.0,
           "configs": configs or {}}
    if compiles is not None:
        out["perf"] = {"compiles_total": compiles, "kernels": {}}
    return out


def _write(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


# -- ledger -----------------------------------------------------------------


def test_append_load_roundtrip_tolerates_torn_tail(tmp_path):
    p = str(tmp_path / "h.jsonl")
    history.append(_rec(100), p)
    history.append(_rec(110), p)
    with open(p, "a") as f:
        f.write('{"torn": ')        # a killed run's partial line
    recs = history.load(p)
    assert [r["value"] for r in recs] == [100, 110]


def test_backfill_from_committed_bench_captures(tmp_path):
    """The committed BENCH_r0*.json trajectory seeds the ledger: captures
    with a parsed final record become history records (backend-labeled),
    crashed rounds are skipped."""
    recs = history.backfill_records(str(ROOT))
    assert len(recs) >= 3
    assert all(r["source"].startswith("backfill:BENCH_r0") for r in recs)
    assert all(r["value"] > 0 for r in recs)
    assert {"cpu", "tpu"} >= {r["backend"] for r in recs}
    # per-config speedups normalize to dicts for both record shapes
    some = [r for r in recs if r["configs"]]
    assert some and all(
        isinstance(v, dict) for r in some for v in r["configs"].values())

    p = str(tmp_path / "h.jsonl")
    n = history.ensure_backfilled(str(ROOT), p)
    assert n == len(recs) == len(history.load(p))
    # a second call never rewrites existing history
    assert history.ensure_backfilled(str(ROOT), p) == 0


def test_record_from_bench_aggregates_compile_counts():
    rec = {"backend": "cpu", "value": 5000, "unit": "ops/sec",
           "vs_baseline": 2.0,
           "configs": {
               "1": {"speedup": 1.2, "engine_ops_per_s": 900,
                     "metrics": {"perf": {"kernels": {
                         "apply_final": {"dispatches": 4, "compiles": 2},
                         "scan_rounds": {"dispatches": 1, "compiles": 1},
                     }}}},
               "5": {"speedup": 2.0, "engine_ops_per_s": 5000,
                     "metrics": {"perf": {"kernels": {
                         "apply_final": {"dispatches": 2, "compiles": 1},
                     }}}}}}
    out = history.record_from_bench(rec)
    assert out["value"] == 5000 and out["backend"] == "cpu"
    assert out["configs"]["5"]["engine_ops_per_s"] == 5000
    assert out["perf"]["compiles_total"] == 4
    assert out["perf"]["kernels"] == {"apply_final": 3, "scan_rounds": 1}


# -- the gate ---------------------------------------------------------------


def test_check_empty_history_skips_cleanly(tmp_path):
    rc, lines = history.check(path=str(tmp_path / "missing.jsonl"))
    assert rc == 0
    assert any("SKIP" in ln for ln in lines)


def test_check_identical_rerun_passes(tmp_path):
    p = str(tmp_path / "h.jsonl")
    _write(p, [_rec(1000, compiles=10), _rec(1000, compiles=10),
               _rec(1000, compiles=10, source="rerun")])
    rc, lines = history.check(path=p)
    assert rc == 0, lines


def test_check_flags_2x_throughput_regression(tmp_path):
    p = str(tmp_path / "h.jsonl")
    _write(p, [_rec(1000), _rec(1050), _rec(500, source="regressed")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("REGRESSION" in ln for ln in lines)


def test_check_flags_compile_count_growth(tmp_path):
    p = str(tmp_path / "h.jsonl")
    _write(p, [_rec(1000, compiles=10), _rec(1000, compiles=10),
               _rec(1000, compiles=40, source="retrace-storm")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("COMPILE GROWTH" in ln for ln in lines)


def test_check_never_compares_across_backends(tmp_path):
    """Backend-labeling rule: a CPU fallback run is judged only against
    CPU history — TPU numbers are an order of magnitude apart and would
    make the gate either blind or permanently red."""
    p = str(tmp_path / "h.jsonl")
    _write(p, [_rec(100000, backend="tpu"), _rec(120000, backend="tpu"),
               _rec(1000, backend="cpu", source="cpu-fallback")])
    rc, lines = history.check(path=p)
    assert rc == 0
    assert any("SKIP" in ln for ln in lines)


def test_check_never_compares_across_headline_configs(tmp_path):
    """A partial run (--config 1) falls back to a different headline
    config than a full run; judging its value against full-run history
    would be a guaranteed false alarm."""
    p = str(tmp_path / "h.jsonl")
    full = history.record_from_bench(
        {"backend": "cpu", "value": 14000000,
         "configs": {"1": 1.2, "5": 90.0}})
    partial = history.record_from_bench(
        {"backend": "cpu", "value": 47000, "configs": {"1": 1.1}},
        source="partial")
    assert full["headline_config"] == "5"
    assert partial["headline_config"] == "1"
    _write(p, [full, full, partial])
    rc, lines = history.check(path=p)
    assert rc == 0
    assert any("SKIP" in ln for ln in lines)


def test_check_explicit_record_against_whole_file(tmp_path):
    p = str(tmp_path / "h.jsonl")
    _write(p, [_rec(1000), _rec(1000)])
    rc, _ = history.check(path=p, record=_rec(980, source="candidate"))
    assert rc == 0
    rc, _ = history.check(path=p, record=_rec(400, source="candidate"))
    assert rc == 1


# -- CLI contract -----------------------------------------------------------


def _cli(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "automerge_tpu.perf", *args],
        capture_output=True, text=True, cwd=str(ROOT), env=env,
        timeout=120)


def test_cli_check_exit_codes(tmp_path):
    p = str(tmp_path / "h.jsonl")
    _write(p, [_rec(1000), _rec(1000), _rec(1000, source="rerun")])
    out = _cli("check", "--history", p, "--no-backfill")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "PERFCHECK OK" in out.stdout

    _write(p, [_rec(1000), _rec(1000), _rec(500, source="regressed")])
    out = _cli("check", "--history", p, "--no-backfill")
    assert out.returncode == 1
    assert "PERFCHECK FAIL" in out.stdout

    out = _cli("check", "--history", str(tmp_path / "none.jsonl"),
               "--no-backfill")
    assert out.returncode == 0
    assert "SKIP" in out.stdout


def test_cli_check_backfills_missing_history(tmp_path):
    p = str(tmp_path / "h.jsonl")
    out = _cli("check", "--history", p)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "backfilled" in out.stdout
    assert len(history.load(p)) >= 3


def test_cli_report_renders_trajectory(tmp_path):
    p = str(tmp_path / "h.jsonl")
    _write(p, [_rec(1000, source="one"), _rec(2000, source="two")])
    out = _cli("report", "--history", p, "--no-backfill")
    assert out.returncode == 0
    assert "bench history — 2 records" in out.stdout
    assert "one" in out.stdout and "two" in out.stdout


def test_cli_rejects_unknown_command():
    out = _cli("frobnicate")
    assert out.returncode == 2
