"""bench_history.jsonl + the perf regression gate
(automerge_tpu/perf/history.py and the `python -m automerge_tpu.perf`
CLI contract). Pure host tests — no jax dispatch work."""

import json
import os
import pathlib
import subprocess
import sys

import pytest

from automerge_tpu.perf import history

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _rec(value, backend="cpu", source="test", compiles=None, configs=None):
    out = {"schema": 1, "at": 0.0, "source": source, "backend": backend,
           "value": value, "unit": "ops/sec", "vs_baseline": 1.0,
           "configs": configs or {}}
    if compiles is not None:
        out["perf"] = {"compiles_total": compiles, "kernels": {}}
    return out


def _frec(value, hashes_s, backend="cpu", source="test"):
    out = _rec(value, backend=backend, source=source)
    if hashes_s is not None:
        out["fleet"] = {"fleet_hashes_s": hashes_s,
                        "fleet_hashes_clean_shards": 8,
                        "fleet_hashes_dirty_shards": 0}
    return out


def _write(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


# -- ledger -----------------------------------------------------------------


def test_append_load_roundtrip_tolerates_torn_tail(tmp_path):
    p = str(tmp_path / "h.jsonl")
    history.append(_rec(100), p)
    history.append(_rec(110), p)
    with open(p, "a") as f:
        f.write('{"torn": ')        # a killed run's partial line
    recs = history.load(p)
    assert [r["value"] for r in recs] == [100, 110]


def test_backfill_from_committed_bench_captures(tmp_path):
    """The committed BENCH_r0*.json trajectory seeds the ledger: captures
    with a parsed final record become history records (backend-labeled),
    crashed rounds are skipped."""
    recs = history.backfill_records(str(ROOT))
    assert len(recs) >= 3
    assert all(r["source"].startswith("backfill:BENCH_r0") for r in recs)
    assert all(r["value"] > 0 for r in recs)
    assert {"cpu", "tpu"} >= {r["backend"] for r in recs}
    # per-config speedups normalize to dicts for both record shapes
    some = [r for r in recs if r["configs"]]
    assert some and all(
        isinstance(v, dict) for r in some for v in r["configs"].values())

    p = str(tmp_path / "h.jsonl")
    n = history.ensure_backfilled(str(ROOT), p)
    assert n == len(recs) == len(history.load(p))
    # a second call never rewrites existing history
    assert history.ensure_backfilled(str(ROOT), p) == 0


def test_record_from_bench_aggregates_compile_counts():
    rec = {"backend": "cpu", "value": 5000, "unit": "ops/sec",
           "vs_baseline": 2.0,
           "configs": {
               "1": {"speedup": 1.2, "engine_ops_per_s": 900,
                     "metrics": {"perf": {"kernels": {
                         "apply_final": {"dispatches": 4, "compiles": 2},
                         "scan_rounds": {"dispatches": 1, "compiles": 1},
                     }}}},
               "5": {"speedup": 2.0, "engine_ops_per_s": 5000,
                     "metrics": {"perf": {"kernels": {
                         "apply_final": {"dispatches": 2, "compiles": 1},
                     }}}}}}
    out = history.record_from_bench(rec)
    assert out["value"] == 5000 and out["backend"] == "cpu"
    assert out["configs"]["5"]["engine_ops_per_s"] == 5000
    assert out["perf"]["compiles_total"] == 4
    assert out["perf"]["kernels"] == {"apply_final": 3, "scan_rounds": 1}


# -- the gate ---------------------------------------------------------------


def test_check_empty_history_skips_cleanly(tmp_path):
    rc, lines = history.check(path=str(tmp_path / "missing.jsonl"))
    assert rc == 0
    assert any("SKIP" in ln for ln in lines)


def test_check_identical_rerun_passes(tmp_path):
    p = str(tmp_path / "h.jsonl")
    _write(p, [_rec(1000, compiles=10), _rec(1000, compiles=10),
               _rec(1000, compiles=10, source="rerun")])
    rc, lines = history.check(path=p)
    assert rc == 0, lines


def test_check_flags_2x_throughput_regression(tmp_path):
    p = str(tmp_path / "h.jsonl")
    _write(p, [_rec(1000), _rec(1050), _rec(500, source="regressed")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("REGRESSION" in ln for ln in lines)


def test_check_flags_compile_count_growth(tmp_path):
    p = str(tmp_path / "h.jsonl")
    _write(p, [_rec(1000, compiles=10), _rec(1000, compiles=10),
               _rec(1000, compiles=40, source="retrace-storm")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("COMPILE GROWTH" in ln for ln in lines)


def test_check_flags_hash_read_cost_growth(tmp_path):
    """The convergence-read gate (r6): a clean-fleet hashes() read that
    regresses back toward O(fleet) — well past the rolling median plus
    the absolute slack — fails the check."""
    p = str(tmp_path / "h.jsonl")
    _write(p, [_frec(1000, 0.02), _frec(1000, 0.03),
               _frec(1000, 6.5, source="o-fleet-regression")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("HASH-READ GROWTH" in ln for ln in lines)


def test_check_hash_gate_passes_within_slack(tmp_path):
    """Sub-second jitter on a milliseconds-scale read must not trip the
    gate (absolute slack): 20ms -> 120ms is noise, not a regression."""
    p = str(tmp_path / "h.jsonl")
    _write(p, [_frec(1000, 0.02), _frec(1000, 0.03),
               _frec(1000, 0.12, source="jittery-rerun")])
    rc, lines = history.check(path=p)
    assert rc == 0, lines


def test_check_hash_gate_skips_when_history_lacks_fleet(tmp_path):
    """Skip-clean semantics, both directions: a record WITH the fleet
    section judged against history WITHOUT it (and vice versa) is
    informational, never a failure."""
    p = str(tmp_path / "h.jsonl")
    _write(p, [_rec(1000), _rec(1000),
               _frec(1000, 5.0, source="first-with-fleet")])
    rc, lines = history.check(path=p)
    assert rc == 0
    assert any("comparison starts next run" in ln for ln in lines)
    _write(p, [_frec(1000, 0.02), _rec(1000, source="no-fleet-run")])
    rc, lines = history.check(path=p)
    assert rc == 0, lines


def test_hash_gate_runs_even_when_throughput_gate_skips(tmp_path):
    """The convergence-read gate has its own comparison pool (config 8
    carries its own numbers): a run whose headline config changed — so
    the throughput gate skips — must still be judged on fleet_hashes_s."""
    p = str(tmp_path / "h.jsonl")
    priors = [dict(_frec(1000, 0.02), headline_config="5"),
              dict(_frec(1000, 0.03), headline_config="5")]
    cur = dict(_frec(900, 8.0, source="headline-fellback"),
               headline_config="1")
    _write(p, priors + [cur])
    rc, lines = history.check(path=p)
    assert any("SKIP throughput" in ln for ln in lines)
    assert rc == 1, lines
    assert any("HASH-READ GROWTH" in ln for ln in lines)


def test_hash_gate_window_not_consumed_by_fleetless_runs(tmp_path):
    """Filter-then-window: runs without config 8 in between must not push
    the comparable fleet records out of the gate's window."""
    p = str(tmp_path / "h.jsonl")
    recs = [_frec(1000, 0.02)] + [_rec(1000) for _ in range(10)] \
        + [_frec(1000, 9.0, source="regressed-after-gap")]
    _write(p, recs)
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("HASH-READ GROWTH" in ln for ln in lines)


def test_check_hash_gate_is_backend_scoped(tmp_path):
    """A CPU run's hash read is never judged against TPU history."""
    p = str(tmp_path / "h.jsonl")
    _write(p, [_frec(1000, 0.001, backend="tpu"),
               _frec(1000, 0.001, backend="tpu"),
               _frec(1000, 0.5, backend="cpu", source="cpu-read")])
    rc, lines = history.check(path=p)
    assert rc == 0, lines


def test_record_from_bench_extracts_fleet_section():
    rec = {"value": 14000, "backend": "cpu", "configs": {
        "8": {"engine_ops_per_s": 14000, "fleet_hashes_s": 0.02,
              "fleet_hashes_first_s": 21.0,
              "fleet_hashes_clean_shards": 8,
              "fleet_hashes_dirty_shards": 0,
              "round_cost_scaling": 1.05, "round_max_s": 0.4,
              "round_max_cause": "GC"}}}
    out = history.record_from_bench(rec)
    assert out["fleet"] == {
        "fleet_hashes_s": 0.02, "fleet_hashes_first_s": 21.0,
        "fleet_hashes_clean_shards": 8, "fleet_hashes_dirty_shards": 0,
        "round_cost_scaling": 1.05, "round_max_s": 0.4}
    # compact/driver records without config-8 detail: no fleet section
    assert "fleet" not in history.record_from_bench(
        {"value": 100, "configs": {"8": 1.5}})


def test_check_never_compares_across_hosts(tmp_path):
    """Host-scoping rule (r6): a host-stamped record is judged only
    against same-host-class records — raw ops/sec differs ~10x between a
    small container and a big runner on identical code. Un-stamped
    (pre-r6 backfill) records fall out of a stamped record's pool."""
    p = str(tmp_path / "h.jsonl")
    big = dict(_rec(10_000_000), host={"cpus": 32, "machine": "x86_64"})
    unstamped = _rec(12_000_000)
    small = dict(_rec(1_000_000, source="small-box"),
                 host={"cpus": 2, "machine": "x86_64"})
    _write(p, [big, unstamped, small])
    rc, lines = history.check(path=p)
    assert rc == 0
    assert any("SKIP" in ln for ln in lines)
    # same-host history DOES gate
    small2 = dict(_rec(400_000, source="small-box-regressed"),
                  host={"cpus": 2, "machine": "x86_64"})
    _write(p, [small, dict(small), small2])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("REGRESSION" in ln for ln in lines)
    # an UN-stamped current record keeps the old pan-host behavior
    _write(p, [_rec(1000), _rec(1000), _rec(980, source="ok-rerun")])
    rc, lines = history.check(path=p)
    assert rc == 0, lines


def test_record_from_bench_stamps_host():
    out = history.record_from_bench({"value": 100, "configs": {}})
    assert out["host"]["cpus"] >= 1
    assert isinstance(out["host"]["machine"], str)


def test_check_never_compares_across_backends(tmp_path):
    """Backend-labeling rule: a CPU fallback run is judged only against
    CPU history — TPU numbers are an order of magnitude apart and would
    make the gate either blind or permanently red."""
    p = str(tmp_path / "h.jsonl")
    _write(p, [_rec(100000, backend="tpu"), _rec(120000, backend="tpu"),
               _rec(1000, backend="cpu", source="cpu-fallback")])
    rc, lines = history.check(path=p)
    assert rc == 0
    assert any("SKIP" in ln for ln in lines)


def test_check_never_compares_across_headline_configs(tmp_path):
    """A partial run (--config 1) falls back to a different headline
    config than a full run; judging its value against full-run history
    would be a guaranteed false alarm."""
    p = str(tmp_path / "h.jsonl")
    full = history.record_from_bench(
        {"backend": "cpu", "value": 14000000,
         "configs": {"1": 1.2, "5": 90.0}})
    partial = history.record_from_bench(
        {"backend": "cpu", "value": 47000, "configs": {"1": 1.1}},
        source="partial")
    assert full["headline_config"] == "5"
    assert partial["headline_config"] == "1"
    _write(p, [full, full, partial])
    rc, lines = history.check(path=p)
    assert rc == 0
    assert any("SKIP" in ln for ln in lines)


def test_check_explicit_record_against_whole_file(tmp_path):
    p = str(tmp_path / "h.jsonl")
    _write(p, [_rec(1000), _rec(1000)])
    rc, _ = history.check(path=p, record=_rec(980, source="candidate"))
    assert rc == 0
    rc, _ = history.check(path=p, record=_rec(400, source="candidate"))
    assert rc == 1


# -- CLI contract -----------------------------------------------------------


def _cli(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "automerge_tpu.perf", *args],
        capture_output=True, text=True, cwd=str(ROOT), env=env,
        timeout=120)


def test_cli_check_exit_codes(tmp_path):
    p = str(tmp_path / "h.jsonl")
    _write(p, [_rec(1000), _rec(1000), _rec(1000, source="rerun")])
    out = _cli("check", "--history", p, "--no-backfill")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "PERFCHECK OK" in out.stdout

    _write(p, [_rec(1000), _rec(1000), _rec(500, source="regressed")])
    out = _cli("check", "--history", p, "--no-backfill")
    assert out.returncode == 1
    assert "PERFCHECK FAIL" in out.stdout

    out = _cli("check", "--history", str(tmp_path / "none.jsonl"),
               "--no-backfill")
    assert out.returncode == 0
    assert "SKIP" in out.stdout


def test_cli_check_backfills_missing_history(tmp_path):
    p = str(tmp_path / "h.jsonl")
    out = _cli("check", "--history", p)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "backfilled" in out.stdout
    assert len(history.load(p)) >= 3


def test_cli_report_renders_trajectory(tmp_path):
    p = str(tmp_path / "h.jsonl")
    _write(p, [_rec(1000, source="one"), _rec(2000, source="two")])
    out = _cli("report", "--history", p, "--no-backfill")
    assert out.returncode == 0
    assert "bench history — 2 records" in out.stdout
    assert "one" in out.stdout and "two" in out.stdout


def test_cli_rejects_unknown_command():
    out = _cli("frobnicate")
    assert out.returncode == 2


# -- r8 gates: bulk text merge (config 10) + keystroke flatness (config 7) --


def _mrec(value, merge_ops, source="test", host=None):
    out = _rec(value, source=source,
               configs={"10": {"merge_ops_per_s": merge_ops,
                               "merge_speedup_vs_perop": 3.0,
                               "merge_speedup_vs_replay": 40.0}})
    if host is not None:
        out["host"] = host
    return out


def test_merge_gate_passes_on_steady_throughput(tmp_path):
    p = str(tmp_path / "h.jsonl")
    _write(p, [_mrec(1000, 9000), _mrec(1000, 9500),
               _mrec(1000, 9200, source="rerun")])
    rc, lines = history.check(path=p)
    assert rc == 0, lines
    assert any("text bulk merge" in ln and "OK" in ln for ln in lines)


def test_merge_gate_flags_regression(tmp_path):
    p = str(tmp_path / "h.jsonl")
    _write(p, [_mrec(1000, 9000), _mrec(1000, 9500),
               _mrec(1000, 3000, source="regressed")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("MERGE REGRESSION" in ln for ln in lines)


def test_merge_gate_first_run_and_absent_config_skip_cleanly(tmp_path):
    p = str(tmp_path / "h.jsonl")
    # no prior config-10 history: informational line, rc 0
    _write(p, [_rec(1000), _mrec(1000, 9000, source="first")])
    rc, lines = history.check(path=p)
    assert rc == 0, lines
    assert any("comparison starts next run" in ln
               for ln in lines if "merge" in ln)
    # run without config 10 against merge-carrying history: no gate line
    _write(p, [_mrec(1000, 9000), _rec(1000, source="no-cfg10")])
    rc, lines = history.check(path=p)
    assert rc == 0, lines
    assert not any("text bulk merge" in ln for ln in lines)


def test_merge_gate_is_host_scoped(tmp_path):
    """A big-host record must not set the bar for a small-host run."""
    p = str(tmp_path / "h.jsonl")
    big = {"cpus": 32, "machine": "x86_64"}
    small = {"cpus": 2, "machine": "x86_64"}
    _write(p, [_mrec(1000, 90000, host=big), _mrec(1000, 90000, host=big),
               _mrec(1000, 9000, source="small-host", host=small)])
    rc, lines = history.check(path=p)
    assert rc == 0, lines   # no same-host history -> skip, not fail


def test_flatness_gate_ok_and_ceiling(tmp_path):
    p = str(tmp_path / "h.jsonl")

    def frec(flat, source="test"):
        return _rec(1000, source=source,
                    configs={"7": {"keystroke_flatness": flat,
                                   "ms_per_keystroke": 0.3}})

    _write(p, [frec(1.0), frec(1.1, source="ok")])
    rc, lines = history.check(path=p)
    assert rc == 0, lines
    assert any("keystroke flatness" in ln and "OK" in ln for ln in lines)

    _write(p, [frec(1.0), frec(1.8, source="regressed")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("FLATNESS REGRESSION" in ln for ln in lines)

    # records without config 7 never produce the line
    _write(p, [frec(1.0), _rec(1000, source="no-cfg7")])
    rc, lines = history.check(path=p)
    assert rc == 0
    assert not any("keystroke flatness" in ln for ln in lines)


def test_norm_configs_carries_span_plane_fields():
    rec = {"backend": "cpu", "value": 10, "configs": {
        "7": {"speedup": 1.1, "ms_per_keystroke": 0.31,
              "keystroke_flatness": 1.05},
        "10": {"speedup": 40.0, "merge_ops_per_s": 9100,
               "merge_speedup_vs_perop": 3.1,
               "merge_speedup_vs_replay": 41.5,
               "span_merge_s": 1.2, "perop_merge_s": 3.8}}}
    out = history.record_from_bench(rec)
    assert out["configs"]["7"]["keystroke_flatness"] == 1.05
    assert out["configs"]["7"]["ms_per_keystroke"] == 0.31
    assert out["configs"]["10"]["merge_ops_per_s"] == 9100
    assert out["configs"]["10"]["merge_speedup_vs_perop"] == 3.1
    assert out["configs"]["10"]["span_merge_s"] == 1.2


def test_ledger_gate_budget_ok_over_and_absent(tmp_path):
    """Config-12 doc-ledger duty-cycle gate (LEDGER_BUDGET_PCT): absolute
    budget like the scrape gate — over fails, under passes, runs without
    config 12 skip cleanly."""
    p = str(tmp_path / "h.jsonl")

    def lrec(pct, source="test"):
        return _rec(1000, source=source,
                    configs={"12": {"ledger_overhead_pct": pct,
                                    "redundancy_ratio": 1.8,
                                    "redundancy_floor": 1.0,
                                    "doc_lag_p99_s": 0.09,
                                    "explain_attributed": 1}})

    _write(p, [lrec(0.5), lrec(0.9, source="ok")])
    rc, lines = history.check(path=p)
    assert rc == 0, lines
    assert any("doc-ledger duty cycle" in ln and "OK" in ln
               for ln in lines)
    assert any("mesh redundancy x1.8" in ln and "floor 1.0" in ln
               for ln in lines)
    assert any("explain attribution OK" in ln for ln in lines)

    _write(p, [lrec(0.5), lrec(3.7, source="heavy")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("LEDGER OVER BUDGET" in ln for ln in lines)

    _write(p, [lrec(0.5), _rec(1000, source="no-cfg12")])
    rc, lines = history.check(path=p)
    assert rc == 0
    assert not any("doc-ledger" in ln for ln in lines)


def test_norm_configs_carries_doc_obs_fields():
    rec = {"backend": "cpu", "value": 10, "configs": {
        "12": {"doc_lag_p50_s": 0.0, "doc_lag_p99_s": 0.09,
               "doc_lag_max_s": 0.13, "redundancy_ratio": 1.85,
               "redundancy_floor": 1.0, "ledger_overhead_pct": 0.56,
               "explain_attributed": 1, "mesh_nodes": 4,
               "redundancy_note": "dropped (string, non-numeric keys "
                                  "only ride the detail sidecar)"}}}
    out = history.record_from_bench(rec)
    c12 = out["configs"]["12"]
    assert c12["doc_lag_p99_s"] == 0.09
    assert c12["redundancy_ratio"] == 1.85
    assert c12["redundancy_floor"] == 1.0
    assert c12["ledger_overhead_pct"] == 0.56
    assert c12["explain_attributed"] == 1
    assert c12["mesh_nodes"] == 4


def test_sub_relay_gates_ok_over_and_absent(tmp_path):
    """Config-13 partial-replication gates: growth exponent, bytes/sub
    ceiling vs the flat baseline, relay redundancy, subscribed-doc SLO,
    backfill — all absolute; runs without config 13 skip cleanly."""
    p = str(tmp_path / "h.jsonl")

    def srec(exp=0.74, frac=0.18, red=0.0, p99=0.07, bf=1,
             source="test"):
        return _rec(1000, source=source,
                    configs={"13": {"fanout_growth_exponent": exp,
                                    "fanout_vs_mesh_fraction": frac,
                                    "sub_redundancy_ratio": red,
                                    "sub_converge_p99_s": p99,
                                    "sub_backfill_ok": bf}})

    _write(p, [srec(), srec(source="ok")])
    rc, lines = history.check(path=p)
    assert rc == 0, lines
    assert any("relay fan-out growth" in ln and "OK" in ln
               for ln in lines)
    assert any("bytes/subscriber vs flat baseline" in ln and "OK" in ln
               for ln in lines)
    assert any("relay redundancy ratio" in ln and "OK" in ln
               for ln in lines)
    assert any("subscribed-doc converge p99" in ln and "OK" in ln
               for ln in lines)
    assert any("late-subscribe backfill: OK" in ln for ln in lines)

    _write(p, [srec(), srec(exp=1.02, source="linear")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("FAN-OUT NOT SUBLINEAR" in ln for ln in lines)

    _write(p, [srec(), srec(frac=0.8, source="fat")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("FAN-OUT OVER MESH CEILING" in ln for ln in lines)

    _write(p, [srec(), srec(red=1.5, source="dup")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("RELAY REDUNDANCY OVER BUDGET" in ln for ln in lines)

    _write(p, [srec(), srec(p99=3.0, source="slow")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("SUBSCRIBED-DOC SLO BREACH" in ln for ln in lines)

    _write(p, [srec(), srec(bf=0, source="nofill")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("late-subscribe backfill: MISS" in ln for ln in lines)

    _write(p, [srec(), _rec(1000, source="no-cfg13")])
    rc, lines = history.check(path=p)
    assert rc == 0
    assert not any("relay fan-out" in ln for ln in lines)


def test_norm_configs_carries_sub_relay_fields():
    rec = {"backend": "cpu", "value": 10, "configs": {
        "13": {"fanout_bytes_per_sub": 6662.5,
               "mesh_bytes_per_sub": 36847.0,
               "fanout_vs_mesh_fraction": 0.18,
               "fanout_growth_exponent": 0.735,
               "sub_redundancy_ratio": 0.0,
               "sub_converge_p99_s": 0.066,
               "sub_slo_bound_s": 2.0,
               "sub_backfill_ok": 1,
               "backfill": {"dropped": "(dict fields only ride the "
                                       "detail sidecar)"}}}}
    out = history.record_from_bench(rec)
    c13 = out["configs"]["13"]
    assert c13["fanout_growth_exponent"] == 0.735
    assert c13["fanout_vs_mesh_fraction"] == 0.18
    assert c13["mesh_bytes_per_sub"] == 36847.0
    assert c13["sub_redundancy_ratio"] == 0.0
    assert c13["sub_converge_p99_s"] == 0.066
    assert c13["sub_backfill_ok"] == 1
    assert "backfill" not in c13


def test_remed_gates_ok_over_and_absent(tmp_path):
    """Config-14 remediation gates: MTTR budget, recovered-class floor,
    steady-state duty cycle, dry-run cleanliness — all absolute, each
    judged independently; runs without config 14 skip cleanly."""
    p = str(tmp_path / "h.jsonl")

    def rrec(mttr=6.2, classes=4, ovh=0.4, dry=1, source="test"):
        return _rec(1000, source=source,
                    configs={"14": {"mttr_max_s": mttr,
                                    "fault_classes_injected": 4,
                                    "fault_classes_recovered": classes,
                                    "remed_overhead_pct": ovh,
                                    "remed_dry_run_clean": dry}})

    _write(p, [rrec(), rrec(source="ok")])
    rc, lines = history.check(path=p)
    assert rc == 0, lines
    assert any("remediation MTTR" in ln and "OK" in ln for ln in lines)
    assert any("remediation classes recovered: 4/4" in ln and "OK" in ln
               for ln in lines)
    assert any("remediation duty cycle" in ln and "OK" in ln
               for ln in lines)
    assert any("remediation dry-run: OK" in ln for ln in lines)

    _write(p, [rrec(), rrec(mttr=45.0, source="slow-heal")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("MTTR OVER BUDGET" in ln for ln in lines)

    _write(p, [rrec(), rrec(classes=2, source="half-healed")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("TOO FEW CLASSES RECOVERED" in ln for ln in lines)

    _write(p, [rrec(), rrec(ovh=3.1, source="heavy")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("REMEDIATION OVER BUDGET" in ln for ln in lines)

    _write(p, [rrec(), rrec(dry=0, source="wet-run")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("EXECUTED SOMETHING" in ln for ln in lines)

    # a record missing only the MTTR must not vacate the other gates
    bad = rrec(ovh=3.1, source="partial")
    del bad["configs"]["14"]["mttr_max_s"]
    _write(p, [rrec(), bad])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("REMEDIATION OVER BUDGET" in ln for ln in lines)

    _write(p, [rrec(), _rec(1000, source="no-cfg14")])
    rc, lines = history.check(path=p)
    assert rc == 0
    assert not any("remediation" in ln for ln in lines)


def test_norm_configs_carries_remed_fields():
    rec = {"backend": "cpu", "value": 10, "configs": {
        "14": {"mttr_max_s": 6.2, "mttr_mean_s": 4.1,
               "mttr_budget_s": 30.0,
               "fault_classes_injected": 4,
               "fault_classes_recovered": 4,
               "remed_overhead_pct": 0.4,
               "remed_tick_p50_s": 0.0016,
               "remed_dry_run_clean": 1,
               "remed_actions_total": 2,
               "reconnects_total": 3,
               "faults": {"dropped": "(dict fields ride the detail "
                                     "sidecar only)"}}}}
    out = history.record_from_bench(rec)
    c14 = out["configs"]["14"]
    assert c14["mttr_max_s"] == 6.2
    assert c14["mttr_budget_s"] == 30.0
    assert c14["fault_classes_recovered"] == 4
    assert c14["remed_overhead_pct"] == 0.4
    assert c14["remed_dry_run_clean"] == 1
    assert c14["reconnects_total"] == 3
    assert "faults" not in c14


def test_move_gates_ok_over_and_absent(tmp_path):
    """Config-16 move-plane gates: atom-vs-emulation byte ratios (wire +
    archive), batched-resolution direction, kernel/pallas parity and the
    two-replica storm verdict — all absolute, each judged independently;
    runs without config 16 skip cleanly."""
    p = str(tmp_path / "h.jsonl")

    def mrec(wire=6.7, arch=6.9, spd=196.0, kpar=1, ppar=1, conv=1,
             source="test"):
        return _rec(1000, source=source,
                    configs={"16": {"move_wire_ratio_x": wire,
                                    "move_archive_ratio_x": arch,
                                    "move_resolve_speedup_x": spd,
                                    "move_storm_moves": 1536,
                                    "move_kernel_parity": kpar,
                                    "move_pallas_parity": ppar,
                                    "move_storm_converged": conv}})

    _write(p, [mrec(), mrec(source="ok")])
    rc, lines = history.check(path=p)
    assert rc == 0, lines
    assert any("move-as-atom wire-frame" in ln and "OK" in ln
               for ln in lines)
    assert any("move-as-atom archived-log" in ln and "OK" in ln
               for ln in lines)
    assert any("batched move resolution" in ln and "OK" in ln
               for ln in lines)
    assert any("move host/XLA parity: OK" in ln for ln in lines)
    assert any("move pallas parity: OK" in ln for ln in lines)
    assert any("move two-replica storm convergence: OK" in ln
               for ln in lines)

    _write(p, [mrec(), mrec(wire=3.0, source="fat-wire")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("MOVE NOT BEATING DELETE+REINSERT" in ln for ln in lines)

    _write(p, [mrec(), mrec(spd=0.8, source="slow-batch")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("BATCHED RESOLUTION NOT FASTER" in ln for ln in lines)

    _write(p, [mrec(), mrec(ppar=0, source="diverged")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("move pallas parity: FAILED" in ln for ln in lines)

    # a record missing only the wire ratio must not vacate the others
    bad = mrec(conv=0, source="partial")
    del bad["configs"]["16"]["move_wire_ratio_x"]
    _write(p, [mrec(), bad])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("move two-replica storm convergence: FAILED" in ln
               for ln in lines)

    _write(p, [mrec(), _rec(1000, source="no-cfg16")])
    rc, lines = history.check(path=p)
    assert rc == 0
    assert not any("move" in ln for ln in lines)


def test_norm_configs_carries_move_fields():
    rec = {"backend": "cpu", "value": 10, "configs": {
        "16": {"move_wire_ratio_x": 6.73, "move_archive_ratio_x": 6.93,
               "move_atom_ops_per_s": 2287.8,
               "reorder_ops_per_s": 3594.8,
               "move_resolve_speedup_x": 196.03,
               "move_batch_resolve_s": 0.058,
               "move_perop_resolve_s": 11.35,
               "move_storm_moves": 1536,
               "move_cycles_dropped": 2,
               "move_kernel_parity": True,
               "move_pallas_parity": True,
               "move_storm_converged": True,
               "protocol": "(string fields ride the detail sidecar)"}}}
    out = history.record_from_bench(rec)
    c16 = out["configs"]["16"]
    assert c16["move_wire_ratio_x"] == 6.73
    assert c16["move_archive_ratio_x"] == 6.93
    assert c16["move_resolve_speedup_x"] == 196.03
    assert c16["move_storm_moves"] == 1536
    assert c16["move_kernel_parity"] is True
    assert c16["move_storm_converged"] is True
    assert "protocol" not in c16  # prose rides the detail sidecar only


def test_trace_gates_ok_over_and_absent(tmp_path):
    """Config-19 trace-plane gates: duty-cycle budget, sampled-trace
    completeness floor, stage-sum-vs-e2e reconciliation bound and the
    unset-path parity verdict — all absolute, each judged
    independently; runs without config 19 skip cleanly."""
    p = str(tmp_path / "h.jsonl")

    def trec(duty=0.1, comp=100.0, serr=2.3, par=1, source="test"):
        return _rec(1000, source=source,
                    configs={"19": {"trace_ledger_overhead_pct": duty,
                                    "trace_completeness_pct": comp,
                                    "trace_stage_sum_err_pct": serr,
                                    "trace_disabled_parity": par,
                                    "trace_crit_p50_s": 0.12,
                                    "trace_crit_p99_s": 1.18,
                                    "trace_stitched": 47}})

    _write(p, [trec(), trec(source="ok")])
    rc, lines = history.check(path=p)
    assert rc == 0, lines
    assert any("trace-plane duty cycle" in ln and "OK" in ln
               for ln in lines)
    assert any("trace completeness" in ln and "OK" in ln for ln in lines)
    assert any("trace stage-sum vs e2e lag" in ln and "OK" in ln
               for ln in lines)
    assert any("trace-plane unset-path parity: OK" in ln for ln in lines)
    assert any("trace critical-path baseline" in ln
               and "47 stitched across the wire" in ln for ln in lines)

    _write(p, [trec(), trec(duty=3.4, source="heavy")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("TRACE PLANE OVER BUDGET" in ln for ln in lines)

    _write(p, [trec(), trec(comp=91.0, source="leaky")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("SAMPLED TRACES LOST MID-LIFECYCLE" in ln for ln in lines)

    _write(p, [trec(), trec(serr=11.5, source="gappy")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("STAGES DO NOT RECONCILE WITH E2E LAG" in ln
               for ln in lines)

    _write(p, [trec(), trec(par=0, source="tainted")])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("trace-plane unset-path parity: DIVERGED" in ln
               for ln in lines)

    # a record missing only the duty figure must not vacate the others
    bad = trec(comp=91.0, source="partial")
    del bad["configs"]["19"]["trace_ledger_overhead_pct"]
    _write(p, [trec(), bad])
    rc, lines = history.check(path=p)
    assert rc == 1
    assert any("SAMPLED TRACES LOST MID-LIFECYCLE" in ln for ln in lines)

    _write(p, [trec(), _rec(1000, source="no-cfg19")])
    rc, lines = history.check(path=p)
    assert rc == 0
    assert not any("trace" in ln for ln in lines)


def test_norm_configs_carries_trace_fields():
    rec = {"backend": "cpu", "value": 10, "configs": {
        "19": {"trace_sampled": 51, "trace_completed": 51,
               "trace_stitched": 47,
               "trace_completeness_pct": 100.0,
               "trace_stage_sum_err_pct": 2.5,
               "trace_ledger_overhead_pct": 0.074,
               "trace_disabled_parity": 1,
               "trace_crit_p50_s": 0.118, "trace_crit_p99_s": 1.183,
               "trace_stages": {"dropped": "(dict fields ride the "
                                           "detail sidecar only)"}}}}
    out = history.record_from_bench(rec)
    c19 = out["configs"]["19"]
    assert c19["trace_sampled"] == 51
    assert c19["trace_stitched"] == 47
    assert c19["trace_completeness_pct"] == 100.0
    assert c19["trace_stage_sum_err_pct"] == 2.5
    assert c19["trace_ledger_overhead_pct"] == 0.074
    assert c19["trace_disabled_parity"] == 1
    assert c19["trace_crit_p99_s"] == 1.183
    assert "trace_stages" not in c19
