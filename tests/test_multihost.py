"""Multi-host execution (VERDICT r1 #8): two real OS processes, each with
its own CPU device set, sync divergent DocSets over TCP speaking the
reference's {docId, clock, changes} protocol, then join one global
8-device mesh (jax.distributed) for a single SPMD reconcile and a
cross-host clock-union collective. The worker logic lives in
tests/multihost_worker.py; this module just orchestrates the processes."""

import os
import socket
import subprocess
import sys

import pytest

import jax.distributed

# The whole module drives jax.distributed workers; some images ship a jax
# whose distributed module lacks is_initialized (parallel/multihost.py's
# idempotence guard — the workers die with AttributeError before ever
# syncing). Inherited breakage, not a code defect: skip with the reason
# on those images instead of failing tier-1 (ROADMAP "carried small
# debts"; the tests run wherever the API exists).
pytestmark = pytest.mark.skipif(
    not hasattr(jax.distributed, "is_initialized"),
    reason="jax.distributed.is_initialized missing in this jax build "
           "(multihost init guard cannot run; see ROADMAP.md #5)")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(worker_file: str, ok_marker: str, extra_env=None):
    worker = os.path.join(os.path.dirname(__file__), worker_file)
    coord, sync = _free_port(), _free_port()
    env = dict(os.environ)
    # the workers pin their own platform/device-count; scrub inherited
    # settings that would fight them
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env.update(extra_env or {})

    procs = [subprocess.Popen(
        [sys.executable, worker, str(pid), str(coord), str(sync)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in (0, 1)]
    outs = ["", ""]
    deadline = 240
    import time
    t0 = time.time()
    try:
        for k, p in enumerate(procs):
            left = max(1.0, deadline - (time.time() - t0))
            outs[k], _ = p.communicate(timeout=left)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        # drain whatever the killed workers managed to print
        for k, p in enumerate(procs):
            try:
                out, _ = p.communicate(timeout=10)
                outs[k] = outs[k] or out or ""
            except Exception:
                pass
        pytest.fail("multihost workers timed out:\n"
                    + "\n---\n".join(o[-3000:] for o in outs))

    for pid, (p, out) in enumerate(zip(procs, outs)):
        tail = "\n".join(out.splitlines()[-25:])
        assert p.returncode == 0, f"worker {pid} failed:\n{tail}"
        assert f"{ok_marker} p{pid}" in out, f"worker {pid} output:\n{tail}"


def test_two_process_sync_and_global_mesh():
    """Interpretive DocSets over the reference JSON protocol (r2 shape)."""
    _run_workers("multihost_worker.py", "MULTIHOST-OK")


def test_two_process_resident_columnar_sync():
    """Device-resident EngineDocSets syncing BINARY columnar frames over
    TCP, then a global-mesh SPMD reconcile + clock-union collective
    (VERDICT r2 #7)."""
    _run_workers("multihost_resident_worker.py", "MULTIHOST-RESIDENT-OK")


def test_two_process_rows_backend_columnar_sync():
    """Same protocol, but document truth in the docs-minor streaming engine
    (EngineDocSet backend="rows") on both hosts."""
    _run_workers("multihost_resident_worker.py", "MULTIHOST-RESIDENT-OK",
                 extra_env={"AMTPU_MH_BACKEND": "rows"})


def test_four_process_hub_sync_and_global_mesh():
    """Four OS processes (2 virtual devices each): hub-and-spoke TCP sync
    with Connection forwarding relaying every spoke's changes, then ONE
    global 8-device jax.distributed mesh for the SPMD reconcile and a
    clock union that must contain all four hosts' seqs."""
    worker = os.path.join(os.path.dirname(__file__),
                          "multihost_ring_worker.py")
    coord, sync = _free_port(), _free_port()
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)

    nprocs = 4
    procs = [subprocess.Popen(
        [sys.executable, worker, str(pid), str(nprocs), str(coord),
         str(sync)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(nprocs)]
    outs = [""] * nprocs
    deadline = 300
    import time
    t0 = time.time()
    try:
        for k, p in enumerate(procs):
            left = max(1.0, deadline - (time.time() - t0))
            outs[k], _ = p.communicate(timeout=left)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        for k, p in enumerate(procs):
            try:
                out, _ = p.communicate(timeout=10)
                outs[k] = outs[k] or out or ""
            except Exception:
                pass
        pytest.fail("4-process workers timed out:\n"
                    + "\n---\n".join(o[-2000:] for o in outs))

    winners = set()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        tail = "\n".join(out.splitlines()[-25:])
        assert p.returncode == 0, f"worker {pid} failed:\n{tail}"
        assert f"MULTIHOST4-OK p{pid}" in out, f"worker {pid}:\n{tail}"
        for line in out.splitlines():
            if line.startswith(f"MULTIHOST4-OK p{pid}"):
                winners.add(line.split("winner=")[1].split()[0])
    # every host agreed on the same LWW winner for the contested field
    assert len(winners) == 1, winners


def test_two_process_sharded_service_columnar_sync():
    """The sharded service node (K engine shards behind one sync surface)
    syncing binary columnar frames over TCP between two OS processes."""
    _run_workers("multihost_resident_worker.py", "MULTIHOST-RESIDENT-OK",
                 extra_env={"AMTPU_MH_BACKEND": "sharded"})
