"""The baseline-calibration cost model (refmodel.py) must be semantically
faithful to the reference's apply path — same LWW winners, same conflict
sets, same causal queueing — or its measured time means nothing."""

import automerge_tpu as am
import refmodel
from automerge_tpu.core.change import Change, Op


def _doc_trace():
    s1 = am.change(am.init("A"), lambda d: am.assign(
        d, {"n": 1, "tag": "x", "flags": {"hot": True}}))
    s2 = am.merge(am.init("B"), s1)
    s1 = am.change(s1, lambda d: d.__setitem__("n", 2))
    s2 = am.change(s2, lambda d: am.assign(d, {"n": -1, "owner": "B"}))
    m = am.merge(s1, s2)
    return m, m._doc.opset.get_missing_changes({})


def _fold_root(diffs):
    final = {}
    conflicts = {}
    for d in diffs:
        if d.get("type") == "map" and d["obj"] == refmodel.ROOT:
            if d["action"] == "set":
                final[d["key"]] = d["value"]
                if d.get("conflicts"):
                    conflicts[d["key"]] = {c["actor"]: c["value"]
                                           for c in d["conflicts"]}
                else:
                    conflicts.pop(d["key"], None)
            elif d["action"] == "remove":
                final.pop(d["key"], None)
    return final, conflicts


def test_refmodel_lww_and_conflicts_match_oracle():
    doc, changes = _doc_trace()
    _, diffs = refmodel.apply_changes(refmodel._init_opset(), changes)
    final, conflicts = _fold_root(diffs)
    # scalar root fields must agree with the oracle (links are object ids
    # in the model; skip them)
    for k in ("n", "tag", "owner"):
        assert final[k] == doc[k], (k, final[k], doc[k])
    # the concurrent n-writes surface the loser as a conflict, like the
    # oracle's _conflicts (op_set.js:160-176 + getConflicts)
    want = am.get_conflicts(doc, doc)
    assert set(conflicts.get("n", {})) == set(want.get("n", {}))


def test_refmodel_queues_causally_unready():
    later = Change("A", 2, {}, (Op("set", refmodel.ROOT, key="k", value=2),))
    opset, diffs = refmodel.apply_changes(refmodel._init_opset(), [later])
    assert opset.get("queue") == (later,) and diffs == []
    first = Change("A", 1, {}, (Op("set", refmodel.ROOT, key="k", value=1),))
    opset, diffs = refmodel.apply_changes(opset, [first])
    assert opset.get("queue") == ()
    final, _ = _fold_root(diffs)
    assert final["k"] == 2  # both applied, in causal order


def test_refmodel_idempotent_redelivery():
    _, changes = _doc_trace()
    opset, d1 = refmodel.apply_changes(refmodel._init_opset(), changes)
    opset2, d2 = refmodel.apply_changes(opset, changes)
    assert d2 == []  # duplicate (actor, seq) deliveries are dropped
