"""Imperative transaction API and remaining proxy parity."""

import pytest

import automerge_tpu as am


class TestTransaction:
    def test_basic(self):
        doc = am.init()
        tx = am.begin(doc)
        tx.root["title"] = "hello"
        tx.root["items"] = [1]
        tx.root["items"].append(2)
        assert tx.root["items"] == [1, 2]  # reads see writes
        doc2 = tx.commit("setup")
        assert doc2 == {"title": "hello", "items": [1, 2]}
        assert doc == {}
        assert am.get_history(doc2)[-1].change["message"] == "setup"

    def test_empty_commit_returns_same_doc(self):
        doc = am.change(am.init(), lambda d: d.__setitem__("x", 1))
        tx = am.begin(doc)
        assert tx.commit() is doc

    def test_reuse_after_commit_raises(self):
        tx = am.begin(am.init())
        tx.root["x"] = 1
        tx.commit()
        with pytest.raises(RuntimeError):
            tx.commit()

    def test_rollback_discards(self):
        doc = am.change(am.init(), lambda d: d.__setitem__("x", 1))
        tx = am.begin(doc)
        tx.root["x"] = 999
        tx.rollback()
        assert doc == {"x": 1}

    def test_transaction_attribute_style(self):
        tx = am.begin(am.init())
        tx.root.name = "attr"
        doc = tx.commit()
        assert doc["name"] == "attr"


class TestProxyGet:
    def test_get_by_object_id(self):
        doc = am.change(am.init(), lambda d: d.__setitem__("m", {"x": 1}))
        obj_id = doc["m"]._object_id

        def cb(d):
            proxy = d._get(obj_id)
            assert proxy["x"] == 1
            proxy["y"] = 2
        doc2 = am.change(doc, cb)
        assert doc2["m"] == {"x": 1, "y": 2}


class TestMoreConformance:
    def test_insert_and_delete_in_same_change(self):
        doc = am.change(am.init(), lambda d: d.__setitem__("xs", ["a", "b"]))

        def cb(d):
            d["xs"].insert_at(1, "mid")
            d["xs"].delete_at(0)
        doc = am.change(doc, cb)
        assert doc == {"xs": ["mid", "b"]}

    def test_link_same_object_under_two_keys_then_delete_one(self):
        doc = am.change(am.init(), lambda d: d.__setitem__("a", {"v": 1}))
        doc = am.change(doc, lambda d: d.__setitem__("b", d["a"]))
        doc = am.change(doc, lambda d: d.__delitem__("a"))
        assert doc == {"b": {"v": 1}}
        doc = am.change(doc, lambda d: d["b"].__setitem__("v", 2))
        assert doc == {"b": {"v": 2}}

    def test_empty_change_is_undoable(self):
        doc = am.change(am.init(), lambda d: d.__setitem__("x", 1))
        doc = am.empty_change(doc, "noop")
        assert am.can_undo(doc)
        doc = am.undo(doc)  # undoing the empty change changes nothing
        assert doc == {"x": 1}

    def test_list_conflicts_via_get_conflicts(self):
        s1 = am.change(am.init("A"), lambda d: d.__setitem__("xs", ["v"]))
        s2 = am.merge(am.init("B"), s1)
        s1 = am.change(s1, lambda d: d["xs"].__setitem__(0, "from A"))
        s2 = am.change(s2, lambda d: d["xs"].__setitem__(0, "from B"))
        m = am.merge(s1, s2)
        conflicts = am.get_conflicts(m, m["xs"])
        assert conflicts == [{"A": "from A"}]

    def test_deeply_nested_incremental_update(self):
        doc = am.change(am.init(), lambda d: d.__setitem__(
            "a", {"b": {"c": {"d": {"e": 1}}}}))
        doc2 = am.change(doc, lambda d: d["a"]["b"]["c"]["d"].__setitem__("e", 2))
        assert doc2["a"]["b"]["c"]["d"]["e"] == 2
        assert doc["a"]["b"]["c"]["d"]["e"] == 1

    def test_concurrent_nested_object_creation_same_key(self):
        s1 = am.change(am.init("A"), lambda d: d.__setitem__("cfg", {"a": 1}))
        s2 = am.change(am.init("B"), lambda d: d.__setitem__("cfg", {"b": 2}))
        m1, m2 = am.merge(s1, s2), am.merge(s2, s1)
        # B wins; A's whole object is the conflict loser
        assert m1 == {"cfg": {"b": 2}}
        assert m1._conflicts["cfg"]["A"] == {"a": 1}
        assert am.equals(m1, m2)
