"""Subscription layer + relay fabric + SLO-coupled shedding (round 12).

Covers the interest-based partial-replication plane end to end:

- InterestSet semantics (cover/advert-only/unknown, prefix merge rule);
- sender-side filtering: unsubscribed docs are never framed, never
  advertised; explicitly-removed docs keep clock adverts but stop
  frames;
- late-subscribe backfill equals full-history convergence (hashes +
  ConvergenceAuditor), via the missing_changes plane;
- relay hubs: cover-set merge, deduped upward subscriptions, interest-
  filtered fan-down, crash re-homing of downstream interest;
- interest filtering composing with the chaos doc_stall fault, and the
  new sub_flap chaos class (inert-unset pinned);
- the admission governor: sustained converge-p99 breach -> delay/shed
  low-priority ingress, disclosed on sync_shed_*; SLO-engine coupling;
- the ledger's sub lanes + `perf explain` doc_unsubscribed cause + the
  export-cap satellite (AMTPU_DOCLEDGER_K honored, --k, truncation
  disclosed).
"""

from __future__ import annotations

import time
from collections import deque

import pytest

from automerge_tpu.core.change import Change, Op
from automerge_tpu.core.ids import ROOT_ID
from automerge_tpu.sync import epochs
from automerge_tpu.sync.connection import Connection, InterestSet
from automerge_tpu.sync.docset import DocSet
from automerge_tpu.sync.relay import RelayHub
from automerge_tpu.sync.service import EngineDocSet
from automerge_tpu.utils import chaos, metrics


# ---------------------------------------------------------------------------
# plumbing


class Pair:
    """Two Connections cross-wired through deques, pumped on demand."""

    def __init__(self, ds_a, ds_b, wire="columnar", label_a=None,
                 label_b=None):
        self.qa, self.qb = deque(), deque()  # a->b, b->a
        self.a = Connection(ds_a, self.qa.append, wire=wire)
        self.b = Connection(ds_b, self.qb.append, wire=wire)
        if label_a:
            self.b.peer_label = label_a
        if label_b:
            self.a.peer_label = label_b

    def pump(self):
        for _ in range(10_000):
            if not self.qa and not self.qb:
                return
            while self.qa:
                self.b.receive_msg(self.qa.popleft())
            while self.qb:
                self.a.receive_msg(self.qb.popleft())
        raise AssertionError("pair failed to quiesce")

    def open(self):
        self.a.open()
        self.b.open()
        self.pump()

    def close(self):
        for c in (self.a, self.b):
            try:
                c.close()
            except Exception:
                pass


def _write(ds, doc, actor, seqs, n=1):
    for _ in range(n):
        seqs[(actor, doc)] = seqs.get((actor, doc), 0) + 1
        ds.apply_changes(doc, [Change(
            actor=actor, seq=seqs[(actor, doc)], deps={},
            ops=[Op("set", ROOT_ID, key="k",
                    value=seqs[(actor, doc)])])])


@pytest.fixture(autouse=True)
def _clean_metrics():
    metrics.reset()
    yield
    metrics.reset()


# ---------------------------------------------------------------------------
# InterestSet semantics


def test_interest_defaults_to_everything():
    it = InterestSet()
    assert it.covers("anything") and it.wants_adverts("anything")
    assert not it.explicit


def test_interest_explicit_cover_advert_unknown():
    it = InterestSet()
    it.apply(add=["a"], prefixes=["chat/"])
    assert it.explicit
    assert it.covers("a") and it.covers("chat/7")
    assert not it.covers("b") and not it.wants_adverts("b")
    it.apply(remove=["a"])
    assert not it.covers("a")          # frames stop...
    assert it.wants_adverts("a")       # ...adverts keep flowing
    # prefix-covered docs are NOT removable by doc id (prefix wins)
    it.apply(remove=["chat/7"])
    assert it.covers("chat/7")
    it.apply(remove_prefixes=["chat/"])
    assert not it.covers("chat/7")
    # mode="all" resets everything
    it.apply(mode="all")
    assert it.covers("b") and not it.explicit


def test_interest_apply_reports_newly_covered_only():
    it = InterestSet()
    new, newp = it.apply(add=["a", "b"])
    assert new == ["a", "b"]
    new, _ = it.apply(add=["a", "c"])   # a already covered
    assert new == ["c"]
    _, newp = it.apply(prefixes=["p/"])
    assert newp == ["p/"]
    new, _ = it.apply(add=["p/x"])      # under the prefix: not "new"
    assert new == []


# ---------------------------------------------------------------------------
# sender-side filtering + backfill (engine services, columnar wire)


def test_unsubscribed_docs_never_framed_never_advertised():
    a, b = EngineDocSet(backend="rows"), EngineDocSet(backend="rows")
    p = Pair(a, b)
    seqs = {}
    try:
        p.b.subscribe(docs=["d0"])
        p.pump()
        p.open()
        _write(a, "d0", "A", seqs, 3)
        _write(a, "d1", "A", seqs, 3)
        p.pump()
        assert b.doc_ids == ["d0"]
        assert b.clock_of("d0") == a.clock_of("d0")
        # the ledger agrees: zero traffic lanes for d1 on b's side
        if b.doc_ledger is not None:
            sec = b.doc_ledger.section() or {}
            assert "d1" not in (sec.get("docs") or {})
        assert int(metrics.snapshot()
                   .get("sync_sub_frames_suppressed", 0)) > 0
    finally:
        p.close()
        a.close()
        b.close()


def test_unsubscribe_stops_frames_keeps_adverts():
    a, b = EngineDocSet(backend="rows"), EngineDocSet(backend="rows")
    p = Pair(a, b)
    seqs = {}
    try:
        p.b.subscribe(docs=["d0", "d1"])
        p.pump()
        p.open()
        _write(a, "d0", "A", seqs, 2)
        _write(a, "d1", "A", seqs, 2)
        p.pump()
        assert b.clock_of("d0") == {"A": 2}
        p.b.subscribe(remove=["d0"])
        p.pump()
        _write(a, "d0", "A", seqs, 3)
        _write(a, "d1", "A", seqs, 1)
        p.pump()
        # frames stopped: b's d0 frontier froze; d1 kept syncing
        assert b.clock_of("d0") == {"A": 2}
        assert b.clock_of("d1") == {"A": 3}
        # adverts kept flowing: b's ledger SEES the unreachable frontier
        led = b.doc_ledger
        assert led is not None
        sec = led.section() or {}
        lane = sec["docs"]["d0"]["peers"]
        (pv,) = lane.values()
        assert pv["advert_clock"] == {"A": 5}
        assert pv["unsubscribed"] is True
        assert sec["docs"]["d0"]["lag_changes"] == 3
    finally:
        p.close()
        a.close()
        b.close()


def test_late_subscribe_backfill_equals_full_history():
    a, b = EngineDocSet(backend="rows"), EngineDocSet(backend="rows")
    p = Pair(a, b)
    seqs = {}
    try:
        p.b.subscribe(docs=["warm"])
        p.pump()
        p.open()
        _write(a, "warm", "A", seqs, 2)
        for _ in range(10):
            _write(a, "deep", "A", seqs, 3)
            p.pump()
        assert b.doc_ids == ["warm"]
        backfills0 = int(metrics.snapshot().get("sync_sub_backfills", 0))
        p.b.subscribe(docs=["deep"])
        p.pump()
        # byte-identical state: equal engine hashes on the shared docs
        assert a.hashes_for(["deep", "warm"]) \
            == b.hashes_for(["deep", "warm"])
        assert b.clock_of("deep") == {"A": 30}
        assert int(metrics.snapshot()
                   .get("sync_sub_backfills", 0)) > backfills0
        # and the auditor agrees (digests filtered to the intersection)
        from automerge_tpu.sync.audit import ConvergenceAuditor
        auditor = ConvergenceAuditor(b, p.b, period_s=0)
        auditor.audit_once()
        p.pump()
        assert auditor.rounds_clean >= 1
        assert not auditor.divergences
    finally:
        p.close()
        a.close()
        b.close()


def test_prefix_subscription_and_backfill():
    a, b = EngineDocSet(backend="rows"), EngineDocSet(backend="rows")
    p = Pair(a, b)
    seqs = {}
    try:
        p.open()
        # b starts with explicit empty-ish interest
        p.b.subscribe(docs=["other"])
        p.pump()
        for k in range(3):
            _write(a, f"chat/{k}", "A", seqs, 2)
        _write(a, "misc", "A", seqs, 2)
        p.pump()
        assert not any(d.startswith("chat/") for d in b.doc_ids)
        p.b.subscribe(prefixes=["chat/"])
        p.pump()
        for k in range(3):
            assert b.clock_of(f"chat/{k}") == {"A": 2}
        assert "misc" not in b.doc_ids
    finally:
        p.close()
        a.close()
        b.close()


def test_interest_composes_with_chaos_doc_stall(monkeypatch):
    """A chaos-stalled doc inside the SUBSCRIBED set degrades to
    adverts exactly as on a full-sync connection, while interest keeps
    filtering everything else — the two planes compose."""
    a, b = EngineDocSet(backend="rows"), EngineDocSet(backend="rows")
    monkeypatch.setenv("AMTPU_CHAOS_STALL_DOC", "stalled")
    chaos.reload()
    p = Pair(a, b)
    seqs = {}
    try:
        p.b.subscribe(docs=["stalled", "fine"])
        p.pump()
        p.open()
        _write(a, "stalled", "A", seqs, 3)
        _write(a, "fine", "A", seqs, 3)
        _write(a, "unsub", "A", seqs, 3)
        p.pump()
        assert b.clock_of("fine") == {"A": 3}
        assert "unsub" not in b.doc_ids          # interest filtered
        assert "stalled" not in b.doc_ids or \
            b.clock_of("stalled") == {}          # chaos suppressed
        # ...but the advert got through: the ledger sees the frontier
        sec = (b.doc_ledger.section() or {}).get("docs", {})
        assert sec.get("stalled", {}).get("lag_changes", 0) >= 3
        assert int(metrics.snapshot().get("sync_frames_dropped", 0)) > 0
    finally:
        monkeypatch.delenv("AMTPU_CHAOS_STALL_DOC", raising=False)
        chaos.reload()
        p.close()
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# relay fabric


def _tree(n_leaves=4):
    """root -> hub -> leaves, all plain DocSets, pump-on-demand."""
    msgs = deque()
    conns = {}

    def link(ds_a, ds_b, name):
        a = Connection(ds_a, lambda m, n=name: msgs.append((n + ".b", m)),
                       wire="columnar")
        b = Connection(ds_b, lambda m, n=name: msgs.append((n + ".a", m)),
                       wire="columnar")
        conns[name + ".a"], conns[name + ".b"] = a, b
        return a, b

    def pump():
        for _ in range(100_000):
            if not msgs:
                return
            name, m = msgs.popleft()
            conns[name].receive_msg(m)
        raise AssertionError("tree failed to quiesce")

    root, hubds = DocSet(), DocSet()
    hub = RelayHub(hubds, label="hub")
    root_hub, hub_root = link(root, hubds, "rh")
    hub.set_upstream(hub_root)
    leaves, leaf_conns = [], []
    for i in range(n_leaves):
        leaf = DocSet()
        hub_side, leaf_side = link(hubds, leaf, f"hl{i}")
        hub.attach_child(hub_side)
        leaves.append(leaf)
        leaf_conns.append(leaf_side)
    return root, hub, leaves, leaf_conns, conns, msgs, pump, link


def test_relay_cover_merge_and_upward_dedup():
    root, hub, leaves, leaf_conns, conns, msgs, pump, _link = _tree(3)
    leaf_conns[0].subscribe(docs=["hot", "a"])
    pump()
    deduped0 = int(metrics.snapshot().get("sync_relay_sub_deduped", 0))
    leaf_conns[1].subscribe(docs=["hot", "b"])
    leaf_conns[2].subscribe(docs=["hot"])
    pump()
    docs, prefixes = hub.cover()
    assert docs == {"hot", "a", "b"} and not prefixes
    # "hot" went upstream ONCE; the two later adds were deduped
    assert int(metrics.snapshot()
               .get("sync_relay_sub_deduped", 0)) >= deduped0 + 2
    # root's hub-facing peer interest is the merged cover
    assert conns["rh.a"]._peer_interest.docs == {"hot", "a", "b"}


def test_relay_fan_down_filtered_and_dedup_proven_by_lanes():
    root, hub, leaves, leaf_conns, conns, msgs, pump, _link = _tree(3)
    leaf_conns[0].subscribe(docs=["hot", "a"])
    leaf_conns[1].subscribe(docs=["hot", "b"])
    leaf_conns[2].subscribe(docs=["hot"])
    pump()
    for c in conns.values():
        c.open()
    pump()
    seqs = {}
    for d in ("hot", "a", "b", "cold"):
        _write(root, d, "R", seqs, 2)
        pump()
    assert sorted(leaves[0].doc_ids) == ["a", "hot"]
    assert sorted(leaves[1].doc_ids) == ["b", "hot"]
    assert leaves[2].doc_ids == ["hot"]
    assert "cold" not in hub.doc_set.doc_ids
    for leaf in leaves:
        assert leaf.get_doc("hot")._doc.opset.clock == {"R": 2}
    snap = metrics.snapshot()
    # the dedup proof: every delivery was useful — zero duplicates
    assert int(snap.get("sync_conn_changes_delivered", 0)) > 0
    assert int(snap.get("sync_conn_changes_duplicate", 0) or 0) == 0


def test_relay_prefix_absorbs_doc_subscriptions_upstream():
    root, hub, leaves, leaf_conns, conns, msgs, pump, _link = _tree(2)
    leaf_conns[0].subscribe(docs=["chat/1"])
    pump()
    assert "chat/1" in conns["rh.a"]._peer_interest.docs
    leaf_conns[1].subscribe(prefixes=["chat/"])
    pump()
    up = conns["rh.a"]._peer_interest
    # the prefix went up; the absorbed doc-id sub was withdrawn
    assert "chat/" in up.prefixes
    assert up.covers("chat/1") and up.covers("chat/999")


def test_relay_crash_rehomes_downstream_interest():
    root, hub, leaves, leaf_conns, conns, msgs, pump, link = _tree(2)
    leaf_conns[0].subscribe(docs=["hot"])
    leaf_conns[1].subscribe(docs=["hot", "b"])
    pump()
    for c in conns.values():
        c.open()
    pump()
    seqs = {}
    _write(root, "hot", "R", seqs, 2)
    _write(root, "b", "R", seqs, 2)
    pump()
    # hub dies: close its connections; leaf 1 re-homes DIRECTLY to root
    for name in ("hl1.a", "hl1.b", "rh.a", "rh.b"):
        conns[name].close()
    orphan_interest = leaf_conns[1]._local_interest
    root_side, leaf_side = link(root, leaves[1], "rehome")
    leaf_side._local_interest = orphan_interest
    leaf_side.resubscribe()
    pump()
    root_side.open()
    leaf_side.open()
    pump()
    _write(root, "hot", "R", seqs, 2)
    _write(root, "b", "R", seqs, 1)
    pump()
    assert leaves[1].get_doc("hot")._doc.opset.clock == {"R": 4}
    assert leaves[1].get_doc("b")._doc.opset.clock == {"R": 3}
    assert int(metrics.snapshot().get("sync_sub_resubscribes", 0)) == 1


def test_relay_detach_child_releases_cover():
    root, hub, leaves, leaf_conns, conns, msgs, pump, _link = _tree(2)
    leaf_conns[0].subscribe(docs=["hot", "a"])
    leaf_conns[1].subscribe(docs=["hot"])
    pump()
    hub.detach_child(conns["hl0.a"])
    pump()
    docs, _ = hub.cover()
    assert docs == {"hot"}      # "a" released; "hot" still refcounted
    up = conns["rh.a"]._peer_interest
    assert not up.covers("a") and up.covers("hot")


# ---------------------------------------------------------------------------
# chaos sub_flap


def test_sub_flap_inert_unset():
    chaos.reload()
    assert chaos.sub_flap(None, "any-doc") is False
    assert "obs_chaos_injected{fault=sub_flap}" not in metrics.snapshot()


def test_sub_flap_churns_subscription_and_is_disclosed(monkeypatch):
    monkeypatch.setenv("AMTPU_CHAOS_SUB_FLAP_DOC", "victim")
    monkeypatch.setenv("AMTPU_CHAOS_SUB_FLAP_EVERY", "2")
    chaos.reload()
    a, b = EngineDocSet(backend="rows"), EngineDocSet(backend="rows")
    p = Pair(a, b)
    seqs = {}
    try:
        p.b.subscribe(docs=["victim", "fine"])
        p.pump()
        p.open()
        for _ in range(8):
            _write(a, "victim", "A", seqs, 1)
            _write(a, "fine", "A", seqs, 1)
            p.pump()
        snap = metrics.snapshot()
        assert int(snap.get("obs_chaos_injected{fault=sub_flap}", 0)) > 0
        # the ledger lane carries the churn evidence
        sec = (b.doc_ledger.section() or {}).get("docs", {})
        lane = next(iter(sec["victim"]["peers"].values()))
        assert int(lane.get("sub_events") or 0) >= 2
        assert b.clock_of("fine") == a.clock_of("fine")
    finally:
        chaos.reload()
        p.close()
        a.close()
        b.close()


def test_explain_names_doc_unsubscribed_not_a_stall():
    """A lagging-but-unsubscribed doc is EXPLAINED (doc_unsubscribed),
    never flagged in the hot list — the satellite contract."""
    from automerge_tpu.perf import explain as ex

    a, b = EngineDocSet(backend="rows"), EngineDocSet(backend="rows")
    if a.doc_ledger is not None:
        a.doc_ledger.label = "na"
    if b.doc_ledger is not None:
        b.doc_ledger.label = "nb"
    p = Pair(a, b, label_a="na", label_b="nb")
    seqs = {}
    try:
        p.b.subscribe(docs=["d0"])
        p.pump()
        p.open()
        _write(a, "d0", "A", seqs, 2)
        p.pump()
        p.b.subscribe(remove=["d0"])
        p.pump()
        _write(a, "d0", "A", seqs, 3)
        p.pump()
        views = ex.gather_local()
        rep = ex.explain_doc("d0", views, now=time.time())
        causes = [c["cause"] for c in rep["causes"]]
        assert causes and causes[0] == "doc_unsubscribed", causes
        assert ex.hot_docs(views) == []
    finally:
        p.close()
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# admission governor (SLO-coupled shedding)


def _cols(doc, seq):
    from automerge_tpu.native.wire import changes_to_columns
    return changes_to_columns([Change(
        actor="W", seq=seq, deps={},
        ops=[Op("set", ROOT_ID, key="k", value=seq)])])


def test_governor_delays_low_priority_only_and_discloses():
    svc = EngineDocSet(backend="rows")
    gov = epochs.IngressGovernor(
        bound_s=2.0, sustain_s=0.0, delay_s=0.03,
        high_priority=lambda d: d.startswith("vip"))
    svc.attach_governor(gov)
    try:
        assert gov.judge(0.5) is False
        assert gov.judge(9.0) is True
        t0 = time.perf_counter()
        svc.apply_columns("low", _cols("low", 1))
        slow = time.perf_counter() - t0
        t0 = time.perf_counter()
        svc.apply_columns("vip-doc", _cols("vip-doc", 1))
        vip = time.perf_counter() - t0
        assert slow >= 0.03 and vip < slow
        assert gov.judge(0.2) is False   # recovery transition
        snap = metrics.snapshot()
        assert int(snap.get("sync_shed_delayed", 0)) == 1
        assert int(snap.get("sync_shed_transitions", 0)) == 2
        assert snap.get("sync_shed_active") == 0
    finally:
        svc.close()


def test_governor_sustain_window_filters_transients():
    gov = epochs.IngressGovernor(bound_s=1.0, sustain_s=10.0)
    now = time.monotonic()
    assert gov.judge(5.0, now=now) is False          # breach starts
    assert gov.judge(5.0, now=now + 5) is False      # not sustained yet
    assert gov.judge(0.5, now=now + 6) is False      # recovered: reset
    assert gov.judge(5.0, now=now + 7) is False      # new breach window
    assert gov.judge(5.0, now=now + 18) is True      # sustained
    assert gov.admit("anything") > 0


def test_governor_shed_mode_raises_and_recovers():
    svc = EngineDocSet(backend="rows")
    gov = epochs.IngressGovernor(bound_s=1.0, sustain_s=0.0, mode="shed")
    svc.attach_governor(gov)
    try:
        gov.judge(9.0)
        with pytest.raises(epochs.IngressShedError):
            svc.apply_columns("low", _cols("low", 1))
        assert int(metrics.snapshot().get("sync_shed_dropped", 0)) == 1
        gov.judge(0.1)
        svc.apply_columns("low", _cols("low", 1))
        assert svc.clock_of("low") == {"W": 1}
    finally:
        svc.close()


def test_slo_engine_drives_governor():
    from automerge_tpu.perf.slo import SloEngine

    class FakeCollector:
        def __init__(self, p99):
            self.p99 = p99

        def fleet_state(self):
            return {"rollup": {"converge_p99_s": self.p99,
                               "watchdog_fires": 0, "retraced": 0},
                    "scrape": {"p50_s": 0.001}, "nodes": {}}

    eng = SloEngine()
    eng.governor = epochs.IngressGovernor(bound_s=2.0, sustain_s=0.0)
    eng.evaluate(FakeCollector(9.0))
    assert eng.governor.shedding is True
    eng.evaluate(FakeCollector(0.1))
    assert eng.governor.shedding is False


# ---------------------------------------------------------------------------
# export-cap satellite (AMTPU_DOCLEDGER_K / --k / truncation disclosure)


def test_export_cap_default_32_and_truncation_disclosed(monkeypatch):
    from automerge_tpu.sync import docledger
    monkeypatch.delenv("AMTPU_DOCLEDGER_K", raising=False)
    ds = DocSet()
    led = docledger.DocLedger(ds, top_k=64)
    assert led.export_k == 32
    conn = object()
    for k in range(50):
        led.record_send(f"doc{k:03d}", conn, 1)
    sec = led.section()
    assert sec["exported"] == 32
    assert sec["truncated"] == 18
    # per-call override (the --k path)
    sec_k = led.section(k=50)
    assert sec_k["exported"] == 50 and sec_k["truncated"] == 0


def test_export_cap_honors_explicit_env_k(monkeypatch):
    from automerge_tpu.sync import docledger
    monkeypatch.setenv("AMTPU_DOCLEDGER_K", "48")
    ds = DocSet()
    led = docledger.DocLedger(ds)
    assert led.top_k == 48 and led.export_k == 48
    conn = object()
    for k in range(48):
        led.record_send(f"doc{k:03d}", conn, 1)
    sec = led.section()
    assert sec["exported"] == 48 and sec["truncated"] == 0


def test_perf_top_hot_doc_panel_states_truncation():
    from automerge_tpu.perf.top import hot_doc_lines

    class St:
        def __init__(self, snap):
            self.last_snapshot = snap

    class Coll:
        def __init__(self, snap):
            self.nodes = {"n0": St(snap)}

    snap = {"docledger": {"nodes": {"n0": {
        "tracked": 40, "exported": 32, "truncated": 8,
        "docs": {"d0": {"lag_changes": 5, "lag_s": 1.0, "buffered": 0,
                        "behind_since": None, "behind_peer": "n1",
                        "peers": {}}}}}}}
    lines = hot_doc_lines(Coll(snap))
    assert any("+8 tracked doc(s) beyond the export cap" in line
               for line in lines)


# ---------------------------------------------------------------------------
# review-hardening regression pins (r12 post-review)


def test_pure_remove_on_full_interest_never_darkens_connection():
    """A remove-only first delta on a default full-interest connection
    keeps mode 'all' (exclusion style): ONLY the removed doc degrades
    to advert-only; every other doc keeps full sync. (Pre-fix, the set
    flipped to explicit-empty and the whole connection went dark.)"""
    it = InterestSet()
    it.apply(remove=["noisy"])
    assert not it.explicit
    assert not it.covers("noisy") and it.wants_adverts("noisy")
    assert it.covers("anything-else")
    # a re-add lifts the exclusion and reports it newly covered
    new, _ = it.apply(add=["noisy"])
    assert new == ["noisy"] and it.covers("noisy")

    # end-to-end: frames stop for the removed doc only
    a, b = EngineDocSet(backend="rows"), EngineDocSet(backend="rows")
    p = Pair(a, b)
    seqs = {}
    try:
        p.open()
        p.b.subscribe(remove=["noisy"])
        p.pump()
        _write(a, "noisy", "A", seqs, 2)
        _write(a, "fine", "A", seqs, 2)
        p.pump()
        assert b.clock_of("fine") == {"A": 2}
        assert "noisy" not in b.doc_ids
        # adverts kept flowing: the exclusion is visible as honest lag
        sec = (b.doc_ledger.section() or {}).get("docs", {})
        assert sec.get("noisy", {}).get("lag_changes", 0) >= 2
    finally:
        p.close()
        a.close()
        b.close()


def test_reset_resubscribe_does_not_inflate_hub_refcounts():
    """A reset-form sub on the SAME connection (resubscribe after a
    transient hiccup) must replace the peer interest, not double-count
    it: when the child later detaches, the cover releases fully."""
    root, hub, leaves, leaf_conns, conns, msgs, pump, _link = _tree(2)
    leaf_conns[0].subscribe(docs=["hot"])
    leaf_conns[1].subscribe(docs=["hot", "b"])
    pump()
    leaf_conns[1].resubscribe()      # same conn, reset form
    pump()
    docs, _ = hub.cover()
    assert docs == {"hot", "b"}
    hub.detach_child(conns["hl1.a"])
    pump()
    docs, _ = hub.cover()
    assert docs == {"hot"}           # "b" fully released, "hot" kept
    up = conns["rh.a"]._peer_interest
    assert not up.covers("b") and up.covers("hot")


def test_reset_to_empty_interest_stays_explicit():
    it = InterestSet()
    it.apply(add=["a"])
    it.apply(remove=["a"])           # explicit, empty docs
    wire = it.to_wire()
    fresh = InterestSet()
    fresh.apply(add=wire.get("add"), prefixes=wire.get("prefixes"),
                remove=wire.get("remove"), mode=wire.get("mode"))
    assert fresh.explicit
    assert not fresh.covers("unrelated")


def test_prefix_removal_restores_absorbed_upstream_doc_subs():
    """A prefix that absorbed doc-id subscriptions upstream must give
    them back when it departs — still-refcounted docs keep flowing."""
    root, hub, leaves, leaf_conns, conns, msgs, pump, _link = _tree(2)
    leaf_conns[0].subscribe(docs=["chat/1"])
    leaf_conns[1].subscribe(prefixes=["chat/"])
    pump()
    up = conns["rh.a"]._peer_interest
    assert "chat/" in up.prefixes
    leaf_conns[1].subscribe(remove_prefixes=["chat/"])
    pump()
    up = conns["rh.a"]._peer_interest
    assert "chat/" not in up.prefixes
    assert up.covers("chat/1")       # the absorbed doc-sub came back
    for c in conns.values():
        c.open()
    pump()
    seqs = {}
    _write(root, "chat/1", "R", seqs, 2)
    pump()
    assert leaves[0].get_doc("chat/1")._doc.opset.clock == {"R": 2}


def test_auditor_stays_green_after_unsubscribe():
    """An advert-only (unsubscribed) doc's frozen state must not turn
    every audit round into a digest mismatch: both sides digest the
    covered subset only."""
    from automerge_tpu.sync.audit import ConvergenceAuditor

    a, b = EngineDocSet(backend="rows"), EngineDocSet(backend="rows")
    p = Pair(a, b)
    seqs = {}
    try:
        p.b.subscribe(docs=["d0", "d1"])
        p.pump()
        p.open()
        _write(a, "d0", "A", seqs, 2)
        _write(a, "d1", "A", seqs, 2)
        p.pump()
        p.b.subscribe(remove=["d1"])
        p.pump()
        _write(a, "d1", "A", seqs, 3)   # b's d1 state is now frozen
        _write(a, "d0", "A", seqs, 1)
        p.pump()
        auditor = ConvergenceAuditor(b, p.b, period_s=0)
        auditor.audit_once()
        p.pump()
        assert auditor.rounds_clean >= 1, "frozen advert-only doc " \
            "degraded the audit to a per-round bisect"
        assert not auditor.divergences
    finally:
        p.close()
        a.close()
        b.close()


def test_history_sub_gates_run_independently_per_field():
    """A config-13 record missing only the growth exponent must still
    judge the other four gates (no silent vacation)."""
    import json
    import tempfile

    from automerge_tpu.perf import history

    rec = {"schema": 1, "at": 0.0, "source": "test", "backend": "cpu",
           "value": 1000, "unit": "ops/sec", "vs_baseline": 1.0,
           "configs": {"13": {"sub_converge_p99_s": 9.0,
                              "sub_backfill_ok": 0}}}
    with tempfile.NamedTemporaryFile("w", suffix=".jsonl",
                                     delete=False) as f:
        f.write(json.dumps(rec) + "\n")
        path = f.name
    rc, lines = history.check(path=path)
    assert rc == 1
    assert any("SUBSCRIBED-DOC SLO BREACH" in ln for ln in lines)
    assert any("late-subscribe backfill: MISS" in ln for ln in lines)
