"""Cross-path randomized soaks: every execution path the engine has —
interpretive oracle, packed XLA, docs-minor rows kernel, XL kernel, compact
byte wire — must produce identical state hashes on random mixed workloads;
and the streaming frames path must match the apply_rounds twin under
adversarial rounds (duplicates, multi-change docs, new actors)."""

import random

import jax
import jax.numpy as jnp
import numpy as np

import automerge_tpu as am
from automerge_tpu.engine.batchdoc import apply_batch
from automerge_tpu.engine.encode import encode_doc, stack_docs
from automerge_tpu.engine.pack import (apply_rows_hash,
                                       apply_rows_hash_bytes, pack_rows,
                                       pack_rows_bytes, rows_eligible)
from automerge_tpu.engine.pallas_kernels import reconcile_rows_hash

CHARS = "abcxyz "


def _random_doc(seed):
    r = random.Random(seed)
    base = am.change(am.init("base"), lambda d: am.assign(
        d, {"n": 0, "xs": [1], "t": am.Text()}))
    reps = {a: am.merge(am.init(a), base)
            for a in ("A", "B", "C")[:r.randint(1, 3)]}
    for _ in range(r.randint(3, 18)):
        a = r.choice(list(reps))
        d = reps[a]
        k = r.random()
        if k < 0.3:
            d = am.change(d, lambda x: x.__setitem__(
                r.choice("nmpq"), r.randint(0, 99)))
        elif k < 0.5:
            n = len(d["xs"])
            d = am.change(d, lambda x: x["xs"].insert_at(
                r.randint(0, n), r.randint(0, 9)))
        elif k < 0.65 and len(d["xs"]):
            d = am.change(d, lambda x: x["xs"].delete_at(
                r.randrange(len(x["xs"]))))
        elif k < 0.85:
            n = len(d["t"])
            d = am.change(d, lambda x: x["t"].insert_at(
                r.randint(0, n), r.choice(CHARS)))
        elif len(d["t"]):
            d = am.change(d, lambda x: x["t"].delete_at(
                r.randrange(len(x["t"]))))
        if r.random() < 0.2 and len(reps) > 1:
            d = am.merge(d, reps[r.choice([x for x in reps if x != a])])
        reps[a] = d
    m = None
    for d in reps.values():
        m = d if m is None else am.merge(m, d)
    return m._doc.opset.get_missing_changes({})


def test_all_batch_paths_hash_identically():
    docs = [_random_doc(i) for i in range(30)]
    n = len(docs)
    _, _, ref = apply_batch(docs)
    want = np.asarray(ref["hash"])[:n].astype(np.uint32)

    actors = sorted({c.actor for chs in docs for c in chs})
    encs = [encode_doc(c, actors) for c in docs]
    batch = stack_docs(encs)
    mf = batch.pop("max_fids")
    assert rows_eligible(batch, mf)
    rows, dims, _n = pack_rows(batch, mf)
    interp = jax.default_backend() != "tpu"
    base = np.asarray(apply_rows_hash(
        jnp.asarray(rows), dims, n, interpret=interp)).astype(np.uint32)
    np.testing.assert_array_equal(base, want)
    xl = np.asarray(reconcile_rows_hash(
        jnp.asarray(rows), dims, interp, True))[:n].astype(np.uint32)
    np.testing.assert_array_equal(xl, want)
    wire, bmeta, dims2, _n2 = pack_rows_bytes(batch, mf)
    byt = np.asarray(apply_rows_hash_bytes(
        jnp.asarray(wire), bmeta, dims2, interp))[:n].astype(np.uint32)
    np.testing.assert_array_equal(byt, want)


def test_streaming_frames_adversarial_rounds():
    from automerge_tpu.engine.resident_rows import ResidentRowsDocSet
    from automerge_tpu.sync.frames import encode_round_frame

    rng = random.Random(77)
    N = 8
    ids = [f"d{i}" for i in range(N)]
    docs, logs = {}, {}
    for i, did in enumerate(ids):
        d = am.change(am.init("M"), lambda x, i=i: am.assign(
            x, {"n": i, "xs": [i], "t": am.Text()}))
        docs[did] = d
        logs[did] = d._doc.opset.get_missing_changes({})
    a, b = ResidentRowsDocSet(ids), ResidentRowsDocSet(ids)
    boot = [{d: logs[d] for d in ids}]
    a.apply_rounds(boot)
    b.apply_rounds(boot)
    pending_dups = []
    for rnd in range(12):
        deltas = {}
        for did in rng.sample(ids, rng.randint(1, N)):
            prev = docs[did]
            new = prev
            for _ in range(rng.randint(1, 3)):
                k = rng.random()
                if k < 0.5:
                    new = am.change(new, lambda x, r=rng.randint(0, 999):
                                    x.__setitem__("n", r))
                elif k < 0.8:
                    n = len(new["t"])
                    new = am.change(new, lambda x, p=rng.randint(0, n):
                                    x["t"].insert_at(p, rng.choice("qrs")))
                else:
                    peer = am.change(
                        am.merge(am.init(f"P{rng.randint(0, 3)}"), new),
                        lambda x: x.__setitem__("p", 1))
                    new = am.merge(new, peer)  # new actors appear
            deltas[did] = new._doc.opset.get_missing_changes(
                prev._doc.opset.clock)
            docs[did] = new
        if deltas and rng.random() < 0.3:
            pending_dups.append(dict(deltas))
        if pending_dups and rng.random() < 0.4:
            for did, chs in pending_dups.pop(0).items():
                deltas[did] = list(deltas.get(did, [])) + list(chs)  # dups
        h = np.asarray(a.apply_round_frames(
            [encode_round_frame(deltas)]))[:N]
        hs = b.apply_rounds([deltas])
        np.testing.assert_array_equal(h, hs[-1], err_msg=f"round {rnd}")
    a.sync_tables()
    b.sync_tables()
    for ta, tb in zip(a.tables, b.tables):
        assert ta.clock == tb.clock
        assert ta.n_changes == tb.n_changes
