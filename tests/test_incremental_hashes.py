"""The incremental convergence plane (r6 tentpole): O(dirty) hash reads.

Pins the three product claims the plane makes:

1. incremental `hashes()` ≡ full recompute — a hypothesis property over
   random interleavings of delta admission, flush coalescing, log-horizon
   archival, compaction, rebuild-from-log, and injected dispatch failure
   (the r5 recovery classes), asserting after every step that the
   mirror-served incremental read equals a from-scratch reconcile of the
   same host row state;
2. a clean-fleet read performs ZERO reconcile dispatches and zero device
   readbacks (asserted via the exact perfscope dispatch counters — the
   acceptance criterion of ISSUE 5);
3. partial reads (`hashes_for`, the auditor's bisect read) never
   reconcile untouched docs, and per-shard caches serve clean shards
   without touching their engines.
"""

from __future__ import annotations

import numpy as np
import pytest

try:
    from hypothesis import HealthCheck, given, settings
    from hypothesis import strategies as st
    HAVE_HYPOTHESIS = True
except ImportError:  # pragma: no cover — the CI image ships no hypothesis
    HAVE_HYPOTHESIS = False

from automerge_tpu.core.change import Change, Op
from automerge_tpu.core.ids import ROOT_ID
from automerge_tpu.engine.resident_rows import DeviceDispatchError
from automerge_tpu.native.wire import changes_to_columns
from automerge_tpu.sync.service import EngineDocSet
from automerge_tpu.sync.sharded_service import ShardedEngineDocSet
from automerge_tpu.utils import metrics

RECONCILE_KERNELS = ("reconcile_rows_hash", "apply_final", "scan_rounds",
                     "apply_doc")


def _change(actor: str, seq: int, key: str, val: int,
            deps: dict | None = None) -> Change:
    return Change(actor=actor, seq=seq, deps=deps or {},
                  ops=[Op("set", ROOT_ID, key=key, value=val)])


def _cols(actor: str, seq: int, key: str, val: int, deps=None):
    return changes_to_columns([_change(actor, seq, key, val, deps)])


def _force_full(svc: EngineDocSet) -> dict[str, int]:
    """Full recompute: wipe the incremental plane (mirror, dirty set,
    cached device handle AND buffer) and read — the oracle the
    incremental read must equal."""
    r = svc._resident
    r._hash_mirror = None
    r._doc_dirty = set(range(len(r.doc_ids)))
    r._hash_handle = None
    r._dirty = True
    r.rows_dev = None
    return svc.hashes()


def _dispatch_counts() -> dict[str, int]:
    """Per-kernel dispatch counts from the perfscope section of the
    metrics snapshot (the EXACT counters metrics.dispatch_jit maintains)."""
    perf = metrics.snapshot().get("perf") or {}
    kernels = perf.get("kernels") or {}
    return {k: (kernels.get(k) or {}).get("dispatches", 0)
            for k in RECONCILE_KERNELS}


# ---------------------------------------------------------------------------
# acceptance: clean-fleet reads are free


def test_clean_fleet_read_zero_reconcile_dispatches():
    """After one reconciled read, a clean-fleet hashes() must do ZERO
    reconcile dispatches — served purely from the per-shard hash caches
    (ISSUE 5 acceptance: asserted via the perfscope dispatch counters)."""
    svc = ShardedEngineDocSet(n_shards=3)
    with svc.batch():
        for i in range(90):
            svc.apply_columns(f"d{i}", _cols(f"W{i % 5}", 1, "k", i))
    h1 = svc.hashes()          # pays the reconcile (everything dirty)
    before = _dispatch_counts()
    flat_before = metrics.snapshot().get(
        "engine_kernels_dispatched{kernel=reconcile_rows_hash}", 0)
    h2 = svc.hashes()          # clean fleet: must be cache-only
    after = _dispatch_counts()
    flat_after = metrics.snapshot().get(
        "engine_kernels_dispatched{kernel=reconcile_rows_hash}", 0)
    assert h2 == h1
    assert after == before, f"clean read dispatched: {before} -> {after}"
    assert flat_after == flat_before
    assert svc.last_hashes_clean_shards == 3
    assert svc.last_hashes_dirty_shards == 0
    for s in svc.shards:
        assert s._resident.hashes_clean


def test_single_dirty_shard_fans_out_to_one_shard():
    svc = ShardedEngineDocSet(n_shards=3)
    with svc.batch():
        for i in range(60):
            svc.apply_columns(f"d{i}", _cols("A", 1, "k", i))
    h1 = svc.hashes()
    victim = "d7"
    svc.apply_columns(victim, _cols("A", 2, "k", 999))
    h2 = svc.hashes()
    assert svc.last_hashes_dirty_shards == 1
    assert svc.last_hashes_clean_shards == 2
    changed = {d for d in h1 if h1[d] != h2[d]}
    assert changed == {victim}
    # and the dirty shard's engine reconciled ONLY the touched lane set
    assert h2 == _force_full_sharded(svc)


def _force_full_sharded(svc: ShardedEngineDocSet) -> dict[str, int]:
    out: dict[str, int] = {}
    for s in svc.shards:
        out.update(_force_full(s))
    return out


def test_partial_read_reconciles_only_requested():
    """hashes_for must leave unrequested dirty docs dirty (their
    reconcile is deferred until someone actually asks)."""
    svc = EngineDocSet(backend="rows")
    with svc.batch():
        for i in range(64):
            svc.apply_columns(f"d{i}", _cols("A", 1, "k", i))
    svc.hashes()
    with svc.batch():
        for i in range(8):
            svc.apply_columns(f"d{i}", _cols("A", 2, "k", -i))
    r = svc._resident
    asked = ["d0", "d1", "d2"]
    out = svc.hashes_for(asked + ["never-created"])
    assert set(out) == set(asked)
    # the five untouched-by-the-read dirty docs are STILL dirty
    still = {r.doc_ids[i] for i in r._doc_dirty}
    assert {f"d{i}" for i in range(3, 8)} <= still
    assert not any(d in still for d in asked)
    # and the values are the converged ones
    full = _force_full(svc)
    assert all(out[d] == full[d] for d in asked)


def test_archival_does_not_invalidate_hashes(tmp_path):
    """Log-horizon archival moves change_log entries out of RAM but does
    not touch row state: the mirror must stay clean (zero-dispatch read)
    and the hashes identical."""
    svc = EngineDocSet(backend="rows", log_archive_dir=str(tmp_path),
                       log_horizon_changes=2)
    for i in range(8):
        for seq in (1, 2, 3, 4):
            svc.apply_columns(f"d{i}", _cols("A", seq, f"k{seq % 2}",
                                             seq * 10 + i))
    h1 = svc.hashes()
    archived = svc.archive_logs()
    assert sum(archived.values()) > 0, "nothing archived — test is vacuous"
    before = _dispatch_counts()
    h2 = svc.hashes()
    assert h2 == h1
    assert _dispatch_counts() == before, \
        "archival alone must not force a reconcile"
    assert h2 == _force_full(svc)


def test_compaction_invalidates_and_matches_full():
    svc = EngineDocSet(backend="rows")
    for i in range(6):
        for seq in range(1, 9):   # enough dominated ops to reclaim
            svc.apply_columns(f"d{i}", _cols("A", seq, "k", seq))
    h1 = svc.hashes()
    r = svc._resident
    floors = {d: dict(r.tables[r.doc_index[d]].clock) for d in r.doc_ids}
    stats = r.compact(floors)
    assert any(s["ops_after"] < s["ops_before"] for s in stats.values())
    assert not r.hashes_clean, "compaction must dirty the moved docs"
    h2 = svc.hashes()
    assert h2 == h1, "compaction must preserve convergence hashes"
    assert h2 == _force_full(svc)


def test_dispatch_failure_then_retry_recovers(monkeypatch):
    svc = EngineDocSet(backend="rows")
    for i in range(40):
        svc.apply_columns(f"d{i}", _cols("A", 1, "k", i))
    svc.hashes()
    svc.apply_columns("d3", _cols("A", 2, "k", 77))

    real = metrics.dispatch_jit
    state = {"fail": True}

    def flaky(kernel, fn, *a, **kw):
        if state["fail"] and kernel == "reconcile_rows_hash":
            state["fail"] = False
            raise RuntimeError("injected device fault")
        return real(kernel, fn, *a, **kw)

    monkeypatch.setattr(metrics, "dispatch_jit", flaky)
    # resident_rows imported dispatch_jit via the metrics module object,
    # so patching the module attribute is enough
    with pytest.raises(DeviceDispatchError):
        svc.hashes()
    h = svc.hashes()            # retry: dirty set survived the failure
    assert "d3" in h
    assert h == _force_full(svc)


def test_epoch_monotonic_across_rebuild():
    svc = EngineDocSet(backend="rows")
    for i in range(6):
        svc.apply_columns(f"d{i}", _cols("A", 1, "k", i))
    h1, e1 = svc.hashes_snapshot()
    assert not svc.hashes_dirty_since(e1)
    svc._resident._rebuild_from_log()
    assert svc.hashes_dirty_since(e1), \
        "rebuild must not be invisible to epoch holders"
    h2, e2 = svc.hashes_snapshot()
    assert e2 > e1
    assert h2 == h1             # rebuild replays the same admitted log


def test_pending_ingress_counts_as_dirty():
    svc = EngineDocSet(backend="rows")
    svc.apply_columns("d0", _cols("A", 1, "k", 1))
    _h, epoch = svc.hashes_snapshot()
    cm = svc.batch()
    with cm:
        svc.apply_columns("d0", _cols("A", 2, "k", 2))
        # coalesced, not yet flushed: a read WOULD flush, so it is dirty
        assert svc.hashes_dirty_since(epoch)


def test_docs_major_incremental_matches_full():
    """The docs-major engine shares the plane: scatter-only applies mark
    dirty docs; hashes() partial-reconciles only those."""
    svc = EngineDocSet(backend="resident")
    for i in range(24):
        svc.apply_changes(f"d{i}", [_change("A", 1, "k", i)])
    h1 = svc.hashes()
    r = svc._resident
    assert r.hashes_clean
    for i in range(3):
        svc.apply_changes(f"d{i}", [_change("A", 2, "k", 1000 + i)])
    h2 = svc.hashes()
    changed = {d for d in h1 if h1[d] != h2[d]}
    assert changed == {"d0", "d1", "d2"}
    # force-full on docs-major: wipe mirror + cached reconcile output
    r._hash_mirror = None
    r._doc_dirty = set(range(len(r.doc_ids)))
    r._out = None
    assert svc.hashes() == h2


def test_poisoned_engine_still_raises_on_hash_read():
    svc = EngineDocSet(backend="rows")
    svc.apply_columns("d0", _cols("A", 1, "k", 1))
    svc.hashes()
    svc._resident._poison(RuntimeError("boom"))
    assert not svc._resident.hashes_clean
    with pytest.raises(RuntimeError, match="no longer reflects"):
        svc.hashes()


# ---------------------------------------------------------------------------
# the property: incremental ≡ full recompute across random interleavings
#
# The walk is shared by two drivers: the hypothesis property (shrinkable,
# skipped when the image ships no hypothesis — the repo's standing fuzz
# convention) and a seeded deterministic variant that ALWAYS runs in
# tier-1, so the invariant is never silently uncovered.

ACTIONS = ("admit", "admit2", "burst", "archive", "compact",
           "rebuild", "fail_read")


def _interleaving_walk(tmp: str, n_steps: int, choose):
    """Run one interleaving of the r5 recovery classes, asserting after
    EVERY step that the incremental read equals a full recompute of the
    same host row state. `choose(options)` supplies the randomness."""
    docs = [f"d{i}" for i in range(5)]
    svc = EngineDocSet(backend="rows", log_archive_dir=tmp,
                       log_horizon_changes=3)
    seqs = {(d, a): 0 for d in docs for a in ("A", "B")}
    real_dispatch = metrics.dispatch_jit

    def admit(d, actor):
        seqs[(d, actor)] += 1
        seq = seqs[(d, actor)]
        svc.apply_columns(d, _cols(actor, seq, f"k{seq % 3}",
                                   seq * 7 + ord(actor)))

    for _ in range(n_steps):
        action = choose(ACTIONS)
        if action == "admit":
            admit(choose(docs), "A")
        elif action == "admit2":
            admit(choose(docs), "B")
        elif action == "burst":
            with svc.batch():
                k = choose((1, 2, 3, 4))
                for d in docs[:k]:
                    admit(d, "A")
        elif action == "archive":
            svc.archive_logs()
        elif action == "compact":
            svc.flush()
            r = svc._resident
            floors = {d: dict(r.tables[r.doc_index[d]].clock)
                      for d in r.doc_ids}
            r.compact(floors)
        elif action == "rebuild":
            svc.flush()
            svc._resident._rebuild_from_log()
        elif action == "fail_read":
            state = {"armed": True}

            def flaky(kernel, fn, *a, **kw):
                if state["armed"] and kernel == "reconcile_rows_hash":
                    state["armed"] = False
                    raise RuntimeError("injected fault")
                return real_dispatch(kernel, fn, *a, **kw)

            metrics.dispatch_jit = flaky
            try:
                if svc._resident.hashes_clean and not svc._pending:
                    svc.hashes()       # clean read: no dispatch to fail
                else:
                    with pytest.raises(DeviceDispatchError):
                        svc.hashes()
            finally:
                metrics.dispatch_jit = real_dispatch
        # THE invariant: the incremental read (whatever mix of mirror,
        # cached handle, and partial lanes it uses) equals a full
        # recompute of the same host state
        h_inc = svc.hashes()
        assert h_inc == _force_full(svc)


@pytest.mark.parametrize("seed", [11, 23, 47, 101])
def test_incremental_equals_full_recompute_seeded(tmp_path, seed):
    """Deterministic driver of the interleaving walk (always runs —
    hypothesis is optional in the CI image)."""
    import random
    rng = random.Random(seed)
    _interleaving_walk(str(tmp_path / str(seed)), n_steps=9,
                       choose=rng.choice)


if HAVE_HYPOTHESIS:
    @settings(max_examples=10, deadline=None,
              suppress_health_check=[HealthCheck.too_slow,
                                     HealthCheck.data_too_large])
    @given(st.data())
    def test_incremental_equals_full_recompute_property(tmp_path_factory,
                                                        data):
        """Shrinkable hypothesis driver of the same walk (deep runs:
        AMTPU_FUZZ_EXAMPLES-style, see tests/test_hypothesis_*)."""
        tmp = tmp_path_factory.mktemp("hashprop")
        n_steps = data.draw(st.integers(4, 10), label="n_steps")
        _interleaving_walk(
            str(tmp), n_steps,
            choose=lambda opts: data.draw(st.sampled_from(list(opts))))
