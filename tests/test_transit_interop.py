"""Transit-format interop tests.

The reference saves documents as transit JSON of the change history
(/root/reference/src/automerge.js:223-226 via transit-immutable-js). These
tests cover the codec itself (escapes, caching, tags) and document-level
round-trips, including decoding a hand-built fixture in exactly the form
transit-js emits (tag caching with ^-codes).
"""

import json
import math

import automerge_tpu as am
from automerge_tpu.interop import transit


class TestCodec:
    def test_scalar_roundtrip(self):
        for v in ["hello", "", 0, 1, -7, 1.5, True, False, None]:
            assert transit.loads(transit.dumps(v)) == v

    def test_top_level_scalar_is_quoted(self):
        assert json.loads(transit.dumps(42)) == ["~#'", 42]

    def test_escape_roundtrip(self):
        for s in ["~tilde", "^caret", "`tick", "~~", "^ ", "~:notkw"]:
            assert transit.loads(transit.dumps(s)) == s

    def test_special_floats(self):
        assert math.isnan(transit.loads(transit.dumps(math.nan)))
        assert transit.loads(transit.dumps(math.inf)) == math.inf
        assert transit.loads(transit.dumps(-math.inf)) == -math.inf

    def test_big_int_precision(self):
        big = (1 << 60) + 3
        assert transit.loads(transit.dumps(big)) == big
        assert f"~i{big}" in transit.dumps(big)

    def test_map_and_list_tags(self):
        doc = {"a": 1, "xs": [1, "two", None]}
        encoded = json.loads(transit.dumps(doc))
        assert encoded[0] == "~#iM"
        assert transit.loads(transit.dumps(doc)) == doc

    def test_tag_caching_assigns_codes_in_write_order(self):
        # two maps inside a list: iL first (code ^0), iM second (code ^1);
        # the second map must be emitted via the cache code.
        val = [{"k": 1}, {"k": 2}]
        raw = transit.dumps(val)
        j = json.loads(raw)
        assert j[0] == "~#iL"
        assert j[1][0][0] == "~#iM"
        assert j[1][1][0] == "^1"       # iL took ^0, iM took ^1
        assert transit.loads(raw) == val

    def test_decodes_keywords_and_symbols_as_strings(self):
        assert transit.loads('["~#\'","~:actor"]') == "actor"
        assert transit.loads('["~#\'","~$sym"]') == "sym"

    def test_decodes_verbose_map(self):
        assert transit.loads('{"a":1,"b":[1,2]}') == {"a": 1, "b": [1, 2]}

    def test_decodes_caret_space_map_with_key_caching(self):
        # map keys >3 chars are cacheable; the repeat uses the code
        raw = '[["^ ","actorId",1],["^ ","^0",2]]'
        assert transit.loads(raw) == [{"actorId": 1}, {"actorId": 2}]

    def test_cache_reset_after_capacity(self):
        # 44*44 distinct cacheable keys overflow the cache; the writer
        # resets and the reader must follow the same reset rule.
        n = 44 * 44 + 10
        val = [{f"key{i:04d}": i} for i in range(n)] * 2
        assert transit.loads(transit.dumps(val)) == val


class TestReferenceFixture:
    def test_decode_handwritten_reference_save(self):
        """A save in the exact shape transit-js produces for a two-change
        history: iL/iM tags cached after first use, plain-string keys in
        iM rep arrays, scalar values inline."""
        fixture = json.dumps([
            "~#iL",
            [["~#iM", ["ops",
                       ["^0", [["^1", ["action", "set", "obj",
                                       "00000000-0000-0000-0000-000000000000",
                                       "key", "title", "value", "hello"]]]],
                       "actor", "aaaa", "seq", 1,
                       "deps", ["^1", []]]],
             ["^1", ["ops",
                     ["^0", [["^1", ["action", "set", "obj",
                                     "00000000-0000-0000-0000-000000000000",
                                     "key", "n", "value", 7]]]],
                     "actor", "bbbb", "seq", 1,
                     "deps", ["^1", ["aaaa", 1]]]]],
        ], separators=(",", ":"))
        doc = am.load_transit(fixture)
        assert doc["title"] == "hello"
        assert doc["n"] == 7
        changes = transit.changes_from_transit(fixture)
        assert [c.actor for c in changes] == ["aaaa", "bbbb"]
        assert changes[1].deps == {"aaaa": 1}


class TestDocumentRoundTrip:
    def build(self):
        d = am.change(am.init("A"), lambda doc: am.assign(doc, {
            "title": "board", "cards": [{"t": "one", "done": False}],
            "meta": {"n": 3, "odd~key": "^weird"},
        }))
        d2 = am.change(am.merge(am.init("B"), d),
                       lambda doc: doc["cards"].append({"t": "two", "done": True}))
        d = am.change(d, lambda doc: doc.__setitem__("title", "board!"))
        return am.merge(d, d2)

    def test_save_transit_load_transit(self):
        doc = self.build()
        data = am.save_transit(doc)
        loaded = am.load_transit(data, "C")
        assert am.equals(loaded, doc)
        # history survives byte-for-byte: re-save matches
        assert am.save_transit(loaded) == data

    def test_transit_save_matches_json_save_semantics(self):
        doc = self.build()
        via_transit = am.load_transit(am.save_transit(doc), "C")
        via_json = am.load(am.save(doc), "C")
        assert am.equals(via_transit, via_json)

    def test_text_and_message_roundtrip(self):
        def mk(doc):
            doc["t"] = am.Text()
            doc["t"].insert_at(0, *"hi~^`there")
        d = am.change(am.init("A"), "made text", mk)
        loaded = am.load_transit(am.save_transit(d))
        assert "".join(loaded["t"]) == "hi~^`there"
        assert am.get_history(loaded)[-1].change["message"] == "made text"

    def test_conflicts_survive_roundtrip(self):
        # test/test.js:1107-1116: conflicts must survive save/load
        d1 = am.change(am.init("A"), lambda d: d.__setitem__("x", "from A"))
        d2 = am.change(am.init("B"), lambda d: d.__setitem__("x", "from B"))
        m = am.merge(d1, d2)
        loaded = am.load_transit(am.save_transit(m))
        assert loaded["x"] == m["x"]
        assert am.get_conflicts(loaded, loaded) == am.get_conflicts(m, m)
