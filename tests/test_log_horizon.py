"""Log-horizon layer (sync/logarchive.py + archive_log_prefix): the
causally-stable log prefix moves out of RAM; the reference wire protocol
keeps working via transparent archive cold-reads; rebuild-from-log replays
archive + tail; a lagging registered peer bounds what may be archived.
Completes the long-lived-document story: row compaction bounds device
memory, the horizon bounds host memory."""

import numpy as np
import pytest

import automerge_tpu as am
from automerge_tpu.core.change import Change
from automerge_tpu.sync.connection import Connection
from automerge_tpu.sync.docset import DocSet
from automerge_tpu.sync.service import EngineDocSet
from automerge_tpu.utils import metrics

from tests.test_rows_service import drain, oracle_hash


def changes_of(doc):
    return doc._doc.opset.get_missing_changes({})


def history(n_rounds=40):
    d = am.change(am.init("alice"), lambda x: x.__setitem__("t", am.Text()))
    d = am.change(d, lambda x: x["t"].insert_at(0, *"hello"))
    for k in range(n_rounds):
        d = am.change(d, lambda x, k=k: x.__setitem__("n", k))
    return d


def make_service(tmp_path, **kw):
    return EngineDocSet(backend="rows",
                        log_archive_dir=str(tmp_path / "arch"), **kw)


def test_archive_shrinks_ram_log_and_serves_full_history(tmp_path):
    d = history()
    chs = changes_of(d)
    e = make_service(tmp_path)
    e.apply_changes("doc", chs)
    rset = e._resident
    i = rset.doc_index["doc"]
    h0 = np.uint32(e.hashes()["doc"])
    before = e.missing_changes("doc", {})
    ram_before = len(rset.change_log[i])

    moved = e.archive_logs()["doc"]
    assert moved == ram_before            # no peers: floor = own clock
    assert len(rset.change_log[i]) == 0
    assert rset.log_horizon[i]            # horizon advanced

    # full-history serve now cold-reads the archive, same change set
    after = e.missing_changes("doc", {})
    assert sorted((c.actor, c.seq) for c in after) == \
        sorted((c.actor, c.seq) for c in before)
    assert np.uint32(e.hashes()["doc"]) == h0


def test_fresh_peer_syncs_through_archive_over_wire(tmp_path):
    d = history()
    e = make_service(tmp_path)
    e.apply_changes("doc", changes_of(d))
    e.archive_logs()

    fresh = DocSet()
    qa, qb = [], []
    ca = Connection(e, qa.append)
    cb = Connection(fresh, qb.append)
    ca.open(); cb.open()
    cb.send_msg("doc", {})
    drain(qa, ca, qb, cb)
    got = fresh.get_doc("doc")
    assert got is not None
    assert "".join(got["t"]) == "hello"
    assert got["n"] == 39


def test_caught_up_peer_never_cold_reads(tmp_path):
    d = history()
    chs = changes_of(d)
    e = make_service(tmp_path)
    e.apply_changes("doc", chs[:-3])
    e.archive_logs()
    e.apply_changes("doc", chs[-3:])      # tail stays in RAM

    metrics.reset()
    horizon_clock = {c.actor: c.seq for c in chs[:-3]}
    out = e.missing_changes("doc", horizon_clock)
    assert len(out) == 3
    assert metrics.snapshot().get("sync_archive_cold_reads", 0) == 0


def test_lagging_registered_peer_bounds_the_horizon(tmp_path):
    d = history()
    chs = changes_of(d)
    e = make_service(tmp_path)
    e.apply_changes("doc", chs)
    # peer acked only the first 10 changes
    e.note_peer_clock("peer-1", "doc", {"alice": 10})
    moved = e.archive_logs()["doc"]
    assert moved == 10                    # only the acked prefix left RAM
    rset = e._resident
    i = rset.doc_index["doc"]
    assert len(rset.change_log[i]) == len(chs) - 10

    # the lagging peer's catch-up comes wholly from RAM (no cold read)
    metrics.reset()
    out = e.missing_changes("doc", {"alice": 10})
    assert len(out) == len(chs) - 10
    assert metrics.snapshot().get("sync_archive_cold_reads", 0) == 0


def test_auto_archive_keeps_ram_log_bounded(tmp_path):
    e = make_service(tmp_path, log_horizon_changes=25)
    d = am.change(am.init("w"), lambda x: x.__setitem__("t", am.Text()))
    e.apply_changes("doc", changes_of(d))
    served = len(changes_of(d))
    rset = e._resident
    i = rset.doc_index["doc"]
    peak = 0
    for k in range(120):
        d = am.change(d, lambda x, k=k: x.__setitem__("n", k))
        new = changes_of(d)[served:]
        served += len(new)
        e.apply_changes("doc", new)
        peak = max(peak, len(rset.change_log[i]))
    assert peak <= 26 + 1                  # bounded near the threshold
    assert np.uint32(e.hashes()["doc"]) == oracle_hash(changes_of(d))
    assert "".join(e.materialize("doc")["data"]["t"]) == "".join(d["t"])
    # a brand-new observer still reconstructs everything
    fresh = am.apply_changes(am.init("obs"),
                             list(e.missing_changes("doc", {})))
    assert am.equals(fresh, d)


def test_rebuild_from_log_replays_archive_plus_tail(tmp_path):
    d = history()
    e = make_service(tmp_path)
    e.apply_changes("doc", changes_of(d))
    rset = e._resident
    if rset._native is None:
        pytest.skip("python-encoder fallback exercises a different path")
    e.archive_logs()

    # mid-admission failure on the next ingress -> rebuild-from-log,
    # which must replay the ARCHIVED prefix plus the RAM tail
    rset._cols_triplets = lambda enc: (_ for _ in ()).throw(
        MemoryError("grow failed mid-scatter"))
    d2 = am.change(d, lambda x: x.__setitem__("post", 1))
    e.apply_changes("doc", [changes_of(d2)[-1]])
    e.flush()
    assert np.uint32(e.hashes()["doc"]) == oracle_hash(changes_of(d2))
    # rebuilt instance holds the full log in RAM with a reset horizon;
    # re-archiving afterwards is clean (read-side dedup)
    e.archive_logs()
    fresh = am.apply_changes(am.init("obs"),
                             list(e.missing_changes("doc", {})))
    assert am.equals(fresh, d2)


def test_torn_archive_tail_is_skipped(tmp_path):
    """A crash mid-append can tear only the final line; read() skips it
    (the RAM log was not truncated for a failed append) while corruption
    before the tail still raises."""
    import json as _json

    d = history()
    e = make_service(tmp_path)
    e.apply_changes("doc", changes_of(d))
    e.archive_logs()
    rset = e._resident
    arch = rset.log_archive
    path = arch._path("doc")
    with open(path, "a") as f:
        f.write('{"actor": "alice", "se')     # torn mid-record, no newline
    got = arch.read("doc")
    assert len(got) == len(changes_of(d))
    fresh = am.apply_changes(am.init("obs"),
                             list(e.missing_changes("doc", {})))
    assert am.equals(fresh, d)
    # mid-file corruption is NOT silently skipped
    lines = open(path).read().split("\n")
    lines[1] = lines[1][:10]
    open(path, "w").write("\n".join(lines))
    with pytest.raises(_json.JSONDecodeError):
        arch.read("doc")


def test_append_after_torn_tail_repairs_not_glues(tmp_path):
    """An append following a torn tail must truncate the fragment first:
    gluing records onto it would turn a recoverable tear into permanent
    mid-file corruption."""
    from automerge_tpu.sync.logarchive import LogArchive

    d = history(6)
    chs = changes_of(d)
    arch = LogArchive(str(tmp_path / "a"))
    arch.append("d", chs[:3])
    with open(arch._path("d"), "a") as f:
        f.write('{"torn": tru')                 # crash mid-append
    assert len(arch.read("d")) == 3             # tail skipped
    arch.append("d", chs[3:])                   # repairs, then appends
    got = arch.read("d")
    assert sorted((c.actor, c.seq) for c in got) == \
        sorted((c.actor, c.seq) for c in chs)
    assert metrics.snapshot().get("sync_archive_tail_repaired")


def test_first_archive_append_fsyncs_directory(tmp_path, monkeypatch):
    """ADVICE low #1 (landed r8, pinned here): the FIRST creation of a
    doc's archive file must fsync the containing directory before
    append() returns — archive_log_prefix truncates the RAM log right
    after, so losing the brand-new directory entry in a crash would lose
    the only copy of the archived prefix. Later appends to the existing
    file must NOT re-pay the directory fsync."""
    import os as _os

    from automerge_tpu.sync.logarchive import LogArchive

    d = history(6)
    chs = changes_of(d)
    arch = LogArchive(str(tmp_path / "a"))
    dir_syncs = []
    real = LogArchive._fsync_dir
    monkeypatch.setattr(
        LogArchive, "_fsync_dir",
        lambda self: (dir_syncs.append(self.root), real(self))[1])
    arch.append("d", chs[:3])
    assert dir_syncs == [arch.root]     # first creation: directory synced
    assert _os.path.exists(arch._path("d"))
    arch.append("d", chs[3:])
    assert dir_syncs == [arch.root]     # existing file: no re-sync
    arch.append("d2", chs[:2])          # a NEW doc's file: synced again
    assert dir_syncs == [arch.root, arch.root]


def test_cold_read_parses_outside_lock_and_caches_prefix(tmp_path):
    """ADVICE low #2 (landed r8, pinned here): repeated cold reads of an
    unchanged archive are served from the parsed-prefix cache (one
    parse, keyed by file identity), the cache invalidates when the file
    grows, and the O(history) parse itself runs with the archive lock
    RELEASED — a concurrent append must be able to take the lock while
    a slow read is mid-parse."""
    import threading

    from automerge_tpu.sync import logarchive as la

    metrics.reset()
    d = history(8)
    chs = changes_of(d)
    arch = la.LogArchive(str(tmp_path / "a"))
    arch.append("d", chs[:4])
    assert len(arch.read("d")) == 4     # cold: parses
    m0 = metrics.snapshot().get("sync_archive_reads_cached", 0)
    assert len(arch.read("d")) == 4     # warm: cache hit
    assert metrics.snapshot().get("sync_archive_reads_cached", 0) == m0 + 1
    arch.append("d", chs[4:6])          # file identity moved
    assert len(arch.read("d")) == 6     # re-parse, not a stale serve
    assert metrics.snapshot().get("sync_archive_reads_cached", 0) == m0 + 1

    # the parse runs outside the lock: stall the parse via a slow json
    # decode and assert an append can acquire the archive lock meanwhile.
    # Grow the file first so the stalled read is a genuine re-parse,
    # not a cache hit.
    arch.append("d", chs[6:8])
    parse_started = threading.Event()
    release_parse = threading.Event()
    real_loads = la.json.loads
    stall = {"on": False}

    def slow_loads(s, *a, **kw):
        if stall["on"]:
            parse_started.set()
            release_parse.wait(timeout=10.0)
        return real_loads(s, *a, **kw)

    la.json.loads = slow_loads
    try:
        stall["on"] = True
        out: list = []
        t = threading.Thread(
            target=lambda: out.append(arch.read("d")),
            name="amtpu-test-coldread", daemon=True)
        t.start()
        assert parse_started.wait(timeout=10.0)
        # the reader is mid-parse NOW; the archive lock must be free
        got_lock = arch._lock.acquire(timeout=5.0)
        assert got_lock, "cold-read parse held the archive lock"
        arch._lock.release()
        stall["on"] = False
        release_parse.set()
        t.join(timeout=10.0)
        assert not t.is_alive() and len(out[0]) == 8
    finally:
        la.json.loads = real_loads
        release_parse.set()


def test_post_rebuild_overlap_is_not_served_twice(tmp_path):
    """After a rebuild restores the full log to RAM, a later PARTIAL
    re-archive leaves the archive holding more than the horizon covers;
    cold reads clip to the current horizon so no change ships twice."""
    d = history()
    chs = changes_of(d)
    e = make_service(tmp_path)
    e.apply_changes("doc", chs)
    e.archive_logs()                          # archive holds 1..N
    rset = e._resident
    i = rset.doc_index["doc"]
    # simulate the post-rebuild state: full log back in RAM, horizon reset,
    # then a lagging peer pins the re-archive at seq 10
    full = [c for c in e.missing_changes("doc", {})]
    rset.change_log[i] = list(full)
    rset.log_horizon[i] = {}
    e.note_peer_clock("peer-1", "doc", {"alice": 10})
    e.archive_logs()                          # horizon now alice:10
    assert rset.log_horizon[i] == {"alice": 10}

    out = e.missing_changes("doc", {})
    keys = [(c.actor, c.seq) for c in out]
    assert len(keys) == len(set(keys)), "duplicate changes on the wire"
    assert sorted(keys) == sorted((c.actor, c.seq) for c in chs)


def test_soak_both_walls_bounded_together(tmp_path):
    """The complete long-lived-document story: row compaction bounds the
    DEVICE working set (VMEM budget) while the log horizon bounds HOST
    memory, simultaneously, under continuous editing past the
    pre-compaction op budget — with hash parity against the full-history
    oracle and a fresh observer still able to reconstruct everything."""
    import random

    from automerge_tpu.engine.pack import ROWS_MAX_OPS
    from tests.test_compaction import _edit_round

    rng = random.Random(11)
    e = make_service(tmp_path, log_horizon_changes=40)
    d = am.change(am.init("W"), lambda x: x.__setitem__("t", am.Text()))
    e.apply_changes("doc", changes_of(d))
    served = len(changes_of(d))
    rset = e._resident
    i = rset.doc_index["doc"]

    total_ops = sum(len(c.ops) for c in changes_of(d))
    peak_log = 0
    for r in range(65):
        d = _edit_round(d, rng)
        new = changes_of(d)[served:]
        served += len(new)
        total_ops += sum(len(c.ops) for c in new)
        with e.batch():
            for c in new:
                e.apply_changes("doc", [c])
        peak_log = max(peak_log, len(rset.change_log[i]))
    assert total_ops > ROWS_MAX_OPS        # crossed the device budget
    assert metrics.snapshot().get("rows_docs_compacted"), "never compacted"
    assert rset.log_horizon[i], "never archived"
    assert peak_log < served               # host log really was truncated
    assert np.uint32(e.hashes()["doc"]) == oracle_hash(changes_of(d))
    assert "".join(e.materialize("doc")["data"]["t"]) == "".join(d["t"])
    fresh = am.apply_changes(am.init("obs"),
                             list(e.missing_changes("doc", {})))
    assert am.equals(fresh, d)


def test_fresh_peer_syncs_through_archive_over_real_tcp(tmp_path):
    """The archive cold path over a REAL socket: an archiving rows node
    serves a brand-new TCP peer its full history (cold prefix + RAM
    tail), and the peer's edits flow back past the horizon."""
    from automerge_tpu.sync.tcp import TcpSyncClient, TcpSyncServer
    from tests.test_tcp_sync import wait_until

    d = history()
    node = make_service(tmp_path)
    node.apply_changes("doc", changes_of(d))
    node.archive_logs()
    rset = node._resident
    assert not rset.change_log[rset.doc_index["doc"]]  # all archived

    fresh = DocSet()
    server = TcpSyncServer(node).start()
    client = TcpSyncClient(fresh, server.host, server.port).start()
    try:
        assert wait_until(lambda: (fresh.get_doc("doc") is not None
                                   and fresh.get_doc("doc").get("n") == 39))
        got = fresh.get_doc("doc")
        assert "".join(got["t"]) == "hello"
        # edit on the fresh peer; the archiving node converges
        fresh.set_doc("doc", am.change(
            got, lambda x: x["t"].insert_at(5, "!")))
        assert wait_until(lambda: "".join(
            node.materialize("doc")["data"]["t"]) == "hello!")
    finally:
        client.close()
        server.close()


def test_concurrent_writers_archiver_and_reader(tmp_path):
    """Threaded stress: three writer threads streaming per-actor changes,
    one thread archiving in a loop, one reading missing_changes/hashes —
    all against one node. Validates the lock discipline (no deadlock, no
    torn state) and final convergence with full reconstruction; the class
    of bug the r5 gossip-re-entry deadlock belonged to."""
    import threading

    e = make_service(tmp_path, log_horizon_changes=15)
    base = am.change(am.init("root"),
                     lambda x: x.__setitem__("t", am.Text()))
    e.apply_changes("doc", changes_of(base))
    errors = []
    docs = {}

    def writer(actor):
        try:
            d = am.merge(am.init(actor), base)
            served = {c.actor: c.seq for c in changes_of(d)}
            for k in range(60):
                d = am.change(d, lambda x, k=k, actor=actor: x.__setitem__(
                    f"{actor}{k % 7}", k))
                new = [c for c in changes_of(d)
                       if c.seq > served.get(c.actor, 0)]
                for c in new:
                    served[c.actor] = c.seq
                e.apply_changes("doc", [c for c in new if c.actor == actor])
            docs[actor] = d
        except Exception as ex:  # pragma: no cover - failure reporting
            errors.append(ex)

    stop = threading.Event()

    def archiver():
        try:
            while not stop.is_set():
                e.archive_logs(["doc"])
        except Exception as ex:
            errors.append(ex)

    def reader():
        try:
            while not stop.is_set():
                e.missing_changes("doc", {})
                e.hashes()
        except Exception as ex:
            errors.append(ex)

    # daemon=True: if the deadlock this test hunts ever reappears, the
    # assertion below must REPORT it — non-daemon threads would hang the
    # interpreter at exit instead
    ws = [threading.Thread(target=writer, args=(a,), daemon=True)
          for a in "ABC"]
    aux = [threading.Thread(target=archiver, daemon=True),
           threading.Thread(target=reader, daemon=True)]
    for t in ws + aux:
        t.start()
    for t in ws:
        t.join(timeout=120)
    stop.set()
    for t in aux:
        t.join(timeout=30)
    assert not errors, errors
    assert all(not t.is_alive() for t in ws + aux), "deadlocked thread"

    # final truth: merge every writer's replica; the node must match and
    # a fresh observer must reconstruct it through the archive
    m = base
    for d in docs.values():
        m = am.merge(m, d)
    e.flush()
    assert np.uint32(e.hashes()["doc"]) == oracle_hash(changes_of(m))
    fresh = am.apply_changes(am.init("obs"),
                             list(e.missing_changes("doc", {})))
    assert am.equals(fresh, m)


def test_archive_requires_rows_backend(tmp_path):
    with pytest.raises(ValueError):
        EngineDocSet(backend="resident",
                     log_archive_dir=str(tmp_path / "a"))
    e = EngineDocSet(backend="rows")
    with pytest.raises(ValueError):
        e.archive_logs()
    # a threshold with nowhere to put the prefix must fail loudly, not
    # silently leave the RAM log unbounded
    with pytest.raises(ValueError):
        EngineDocSet(backend="rows", log_horizon_changes=100)


def test_sharded_node_archives_per_shard(tmp_path):
    from automerge_tpu.sync.sharded_service import ShardedEngineDocSet

    node = ShardedEngineDocSet(n_shards=3,
                               log_archive_dir=str(tmp_path / "arch"),
                               log_horizon_changes=20)
    docs = {}
    for k in range(6):
        d = am.init(f"a{k}")
        for j in range(30):
            d = am.change(d, lambda x, j=j: x.__setitem__(f"f{j % 4}", j))
        docs[f"doc{k}"] = d
        node.apply_changes(f"doc{k}", changes_of(d))
    # the per-shard auto-trigger already archived during ingress
    # (threshold 20 < 30 changes/doc): horizons set, RAM logs bounded
    for did in docs:
        s = node.shard_of(did)
        i = s._resident.doc_index[did]
        assert s._resident.log_horizon[i], did
        assert len(s._resident.change_log[i]) <= 20, did
    assert sum(node.archive_logs().values()) == 0   # nothing left to move
    for did, d in docs.items():
        fresh = am.apply_changes(am.init("obs"),
                                 list(node.missing_changes(did, {})))
        assert am.equals(fresh, d), did


def test_pinned_floor_skips_rescan_and_archives_after_catchup(tmp_path):
    d = history()
    chs = changes_of(d)
    e = make_service(tmp_path)
    e.apply_changes("doc", chs)
    e.note_peer_clock("peer-1", "doc", {"alice": 10})
    assert e.archive_logs()["doc"] == 10
    # floor pinned at the horizon: repeat calls are cheap no-ops
    assert e.archive_logs()["doc"] == 0
    assert e.archive_logs()["doc"] == 0
    # peer catches up: the rest archives
    e.note_peer_clock("peer-1", "doc", {"alice": chs[-1].seq})
    assert e.archive_logs()["doc"] == len(chs) - 10
    fresh = am.apply_changes(am.init("obs"),
                             list(e.missing_changes("doc", {})))
    assert am.equals(fresh, d)
