"""Worker for the 4-process multi-host test (not a pytest module).

Generalizes multihost_worker.py to N processes: host 0 is the sync hub
(TcpSyncServer), hosts 1..N-1 connect as clients; the hub's DocSet relays
admissions between spokes (Connection forwarding, the reference's
multi-peer DocSet posture). After DCN convergence every process joins ONE
global jax.distributed mesh (8 virtual CPU devices total) for a single
SPMD reconcile with per-shard oracle parity and a cross-host clock union.

Usage: python tests/multihost_ring_worker.py <pid> <nprocs> <coord_port>
       <sync_port>
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

pid = int(sys.argv[1])
nprocs = int(sys.argv[2])
coord_port = sys.argv[3]
sync_port = int(sys.argv[4])
per_host = 8 // nprocs

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={per_host}").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from automerge_tpu.parallel.multihost import (global_mesh,  # noqa: E402
                                              init_multihost,
                                              reconcile_global)

init_multihost(f"127.0.0.1:{coord_port}", num_processes=nprocs,
               process_id=pid)
assert jax.device_count() == 8, jax.device_count()
assert jax.local_device_count() == per_host

import automerge_tpu as am  # noqa: E402
from automerge_tpu.sync.docset import DocSet  # noqa: E402
from automerge_tpu.sync.tcp import (TcpSyncClient, TcpSyncServer,  # noqa: E402
                                    sync_lock)

N = 8
ACTOR = f"host{pid}"
ds = DocSet()
for i in range(N):
    if i % nprocs == pid:  # each host authors its residue class
        d = am.change(am.init(ACTOR), lambda x, i=i: am.assign(
            x, {"n": i, "xs": [i, i + 1], "owner": ACTOR}))
        ds.set_doc(f"doc{i}", d)

# --- phase 1: hub-and-spoke DCN sync ------------------------------------
if pid == 0:
    link = TcpSyncServer(ds, port=sync_port).start()
else:
    link = None
    for _ in range(200):
        try:
            link = TcpSyncClient(ds, "127.0.0.1", sync_port).start()
            break
        except OSError:
            time.sleep(0.1)
    assert link is not None, "could not reach the hub"

deadline = time.time() + 90
while time.time() < deadline:
    docs = [ds.get_doc(f"doc{i}") for i in range(N)]
    if all(d is not None and "owner" in d for d in docs):
        break
    time.sleep(0.05)
else:
    missing = [i for i in range(N) if ds.get_doc(f"doc{i}") is None]
    raise AssertionError(f"[p{pid}] spoke sync did not converge: {missing}")

# every host contributes one concurrent edit to the shared doc0
with sync_lock(ds):
    doc0 = ds.get_doc("doc0")
    if doc0._doc.actor_id == ACTOR:
        ds.set_doc("doc0", am.change(
            doc0, lambda x: x.__setitem__("winner", ACTOR)))
    else:
        mine = am.change(am.merge(am.init(ACTOR), doc0),
                         lambda x: x.__setitem__("winner", ACTOR))
        ds.set_doc("doc0", am.merge(ds.get_doc("doc0"), mine))

deadline = time.time() + 90
while time.time() < deadline:
    clock = ds.get_doc("doc0")._doc.opset.clock
    if all(f"host{h}" in clock for h in range(nprocs)):
        break
    time.sleep(0.05)
else:
    raise AssertionError(
        f"[p{pid}] concurrent-edit sync did not converge: "
        f"{ds.get_doc('doc0')._doc.opset.clock}")
assert ds.get_doc("doc0")["winner"] in {f"host{h}" for h in range(nprocs)}

# --- phase 2: one global mesh across all processes ----------------------
mesh = global_mesh()
with sync_lock(ds):
    doc_changes = [ds.get_doc(f"doc{i}")._doc.opset.get_missing_changes({})
                   for i in range(N)]
lo, hi, local_hashes = reconcile_global(doc_changes, mesh)

from automerge_tpu.engine.batchdoc import apply_batch  # noqa: E402

_, _, ref_out = apply_batch(doc_changes)
ref = np.asarray(ref_out["hash"]).astype(np.uint32)
want = ref[lo:min(hi, N)]
got = local_hashes[:len(want)]
assert (got == want).all(), f"[p{pid}] shard hash mismatch"

# --- phase 3: cross-host clock union ------------------------------------
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from automerge_tpu.parallel.collective import global_clock_union  # noqa: E402
from automerge_tpu.parallel.mesh import DOCS_AXIS  # noqa: E402

actors = sorted({c.actor for chs in doc_changes for c in chs})
rank = {a: k for k, a in enumerate(actors)}
clocks = np.zeros((N, len(actors)), np.int32)
for i in range(N):
    for a, s in ds.get_doc(f"doc{i}")._doc.opset.clock.items():
        clocks[i, rank[a]] = s
sh = NamedSharding(mesh, P(DOCS_AXIS))
arr = jax.make_array_from_process_local_data(
    sh, np.ascontiguousarray(clocks[lo:hi]), global_shape=clocks.shape)
union = np.asarray(global_clock_union(arr, mesh))
# the union must contain EVERY host's seqs even though each host only fed
# its own shard — the reduction really crossed all process boundaries
want_union = clocks.max(axis=0)
assert (union == want_union).all(), f"[p{pid}] union {union} != {want_union}"
assert all(union[rank[f"host{h}"]] > 0 for h in range(nprocs))

if link is not None:
    link.close()
print(f"MULTIHOST4-OK p{pid} winner={ds.get_doc('doc0')['winner']} "
      f"union={union.tolist()}", flush=True)
