"""Threaded regression pins for the shared-state races graftlint's race
plane found (analysis/races.py) and this PR fixed.

Each test hammers the exact interleaving the static finding described.
They are probabilistic by nature (a lost race just passes vacuously),
but at these iteration counts the pre-fix code failed reliably — and
the point of the pin is that the LOCKED code can never fail, however
the scheduler interleaves.
"""

import threading
import time

from automerge_tpu import DocSet
from automerge_tpu.perf.fleet import FleetCollector
from automerge_tpu.sync import docledger
from automerge_tpu.sync.service import EngineDocSet
from automerge_tpu.sync.tcp import TcpSyncClient, TcpSyncServer


class _Conn:
    """Bare connection stand-in: no peer_label/peer_node, so the ledger
    must allocate a positional conn<k> label."""
    peer_label = None
    peer_node = None


def _run_threads(n, fn):
    """Start n threads on fn(i), join, and re-raise the first error."""
    errors = []

    def wrap(i):
        try:
            fn(i)
        except BaseException as e:     # pragma: no cover - failure path
            errors.append(e)

    threads = [threading.Thread(target=wrap, args=(i,), name=f"race-{i}")
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]


def test_docledger_conn_labels_unique_under_contention():
    # pre-fix: conn_label's read-modify-write of _conn_seq ran unlocked,
    # so two tcp reader threads could both see seq=k and hand their
    # connections the same "conn<k+1>" label, merging two peers' lanes
    led = docledger.DocLedger()
    conns = [_Conn() for _ in range(16)]
    barrier = threading.Barrier(16)

    labels = [None] * 16

    def worker(i):
        barrier.wait()
        for _ in range(200):
            labels[i] = led.conn_label(conns[i])

    _run_threads(16, worker)
    assert len(set(labels)) == 16
    # stable across calls, too
    assert [led.conn_label(c) for c in conns] == labels


def test_engine_doc_set_add_doc_concurrent_single_registration():
    # pre-fix: two threads adding the same unseen doc could both pass
    # the membership check and double-register it in the resident engine
    e = EngineDocSet()
    barrier = threading.Barrier(8)

    def worker(i):
        barrier.wait()
        for k in range(20):
            e.add_doc(f"doc{k}")

    _run_threads(8, worker)
    ids = e.doc_ids
    assert sorted(ids) == sorted(set(ids))
    assert len(ids) == 20


def test_tcp_server_close_races_accept_loop():
    # pre-fix: the accept thread rebound self.peers (prune + append)
    # while close() iterated it — a peer accepted concurrently with
    # close could miss the close sweep and leak its reader thread
    for _ in range(3):
        ds = DocSet()
        server = TcpSyncServer(ds).start()
        clients = []
        stop = threading.Event()

        def dial():
            while not stop.is_set():
                try:
                    clients.append(TcpSyncClient(DocSet(), server.host,
                                                 server.port, timeout=2.0))
                except OSError:
                    return

        t = threading.Thread(target=dial, name="race-dialer")
        t.start()
        time.sleep(0.05)
        server.close()
        stop.set()
        t.join()
        deadline = time.time() + 5.0
        for p in server.peers:
            assert p.closed.wait(max(0.0, deadline - time.time()))
        for c in clients:
            c.close()


def test_fleet_registration_races_scrape_loop():
    # pre-fix: add_local/add_peer appended to the registries the scrape
    # thread was iterating, and _node grew self.nodes mid-_judge —
    # "dict changed size during iteration" in the collector loop
    c = FleetCollector(interval_s=0.01, min_nodes=3)
    stop = threading.Event()
    scrape_errors = []

    def scraper():
        while not stop.is_set():
            try:
                c.scrape_once()
            except BaseException as e:   # pragma: no cover - failure path
                scrape_errors.append(e)
                return

    t = threading.Thread(target=scraper, name="race-scraper")
    t.start()

    def register(i):
        for k in range(40):
            c.add_local(f"n{i}-{k}", snapshot_fn=lambda: {"ops_per_s": 1.0})
            c.quarantine(f"q{i}-{k}")
            c.unquarantine(f"q{i}-{k}")

    try:
        _run_threads(4, register)
    finally:
        stop.set()
        t.join()
    assert not scrape_errors, scrape_errors
    state = c.scrape_once()
    assert state["rollup"]["nodes"] >= 160
