"""jit-hygiene pass tests: fixture snippets per rule, positive and
negative — host syncs on tracers, branches on tracers, static-argument
propagation through call edges, retrace hazards, shape-literal drift —
plus the no-new-findings check against the real repo (everything the pass
reports there must be either fixed or baselined)."""

import pathlib
import textwrap

import pytest

from automerge_tpu.analysis import load_project
from automerge_tpu.analysis.jit_hygiene import JitHygienePass

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _run(tmp_path, source, rel="automerge_tpu/engine/fix.py"):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))
    return JitHygienePass().run(load_project(tmp_path))


def _rules(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# host-sync hazards


def test_item_and_scalar_casts_on_tracer_flagged(tmp_path):
    findings = _run(tmp_path, '''\
        import jax

        @jax.jit
        def f(x):
            a = x.sum()
            b = a.item()              # host sync
            c = float(x)              # host sync
            return b + c
        ''')
    assert _rules(findings).count("jit-host-sync") == 2


def test_np_asarray_of_tracer_flagged_but_static_ok(tmp_path):
    findings = _run(tmp_path, '''\
        from functools import partial
        import numpy as np
        import jax

        @partial(jax.jit, static_argnames=("meta",))
        def f(x, meta):
            shape = np.asarray(meta)      # static arg: fine
            y = np.asarray(x)             # tracer readback: flagged
            return y.reshape(shape)
        ''')
    assert _rules(findings).count("jit-host-sync") == 1


def test_block_until_ready_in_jit_reachable_code_flagged(tmp_path):
    findings = _run(tmp_path, '''\
        import jax

        @jax.jit
        def f(x):
            return x.block_until_ready()
        ''')
    assert "jit-host-sync" in _rules(findings)


def test_host_sync_found_through_call_graph(tmp_path):
    """The hazard sits in a helper that is only reachable FROM a jit
    root — the reachability walk must still find it."""
    findings = _run(tmp_path, '''\
        import jax

        def helper(v):
            return int(v)             # host sync, but only under jit

        @jax.jit
        def f(x):
            return helper(x + 1)
        ''')
    assert "jit-host-sync" in _rules(findings)


def test_unreachable_helper_not_flagged(tmp_path):
    findings = _run(tmp_path, '''\
        import jax

        def host_only(v):
            return int(v)             # never called from traced code

        @jax.jit
        def f(x):
            return x + 1
        ''')
    assert findings == []


# ---------------------------------------------------------------------------
# tracer branching


def test_branch_on_tracer_flagged_static_branch_ok(tmp_path):
    findings = _run(tmp_path, '''\
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("flag",))
        def f(x, flag):
            if flag:                  # static: fine
                x = x + 1
            if x > 0:                 # tracer: flagged
                x = x - 1
            return x
        ''')
    assert _rules(findings).count("jit-tracer-branch") == 1


def test_shape_reads_and_len_are_static(tmp_path):
    findings = _run(tmp_path, '''\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            if x.shape[0] > 4:        # shapes are python values
                x = x[:4]
            n = len(x)
            for i in range(n):        # static loop
                x = x + i
            return jnp.where(x > 0, x, -x)   # device select: fine
        ''')
    assert findings == []


def test_static_propagates_through_call_edge(tmp_path):
    """A param that only ever receives static values at call sites from
    traced code is static in the callee; one traced call site makes it
    traced."""
    findings = _run(tmp_path, '''\
        from functools import partial
        import jax

        def helper(v, mode):
            if mode:                  # static at every call site: fine
                return v + 1
            return v - 1

        def helper2(v, w):
            if w:                     # w receives a tracer below: flagged
                return v
            return -v

        @partial(jax.jit, static_argnames=("mode",))
        def f(x, mode):
            return helper(x, mode) + helper2(x, x * 2)
        ''')
    rules = _rules(findings)
    assert rules.count("jit-tracer-branch") == 1


# ---------------------------------------------------------------------------
# retrace hazards


def test_jit_wrapped_inside_function_flagged(tmp_path):
    findings = _run(tmp_path, '''\
        import jax

        def apply(arrays):
            fn = jax.jit(lambda b: b + 1)     # fresh cache per call
            return fn(arrays)
        ''')
    assert "jit-retrace" in _rules(findings)


def test_cached_wrapper_builder_not_flagged(tmp_path):
    findings = _run(tmp_path, '''\
        import jax

        _CACHE = {}

        def builder(key):
            fn = _CACHE.get(key)
            if fn is None:
                fn = jax.jit(lambda b: b + 1)
                _CACHE[key] = fn              # memoized: cache survives
            return fn
        ''')
    assert "jit-retrace" not in _rules(findings)


def test_module_level_jit_wrap_not_flagged(tmp_path):
    findings = _run(tmp_path, '''\
        import jax

        def _impl(b):
            return b + 1

        f = jax.jit(_impl)
        ''')
    assert "jit-retrace" not in _rules(findings)


def test_jit_call_expression_honors_static_argnums(tmp_path):
    """`jax.jit(f, static_argnums=1)` at module level: parameter 1 of f
    is static, so branching on it is fine (the argnums->name mapping
    needs the resolved target, not the jit call alone)."""
    findings = _run(tmp_path, '''\
        import jax

        def f(x, n):
            if n > 3:                 # static via static_argnums: fine
                return x + n
            return x

        g = jax.jit(f, static_argnums=1)
        ''')
    assert "jit-tracer-branch" not in _rules(findings)


def test_static_argnames_typo_flagged(tmp_path):
    findings = _run(tmp_path, '''\
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("metaa",))
        def f(x, meta):
            return x
        ''')
    assert "jit-retrace" in _rules(findings)


# ---------------------------------------------------------------------------
# shape-literal drift


def test_lane_pad_literal_flagged_outside_pack(tmp_path):
    findings = _run(tmp_path, '''\
        def pad(n):
            return ((n + 127) // 128) * 128
        ''')
    assert "jit-shape-drift" in _rules(findings)


def test_vmem_budget_literal_flagged(tmp_path):
    findings = _run(tmp_path, '''\
        BUDGET = 22528
        ''')
    assert "jit-shape-drift" in _rules(findings)


def test_pack_itself_owns_the_constants(tmp_path):
    findings = _run(tmp_path, '''\
        LANE = 128
        ROWS_VMEM_BUDGET = 22528

        def pad_to_lanes(n):
            return ((n + LANE - 1) // LANE) * LANE
        ''', rel="automerge_tpu/engine/pack.py")
    assert findings == []


def test_out_of_scope_modules_ignored(tmp_path):
    findings = _run(tmp_path, '''\
        import jax

        @jax.jit
        def f(x):
            return float(x)
        ''', rel="automerge_tpu/sync/fix.py")
    assert findings == []


# ---------------------------------------------------------------------------
# the real repo: everything is fixed or baselined


def test_repo_jit_findings_are_all_baselined():
    from automerge_tpu.analysis import Baseline
    from automerge_tpu.analysis.core import BASELINE_NAME, run_passes
    proj = load_project(ROOT)
    findings = run_passes(proj, [JitHygienePass()])
    baseline = Baseline.load(ROOT / BASELINE_NAME)
    _, new, _ = baseline.split(findings)
    assert not new, "new jit-hygiene findings:\n" + "\n".join(
        f.render() for f in new)
