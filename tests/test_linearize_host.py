"""Host linearizer (native + Python fallback) vs the device scan."""

import random

import numpy as np
import pytest

from automerge_tpu.native.linearize import linearize_host


def random_tree(rng, n):
    """Random insertion tree honoring parent.elem < child.elem."""
    ins_mask = np.zeros(n, dtype=bool)
    ins_elem = np.zeros(n, dtype=np.int32)
    ins_actor = np.zeros(n, dtype=np.int32)
    ins_parent = np.full(n, -1, dtype=np.int32)
    k = rng.randint(1, n)
    for i in range(k):
        ins_mask[i] = True
        ins_elem[i] = i + 1
        ins_actor[i] = rng.randint(0, 3)
        ins_parent[i] = rng.randint(-1, i - 1) if i else -1
    return ins_mask, ins_elem, ins_actor, ins_parent


@pytest.mark.parametrize("seed", range(8))
def test_matches_device_scan(seed):
    import jax
    from automerge_tpu.engine.kernels import linearize
    rng = random.Random(seed)
    args = random_tree(rng, 32)
    host = linearize_host(*args)
    device = np.asarray(jax.jit(linearize)(*map(np.asarray, args)))
    valid = args[0]
    np.testing.assert_array_equal(host[valid], device[valid])
    # masked-out slots are -1 on the host path
    assert (host[~valid] == -1).all()


def test_python_fallback_matches_native():
    from automerge_tpu import native
    if not native.native_available():
        pytest.skip("no native lib; fallback is the only path")
    rng = random.Random(99)
    args = random_tree(rng, 64)
    native_out = linearize_host(*args)

    # force the fallback by monkeypatching get_lib
    import automerge_tpu.native.linearize as lin
    orig = lin.get_lib
    lin.get_lib = lambda: None
    try:
        fallback_out = linearize_host(*args)
    finally:
        lin.get_lib = orig
    np.testing.assert_array_equal(native_out, fallback_out)


def test_long_chain_fast():
    import time
    n = 65536
    ins_mask = np.ones(n, dtype=bool)
    ins_elem = np.arange(1, n + 1, dtype=np.int32)
    ins_actor = np.zeros(n, dtype=np.int32)
    ins_parent = np.arange(-1, n - 1, dtype=np.int32)
    t0 = time.perf_counter()
    pos = linearize_host(ins_mask, ins_elem, ins_actor, ins_parent)
    dt = time.perf_counter() - t0
    np.testing.assert_array_equal(pos, np.arange(n))
    assert dt < 1.0, f"host linearize too slow: {dt:.3f}s"


def test_empty():
    out = linearize_host(np.zeros(4, bool), np.zeros(4, np.int32),
                         np.zeros(4, np.int32), np.full(4, -1, np.int32))
    assert (out == -1).all()
