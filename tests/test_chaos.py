"""Chaos fault-injection hooks (utils/chaos.py) + end-to-end straggler /
doctor attribution over per-node captures of real degraded services.

The inertness contract matters as much as the injection: with no
AMTPU_CHAOS_* set every hook must be a cached check that records
nothing — these hooks sit on the round-flush and transport hot paths.
"""

import os
import time

import pytest

import automerge_tpu as am
from automerge_tpu import DocSet
from automerge_tpu.core.change import Change, Op
from automerge_tpu.core.ids import ROOT_ID
from automerge_tpu.native.wire import changes_to_columns
from automerge_tpu.sync.service import EngineDocSet
from automerge_tpu.sync.tcp import TcpSyncClient, TcpSyncServer
from automerge_tpu.utils import chaos, metrics

CHAOS_VARS = ("AMTPU_CHAOS_SLOW_APPLY_S", "AMTPU_CHAOS_LOCK_HOLD_S",
              "AMTPU_CHAOS_LOCK_HOLD_EVERY_S", "AMTPU_CHAOS_DROP_FRAMES",
              "AMTPU_CHAOS_NODE")


@pytest.fixture(autouse=True)
def _clean_chaos(monkeypatch):
    """Every test starts and ends with a pristine chaos config."""
    for var in CHAOS_VARS:
        monkeypatch.delenv(var, raising=False)
    chaos.reload()
    yield
    for var in CHAOS_VARS:
        monkeypatch.delenv(var, raising=False)
    chaos.reload()
    metrics.reset()


def _one_op_cols(actor, seq, key="k", value=1):
    return changes_to_columns([Change(
        actor=actor, seq=seq, deps={},
        ops=[Op("set", ROOT_ID, key=key, value=value)])])


def wait_until(predicate, timeout=10.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# inertness


def test_hooks_fully_inert_when_unset():
    metrics.reset()
    assert not chaos.enabled()
    assert chaos.maybe_lock_holder(object()) is None
    assert chaos.drop_frame("any", "frame") is False
    t0 = time.perf_counter()
    chaos.slow_apply("any")
    assert time.perf_counter() - t0 < 0.05   # no sleep happened
    svc = EngineDocSet(backend="rows")
    try:
        assert svc._chaos_holder is None
        svc.apply_columns("d0", _one_op_cols("A", 1))
    finally:
        svc.close()
    snap = metrics.snapshot()
    assert not any(k.startswith("obs_chaos") for k in snap), \
        [k for k in snap if k.startswith("obs_chaos")]
    assert snap.get("sync_frames_dropped", 0) == 0


def test_drop_frame_never_touches_telemetry_kinds(monkeypatch):
    monkeypatch.setenv("AMTPU_CHAOS_DROP_FRAMES", "1.0")
    chaos.reload()
    # change-bearing kinds drop at p=1.0; telemetry kinds never do
    assert chaos.drop_frame(None, "frame") is True
    assert chaos.drop_frame(None, "metrics:pull") is False
    assert chaos.drop_frame(None, "metrics:snapshot") is False
    assert chaos.drop_frame(None, "audit:pull") is False
    assert chaos.drop_frame(None, "clock") is False


def test_node_targeting(monkeypatch):
    metrics.reset()
    monkeypatch.setenv("AMTPU_CHAOS_SLOW_APPLY_S", "0.2")
    monkeypatch.setenv("AMTPU_CHAOS_NODE", "victim")
    chaos.reload()
    # a non-matching node is untouched
    t0 = time.perf_counter()
    chaos.slow_apply("innocent")
    chaos.slow_apply(None)
    assert time.perf_counter() - t0 < 0.1
    assert metrics.snapshot().get(
        "obs_chaos_injected{fault=slow_apply}", 0) == 0
    # the matching node pays
    t0 = time.perf_counter()
    chaos.slow_apply("victim")
    assert time.perf_counter() - t0 >= 0.2
    assert metrics.snapshot().get(
        "obs_chaos_injected{fault=slow_apply}", 0) == 1


# ---------------------------------------------------------------------------
# the three fault classes against real services


def test_slow_apply_inflates_round_flush(monkeypatch):
    metrics.reset()
    monkeypatch.setenv("AMTPU_CHAOS_SLOW_APPLY_S", "0.05")
    chaos.reload()
    svc = EngineDocSet(backend="rows")
    try:
        t0 = time.perf_counter()
        svc.apply_columns("d0", _one_op_cols("A", 1))
        assert time.perf_counter() - t0 >= 0.05
    finally:
        svc.close()
    snap = metrics.snapshot()
    assert snap.get("obs_chaos_injected{fault=slow_apply}", 0) >= 1
    # the sleep lands INSIDE the flush window (the slow-apply signature)
    assert snap.get("sync_round_flush_s", 0) >= 0.05


def test_lock_hold_auto_holder_and_close(monkeypatch):
    metrics.reset()
    monkeypatch.setenv("AMTPU_CHAOS_LOCK_HOLD_S", "0.04")
    monkeypatch.setenv("AMTPU_CHAOS_LOCK_HOLD_EVERY_S", "0.02")
    chaos.reload()
    svc = EngineDocSet(backend="rows")
    try:
        assert svc._chaos_holder is not None
        holder_thread = svc._chaos_holder._thread
        assert holder_thread.name == "amtpu-chaos-lockhold"
        assert wait_until(lambda: metrics.snapshot().get(
            "obs_chaos_injected{fault=lock_hold}", 0) >= 2)
    finally:
        svc.close()
    # close() stops AND joins the holder (thread hygiene)
    assert not holder_thread.is_alive()
    snap = metrics.snapshot()
    # the hold shows on the instrumented service lock — the signature
    # that separates lock_hold from slow_apply for the doctor
    assert snap.get("sync_lock_hold_s{lock=service}_max", 0) >= 0.03


def test_frame_drop_over_tcp_spares_telemetry(monkeypatch):
    metrics.reset()
    monkeypatch.setenv("AMTPU_CHAOS_DROP_FRAMES", "1.0")
    monkeypatch.setenv("AMTPU_CHAOS_NODE", "victim")
    chaos.reload()
    ds_server, ds_client = DocSet(), DocSet()
    ds_client._chaos_node = "victim"
    server = TcpSyncServer(ds_server).start()
    client = TcpSyncClient(ds_client, server.host, server.port).start()
    try:
        ds_client.set_doc("doc1", am.change(
            am.init(), lambda d: d.__setitem__("hello", "net")))
        time.sleep(0.5)
        # the change-bearing message was dropped at the victim's sender
        assert ds_server.get_doc("doc1") is None
        snap = metrics.snapshot()
        assert snap.get("sync_frames_dropped", 0) >= 1
        assert snap.get("obs_chaos_injected{fault=frame_drop}", 0) >= 1
        # the telemetry plane still works THROUGH the degraded link:
        # a metrics pull round-trips (chaos never drops metrics kinds)
        conn = client.peer.connection
        conn.request_metrics()
        assert wait_until(lambda: conn.peer_metrics is not None)
    finally:
        client.close()
        server.close()


# ---------------------------------------------------------------------------
# end-to-end attribution: three per-node captures per fault class, the
# collector must flag the degraded node and the doctor must rank the
# injected cause first (the ISSUE acceptance shape, in-process)


def _capture_service_node(monkeypatch, fault_env: dict, n_ops=16):
    """Run one rows service (optionally degraded) and return the
    (mid, end) metrics snapshot pair a collector source can replay.
    The registry is reset first so the snapshots are this node's own."""
    for k, v in fault_env.items():
        monkeypatch.setenv(k, v)
    chaos.reload()
    metrics.reset()
    svc = EngineDocSet(backend="rows")
    try:
        for k in range(n_ops):
            svc.apply_columns(f"d{k % 4}", _one_op_cols("A", k // 4 + 1,
                                                        key=f"f{k % 3}"))
        mid = metrics.snapshot()
        for k in range(n_ops):
            svc.apply_columns(f"d{k % 4}",
                              _one_op_cols("A", n_ops // 4 + k // 4 + 1,
                                           key=f"f{k % 3}"))
        end = metrics.snapshot()
    finally:
        svc.close()
        for k in fault_env:
            monkeypatch.delenv(k, raising=False)
        chaos.reload()
    return mid, end


def _capture_dropping_node(monkeypatch, n_ops=10):
    """A node whose outgoing change frames are dropped (TCP pair)."""
    monkeypatch.setenv("AMTPU_CHAOS_DROP_FRAMES", "1.0")
    chaos.reload()
    metrics.reset()
    ds_server, ds_client = DocSet(), DocSet()
    server = TcpSyncServer(ds_server).start()
    client = TcpSyncClient(ds_client, server.host, server.port).start()
    try:
        def burst(base):
            # the drops happen on the tcp writer thread — wait for them
            # to land before snapshotting, or the mid/end delta races to 0
            before = metrics.snapshot().get("sync_frames_dropped", 0)
            for k in range(n_ops):
                ds_client.set_doc(f"doc{base + k}", am.change(
                    am.init(), lambda d, k=k: d.__setitem__("n", k)))
            assert wait_until(lambda: metrics.snapshot().get(
                "sync_frames_dropped", 0) >= before + n_ops)
        burst(0)
        mid = metrics.snapshot()
        burst(n_ops)
        end = metrics.snapshot()
    finally:
        client.close()
        server.close()
        monkeypatch.delenv("AMTPU_CHAOS_DROP_FRAMES", raising=False)
        chaos.reload()
    return mid, end


def _replay_source(pair):
    """Collector source that serves the mid snapshot once, then end."""
    state = {"n": 0}

    def fn():
        state["n"] += 1
        return pair[0] if state["n"] == 1 else pair[1]
    return fn


@pytest.mark.parametrize("fault,env,expected_cause", [
    ("slow_apply", {"AMTPU_CHAOS_SLOW_APPLY_S": "0.04"}, "slow_apply"),
    ("lock_hold", {"AMTPU_CHAOS_LOCK_HOLD_S": "0.05",
                   "AMTPU_CHAOS_LOCK_HOLD_EVERY_S": "0.01"},
     "lock_contention"),
    ("frame_drop", {}, "frame_loss"),
])
def test_straggler_and_doctor_attribution(monkeypatch, fault, env,
                                          expected_cause):
    from automerge_tpu.perf import doctor
    from automerge_tpu.perf.fleet import FleetCollector

    captures = {}
    for node in ("a", "b"):
        captures[node] = _capture_service_node(monkeypatch, {})
    if fault == "frame_drop":
        captures["x"] = _capture_dropping_node(monkeypatch)
    else:
        captures[("x")] = _capture_service_node(monkeypatch, env)

    metrics.reset()   # the collector's own exports start clean
    collector = FleetCollector(interval_s=0.05, k_sigma=3.0, min_nodes=3)
    for node, pair in captures.items():
        collector.add_local(node, _replay_source(pair), role="peer")
    collector.scrape_once()
    time.sleep(0.05)
    state = collector.scrape_once()

    assert state["stragglers"] == ["x"], (fault, state["nodes"])
    assert state["nodes"]["x"]["straggler_score"] >= 3.0
    report = doctor.diagnose_live(collector)
    top = report["causes"][0]
    assert top["cause"] == expected_cause and top["node"] == "x", (
        fault, [(c["cause"], c["node"], c["score"])
                for c in report["causes"][:4]])
    # the collector disclosed the flag through the export surface too
    snap = metrics.snapshot()
    assert snap.get("obs_fleet_stragglers_flagged{node=x}", 0) == 1
    assert snap.get("obs_fleet_straggler_score{node=x}", 0) >= 3.0
