"""Per-doc convergence ledger (sync/docledger.py): frontier lanes,
usefulness/duplicate accounting, bounded memory, pure-state export, and
the connection/service/tcp hooks that feed it."""

import json
import os
import time

import pytest

from automerge_tpu.core.change import Change, Op
from automerge_tpu.core.ids import ROOT_ID
from automerge_tpu.sync import docledger
from automerge_tpu.sync.connection import Connection
from automerge_tpu.sync.docledger import DocLedger
from automerge_tpu.sync.service import EngineDocSet
from automerge_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _clean():
    metrics.reset()
    yield
    metrics.reset()


def _chg(actor, seq, value=1):
    return Change(actor=actor, seq=seq, deps={},
                  ops=[Op("set", ROOT_ID, key="k", value=value)])


def _pair(wire="columnar"):
    """Two rows services synced over in-process queue connections, with
    labeled lanes (the cross-node join perf explain needs)."""
    a, b = EngineDocSet(backend="rows"), EngineDocSet(backend="rows")
    qa, qb = [], []
    ca = Connection(a, qa.append, wire=wire)
    cb = Connection(b, qb.append, wire=wire)
    ca.peer_label, cb.peer_label = "B", "A"
    a.doc_ledger.label, b.doc_ledger.label = "A", "B"
    ca.open()
    cb.open()

    def drain():
        for _ in range(50):
            if not (qa or qb):
                return
            while qa:
                cb.receive_msg(qa.pop(0))
            while qb:
                ca.receive_msg(qb.pop(0))
        raise AssertionError("pair failed to quiesce")
    return a, b, ca, cb, drain


def _close(*svcs):
    for s in svcs:
        s.close()


# -- core lane mechanics ----------------------------------------------------


def test_advert_vs_local_frontier_builds_lag_then_clears():
    led = DocLedger(label="n")

    class _Conn:
        peer_label = "W"
    conn = _Conn()
    led.record_advert("d", conn, {"x": 3})
    sec = led.section()
    e = sec["docs"]["d"]
    # no doc_set attached: local frontier indeterminate -> no deficit
    # invented (lag stays 0 rather than lying)
    assert e["lag_changes"] == 0

    svc = EngineDocSet(backend="rows")
    try:
        led2 = svc.doc_ledger
        led2.label = "n2"
        led2.record_advert("d", conn, {"x": 3})
        e = led2.section()["docs"]["d"]
        # the service does NOT hold doc "d" at all: frontier {} by
        # definition, the whole advert is deficit
        assert e["lag_changes"] == 3
        assert e["behind_peer"] == "W"
        assert e["behind_since"] is not None
        # catch up: admit the changes, then the export-time catchup
        # (post-read cache warm) must clear the deficit
        for s in (1, 2, 3):
            svc.apply_changes("d", [_chg("x", s)])
        svc.clock_of("d")               # warm the snapshot read cache
        e = led2.section()["docs"]["d"]
        assert e["lag_changes"] == 0
        assert e["behind_since"] is None
        assert e["lag_s"] == 0.0
    finally:
        _close(svc)


def test_receive_split_counts_duplicates_and_redundancy():
    a, b, ca, cb, drain = _pair()
    try:
        a.apply_changes("d", [_chg("x", 1)])
        drain()
        # re-deliver the same change out of band: the clock covers it,
        # so it must count as duplicate wire work, not useful
        from automerge_tpu.sync.frames import encode_frame
        cb.receive_msg({"docId": "d", "clock": {"x": 1},
                        "frame": encode_frame([_chg("x", 1)])})
        snap = metrics.snapshot()
        assert snap["sync_conn_changes_delivered"] >= 1
        assert snap["sync_conn_changes_duplicate"] == 1
        red = b.doc_ledger.redundancy()
        assert red["duplicate"] == 1
        assert red["ratio"] == round(1 / red["useful"], 4)
        lane = b.doc_ledger.section()["docs"]["d"]["peers"]["A"]
        assert lane["recv_duplicate"] == 1
        assert lane["bytes_received"] > 0
    finally:
        _close(a, b)


def test_changes_ahead_of_frontier_count_useful_not_duplicate():
    """A causally-early delivery (seq 2 before seq 1) is NEW information
    — it parks in the causal queue but is not wasted wire work."""
    a, b, ca, cb, drain = _pair(wire="json")
    try:
        cb.receive_msg({"docId": "d", "clock": {"x": 2},
                        "changes": [_chg("x", 2).to_dict()]})
        snap = metrics.snapshot()
        assert snap.get("sync_conn_changes_delivered") == 1
        assert "sync_conn_changes_duplicate" not in snap
    finally:
        _close(a, b)


def test_bounded_memory_evicts_lru_into_aggregate_keeping_laggards():
    led = DocLedger(label="n", top_k=8)

    class _Conn:
        peer_label = "W"
    conn = _Conn()
    # make doc "behind0" permanently lagging (no doc_set -> use explicit
    # receive counts only; mark behind via the entry directly)
    for i in range(8):
        led.record_receive(f"cold{i}", conn, 1, 0)
    with led._lock:
        led._docs["cold0"].behind_since = time.time()   # the laggard
    for i in range(6):
        led.record_receive(f"hot{i}", conn, 2, 1)
    sec = led.section()
    assert sec["tracked"] <= 8
    assert sec["evictions"] == 6
    assert metrics.snapshot()["obs_doc_evictions"] == 6
    # the lagging doc survived every eviction scan; the evicted docs'
    # counts folded into the aggregate bucket
    assert "cold0" in sec["docs"]
    assert sec["aggregate"]["docs"] == 6
    assert sec["aggregate"]["recv_useful"] == 6
    # global redundancy counters survive eviction untouched
    assert sec["redundancy"]["useful"] == 8 + 12
    assert sec["redundancy"]["duplicate"] == 6


def test_section_is_pure_and_json_clean_and_resets():
    a, b, ca, cb, drain = _pair()
    try:
        for s in (1, 2):
            a.apply_changes("d", [_chg("x", s)])
            drain()
        s1 = metrics.snapshot()
        s2 = metrics.snapshot()
        assert s1 == s2, "snapshot export must be pure (no wall reads)"
        assert json.loads(json.dumps(s1)) == s1
        nodes = s1["docledger"]["nodes"]
        assert set(nodes) == {"A", "B"}
        assert nodes["B"]["docs"]["d"]["peers"]["A"]["recv_useful"] == 2
        metrics.reset()
        assert metrics.snapshot() == {}
        # a still-live service re-registers on its next mutation
        a.apply_changes("d", [_chg("x", 3)])
        drain()
        assert "docledger" in metrics.snapshot()
    finally:
        _close(a, b)


def test_gauges_refresh_on_mutation_cadence():
    led = DocLedger(label="n")

    class _Conn:
        peer_label = "W"
    conn = _Conn()
    for i in range(docledger.GAUGE_REFRESH):
        led.record_receive("d", conn, 1, 1)
    snap = metrics.snapshot()
    assert snap["obs_doc_tracked"] == 1
    assert snap["obs_doc_redundancy_ratio"] == 1.0
    assert snap["obs_doc_ledger_s_count"] >= 1
    assert snap["obs_doc_ledger_s_sum"] > 0


def test_epoch_buffer_visibility_and_doc_count():
    from automerge_tpu.native.wire import changes_to_columns
    from automerge_tpu.sync.epochs import EpochIngestBuffer
    buf = EpochIngestBuffer()
    cols = changes_to_columns([_chg("x", 1)])
    buf.append("d", cols, None)
    buf.append("d", cols, None)
    buf.append("e", cols, None)
    assert buf.doc_count("d") == 2
    assert buf.doc_count("e") == 1
    assert buf.doc_count("zz") == 0
    entries = buf.seal()
    EpochIngestBuffer.resolve([e.ticket for e in entries])
    assert buf.doc_count("d") == 0


def test_disabled_plane_is_inert(monkeypatch):
    monkeypatch.setenv("AMTPU_DOCLEDGER", "0")
    docledger._reload_for_tests()
    try:
        svc = EngineDocSet(backend="rows")
        try:
            assert svc.doc_ledger is None
            q = []
            conn = Connection(svc, q.append, wire="columnar")
            assert conn._ledger is None
            conn.open()
            svc.apply_changes("d", [_chg("x", 1)])
            snap = metrics.snapshot()
            assert "docledger" not in snap
            assert not any(k.startswith("obs_doc_") for k in snap)
            assert not any(k.startswith("sync_conn_changes_")
                           for k in snap)
        finally:
            svc.close()
    finally:
        monkeypatch.delenv("AMTPU_DOCLEDGER")
        docledger._reload_for_tests()


def test_service_admission_stamps_and_forget_conn():
    a, b, ca, cb, drain = _pair()
    try:
        a.apply_changes("d", [_chg("x", 1)])
        drain()
        e = a.doc_ledger.section()["docs"]["d"]
        assert e["admitted"] == 1
        assert e["last_admit_at"] is not None
        assert "B" in e["peers"]
        ca.close()
        assert "B" not in a.doc_ledger.section()["docs"]["d"]["peers"]
    finally:
        _close(a, b)


def test_tcp_per_kind_byte_accounting():
    """Exact wire bytes split by kind over a real TCP pair, plus the
    ledger lanes riding the same sync."""
    from automerge_tpu.sync.tcp import TcpSyncClient, TcpSyncServer
    a, b = EngineDocSet(backend="rows"), EngineDocSet(backend="rows")
    server = TcpSyncServer(a, wire="columnar").start()
    client = TcpSyncClient(b, "127.0.0.1", server.port,
                           wire="columnar").start()
    try:
        b.apply_changes("d", [_chg("x", 1)])
        deadline = time.time() + 10
        while time.time() < deadline:
            if a.clock_of("d") if "d" in a.doc_ids else {}:
                break
            time.sleep(0.02)
        assert a.clock_of("d") == {"x": 1}
        snap = metrics.snapshot()
        by_kind = {k: v for k, v in snap.items()
                   if k.startswith("sync_conn_bytes_")}
        assert "sync_conn_bytes_sent{kind=frame}" in by_kind
        assert "sync_conn_bytes_sent{kind=clock}" in by_kind
        assert by_kind["sync_conn_bytes_sent{kind=frame}"] > \
            by_kind["sync_conn_bytes_sent{kind=clock}"] / 10
    finally:
        client.close()
        server.close()
        _close(a, b)


def test_refresh_clocks_restamps_against_locked_read():
    svc = EngineDocSet(backend="rows")
    try:
        led = svc.doc_ledger

        class _Conn:
            peer_label = "W"
        for s in (1, 2):
            svc.apply_changes("d", [_chg("x", s)])
        led.record_advert("d", _Conn(), {"x": 5})
        # peek may or may not be warm; the explicit refresh must settle
        # the deficit exactly against the locked read
        assert led.refresh_clocks() >= 1
        e = led.section()["docs"]["d"]
        assert e["lag_changes"] == 3
    finally:
        _close(svc)


def test_chaos_doc_stall_counts_and_adverts_still_flow(monkeypatch):
    from automerge_tpu.utils import chaos
    monkeypatch.setenv("AMTPU_CHAOS_STALL_DOC", "victim")
    chaos.reload()
    try:
        a, b, ca, cb, drain = _pair()
        try:
            a.apply_changes("victim", [_chg("x", 1)])
            a.apply_changes("ok", [_chg("x", 1)])
            drain()
            # the untouched doc synced; the victim's changes never left,
            # but its clock advert DID (chaos never blinds instruments)
            assert b.clock_of("ok") == {"x": 1}
            assert "victim" not in b.doc_ids
            snap = metrics.snapshot()
            assert snap["sync_frames_dropped"] >= 1
            assert snap["obs_chaos_injected{fault=doc_stall}"] >= 1
            lane_b = b.doc_ledger.section()["docs"]["victim"]
            assert lane_b["lag_changes"] == 1
            lane_a = a.doc_ledger.section()["docs"]["victim"]
            assert lane_a["peers"]["B"]["drops"] >= 1
        finally:
            _close(a, b)
    finally:
        monkeypatch.delenv("AMTPU_CHAOS_STALL_DOC")
        chaos.reload()


def test_chaos_stall_doc_inert_when_unset():
    from automerge_tpu.utils import chaos
    assert os.environ.get("AMTPU_CHAOS_STALL_DOC") is None
    chaos.reload()
    assert chaos.stall_doc(None, "any") is False
    assert not chaos.enabled()
