"""Fleet megabatching (r20): fused multi-doc round dispatch.

The invariant every test here pins: a megabatched round's converged
hashes are BYTE-IDENTICAL to the per-doc path's, because each bucket's
gather is a pure row-index subset of the full docs-minor layout
(engine/pack.py mega_row_map). Doc identity is actor-random at init, so
parity tests generate each change set ONCE and replay it into every
service under comparison — rebuilding a "same" doc yields different
hashes by design.

Routing is cost-model driven and the baked-in link constants price
dispatches at TPU PCIe cost, so service-level tests recalibrate to
CPU-scale constants (fixture) and grow the resident caps with one large
doc so a small-doc storm's fused subset gather beats the classic
full-layout gather — the regime ROADMAP #2 targets, reproduced small.
"""

import numpy as np
import pytest

import automerge_tpu as am
from automerge_tpu.core.change import Change, Op
from automerge_tpu.core.ids import ROOT_ID
from automerge_tpu.engine import dispatch, dispatchledger, pack
from automerge_tpu.sync.service import EngineDocSet
from automerge_tpu.utils import metrics


@pytest.fixture
def cpu_link():
    """CPU-scale link constants so the planner's wire comparison (not
    the TPU round-trip tax) decides routing; restored after."""
    keys = ("dispatch_fixed_s", "h2d_call_s", "d2h_call_s")
    saved = {k: dispatch._LINK[k] for k in keys}
    dispatch.calibrate(dispatch_fixed_s=1e-5, h2d_call_s=1e-6,
                       d2h_call_s=1e-5)
    yield
    dispatch.calibrate(**saved)


def eager(svc):
    svc._lazy_resolved = True
    svc._resident.lazy_dispatch = False
    return svc


def big_doc_changes(n_ops=96):
    doc = am.change(am.init("big"), lambda d: am.assign(
        d, {"items": list(range(n_ops)), "meta": {"kind": "big"}}))
    return doc._doc.opset.get_missing_changes({})


def small_doc_changes(i):
    doc = am.change(am.init(f"w{i:03d}"), lambda d: am.assign(
        d, {"x": i, "tags": ["a", "b"]}))
    return doc._doc.opset.get_missing_changes({})


def run_fleet(changes, mega, monkeypatch=None):
    """Replay (doc_id, changes) pairs: the first pair alone (grows
    caps), the rest as ONE coalesced storm round. Returns hashes."""
    if not mega:
        assert monkeypatch is not None
        monkeypatch.setenv("AMTPU_MEGABATCH", "0")
    dispatch._reload_for_tests()
    svc = eager(EngineDocSet(backend="rows"))
    try:
        did0, chs0 = changes[0]
        svc.apply_changes(did0, chs0)
        svc.hashes()
        with svc.batch():
            for did, chs in changes[1:]:
                svc.apply_changes(did, chs)
        return {d: np.uint32(h) for d, h in svc.hashes().items()}
    finally:
        svc.close()
        if not mega:
            monkeypatch.delenv("AMTPU_MEGABATCH", raising=False)
        dispatch._reload_for_tests()


def mega_totals():
    sec = dispatchledger.ledger().section() or {}
    return {k: int(sec.get(f"mega_{k}_total") or 0)
            for k in ("rounds", "dispatches", "docs")}


# ---------------------------------------------------------------------------
# pack: quantize / row map / bucket planning


def test_mega_quantize_power_of_two_ladder():
    assert pack.mega_quantize(1, 256) == pack.MEGA_MIN_DIM
    assert pack.mega_quantize(8, 256) == 8
    assert pack.mega_quantize(9, 256) == 16
    assert pack.mega_quantize(100, 256) == 128
    # clamped at the cap even off-ladder
    assert pack.mega_quantize(100, 96) == 96
    assert pack.mega_quantize(0, 96) == pack.MEGA_MIN_DIM


def test_mega_row_map_is_an_exact_subset():
    i, a, le = 64, 2, 8 * 16
    i_b, le_b = 16, 2 * 16
    rmap = pack.mega_row_map(i, a, le, i_b, le_b)
    full = pack.rows_count(i, a, le)
    assert len(rmap) == pack.rows_count(i_b, a, le_b)
    assert len(set(rmap.tolist())) == len(rmap)      # no row twice
    assert rmap.min() >= 0 and rmap.max() < full     # inside the layout


def test_mega_row_map_full_dims_is_identity():
    i, a, le = 32, 3, 4 * 8
    rmap = pack.mega_row_map(i, a, le, i, le)
    assert np.array_equal(rmap, np.arange(pack.rows_count(i, a, le)))


def test_plan_megabuckets_caps_bucket_count():
    # pathological spread: every doc a different size
    i_used = np.asarray([1, 3, 7, 15, 31, 63, 127, 200, 9, 80],
                        np.int64)
    l_used = np.asarray([0, 1, 2, 4, 8, 16, 3, 30, 0, 12], np.int64)
    caps = (256, 2, 32 * 16)
    buckets = pack.plan_megabuckets(i_used, l_used, caps, 16)
    assert 1 <= len(buckets) <= pack.MEGA_MAX_BUCKETS
    # every doc position lands in exactly one bucket...
    seen = sorted(p for b in buckets for p in b["docs"].tolist())
    assert seen == list(range(len(i_used)))
    # ...whose dims cover its used sizes (no truncated reconcile)
    for b in buckets:
        i_b, le_b = b["dims"]
        for p in b["docs"].tolist():
            assert i_b >= i_used[p]
            assert le_b >= l_used[p] * 16 or le_b == caps[2]


# ---------------------------------------------------------------------------
# routing


def test_one_doc_round_stays_per_doc(cpu_link):
    svc = eager(EngineDocSet(backend="rows"))
    try:
        svc.apply_changes("a", small_doc_changes(0))
        svc.apply_changes("b", small_doc_changes(1))
        svc.hashes()
        rset = svc._resident
        plan = dispatch.plan_round(rset, [0])
        assert plan.route == "per_doc"          # below the doc floor
        assert dispatch.apply_round_adaptive(rset, plan) is None
    finally:
        svc.close()


def test_disabled_env_short_circuits_planning(cpu_link, monkeypatch):
    monkeypatch.setenv("AMTPU_MEGABATCH", "0")
    dispatch._reload_for_tests()
    try:
        svc = eager(EngineDocSet(backend="rows"))
        try:
            for i in range(6):
                svc.apply_changes(f"d{i}", small_doc_changes(i))
            svc.hashes()
            plan = dispatch.plan_round(svc._resident, list(range(6)))
            assert plan.route == "per_doc"
            assert plan.buckets == []           # never even planned
        finally:
            svc.close()
    finally:
        monkeypatch.delenv("AMTPU_MEGABATCH", raising=False)
        dispatch._reload_for_tests()


def test_planner_never_picks_a_costlier_fused_plan(cpu_link):
    """Pathological spread: whatever the route, the executed side of
    the cost comparison is the cheaper one — fused amplification can
    never exceed the per-doc baseline by construction."""
    svc = eager(EngineDocSet(backend="rows"))
    try:
        svc.apply_changes("big", big_doc_changes(120))
        for i in range(8):
            # one shared actor id across docs: the actor axis is pooled
            # fleet-wide and scales every row band
            doc = am.change(am.init("W"), lambda d, i=i: am.assign(
                d, {"v": i, "pad": list(range(1 + 4 * i))}))
            svc.apply_changes(f"d{i}",
                              doc._doc.opset.get_missing_changes({}))
        svc.hashes()
        plan = dispatch.plan_round(svc._resident, list(range(1, 9)))
        assert plan.buckets and \
            len(plan.buckets) <= pack.MEGA_MAX_BUCKETS
        if plan.route == "megabatch":
            assert plan.est_mega_s <= plan.est_alt_s
        else:
            assert plan.est_mega_s > plan.est_alt_s
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# byte parity vs the per-doc path (the tentpole invariant)


def test_same_shape_storm_one_bucket_one_dispatch(cpu_link, monkeypatch):
    changes = [("doc-big", big_doc_changes())]
    changes += [(f"doc{i:03d}", small_doc_changes(i)) for i in range(12)]
    base = mega_totals()
    fused = run_fleet(changes, mega=True)
    after = mega_totals()
    classic = run_fleet(changes, mega=False, monkeypatch=monkeypatch)
    assert fused == classic                     # byte-equal, all docs
    assert after["rounds"] - base["rounds"] == 1
    assert after["dispatches"] - base["dispatches"] == 1  # one bucket
    assert after["docs"] - base["docs"] == 12


def test_mixed_shape_storm_byte_equal(cpu_link, monkeypatch):
    # two shape clusters (tiny maps vs mid-size lists): few buckets,
    # each far below the full layout — the fused plan's home turf
    changes = [("doc-big", big_doc_changes(96))]
    for i in range(10):
        n_xs = 2 if i % 2 == 0 else 18
        doc = am.change(am.init("W"), lambda d, i=i, n=n_xs: am.assign(
            d, {"n": i, "xs": list(range(n))}))
        changes.append((f"doc{i:02d}",
                        doc._doc.opset.get_missing_changes({})))
    base = mega_totals()
    fused = run_fleet(changes, mega=True)
    after = mega_totals()
    classic = run_fleet(changes, mega=False, monkeypatch=monkeypatch)
    assert fused == classic
    assert after["rounds"] > base["rounds"]
    assert after["dispatches"] - base["dispatches"] <= \
        pack.MEGA_MAX_BUCKETS


def test_mixed_map_list_move_round_byte_equal(cpu_link, monkeypatch):
    """Raw map/list/move ops through the fused round — the op families
    bench config 16/20 mix, each doc's change set shared verbatim."""
    def doc_changes(i):
        ops = [Op("makeMap", f"f{i}a"), Op("makeMap", f"f{i}b"),
               Op("link", ROOT_ID, key="ka", value=f"f{i}a"),
               Op("link", ROOT_ID, key="kb", value=f"f{i}b"),
               Op("makeList", f"L{i}"),
               Op("link", ROOT_ID, key="L", value=f"L{i}")]
        prev = "_head"
        for e in range(1, 3 + i % 4):
            ops.append(Op("ins", f"L{i}", key=prev, elem=e))
            ops.append(Op("set", f"L{i}", key=f"A:{e}", value=e * 10))
            prev = f"A:{e}"
        chs = [Change("A", 1, {}, ops),
               Change("A", 2, {},
                      [Op("move", f"f{i}b", key="moved",
                          value=f"f{i}a")])]
        return chs

    changes = [("doc-big", big_doc_changes())]
    changes += [(f"doc{i}", doc_changes(i)) for i in range(9)]
    fused = run_fleet(changes, mega=True)
    classic = run_fleet(changes, mega=False, monkeypatch=monkeypatch)
    assert fused == classic


def test_both_orders_storm_converges_through_megabatch(cpu_link):
    """Two concurrent writers per doc, applied in opposite orders on
    two megabatched services: same converged hash per doc — CRDT
    convergence survives lane sharing."""
    big_chs = big_doc_changes()         # ONE shared change set: doc
    per_doc = []                        # init is actor-random
    for i in range(8):
        a = am.change(am.init(f"A{i}"),
                      lambda d, i=i: am.assign(d, {"x": i, "l": [i]}))
        b = am.merge(am.init(f"B{i}"), a)
        a2 = am.change(a, lambda d: d.__setitem__("x", 99))
        b2 = am.change(b, lambda d: d["l"].append(7))
        clk = {c.actor: c.seq
               for c in a._doc.opset.get_missing_changes({})}
        per_doc.append((a._doc.opset.get_missing_changes({}),
                        a2._doc.opset.get_missing_changes(clk),
                        b2._doc.opset.get_missing_changes(clk)))

    def storm(order):
        dispatch._reload_for_tests()
        svc = eager(EngineDocSet(backend="rows"))
        try:
            svc.apply_changes("doc-big", big_chs)
            svc.hashes()
            with svc.batch():
                for i, (base, da, db) in enumerate(per_doc):
                    svc.apply_changes(f"d{i}", base)
            first, second = (1, 2) if order == "ab" else (2, 1)
            with svc.batch():
                for i, chs in enumerate(per_doc):
                    svc.apply_changes(f"d{i}", chs[first])
            with svc.batch():
                for i, chs in enumerate(per_doc):
                    svc.apply_changes(f"d{i}", chs[second])
            return {d: np.uint32(h) for d, h in svc.hashes().items()}
        finally:
            svc.close()

    assert storm("ab") == storm("ba")


def test_fused_dispatch_failure_recovers_byte_equal(cpu_link, monkeypatch):
    """A device failure inside the fused bucket dispatch surfaces as
    DeviceDispatchError(admission_complete=True) — host truth already
    holds the round, so the sync service swallows it without replay and
    the next hash read reconciles the still-dirty lanes byte-equal to
    the classic path (the r20 counterpart of the per-doc failure soak in
    tests/test_soak_failure_injection.py)."""
    changes = [("doc-big", big_doc_changes())]
    changes += [(f"doc{i:03d}", small_doc_changes(i)) for i in range(8)]
    classic = run_fleet(changes, mega=False, monkeypatch=monkeypatch)
    dispatch._reload_for_tests()
    svc = eager(EngineDocSet(backend="rows"))
    try:
        did0, chs0 = changes[0]
        svc.apply_changes(did0, chs0)
        svc.hashes()
        rset = svc._resident
        real = rset._to_dev
        armed = {"now": True}

        def flaky(x):
            if armed["now"]:
                armed["now"] = False
                raise RuntimeError("injected fused dispatch failure")
            return real(x)

        monkeypatch.setattr(rset, "_to_dev", flaky)
        failed_before = metrics.snapshot().get("rows_dispatch_failed", 0)
        with svc.batch():
            for did, chs in changes[1:]:
                svc.apply_changes(did, chs)
        failed_after = metrics.snapshot().get("rows_dispatch_failed", 0)
        assert failed_after - failed_before >= 1    # injection fired
        assert not armed["now"]
        got = {d: np.uint32(h) for d, h in svc.hashes().items()}
        assert got == classic
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# occupancy accounting rides the fused round


def test_fused_round_summary_and_ledger_account(cpu_link):
    changes = [("doc-big", big_doc_changes())]
    changes += [(f"doc{i:03d}", small_doc_changes(i)) for i in range(12)]
    base = mega_totals()
    run_fleet(changes, mega=True)
    sec = dispatchledger.ledger().section() or {}
    after = mega_totals()
    assert after["docs"] - base["docs"] == 12
    assert int(sec.get("mega_docs_cap_total") or 0) > 0
