"""Pallas domination kernel vs a direct reference computation.

Runs in the pallas interpreter on the CPU test backend; the same kernel
compiles for real on TPU (exercised by bench/driver runs there).
"""

import numpy as np
import pytest

import jax

pl_mod = pytest.importorskip("jax.experimental.pallas")

from automerge_tpu.engine.pallas_kernels import HAVE_PALLAS, dominated_pallas  # noqa: E402

if not HAVE_PALLAS:
    pytest.skip("pallas unavailable", allow_module_level=True)


def reference_dominated(clock_op, actor, fid, seq, change_idx, amask):
    docs, n, _ = clock_op.shape
    out = np.zeros((docs, n), dtype=bool)
    for d in range(docs):
        for i in range(n):
            if not amask[d, i]:
                continue
            for j in range(n):
                if (amask[d, j] and fid[d, j] == fid[d, i]
                        and change_idx[d, j] != change_idx[d, i]
                        and clock_op[d, j, actor[d, i]] >= seq[d, i]):
                    out[d, i] = True
                    break
    return out


def random_case(rng, docs=3, n=24, n_actors=4, n_fids=6, n_changes=8):
    clock_op = rng.integers(0, 5, size=(docs, n, n_actors)).astype(np.int32)
    actor = rng.integers(0, n_actors, size=(docs, n)).astype(np.int32)
    fid = rng.integers(0, n_fids, size=(docs, n)).astype(np.int32)
    seq = rng.integers(1, 6, size=(docs, n)).astype(np.int32)
    change_idx = rng.integers(0, n_changes, size=(docs, n)).astype(np.int32)
    amask = rng.random(size=(docs, n)) < 0.8
    return clock_op, actor, fid, seq, change_idx, amask


@pytest.mark.parametrize("seed", range(5))
def test_matches_reference(seed):
    rng = np.random.default_rng(seed)
    args = random_case(rng)
    expected = reference_dominated(*args)
    interpret = jax.default_backend() != "tpu"
    actual = np.asarray(dominated_pallas(*map(jax.numpy.asarray, args),
                                         interpret=interpret))
    np.testing.assert_array_equal(actual, expected)


def test_engine_parity_on_real_batch():
    """The pallas kernel agrees with the XLA path inside field_states on a
    real encoded document batch."""
    import automerge_tpu as am
    from automerge_tpu.engine.encode import encode_doc, stack_docs, A_SET

    s1 = am.change(am.init("A"), lambda d: am.assign(d, {"x": 1, "y": 2}))
    s2 = am.merge(am.init("B"), s1)
    s1 = am.change(s1, lambda d: d.__setitem__("x", 10))
    s2 = am.change(s2, lambda d: am.assign(d, {"x": 20, "z": 3}))
    m = am.merge(s1, s2)
    changes = m._doc.opset.get_missing_changes({})
    enc = encode_doc(changes, sorted({c.actor for c in changes}))
    batch = stack_docs([enc])
    batch.pop("max_fids")

    clock_op = batch["clock"][np.arange(1)[:, None], batch["change_idx"]]
    amask = batch["op_mask"] & (batch["action"] >= A_SET)
    interpret = jax.default_backend() != "tpu"
    dom = np.asarray(dominated_pallas(
        jax.numpy.asarray(clock_op), jax.numpy.asarray(batch["actor"]),
        jax.numpy.asarray(batch["fid"]), jax.numpy.asarray(batch["seq"]),
        jax.numpy.asarray(batch["change_idx"]), jax.numpy.asarray(amask),
        interpret=interpret))
    expected = reference_dominated(clock_op, batch["actor"], batch["fid"],
                                   batch["seq"], batch["change_idx"], amask)
    np.testing.assert_array_equal(dom, expected)
