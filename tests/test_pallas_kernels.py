"""Pallas domination kernel vs a direct reference computation.

Runs in the pallas interpreter on the CPU test backend; the same kernel
compiles for real on TPU (exercised by bench/driver runs there).
"""

import numpy as np
import pytest

import jax

pl_mod = pytest.importorskip("jax.experimental.pallas")

from automerge_tpu.engine.pallas_kernels import HAVE_PALLAS, dominated_pallas  # noqa: E402

if not HAVE_PALLAS:
    pytest.skip("pallas unavailable", allow_module_level=True)


def reference_dominated(clock_op, actor, fid, seq, change_idx, amask):
    docs, n, _ = clock_op.shape
    out = np.zeros((docs, n), dtype=bool)
    for d in range(docs):
        for i in range(n):
            if not amask[d, i]:
                continue
            for j in range(n):
                if (amask[d, j] and fid[d, j] == fid[d, i]
                        and change_idx[d, j] != change_idx[d, i]
                        and clock_op[d, j, actor[d, i]] >= seq[d, i]):
                    out[d, i] = True
                    break
    return out


def random_case(rng, docs=3, n=24, n_actors=4, n_fids=6, n_changes=8):
    clock_op = rng.integers(0, 5, size=(docs, n, n_actors)).astype(np.int32)
    actor = rng.integers(0, n_actors, size=(docs, n)).astype(np.int32)
    fid = rng.integers(0, n_fids, size=(docs, n)).astype(np.int32)
    seq = rng.integers(1, 6, size=(docs, n)).astype(np.int32)
    change_idx = rng.integers(0, n_changes, size=(docs, n)).astype(np.int32)
    amask = rng.random(size=(docs, n)) < 0.8
    return clock_op, actor, fid, seq, change_idx, amask


@pytest.mark.parametrize("seed", range(5))
def test_matches_reference(seed):
    rng = np.random.default_rng(seed)
    args = random_case(rng)
    expected = reference_dominated(*args)
    interpret = jax.default_backend() != "tpu"
    actual = np.asarray(dominated_pallas(*map(jax.numpy.asarray, args),
                                         interpret=interpret))
    np.testing.assert_array_equal(actual, expected)


def test_engine_parity_on_real_batch():
    """The pallas kernel agrees with the XLA path inside field_states on a
    real encoded document batch."""
    import automerge_tpu as am
    from automerge_tpu.engine.encode import encode_doc, stack_docs, A_SET

    s1 = am.change(am.init("A"), lambda d: am.assign(d, {"x": 1, "y": 2}))
    s2 = am.merge(am.init("B"), s1)
    s1 = am.change(s1, lambda d: d.__setitem__("x", 10))
    s2 = am.change(s2, lambda d: am.assign(d, {"x": 20, "z": 3}))
    m = am.merge(s1, s2)
    changes = m._doc.opset.get_missing_changes({})
    enc = encode_doc(changes, sorted({c.actor for c in changes}))
    batch = stack_docs([enc])
    batch.pop("max_fids")

    clock_op = batch["clock"][np.arange(1)[:, None], batch["change_idx"]]
    amask = batch["op_mask"] & (batch["action"] >= A_SET)
    interpret = jax.default_backend() != "tpu"
    dom = np.asarray(dominated_pallas(
        jax.numpy.asarray(clock_op), jax.numpy.asarray(batch["actor"]),
        jax.numpy.asarray(batch["fid"]), jax.numpy.asarray(batch["seq"]),
        jax.numpy.asarray(batch["change_idx"]), jax.numpy.asarray(amask),
        interpret=interpret))
    expected = reference_dominated(clock_op, batch["actor"], batch["fid"],
                                   batch["seq"], batch["change_idx"], amask)
    np.testing.assert_array_equal(dom, expected)


# ---------------------------------------------------------------------------
# Fused reconcile megakernel: bit-parity with the XLA apply path


def _hash_both_ways(doc_changes):
    """Return (xla_hashes, pallas_hashes) for a list of per-doc change
    lists, through the packed-XLA and docs-minor-rows paths."""
    from automerge_tpu.engine.encode import encode_doc, stack_docs
    from automerge_tpu.engine.pack import (apply_packed_hash, apply_rows_hash,
                                           pack_batch, pack_rows,
                                           rows_eligible)

    actors = sorted({c.actor for changes in doc_changes for c in changes})
    encs = [encode_doc(c, actors) for c in doc_changes]
    batch = stack_docs(encs)
    max_fids = batch.pop("max_fids")
    flat, meta = pack_batch(batch)
    ref = np.asarray(apply_packed_hash(jax.numpy.asarray(flat), meta,
                                       max_fids))
    assert rows_eligible(batch, max_fids)
    rows, dims, n = pack_rows(batch, max_fids)
    interpret = jax.default_backend() != "tpu"
    got = np.asarray(apply_rows_hash(jax.numpy.asarray(rows), dims, n,
                                     interpret=interpret))
    return ref, got


def test_reconcile_rows_map_docs():
    """Concurrent map edits across a small DocSet batch: the megakernel's
    hashes are bit-identical to the XLA path's."""
    import automerge_tpu as am

    doc_changes = []
    for i in range(7):
        s1 = am.change(am.init("A"), lambda d, i=i: am.assign(
            d, {"n": i, "tag": f"t{i % 3}", "flags": {"hot": i % 2 == 0}}))
        s2 = am.merge(am.init("B"), s1)
        s1 = am.change(s1, lambda d, i=i: d.__setitem__("n", i + 1))
        s2 = am.change(s2, lambda d, i=i: am.assign(d, {"n": -i, "o": "B"}))
        m = am.merge(s1, s2)
        doc_changes.append(m._doc.opset.get_missing_changes({}))
    ref, got = _hash_both_ways(doc_changes)
    np.testing.assert_array_equal(ref, got)


def test_reconcile_rows_lists_and_tombstones():
    """List inserts/deletes (tombstone ranks, list-element hashing) agree."""
    import automerge_tpu as am

    doc_changes = []
    for i in range(3):
        d = am.change(am.init("A"), lambda doc: doc.__setitem__("xs", []))
        for j in range(4):
            d = am.change(d, lambda doc, j=j: doc["xs"].insert_at(j, j * 10))
        d = am.change(d, lambda doc: doc["xs"].delete_at(1))
        r = am.merge(am.init("B"), d)
        r = am.change(r, lambda doc: doc["xs"].insert_at(0, 99))
        m = am.merge(d, r)
        doc_changes.append(m._doc.opset.get_missing_changes({}))
    ref, got = _hash_both_ways(doc_changes)
    np.testing.assert_array_equal(ref, got)


def test_reconcile_rows_large_dims():
    """VERDICT r1 #5 done-criterion: the blocked megakernel handles I>=256
    and F>=128 per doc (far past the old unrolled kernel's 64-caps) with
    bit-identical hashes vs the XLA path."""
    import automerge_tpu as am

    big = am.change(am.init("A"), lambda d: d.__setitem__(
        "xs", list(range(12))))
    for i in range(130):
        big = am.change(big, lambda d, i=i: d.__setitem__(f"k{i}", i))
    b2 = am.change(am.merge(am.init("B"), big),
                   lambda d: d.__setitem__("k3", -1))
    big = am.merge(big, b2)
    changes = big._doc.opset.get_missing_changes({})

    from automerge_tpu.engine.encode import encode_doc, stack_docs
    actors = sorted({c.actor for c in changes})
    batch = stack_docs([encode_doc(changes, actors)] * 2)
    max_fids = batch.pop("max_fids")
    assert batch["op_mask"].shape[1] >= 256
    assert max_fids >= 128

    ref, got = _hash_both_ways([changes] * 2)
    np.testing.assert_array_equal(ref, got)


def test_reconcile_rows_convergence_hash():
    """Two replicas that merged in opposite orders hash identically through
    the megakernel (delivery-order independence)."""
    import automerge_tpu as am

    a = am.change(am.init("A"), lambda d: am.assign(d, {"x": 1, "y": [1, 2]}))
    b = am.merge(am.init("B"), a)
    a2 = am.change(a, lambda d: d.__setitem__("x", 5))
    b2 = am.change(b, lambda d: d["y"].insert_at(0, 7))
    ab = am.merge(a2, b2)
    ba = am.merge(b2, a2)
    ref, got = _hash_both_ways([
        ab._doc.opset.get_missing_changes({}),
        ba._doc.opset.get_missing_changes({})])
    np.testing.assert_array_equal(ref, got)
    assert got[0] == got[1]


def _xl_parity(doc_changes):
    """force_xl vs base kernel: bit-identical hashes (interpret mode)."""
    import jax.numpy as jnp

    from automerge_tpu.engine.encode import encode_doc, stack_docs
    from automerge_tpu.engine.pack import pack_rows
    from automerge_tpu.engine.pallas_kernels import reconcile_rows_hash

    actors = sorted({c.actor for chs in doc_changes for c in chs})
    encs = [encode_doc(c, actors) for c in doc_changes]
    batch = stack_docs(encs)
    mf = batch.pop("max_fids")
    rows, dims, n = pack_rows(batch, mf)
    assert dims[0] % 32 == 0, f"test shape must pad I to 32: {dims}"
    interp = jax.default_backend() != "tpu"
    base = np.asarray(reconcile_rows_hash(
        jnp.asarray(rows), dims, interp, False))[:n]
    xl = np.asarray(reconcile_rows_hash(
        jnp.asarray(rows), dims, interp, True))[:n]
    np.testing.assert_array_equal(base, xl)
    return dims


def test_xl_kernel_parity_maps_and_lists():
    """The doubly-blocked XL kernel (for dims whose joins would blow VMEM
    with a full axis live) hashes bit-identically to the base kernel."""
    import automerge_tpu as am

    docs = []
    for i in range(5):
        s1 = am.change(am.init("A"), lambda d, i=i: am.assign(
            d, {"n": i, "xs": [1, 2, 3]}))
        s2 = am.merge(am.init("B"), s1)
        s1 = am.change(s1, lambda d: d["xs"].delete_at(0))
        s2 = am.change(s2, lambda d, i=i: am.assign(d, {"n": -i, "o": "B"}))
        for k in range(10):
            s1 = am.change(s1, lambda d, k=k: d.__setitem__(f"k{k}", k))
        m = am.merge(s1, s2)
        docs.append(m._doc.opset.get_missing_changes({}))
    _xl_parity(docs)


def test_xl_kernel_parity_concurrent_text():
    """Concurrent text editing (tombstones, rank shifts, 3 actors) through
    the XL kernel: the shape class config 3 batched lands in."""
    import random

    import automerge_tpu as am

    rng = random.Random(9)
    docs = []
    for _ in range(2):
        def mk(d):
            d["t"] = am.Text()
            d["t"].insert_at(0, *"hello world ok")
        base = am.change(am.init("base"), mk)
        reps = {a: am.merge(am.init(a), base) for a in "AB"}
        for step in range(30):
            a = rng.choice("AB")
            d = reps[a]
            n = len(d["t"])
            if rng.random() < 0.7 or n == 0:
                d = am.change(d, lambda x, p=rng.randint(0, n):
                              x["t"].insert_at(p, rng.choice("xyz")))
            else:
                d = am.change(d, lambda x, p=rng.randrange(n):
                              x["t"].delete_at(p))
            reps[a] = d
        m = am.merge(reps["A"], reps["B"])
        docs.append(m._doc.opset.get_missing_changes({}))
    dims = _xl_parity(docs)
    assert dims[0] >= 32 and dims[2] >= 32  # ops and elems both blocked
