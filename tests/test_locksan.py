"""Unit tests for the runtime lock-order sanitizer (utils/locksan.py):
inert when AMTPU_LOCKSAN is unset, records committed-order inversions
at level 1, raises at level 2, resolves renamed locks by manifest-name
prefix, depth-counts reentrant acquires, and flags long holds only
when another thread is actually blocked."""

import json
import threading
import time

import pytest

from automerge_tpu.utils import locksan

MANIFEST = {
    "version": 1,
    "locks": [{"id": "A._a", "name": "alpha"},
              {"id": "B._b", "name": "beta"}],
    "order": [{"before": "A._a", "after": "B._b", "site": "A.both"}],
    "lockfree": [],
}


@pytest.fixture(autouse=True)
def _sanitizer_isolation(monkeypatch):
    """Every test leaves the module exactly as an unconfigured process
    would see it: env restored first, then caches re-read."""
    yield
    monkeypatch.undo()
    locksan._reload_for_tests()


def _arm(monkeypatch, tmp_path, lvl, hold_s=None, manifest=MANIFEST):
    path = tmp_path / "locks_manifest.json"
    path.write_text(json.dumps(manifest))
    monkeypatch.setenv("AMTPU_LOCKSAN_MANIFEST", str(path))
    monkeypatch.setenv("AMTPU_LOCKSAN", str(lvl))
    if hold_s is not None:
        monkeypatch.setenv("AMTPU_LOCKSAN_HOLD_S", str(hold_s))
    locksan._reload_for_tests()


def test_inert_when_unset(monkeypatch):
    monkeypatch.delenv("AMTPU_LOCKSAN", raising=False)
    locksan._reload_for_tests()
    assert locksan.on is False and locksan.level() == 0
    # the factory hands out a plain Lock: zero wrapper overhead
    lock = locksan.named_lock("alpha")
    assert isinstance(lock, type(threading.Lock()))
    # the hooks are no-ops, not errors
    locksan.note_acquire("alpha")
    locksan.note_release("alpha")
    assert locksan.violations() == []


def test_committed_order_is_clean(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path, 1)
    a = locksan.named_lock("alpha")
    b = locksan.named_lock("beta")
    with a:
        with b:
            pass
    assert locksan.violations() == []


def test_inversion_recorded_at_level_one(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path, 1)
    a = locksan.named_lock("alpha")
    b = locksan.named_lock("beta")
    with b:
        with a:        # manifest commits alpha (A._a) before beta (B._b)
            pass
    vs = locksan.violations()
    assert [v["kind"] for v in vs] == ["order"]
    assert vs[0]["lock"] == "alpha" and vs[0]["held"] == "beta"
    assert "A._a -> B._b" in vs[0]["detail"]


def test_strict_mode_raises(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path, 2)
    a = locksan.named_lock("alpha")
    b = locksan.named_lock("beta")
    b.acquire()
    try:
        with pytest.raises(locksan.LockOrderViolation):
            a.acquire()
        # strict raises AFTER the acquire: the lock is held past the
        # raise (documented test/storm-harness caveat)
        a.release()
    finally:
        b.release()
    assert len(locksan.violations()) == 1


def test_prefix_rename_keeps_identity(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path, 1)
    a3 = locksan.named_lock("alpha_shard3")     # resolves to A._a
    b = locksan.named_lock("beta")
    with b:
        with a3:
            pass
    vs = locksan.violations()
    assert len(vs) == 1 and vs[0]["lock_id"] == "A._a"


def test_unknown_name_skips_order_checking(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path, 2)
    mystery = locksan.named_lock("unmapped")
    b = locksan.named_lock("beta")
    with b:
        with mystery:      # no manifest identity: nothing to invert
            pass
    assert locksan.violations() == []


def test_reentrant_acquire_depth_counts(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path, 2)
    # simulate an RLock wrapper reporting the same name twice
    locksan.note_acquire("alpha")
    locksan.note_acquire("alpha")
    locksan.note_release("alpha")
    locksan.note_release("alpha")
    assert locksan.violations() == []
    assert getattr(locksan._tls, "stack") == []


def test_long_hold_flagged_only_with_waiters(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path, 1, hold_s=0.01)
    lock = locksan.named_lock("alpha")

    # slow hold, nobody waiting: silent
    with lock:
        time.sleep(0.03)
    assert locksan.violations() == []

    # slow hold with a blocked thread: flagged
    lock.acquire()
    t = threading.Thread(target=lambda: (lock.acquire(), lock.release()))
    t.start()
    for _ in range(200):                     # wait for the thread to block
        with locksan._meta_lock:
            if locksan._waiters.get("alpha"):
                break
        time.sleep(0.005)
    time.sleep(0.03)
    lock.release()
    t.join(timeout=5)
    vs = [v for v in locksan.violations() if v["kind"] == "long-hold"]
    assert len(vs) == 1
    assert vs[0]["waiters"] >= 1 and vs[0]["hold_s"] >= 0.01


def test_long_hold_never_raises_in_strict(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path, 2, hold_s=0.0)
    lock = locksan.named_lock("alpha")
    lock.acquire()
    t = threading.Thread(target=lambda: (lock.acquire(), lock.release()))
    t.start()
    for _ in range(200):
        with locksan._meta_lock:
            if locksan._waiters.get("alpha"):
                break
        time.sleep(0.005)
    lock.release()                           # must NOT raise
    t.join(timeout=5)


def test_missing_manifest_disarms_order_checks(monkeypatch, tmp_path):
    monkeypatch.setenv("AMTPU_LOCKSAN_MANIFEST",
                       str(tmp_path / "absent.json"))
    monkeypatch.setenv("AMTPU_LOCKSAN", "2")
    locksan._reload_for_tests()
    a = locksan.named_lock("alpha")
    b = locksan.named_lock("beta")
    with b:
        with a:
            pass
    assert locksan.violations() == []


def test_reload_for_tests_resets_everything(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path, 1)
    b = locksan.named_lock("beta")
    a = locksan.named_lock("alpha")
    with b:
        with a:
            pass
    assert locksan.violations()
    monkeypatch.delenv("AMTPU_LOCKSAN")
    locksan._reload_for_tests()
    assert locksan.on is False
    assert locksan.violations() == []


def test_violation_discloses_to_metrics_and_flightrec(monkeypatch,
                                                      tmp_path):
    """An order violation lands on all three disclosure surfaces with
    the right shapes: the labeled counter, a flightrec event whose kind
    stays `locksan_violation` (the violation class rides as
    `violation` — regression: it used to clobber the event kind), and
    the bounded list."""
    from automerge_tpu.utils import flightrec, metrics
    _arm(monkeypatch, tmp_path, 1)
    seen = len(flightrec.events())
    with locksan.named_lock("beta"):
        with locksan.named_lock("alpha"):
            pass
    snap = metrics.snapshot()
    assert snap.get(
        "obs_locksan_order_violations_total{lock=alpha}", 0) >= 1
    ev = [e for e in flightrec.events()[seen:]
          if e.get("kind") == "locksan_violation"]
    assert len(ev) == 1
    assert ev[0]["violation"] == "order" and ev[0]["lock"] == "alpha"


def test_arms_at_import_in_fresh_process(tmp_path):
    """AMTPU_LOCKSAN=1 must arm at import: the lockprof fast path tests
    `locksan.on` directly, so a process whose only named locks are
    lockprof wrappers never calls level() — the flag has to be correct
    without it (regression: it used to stay False until the first
    named_lock/level call)."""
    import os
    import subprocess
    import sys
    env = dict(os.environ, AMTPU_LOCKSAN="1", JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-c",
         "from automerge_tpu.utils import locksan; print(locksan.on)"],
        env=env, capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "True"


def test_lockprof_reports_to_sanitizer(monkeypatch, tmp_path):
    """The instrumented-lock plane feeds the sanitizer: an inversion
    through lockprof wrappers is caught exactly like a named_lock one."""
    from automerge_tpu.utils import lockprof
    _arm(monkeypatch, tmp_path, 1)
    a = lockprof.InstrumentedLock("alpha")
    b = lockprof.InstrumentedLock("beta")
    with b:
        with a:
            pass
    vs = locksan.violations()
    assert [v["kind"] for v in vs] == ["order"]
    assert vs[0]["lock"] == "alpha"
