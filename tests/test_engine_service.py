"""EngineDocSet: device-resident DocSet service syncing over columnar frames.

The r1 verdict's missing keystone: peers exchanging packed columnar deltas
end-to-end with the engine as the document store (VERDICT r1 #3). These
tests pin hash parity between engine-backed nodes and the oracle, protocol
behavior (auto-create, request-unknown-doc, duplicate/drop tolerance), and
the TCP transport carrying real binary frames.
"""

import numpy as np

import automerge_tpu as am
from automerge_tpu.engine.batchdoc import apply_batch
from automerge_tpu.sync.connection import Connection
from automerge_tpu.sync.service import EngineDocSet
from automerge_tpu.sync.tcp import TcpSyncClient, TcpSyncServer


def oracle_hash(changes):
    _, _, out = apply_batch([changes])
    return int(np.asarray(out["hash"])[0])


def two_replica_trace():
    a = am.change(am.init("A"),
                  lambda d: am.assign(d, {"x": 1, "tags": ["p", "q"]}))
    b = am.merge(am.init("B"), a)
    a = am.change(a, lambda d: d.__setitem__("x", 5))
    b = am.change(b, lambda d: d["tags"].append("r"))
    merged = am.merge(a, b)
    return (a._doc.opset.get_missing_changes({}),
            b._doc.opset.get_missing_changes({}),
            merged._doc.opset.get_missing_changes({}))


def drain(qa, ca, qb, cb, rounds=30):
    n_frames = 0
    for _ in range(rounds):
        moved = False
        while qa:
            m = qa.pop(0)
            n_frames += 1 if m.get("frame") is not None else 0
            cb.receive_msg(m)
            moved = True
        while qb:
            m = qb.pop(0)
            n_frames += 1 if m.get("frame") is not None else 0
            ca.receive_msg(m)
            moved = True
        if not moved:
            break
    return n_frames


class TestEngineService:
    def test_columnar_sync_hash_parity(self):
        chs_a, chs_b, chs_all = two_replica_trace()
        qa, qb = [], []
        ea, eb = EngineDocSet(), EngineDocSet()
        ca = Connection(ea, qa.append, wire="columnar")
        cb = Connection(eb, qb.append, wire="columnar")
        ca.open(); cb.open()
        ea.apply_changes("doc", chs_a)
        eb.apply_changes("doc", chs_b)
        n_frames = drain(qa, ca, qb, cb)
        assert n_frames >= 2  # both directions actually shipped columns
        assert ea.hashes()["doc"] == eb.hashes()["doc"] == oracle_hash(chs_all)

    def test_materialized_state_matches_oracle(self):
        chs_a, chs_b, chs_all = two_replica_trace()
        qa, qb = [], []
        ea, eb = EngineDocSet(), EngineDocSet()
        ca = Connection(ea, qa.append, wire="columnar")
        cb = Connection(eb, qb.append, wire="columnar")
        ca.open(); cb.open()
        ea.apply_changes("doc", chs_a)
        eb.apply_changes("doc", chs_b)
        drain(qa, ca, qb, cb)
        state = ea.materialize("doc")
        assert state["data"] == {"x": 5, "tags": ["p", "q", "r"]}

    def test_engine_node_syncs_with_interactive_json_peer(self):
        """An engine node and a plain interpretive DocSet (reference
        protocol, JSON wire) converge in both directions."""
        chs_a, chs_b, chs_all = two_replica_trace()
        qa, qb = [], []
        engine = EngineDocSet()
        plain = am.DocSet()
        ce = Connection(engine, qa.append, wire="columnar")
        cp = Connection(plain, qb.append, wire="json")
        ce.open(); cp.open()
        engine.apply_changes("doc", chs_a)
        plain.apply_changes("doc", chs_b)
        drain(qa, ce, qb, cp)
        doc = plain.get_doc("doc")
        assert engine.hashes()["doc"] == oracle_hash(chs_all)
        assert dict(doc)["x"] == 5 and list(doc["tags"]) == ["p", "q", "r"]

    def test_duplicate_and_out_of_order_delivery(self):
        chs_a, chs_b, chs_all = two_replica_trace()
        e = EngineDocSet()
        # deliver b's changes first (deps on a's unseen changes buffer),
        # then a's, then everything again (idempotent redelivery)
        e.apply_changes("doc", chs_b)
        e.apply_changes("doc", chs_a)
        e.apply_changes("doc", chs_a + chs_b)
        assert e.hashes()["doc"] == oracle_hash(chs_all)

    def test_unknown_doc_requested_and_filled(self):
        chs_a, _, _ = two_replica_trace()
        qa, qb = [], []
        have, want = EngineDocSet(), EngineDocSet()
        ch = Connection(have, qa.append, wire="columnar")
        cw = Connection(want, qb.append, wire="columnar")
        have.apply_changes("doc", chs_a)
        ch.open(); cw.open()
        # `have` advertises; `want` doesn't know the doc and requests it
        drain(qa, ch, qb, cw)
        assert want.get_doc("doc") is not None
        assert want.hashes()["doc"] == have.hashes()["doc"]

    def test_missing_changes_per_actor_suffix(self):
        chs_a, chs_b, _ = two_replica_trace()
        e = EngineDocSet()
        e.apply_changes("doc", chs_a + chs_b)
        full_clock = e.clock_of("doc")
        assert e.missing_changes("doc", full_clock) == []
        got = e.missing_changes("doc", {})
        assert {(c.actor, c.seq) for c in got} == \
            {(c.actor, c.seq) for c in chs_a + chs_b}

    def test_doc_axis_grows_pow2(self):
        """Auto-created docs must not change resident shapes per doc
        (VERDICT r2 review: O(log n) recompiles, not O(n))."""
        e = EngineDocSet()
        shapes = set()
        for i in range(20):
            d = am.change(am.init("A"), lambda x, i=i: x.__setitem__("n", i))
            e.apply_changes(f"doc{i}", d._doc.opset.get_missing_changes({}))
            shapes.add(e._resident.cap_docs)
        assert len(shapes) <= 4  # 1 -> 8 -> 16 -> 32, not 20 distinct sizes
        # padding rows don't corrupt real ones
        d0 = am.change(am.init("A"), lambda x: x.__setitem__("n", 0))
        assert e.hashes()["doc0"] == oracle_hash(
            d0._doc.opset.get_missing_changes({}))

    def test_hashes_cached_between_deltas(self):
        chs_a, _, _ = two_replica_trace()
        e = EngineDocSet()
        e.apply_changes("doc", chs_a)
        h1 = e.hashes()
        out_ref = e._resident._out
        h2 = e.hashes()
        assert h1 == h2 and e._resident._out is out_ref  # no re-dispatch

    def test_two_peer_tcp_no_deadlock(self):
        """Two clients ingesting into one server concurrently must not
        ABBA-deadlock across connection locks (gossip re-enters the other
        peer's connection from inside a locked receive)."""
        import time
        chs_a, chs_b, chs_all = two_replica_trace()
        hub = EngineDocSet()
        pa, pb = EngineDocSet(), EngineDocSet()
        pa.apply_changes("doc", chs_a)
        pb.apply_changes("doc", chs_b)
        server = TcpSyncServer(hub, wire="columnar").start()
        ca = TcpSyncClient(pa, server.host, server.port, wire="columnar").start()
        cb = TcpSyncClient(pb, server.host, server.port, wire="columnar").start()
        try:
            target = oracle_hash(chs_all)
            deadline = time.time() + 25
            sets = (hub, pa, pb)
            while time.time() < deadline:
                if all(s.get_doc("doc") is not None
                       and s.hashes()["doc"] == target for s in sets):
                    break
                time.sleep(0.05)
            assert [s.hashes()["doc"] for s in sets] == [target] * 3
        finally:
            ca.close(); cb.close(); server.close()

    def test_tcp_columnar_sync(self):
        chs_a, chs_b, chs_all = two_replica_trace()
        server_set, client_set = EngineDocSet(), EngineDocSet()
        server_set.apply_changes("doc", chs_a)
        client_set.apply_changes("doc", chs_b)
        server = TcpSyncServer(server_set, wire="columnar").start()
        client = TcpSyncClient(client_set, server.host, server.port,
                               wire="columnar").start()
        try:
            import time
            deadline = time.time() + 20
            target = oracle_hash(chs_all)
            while time.time() < deadline:
                if (server_set.clock_of("doc") == client_set.clock_of("doc")
                        and server_set.hashes()["doc"] == target):
                    break
                time.sleep(0.05)
            assert server_set.hashes()["doc"] == target
            assert client_set.hashes()["doc"] == target
        finally:
            client.close()
            server.close()


class TestLiveViews:
    """EngineDocSet(live_views=True): the engine's diff stream drives
    incrementally-maintained views at the service layer — frontends read
    materialized state with zero device work and subscribers receive the
    same records a remote mirror would fold in."""

    def test_views_track_engine_and_oracle_through_sync(self):
        from automerge_tpu.engine.batchdoc import oracle_state
        from automerge_tpu.frontend.materialize import apply_changes_to_doc

        chs_a, chs_b, chs_all = two_replica_trace()
        qa, qb = [], []
        ea = EngineDocSet(live_views=True)
        eb = EngineDocSet(live_views=True)
        ca = Connection(ea, qa.append, wire="columnar")
        cb = Connection(eb, qb.append, wire="columnar")
        ca.open(); cb.open()
        ea.apply_changes("doc", chs_a)
        eb.apply_changes("doc", chs_b)
        drain(qa, ca, qb, cb)

        # both nodes' live views equal their own device materialization...
        for node in (ea, eb):
            assert node.view("doc") == node.materialize("doc")
        # ...and the interpretive oracle of the merged history
        doc = am.init("o")
        doc = apply_changes_to_doc(doc, doc._doc.opset, chs_all,
                                   incremental=False)
        assert ea.view("doc") == oracle_state(doc)
        assert eb.view("doc") == ea.view("doc")

    def test_subscribers_receive_the_diff_stream(self):
        seen = []
        e = EngineDocSet(live_views=True)
        e.subscribe_views(lambda doc_id, recs: seen.append((doc_id, recs)))
        base = am.change(am.init("A"), lambda d: d.__setitem__("xs", [1]))
        e.apply_changes("d", base._doc.opset.get_missing_changes({}))
        assert seen and seen[0][0] == "d"
        actions = {(r["action"], r.get("type")) for r in seen[0][1]}
        assert ("insert", "list") in actions

        # a remote mirror fed only by the subscription tracks the service
        from automerge_tpu.core.ids import ROOT_ID
        from automerge_tpu.engine.diffs import MirrorDoc
        remote = MirrorDoc()
        for _d, recs in seen:
            remote.apply(recs)
        nxt = am.change(base, lambda d: d["xs"].insert_at(0, 0))
        e.apply_changes("d", nxt._doc.opset.get_missing_changes(
            base._doc.opset.clock))
        for _d, recs in seen[1:]:
            remote.apply(recs)
        assert remote.snapshot(ROOT_ID) == e.view("d") == e.materialize("d")

    def test_view_requires_live_mode(self):
        import pytest
        e = EngineDocSet()
        with pytest.raises(RuntimeError):
            e.view("d")

    def test_subscriber_sees_rounds_in_ingress_order(self):
        """Diff batches are index-based patches: the subscriber stream must
        be ordered per doc even with concurrent ingress threads (ADVICE r2).
        Order is frozen under the service lock; delivery never holds it."""
        import threading

        e = EngineDocSet(live_views=True)
        seen = []
        e.subscribe_views(lambda doc_id, recs: seen.append(recs))
        doc = am.change(am.init("A"), lambda d: d.__setitem__("n", -1))
        e.apply_changes("d", doc._doc.opset.get_missing_changes({}))

        rounds = []
        for i in range(16):
            prev = doc
            doc = am.change(doc, lambda d, i=i: d.__setitem__("n", i))
            rounds.append(doc._doc.opset.get_missing_changes(
                prev._doc.opset.clock))
        barrier = threading.Barrier(4)
        it = iter(rounds)
        lock = threading.Lock()

        def worker():
            barrier.wait()
            while True:
                with lock:
                    chs = next(it, None)
                if chs is None:
                    return
                e.apply_changes("d", chs)

        ts = [threading.Thread(target=worker) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # fold the delivered stream into a fresh mirror: if any batch were
        # delivered out of ingress order the index patches would corrupt it
        from automerge_tpu.core.ids import ROOT_ID
        from automerge_tpu.engine.diffs import MirrorDoc
        remote = MirrorDoc()
        for recs in seen:
            remote.apply(recs)
        assert remote.snapshot(ROOT_ID) == e.view("d")

    def test_subscriber_may_reenter_service(self):
        """A subscriber that calls back into the node (reads a view, applies
        a follow-up change) must not deadlock against the delivery path."""
        e = EngineDocSet(live_views=True)
        reentered = []

        def sub(doc_id, recs):
            reentered.append(e.view(doc_id)["data"].get("k"))

        e.subscribe_views(sub)
        doc = am.change(am.init("A"), lambda d: d.__setitem__("k", 1))
        e.apply_changes("d", doc._doc.opset.get_missing_changes({}))
        assert reentered == [1]
