"""Dispatch-efficiency ledger (engine/dispatchledger.py): env gate,
round/call folding, ambient accounting, bounded memory, pure-state
export, amplification/padding math, and the reset hook."""

import pytest

from automerge_tpu.engine import dispatchledger as dl
from automerge_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _clean():
    metrics.reset()
    yield
    metrics.reset()


class _Plan:
    def __init__(self, backend="host", est_device_s=0.002,
                 est_host_s=0.001):
        self.backend = backend
        self.est_device_s = est_device_s
        self.est_host_s = est_host_s


def _one_round(dirty=4, calls=2, ambient=1,
               axes={"docs": (3, 8), "ops": (10, 16)}):
    with dl.round_scope(dirty, label="flush"):
        for _ in range(calls):
            with dl.call_scope("fam", plan=_Plan(), docs=3, axes=axes):
                pass
        for _ in range(ambient):
            dl.note_jit("stray_kernel", retraced=False)


# -- env gate ----------------------------------------------------------------


def test_env_gate_disables_every_hook(monkeypatch):
    monkeypatch.setenv("AMTPU_DISPATCHLEDGER", "0")
    dl._reload_for_tests()
    try:
        assert dl.enabled() is False
        _one_round()
        dl.note_jit("k", retraced=True)
        assert dl.ledger().section() is None
        assert dl.snapshot_section() is None
    finally:
        monkeypatch.delenv("AMTPU_DISPATCHLEDGER")
        dl._reload_for_tests()
    assert dl.enabled() is True


# -- round/call folding ------------------------------------------------------


def test_round_folds_calls_kernels_and_buckets():
    _one_round(dirty=4, calls=2, ambient=1)
    sec = dl.ledger().section()
    assert sec["rounds_total"] == 1
    assert sec["dispatches_total"] == 2
    assert sec["ambient_total"] == 1
    assert sec["dirty_docs_total"] == 4
    (rnd,) = sec["ring"]
    assert rnd["label"] == "flush"
    assert rnd["dirty_docs"] == 4 and rnd["dispatches"] == 2
    assert rnd["ambient"] == 1
    k = rnd["kernels"]["fam"]
    assert k["calls"] == 2 and k["host"] == 2 and k["device"] == 0
    # axes {"docs": (3, 8), "ops": (10, 16)}: logical 30, padded 128
    b = rnd["buckets"]["fam:8x16"]
    assert b["calls"] == 2 and b["docs"] == 6
    assert b["docs_cap"] == 16          # padded docs axis x 2 calls
    assert b["logical"] == 60 and b["padded"] == 256


def test_window_amplification_and_waste_math():
    _one_round(dirty=4, calls=2, ambient=1)
    w = dl.ledger().section()["window"]
    # (2 dispatches + 1 ambient) / 4 dirty docs
    assert w["amplification"] == pytest.approx(0.75)
    # 100 * (1 - 60/256)
    assert w["pad_waste_pct"] == pytest.approx(76.562, abs=1e-3)
    assert w["dispatches_per_round"] == 2.0


def test_note_jit_marks_open_call_device_and_retraces():
    with dl.round_scope(1):
        with dl.call_scope("fam", backend="host"):
            dl.note_jit("fam_kernel", retraced=False)
            dl.note_jit("fam_kernel", retraced=True)
    (rnd,) = dl.ledger().section()["ring"]
    k = rnd["kernels"]["fam"]
    assert k["jits"] == 2 and k["retraces"] == 1
    assert k["device"] == 1 and k["host"] == 0   # jit => device dispatch


def test_nested_round_scope_is_a_noop():
    with dl.round_scope(2, label="outer"):
        with dl.round_scope(99, label="inner"):
            with dl.call_scope("fam"):
                pass
    sec = dl.ledger().section()
    assert sec["rounds_total"] == 1
    assert sec["ring"][0]["label"] == "outer"
    assert sec["ring"][0]["dirty_docs"] == 2


# -- ambient paths -----------------------------------------------------------


def test_call_outside_round_folds_as_ambient_pseudo_round():
    with dl.call_scope("fam", docs=5, axes={"docs": (5, 8)}):
        pass
    (rnd,) = dl.ledger().section()["ring"]
    assert rnd["label"] == "ambient"
    assert rnd["dirty_docs"] == 5 and rnd["dispatches"] == 1


def test_jit_with_no_scope_counts_ambient_total():
    dl.note_jit("stray", retraced=False)
    sec = dl.ledger().section()
    assert sec["ambient_total"] == 1
    assert sec["rounds_total"] == 0


# -- bounded memory ----------------------------------------------------------


def test_ring_is_bounded_and_export_truncates():
    for _ in range(dl.RING + 10):
        with dl.round_scope(1):
            pass
    sec = dl.ledger().section()
    assert sec["rounds_total"] == dl.RING + 10
    assert sec["window"]["rounds"] == dl.RING
    assert len(sec["ring"]) == dl.EXPORT_ROUNDS
    assert sec["ring_truncated"] == dl.RING - dl.EXPORT_ROUNDS


def test_call_cap_drops_detail_but_keeps_count():
    with dl.round_scope(1):
        for _ in range(dl.CALL_CAP + 5):
            with dl.call_scope("fam"):
                pass
    (rnd,) = dl.ledger().section()["ring"]
    assert rnd["dispatches"] == dl.CALL_CAP
    assert rnd["dropped"] == 5


def test_bucket_export_cap_reports_truncation():
    with dl.round_scope(1):
        for i in range(dl.EXPORT_BUCKETS + 3):
            with dl.call_scope("fam", axes={"docs": (1, i + 1)}):
                pass
    w = dl.ledger().section()["window"]
    assert len(w["buckets"]) == dl.EXPORT_BUCKETS
    assert w["buckets_truncated"] == 3


# -- export purity / registration -------------------------------------------


def test_section_is_pure_two_idle_snapshots_equal():
    _one_round()
    a = dl.ledger().section()
    b = dl.ledger().section()
    assert a == b


def test_snapshot_section_registered_with_nodes_shape():
    _one_round()
    snap = metrics.snapshot()
    nodes = snap["dispatchledger"]["nodes"]
    (label,) = nodes
    assert nodes[label]["rounds_total"] == 1


def test_metrics_reset_clears_ledger():
    _one_round()
    assert dl.ledger().section() is not None
    metrics.reset()
    assert dl.ledger().section() is None
    assert dl.snapshot_section() is None


def test_self_seconds_accumulates_but_stays_tiny():
    for _ in range(50):
        _one_round()
    s = dl.ledger().self_seconds()
    assert 0 < s < 1.0
