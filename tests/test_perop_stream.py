"""PerOpDiffStream (VERDICT r3 #7): the engine path's opt-in per-op,
application-ordered diff stream must match the reference-shaped stream the
interpretive oracle emits for the same admitted changes — record for
record — on both EngineDocSet backends, and a MirrorDoc folded from it
must match the node's own materialized state."""

import random

import pytest

import automerge_tpu as am
from automerge_tpu.engine.diffs import MirrorDoc, PerOpDiffStream
from automerge_tpu.sync.service import EngineDocSet


def _rounds(rng, n_rounds=6):
    """Concurrent 2-actor rounds on one doc; yields per-round deltas."""
    def mk(d):
        d["t"] = am.Text()
        d["t"].insert_at(0, *"seed")
        d["m"] = {"k": 1}
        d["xs"] = [1, 2]
    base = am.change(am.init("base"), mk)
    a = am.merge(am.init("A"), base)
    b = am.merge(am.init("B"), base)
    shipped = base
    yield base._doc.opset.get_missing_changes({})
    for rnd in range(n_rounds):
        for _ in range(rng.randint(1, 3)):
            if rng.random() < 0.5:
                n = len(a["t"])
                a = am.change(a, lambda d, p=rng.randint(0, n):
                              d["t"].insert_at(p, rng.choice("xyz")))
            else:
                a = am.change(a, lambda d, r=rnd: d["m"].__setitem__(
                    "k", r))
        b = am.change(b, lambda d, r=rnd: d["xs"].append(r))
        a = am.merge(a, b)
        b = am.merge(b, a)
        delta = a._doc.opset.get_missing_changes(shipped._doc.opset.clock)
        shipped = a
        if delta:
            yield delta


@pytest.mark.parametrize("backend", ["resident", "rows"])
def test_perop_stream_matches_oracle_record_for_record(backend):
    rng = random.Random(7)
    e = EngineDocSet(backend=backend)
    e.add_doc("d")

    got_records = []
    stream = PerOpDiffStream(e, "d", got_records.extend)

    oracle = am.init("oracle-obs")._doc.opset
    want_records = []

    for delta in _rounds(rng):
        e.apply_changes("d", delta)
        # the oracle folds what the NODE serves for the same clock window
        # (per-actor runs on docs-major, admission order on rows) so both
        # sides apply identical change sequences
        chs = e.missing_changes("d", dict(oracle.clock))
        oracle, diffs = oracle.add_changes(chs)
        want_records.extend(diffs)

    assert got_records == want_records
    assert len(got_records) > 0

    # folding the per-op stream reproduces the node's own state
    m = MirrorDoc()
    for rec in got_records:
        m.apply([rec])
    from automerge_tpu.core.ids import ROOT_ID
    snap = m.snapshot(ROOT_ID)
    assert snap == e.materialize("d")
    stream.close()


def test_perop_stream_late_attach_catches_up():
    """Attaching after admissions folds the existing log immediately."""
    e = EngineDocSet(backend="rows")
    e.add_doc("d")
    doc = am.change(am.init("W"), lambda d: am.assign(
        d, {"n": 5, "xs": [1]}))
    e.apply_changes("d", doc._doc.opset.get_missing_changes({}))

    got = []
    stream = PerOpDiffStream(e, "d", got.extend)
    assert got, "late attach must emit catch-up records"
    m = MirrorDoc()
    m.apply(got)
    from automerge_tpu.core.ids import ROOT_ID
    assert m.snapshot(ROOT_ID) == e.materialize("d")
    stream.close()
