"""save/load, history API, and diff (ports /root/reference/test/test.js
1082-1217)."""

import json

import pytest

import automerge_tpu as am


class TestSaveLoad:
    def test_roundtrip_empty(self):
        s = am.init()
        s2 = am.load(am.save(s))
        assert s2 == {}

    def test_roundtrip_map_and_list(self):
        s = am.change(am.init(), lambda d: am.assign(d, {
            "title": "hello", "tags": ["a", "b"], "meta": {"n": 1}}))
        s2 = am.load(am.save(s))
        assert s2 == {"title": "hello", "tags": ["a", "b"], "meta": {"n": 1}}

    def test_save_is_json(self):
        s = am.change(am.init(), lambda d: d.__setitem__("x", 1))
        payload = json.loads(am.save(s))
        assert "changes" in payload

    def test_load_with_actor_id(self):
        s = am.change(am.init(), lambda d: d.__setitem__("x", 1))
        s2 = am.load(am.save(s), "fresh-actor")
        assert am.get_actor_id(s2) == "fresh-actor"
        s3 = am.change(s2, lambda d: d.__setitem__("y", 2))
        assert s3 == {"x": 1, "y": 2}

    def test_conflicts_survive_roundtrip(self):
        s1 = am.change(am.init("A"), lambda d: d.__setitem__("f", "a"))
        s2 = am.change(am.init("B"), lambda d: d.__setitem__("f", "b"))
        s1 = am.merge(s1, s2)
        loaded = am.load(am.save(s1))
        assert loaded["f"] == "b"
        assert loaded._conflicts == {"f": {"A": "a"}}

    def test_history_preserved_after_roundtrip(self):
        s = am.change(am.init(), "first", lambda d: d.__setitem__("x", 1))
        s = am.change(s, "second", lambda d: d.__setitem__("y", 2))
        loaded = am.load(am.save(s))
        history = am.get_history(loaded)
        assert [h.change["message"] for h in history] == ["first", "second"]

    def test_text_survives_roundtrip(self):
        def edit(doc):
            doc["text"] = am.Text()
            doc["text"].insert_at(0, "h", "i")
        s = am.change(am.init(), edit)
        loaded = am.load(am.save(s))
        assert str(loaded["text"]) == "hi"


class TestHistory:
    def test_history_records_changes_and_snapshots(self):
        s = am.change(am.init(), "one", lambda d: d.__setitem__("a", 1))
        s = am.change(s, "two", lambda d: d.__setitem__("b", 2))
        history = am.get_history(s)
        assert len(history) == 2
        assert history[0].change["message"] == "one"
        assert history[0].snapshot == {"a": 1}
        assert history[1].snapshot == {"a": 1, "b": 2}

    def test_history_after_merge(self):
        s1 = am.change(am.init("A"), lambda d: d.__setitem__("a", 1))
        s2 = am.change(am.init("B"), lambda d: d.__setitem__("b", 2))
        m = am.merge(s1, s2)
        assert len(am.get_history(m)) == 2


class TestDiff:
    def test_diff_empty(self):
        s = am.init()
        assert am.diff(s, s) == []

    def test_diff_set_field(self):
        s1 = am.init()
        s2 = am.change(s1, lambda d: d.__setitem__("x", 1))
        diffs = am.diff(s1, s2)
        assert len(diffs) == 1
        d = diffs[0]
        assert d["action"] == "set" and d["key"] == "x" and d["value"] == 1
        assert d["type"] == "map" and d["obj"] == am.ROOT_ID
        assert d["path"] == []

    def test_diff_nested_create(self):
        s1 = am.init()
        s2 = am.change(s1, lambda d: d.__setitem__("m", {"k": "v"}))
        diffs = am.diff(s1, s2)
        actions = [(d["action"], d.get("key")) for d in diffs]
        assert ("create", None) in actions
        assert any(d["action"] == "set" and d.get("link") for d in diffs)

    def test_diff_list_ops(self):
        s1 = am.change(am.init(), lambda d: d.__setitem__("xs", ["a"]))
        s2 = am.change(s1, lambda d: d["xs"].append("b"))
        diffs = am.diff(s1, s2)
        assert len(diffs) == 1
        assert diffs[0]["action"] == "insert"
        assert diffs[0]["index"] == 1
        assert diffs[0]["value"] == "b"
        assert diffs[0]["path"] == ["xs"]

    def test_diff_list_delete(self):
        s1 = am.change(am.init(), lambda d: d.__setitem__("xs", ["a", "b"]))
        s2 = am.change(s1, lambda d: d["xs"].delete_at(0))
        diffs = am.diff(s1, s2)
        assert diffs[0]["action"] == "remove"
        assert diffs[0]["index"] == 0

    def test_diff_diverged_raises(self):
        s1 = am.change(am.init(), lambda d: d.__setitem__("a", 1))
        s2 = am.change(am.init(), lambda d: d.__setitem__("b", 2))
        with pytest.raises(ValueError):
            am.diff(s1, s2)

    def test_diff_does_not_modify_old_doc(self):
        s1 = am.init()
        s2 = am.change(s1, lambda d: d.__setitem__("x", 1))
        am.diff(s1, s2)
        assert s1 == {}


class TestInspectEquals:
    def test_inspect_plain(self):
        s = am.change(am.init(), lambda d: am.assign(d, {"a": [1, {"b": 2}]}))
        plain = am.inspect(s)
        assert plain == {"a": [1, {"b": 2}]}
        assert type(plain) is dict
        assert type(plain["a"]) is list

    def test_equals(self):
        s1 = am.change(am.init(), lambda d: d.__setitem__("a", {"b": [1, 2]}))
        s2 = am.change(am.init(), lambda d: d.__setitem__("a", {"b": [1, 2]}))
        assert am.equals(s1, s2)
        s3 = am.change(am.init(), lambda d: d.__setitem__("a", {"b": [1, 3]}))
        assert not am.equals(s1, s3)
