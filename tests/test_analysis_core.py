"""graftlint framework tests: suppression comments, baseline round-trip
and drift-tolerance, the registry pass on fixtures, and the CLI contract
(exit 0 on the repo with the committed baseline; non-zero when a hazard
is introduced)."""

import json
import pathlib
import textwrap

import pytest

from automerge_tpu.analysis import (
    Baseline, Finding, load_project, run_passes)
from automerge_tpu.analysis.__main__ import main as cli_main
from automerge_tpu.analysis.core import (
    BASELINE_NAME, apply_suppressions, parse_source)
from automerge_tpu.analysis.registry import RegistryConformancePass

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _mini_repo(tmp_path, rel, source):
    """A throwaway project holding one fixture module at `rel`."""
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))
    return load_project(tmp_path)


# ---------------------------------------------------------------------------
# registry pass on fixtures (positive + negative per rule)


def test_registry_flags_unregistered_and_fstring_names(tmp_path):
    proj = _mini_repo(tmp_path, "automerge_tpu/sync/fix.py", '''\
        from ..utils import metrics

        def good():
            metrics.bump("sync_frames_sent")          # registered: ok

        def typo():
            metrics.bump("sync_frames_snet")          # unregistered

        def indirect():
            name = "sync_frames_received"
            metrics.bump(name)                        # resolves: ok

        def fstring(kind):
            metrics.bump(f"sync_{kind}_sent")         # computed: dynamic
        ''')
    rules = {}
    for f in RegistryConformancePass().run(proj):
        rules.setdefault(f.rule, []).append(f)
    assert len(rules.get("metric-unregistered", [])) == 1
    assert "sync_frames_snet" in rules["metric-unregistered"][0].message
    assert len(rules.get("metric-dynamic", [])) == 1


def test_registry_flags_kind_mismatch_and_retired(tmp_path):
    proj = _mini_repo(tmp_path, "automerge_tpu/sync/fix.py", '''\
        from ..utils import metrics

        def wrong_kind():
            with metrics.trace("sync_frames_sent"):   # a COUNTER traced
                pass

        def retired():
            metrics.bump("changes_applied")           # pre-rename name
        ''')
    rules = {f.rule for f in RegistryConformancePass().run(proj)}
    assert "metric-kind" in rules
    assert "metric-retired" in rules


def test_registry_checks_flightrec_kinds_and_bare_imports(tmp_path):
    proj = _mini_repo(tmp_path, "automerge_tpu/sync/fix.py", '''\
        from ..utils import flightrec
        from ..utils.metrics import bump

        def ok():
            flightrec.record("frame_send", n=1)       # declared kind
            bump("sync_frames_sent")                  # bare import: checked

        def bad():
            flightrec.record("frme_send", n=1)        # typo kind
            bump("sync_frames_snet")                  # typo name
        ''')
    rules = {}
    for f in RegistryConformancePass().run(proj):
        rules.setdefault(f.rule, []).append(f)
    assert len(rules.get("flightrec-kind", [])) == 1
    assert len(rules.get("metric-unregistered", [])) == 1


def test_registry_module_constant_survives_local_rebind(tmp_path):
    """A function-local rebind of a name must not clobber the
    module-level constant other functions resolve through."""
    proj = _mini_repo(tmp_path, "automerge_tpu/sync/fix.py", '''\
        from ..utils import metrics

        NAME = "sync_frames_sent"

        def unrelated():
            NAME = compute()     # local shadow, different scope

        def uses_constant():
            metrics.bump(NAME)   # resolves to the module constant: ok
        ''')
    assert RegistryConformancePass().run(proj) == []


def test_registry_skips_wrapper_parameter_forwarding(tmp_path):
    proj = _mini_repo(tmp_path, "automerge_tpu/sync/fix.py", '''\
        from ..utils import metrics

        def wrapper(name):
            metrics.bump(name)      # plumbing: call sites are checked
        ''')
    assert RegistryConformancePass().run(proj) == []


# ---------------------------------------------------------------------------
# suppressions


def test_suppression_comment_silences_rule(tmp_path):
    proj = _mini_repo(tmp_path, "automerge_tpu/sync/fix.py", '''\
        from ..utils import metrics

        def a():
            metrics.bump("not_a_name")  # graftlint: disable=metric-unregistered

        def b():
            # graftlint: disable=metric-unregistered
            metrics.bump("also_not_a_name")

        def c():
            metrics.bump("still_not_a_name")   # NOT suppressed
        ''')
    findings = run_passes(proj, [RegistryConformancePass()])
    assert len(findings) == 1
    assert "still_not_a_name" in findings[0].message


def test_skip_file_marker(tmp_path):
    proj = _mini_repo(tmp_path, "automerge_tpu/sync/fix.py", '''\
        # graftlint: skip-file
        from ..utils import metrics

        def a():
            metrics.bump("not_a_name")
        ''')
    assert run_passes(proj, [RegistryConformancePass()]) == []


def test_suppression_only_silences_named_rule(tmp_path):
    unit = parse_source(tmp_path / "x.py", "x.py",
                        'a = 1  # graftlint: disable=other-rule\n')
    proj = load_project(tmp_path)
    proj.units.append(unit)
    f = Finding(rule="my-rule", path="x.py", line=1, col=0,
                severity="error", message="m")
    assert apply_suppressions(proj, [f]) == [f]


# ---------------------------------------------------------------------------
# baseline


def _f(rule="r", path="p.py", line=3, message="m"):
    return Finding(rule=rule, path=path, line=line, col=0,
                   severity="error", message=message)


def test_baseline_round_trip(tmp_path):
    findings = [_f(), _f(message="m2"), _f(message="m2")]
    b = Baseline.from_findings(findings)
    out = tmp_path / BASELINE_NAME
    b.save(out)
    b2 = Baseline.load(out)
    assert b2.entries == b.entries
    assert b2.entries[("r", "p.py", "m2")]["count"] == 2
    grandfathered, new, stale = b2.split(findings)
    assert (len(grandfathered), new, stale) == (3, [], [])


def test_baseline_tolerates_line_drift_but_not_new_findings():
    b = Baseline.from_findings([_f(line=3)])
    drifted = _f(line=300)                       # same finding, moved
    grand, new, stale = b.split([drifted])
    assert grand == [drifted] and not new and not stale
    extra = _f(message="brand new")
    grand, new, stale = b.split([drifted, extra])
    assert new == [extra]


def test_baseline_reports_stale_entries():
    b = Baseline.from_findings([_f(), _f(message="gone")])
    grand, new, stale = b.split([_f()])
    assert ("r", "p.py", "gone") in stale


def test_baseline_rewrite_preserves_justifications(tmp_path):
    out = tmp_path / BASELINE_NAME
    b = Baseline.from_findings([_f()])
    b.entries[("r", "p.py", "m")]["justification"] = "deliberate: why"
    b.save(out)
    regen = Baseline.from_findings([_f(line=99)], old=Baseline.load(out))
    assert regen.entries[("r", "p.py", "m")]["justification"] \
        == "deliberate: why"


# ---------------------------------------------------------------------------
# CLI contract (the acceptance criterion)


def test_cli_exits_zero_on_repo_with_committed_baseline(capsys):
    rc = cli_main(["--root", str(ROOT)])
    out = capsys.readouterr().out
    assert rc == 0, f"graftlint is red on the repo:\n{out}"
    assert "stale baseline" not in out, (
        f"baseline has stale entries — shrink it:\n{out}")


def test_cli_exits_nonzero_when_hazard_introduced(tmp_path, capsys):
    """A fresh mini-repo with one of each fixture hazard and no baseline:
    the CLI must fail. With a --write-baseline pass first, it must then
    exit 0 (the grandfathering workflow)."""
    src = tmp_path / "automerge_tpu" / "engine" / "hazard.py"
    src.parent.mkdir(parents=True)
    src.write_text(textwrap.dedent('''\
        import jax

        @jax.jit
        def f(x):
            return float(x)          # host sync under jit
        '''))
    rc = cli_main(["--root", str(tmp_path)])
    assert rc == 1
    assert "jit-host-sync" in capsys.readouterr().out
    assert cli_main(["--root", str(tmp_path), "--write-baseline"]) == 0
    assert (tmp_path / BASELINE_NAME).exists()
    assert cli_main(["--root", str(tmp_path)]) == 0


def test_cli_list_shows_grandfathered(tmp_path, capsys):
    rc = cli_main(["--root", str(ROOT), "--list"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "[baselined]" in out     # the committed deliberate holds show


def test_committed_baseline_entries_all_have_justifications():
    doc = json.loads((ROOT / BASELINE_NAME).read_text())
    assert doc["version"] == 1
    for e in doc["findings"]:
        assert e["justification"].strip(), (
            f"baseline entry without a justification: {e}")
