"""Device-side diff emission (VERDICT r1 #6): the resident engine reports
which fields/elements changed per round as reference-shaped edit records
(op_set.js:105-176), and a frontend mirror updated ONLY from those records
stays equal to a full materialization — and to the oracle."""

import random

import numpy as np
import pytest

import automerge_tpu as am
from automerge_tpu.core.ids import ROOT_ID
from automerge_tpu.engine.batchdoc import oracle_state
from automerge_tpu.engine.diffs import MirrorDoc
from automerge_tpu.engine.resident import ResidentDocSet
from automerge_tpu.frontend.materialize import apply_changes_to_doc


def _delta(prev, new):
    return new._doc.opset.get_missing_changes(prev._doc.opset.clock)


class _Tracker:
    """A resident DocSet plus per-doc mirrors fed only by engine diffs."""

    def __init__(self, doc_ids, native=None):
        self.rset = ResidentDocSet(doc_ids, native=native)
        self.mirrors = {d: MirrorDoc() for d in doc_ids}

    def round(self, changes_by_doc):
        hashes, diffs = self.rset.apply_and_reconcile(changes_by_doc,
                                                      diffs=True)
        for doc_id, records in diffs.items():
            self.mirrors[doc_id].apply(records)
        return hashes, diffs

    def check(self, doc_id):
        got = self.mirrors[doc_id].snapshot(ROOT_ID)
        want = self.rset.materialize(doc_id)
        assert got == want, f"{doc_id}:\nmirror: {got}\nengine: {want}"


@pytest.mark.parametrize("native", [None, False])
def test_incremental_mirror_follows_engine_diffs(native):
    docs = {}
    a = am.change(am.init("A"), lambda d: am.assign(
        d, {"n": 1, "xs": [10, 20], "t": am.Text(), "m": {"deep": True}}))
    a = am.change(a, lambda d: d["t"].insert_at(0, *"hi"))
    docs["d0"] = a
    b = am.change(am.init("A"), lambda d: am.assign(d, {"x": "y"}))
    docs["d1"] = b

    tr = _Tracker(["d0", "d1"], native=native)
    # round 1: initial load — diffs describe construction from empty
    tr.round({d: doc._doc.opset.get_missing_changes({})
              for d, doc in docs.items()})
    tr.check("d0")
    tr.check("d1")

    # round 2: map set + list insert + text edit + delete on d0 only
    prev = docs["d0"]
    new = am.change(prev, lambda d: d.__setitem__("n", 2))
    new = am.change(new, lambda d: d["xs"].insert_at(1, 15))
    new = am.change(new, lambda d: d["t"].insert_at(2, "!"))
    new = am.change(new, lambda d: d["m"].__delitem__("deep"))
    _, diffs = tr.round({"d0": _delta(prev, new)})
    docs["d0"] = new
    assert "d1" not in diffs, "unchanged doc must emit no records"
    tr.check("d0")
    tr.check("d1")

    # round 3: removals and a set on an existing element
    prev = docs["d0"]
    new = am.change(prev, lambda d: d["xs"].delete_at(0))
    new = am.change(new, lambda d: d["t"].delete_at(0))
    new = am.change(new, lambda d: d["xs"].__setitem__(0, 99))
    _, diffs = tr.round({"d0": _delta(prev, new)})
    docs["d0"] = new
    tr.check("d0")


def test_conflict_only_change_is_reported():
    """A concurrent losing write changes no winner, no visibility, no rank —
    only the conflict set. The survivor-hash mask must still catch it."""
    base = am.change(am.init("B"), lambda d: d.__setitem__("k", "v0"))
    tr = _Tracker(["d"])
    tr.round({"d": base._doc.opset.get_missing_changes({})})
    tr.check("d")

    # truly concurrent writes: B (higher actor) wins, A lands in conflicts
    fork = am.merge(am.init("A"), base)
    b2 = am.change(base, lambda d: d.__setitem__("k", "vb"))
    a2 = am.change(fork, lambda d: d.__setitem__("k", "va"))
    merged = am.merge(b2, a2)
    delta = merged._doc.opset.get_missing_changes(base._doc.opset.clock)
    _, diffs = tr.round({"d": delta})
    assert "d" in diffs, "conflict-only change produced no diff"
    recs = [r for r in diffs["d"] if r.get("key") == "k"]
    assert recs and recs[0]["action"] == "set" and recs[0]["value"] == "vb"
    assert recs[0]["conflicts"] == [{"actor": "A", "value": "va"}]
    tr.check("d")


def test_diff_records_match_oracle_diffs_shape():
    """Engine records for a simple round carry the same action/obj/key/value
    content as the interpretive oracle's diff stream."""
    base = am.change(am.init("A"), lambda d: am.assign(d, {"xs": [1, 2]}))
    tr = _Tracker(["d"])
    tr.round({"d": base._doc.opset.get_missing_changes({})})

    new = am.change(base, lambda d: d["xs"].insert_at(1, 7))
    new = am.change(new, lambda d: d.__setitem__("k", "v"))
    delta = _delta(base, new)
    _, diffs = tr.round({"d": delta})

    # oracle diff stream for the same delta
    _, oracle_diffs = base._doc.opset.add_changes(delta)

    def norm(recs):
        out = set()
        for r in recs:
            if r["action"] == "create":
                continue
            out.add((r["action"], r["type"], r.get("key"), r.get("index"),
                     repr(r.get("value"))))
        return out

    assert norm(diffs["d"]) == norm(oracle_diffs)
    tr.check("d")


def test_random_rounds_mirror_parity():
    """Randomized multi-round soak: mirrors driven purely by engine diffs
    track full materialization and the interpretive oracle."""
    rng = random.Random(5)
    n = 4
    ids = [f"d{i}" for i in range(n)]
    docs = {}
    for i, did in enumerate(ids):
        d = am.change(am.init("A"), lambda x, i=i: am.assign(
            x, {"n": i, "xs": [i], "t": am.Text()}))
        docs[did] = d

    tr = _Tracker(ids)
    tr.round({d: docs[d]._doc.opset.get_missing_changes({}) for d in ids})

    for rnd in range(6):
        round_changes = {}
        for did in rng.sample(ids, rng.randint(1, n)):
            prev = docs[did]
            r = rng.random()
            if r < 0.35:
                new = am.change(prev, lambda d, rnd=rnd: d.__setitem__(
                    "n", rnd * 10))
            elif r < 0.6:
                pos = rng.randint(0, len(prev["xs"]))
                new = am.change(prev, lambda d, p=pos, rnd=rnd:
                                d["xs"].insert_at(p, rnd))
            elif r < 0.8 and len(prev["xs"]):
                pos = rng.randrange(len(prev["xs"]))
                new = am.change(prev, lambda d, p=pos: d["xs"].delete_at(p))
            else:
                pos = rng.randint(0, len(prev["t"]))
                new = am.change(prev, lambda d, p=pos: d["t"].insert_at(
                    p, rng.choice("xyz")))
            round_changes[did] = _delta(prev, new)
            docs[did] = new
        tr.round(round_changes)
        for did in ids:
            tr.check(did)
            # and the oracle agrees with the engine materialization
            assert tr.rset.materialize(did) == oracle_state(docs[did])


def test_baseline_survives_add_docs_and_hash_only_rounds():
    """add_docs and diffs=False rounds must not reset the diff baseline:
    the next diff round reports only what the consumer hasn't seen (list
    inserts are not idempotent, so a reset would duplicate elements)."""
    a = am.change(am.init("A"), lambda d: d.__setitem__("xs", [1, 2, 3]))
    tr = _Tracker(["d0"])
    tr.round({"d0": a._doc.opset.get_missing_changes({})})
    tr.check("d0")

    # mid-stream doc addition nulls _out but must not reset the baseline
    tr.rset.add_docs(["d1"])
    tr.mirrors["d1"] = MirrorDoc()
    b = am.change(am.init("B"), lambda d: d.__setitem__("y", 1))
    prev_a = a
    a2 = am.change(a, lambda d: d.__setitem__("n", 7))
    _, diffs = tr.round({"d0": _delta(prev_a, a2),
                         "d1": b._doc.opset.get_missing_changes({})})
    # d0's records must NOT re-insert xs elements
    assert all(r.get("type") != "list" for r in diffs["d0"]), diffs["d0"]
    tr.check("d0")
    tr.check("d1")

    # a hash-only round's effects surface on the NEXT diff round
    a3 = am.change(a2, lambda d: d["xs"].insert_at(0, 0))
    tr.rset.apply_and_reconcile({"d0": _delta(a2, a3)})  # diffs=False
    a4 = am.change(a3, lambda d: d.__setitem__("n", 8))
    _, diffs = tr.round({"d0": _delta(a3, a4)})
    kinds = {(r["action"], r.get("type")) for r in diffs["d0"]}
    assert ("insert", "list") in kinds, "hash-only round's insert was lost"
    tr.check("d0")


def test_capacity_growth_between_hash_only_and_diff_rounds():
    """A diff round whose delta grows capacities after a hash-only round
    must not crash on baseline shape mismatch."""
    a = am.change(am.init("A"), lambda d: d.__setitem__("k", 0))
    r = ResidentDocSet(["d"])
    r.apply_and_reconcile({"d": a._doc.opset.get_missing_changes({})})
    prev = a
    big = am.change(prev, lambda d: am.assign(
        d, {f"k{i}": i for i in range(40)}))  # grows cap_ops/cap_fids
    h, diffs = r.apply_and_reconcile({"d": _delta(prev, big)}, diffs=True)
    m = MirrorDoc()
    m.apply(diffs["d"])
    # baseline was empty (first diff round): mirror sees the full doc
    assert m.snapshot(ROOT_ID) == r.materialize("d")


def test_new_actor_remap_emits_no_spurious_diffs():
    """Registering an actor that re-sorts ranks must not flag unchanged
    documents as changed."""
    docs = {}
    for i in range(3):
        docs[f"d{i}"] = am.change(am.init("M"), lambda d, i=i: am.assign(
            d, {"n": i, "xs": [i]}))
    tr = _Tracker(list(docs))
    tr.round({d: doc._doc.opset.get_missing_changes({})
              for d, doc in docs.items()})

    # actor "A" sorts before "M": global rank remap
    prev = docs["d0"]
    peer = am.change(am.merge(am.init("A"), prev),
                     lambda d: d.__setitem__("n", 99))
    merged = am.merge(prev, peer)
    _, diffs = tr.round({"d0": _delta(prev, merged)})
    docs["d0"] = merged
    assert set(diffs) == {"d0"}, f"spurious diffs: {sorted(diffs)}"
    for d in docs:
        tr.check(d)


def test_hash_only_path_unaffected():
    """diffs=False keeps the old contract (hashes only, no diff state)."""
    base = am.change(am.init("A"), lambda d: d.__setitem__("k", 1))
    r = ResidentDocSet(["d"])
    h = r.apply_and_reconcile({"d": base._doc.opset.get_missing_changes({})})
    assert isinstance(h, np.ndarray) and h.shape == (1,)


# ---------------------------------------------------------------------------
# move-plane diffs (r17 satellite: the diff stream used to FILTER move loc
# fields — now it emits location updates, and the docs-major materialize
# renders the single-location view the mirror converges to)


@pytest.mark.parametrize("native", [None, False])
def test_map_move_diffs_relocate_child(native):
    """A map move arrives as ordinary vocabulary — `remove` at the old
    parent key plus `set {link: True}` at the destination — and a chained
    move in a later round re-homes the child again."""
    base = am.change(am.init("A"), lambda d: am.assign(
        d, {"src": {"child": {"x": 1}}, "dst": {}}))
    tr = _Tracker(["d"], native=native)
    tr.round({"d": base._doc.opset.get_missing_changes({})})
    tr.check("d")

    new = am.change(base, lambda d: d["src"].move("child", d["dst"], "kid"))
    _, diffs = tr.round({"d": _delta(base, new)})
    acts = [(r["action"], r.get("key")) for r in diffs["d"]]
    assert ("remove", "child") in acts and ("set", "kid") in acts
    setrec = next(r for r in diffs["d"] if r["action"] == "set")
    assert setrec["link"] is True
    tr.check("d")
    assert tr.mirrors["d"].snapshot(ROOT_ID)["data"] == {
        "src": {}, "dst": {"kid": {"x": 1}}}

    # chained move: dst.kid -> root.home
    prev, new = new, am.change(new, lambda d: d["dst"].move("kid", d, "home"))
    _, diffs = tr.round({"d": _delta(prev, new)})
    acts = [(r["action"], r.get("key")) for r in diffs["d"]]
    assert ("remove", "kid") in acts and ("set", "home") in acts
    tr.check("d")
    assert tr.mirrors["d"].snapshot(ROOT_ID)["data"] == {
        "src": {}, "dst": {}, "home": {"x": 1}}


def test_same_round_create_and_move():
    """When the creating link and the move land in one round, the stale
    base link is suppressed (single-location rule) instead of paired with
    a remove — the mirror never sees the child at two homes."""
    base = am.change(am.init("A"), lambda d: am.assign(
        d, {"src": {"child": {"x": 1}}, "dst": {}}))
    new = am.change(base, lambda d: d["src"].move("child", d["dst"], "kid"))
    tr = _Tracker(["d"])
    _, diffs = tr.round({"d": new._doc.opset.get_missing_changes({})})
    tr.check("d")
    snap = tr.mirrors["d"].snapshot(ROOT_ID)["data"]
    assert snap == {"src": {}, "dst": {"kid": {"x": 1}}}
    # no remove was needed: the base link never surfaced
    assert not any(r["action"] == "remove" for r in diffs["d"])


def test_concurrent_map_moves_match_oracle():
    """Two replicas move the same child from the same context: the engine's
    diff stream, its materialize, and the interpretive oracle all pick the
    same single winner destination."""
    from automerge_tpu import api

    base = am.change(am.init("A"), lambda d: am.assign(
        d, {"src": {"child": {"x": 1}}, "p": {}, "q": {}}))
    forkB = am.merge(am.init("B"), base)
    a2 = am.change(base, lambda d: d["src"].move("child", d["p"], "ka"))
    b2 = am.change(forkB, lambda d: d["src"].move("child", d["q"], "kb"))
    merged = am.merge(a2, b2)

    tr = _Tracker(["d"])
    tr.round({"d": base._doc.opset.get_missing_changes({})})
    tr.round({"d": merged._doc.opset.get_missing_changes(
        base._doc.opset.clock)})
    tr.check("d")
    assert tr.mirrors["d"].snapshot(ROOT_ID)["data"] == api.inspect(merged)


def test_list_move_emits_explicit_record():
    """List moves ship an explicit `move` record (engine element ranks are
    move-agnostic by design); the mirror deliberately ignores it and stays
    in lockstep with the engine's materialize."""
    base = am.change(am.init("A"), lambda d: am.assign(d, {"xs": [10, 20, 30]}))
    tr = _Tracker(["d"])
    tr.round({"d": base._doc.opset.get_missing_changes({})})
    new = am.change(base, lambda d: d["xs"].move(0, 2))
    _, diffs = tr.round({"d": _delta(base, new)})
    movs = [r for r in diffs["d"] if r["action"] == "move"]
    assert len(movs) == 1
    rec = movs[0]
    assert rec["type"] == "list"
    assert rec["elem"].startswith("A:") and rec["anchor"].startswith("A:")
    assert isinstance(rec["counter"], int)
    tr.check("d")   # mirror == engine materialize (both move-agnostic)
