"""Hypothesis fuzz for undo/redo semantics against a snapshot model.

The model (ports the reference's contract, test.js 770-1080): undo reverts
the doc's LOCAL top-level state to the snapshot taken before the most
recent not-yet-undone local change; redo re-applies in LIFO order; a new
local change clears the redo stack; remote changes to OTHER fields merge
through undo/redo untouched. Each program also re-checks save/load
round-tripping and engine-hash parity of the final doc, so the undo
machinery's inverse ops stay inside the conformance envelope."""

import pytest

try:
    from hypothesis import HealthCheck, given, settings
    from hypothesis import strategies as st
except ImportError:  # pragma: no cover
    pytest.skip("hypothesis unavailable", allow_module_level=True)

import numpy as np

import automerge_tpu as am
from automerge_tpu.engine.batchdoc import apply_batch

_step = st.tuples(
    st.sampled_from(("set", "del", "undo", "redo", "remote")),
    st.integers(min_value=0, max_value=5),
    st.integers(min_value=-50, max_value=50),
)


@settings(max_examples=40, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(st.lists(_step, min_size=1, max_size=30))
def test_undo_redo_matches_snapshot_model(steps):
    doc = am.init("L")
    remote = am.merge(am.init("R"), doc)
    remote_counter = 0

    # model: stack of (pre-change local snapshot) for each undoable local
    # change; redo stack of snapshots undone
    undo_snaps: list[dict] = []
    redo_snaps: list[dict] = []

    def local_state():
        # only the fields local changes touch (kN); remote uses rN keys
        return {k: v for k, v in dict(doc).items() if k.startswith("k")}

    for (kind, k, v) in steps:
        if kind == "set":
            pre = local_state()
            new = am.change(doc, lambda d, k=k, v=v: d.__setitem__(
                f"k{k}", v))
            # writing the current value is a no-op change (test.js:94):
            # nothing lands, nothing becomes undoable
            if new is not doc:
                undo_snaps.append(pre)
                redo_snaps.clear()
            doc = new
        elif kind == "del":
            key = f"k{k}"
            if key in doc:
                pre = local_state()
                doc = am.change(doc, lambda d, key=key: d.__delitem__(key))
                undo_snaps.append(pre)
                redo_snaps.clear()
        elif kind == "undo":
            assert am.can_undo(doc) == bool(undo_snaps)
            if undo_snaps:
                redo_snaps.append(local_state())
                doc = am.undo(doc)
                want = undo_snaps.pop()
                assert local_state() == want, (local_state(), want)
        elif kind == "redo":
            assert am.can_redo(doc) == bool(redo_snaps)
            if redo_snaps:
                pre = local_state()
                doc = am.redo(doc)
                want = redo_snaps.pop()
                assert local_state() == want, (local_state(), want)
                undo_snaps.append(pre)  # the redone change is undoable
        elif kind == "remote":
            remote = am.merge(remote, doc)
            remote = am.change(remote, lambda d, c=remote_counter, v=v:
                               d.__setitem__(f"r{c % 3}", v))
            remote_counter += 1
            doc = am.merge(doc, remote)
            # remote edits must not disturb the undo model's view
    # end-state conformance: save/load, engine hash parity
    loaded = am.load(am.save(doc))
    assert am.equals(loaded, doc)
    changes = doc._doc.opset.get_missing_changes({})
    _, _, out = apply_batch([changes])
    _, _, out2 = apply_batch(
        [loaded._doc.opset.get_missing_changes({})])
    assert int(np.asarray(out["hash"])[0]) == int(
        np.asarray(out2["hash"])[0])
