"""Compact (dtype-narrowed) row wire: pack_rows_compact + widen_rows must
rebuild the exact int32 row buffer, and the one-dispatch compact apply must
hash bit-identically to the wide paths. The wire exists to cut transfer
bytes/calls on the host->device hop (VERDICT r2 #2: close the headline
end-to-end gap — the device reconcile already wins 50x+, the wire is what
the end-to-end number pays for)."""

import jax
import numpy as np
import pytest

import automerge_tpu as am
from automerge_tpu.engine.encode import encode_doc, stack_docs
from automerge_tpu.engine.pack import (apply_rows_hash,
                                       apply_rows_hash_compact, pack_rows,
                                       pack_rows_compact, rows_eligible,
                                       widen_rows)


def _batch_of(doc_changes):
    actors = sorted({c.actor for chs in doc_changes for c in chs})
    encs = [encode_doc(c, actors) for c in doc_changes]
    batch = stack_docs(encs)
    return batch, batch.pop("max_fids")


def _mixed_docs(n=6):
    out = []
    for i in range(n):
        s1 = am.change(am.init("A"), lambda d, i=i: am.assign(
            d, {"n": i, "tag": f"t{i % 3}", "flags": {"hot": i % 2 == 0}}))
        s2 = am.merge(am.init("B"), s1)
        s1 = am.change(s1, lambda d: d.__setitem__("xs", [1, 2, 3]))
        s1 = am.change(s1, lambda d: d["xs"].delete_at(0))
        s2 = am.change(s2, lambda d, i=i: am.assign(d, {"n": -i, "o": "B"}))
        m = am.merge(s1, s2)
        out.append(m._doc.opset.get_missing_changes({}))
    return out


def test_compact_roundtrip_exact():
    batch, max_fids = _batch_of(_mixed_docs())
    rows, dims, n = pack_rows(batch, max_fids)
    (b8, b16, b32), meta, dims2, n2 = pack_rows_compact(batch, max_fids)
    assert dims2 == dims and n2 == n
    rebuilt = np.asarray(widen_rows(
        jax.numpy.asarray(b8), jax.numpy.asarray(b16),
        jax.numpy.asarray(b32), meta))
    np.testing.assert_array_equal(rebuilt, rows)
    # the narrow wire is actually narrower (map+small-list batch: the
    # hash groups are the only 32-bit rows)
    compact_bytes = b8.nbytes + b16.nbytes + b32.nbytes
    assert compact_bytes < rows.nbytes * 0.6, (compact_bytes, rows.nbytes)


def test_compact_hash_parity():
    batch, max_fids = _batch_of(_mixed_docs())
    assert rows_eligible(batch, max_fids)
    rows, dims, n = pack_rows(batch, max_fids)
    interpret = jax.default_backend() != "tpu"
    want = np.asarray(apply_rows_hash(jax.numpy.asarray(rows), dims, n,
                                      interpret=interpret))
    (b8, b16, b32), meta, dims, n = pack_rows_compact(batch, max_fids)
    got = np.asarray(apply_rows_hash_compact(
        jax.numpy.asarray(b8), jax.numpy.asarray(b16),
        jax.numpy.asarray(b32), meta, dims, interpret))[:n]
    np.testing.assert_array_equal(want, got)


def test_bytes_wire_roundtrip_and_hash_parity():
    """Single-buffer uint8 wire: bitcast widen rebuilds the exact rows and
    hashes bit-identically (also guards byte-order assumptions)."""
    from automerge_tpu.engine.pack import (apply_rows_hash_bytes,
                                           pack_rows_bytes, widen_bytes)

    batch, max_fids = _batch_of(_mixed_docs())
    rows, dims, n = pack_rows(batch, max_fids)
    wire, bmeta, dims2, n2 = pack_rows_bytes(batch, max_fids)
    assert dims2 == dims and n2 == n
    assert wire.dtype == np.uint8 and wire.ndim == 1
    assert wire.nbytes < rows.nbytes * 0.6
    rebuilt = np.asarray(jax.jit(widen_bytes, static_argnums=1)(
        jax.numpy.asarray(wire), bmeta))
    np.testing.assert_array_equal(rebuilt, rows)

    interpret = jax.default_backend() != "tpu"
    want = np.asarray(apply_rows_hash(jax.numpy.asarray(rows), dims, n,
                                      interpret=interpret))
    got = np.asarray(apply_rows_hash_bytes(
        jax.numpy.asarray(wire), bmeta, dims, interpret))[:n]
    np.testing.assert_array_equal(want, got)


def test_compact_wide_values_fall_back_to_int32():
    """A field whose values exceed int16 keeps full width — the format is
    range-exact, not schema-fixed."""
    docs = []
    d = am.change(am.init("A"), lambda x: x.__setitem__("k", 1))
    # hash rows are always int32; fabricate a wide seq by many changes
    for i in range(40):
        d = am.change(d, lambda x, i=i: x.__setitem__("k", i))
    docs.append(d._doc.opset.get_missing_changes({}))
    batch, max_fids = _batch_of(docs)
    (b8, b16, b32), meta, dims, n = pack_rows_compact(batch, max_fids)
    rows, _, _ = pack_rows(batch, max_fids)
    rebuilt = np.asarray(widen_rows(
        jax.numpy.asarray(b8), jax.numpy.asarray(b16),
        jax.numpy.asarray(b32), meta))
    np.testing.assert_array_equal(rebuilt, rows)
    assert b32.shape[0] >= 24  # the three hash groups stay 32-bit


def test_field_sharded_virtual_docs_recombine_exactly():
    """A wide map document (2 actors x many LWW sets, config-1 shape) splits
    into field-disjoint virtual docs whose megakernel hashes SUM back to the
    real document's hash — survivor analysis is per-field independent and
    the state hash is a commutative uint32 sum."""
    from automerge_tpu.engine.batchdoc import apply_batch
    from automerge_tpu.engine.pack import (recombine_hashes,
                                           shard_batch_by_fields)

    docs = []
    for rep in range(2):
        a = am.init("A")
        for i in range(150):
            a = am.change(a, lambda d, i=i: d.__setitem__(
                f"k{i % 40}", f"A{i}"))
        b = am.merge(am.init("B"), a)
        b = am.change(b, lambda d: d.__setitem__("xs", [1, 2]))
        for i in range(120):
            b = am.change(b, lambda d, i=i: d.__setitem__(
                f"k{i % 40}", f"B{i}"))
        b = am.change(b, lambda d: d["xs"].insert_at(1, 9))
        m = am.merge(a, b)
        docs.append(m._doc.opset.get_missing_changes({}))
    # plus one small doc that must pass through whole
    small = am.change(am.init("C"), lambda d: am.assign(d, {"n": 1}))
    docs.append(small._doc.opset.get_missing_changes({}))

    batch, max_fids = _batch_of(docs)
    n = len(docs)
    sharded, owner = shard_batch_by_fields(batch, max_fids, target_ops=64)
    assert len(owner) > n, "wide docs did not split"
    assert sharded["op_mask"].shape[1] <= 128  # virtual op axis shrank
    assert rows_eligible(sharded, max_fids)
    rows, dims, nv = pack_rows(sharded, max_fids)
    interp = jax.default_backend() != "tpu"
    vh = np.asarray(apply_rows_hash(jax.numpy.asarray(rows), dims, nv,
                                    interpret=interp))
    got = recombine_hashes(vh, owner, n)
    _, _, ref = apply_batch(docs)
    want = np.asarray(ref["hash"])[:n].astype(np.uint32)
    np.testing.assert_array_equal(got, want)


def test_classification_stable_across_stream_batches():
    """ADVICE r3 (pack.py narrowing): the dtype classification is part of
    the jit static key, so two batches of the same declared shape whose
    values differ only within the headroom quantum must classify
    IDENTICALLY (no per-batch retrace), while a counter actually crossing
    half a dtype boundary escalates."""
    from automerge_tpu.engine.pack import classify_row_groups

    batch, max_fids = _batch_of(_mixed_docs())
    rows, dims, _ = pack_rows(batch, max_fids)
    w1 = classify_row_groups(rows, dims, max_fids)

    # same shape, different values (hashes differ, counters in headroom)
    batch2, max_fids2 = _batch_of(_mixed_docs())
    vh = np.asarray(batch2["value_hash"])
    batch2["value_hash"] = np.roll(vh.reshape(-1), 3).reshape(vh.shape)
    rows2, dims2, _ = pack_rows(batch2, max_fids2)
    assert dims2 == dims and max_fids2 == max_fids
    assert classify_row_groups(rows2, dims2, max_fids2) == w1

    # hash groups are pinned to int32 regardless of observed values
    from automerge_tpu.engine.pack import ROW_FIELDS, _HASH_GROUPS
    for g in _HASH_GROUPS:
        assert w1[g] == 2, ROW_FIELDS[g]

    # a counter crossing half the int8 boundary escalates that group only
    seq_g = ROW_FIELDS.index("seq")
    i_ = dims[0]
    rows3 = rows.copy()
    off = seq_g * i_   # seq is the 5th of the i-row groups
    rows3[off:off + i_][rows3[off:off + i_] > 0] += 70  # hi*2 > 127
    w3 = classify_row_groups(rows3, dims, max_fids)
    assert w3[seq_g] == 1
    assert all(w3[g] == w1[g] for g in range(len(w1)) if g != seq_g)


def test_compact_parity_after_stable_classification():
    """The stable policy must keep the byte wire bit-exact: widened rows
    equal the wide path, and hashes match the engine."""
    from automerge_tpu.engine.batchdoc import apply_batch
    from automerge_tpu.engine.pack import apply_rows_hash_bytes, \
        pack_rows_bytes

    doc_changes = _mixed_docs()
    batch, max_fids = _batch_of(doc_changes)
    if not rows_eligible(batch, max_fids):
        pytest.skip("shape outside megakernel envelope")
    wire, bmeta, dims, n = pack_rows_bytes(batch, max_fids)
    got = np.asarray(apply_rows_hash_bytes(
        jax.numpy.asarray(wire), bmeta, dims, True))[:n].astype(np.uint32)
    _, _, ref = apply_batch(doc_changes)
    want = np.asarray(ref["hash"])[:n].astype(np.uint32)
    np.testing.assert_array_equal(got, want)
