"""Failure-injection soak for the round-4 recovery machinery: a rows sync
node ingesting a long random concurrent trace while device dispatches fail
at random points must end bit-identical to a never-failed node and to the
interpretive oracle — admission must be exactly-once (no drops, no double
applies) across dispatch failures, readback failures, and mid-admission
rebuilds."""

import random

import numpy as np
import pytest

import automerge_tpu as am
from automerge_tpu.engine.resident_rows import DeviceDispatchError
from automerge_tpu.sync.service import EngineDocSet

from tests.test_rows_service import oracle_hash


def _trace(rng, n_docs=12, n_rounds=10):
    """Random concurrent 2-actor edits over n_docs docs; yields per-round
    {doc_id: delta} dicts and returns final per-doc full change sets."""
    docs = {}
    for i in range(n_docs):
        a = am.change(am.init("A"), lambda d, i=i: am.assign(
            d, {"n": i, "xs": [i], "t": am.Text()}))
        docs[f"d{i}"] = (a, am.merge(am.init("B"), a))
    # round 0 ships every doc's base state; later rounds are deltas
    rounds = [{did: am.merge(a, b)._doc.opset.get_missing_changes({})
               for did, (a, b) in docs.items()}]
    for rnd in range(n_rounds):
        deltas = {}
        for did in rng.sample(list(docs), rng.randint(1, n_docs)):
            a, b = docs[did]
            which = rng.random()
            if which < 0.4:
                a2 = am.change(a, lambda d, r=rnd: d.__setitem__("n", r))
                b2 = b
            elif which < 0.7:
                b2 = am.change(b, lambda d, r=rnd: d["xs"].append(r))
                a2 = a
            else:
                a2 = am.change(a, lambda d: d["t"].insert_at(
                    0, rng.choice("xyz")))
                b2 = b
            m = am.merge(a2, b2)
            m2 = am.merge(b2, a2)
            old_clock = dict(am.merge(a, b)._doc.opset.clock)
            deltas[did] = m._doc.opset.get_missing_changes(old_clock)
            docs[did] = (m, m2)
        if deltas:
            rounds.append(deltas)
    finals = {did: am.merge(a, b)._doc.opset.get_missing_changes({})
              for did, (a, b) in docs.items()}
    return rounds, finals


@pytest.mark.parametrize("seed", [11, 22, 33])
def test_soak_random_dispatch_failures_converge(seed, monkeypatch):
    rng = random.Random(seed)
    rounds, finals = _trace(rng)

    e = EngineDocSet(backend="rows")
    rset = e._resident
    if rset._native is None:
        pytest.skip("python-encoder fallback has no dispatch stage")
    # this soak targets the DISPATCH failure taxonomy (the TPU posture:
    # eager per-flush dispatch + cached hash handles); pin lazy off so the
    # CPU service default doesn't bypass the machinery under test, and pin
    # megabatch off so the fused round route (r20 — host-mirror
    # authoritative, no cached flush-time handle) doesn't bypass the
    # handle readback under test (the fused route's own failure soak
    # lives in tests/test_megabatch.py)
    from automerge_tpu.engine import dispatch as round_dispatch
    monkeypatch.setattr(round_dispatch, "_megabatch", False)
    rset.lazy_dispatch = False
    e._lazy_resolved = True
    for did in finals:
        e.add_doc(did)

    real_dispatch = rset._dispatch_final
    fail_next = {"mode": None}

    def flaky(trip_list, pre_rows, interpret):
        if fail_next["mode"] == "dispatch":
            fail_next["mode"] = None
            raise RuntimeError("injected dispatch failure")
        return real_dispatch(trip_list, pre_rows, interpret)

    rset._dispatch_final = flaky
    n_injected = 0
    for k, deltas in enumerate(rounds):
        roll = rng.random()
        if roll < 0.35:
            fail_next["mode"] = "dispatch"
            n_injected += 1
        with e.batch():
            for did, chs in deltas.items():
                e.apply_changes(did, chs)
        # the engine object survives (no rebuild on this path), so the
        # monkeypatch stays active; re-assert it is still in place
        assert e._resident is rset
        if roll >= 0.8:
            # mid-stream readback failure: poison the cached handle
            class Boom:
                def __array__(self, *a, **kw):
                    raise RuntimeError("injected readback failure")
            rset._hash_handle = Boom()
            with pytest.raises(DeviceDispatchError):
                rset.hashes()
            n_injected += 1
    rset._dispatch_final = real_dispatch
    assert n_injected >= 2, "soak injected too few failures to mean much"

    # every doc converges to the oracle hash and to a clean node
    clean = EngineDocSet(backend="rows")
    for did, chs in finals.items():
        clean.add_doc(did)
        clean.apply_changes(did, chs)
    h, hc = e.hashes(), clean.hashes()
    for did, chs in finals.items():
        want = oracle_hash(chs)
        assert np.uint32(h[did]) == want, did
        assert np.uint32(hc[did]) == want, did
        # exactly-once admission: log length == total changes
        assert (len(rset.change_log[rset.doc_index[did]])
                == len(chs)), did
        assert e.materialize(did) == clean.materialize(did), did
