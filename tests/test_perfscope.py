"""Performance plane (utils/perfscope.py): compile telemetry, phase
attribution, memory gauges, and their embedding in snapshots and
flight-recorder post-mortems. CPU-only — compile events fire identically
on every backend (the jax.monitoring listener is backend-agnostic)."""

import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import automerge_tpu as am
from automerge_tpu import metrics
from automerge_tpu.utils import flightrec, perfscope


@pytest.fixture(autouse=True)
def _fresh_metrics():
    metrics.reset()
    yield
    metrics.reset()


def _fresh_kernel(scale):
    """A jitted fn whose compile cache starts empty (fresh closure per
    call, so prior tests cannot have warmed it)."""
    @jax.jit
    def k(x):
        return (x * scale + 1).sum()
    return k


# -- compile telemetry ------------------------------------------------------


def test_dispatch_compile_telemetry_rows():
    k = _fresh_kernel(3)
    metrics.dispatch_jit("pf_toy", k, jnp.arange(8))      # compile
    metrics.dispatch_jit("pf_toy", k, jnp.arange(8))      # cache hit
    metrics.dispatch_jit("pf_toy", k, jnp.arange(16))     # retrace
    snap = metrics.snapshot()
    assert snap["engine_kernels_dispatched{kernel=pf_toy}"] == 3
    # exact: the cached dispatch must NOT count as a retrace
    assert snap["engine_kernels_retraced{kernel=pf_toy}"] == 2
    row = snap["perf"]["kernels"]["pf_toy"]
    assert row["dispatches"] == 3 and row["compiles"] == 2
    assert row["compile_s"] > 0
    # the one-time XLA analysis: cost + memory rows, plus gauges
    assert row["cost"]["flops"] > 0
    assert row["cost"]["bytes_accessed"] > 0
    assert row["memory"]["argument"] > 0
    assert snap["engine_kernel_flops{kernel=pf_toy}"] > 0
    assert snap["engine_kernel_hbm_bytes{kernel=pf_toy,section=argument}"] > 0
    assert snap["engine_kernel_compile{kernel=pf_toy}_s"] > 0


def test_dispatch_attribution_is_thread_exact():
    """The r5-era cache-size delta misattributed concurrent dispatches;
    the listener attributes through a per-thread marker stack, so two
    threads compiling different kernels at once each get exactly their
    own retraces."""
    n_shapes = 4
    kernels = {"pf_a": _fresh_kernel(5), "pf_b": _fresh_kernel(7)}
    barrier = threading.Barrier(len(kernels))
    errs = []

    def worker(name, fn):
        try:
            barrier.wait()
            for s in range(n_shapes):
                metrics.dispatch_jit(name, fn, jnp.arange(8 + s))
                metrics.dispatch_jit(name, fn, jnp.arange(8 + s))  # hit
        except Exception as e:                # surfaces on the main thread
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(n, f))
               for n, f in kernels.items()]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    snap = metrics.snapshot()
    for name in kernels:
        assert snap[f"engine_kernels_dispatched{{kernel={name}}}"] \
            == 2 * n_shapes
        assert snap[f"engine_kernels_retraced{{kernel={name}}}"] == n_shapes
        assert snap["perf"]["kernels"][name]["compiles"] == n_shapes


def test_non_jit_callable_degrades_gracefully():
    out = metrics.dispatch_jit("pf_plain", lambda x: x + 1, 41)
    assert out == 42
    snap = metrics.snapshot()
    assert snap["engine_kernels_dispatched{kernel=pf_plain}"] == 1
    assert "engine_kernels_retraced{kernel=pf_plain}" not in snap


def test_perf_section_resets_with_metrics():
    k = _fresh_kernel(11)
    metrics.dispatch_jit("pf_reset", k, jnp.arange(4))
    assert "perf" in metrics.snapshot()
    metrics.reset()
    assert metrics.snapshot() == {}
    # a post-reset dispatch still gets its cached analysis rows (the jit
    # cache survives reset; re-lowering+compiling per bench config would
    # double compile cost for nothing)
    metrics.dispatch_jit("pf_reset", k, jnp.arange(4))    # cache hit
    row = metrics.snapshot()["perf"]["kernels"]["pf_reset"]
    assert row["dispatches"] == 1 and row["compiles"] == 0
    assert "cost" in row and "memory" in row


# -- phase attribution ------------------------------------------------------


def test_phase_rollup_accumulates():
    with perfscope.phase("pack"):
        pass
    with perfscope.phase("pack"):
        with perfscope.phase("readback"):
            pass
    phases = metrics.snapshot()["perf"]["phases"]
    assert phases["pack"]["count"] == 2
    assert phases["readback"]["count"] == 1
    assert phases["pack"]["s"] >= 0


def test_phased_decorator():
    @perfscope.phased("sync_wire")
    def encode(x):
        return x * 2

    assert encode(3) == 6
    assert metrics.snapshot()["perf"]["phases"]["sync_wire"]["count"] == 1


# -- the real engine path (the acceptance-criteria shape) -------------------


def _tiny_rows_engine(n_docs=6):
    from automerge_tpu.engine.resident_rows import ResidentRowsDocSet

    doc_ids = [f"d{i}" for i in range(n_docs)]
    changes = {}
    for i, d in enumerate(doc_ids):
        s = am.change(am.init("A"), lambda doc, i=i: doc.__setitem__("n", i))
        changes[d] = s._doc.opset.get_missing_changes({})
    rset = ResidentRowsDocSet(doc_ids)
    rset.apply_rounds([changes])
    return rset


def test_every_dispatched_kernel_has_perf_rows():
    """The acceptance criterion: every kernel dispatched in a CPU run has
    compile-count, cost, and memory rows in metrics.snapshot()["perf"]."""
    rset = _tiny_rows_engine()
    rset.hashes()
    snap = metrics.snapshot()
    dispatched = {k.split("{kernel=")[1].rstrip("}")
                  for k in snap
                  if k.startswith("engine_kernels_dispatched{")}
    assert dispatched, "the rows engine dispatched nothing?"
    perf_kernels = snap["perf"]["kernels"]
    for kernel in dispatched:
        row = perf_kernels.get(kernel)
        assert row is not None, f"no perf row for dispatched {kernel!r}"
        assert row["dispatches"] >= 1
        assert "compiles" in row
        assert "cost" in row, f"{kernel!r} has no XLA cost analysis"
        assert "memory" in row, f"{kernel!r} has no XLA memory analysis"


def test_phases_cover_the_engine_round():
    rset = _tiny_rows_engine()
    rset.hashes()
    phases = metrics.snapshot()["perf"]["phases"]
    for name in ("dispatch", "readback", "host_materialize"):
        assert phases[name]["count"] >= 1, (name, phases)
    assert set(phases) <= set(perfscope.PHASES)


# -- memory gauges + flight-recorder embedding ------------------------------


def test_memory_gauges_present():
    rset = _tiny_rows_engine()
    rset.hashes()
    snap = metrics.snapshot()
    assert snap["rows_resident_bytes"] == rset.resident_bytes() > 0
    assert snap["obs_live_arrays_peak_bytes"] \
        >= snap["obs_live_arrays_bytes"] >= 0
    mem = snap["perf"]["memory"]
    assert mem["live_array_peak_bytes"] >= mem["live_array_bytes"]


def test_flightrec_dump_embeds_perf_plane(tmp_path):
    rset = _tiny_rows_engine()
    rset.hashes()
    path = flightrec.dump("perfscope-test", path=str(tmp_path / "dump.json"))
    assert path is not None
    doc = json.loads(open(path).read())
    m = doc["metrics"]
    assert "perf" in m and "kernels" in m["perf"]
    assert m["rows_resident_bytes"] > 0
    # the post-mortem carries the same compile telemetry the snapshot does
    assert any(v.get("dispatches", 0) >= 1
               for v in m["perf"]["kernels"].values())


def test_queue_bytes_gauge_tracks_causal_queue():
    # a change whose dependency never arrives parks in the causal queue
    from automerge_tpu.core.change import Change, Op
    from automerge_tpu.core.ids import ROOT_ID

    doc = am.init("X")
    orphan = Change(actor="Y", seq=2, deps={}, ops=[
        Op("set", ROOT_ID, key="k", value=1)])
    am.apply_changes(doc, [orphan])
    snap = metrics.snapshot()
    assert snap["core_queue_depth"] >= 1
    assert snap["core_queue_bytes"] > 0
