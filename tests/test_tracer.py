"""The trace plane (utils/tracer.py): deterministic coordination-free
sampling, the inert-unset contract, the full local lifecycle through a
real EngineDocSet, wire-header stitching (manual roundtrip and over the
in-memory connection pair), bounded tables with disclosed truncation,
TTL expiry, section purity, and the metrics reset hook.
"""

import json
import string
import time

import pytest

from automerge_tpu.core.change import Change, Op
from automerge_tpu.core.ids import ROOT_ID
from automerge_tpu.native.wire import changes_to_columns
from automerge_tpu.sync.connection import Connection
from automerge_tpu.sync.frames import TRACEPLANE_KEY
from automerge_tpu.sync.service import EngineDocSet
from automerge_tpu.utils import flightrec, metrics, tracer

TRACE_VARS = ("AMTPU_TRACE_SAMPLE", "AMTPU_TRACE_RING")


@pytest.fixture(autouse=True)
def _clean_plane(monkeypatch):
    """Every test starts and ends with the plane unset and empty."""
    for var in TRACE_VARS:
        monkeypatch.delenv(var, raising=False)
    tracer._reload_for_tests()
    tracer.reset()
    metrics.reset()          # runs the registered reset hook too
    flightrec.reset()
    yield
    for var in TRACE_VARS:
        monkeypatch.delenv(var, raising=False)
    tracer._reload_for_tests()
    tracer.reset()
    metrics.reset()
    flightrec.reset()


def _cols(actor, seq, key, value):
    return changes_to_columns([Change(
        actor=actor, seq=seq, deps={},
        ops=[Op("set", ROOT_ID, key=key, value=value)])])


def _complete_one(actor, seq, doc="d"):
    """Drive one trace through the module API to completion (origin-
    local path: finalize -> admit -> flush -> visible)."""
    tr = tracer.finalize_begin(actor, seq)
    tracer.finalize_end(tr)
    tracer.admit(doc)
    tracer.flush_round([doc], 1, time.perf_counter(), 0.0)
    tracer.visible([doc])


# ---------------------------------------------------------------------------
# sampling


def test_sampling_deterministic_and_coordination_free():
    tracer.set_sample_rate(4)
    first = [tracer.sampled("W", s) for s in range(64)]
    assert first == [tracer.sampled("W", s) for s in range(64)]
    assert any(first) and not all(first)
    # rate 1 samples everything
    tracer.set_sample_rate(1)
    assert all(tracer.sampled(a, s) for a in "ABC" for s in range(8))


def test_rate_parsing(monkeypatch):
    assert tracer.sample_rate() is None          # unset = off
    for bad in ("0", "-3", "garbage", ""):
        monkeypatch.setenv("AMTPU_TRACE_SAMPLE", bad)
        tracer._reload_for_tests()
        assert tracer.sample_rate() is None, bad
    monkeypatch.setenv("AMTPU_TRACE_SAMPLE", "8")
    tracer._reload_for_tests()
    assert tracer.sample_rate() == 8
    assert tracer.enabled()


# ---------------------------------------------------------------------------
# inert-unset contract


def test_unset_plane_records_nothing():
    assert tracer.finalize_begin("A", 1) is None
    tracer.finalize_end(None)
    tracer.origin_ingress([("A", 1)])
    tracer.admit("d")
    tracer.sealed(["d"])
    tracer.flush_round(["d"], 1, time.perf_counter(), 0.0)
    assert tracer.wire_header("d") is None
    tracer.visible()
    sec = tracer.section()
    assert sec["sample_rate"] is None
    assert sec["sampled"] == sec["completed"] == sec["inflight"] == 0
    assert sec["stages"] == {}


def test_unset_wire_envelope_carries_no_trace_key():
    ea, eb = EngineDocSet(backend="rows"), EngineDocSet(backend="rows")
    seen = []
    qa, qb = [], []
    ca = Connection(ea, lambda m: (seen.append(m), qa.append(m)),
                    wire="columnar")
    cb = Connection(eb, qb.append, wire="columnar")
    ca.open()
    cb.open()
    ea.apply_columns("doc1", _cols("A", 1, "x", 1))
    for _ in range(30):
        moved = False
        while qa:
            cb.receive_msg(qa.pop(0))
            moved = True
        while qb:
            ca.receive_msg(qb.pop(0))
            moved = True
        if not moved:
            break
    assert eb.hashes()["doc1"] == ea.hashes()["doc1"]
    assert seen and all(TRACEPLANE_KEY not in m for m in seen)


# ---------------------------------------------------------------------------
# the local lifecycle through a real service


def test_engine_service_origin_lifecycle_completes():
    tracer.set_sample_rate(1)
    svc = EngineDocSet(backend="rows")
    svc.apply_columns("d", _cols("A", 1, "x", 1))
    svc.hashes()                         # the converged-hash visibility read
    sec = tracer.section()
    assert sec["sampled"] == 1
    assert sec["completed"] == 1 and sec["stitched"] == 0
    (t,) = sec["exemplars"]
    assert t["role"] == "origin" and t["doc"] == "d"
    stages = [s[0] for s in t["spans"]]
    for st in ("finalize", "queue_wait", "coalesce_wait", "dispatch",
               "visibility"):
        assert st in stages, (st, stages)
    # spans tile: each rel start is >= the previous span's start
    rels = [s[1] for s in t["spans"]]
    assert rels == sorted(rels)
    assert t["crit_s"] >= 0.0


def test_unsampled_siblings_record_nothing():
    tracer.set_sample_rate(2)
    hot = next(a for a in string.ascii_uppercase if tracer.sampled(a, 1))
    cold = next(a for a in string.ascii_uppercase
                if not tracer.sampled(a, 1))
    svc = EngineDocSet(backend="rows")
    svc.apply_columns("d", _cols(hot, 1, "x", 1))
    svc.apply_columns("d", _cols(cold, 1, "y", 2))
    svc.hashes()
    sec = tracer.section()
    assert sec["sampled"] == 1 and sec["completed"] == 1
    assert all(t["actor"] == hot for t in sec["exemplars"])


def test_origin_ingress_dedups_frontend_finalized_trace():
    tracer.set_sample_rate(1)
    tr = tracer.finalize_begin("A", 1)
    tracer.finalize_end(tr)
    # the service boundary sees the same change again: no double-count
    tracer.origin_ingress([("A", 1), ("B", 1)])
    assert tracer.section()["sampled"] == 2      # A.1 once + B.1


def test_remote_apply_suppresses_origination():
    tracer.set_sample_rate(1)
    with tracer._plane.remote_apply():
        tracer.origin_ingress([("A", 1)])
    assert tracer.section()["sampled"] == 0
    tracer.origin_ingress([("A", 1)])            # outside: originates
    assert tracer.section()["sampled"] == 1


# ---------------------------------------------------------------------------
# stitching


def test_wire_header_roundtrip_stitches_one_trace():
    tracer.set_sample_rate(1)
    tr = tracer.finalize_begin("A", 7)
    tracer.finalize_end(tr)
    tracer.admit("d")
    tracer.flush_round(["d"], 3, time.perf_counter(), 0.001)
    hdr = tracer.wire_header("d", serialize_s=0.0005)
    assert hdr and hdr[0]["tid"] == "A.7"
    # the header is what rides the envelope: JSON-able end to end
    hdr = json.loads(json.dumps(hdr))
    adopted = tracer.wire_receive(hdr, "d")
    tracer.remote_admitted(adopted, "d", decode_s=0.0002,
                           admission_s=0.0004)
    tracer.visible(["d"])
    sec = tracer.section()
    assert sec["handed_off"] == 1 and sec["received"] == 1
    assert sec["completed"] == 1 and sec["stitched"] == 1
    (t,) = sec["exemplars"]
    assert t["stitched"] and t["role"] == "stitched"
    stages = [s[0] for s in t["spans"]]
    for st in ("finalize", "queue_wait", "dispatch", "wire_serialize",
               "wire", "remote_decode", "remote_admission", "visibility"):
        assert st in stages, (st, stages)
    # the flush round's metadata rode along
    assert t["meta"].get("round") is not None


def test_receiver_completes_even_when_locally_unset():
    """The sender paid the sampling decision: a receiver with
    AMTPU_TRACE_SAMPLE unset still adopts and completes the trace."""
    tracer.set_sample_rate(1)
    tr = tracer.finalize_begin("A", 1)
    tracer.finalize_end(tr)
    tracer.admit("d")
    tracer.flush_round(["d"], 1, time.perf_counter(), 0.0)
    hdr = tracer.wire_header("d")
    tracer.set_sample_rate(None)                 # the receiving side
    adopted = tracer.wire_receive(hdr, "d")
    assert adopted
    tracer.remote_admitted(adopted, "d")
    tracer.visible(["d"])
    sec = tracer.section()
    assert sec["completed"] == 1 and sec["stitched"] == 1


def test_malformed_wire_header_never_breaks_apply():
    tracer.set_sample_rate(1)
    assert tracer.wire_receive(None) is None
    assert tracer.wire_receive([]) is None
    assert tracer.wire_receive([{"actor": "A"}]) is None    # no seq/t0
    assert tracer.wire_receive("garbage") is None
    tracer.remote_admitted(None, "d")            # no-op, no raise


def test_wire_header_caps_per_doc_traces_with_disclosure():
    tracer.set_sample_rate(1)
    for seq in range(1, 7):
        tr = tracer.finalize_begin("A", seq)
        tracer.finalize_end(tr)
        tracer.admit("d")
    tracer.flush_round(["d"], 1, time.perf_counter(), 0.0)
    hdr = tracer.wire_header("d")
    assert len(hdr) == tracer.HEADER_MAX
    assert tracer.section()["dropped"] == 6 - tracer.HEADER_MAX


# ---------------------------------------------------------------------------
# bounded memory: ring, TTL, pending handoff


def test_completed_ring_bounded_with_disclosed_truncation(monkeypatch):
    monkeypatch.setenv("AMTPU_TRACE_RING", "8")
    tracer.reset()                               # re-reads the ring cap
    tracer.set_sample_rate(1)
    for seq in range(1, 13):
        _complete_one("A", seq)
    sec = tracer.section()
    assert sec["completed"] == 12
    assert sec["ring"] == sec["ring_cap"] == 8
    assert sec["truncated"] is True


def test_ttl_expiry_counts_instead_of_leaking():
    tracer.set_sample_rate(1)
    tr = tracer.finalize_begin("A", 1)
    tracer.finalize_end(tr)
    tracer.admit("d")
    tracer.flush_round(["d"], 1, time.perf_counter(), 0.0)
    with tracer._plane._lock:
        for traces in tracer._plane._awaiting_wire.values():
            for t in traces:
                t.born -= tracer.TTL_S + 1.0
    tracer.visible([])                           # expiry sweep, no doc
    sec = tracer.section()
    assert sec["expired"] == 1
    assert sec["inflight"] == 0 and sec["completed"] == 0


def test_pending_handoff_bounded():
    tracer.set_sample_rate(1)
    for seq in range(1, tracer.PENDING_MAX + 4):
        tr = tracer.finalize_begin("A", seq)
        tracer.finalize_end(tr)
    assert tracer.section()["dropped"] == 3      # oldest unclaimed out
    tracer.admit("d")                            # claims the survivors
    assert tracer.section()["inflight"] == tracer.PENDING_MAX


# ---------------------------------------------------------------------------
# export contract


def test_section_is_pure_and_json_able():
    tracer.set_sample_rate(1)
    _complete_one("A", 1)
    a = tracer.section()
    b = tracer.section()
    assert a == b                                # no read-side mutation
    json.dumps(a)                                # JSON-able throughout
    assert a["label"]
    assert list(a["stages"]) == [st for st in tracer.STAGES
                                 if st in a["stages"]]
    assert a["critical_path"]["count"] == 1
    snap = metrics.snapshot()
    assert snap["traceplane"]["nodes"][a["label"]]["completed"] == 1


def test_completion_emits_flightrec_exemplar():
    tracer.set_sample_rate(1)
    _complete_one("A", 1)
    kinds = [e["kind"] for e in flightrec.events()]
    assert "trace_exemplar" in kinds


def test_self_seconds_accounted():
    tracer.set_sample_rate(1)
    _complete_one("A", 1)
    assert tracer.self_seconds() > 0.0
    assert tracer.section()["self_s"] > 0.0


def test_inflight_snapshot_for_post_mortem():
    tracer.set_sample_rate(1)
    tr = tracer.finalize_begin("A", 1)
    tracer.finalize_end(tr)
    tracer.admit("d")
    live = tracer.inflight_snapshot()
    assert live and live[0]["tid"] == "A.1"
    assert live[0]["awaiting"] == "flush"


# ---------------------------------------------------------------------------
# the cross-process stitch over real TCP (the ISSUE acceptance path)


def test_tcp_stitch_one_trace_covers_both_processes():
    """A sampled change on node A crosses a REAL loopback socket and
    completes as ONE stitched trace whose spans cover both processes;
    its stage sum reconciles with the measured end-to-end lag; the
    unsampled sibling writes record nothing."""
    import numpy as np

    from automerge_tpu.sync.tcp import TcpSyncClient, TcpSyncServer

    tracer.set_sample_rate(2)
    hot = next(a for a in string.ascii_uppercase if tracer.sampled(a, 1))
    cold = next(a for a in string.ascii_uppercase
                if not tracer.sampled(a, 1))
    a = EngineDocSet(backend="rows")
    b = EngineDocSet(backend="rows")
    server = TcpSyncServer(a).start()
    client = TcpSyncClient(b, server.host, server.port).start()
    try:
        # warm the converged-hash path so the JIT compile does not land
        # inside the measured trace
        a.apply_columns("warm", _cols(cold, 1, "w", 0))
        deadline = time.perf_counter() + 30.0
        while time.perf_counter() < deadline:
            ha, hb = a.hashes(), b.hashes()
            if "warm" in ha and "warm" in hb:
                break
            time.sleep(0.02)

        t0 = time.perf_counter()
        a.apply_columns("doc1", _cols(hot, 1, "x", 1))
        a.apply_columns("doc1", _cols(cold, 2, "y", 2))
        converged = False
        e2e = None
        while time.perf_counter() < deadline:
            ha, hb = a.hashes(), b.hashes()
            if ("doc1" in ha and "doc1" in hb
                    and np.uint32(ha["doc1"]) == np.uint32(hb["doc1"])):
                e2e = time.perf_counter() - t0
                converged = True
                break
            time.sleep(0.02)
        assert converged, (a.hashes(), b.hashes())

        # the wire receive thread may still be parking the trace when the
        # hash loop exits — give completion a generous window (the flush
        # governor and socket scheduling can stretch this past a second)
        sec = tracer.section()
        for _ in range(500):
            if sec["inflight"] == 0 and sec["stitched"] >= 1:
                break
            time.sleep(0.02)
            a.hashes()
            b.hashes()
            sec = tracer.section()

        assert sec["sampled"] == 1          # hot write only; cold silent
        assert sec["handed_off"] >= 1 and sec["received"] >= 1
        assert sec["stitched"] >= 1, sec
        t = next(t for t in sec["exemplars"]
                 if t["stitched"] and t["doc"] == "doc1")
        assert t["actor"] == hot
        stages = [s[0] for s in t["spans"]]
        for st in ("finalize", "dispatch", "wire", "remote_admission",
                   "visibility"):
            assert st in stages, (st, stages)
        # stage sum reconciles with the trace's own critical path, and
        # that critical path reconciles with the measured e2e lag (the
        # poll interval and scheduling jitter bound the tolerance; at
        # millisecond-scale critical paths a few ms of scheduler gap can
        # exceed any relative bound, so the slack has an absolute floor)
        covered = sum(s[2] for s in t["spans"])
        uncovered = t["crit_s"] - covered
        assert uncovered <= max(0.25 * t["crit_s"], 0.05), (covered, t["crit_s"])
        assert t["crit_s"] <= e2e + 0.25, (t["crit_s"], e2e)
    finally:
        client.close()
        server.close()
        a.close()
        b.close()


def test_metrics_reset_hook_clears_plane():
    tracer.set_sample_rate(1)
    _complete_one("A", 1)
    assert tracer.section()["completed"] == 1
    metrics.reset()
    sec = tracer.section()
    assert sec["sampled"] == 0 and sec["completed"] == 0
    assert sec["ring"] == 0
