"""Remediation plane (round 13): recovery, not just attribution.

Covers the closed loop end to end:

- the new chaos fault classes (`conn_kill`, `peer_hang`): inert-unset
  pinned, onset/one-shot semantics, disclosure;
- the reconnect supervisor (sync/tcp.SupervisedTcpClient): exponential-
  backoff redial after an organic or injected transport death, the
  inbound-idle detector catching an accepted-but-unresponsive peer, and
  resubscribe() targeted backfill carrying a narrowed interest across
  transport generations;
- the RemediationEngine: straggler -> quarantine (with the live doctor
  cause), stale-node -> reconnect, episode recovery with measured MTTR,
  and the escalation auto-dump;
- guardrails: per-action cooldown, global budget exhaustion, quorum
  refusal (never quarantine the majority), and dry-run provably
  executing nothing;
- the governor escalation ladder (delay -> shed -> recover with
  hysteresis) replacing the single-edge SLO coupling;
- the flight-recorder dump rate-limit (per-trigger-class cooldown);
- FleetCollector quarantine/remove_peer semantics.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque

import automerge_tpu as am
import pytest
from automerge_tpu.core.change import Change, Op
from automerge_tpu.core.ids import ROOT_ID
from automerge_tpu.perf import remediate
from automerge_tpu.perf.fleet import FleetCollector, collapse
from automerge_tpu.perf.remediate import (GovernorLadder, Guardrails,
                                          RemediationEngine, fleet_green,
                                          rehome_children)
from automerge_tpu.perf.slo import SloEngine
from automerge_tpu.sync import epochs
from automerge_tpu.sync.connection import Connection
from automerge_tpu.sync.docset import DocSet
from automerge_tpu.sync.relay import RelayHub
from automerge_tpu.sync.tcp import (SupervisedTcpClient, TcpSyncClient,
                                    TcpSyncServer, sync_lock)
from automerge_tpu.utils import chaos, flightrec, metrics


def wait_until(predicate, timeout=10.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for k in list(os.environ):
        if k.startswith("AMTPU_CHAOS_"):
            monkeypatch.delenv(k, raising=False)
    chaos.reload()
    metrics.reset()
    flightrec.reset()
    yield
    chaos.reload()
    metrics.reset()


def _write(ds, doc, actor, seqs, n=1):
    for _ in range(n):
        seqs[(actor, doc)] = seqs.get((actor, doc), 0) + 1
        ds.apply_changes(doc, [Change(
            actor=actor, seq=seqs[(actor, doc)], deps={},
            ops=[Op("set", ROOT_ID, key="k",
                    value=seqs[(actor, doc)])])])


# ---------------------------------------------------------------------------
# chaos: conn_kill / peer_hang semantics


def test_new_faults_inert_unset():
    assert not chaos.enabled()
    assert chaos.conn_kill("n") is False and chaos.conn_kill(None) is False
    assert chaos.peer_hang("n") is False and chaos.peer_hang(None) is False
    snap = metrics.snapshot()
    assert not any(k.startswith("obs_chaos_injected") for k in snap)


def test_conn_kill_fires_once_after_n(monkeypatch):
    monkeypatch.setenv("AMTPU_CHAOS_CONN_KILL_AFTER", "3")
    chaos.reload()
    assert [chaos.conn_kill("x") for _ in range(6)] == [
        False, False, True, False, False, False]
    # an independent node key counts separately
    assert [chaos.conn_kill("y") for _ in range(3)] == [False, False, True]
    assert metrics.snapshot()[
        "obs_chaos_injected{fault=conn_kill}"] == 2


def test_peer_hang_window_and_onset(monkeypatch):
    monkeypatch.setenv("AMTPU_CHAOS_PEER_HANG_S", "0.15")
    monkeypatch.setenv("AMTPU_CHAOS_PEER_HANG_AFTER", "3")
    chaos.reload()
    # onset: the first two eligible receives pass through
    assert chaos.peer_hang("x") is False
    assert chaos.peer_hang("x") is False
    assert chaos.peer_hang("x") is True      # window opens on the 3rd
    assert chaos.peer_hang("x") is True
    time.sleep(0.2)
    assert chaos.peer_hang("x") is False     # window expired: responsive
    assert metrics.snapshot()[
        "obs_chaos_injected{fault=peer_hang}"] == 2


def test_conn_kill_node_targeting(monkeypatch):
    monkeypatch.setenv("AMTPU_CHAOS_CONN_KILL_AFTER", "1")
    monkeypatch.setenv("AMTPU_CHAOS_NODE", "victim")
    chaos.reload()
    assert chaos.conn_kill("bystander") is False
    assert chaos.conn_kill(None) is False
    assert chaos.conn_kill("victim") is True


# ---------------------------------------------------------------------------
# the reconnect supervisor


def test_supervisor_reconnects_after_server_side_death():
    ds_server, ds_client = DocSet(), DocSet()
    server = TcpSyncServer(ds_server).start()
    sup = SupervisedTcpClient(ds_client, server.host, server.port,
                              backoff_s=0.05).start()
    try:
        assert wait_until(lambda: sup.connection is not None
                          and server.peers)
        doc = am.change(am.init("S"), lambda d: d.__setitem__("v", 1))
        with sync_lock(ds_server):
            ds_server.set_doc("d", doc)
        assert wait_until(lambda: ds_client.get_doc("d") == {"v": 1})
        # the server-side peer dies; before the supervisor existed this
        # silently stopped convergence forever
        server.peers[0].close()
        assert wait_until(lambda: sup.generation >= 2)
        with sync_lock(ds_server):
            ds_server.set_doc("d", am.change(
                ds_server.get_doc("d"),
                lambda d: d.__setitem__("after", 2)))
        assert wait_until(
            lambda: ds_client.get_doc("d") == {"v": 1, "after": 2})
        assert metrics.snapshot().get("sync_reconnects", 0) >= 1
    finally:
        sup.close()
        server.close()


def test_supervisor_heals_chaos_conn_kill(monkeypatch):
    monkeypatch.setenv("AMTPU_CHAOS_CONN_KILL_AFTER", "5")
    monkeypatch.setenv("AMTPU_CHAOS_NODE", "cl")
    chaos.reload()
    ds_server, ds_client = DocSet(), DocSet()
    ds_client._chaos_node = "cl"
    server = TcpSyncServer(ds_server).start()
    sup = SupervisedTcpClient(ds_client, server.host, server.port,
                              backoff_s=0.05, node="cl").start()
    try:
        assert wait_until(lambda: sup.connection is not None)
        doc = am.init("C")
        for k in range(12):
            doc = am.change(doc, lambda d, k=k: d.__setitem__(f"k{k}", k))
            with sync_lock(ds_client):
                ds_client.set_doc("d", doc)
            time.sleep(0.02)
        # the killed link must come back and the tail must converge
        assert wait_until(
            lambda: ds_server.get_doc("d") == ds_client.get_doc("d")
            and ds_server.get_doc("d") is not None)
        snap = metrics.snapshot()
        assert snap["obs_chaos_injected{fault=conn_kill}"] == 1
        assert snap.get("sync_reconnects", 0) >= 1
    finally:
        sup.close()
        server.close()


def test_supervisor_idle_kick_heals_peer_hang(monkeypatch):
    monkeypatch.setenv("AMTPU_CHAOS_PEER_HANG_S", "0.6")
    monkeypatch.setenv("AMTPU_CHAOS_NODE", "cl")
    chaos.reload()
    ds_server, ds_client = DocSet(), DocSet()
    ds_client._chaos_node = "cl"
    server = TcpSyncServer(ds_server).start()
    sup = SupervisedTcpClient(ds_client, server.host, server.port,
                              backoff_s=0.05, idle_reconnect_s=0.3,
                              node="cl").start()
    try:
        assert wait_until(lambda: sup.connection is not None)
        doc = am.init("S")
        deadline = time.time() + 4.0
        k = 0
        # keep writing THROUGH the hang window: the client's reader
        # swallows these silently (socket alive, nothing applied) until
        # the idle detector forces a redial and the window expires
        while time.time() < deadline:
            doc = am.change(doc, lambda d, k=k: d.__setitem__(f"k{k}", k))
            with sync_lock(ds_server):
                ds_server.set_doc("d", doc)
            k += 1
            time.sleep(0.05)
            got = ds_client.get_doc("d")
            if k > 12 and got is not None \
                    and got == ds_server.get_doc("d"):
                break
        assert wait_until(
            lambda: ds_client.get_doc("d") == ds_server.get_doc("d")
            and ds_client.get_doc("d") is not None)
        snap = metrics.snapshot()
        assert snap.get("obs_chaos_injected{fault=peer_hang}", 0) >= 1
        assert snap.get("sync_reconnect_idle_kicks", 0) >= 1
    finally:
        sup.close()
        server.close()


def test_supervisor_resubscribe_backfills_narrowed_interest():
    """A narrowed interest survives the transport death: the replacement
    connection replays it with clocks, the server pushes exactly the
    subscribed doc's missing suffix, and the unsubscribed doc is never
    shipped — the targeted-backfill contract across a reconnect."""
    ds_server, ds_client = DocSet(), DocSet()
    server = TcpSyncServer(ds_server, wire="columnar").start()
    sup = SupervisedTcpClient(ds_client, server.host, server.port,
                              wire="columnar", backoff_s=0.05).start()
    seqs: dict = {}
    try:
        assert wait_until(lambda: sup.connection is not None
                          and server.peers)
        sup.connection.subscribe(docs=["a"])
        _write(ds_server, "a", "S", seqs, 2)
        assert wait_until(
            lambda: ds_client.get_doc("a") is not None
            and ds_client.get_doc("a")._doc.opset.clock == {"S": 2})
        server.peers[0].close()          # the link dies...
        assert wait_until(lambda: sup.generation >= 2)
        _write(ds_server, "a", "S", seqs, 3)    # ...while history grows
        _write(ds_server, "b", "S", seqs, 4)
        assert wait_until(
            lambda: ds_client.get_doc("a") is not None
            and ds_client.get_doc("a")._doc.opset.clock == {"S": 5})
        assert ds_client.get_doc("b") is None   # never subscribed
        snap = metrics.snapshot()
        assert snap.get("sync_sub_resubscribes", 0) >= 1
        assert snap.get("sync_sub_backfills", 0) >= 1
    finally:
        sup.close()
        server.close()


def test_supervisor_close_is_idempotent_and_joins():
    ds_server, ds_client = DocSet(), DocSet()
    server = TcpSyncServer(ds_server).start()
    sup = SupervisedTcpClient(ds_client, server.host, server.port,
                              backoff_s=0.05).start()
    assert wait_until(lambda: sup.connection is not None)
    sup.close()
    sup.close()
    assert not sup._thread.is_alive()
    server.close()


# ---------------------------------------------------------------------------
# guardrails


def test_guardrails_cooldown_blocks_repeat():
    g = Guardrails(cooldown_s=10.0, budget=100, window_s=100.0)
    assert g.check("quarantine", "n1", now=0.0) is None
    g.note("quarantine", "n1", 0.0, consume_budget=True)
    assert g.check("quarantine", "n1", now=5.0) == "cooldown"
    # a different node (or action) is an independent cooldown key
    assert g.check("quarantine", "n2", now=5.0) is None
    assert g.check("reconnect", "n1", now=5.0) is None
    assert g.check("quarantine", "n1", now=11.0) is None


def test_guardrails_budget_window_exhaustion():
    g = Guardrails(cooldown_s=0.0, budget=2, window_s=10.0)
    for k in range(2):
        assert g.check("reconnect", f"n{k}", now=0.0) is None
        g.note("reconnect", f"n{k}", 0.0, consume_budget=True)
    assert g.check("reconnect", "n9", now=1.0) == "budget"
    # the window slides: old actions age out
    assert g.check("reconnect", "n9", now=11.0) is None


def test_guardrails_per_action_override():
    g = Guardrails(cooldown_s=100.0, budget=10, window_s=100.0,
                   per_action_cooldown_s={"reconnect": 1.0})
    g.note("reconnect", "n1", 0.0, consume_budget=True)
    assert g.check("reconnect", "n1", now=2.0) is None   # override won


def _synthetic_collector(flush_map, interval_s=0.05):
    """3+ in-process local sources with manufactured per-tick
    round-flush costs — the deviant one reads as a slow_apply straggler
    to both the collector and the live doctor."""
    ticks = {"n": 0}

    def snapshot_fn(flush_per_tick):
        def fn():
            k = ticks["n"]
            return {"sync_ops_ingested": 50.0 * k,
                    "sync_round_flush_s": flush_per_tick * k,
                    "sync_round_flush_count": 10.0 * k}
        return fn

    collector = FleetCollector(interval_s=interval_s, k_sigma=3.0,
                               min_nodes=3)
    for name, flush in flush_map.items():
        collector.add_local(name, snapshot_fn(flush))
    return collector, ticks


def _tick(collector, ticks, n=1, sleep=0.05):
    state = None
    for _ in range(n):
        ticks["n"] += 1
        state = collector.scrape_once()
        time.sleep(sleep)
    return state


def test_engine_quarantines_flagged_straggler(tmp_path, monkeypatch):
    monkeypatch.setenv("AMTPU_FLIGHTREC_DIR", str(tmp_path))
    flightrec.reset()
    collector, ticks = _synthetic_collector(
        {"a": 0.001, "b": 0.001, "c": 1.0})
    engine = RemediationEngine(
        collector, guardrails=Guardrails(cooldown_s=0.01, budget=4,
                                         window_s=10.0))
    executed = []
    engine.on_quarantine = executed.append
    _tick(collector, ticks, 3)
    assert executed == ["c"]
    assert collector.quarantined() == ["c"]
    snap = metrics.snapshot()
    assert snap["obs_remed_actions{action=quarantine}"] == 1
    assert snap["obs_remed_quarantined"] == 1
    evs = [e for e in flightrec.events() if e["kind"] == "remed_action"]
    assert evs and evs[0]["action"] == "quarantine" \
        and evs[0]["node"] == "c" and evs[0]["dry_run"] is False
    # the escalation auto-captured a dump with the doctor report riding
    path = flightrec.last_dump()
    assert path is not None
    doc = json.load(open(path))
    assert doc["reason"] == "remed:quarantine"
    assert doc["extra"]["remediation"]["action"] == "quarantine"
    # quarantined node is OUT of the judged fleet on the next tick
    state = _tick(collector, ticks, 1)
    assert state["nodes"]["c"]["quarantined"] is True
    assert state["nodes"]["c"]["derived"] is None
    assert "c" not in state["stragglers"]


def test_engine_recovery_episode_measures_mttr():
    collector, ticks = _synthetic_collector(
        {"a": 0.001, "b": 0.001, "c": 1.0})
    engine = RemediationEngine(
        collector, guardrails=Guardrails(cooldown_s=0.01, budget=4,
                                         window_s=10.0))
    engine.on_quarantine = lambda n: None
    _tick(collector, ticks, 3)
    assert collector.quarantined() == ["c"]
    # quarantine removed the deviant: the fleet judges green, and after
    # the streak the episode closes with a measured MTTR
    _tick(collector, ticks, 3)
    assert engine.last_recovery is not None
    assert engine.last_recovery["actions"] >= 1
    assert engine.last_recovery["mttr_s"] > 0
    assert metrics.snapshot()["obs_remed_recovered"] == 1
    evs = [e for e in flightrec.events()
           if e["kind"] == "remed_recovered"]
    assert evs and evs[-1]["mttr_s"] > 0


def test_engine_quorum_refuses_majority_quarantine():
    collector, ticks = _synthetic_collector(
        {"a": 0.001, "b": 0.001, "c": 0.001, "d": 1.0})
    engine = RemediationEngine(
        collector, guardrails=Guardrails(cooldown_s=0.01, budget=10,
                                         window_s=10.0))
    executed = []
    engine.on_quarantine = executed.append
    # one node is ALREADY quarantined (a prior episode): cutting d too
    # would leave only half the fleet healthy — the quorum guardrail
    # must refuse, however deviant d looks
    collector.quarantine("c")
    _tick(collector, ticks, 4)
    state = collector.fleet_state()
    assert "d" in state["stragglers"]       # flagged, but...
    assert executed == []                   # ...never cut off
    assert collector.quarantined() == ["c"]
    assert metrics.snapshot().get(
        "obs_remed_skipped{reason=quorum}", 0) >= 1


def test_engine_dry_run_executes_nothing():
    collector, ticks = _synthetic_collector(
        {"a": 0.001, "b": 0.001, "c": 1.0})
    engine = RemediationEngine(
        collector, dry_run=True,
        guardrails=Guardrails(cooldown_s=0.01, budget=4, window_s=10.0))
    executed = []
    engine.on_quarantine = executed.append
    _tick(collector, ticks, 3)
    assert executed == []
    assert collector.quarantined() == []
    snap = metrics.snapshot()
    assert collapse(snap, "obs_remed_actions") == 0
    assert snap.get("obs_remed_skipped{reason=dry_run}", 0) >= 1
    intended = [e for e in engine.log if e["dry_run"]]
    assert intended and intended[0]["action"] == "quarantine" \
        and intended[0]["node"] == "c"
    evs = [e for e in flightrec.events() if e["kind"] == "remed_action"]
    assert evs and all(e["dry_run"] for e in evs)


def test_engine_dry_run_env_knob(monkeypatch):
    monkeypatch.setenv("AMTPU_REMED_DRY_RUN", "1")
    collector, _ = _synthetic_collector({"a": 0.001, "b": 0.001,
                                         "c": 0.001})
    engine = RemediationEngine(collector)
    assert engine.dry_run is True


def test_engine_reconnect_action_for_stale_supervised_node():
    calls = []

    class FakeSupervisor:
        def force_reconnect(self):
            calls.append("kick")

    dead = {"alive": True}

    def flaky():
        if not dead["alive"]:
            raise OSError("gone")
        return {"sync_ops_ingested": 1.0}

    collector = FleetCollector(interval_s=0.02, min_nodes=3)
    collector.add_local("d", flaky)
    engine = RemediationEngine(
        collector, guardrails=Guardrails(cooldown_s=0.01, budget=4,
                                         window_s=10.0))
    engine.register_supervisor("d", FakeSupervisor())
    collector.scrape_once()
    dead["alive"] = False
    time.sleep(0.35)    # > the 0.3s staleness floor: the node is stale
    collector.scrape_once()
    assert calls == ["kick"]
    assert metrics.snapshot()[
        "obs_remed_actions{action=reconnect}"] == 1


def test_engine_tick_costs_bounded_and_recorded():
    collector, ticks = _synthetic_collector(
        {"a": 0.001, "b": 0.001, "c": 0.001})
    RemediationEngine(collector)
    _tick(collector, ticks, 3, sleep=0.01)
    engine = collector.remediator
    costs = engine.tick_costs()
    assert len(costs) == 3 and all(c >= 0 for c in costs)
    assert "obs_remed_tick_s_count" in metrics.snapshot()


# ---------------------------------------------------------------------------
# governor escalation ladder


def test_ladder_escalates_delay_then_shed_and_relaxes_with_hysteresis():
    gov = epochs.IngressGovernor(bound_s=1.0, sustain_s=0.0,
                                 mode="delay")
    ladder = GovernorLadder(gov, bound_s=1.0, sustain_s=1.0,
                            escalate_s=2.0, recover_frac=0.5,
                            recover_sustain_s=1.0)
    assert ladder.desired(2.0, now=0.0) == 0     # breach, not sustained
    assert ladder.desired(2.0, now=1.1) == 1     # sustained: delay
    ladder.apply(1, 2.0)
    assert gov.shedding and gov.mode == "delay"
    assert ladder.desired(2.0, now=1.2) == 1     # fresh sustain window
    assert ladder.desired(2.0, now=3.5) == 2     # sustained again: shed
    ladder.apply(2, 2.0)
    assert gov.shedding and gov.mode == "shed"
    # recovered past the bound but INSIDE the hysteresis band: hold
    assert ladder.desired(0.9, now=4.0) == 2
    assert ladder.desired(0.4, now=5.0) == 2     # below band, not held
    assert ladder.desired(0.4, now=6.1) == 1     # held long enough
    ladder.apply(1, 0.4)
    assert gov.shedding and gov.mode == "delay"
    assert ladder.desired(0.4, now=7.0) == 1
    assert ladder.desired(0.4, now=8.1) == 0
    ladder.apply(0, 0.4)
    assert not gov.shedding
    snap = metrics.snapshot()
    assert snap["obs_remed_governor_stage"] == 0
    assert snap["sync_shed_transitions"] >= 2    # on at delay, off at open


def test_ladder_no_data_never_moves():
    gov = epochs.IngressGovernor(bound_s=1.0)
    ladder = GovernorLadder(gov, bound_s=1.0)
    assert ladder.desired(None) == 0
    ladder.stage = 2
    assert ladder.desired(None) == 2


def test_engine_drives_ladder_through_guardrails():
    collector, ticks = _synthetic_collector(
        {"a": 0.001, "b": 0.001, "c": 0.001})
    slo = SloEngine(slos=[{"name": "converge_p99",
                           "signal": "converge_p99_s", "bound": 1.0}])
    collector.slo_engine = slo
    engine = RemediationEngine(
        collector, slo,
        guardrails=Guardrails(cooldown_s=0.0, budget=10, window_s=10.0))
    gov = epochs.IngressGovernor(bound_s=1.0)
    engine.attach_ladder(gov, bound_s=1.0, sustain_s=0.0,
                         escalate_s=0.0, recover_frac=0.5,
                         recover_sustain_s=0.0)

    def breach_state(p99):
        return {"rollup": {"converge_p99_s": p99}, "stragglers": [],
                "nodes": {}}

    engine.tick(breach_state(5.0))
    assert engine.ladder.stage == 1 and gov.mode == "delay"
    engine.tick(breach_state(5.0))
    assert engine.ladder.stage == 2 and gov.mode == "shed"
    engine.tick(breach_state(0.1))
    engine.tick(breach_state(0.1))
    assert engine.ladder.stage == 0 and not gov.shedding
    snap = metrics.snapshot()
    assert snap["obs_remed_actions{action=governor_escalate}"] == 2
    assert snap["obs_remed_actions{action=governor_relax}"] == 2


# ---------------------------------------------------------------------------
# relay subtree re-homing


def test_rehome_children_moves_cover_and_backfills():
    msgs = deque()
    conns = {}

    def link(ds_a, ds_b, name):
        a = Connection(ds_a, lambda m, n=name: msgs.append((n + ".b", m)),
                       wire="columnar")
        b = Connection(ds_b, lambda m, n=name: msgs.append((n + ".a", m)),
                       wire="columnar")
        conns[name + ".a"], conns[name + ".b"] = a, b
        return a, b

    def pump():
        for _ in range(100_000):
            if not msgs:
                return
            name, m = msgs.popleft()
            conns[name].receive_msg(m)
        raise AssertionError("tree failed to quiesce")

    root = DocSet()
    hubA_ds, hubB_ds = DocSet(), DocSet()
    hubA = RelayHub(hubA_ds, label="hubA")
    hubB = RelayHub(hubB_ds, label="hubB")
    _, a_up = link(root, hubA_ds, "rA")
    hubA.set_upstream(a_up)
    _, b_up = link(root, hubB_ds, "rB")
    hubB.set_upstream(b_up)
    leaves, leaf_conns, hub_sides = [], [], []
    for i in range(2):
        leaf = DocSet()
        hub_side, leaf_side = link(hubA_ds, leaf, f"Al{i}")
        hubA.attach_child(hub_side)
        leaves.append(leaf)
        leaf_conns.append(leaf_side)
        hub_sides.append(hub_side)
    leaf_conns[0].subscribe(docs=["hot"])
    leaf_conns[1].subscribe(docs=["hot", "b"])
    pump()
    for c in conns.values():
        c.open()
    pump()
    seqs: dict = {}
    _write(root, "hot", "R", seqs, 2)
    _write(root, "b", "R", seqs, 1)
    pump()
    assert leaves[0].get_doc("hot")._doc.opset.clock == {"R": 2}

    # hubA is quarantined: re-home its subtree onto hubB — the child
    # links are rebuilt (the old hub's transports die with it) and each
    # child replays its interest to the adopting hub
    old_to_idx = {c: i for i, c in enumerate(hub_sides)}
    new_leaf_sides = {}

    def rebuild(old_conn):
        i = old_to_idx[old_conn]
        old_conn.close()
        conns[f"Al{i}.b"].close()
        new_hub_side, new_leaf_side = link(hubB_ds, leaves[i], f"Bl{i}")
        new_leaf_side._local_interest = leaf_conns[i]._local_interest
        new_leaf_sides[i] = new_leaf_side
        return new_hub_side

    moved = rehome_children(hubA, hubB, rebuild)
    assert len(moved) == 2
    for c in moved:
        c.open()
    for leaf_side in new_leaf_sides.values():
        leaf_side.resubscribe()
        leaf_side.open()
    pump()
    docs, _ = hubB.cover()
    assert docs == {"hot", "b"}
    assert hubA.children() == []
    docsA, _ = hubA.cover()
    assert docsA == set()       # detach released every ref
    _write(root, "hot", "R", seqs, 2)
    pump()
    assert leaves[0].get_doc("hot")._doc.opset.clock == {"R": 4}
    assert leaves[1].get_doc("hot")._doc.opset.clock == {"R": 4}


# ---------------------------------------------------------------------------
# fleet_green + collector plumbing


def test_fleet_green_predicate():
    state = {"stragglers": [], "nodes": {
        "a": {"stale": False, "age_s": 0.1},
        "pending": {"stale": True, "age_s": None},
    }}
    green, reasons = fleet_green(state, {})
    assert green and reasons == []
    state["stragglers"] = ["a"]
    green, reasons = fleet_green(state, {})
    assert not green and reasons == ["straggler:a"]
    state["stragglers"] = []
    state["nodes"]["a"]["stale"] = True
    green, reasons = fleet_green(state, {"s": {"ok": True}})
    assert not green and reasons == ["stale:a"]
    state["nodes"]["a"].update(stale=True, quarantined=True)
    green, reasons = fleet_green(state, {"s": {"ok": False}})
    assert not green and reasons == ["slo:s"]


def test_collector_quarantine_excludes_from_rollup_and_scoring():
    collector, ticks = _synthetic_collector(
        {"a": 0.001, "b": 0.001, "c": 1.0})
    state = _tick(collector, ticks, 3)
    assert "c" in state["stragglers"]
    ops_all = state["rollup"]["ops_per_s"]
    collector.quarantine("c")
    state = _tick(collector, ticks, 1)
    assert state["stragglers"] == []
    assert state["nodes"]["c"]["quarantined"] is True
    assert state["rollup"]["ops_per_s"] < ops_all
    collector.unquarantine("c")
    state = _tick(collector, ticks, 2)
    assert "c" in state["stragglers"]


def test_collector_remove_peer_frees_label_for_reconnect():
    class FakeConn:
        peer_node = None

        def __init__(self):
            self.on_peer_metrics = None

        def request_metrics(self):
            if self.on_peer_metrics is not None:
                self.on_peer_metrics({"sync_ops_ingested": 1.0})

    collector = FleetCollector(interval_s=0.02)
    c1 = FakeConn()
    c1.peer_node = "p1"
    collector.add_peer(c1)
    collector.scrape_once()
    collector.scrape_once()
    assert "p1" in collector.nodes
    samples_before = len(collector.nodes["p1"].samples)
    collector.remove_peer(c1)
    # the reconnected transport self-reports the same label and adopts
    # the surviving NodeState — ring continuity across generations
    c2 = FakeConn()
    c2.peer_node = "p1"
    collector.add_peer(c2)
    collector.scrape_once()
    collector.scrape_once()
    assert len(collector.nodes["p1"].samples) > samples_before
    assert not any(n.startswith("peer") for n in collector.nodes)


def test_slo_on_transition_hook_fires():
    collector, ticks = _synthetic_collector(
        {"a": 0.001, "b": 0.001, "c": 0.001})
    slo = SloEngine(slos=[{"name": "ops_floor",
                           "signal": "ops_per_s", "bound": 1e9}])
    seen = []
    slo.on_transition = lambda *a: seen.append(a)
    collector.slo_engine = slo
    _tick(collector, ticks, 2)
    # ops_per_s <= 1e9 is ok; flip the bound to force a breach edge
    slo.slos[0].bound = -1.0
    _tick(collector, ticks, 1)
    assert seen and seen[-1][0] == "ops_floor" and seen[-1][1] is False


# ---------------------------------------------------------------------------
# flight-recorder dump rate-limit


def test_dump_cooldown_suppresses_same_trigger_class(tmp_path,
                                                     monkeypatch):
    monkeypatch.setenv("AMTPU_FLIGHTREC_DIR", str(tmp_path))
    monkeypatch.setattr(flightrec, "_COOLDOWN_S", 60.0)
    flightrec.reset()
    p1 = flightrec.dump("stormy:loop")
    assert p1 is not None
    p_other = flightrec.dump("calm")
    assert p_other is not None and p_other != p1
    # same class inside the cooldown: suppressed, previous path
    # returned, last_dump NOT updated, suppression counted
    p2 = flightrec.dump("stormy:loop")
    assert p2 == p1
    assert flightrec.last_dump() == p_other
    assert metrics.snapshot()[
        "obs_flightrec_suppressed{reason=stormy}"] == 1
    files = [f for f in os.listdir(tmp_path) if "stormy" in f]
    assert len(files) == 1


def test_dump_force_and_explicit_path_bypass_cooldown(tmp_path,
                                                      monkeypatch):
    monkeypatch.setenv("AMTPU_FLIGHTREC_DIR", str(tmp_path))
    monkeypatch.setattr(flightrec, "_COOLDOWN_S", 60.0)
    flightrec.reset()
    p1 = flightrec.dump("wd")
    p2 = flightrec.dump("wd", force=True)
    assert p2 is not None and p2 != p1
    p3 = flightrec.dump("wd", path=str(tmp_path / "explicit.json"))
    assert p3 == str(tmp_path / "explicit.json")
    assert os.path.exists(p3)


def test_dump_cooldown_zero_disables(tmp_path, monkeypatch):
    monkeypatch.setenv("AMTPU_FLIGHTREC_DIR", str(tmp_path))
    monkeypatch.setattr(flightrec, "_COOLDOWN_S", 0.0)
    flightrec.reset()
    p1 = flightrec.dump("wd")
    p2 = flightrec.dump("wd")
    assert p1 and p2 and p1 != p2


def test_server_prunes_dead_peers_on_reconnect():
    """Supervised reconnect churn must not leak dead _Peer objects:
    the accept loop prunes closed peers as replacements dial in."""
    ds_server, ds_client = DocSet(), DocSet()
    server = TcpSyncServer(ds_server).start()
    sup = SupervisedTcpClient(ds_client, server.host, server.port,
                              backoff_s=0.05).start()
    try:
        assert wait_until(lambda: sup.connection is not None
                          and server.peers)
        for k in range(3):
            gen = sup.generation
            next(p for p in server.peers
                 if not p.closed.is_set()).close()
            assert wait_until(lambda: sup.generation > gen)
        assert wait_until(
            lambda: sum(1 for p in server.peers
                        if not p.closed.is_set()) == 1)
        # at most the one live peer plus the most recent corpse (pruned
        # on the NEXT accept) — never one dead _Peer per reconnect
        assert len(server.peers) <= 2
    finally:
        sup.close()
        server.close()


def test_governor_force_discloses_mode_flip_while_shedding():
    """The delay -> shed escalation changes WHAT happens to appends
    (delay becomes IngressShedError) without changing the shedding
    flag — it must still fire a shed_transition disclosure."""
    gov = epochs.IngressGovernor(bound_s=1.0, mode="delay")
    gov.force(True, mode="delay", p99_s=3.0)
    t1 = metrics.snapshot().get("sync_shed_transitions", 0)
    assert t1 == 1
    gov.force(True, mode="shed", p99_s=3.0)      # severity flip
    assert metrics.snapshot()["sync_shed_transitions"] == t1 + 1
    evs = [e for e in flightrec.events()
           if e["kind"] == "shed_transition"]
    assert evs[-1]["mode"] == "shed" and evs[-1]["shedding"] is True
    gov.force(True, mode="shed", p99_s=3.0)      # no-op: no disclosure
    assert metrics.snapshot()["sync_shed_transitions"] == t1 + 1
    gov.force(False, p99_s=0.2)
    assert metrics.snapshot()["sync_shed_transitions"] == t1 + 2
    assert not gov.shedding


def test_divergence_dump_bypasses_cooldown(tmp_path, monkeypatch):
    """Two distinct divergences inside one dump-cooldown window must
    BOTH persist — sync/audit.py forces its dumps past the rate limit."""
    monkeypatch.setenv("AMTPU_FLIGHTREC_DIR", str(tmp_path))
    monkeypatch.setattr(flightrec, "_COOLDOWN_S", 60.0)
    flightrec.reset()
    from automerge_tpu.sync import audit as audit_mod
    # the audit module's dump call, driven directly with two reports
    p1 = flightrec.dump("divergence", extra={"divergence": {"doc": "a"}},
                        force=True)
    p2 = flightrec.dump("divergence", extra={"divergence": {"doc": "b"}},
                        force=True)
    assert p1 and p2 and p1 != p2
    assert json.load(open(p2))["extra"]["divergence"]["doc"] == "b"
    # and the audit source really does pass force=True
    import inspect
    src = inspect.getsource(audit_mod)
    assert 'force=True' in src
