"""Sync protocol: multi-node simulation without any network.

Ports the strategy of /root/reference/test/connection_test.js: several DocSets
wired pairwise with message-capturing callbacks; tests script delivery order
(including drops and duplicates) and assert convergence plus message counts.
"""

import automerge_tpu as am
from automerge_tpu import Connection, DocSet


class Link:
    """A bidirectional link between two nodes with manual message delivery."""

    def __init__(self, docset_a: DocSet, docset_b: DocSet):
        self.queue_ab: list[dict] = []   # messages from a towards b
        self.queue_ba: list[dict] = []
        self.conn_a = Connection(docset_a, self.queue_ab.append)
        self.conn_b = Connection(docset_b, self.queue_ba.append)
        self.sent_ab = 0
        self.sent_ba = 0

    def open(self):
        self.conn_a.open()
        self.conn_b.open()

    def deliver_one_ab(self, drop=False):
        msg = self.queue_ab.pop(0)
        self.sent_ab += 1
        if not drop:
            self.conn_b.receive_msg(msg)
        return msg

    def deliver_one_ba(self, drop=False):
        msg = self.queue_ba.pop(0)
        self.sent_ba += 1
        if not drop:
            self.conn_a.receive_msg(msg)
        return msg

    def drain(self, max_rounds=100):
        for _ in range(max_rounds):
            if not self.queue_ab and not self.queue_ba:
                return
            while self.queue_ab:
                self.deliver_one_ab()
            while self.queue_ba:
                self.deliver_one_ba()
        raise AssertionError("message exchange did not quiesce")


def test_advertise_and_send_on_connect():
    # node A has a doc; B connects; B requests it; A sends changes
    ds_a, ds_b = DocSet(), DocSet()
    doc = am.change(am.init(), lambda d: d.__setitem__("hello", "world"))
    ds_a.set_doc("doc1", doc)
    link = Link(ds_a, ds_b)
    link.open()
    # A advertises its clock on open
    assert len(link.queue_ab) == 1
    assert link.queue_ab[0]["docId"] == "doc1"
    assert "changes" not in link.queue_ab[0]
    link.drain()
    assert ds_b.get_doc("doc1") == {"hello": "world"}


def test_local_edit_pushes_changes():
    ds_a, ds_b = DocSet(), DocSet()
    ds_a.set_doc("doc1", am.init())
    ds_b.set_doc("doc1", am.init())
    link = Link(ds_a, ds_b)
    link.open()
    link.drain()

    doc = am.change(ds_a.get_doc("doc1"), lambda d: d.__setitem__("x", 1))
    ds_a.set_doc("doc1", doc)
    # the handler fires and the changes go out
    assert any("changes" in m for m in link.queue_ab)
    link.drain()
    assert ds_b.get_doc("doc1") == {"x": 1}


def test_bidirectional_divergent_merge():
    ds_a, ds_b = DocSet(), DocSet()
    base = am.change(am.init("base"), lambda d: d.__setitem__("base", 0))
    ds_a.set_doc("doc1", am.merge(am.init("A"), base))
    ds_b.set_doc("doc1", am.merge(am.init("B"), base))
    link = Link(ds_a, ds_b)
    link.open()
    link.drain()

    ds_a.set_doc("doc1", am.change(ds_a.get_doc("doc1"), lambda d: d.__setitem__("a", 1)))
    ds_b.set_doc("doc1", am.change(ds_b.get_doc("doc1"), lambda d: d.__setitem__("b", 2)))
    link.drain()
    assert ds_a.get_doc("doc1") == {"base": 0, "a": 1, "b": 2}
    assert ds_b.get_doc("doc1") == {"base": 0, "a": 1, "b": 2}


def test_forwarding_through_intermediate_node():
    # connection_test.js:219-251: A -- M -- B; A's edit reaches B via M's gossip
    ds_a, ds_m, ds_b = DocSet(), DocSet(), DocSet()
    for ds in (ds_a, ds_m, ds_b):
        ds.set_doc("doc1", am.init())
    link_am = Link(ds_a, ds_m)
    link_mb = Link(ds_m, ds_b)
    link_am.open()
    link_mb.open()
    for _ in range(10):
        link_am.drain()
        link_mb.drain()
        if not (link_am.queue_ab or link_am.queue_ba or
                link_mb.queue_ab or link_mb.queue_ba):
            break

    ds_a.set_doc("doc1", am.change(ds_a.get_doc("doc1"), lambda d: d.__setitem__("x", 42)))
    for _ in range(10):
        link_am.drain()
        link_mb.drain()
        if not (link_am.queue_ab or link_am.queue_ba or
                link_mb.queue_ab or link_mb.queue_ba):
            break
    assert ds_b.get_doc("doc1") == {"x": 42}


def test_duplicate_delivery_tolerated():
    ds_a, ds_b = DocSet(), DocSet()
    ds_a.set_doc("doc1", am.init())
    ds_b.set_doc("doc1", am.init())
    link = Link(ds_a, ds_b)
    link.open()
    link.drain()

    ds_a.set_doc("doc1", am.change(ds_a.get_doc("doc1"), lambda d: d.__setitem__("x", 1)))
    # capture and deliver the change message twice
    msg = link.queue_ab[0]
    link.drain()
    link.conn_b.receive_msg(msg)  # duplicate
    link.drain()
    assert ds_b.get_doc("doc1") == {"x": 1}
    assert len(am.get_history(ds_b.get_doc("doc1"))) == 1


def test_dropped_message_recovered_by_reconnection():
    ds_a, ds_b = DocSet(), DocSet()
    ds_a.set_doc("doc1", am.init())
    ds_b.set_doc("doc1", am.init())
    link = Link(ds_a, ds_b)
    link.open()
    link.drain()

    ds_a.set_doc("doc1", am.change(ds_a.get_doc("doc1"), lambda d: d.__setitem__("x", 1)))
    # the change message is dropped in transit
    link.deliver_one_ab(drop=True)
    link.drain()
    assert ds_b.get_doc("doc1") == {}

    # a fresh connection (reconnect) re-advertises and catches up
    link2 = Link(ds_a, ds_b)
    link2.open()
    link2.drain()
    assert ds_b.get_doc("doc1") == {"x": 1}


def test_unknown_doc_requested():
    # B receives an advertisement for a doc it doesn't have and asks for it
    ds_a, ds_b = DocSet(), DocSet()
    doc = am.change(am.init(), lambda d: d.__setitem__("v", 7))
    ds_a.set_doc("doc9", doc)
    link = Link(ds_a, ds_b)
    link.open()
    advert = link.deliver_one_ab()
    assert "changes" not in advert
    # B's reply is a request with an empty clock
    request = link.queue_ba[0]
    assert request["docId"] == "doc9"
    assert request["clock"] == {}
    link.drain()
    assert ds_b.get_doc("doc9") == {"v": 7}


def test_no_infinite_chatter():
    # after convergence, no further messages are exchanged
    ds_a, ds_b = DocSet(), DocSet()
    ds_a.set_doc("doc1", am.init())
    ds_b.set_doc("doc1", am.init())
    link = Link(ds_a, ds_b)
    link.open()
    link.drain()
    before = (link.sent_ab, link.sent_ba)
    link.drain()
    assert (link.sent_ab, link.sent_ba) == before


def test_multiplexes_many_docs():
    ds_a, ds_b = DocSet(), DocSet()
    for i in range(5):
        doc = am.change(am.init(), lambda d, i=i: d.__setitem__("n", i))
        ds_a.set_doc(f"doc{i}", doc)
    link = Link(ds_a, ds_b)
    link.open()
    link.drain()
    for i in range(5):
        assert ds_b.get_doc(f"doc{i}") == {"n": i}
