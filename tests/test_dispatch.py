"""Adaptive backend routing (engine/dispatch.py): workloads below the
host<->device link's fixed-cost floor run on the host path; DocSet-scale
batches go to the device. The reference has no such choice to make (one
JS path); for this framework the router IS the product path."""

import automerge_tpu as am
from automerge_tpu.engine.dispatch import (Plan, apply_batch_adaptive,
                                           apply_host, plan_batch)
from automerge_tpu.frontend.materialize import apply_changes_to_doc


def _trace_small():
    d = am.change(am.init("A"), lambda x: am.assign(x, {"n": 1, "xs": [1, 2]}))
    d = am.change(d, lambda x: x["xs"].insert_at(1, 9))
    return d._doc.opset.get_missing_changes({})


def _trace_bulk(n=200):
    d = am.change(am.init("A"), lambda x: x.__setitem__("xs", []))
    for i in range(n):
        d = am.change(d, lambda x, i=i: x["xs"].insert_at(len(x["xs"]), i))
    return d._doc.opset.get_missing_changes({})


def test_plan_small_single_doc_routes_host():
    p = plan_batch(n_docs=1, n_ops=200, wire_bytes=120 * 128 * 4)
    assert p.backend == "host"
    assert p.est_host_s < p.est_device_s


def test_plan_docset_batch_routes_device():
    p = plan_batch(n_docs=10_000, n_ops=80_000, wire_bytes=5_000_000,
                   passes=10)
    assert p.backend == "device"


def test_apply_host_interpretive_parity():
    changes = _trace_small()
    got = apply_host(changes)
    doc = am.init("oracle")
    want = apply_changes_to_doc(doc, doc._doc.opset, changes,
                                incremental=False)
    assert am.equals(got, want)


def test_apply_host_bulk_parity():
    changes = _trace_bulk()
    got = apply_host(changes)  # bulk build engages at this size
    doc = am.init("oracle")
    want = apply_changes_to_doc(doc, doc._doc.opset, changes,
                                incremental=False)
    assert am.equals(got, want)
    assert am.save(got) == am.save(want)


def test_adaptive_small_batch_returns_host_docs():
    doc_changes = [_trace_small(), _trace_bulk(80)]
    plan, result = apply_batch_adaptive(doc_changes)
    assert isinstance(plan, Plan) and plan.backend == "host"
    assert len(result) == 2
    for chs, got in zip(doc_changes, result):
        doc = am.init("oracle")
        want = apply_changes_to_doc(doc, doc._doc.opset, chs,
                                    incremental=False)
        assert am.equals(got, want)


def test_calibrate_from_profile_partial_and_full():
    from automerge_tpu.engine import dispatch as dp

    before = dict(dp._LINK)
    try:
        applied = dp.calibrate_from_profile({
            "h2d_ms_by_mb": {"0.001": 12.0, "1": 14.0, "20": 52.0},
            "d2h_512B_ms": 70.0,
            "tiny_dispatch_plus_readback_ms": 95.0,
        })
        assert applied["h2d_call_s"] == 0.012
        assert abs(applied["h2d_bytes_per_s"] - 19e6 / 0.038) < 1e3
        assert applied["d2h_call_s"] == 0.07
        assert abs(applied["dispatch_fixed_s"] - 0.025) < 1e-9
        for k, v in applied.items():
            assert dp._LINK[k] == v
        # partial profile only touches what it has
        applied2 = dp.calibrate_from_profile({"d2h_512B_ms": 10.0})
        assert set(applied2) == {"d2h_call_s"}
    finally:
        dp.calibrate(**before)


def _trace_concurrent(n_per_actor=100):
    """Merged multi-actor doc: get_missing_changes emits per-actor runs
    whose deps cross runs — NOT causal application order."""
    a = am.change(am.init("A"), lambda x: x.__setitem__("xs", []))
    b = am.merge(am.init("B"), a)
    c = am.merge(am.init("C"), a)
    for i in range(n_per_actor):
        a = am.change(a, lambda x, i=i: x.__setitem__(f"a{i % 9}", i))
        b = am.change(b, lambda x, i=i: x["xs"].insert_at(0, i))
        c = am.change(c, lambda x, i=i: x.__setitem__(f"c{i % 9}", -i))
    m = am.merge(am.merge(a, b), c)
    return m._doc.opset.get_missing_changes({})


def test_causal_order_passthrough_and_reorder():
    from automerge_tpu.engine.dispatch import _causal_order

    linear = _trace_bulk(20)
    assert _causal_order(linear) is linear  # already causal: no copy

    # force a non-causal permutation: per-actor runs with the dependent
    # actors' runs FIRST (their deps point at changes that come later)
    conc = _trace_concurrent(10)
    shuffled = sorted(conc, key=lambda c: (c.actor != "C", c.actor != "B",
                                           c.seq))
    assert _causal_order(shuffled) is not shuffled  # really non-causal
    ordered = _causal_order(shuffled)
    assert ordered is not None
    assert sorted((c.actor, c.seq) for c in ordered) \
        == sorted((c.actor, c.seq) for c in shuffled)
    clock = {}
    for c in ordered:
        assert c.seq == clock.get(c.actor, 0) + 1
        assert all(clock.get(a, 0) >= s for a, s in c.deps.items())
        clock[c.actor] = c.seq

    # an incomplete log has no causal order -> interpretive semantics
    assert _causal_order(shuffled[1:]) is None


def test_apply_host_bulk_engages_on_concurrent_log(monkeypatch):
    """The r3 bench's config-3 routing tax: a merged multi-actor log used
    to pay a failed bulk attempt (causal-order bail) and fall back. After
    the stable reorder, bulk must ENGAGE and match the interpretive result
    exactly. (The threshold is lowered for the test: the r5 no-diff
    interpretive mode pushed the real crossover to tens of thousands of
    changes; this pins the engagement MECHANISM, not the constant.)"""
    from automerge_tpu.engine import dispatch as _dispatch
    monkeypatch.setattr(_dispatch, "HOST_BULK_MIN_CHANGES", 256)
    changes = _trace_concurrent()
    assert len(changes) >= 256
    am.metrics.reset()
    got = apply_host(changes)
    doc = am.init("oracle")
    want = apply_changes_to_doc(doc, doc._doc.opset, changes,
                                incremental=False)
    assert am.equals(got, want)
    snap = am.metrics.snapshot()
    assert snap.get("core_bulk_fallbacks", 0) == 0
    # positive signal: the bulk path really built (not interpretive)
    assert snap.get("engine_bulk_built", 0) == 1, snap


def test_causal_order_property_random_shuffles():
    """Property: for ANY permutation of a complete change log, _causal_order
    returns a valid causal order containing exactly the same changes; for
    any log with a change removed, it returns None."""
    import random as _random

    from automerge_tpu.engine.dispatch import _causal_order

    rng = _random.Random(123)
    conc = _trace_concurrent(8)
    for trial in range(25):
        shuffled = list(conc)
        rng.shuffle(shuffled)
        ordered = _causal_order(shuffled)
        assert ordered is not None
        assert sorted((c.actor, c.seq) for c in ordered) \
            == sorted((c.actor, c.seq) for c in conc)
        clock = {}
        for c in ordered:
            assert c.seq == clock.get(c.actor, 0) + 1
            assert all(clock.get(a, 0) >= s for a, s in c.deps.items())
            clock[c.actor] = c.seq
        # drop one random change: no causal order may exist for the rest
        # of that actor's chain (and usually for cross-actor dependents)
        k = rng.randrange(len(shuffled))
        broken = shuffled[:k] + shuffled[k + 1:]
        got = _causal_order(broken)
        if got is not None:
            # legal only if nothing depended on the dropped change and it
            # was the tail of its actor chain
            dropped = shuffled[k]
            assert all(c.actor != dropped.actor or c.seq < dropped.seq
                       for c in broken)
            assert all(c.deps.get(dropped.actor, 0) < dropped.seq
                       for c in broken)
