"""Test environment: force the CPU backend with 8 virtual devices so the
multi-device sharding path is exercised without TPU hardware (the strategy the
reference uses for its distributed tests is in-process simulation; ours adds a
virtual device mesh — SURVEY.md §4)."""

import os

# Must run before jax creates a backend. Force the CPU platform with 8
# virtual devices so the mesh-sharding paths are exercised deterministically
# and offline. (The environment presets JAX_PLATFORMS to the TPU tunnel and
# its plugin wins over the env var, so the config API is used instead.)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_uuid_factory():
    yield
    import automerge_tpu as am
    am.uuid.reset()
