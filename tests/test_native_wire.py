"""Native C++ wire codec: parity with the pure-Python wire path."""

import json

import pytest

import automerge_tpu as am
from automerge_tpu.core.change import coerce_change

native = pytest.importorskip("automerge_tpu.native")
if not native.native_available():
    pytest.skip(f"native codec unavailable: {native.native_error()}",
                allow_module_level=True)

from automerge_tpu.native.wire import parse_changes_json  # noqa: E402


def wire_of(doc):
    return json.dumps(am.get_changes(am.init(), doc))


def assert_wire_parity(doc):
    wire = wire_of(doc)
    native_changes = parse_changes_json(wire).to_changes()
    py_changes = [coerce_change(c) for c in json.loads(wire)]
    assert native_changes == py_changes


class TestNativeWireCodec:
    def test_scalars(self):
        s = am.change(am.init("a"), lambda d: am.assign(d, {
            "s": "str", "i": 42, "neg": -17, "f": 3.25, "t": True,
            "fl": False, "n": None, "zero": 0, "big": 2**40}))
        assert_wire_parity(s)

    def test_unicode_and_escapes(self):
        s = am.change(am.init("actor-ü"), 'msg "q" \\ ☃',
                      lambda d: d.__setitem__("k", "héllo\n\t☃ \"x\" 𝄞"))
        assert_wire_parity(s)

    def test_nested_structures(self):
        s = am.change(am.init("a"), lambda d: d.__setitem__(
            "board", {"cards": [{"t": "one"}, "plain", 7]}))
        assert_wire_parity(s)

    def test_text_ops(self):
        def edit(doc):
            doc["t"] = am.Text()
            doc["t"].insert_at(0, *"hey")
        s = am.change(am.init("a"), edit)
        s = am.change(s, lambda d: d["t"].delete_at(1))
        assert_wire_parity(s)

    def test_multi_actor_deps(self):
        s1 = am.change(am.init("A"), lambda d: d.__setitem__("a", 1))
        s2 = am.merge(am.init("B"), s1)
        s2 = am.change(s2, lambda d: d.__setitem__("b", 2))
        s1 = am.merge(s1, s2)
        s1 = am.change(s1, lambda d: d.__setitem__("c", 3))
        assert_wire_parity(s1)

    def test_malformed_raises(self):
        with pytest.raises(ValueError):
            parse_changes_json('[{"actor": "a", "seq": }]')
        with pytest.raises(ValueError):
            parse_changes_json('{"not": "an array"}')
        with pytest.raises(ValueError):
            parse_changes_json('[{"actor": "a"}]')  # missing seq/ops

    def test_public_changes_from_json(self):
        s = am.change(am.init("a"), lambda d: d.__setitem__("x", 1))
        wire = wire_of(s)
        changes = am.changes_from_json(wire)
        target = am.apply_changes(am.init(), changes)
        assert target == {"x": 1}

    def test_round_trip_through_document(self):
        s = am.change(am.init("A"), lambda d: am.assign(d, {
            "xs": [1, 2, 3], "meta": {"deep": {"er": "value"}}}))
        s = am.change(s, lambda d: d["xs"].delete_at(1))
        changes = parse_changes_json(wire_of(s)).to_changes()
        rebuilt = am.apply_changes(am.init(), changes)
        assert am.equals(rebuilt, s)


class TestReviewRegressions:
    def test_large_seq_rejected_not_truncated(self):
        with pytest.raises(ValueError):
            parse_changes_json(
                '[{"actor":"a","seq":1099511627776,"deps":{},"ops":[]}]')

    def test_bigint_value_preserved(self):
        wire = json.dumps([{"actor": "a", "seq": 1, "deps": {},
                            "ops": [{"action": "set", "obj": am.ROOT_ID,
                                     "key": "big", "value": 2**70}]}])
        native_changes = parse_changes_json(wire).to_changes()
        py_changes = [coerce_change(c) for c in json.loads(wire)]
        assert native_changes == py_changes
        assert native_changes[0].ops[0].value == 2**70

    def test_unknown_fields_ignored(self):
        wire = json.dumps([{"actor": "a", "seq": 1, "deps": {}, "time": 123,
                            "ops": [{"action": "set", "obj": am.ROOT_ID,
                                     "key": "x", "value": 1, "extra": [1, {"a": 2}]}]}])
        native_changes = parse_changes_json(wire).to_changes()
        py_changes = [coerce_change(c) for c in json.loads(wire)]
        assert native_changes == py_changes

    def test_missing_ops_means_empty(self):
        wire = '[{"actor":"a","seq":1,"deps":{}}]'
        changes = parse_changes_json(wire).to_changes()
        assert changes[0].ops == ()

    def test_lone_surrogate_round_trips(self):
        wire = json.dumps([{"actor": "a", "seq": 1, "deps": {},
                            "ops": [{"action": "set", "obj": am.ROOT_ID,
                                     "key": "s", "value": "x\ud800y"}]}])
        native_changes = parse_changes_json(wire).to_changes()
        py_changes = [coerce_change(c) for c in json.loads(wire)]
        assert native_changes == py_changes


class TestConcatColumns:
    def test_remaps_tables_and_preserves_value_types(self):
        from automerge_tpu.core.change import Change, Op
        from automerge_tpu.core.ids import ROOT_ID
        from automerge_tpu.native.wire import (changes_to_columns,
                                               concat_columns)

        a = changes_to_columns([Change("X", 1, {}, (
            Op("set", ROOT_ID, key="k", value=1.5),
            Op("set", ROOT_ID, key="big", value=2**70),
        ), "msg-a")])
        b = changes_to_columns([Change("Y", 1, {"X": 1}, (
            Op("set", ROOT_ID, key="k", value=True),
            Op("set", ROOT_ID, key="s", value="str"),
        ))])
        m = concat_columns([a, b])
        chs = m.to_changes()
        assert [c.actor for c in chs] == ["X", "Y"]
        assert chs[0].message == "msg-a" and chs[1].message is None
        assert chs[1].deps == {"X": 1}
        vals = [op.value for c in chs for op in c.ops]
        assert vals == [1.5, 2**70, True, "str"]
        # shared strings interned once across parts
        assert m.objects.count(ROOT_ID) == 1
        assert m.keys.count("k") == 1

    def test_single_part_passthrough(self):
        from automerge_tpu.core.change import Change, Op
        from automerge_tpu.core.ids import ROOT_ID
        from automerge_tpu.native.wire import (changes_to_columns,
                                               concat_columns)

        a = changes_to_columns([Change("X", 1, {}, (
            Op("set", ROOT_ID, key="k", value=1),))])
        assert concat_columns([a]) is a

    def test_small_and_numpy_paths_agree_column_for_column(self):
        """concat_columns routes rounds <= _SMALL_CONCAT_OPS through the
        pure-python merge and everything larger through the numpy
        remap/union path. Both must produce IDENTICAL columns (values,
        dtypes, string tables) for the same parts — this pins the numpy
        path (every production-size coalesced round) against the small
        path the other concat tests exercise."""
        import numpy as np

        import automerge_tpu.native.wire as wire
        from automerge_tpu.core.change import Change, Op
        from automerge_tpu.core.ids import ROOT_ID

        parts = []
        for w in range(4):
            chs = []
            for s in range(1, 4):
                chs.append(Change(
                    f"actor{w}", s, {f"actor{(w + 1) % 4}": 1} if s > 1
                    else {},
                    tuple(Op("set", ROOT_ID, key=f"k{(w + i) % 5}",
                             value=v)
                          for i, v in enumerate(
                              (s, 1.5 * w, f"s{w % 2}", True, None))),
                    f"m{w}" if s == 1 else None))
            parts.append(wire.changes_to_columns(chs))
        assert sum(len(p.op_action) for p in parts) <= wire._SMALL_CONCAT_OPS

        small = wire._concat_columns_small(parts)
        # force the numpy branch on the SAME parts
        orig = wire._SMALL_CONCAT_OPS
        wire._SMALL_CONCAT_OPS = 0
        try:
            big = wire.concat_columns(parts)
        finally:
            wire._SMALL_CONCAT_OPS = orig
        assert small is not big
        for f in ("change_actor", "change_seq", "change_msg", "deps_off",
                  "deps_actor", "deps_seq", "op_off", "op_action",
                  "op_obj", "op_key", "op_elem", "op_vtag", "op_vint",
                  "op_vdbl", "op_vstr"):
            s_col, b_col = getattr(small, f), getattr(big, f)
            assert np.asarray(s_col).dtype == np.asarray(b_col).dtype, f
            assert np.array_equal(np.asarray(s_col), np.asarray(b_col)), f
        for f in ("actors", "objects", "keys", "messages", "strings"):
            assert list(getattr(small, f)) == list(getattr(big, f)), f
        assert small.to_changes() == big.to_changes()
