"""Op-lifecycle / convergence-lag plane (utils/oplag.py): sampling rate
honored, zero-overhead off switch, full lineage across a real TCP pair,
causal-queue stage, and snapshot/percentile surfaces."""

import time

import pytest

from automerge_tpu.core.change import Change, Op
from automerge_tpu.core.ids import ROOT_ID
from automerge_tpu.utils import flightrec, metrics, oplag


def wait_until(predicate, timeout=15.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


@pytest.fixture(autouse=True)
def _clean():
    metrics.reset()
    flightrec.reset()
    oplag.set_sample_rate(None)   # env-resolved default
    yield
    metrics.reset()
    flightrec.reset()
    oplag.set_sample_rate(None)


def _change(actor="X", seq=1, deps=None, key="k", value=1):
    return Change(actor=actor, seq=seq, deps=deps or {}, ops=[
        Op("set", ROOT_ID, key=key, value=value)])


def test_sampling_rate_honored():
    oplag.set_sample_rate(3)
    toks = [oplag.admit(f"d{i}") for i in range(9)]
    sampled = [t for t in toks if t is not None]
    assert len(sampled) == 3
    assert metrics.snapshot()["sync_ops_sampled"] == 3
    # every sampled op left an admit breadcrumb with its provenance id
    admits = [e for e in flightrec.events() if e["kind"] == "oplag_admit"]
    assert {e["id"] for e in admits} == {t.id for t in sampled}


def test_sampling_off_is_inert():
    oplag.set_sample_rate(0)
    before = metrics.snapshot()
    assert oplag.admit("d0") is None
    assert oplag.wire_header("d0") is None
    oplag.queue_park("A", 1)
    oplag.queue_admitted("A", 1)
    assert oplag.lag_snapshot() is None
    after = metrics.snapshot()
    assert before == after          # zero metric mutations
    assert not [e for e in flightrec.events()
                if e["kind"].startswith("oplag")]


def test_rows_service_ingress_records_flush_stages():
    oplag.set_sample_rate(1)
    from automerge_tpu.sync.service import EngineDocSet
    svc = EngineDocSet(backend="rows")
    svc.apply_changes("d1", [_change()])
    snap = metrics.snapshot()
    for stage in ("queue_wait", "flush", "origin_total"):
        assert snap[f"sync_op_lag_s{{stage={stage}}}_count"] >= 1, stage
    stages = snap["oplag"]["stages"]
    assert stages["origin_total"]["p50_s"] >= 0.0
    assert snap["oplag"]["sample_rate"] == 1
    # lineage breadcrumbs carry the provenance id through the stages
    evs = [e for e in flightrec.events() if e["kind"] == "oplag_stage"]
    admit = [e for e in flightrec.events() if e["kind"] == "oplag_admit"]
    assert admit and any(e["id"] == admit[0]["id"] for e in evs)


def test_full_lineage_across_real_tcp_pair():
    oplag.set_sample_rate(1)
    from automerge_tpu.sync.service import EngineDocSet
    from automerge_tpu.sync.tcp import TcpSyncClient, TcpSyncServer
    a = EngineDocSet(backend="rows")
    b = EngineDocSet(backend="rows")
    server = TcpSyncServer(a, wire="columnar").start()
    client = TcpSyncClient(b, server.host, server.port,
                           wire="columnar").start()
    try:
        b.apply_changes("d1", [_change()])
        assert wait_until(
            lambda: "d1" in a.doc_ids
            and a.clock_of("d1").get("X") == 1)
        # wire/peer_apply/converge are recorded by the RECEIVING side as
        # the apply completes; both sides share this process's store
        assert wait_until(lambda: "converge" in
                          ((metrics.snapshot().get("oplag") or {})
                           .get("stages", {})))
    finally:
        client.close()
        server.close()
    snap = metrics.snapshot()
    stages = snap["oplag"]["stages"]
    for stage in ("queue_wait", "flush", "origin_total", "wire",
                  "peer_apply", "converge"):
        assert stage in stages, stage
        assert snap[f"sync_op_lag_s{{stage={stage}}}_count"] >= 1
    # end-to-end lag >= its wire component (same-host clocks)
    assert stages["converge"]["max_s"] >= 0.0
    # the percentile gauges refreshed for the converge stage
    assert "sync_op_lag_p50_s{stage=converge}" in snap
    assert "sync_op_lag_p99_s{stage=converge}" in snap


def test_wire_header_roundtrip_and_malformed_tolerated():
    oplag.set_sample_rate(1)
    tok = oplag.admit("doc-w")
    assert tok is not None
    oplag.flushed(tok, flush_start=tok.t0, flush_s=0.001)
    hdr = oplag.wire_header("doc-w")
    assert hdr is not None and hdr.split(",")[0] == tok.id
    assert oplag.wire_header("other-doc") is None
    ctx = oplag.wire_receive(hdr)
    assert ctx is not None and ctx[0] == tok.id
    oplag.peer_applied(ctx)
    stages = metrics.snapshot()["oplag"]["stages"]
    assert "wire" in stages and "converge" in stages
    # malformed / absent headers never raise and record nothing
    assert oplag.wire_receive(None) is None
    assert oplag.wire_receive("not-a-header") is None
    assert oplag.wire_receive(12) is None
    oplag.peer_applied(None)


def test_stale_token_retired_by_later_flush_of_same_doc():
    """A later round of the same doc must retire the awaiting-wire token
    (re-shipping it would record an ever-growing bogus converge lag)."""
    oplag.set_sample_rate(1)
    tok = oplag.admit("doc-s")
    oplag.flushed(tok, flush_start=tok.t0, flush_s=0.001)
    assert oplag.wire_header("doc-s") is not None
    # an UNSAMPLED later flush touching the doc retires the stale token
    oplag.flush_boundary(frozenset({"doc-s", "other"}))
    assert oplag.wire_header("doc-s") is None


def test_stale_token_retired_by_ttl(monkeypatch):
    oplag.set_sample_rate(1)
    tok = oplag.admit("doc-t")
    oplag.flushed(tok, flush_start=tok.t0, flush_s=0.001)
    assert oplag.wire_header("doc-t") is not None
    monkeypatch.setattr(oplag, "WIRE_TTL_S", 0.0)
    time.sleep(0.01)
    assert oplag.wire_header("doc-t") is None


def test_service_reflush_of_doc_stops_reshipping_header():
    """End-to-end: after a second (unsampled) ingress of the same doc
    flushes, Connection.send_msg no longer attaches the first op's
    header to the new change's messages."""
    from automerge_tpu.sync.service import EngineDocSet
    oplag.set_sample_rate(1)
    svc = EngineDocSet(backend="rows")
    svc.apply_changes("d1", [_change(seq=1)])
    assert oplag.wire_header("d1") is not None      # fresh sampled op
    oplag.set_sample_rate(10**9)                    # next ingress unsampled
    svc.apply_changes("d1", [_change(seq=2, value=2)])
    assert oplag.wire_header("d1") is None          # stale token retired


def test_causal_queue_stage_via_opset():
    oplag.set_sample_rate(1)
    from automerge_tpu.core.opset import OpSet
    opset = OpSet.init()
    # seq 2 arrives before seq 1: parks causally-unready
    c2 = _change(seq=2, value=2)
    opset, _ = opset.add_changes([c2])
    assert len(opset.queue) == 1
    time.sleep(0.05)
    opset, _ = opset.add_changes([_change(seq=1, value=1)])
    assert not opset.queue
    snap = metrics.snapshot()
    assert snap["sync_op_lag_s{stage=causal_queue}_count"] == 1
    assert snap["sync_op_lag_s{stage=causal_queue}_max"] >= 0.04


def test_percentiles_and_reset():
    oplag.set_sample_rate(1)
    for i in range(100):
        oplag.record_stage("op", "flush", i / 1000.0)
    lag = oplag.lag_snapshot()
    st = lag["stages"]["flush"]
    assert st["count"] == 100
    assert st["p50_s"] == pytest.approx(0.049, abs=0.003)
    assert st["p99_s"] == pytest.approx(0.099, abs=0.003)
    assert st["max_s"] == pytest.approx(0.099, abs=1e-6)
    metrics.reset()                 # cascades into oplag.reset()
    assert oplag.lag_snapshot() is None


def test_unsampled_ingress_leaves_no_series():
    oplag.set_sample_rate(0)
    from automerge_tpu.sync.service import EngineDocSet
    svc = EngineDocSet(backend="rows")
    svc.apply_changes("d1", [_change()])
    snap = metrics.snapshot()
    assert "oplag" not in snap
    assert not any(k.startswith("sync_op_lag_s") for k in snap)
    assert "sync_ops_sampled" not in snap
