"""Thread hygiene for the observability plane: the shared watchdog
checker exits when idle (instead of parking forever), and the
convergence-audit thread is stopped AND joined by stop() — no
`amtpu-*` background thread may leak across tests/services."""

import threading
import time

from automerge_tpu import metrics
from automerge_tpu.sync.audit import ConvergenceAuditor
from automerge_tpu.sync.connection import Connection
from automerge_tpu.sync.service import EngineDocSet


def wait_until(predicate, timeout=5.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def _obs_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith(("amtpu-watchdog", "amtpu-auditor"))]


def test_watchdog_thread_exits_when_idle(monkeypatch):
    monkeypatch.setattr(metrics._monitor, "linger_s", 0.05)
    with metrics.watchdog("sync_hashes_fanout", budget_s=30.0):
        t = metrics._monitor.thread()
        assert t is not None and t.is_alive()
    # past the linger window the checker thread exits and deregisters
    assert wait_until(lambda: metrics._monitor.thread() is None)
    t.join(timeout=5.0)
    assert not t.is_alive()
    # ...and a later watchdogged region respawns a fresh checker
    with metrics.watchdog("sync_hashes_fanout", budget_s=30.0):
        t2 = metrics._monitor.thread()
        assert t2 is not None and t2.is_alive() and t2 is not t
    assert wait_until(lambda: metrics._monitor.thread() is None)


def test_watchdog_respawn_still_fires(monkeypatch):
    """The exit/respawn cycle must not lose fires: a watchdog armed after
    the checker died still produces its diagnosis."""
    monkeypatch.setattr(metrics._monitor, "linger_s", 0.02)
    metrics.reset()
    with metrics.watchdog("sync_hashes_fanout", budget_s=30.0):
        pass
    assert wait_until(lambda: metrics._monitor.thread() is None)
    with metrics.watchdog("sync_hashes_fanout", budget_s=0.05):
        time.sleep(0.2)
    assert metrics.snapshot().get(
        "obs_watchdog_fired{name=sync_hashes_fanout}") == 1


def test_auditor_stop_joins_thread():
    svc = EngineDocSet(backend="rows")
    conn = Connection(svc, lambda m: None, wire="columnar")
    aud = ConvergenceAuditor(svc, conn, period_s=0.05).start()
    assert wait_until(lambda: any(
        t.name == "amtpu-auditor" for t in threading.enumerate()))
    thread = aud._thread
    aud.stop()
    assert aud._thread is None
    assert not thread.is_alive()
    aud.stop()   # idempotent
    assert not any(t.name == "amtpu-auditor" for t in threading.enumerate())


def test_no_observability_threads_leak_between_tests(monkeypatch):
    """The meta-assertion the satellite asks for: after watchdogged and
    audited work completes, no observability thread stays behind."""
    monkeypatch.setattr(metrics._monitor, "linger_s", 0.05)
    svc = EngineDocSet(backend="rows")
    conn = Connection(svc, lambda m: None, wire="columnar")
    aud = ConvergenceAuditor(svc, conn, period_s=10.0).start()
    with metrics.watchdog("sync_hashes_fanout", budget_s=30.0):
        pass
    aud.stop()
    assert wait_until(lambda: not _obs_threads()), _obs_threads()
