"""Regression pins for two driver-side hygiene fixes (ADVICE.md lows #3
and #4, landed in the round-7 instruments PR but never test-pinned):

- bench.py suspends the periodic faulthandler stack dumps around timed
  host-side measurement regions and RE-ARMS them after — the dumps
  exist for tunnel-hang forensics, not to perturb single-core timings;
- __graft_entry__.py reads the relay probe endpoint from
  AMTPU_ENTRY_PROBE_ADDR instead of a hardcoded socket.

Both are imported by file path: bench.py and __graft_entry__.py keep
heavy imports deferred, so importing the modules is stdlib-cheap."""

import importlib.util
import pathlib
import sys

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _load(name, filename):
    mod = sys.modules.get(name)
    if mod is not None:
        return mod
    spec = importlib.util.spec_from_file_location(name,
                                                  str(ROOT / filename))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def bench():
    return _load("bench", "bench.py")


@pytest.fixture(scope="module")
def graft_entry():
    return _load("__graft_entry__", "__graft_entry__.py")


class _FHRecorder:
    """Stand-in for the faulthandler module surface bench uses."""

    def __init__(self):
        self.calls = []

    def dump_traceback_later(self, interval, repeat=False, exit=False,
                             file=None):
        self.calls.append(("arm", interval, repeat))

    def cancel_dump_traceback_later(self):
        self.calls.append(("cancel",))


# -- faulthandler hygiene around timed regions (ADVICE low #3) --------------


def test_quiet_dumps_cancels_then_rearms(bench, monkeypatch):
    rec = _FHRecorder()
    monkeypatch.setitem(sys.modules, "faulthandler", rec)
    monkeypatch.setattr(bench, "_fh_armed", True)
    with bench._quiet_traceback_dumps():
        assert rec.calls == [("cancel",)], (
            "the periodic dump must be CANCELLED inside a timed region")
    assert rec.calls[-1] == ("arm", bench._FH_INTERVAL_S, True), (
        "the dump must re-arm (repeat=True) when the region exits")


def test_quiet_dumps_rearms_even_when_region_raises(bench, monkeypatch):
    rec = _FHRecorder()
    monkeypatch.setitem(sys.modules, "faulthandler", rec)
    monkeypatch.setattr(bench, "_fh_armed", True)
    with pytest.raises(RuntimeError):
        with bench._quiet_traceback_dumps():
            raise RuntimeError("timed region died")
    assert rec.calls[-1][0] == "arm", (
        "hang forensics must survive a failing measurement region")


def test_quiet_dumps_noop_when_never_armed(bench, monkeypatch):
    """Library/test use never arms the watchdog; the context manager
    must not arm it either (arming belongs to the bench worker only)."""
    rec = _FHRecorder()
    monkeypatch.setitem(sys.modules, "faulthandler", rec)
    monkeypatch.setattr(bench, "_fh_armed", False)
    with bench._quiet_traceback_dumps():
        pass
    assert rec.calls == []


def test_arm_sets_flag_and_uses_repeat(bench, monkeypatch):
    rec = _FHRecorder()
    monkeypatch.setitem(sys.modules, "faulthandler", rec)
    monkeypatch.setattr(bench, "_fh_armed", False)
    bench._arm_traceback_dumps()
    assert bench._fh_armed is True
    assert rec.calls == [("arm", bench._FH_INTERVAL_S, True)]


def test_timed_bench_regions_run_under_quiet_dumps():
    """Every timed host-side measurement helper must route through
    _quiet_traceback_dumps — a new timed region added without it brings
    the perturbation class back. Source-level pin (the helpers defer
    their timing to runtime, so a static check is the cheap reliable
    one)."""
    src = (ROOT / "bench.py").read_text()
    for fn in ("def run_oracle(", "def run_oracle_split(",
               "def run_doc_obs_config(", "def _fleet_health_subrun(",
               "def _fleet_health_overhead_ab("):
        body = src.split(fn, 1)[1].split("\ndef ", 1)[0]
        assert "_quiet_traceback_dumps()" in body, (
            f"{fn.strip('def (')} times host work without suspending "
            "the periodic faulthandler dumps")


# -- relay probe endpoint override (ADVICE low #4) --------------------------


def test_probe_addr_default_and_override(graft_entry):
    assert graft_entry._probe_addr(None) == ("127.0.0.1", 8083)
    assert graft_entry._probe_addr("relay.internal:9100") == \
        ("relay.internal", 9100)


def test_probe_addr_bare_host_keeps_default_port(graft_entry):
    assert graft_entry._probe_addr("relayhost") == ("relayhost", 8083)


def test_probe_addr_malformed_falls_back(graft_entry, capsys):
    assert graft_entry._probe_addr("host:notaport") == \
        ("127.0.0.1", 8083)
    assert "bad AMTPU_ENTRY_PROBE_ADDR" in capsys.readouterr().err


def test_guard_reads_env_not_hardcoded(graft_entry):
    """The guard itself must consume the helper (no resurrected
    hardcoded socket)."""
    import inspect
    src = inspect.getsource(graft_entry._guard_dead_tunnel)
    assert "_probe_addr(os.environ.get(\"AMTPU_ENTRY_PROBE_ADDR\"))" \
        in src
