"""Epoch-batched ingestion (sync/epochs.py + the service's epoch mode):
group-commit coalescing, snapshot-read consistency under concurrent
writers, flush-failure ticket/retry semantics, the oplag buffer_wait
stage, and flusher thread lifecycle."""

import threading
import time

import numpy as np
import pytest

import automerge_tpu as am
from automerge_tpu.core.change import Change, Op
from automerge_tpu.core.ids import ROOT_ID
from automerge_tpu.sync.service import EngineDocSet
from automerge_tpu.sync.sharded_service import ShardedEngineDocSet
from automerge_tpu.utils import metrics, oplag

from tests.test_rows_service import oracle_hash


def wire_change(actor, seq, key="k", value=0):
    from automerge_tpu.native.wire import changes_to_columns
    return changes_to_columns([Change(actor=actor, seq=seq, deps={},
                                      ops=[Op("set", ROOT_ID, key=key,
                                              value=value)])])


def chs(actor, n, key="k"):
    return [Change(actor=actor, seq=s, deps={},
                   ops=[Op("set", ROOT_ID, key=key, value=s)])
            for s in range(1, n + 1)]


def test_epoch_mode_is_the_rows_default():
    e = EngineDocSet(backend="rows")
    assert e.ingest_mode == "epoch"
    assert e._epoch is not None and e._flusher is not None
    # docs-major applies inline regardless of the requested mode
    r = EngineDocSet(backend="resident", ingest_mode="epoch")
    assert r.ingest_mode == "locked"
    with pytest.raises(ValueError, match="ingest_mode"):
        EngineDocSet(backend="rows", ingest_mode="bogus")


def test_apply_returns_flushed_and_readable():
    """The synchronous contract survives the buffered admission path:
    when apply_changes returns, the change is engine truth."""
    e = EngineDocSet(backend="rows")
    cs = chs("A", 3)
    e.apply_changes("d", cs)
    assert e._pending == {} and e._epoch.empty()
    assert e.clock_of("d") == {"A": 3}
    got = e.missing_changes("d", {})
    assert {(c.actor, c.seq) for c in got} == {("A", s) for s in (1, 2, 3)}
    assert np.uint32(e.hashes()["d"]) == oracle_hash(cs)
    e.close()


def test_concurrent_writers_group_commit_and_converge():
    """N writer threads through one epoch-mode service: fewer rounds than
    ingresses (group commit), every doc converges to the oracle, and no
    writer ever waits on the service lock."""
    am.metrics.reset()
    e = EngineDocSet(backend="rows")
    n_writers, n_ops = 4, 40
    docs = {w: f"w{w}" for w in range(n_writers)}
    for w, d in docs.items():
        e.apply_changes(d, chs(f"W{w}", 1))
    m0 = metrics.snapshot()
    errs = []

    def writer(w):
        try:
            for s in range(2, n_ops + 2):
                e.apply_columns(docs[w], wire_change(f"W{w}", s, value=s))
        except BaseException as exc:
            errs.append(exc)

    ts = [threading.Thread(target=writer, args=(w,), daemon=True,
                           name=f"t-epoch-w{w}") for w in range(n_writers)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs
    m1 = metrics.snapshot()
    rounds = (m1.get("sync_rounds_flushed", 0)
              - m0.get("sync_rounds_flushed", 0))
    total = n_writers * n_ops
    assert 0 < rounds < total, (rounds, total)   # coalescing happened
    assert (m1.get("sync_epochs_sealed", 0)
            - m0.get("sync_epochs_sealed", 0)) >= 1
    assert (m1.get("sync_ops_buffered", 0)
            - m0.get("sync_ops_buffered", 0)) == total
    wait_key = "sync_lock_wait_s{lock=service}_sum"
    assert (m1.get(wait_key, 0.0) - m0.get(wait_key, 0.0)) < 0.5
    for w, d in docs.items():
        want = oracle_hash(chs(f"W{w}", n_ops + 1))
        assert np.uint32(e.hashes()[d]) == want, d
    e.close()


def test_abandoned_async_handle_still_gossips():
    """The drain thread's gossip backstop: an apply_columns_async caller
    that drops its handle without waiting must not strand _admit_notify
    — attached handlers still hear about the admission, and a handler
    that re-enters apply ON the drain thread takes the inline locked
    path instead of deadlocking the drainer on its own ticket."""
    e = EngineDocSet(backend="rows")
    e.apply_changes("d", chs("A", 1))
    e.apply_changes("other", chs("B", 1))
    seen = []

    def handler(doc_id, handle):
        seen.append(doc_id)
        if doc_id == "d" and seen.count("d") == 1:
            # re-entrant apply on whatever thread runs the gossip
            e.apply_columns("other", wire_change("B", 2, value=2))

    e.handlers.append(handler)
    e.apply_columns_async("d", wire_change("A", 2, value=2))  # abandoned
    deadline = time.time() + 10.0
    while ("d" not in seen or e.clock_of("other") != {"B": 2}) \
            and time.time() < deadline:
        time.sleep(0.01)
    assert "d" in seen, "abandoned ingress never gossiped"
    assert e.clock_of("other") == {"B": 2}, "re-entrant apply lost"
    assert e.clock_of("d") == {"A": 2}
    e.close()


def test_sync_apply_gossips_on_the_calling_thread_before_return():
    """A synchronous apply's ticket is CLAIMED, so the flusher's gossip
    backstop stays off the round: when apply_columns returns, the
    admission gossip has been delivered — and by the applying thread
    itself (a relayed send must run inside the serve span that
    triggered it; a single-threaded test pumping an in-memory wire
    must find the message already queued). This is the regression
    pin for the backstop/writer delivery race."""
    e = EngineDocSet(backend="rows")
    seen = []
    e.handlers.append(
        lambda doc_id, handle: seen.append(
            (doc_id, threading.current_thread().name)))
    for i in range(1, 21):
        e.apply_columns("d", wire_change("A", i, value=i))
        assert ("d", threading.current_thread().name) in seen, \
            f"ingress {i}: gossip not delivered on the caller by return"
        assert not any(t.startswith("amtpu-flusher") for _, t in seen), \
            "flusher backstop stole a claimed round's gossip"
        seen.clear()
    e.close()


def test_refill_probe_waits_on_growth_never_on_a_clock():
    """The flusher's pre-seal refill window (_refill_probe) yields the
    GIL only while the buffer is still GROWING: a static or empty
    buffer quiesces on the first poll (no latency tax on a solo or
    synchronous writer), the probe never consumes entries (sealing is
    _drain_epochs_once's job), and a pathological never-waiting append
    flood cannot hold it past the hard cap (_REFILL_CAP_S)."""
    e = EngineDocSet(backend="rows")
    try:
        t0 = time.perf_counter()
        for _ in range(50):
            e._refill_probe()           # empty: nothing to wait for
        assert time.perf_counter() - t0 < 0.25
        e._epoch.append("d", wire_change("A", 1, value=1), None)
        t0 = time.perf_counter()
        e._refill_probe()               # static: one no-growth poll
        assert time.perf_counter() - t0 < 0.25
        assert e._epoch.count() == 1    # probe observed, never sealed
        stop = threading.Event()

        def flood():
            s = 2
            while not stop.is_set():
                e._epoch.append("d", wire_change("A", s, value=s), None)
                s += 1

        th = threading.Thread(target=flood, daemon=True)
        th.start()
        try:
            t0 = time.perf_counter()
            e._refill_probe()           # growth every poll: cap bounds it
            assert time.perf_counter() - t0 < 0.25
        finally:
            stop.set()
            th.join()
    finally:
        e.close()


def test_seal_is_one_atomic_cut_across_stripes():
    """seal() holds ALL stripe locks across the swap: with one stripe
    lock held externally, a blocked seal must not have drained ANY
    stripe (a per-stripe sequential drain would let a writer's later
    append seal into an earlier round than its prior append to an
    already-drained stripe, breaking per-thread durability order)."""
    from automerge_tpu.sync.epochs import EpochIngestBuffer

    buf = EpochIngestBuffer()
    # two docs landing in different stripes
    docs = {}
    for i in range(64):
        d = f"doc{i}"
        k = buf._stripes.index(buf._stripe_of(d))
        docs.setdefault(k, d)
        if len(docs) >= 2:
            break
    (k_lo, d_lo), (k_hi, d_hi) = sorted(docs.items())[:2]
    buf.append(d_lo, None, None)
    buf.append(d_hi, None, None)
    sealed = []
    with buf._stripes[k_hi].lock:        # block the cut at a LATER stripe
        t = threading.Thread(target=lambda: sealed.append(buf.seal()),
                             daemon=True)
        t.start()
        time.sleep(0.15)
        assert t.is_alive()
        # nothing swapped yet: the earlier stripe still holds its entry
        assert len(buf._stripes[k_lo].entries) == 1
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert {e.doc_id for e in sealed[0]} == {d_lo, d_hi}
    assert buf.empty() and not buf.has(d_lo) and not buf.has(d_hi)


def test_concurrent_readers_see_only_sealed_epochs():
    """Readers racing writers never observe torn state: every clock_of /
    missing_changes pair is internally consistent (the served changes
    cover exactly the served clock), and mid-flight reads equal a
    quiesced re-read once writers stop."""
    e = EngineDocSet(backend="rows")
    e.apply_changes("d", chs("A", 1))
    stop = threading.Event()
    bad = []

    def reader():
        while not stop.is_set():
            clk = e.clock_of("d")
            got = e.missing_changes("d", {})
            seqs = sorted(c.seq for c in got if c.actor == "A")
            # no torn reads: the log is a contiguous prefix 1..k and the
            # clock read beside it is some (possibly older/newer) k'
            if seqs != list(range(1, len(seqs) + 1)):
                bad.append(("gap", seqs))
            if clk.get("A", 0) > 60:
                bad.append(("clock overrun", clk))

    def writer():
        for s in range(2, 61):
            e.apply_columns("d", wire_change("A", s, value=s))

    rs = [threading.Thread(target=reader, daemon=True, name=f"t-rd{i}")
          for i in range(2)]
    w = threading.Thread(target=writer, daemon=True, name="t-wr")
    for t in rs:
        t.start()
    w.start()
    w.join()
    stop.set()
    for t in rs:
        t.join(timeout=10)
    assert not bad, bad[:3]
    # quiesced re-read agrees with the final mid-flight view
    assert e.clock_of("d") == {"A": 60}
    assert len(e.missing_changes("d", {})) == 60
    e.close()


def test_flush_failure_reaches_writer_and_retry_succeeds():
    """A pre-admission flush failure resolves the waiting writer's ticket
    with the error, leaves the round in _pending (buffer intact for
    retry), and an explicit flush() retries it to truth."""
    e = EngineDocSet(backend="rows")
    rset = e._resident
    if rset._native is None:
        pytest.skip("python-encoder fallback exercises a different path")
    cs = chs("A", 2)
    real = rset.apply_round_frames
    rset.apply_round_frames = lambda *a, **k: (_ for _ in ()).throw(
        RuntimeError("budget precheck failed"))
    with pytest.raises(RuntimeError, match="precheck"):
        e.apply_changes("d", cs)
    rset.apply_round_frames = real
    assert "d" in e._pending          # restored for retry
    e.flush()
    assert e._pending == {}
    assert np.uint32(e.hashes()["d"]) == oracle_hash(cs)
    e.close()


def test_reads_mid_flush_equal_quiesced_reread():
    """hashes()/missing_changes served while a flush is in flight equal
    a quiesced re-read: a slow engine apply cannot expose half-applied
    state to the read surface."""
    e = EngineDocSet(backend="rows")
    e.apply_changes("d", chs("A", 1))
    rset = e._resident
    real = rset.apply_round_frames
    entered = threading.Event()

    def slow(*a, **k):
        entered.set()
        time.sleep(0.15)
        return real(*a, **k)

    rset.apply_round_frames = slow
    t = threading.Thread(
        target=lambda: e.apply_columns("d", wire_change("A", 2, value=2)),
        daemon=True, name="t-slow-writer")
    t.start()
    assert entered.wait(5.0)
    # mid-flush reads: block-and-observe or serve the pre-flush snapshot
    # — either way internally consistent
    clk = e.clock_of("d")
    assert clk.get("A") in (1, 2)
    t.join(timeout=10)
    rset.apply_round_frames = real
    assert e.clock_of("d") == {"A": 2}
    assert len(e.missing_changes("d", {})) == 2
    assert np.uint32(e.hashes()["d"]) == oracle_hash(chs("A", 2))
    e.close()


def test_snapshot_read_cache_serves_and_invalidates():
    """Repeated clock_of/missing_changes reads of an untouched doc serve
    from the snapshot cache (sync_reads_cached moves); an admission
    invalidates, and the next read sees the new truth."""
    am.metrics.reset()
    e = EngineDocSet(backend="rows")
    e.apply_changes("d", chs("A", 2))
    e.clock_of("d")                    # fills the cache
    m0 = metrics.snapshot().get("sync_reads_cached", 0)
    for _ in range(3):
        assert e.clock_of("d") == {"A": 2}
        assert len(e.missing_changes("d", {"A": 1})) == 1
    m1 = metrics.snapshot().get("sync_reads_cached", 0)
    assert m1 - m0 >= 5
    e.apply_columns("d", wire_change("A", 3, value=3))
    assert e.clock_of("d") == {"A": 3}           # invalidated + refilled
    assert len(e.missing_changes("d", {})) == 3
    e.close()


def test_oplag_buffer_wait_stage_records():
    """Sampled epoch-mode ingresses record the buffer_wait stage (append
    -> seal) alongside the existing flush stages."""
    am.metrics.reset()
    oplag.set_sample_rate(1)
    try:
        e = EngineDocSet(backend="rows")
        e.apply_changes("d", chs("A", 2))
        snap = metrics.snapshot()
        for stage in ("buffer_wait", "queue_wait", "flush", "origin_total"):
            assert snap.get(f"sync_op_lag_s{{stage={stage}}}_count",
                            0) >= 1, stage
        assert "buffer_wait" in snap["oplag"]["stages"]
        e.close()
    finally:
        oplag.set_sample_rate(None)
        am.metrics.reset()


def test_locked_mode_still_available_and_converges():
    e = EngineDocSet(backend="rows", ingest_mode="locked")
    assert e._epoch is None and e._flusher is None
    cs = chs("A", 3)
    e.apply_changes("d", cs)
    assert np.uint32(e.hashes()["d"]) == oracle_hash(cs)
    assert e.clock_of("d") == {"A": 3}


def test_flusher_thread_named_and_joined_on_close():
    """The flusher spawns lazily with the amtpu-flusher-<shard> name
    (flight-recorder attribution), and close() joins it."""
    s = ShardedEngineDocSet(n_shards=2)
    s.apply_changes("doc-a", chs("A", 1))
    s.apply_changes("doc-b", chs("B", 1))
    names = {t.name for t in threading.enumerate()}
    assert any(n.startswith("amtpu-flusher-") for n in names), names
    s.close()
    deadline = time.time() + 5.0
    while time.time() < deadline:
        alive = [t for t in threading.enumerate()
                 if t.name.startswith("amtpu-flusher-")]
        if not alive:
            break
        time.sleep(0.02)
    assert not alive


def test_flusher_exits_after_idle_linger_and_respawns(monkeypatch):
    """An idle flusher exits past the linger window (no thread leak per
    service) and a later ingress respawns a fresh one."""
    e = EngineDocSet(backend="rows")
    e._flusher._linger_s = 0.05
    e.apply_changes("d", chs("A", 1))
    t1 = e._flusher._thread
    assert t1 is not None and t1.is_alive()
    deadline = time.time() + 5.0
    while time.time() < deadline and e._flusher._thread is not None:
        time.sleep(0.02)
    assert e._flusher._thread is None
    t1.join(timeout=5.0)
    e.apply_columns("d", wire_change("A", 2, value=2))    # respawns
    assert e.clock_of("d") == {"A": 2}
    e.close()


def test_batch_still_one_round_in_epoch_mode():
    am.metrics.reset()
    e = EngineDocSet(backend="rows")
    with e.batch():
        for i in range(5):
            e.apply_changes(f"d{i}", chs(f"W{i}", 1))
    snap = am.metrics.snapshot()
    assert (snap.get("rows_rounds_batched", 0)
            + snap.get("rows_rounds_fallback", 0)) == 1, snap
    for i in range(5):
        assert np.uint32(e.hashes()[f"d{i}"]) == oracle_hash(chs(f"W{i}", 1))
    e.close()


def test_sharded_concurrent_writers_audit_green():
    """Concurrent multi-writer load on a sharded node: the convergence
    audit surface still reports consistent per-shard digests, and an
    injected divergence is still isolated through the epoch-snapshot
    read path."""
    from automerge_tpu.sync.audit import state_digest

    s = ShardedEngineDocSet(n_shards=2)
    docs = [f"doc{i}" for i in range(6)]
    for d in docs:
        s.apply_changes(d, chs("B", 1, key="base"))

    def writer(w):
        for seq in range(2, 12):
            s.apply_columns(docs[w % len(docs)], wire_change("B", seq, value=seq))

    ts = [threading.Thread(target=writer, args=(w,), daemon=True,
                           name=f"t-shw{w}") for w in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    st = s.audit_state()
    assert set(st) == {"0", "1"}
    # digests recompute identically from the doc-level surface
    for shard, info in st.items():
        detail = s.audit_shard_state(shard)
        assert state_digest(detail["hashes"]) == info["digest"]
    # inject divergence in one shard's engine and re-read: the digest of
    # exactly that shard moves
    victim = s.shard_of(docs[0])
    rset = victim._resident
    i = rset.doc_index[docs[0]]
    rset._mark_dirty([i]) if hasattr(rset, "_mark_dirty") else None
    before = s.audit_state()
    victim.apply_changes(docs[0], [Change(
        actor="EVIL", seq=1, deps={},
        ops=[Op("set", ROOT_ID, key="x", value=666)])])
    after = s.audit_state()
    vlabel = victim._shard
    assert after[vlabel]["digest"] != before[vlabel]["digest"]
    other = [k for k in after if k != vlabel][0]
    assert after[other]["digest"] == before[other]["digest"]
    s.close()


def test_apply_columns_async_pipeline():
    """The pipelined admission surface: tickets resolve with flush
    durability, in-order per writer thread, and errors reach the
    awaiting caller; locked-mode services degrade to synchronous apply
    with a pre-resolved handle."""
    e = EngineDocSet(backend="rows")
    pend = [e.apply_columns_async("d", wire_change("A", s, value=s))
            for s in range(1, 6)]
    for p in pend:
        p.wait()
    # wait is idempotent: a repeat wait on a resolved ticket returns
    # immediately instead of parking on the already-consumed futex
    for p in pend:
        p.wait()
    assert e.clock_of("d") == {"A": 5}
    assert np.uint32(e.hashes()["d"]) == oracle_hash(chs("A", 5))
    # error propagation: a failing flush reaches the awaiting caller
    rset = e._resident
    if rset._native is not None:
        real = rset.apply_round_frames
        rset.apply_round_frames = lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("boom async"))
        p = e.apply_columns_async("d", wire_change("A", 6, value=6))
        with pytest.raises(RuntimeError, match="boom async"):
            p.wait()
        with pytest.raises(RuntimeError, match="boom async"):
            p.wait()                    # repeat wait re-raises, no hang
        rset.apply_round_frames = real
        e.flush()                       # retry drains the restored round
        assert e.clock_of("d") == {"A": 6}
    e.close()
    # locked mode: synchronous fallback, handle pre-resolved
    el = EngineDocSet(backend="rows", ingest_mode="locked")
    h = el.apply_columns_async("d", wire_change("B", 1, value=1))
    assert h.done
    h.wait()
    assert el.clock_of("d") == {"B": 1}
