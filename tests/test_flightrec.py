"""Flight recorder: event ring, dump schema, and the ISSUE acceptance —
a stalled sharded hash fan-out under the watchdog produces a JSON
post-mortem naming the stalled span stack and the last events per
thread."""

import json
import threading
import time

from automerge_tpu import metrics
from automerge_tpu.core.change import Change, Op
from automerge_tpu.core.ids import ROOT_ID
from automerge_tpu.native.wire import changes_to_columns
from automerge_tpu.sync import sharded_service
from automerge_tpu.sync.sharded_service import ShardedEngineDocSet
from automerge_tpu.utils import flightrec


def wait_until(predicate, timeout=5.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def _cols(actor, seq, key, value):
    return changes_to_columns([Change(
        actor=actor, seq=seq, deps={},
        ops=[Op("set", ROOT_ID, key=key, value=value)])])


def test_record_and_ring_bound():
    flightrec.reset()
    for i in range(10):
        flightrec.record("test_evt", i=i)
    evs = flightrec.events()
    assert [e["i"] for e in evs] == list(range(10))
    assert all(e["kind"] == "test_evt" and "t" in e and "thread" in e
               for e in evs)
    # seq is monotonic across threads
    assert [e["seq"] for e in evs] == sorted(e["seq"] for e in evs)


def test_dump_schema_and_counter(tmp_path, monkeypatch):
    monkeypatch.setenv("AMTPU_FLIGHTREC_DIR", str(tmp_path))
    metrics.reset()
    flightrec.reset()
    flightrec.record("test_evt", x=1)
    with metrics.trace("engine_hashes"):
        path = flightrec.dump("unit-test", extra={"note": "hello"})
    assert path and path.startswith(str(tmp_path))
    doc = json.load(open(path))
    assert doc["reason"] == "unit-test"
    assert doc["extra"] == {"note": "hello"}
    # the dumping thread's active span stack is captured
    stacks = doc["span_stacks"]
    assert any("engine_hashes" in frame
               for stack in stacks.values() for frame in stack)
    # per-thread event tails
    me = threading.current_thread().name
    assert any(e["kind"] == "test_evt" for e in doc["threads"][me])
    assert isinstance(doc["metrics"], dict)
    assert metrics.snapshot()["obs_flightrec_dumps{reason=unit-test}"] == 1
    assert flightrec.last_dump() == path


def test_stalled_sharded_fanout_dumps_postmortem(tmp_path, monkeypatch):
    """ISSUE acceptance: force a stall in the sharded `hashes` fan-out
    under the watchdog; the flight-recorder JSON dump names the stalled
    span stack and carries the last N events per thread."""
    monkeypatch.setenv("AMTPU_FLIGHTREC_DIR", str(tmp_path))
    svc = ShardedEngineDocSet(n_shards=2)
    for i in range(6):
        svc.apply_columns(f"d{i}", _cols(f"W{i}", 1, "x", i))
    svc.hashes()   # warm the hash kernels under the default (120s) budget:
    #              # the cold-compile must not be what trips the watchdog
    monkeypatch.setattr(sharded_service, "STALL_WATCHDOG_S", 0.15)
    metrics.reset()
    flightrec.reset()

    stalled_shard = svc.shards[1]
    # the incremental plane serves a CLEAN fleet from the per-shard hash
    # caches without fanning out at all — dirty the stalled shard so the
    # fan-out genuinely reads it
    victim = next(d for d in svc.doc_ids
                  if svc.shard_of(d) is stalled_shard)
    svc.apply_columns(victim, _cols("W9", 1, "x", 99))
    orig_snapshot = stalled_shard.hashes_snapshot

    def stalled():
        with metrics.trace("rows_hashes"):   # the classic readback stall
            time.sleep(0.6)
        return orig_snapshot()

    monkeypatch.setattr(stalled_shard, "hashes_snapshot", stalled)
    before = flightrec.last_dump()
    h = svc.hashes()          # stalls past the watchdog budget, completes
    assert len(h) == 6
    assert wait_until(lambda: flightrec.last_dump() not in (None, before))
    path = flightrec.last_dump()
    doc = json.load(open(path))
    assert doc["reason"] == "watchdog:sync_hashes_fanout"

    # the stalled span stack: fan-out > per-shard hash > readback
    stacks = doc["span_stacks"]
    joined = [" > ".join(stack) for stack in stacks.values()]
    assert any("sync_hashes_fanout" in s and "rows_hashes" in s
               for s in joined), stacks

    # last-N events per thread, including the fan-out progress breadcrumbs
    # that say how far the fan-out got. Since the incremental plane,
    # CLEAN shards never enter the fan-out at all (served from the
    # per-shard hash cache) — only the dirty, stalled shard 1 left a
    # breadcrumb, which is exactly the post-mortem's answer to "where
    # did it stall"
    evs = [e for es in doc["threads"].values() for e in es]
    shards_entered = {e["shard"] for e in evs if e["kind"] == "hash_shard"}
    assert shards_entered == {"1"}
    assert not any(e["kind"] == "hash_fanout_done" for e in evs)

    # the watchdog diagnosis itself rode along
    assert any(w["name"] == "sync_hashes_fanout"
               for w in doc["watchdog_events"])
    snap = metrics.snapshot()
    assert snap["obs_watchdog_fired{name=sync_hashes_fanout}"] == 1


def test_excepthook_dump(tmp_path, monkeypatch):
    """install() dumps on an unhandled thread exception, chaining to the
    previous hook."""
    monkeypatch.setenv("AMTPU_FLIGHTREC_DIR", str(tmp_path))
    flightrec.reset()
    seen = []
    monkeypatch.setattr(threading, "excepthook", seen.append)
    flightrec.install(signals=False)
    try:
        before = flightrec.last_dump()

        def boom():
            raise RuntimeError("crash for the recorder")

        t = threading.Thread(target=boom, name="crasher")
        t.start()
        t.join()
        assert wait_until(lambda: flightrec.last_dump() not in (None, before))
        doc = json.load(open(flightrec.last_dump()))
        assert doc["reason"] == "thread-exception"
        assert "crash for the recorder" in doc["extra"]["exception"]
        assert doc["extra"]["thread"] == "crasher"
        assert seen, "previous excepthook was not chained"
    finally:
        flightrec.uninstall()


def test_disabled_recorder_is_inert(monkeypatch):
    monkeypatch.setattr(flightrec, "_ENABLED", False)
    flightrec.reset()
    flightrec.record("test_evt")
    assert flightrec.events() == []
    assert flightrec.dump("nope") is None
