"""Deterministic render tests for the `perf top` dashboard
(automerge_tpu/perf/top.py): the SLO verdict strip, the fleet table
(straggler/stale marks, column values), unicode sparklines, and the
per-doc hot-list panel fed by the convergence ledger — all against a
synthetic collector state, no TTY required."""

import time

import pytest

from automerge_tpu.perf import slo
from automerge_tpu.perf.fleet import FleetCollector
from automerge_tpu.perf.top import (dispatch_lines, hot_doc_lines, render,
                                    spark, tenant_lines, trace_lines)
from automerge_tpu.utils import flightrec, metrics


@pytest.fixture(autouse=True)
def _clean_metrics():
    metrics.reset()
    flightrec.reset()
    yield
    metrics.reset()
    flightrec.reset()


def _snap(ops=0, flush_s=0.0, flush_n=0, lockw=0.0, drops=0, conv=None,
          docledger=None, dispatchledger=None, tenantledger=None,
          traceplane=None):
    out = {
        "sync_ops_ingested": ops,
        "sync_frames_dropped": drops,
        "sync_round_flush_s": flush_s,
        "sync_round_flush_count": flush_n,
        "sync_lock_wait_s{lock=service}_sum": lockw,
        "sync_lock_wait_s{lock=service}_count": 10,
        "sync_lock_hold_s{lock=service}_sum": lockw * 1.5,
    }
    if conv is not None:
        out["oplag"] = {"sample_rate": 4, "stages": {
            "converge": {"count": 8, "p50_s": conv / 2, "p90_s": conv,
                         "p99_s": conv, "max_s": conv}}}
    if docledger is not None:
        out["docledger"] = docledger
    if dispatchledger is not None:
        out["dispatchledger"] = dispatchledger
    if tenantledger is not None:
        out["tenantledger"] = tenantledger
    if traceplane is not None:
        out["traceplane"] = traceplane
    return out


def _scripted(*snaps):
    seq = list(snaps)

    def fn():
        return seq.pop(0) if len(seq) > 1 else seq[0]
    return fn


def _ledger_section(doc, lag_changes, lag_s, behind="w", buffered=0,
                    label="y"):
    return {"nodes": {label: {
        "label": label, "tracked": 1, "top_k": 128, "exported": 1,
        "evictions": 0, "aggregate": {}, "redundancy": {},
        "lag": {}, "docs": {doc: {
            "admitted": 0, "last_admit_at": None, "buffered": buffered,
            "lag_changes": lag_changes, "lag_s": lag_s,
            "behind_since": None, "behind_peer": behind, "peers": {}}}}}}


def _dispatch_section(label="y", amp=6.5, waste=88.2, dispatches=13,
                      ambient=0, rounds=2, bucket="rows_apply:128x128",
                      padded=16384):
    return {"nodes": {label: {
        "label": label, "rounds_total": rounds,
        "dispatches_total": dispatches, "ambient_total": ambient,
        "window": {
            "rounds": rounds, "dispatches": dispatches,
            "ambient": ambient, "dirty_docs": 2,
            "amplification": amp, "pad_waste_pct": waste,
            "dispatches_per_round": (dispatches / rounds if rounds
                                     else None),
            "buckets": {bucket: {"calls": dispatches, "docs": 2,
                                 "docs_cap": 128, "logical": 2,
                                 "padded": padded, "wall_s": 0.01}},
        }, "ring": []}}}


def _tenant_section(label="y", tenants=None):
    """A minimal `"tenantledger"` snapshot section: tenants maps
    tenant-id -> (ingress_share_pct, dispatch_share, p99_s, shed)."""
    body = {}
    for tid, (share, disp, p99, shed) in (tenants or {}).items():
        body[tid] = {
            "admitted": 10, "sent_changes": 0, "bytes_sent": 0,
            "recv_useful": 0, "recv_duplicate": 0, "bytes_received": 0,
            "drops": 0, "shed_dropped": shed, "shed_delayed": 0,
            "delayed_s": 0.0, "rounds": 1, "dirty_docs": 1,
            "dispatch_share": disp, "padded_share": 0.0,
            "logical_share": 0.0, "wall_share_s": 0.0,
            "ingress_share_pct": share,
            "lag": {"p50_s": p99 / 2, "p99_s": p99, "max_s": p99},
        }
    return {"nodes": {label: {
        "label": label, "prefix": "tenant/", "tracked": len(body),
        "truncated": 0, "overflow_tenants": 0,
        "admitted_total": 10 * len(body), "rounds_total": 1,
        "self_s": 0.0, "tenants": body}}}


def _three_node_collector(straggler_conv=2.0, docledger=None,
                          dispatchledger=None, tenantledger=None,
                          traceplane=None):
    c = FleetCollector(interval_s=0.02, min_nodes=3)
    c.add_local("a", _scripted(_snap(), _snap(ops=60, flush_s=0.06,
                                              flush_n=30, conv=0.01)),
                role="peer")
    c.add_local("b", _scripted(_snap(), _snap(ops=60, flush_s=0.06,
                                              flush_n=30, conv=0.01)),
                role="peer")
    c.add_local("x", _scripted(_snap(), _snap(ops=10, flush_s=4.0,
                                              flush_n=10,
                                              conv=straggler_conv,
                                              docledger=docledger,
                                              dispatchledger=dispatchledger,
                                              tenantledger=tenantledger,
                                              traceplane=traceplane)),
                role="peer")
    c.scrape_once()
    time.sleep(0.02)
    c.scrape_once()
    return c


# -- sparkline --------------------------------------------------------------


def test_spark_shape_and_bounds():
    assert spark([]) == ""
    line = spark([0, 1, 2, 3])
    assert len(line) == 4
    assert line[0] == "▁" and line[-1] == "█"
    # constant series renders the low block, not a crash (span 0 guard)
    assert set(spark([5, 5, 5])) == {"▁"}
    # width cap keeps the panel one line
    assert len(spark(list(range(100)), width=24)) == 24


# -- SLO strip --------------------------------------------------------------


def test_slo_strip_cells_ok_breach_and_nodata():
    eng = slo.SloEngine(slos=[
        {"name": "converge_p99", "signal": "converge_p99_s", "bound": 1.0},
        {"name": "ops_floor", "signal": "ops_per_s", "bound": 1e9},
        {"name": "ghost", "signal": "never_recorded", "bound": 1.0},
    ])
    c = _three_node_collector()
    c.slo_engine = eng
    c.scrape_once()
    lines = render(c, eng)
    slo_line = next(line for line in lines if line.startswith("SLO: "))
    assert "[BREACH] converge_p99" in slo_line
    assert "[OK] ops_floor" in slo_line
    assert "[--] ghost" in slo_line


# -- fleet table ------------------------------------------------------------


def test_fleet_table_columns_straggler_and_header():
    c = _three_node_collector()
    lines = render(c)
    text = "\n".join(lines)
    header = next(line for line in lines if line.startswith("node"))
    for col in ("ops/s", "conv p99", "flush", "lockw/s", "drops/s",
                "score", "age"):
        assert col in header
    xrow = next(line for line in lines if line.startswith("x "))
    assert "<< STRAGGLER" in xrow
    assert "2.000s" in xrow          # conv p99 column
    arow = next(line for line in lines if line.startswith("a "))
    assert "STRAGGLER" not in arow
    assert "3 node(s)" in lines[0]
    assert "1 straggler(s)" in lines[0]


def test_fleet_table_marks_stale_nodes():
    c = FleetCollector(interval_s=0.01, min_nodes=3)
    c.add_local("live", _scripted(_snap(), _snap(ops=10, flush_s=0.01,
                                                 flush_n=5)))
    c.scrape_once()
    st = c._node("dead", "node")
    st.add_sample(time.time() - 60.0, _snap())
    time.sleep(0.01)
    c.scrape_once()
    lines = render(c)
    dead = next(line for line in lines if line.startswith("dead"))
    assert "(stale)" in dead


def test_sparkline_band_follows_busiest_node():
    c = _three_node_collector()
    lines = render(c)
    text = "\n".join(lines)
    # the straggler is focused; its ring history renders as sparklines
    assert any(line.startswith("x conv p99") or
               line.startswith("x flush") or
               line.startswith("x ops/s") for line in lines), text


# -- per-doc hot list (the docledger panel) ---------------------------------


def test_hot_doc_panel_renders_ledger_rows():
    sec = _ledger_section("orders-007", 12, 3.25, behind="w1",
                          buffered=2, label="y")
    c = _three_node_collector(docledger=sec)
    lines = render(c)
    text = "\n".join(lines)
    assert "hot docs (converge lag; `perf explain <doc>`):" in text
    row = next(line for line in lines if "orders-007" in line)
    assert "@ y" in row
    assert "12 chg" in row
    assert "behind w1" in row
    assert "[2 buffered]" in row


def test_hot_doc_panel_absent_without_ledgers():
    c = _three_node_collector()
    assert hot_doc_lines(c) == []
    assert not any("hot docs" in line for line in render(c))


def test_hot_doc_panel_ranks_and_caps():
    nodes = {}
    for k in range(8):
        nodes[f"n{k}"] = _ledger_section(
            f"doc{k}", k + 1, float(k), label=f"n{k}")["nodes"][f"n{k}"]
    sec = {"nodes": nodes}
    c = FleetCollector(interval_s=0.01, min_nodes=3)
    c.add_local("hub", _scripted(_snap(docledger=sec)))
    c.scrape_once()
    lines = hot_doc_lines(c, limit=3)
    assert len(lines) == 1 + 3
    # worst lag first
    assert "doc7" in lines[1] and "doc6" in lines[2] and "doc5" in lines[3]


# -- dispatch-waste band (the dispatchledger panel, r17) ---------------------


def test_dispatch_band_renders_ledger_rows():
    sec = _dispatch_section(label="y", amp=6.5, waste=88.2,
                            dispatches=13, rounds=2,
                            bucket="rows_apply:128x128")
    c = _three_node_collector(dispatchledger=sec)
    lines = render(c)
    text = "\n".join(lines)
    assert "dispatch waste (amplification; `perf dispatch`):" in text
    row = next(line for line in lines if "rows_apply:128x128" in line)
    assert "amp" in row and "6.50x" in row
    assert "waste" in row and "88.2%" in row
    assert "13 disp/2 rnd" in row
    assert "worst rows_apply:128x128" in row


def test_dispatch_band_absent_without_ledger():
    c = _three_node_collector()
    assert dispatch_lines(c) == []
    assert not any("dispatch waste" in line for line in render(c))
    # a ledger section with an empty window disappears the same way
    empty = _dispatch_section(dispatches=0, ambient=0)
    c2 = _three_node_collector(dispatchledger=empty)
    assert dispatch_lines(c2) == []


def test_dispatch_band_ranks_and_caps():
    nodes = {}
    for k in range(8):
        nodes[f"n{k}"] = _dispatch_section(
            label=f"n{k}", amp=float(k), dispatches=k + 1,
            bucket=f"fam:{k}")["nodes"][f"n{k}"]
    sec = {"nodes": nodes}
    c = FleetCollector(interval_s=0.01, min_nodes=3)
    c.add_local("hub", _scripted(_snap(dispatchledger=sec)))
    c.scrape_once()
    lines = dispatch_lines(c, limit=3)
    assert len(lines) == 1 + 3 + 1       # header + rows + overflow note
    # worst amplification first
    assert "n7" in lines[1] and "n6" in lines[2] and "n5" in lines[3]
    assert "+5 more ledger node(s)" in lines[4]


# -- tenant band (the tenantledger panel, r18) -------------------------------


def test_tenant_band_renders_ledger_rows():
    sec = _tenant_section(label="y", tenants={
        "acme": (62.5, 4.0, 3.25, 7),
        "_default": (37.5, 1.0, 0.01, 0),
    })
    c = _three_node_collector(tenantledger=sec)
    lines = render(c)
    text = "\n".join(lines)
    assert "tenants (ingress share; `perf tenant`):" in text
    row = next(line for line in lines if "acme" in line)
    assert "@ y" in row
    assert "share" in row and "62.5%" in row
    assert "disp" in row and "4.0" in row
    assert "p99" in row and "3.2500s" in row
    assert "[7 shed]" in row
    quiet = next(line for line in lines if "_default" in line)
    assert "shed" not in quiet      # zero shed suppresses the tag
    # hottest share ranks first
    assert lines.index(row) < lines.index(quiet)


def test_tenant_band_absent_without_ledger():
    c = _three_node_collector()
    assert tenant_lines(c) == []
    assert not any("tenants (" in line for line in render(c))
    # a section with no tenants disappears the same way
    empty = _tenant_section(label="y", tenants={})
    c2 = _three_node_collector(tenantledger=empty)
    assert tenant_lines(c2) == []


def test_tenant_band_ranks_and_caps():
    tenants = {f"t{k}": (float(k * 10), float(k), 0.1 * k, 0)
               for k in range(8)}
    sec = _tenant_section(label="hub", tenants=tenants)
    c = FleetCollector(interval_s=0.01, min_nodes=3)
    c.add_local("hub", _scripted(_snap(tenantledger=sec)))
    c.scrape_once()
    lines = tenant_lines(c, limit=3)
    assert len(lines) == 1 + 3 + 1       # header + rows + overflow note
    # highest ingress share first
    assert "t7" in lines[1] and "t6" in lines[2] and "t5" in lines[3]
    assert "+5 more tenant row(s)" in lines[4]


def _trace_section(label="y", stages=None, crit_p99=0.5, completed=12):
    """A minimal `"traceplane"` snapshot section: stages maps
    stage -> (count, sum_s, p99_s)."""
    body = {st: {"count": n, "sum_s": s, "p50_s": s / max(n, 1),
                 "p99_s": p99}
            for st, (n, s, p99) in (stages or {}).items()}
    return {"nodes": {label: {
        "label": label, "sample_rate": 4, "sampled": completed,
        "completed": completed, "stitched": completed, "expired": 0,
        "dropped": 0, "inflight": 0, "self_s": 0.001,
        "stages": body,
        "critical_path": {"count": completed, "p50_s": crit_p99 / 2,
                          "p99_s": crit_p99, "max_s": crit_p99},
        "exemplars": [],
    }}}


def test_trace_band_renders_stage_rows():
    sec = _trace_section(label="y", stages={
        "coalesce_wait": (12, 6.0, 0.9),
        "wire": (12, 3.0, 0.4),
        "visibility": (12, 50.0, 5.0),   # excluded from the share
    }, crit_p99=1.25)
    c = _three_node_collector(traceplane=sec)
    lines = render(c)
    text = "\n".join(lines)
    assert "trace stages (critical-path share; `perf trace`):" in text
    row = next(line for line in lines if "coalesce_wait" in line)
    assert "@ y" in row
    assert "share" in row and "66.7%" in row      # 6.0 of 9.0
    assert "p99" in row and "0.9000s" in row
    assert "e2e p99" in row and "1.2500s" in row
    assert "(12 done)" in row
    # visibility is read-cadence bound by design: no row for it
    assert not any(line.lstrip().startswith("visibility")
                   for line in lines)
    wire_row = next(line for line in lines if " wire " in line)
    assert lines.index(row) < lines.index(wire_row)


def test_trace_band_absent_without_section():
    c = _three_node_collector()
    assert trace_lines(c) == []
    assert not any("trace stages (" in line for line in render(c))
    # a section with no stages disappears the same way
    empty = _trace_section(label="y", stages={})
    c2 = _three_node_collector(traceplane=empty)
    assert trace_lines(c2) == []


def test_trace_band_ranks_and_caps():
    stages = {f"s{k}": (4, float(k), 0.1 * k) for k in range(1, 9)}
    sec = _trace_section(label="hub", stages=stages)
    c = FleetCollector(interval_s=0.01, min_nodes=3)
    c.add_local("hub", _scripted(_snap(traceplane=sec)))
    c.scrape_once()
    lines = trace_lines(c, limit=3)
    assert len(lines) == 1 + 3 + 1       # header + rows + overflow note
    # biggest critical-path share first
    assert "s8" in lines[1] and "s7" in lines[2] and "s6" in lines[3]
    assert "+5 more stage row(s)" in lines[4]


def test_render_width_clamp():
    sec = _ledger_section("x" * 120, 3, 1.0)
    c = _three_node_collector(docledger=sec)
    for line in render(c, width=80):
        assert len(line) <= 80
