"""Span-granularity batched text merging (core/textspans.py +
engine/span_kernels.py).

Three layers of pinning:

- **Host plane ≡ per-op RGA replay.** `OpSet.add_changes(text_batch=True)`
  must produce bit-identical CRDT state (element order, values, field
  tables, clocks) to the per-op path on the SAME batch — seeded
  regression cases for every structural edge (concurrent interleave at
  one position, range deletes across runs, splits mid-run, resurrection,
  insert-then-delete tombstone runs) plus a hypothesis driver over random
  divergent histories, asserting parity AND byte-identical convergence
  regardless of merge order.

- **Kernel parity.** merge_spans (jitted XLA) ≡ merge_spans_host (numpy)
  ≡ span_rank_hash_pallas (interpret mode) on random span tables, and an
  end-to-end check that the kernel's merge order reconstructs the text
  the host CRDT merge produced.

- **Fleet convergence.** Concurrent text edits across a two-service
  engine fleet converge (equal hashes) and the convergence auditor
  reports zero divergence.
"""

import random

import numpy as np
import pytest

try:
    from hypothesis import HealthCheck, given, settings
    from hypothesis import strategies as st
    HAVE_HYPOTHESIS = True
except ImportError:  # pragma: no cover
    HAVE_HYPOTHESIS = False

import automerge_tpu as am
from automerge_tpu.core import textspans
from automerge_tpu.core.change import Change, Op
from automerge_tpu.core.elems import CHUNK, ElemList
from automerge_tpu.core.ids import ROOT_ID
from automerge_tpu.utils import metrics


@pytest.fixture
def span_plane(monkeypatch):
    """Force the span plane on tiny batches (the product threshold keeps
    interactive-size batches on the per-op path for their diff records)."""
    monkeypatch.setattr(textspans, "TEXT_BATCH_MIN_OPS", 1)


def _missing(doc, clock):
    return doc._doc.opset.get_missing_changes(dict(clock))


def _text_state(opset):
    """(elem keys, values, field tables) of the single text object."""
    for oid, obj in opset.by_object.items():
        if obj.init_action == "makeText":
            return (obj.elem_ids.keys, obj.elem_ids.values,
                    dict(obj.fields))
    raise AssertionError("no text object")


def _merge_both_ways(a, b):
    """Merge b's missing changes into a's opset through BOTH paths and
    assert bit-identical text CRDT state; returns the batch diffs."""
    missing = _missing(b, a._doc.opset.clock)
    o1, d1 = a._doc.opset.add_changes(missing)
    o2, d2 = a._doc.opset.add_changes(missing, text_batch=True)
    k1, v1, f1 = _text_state(o1)
    k2, v2, f2 = _text_state(o2)
    assert k1 == k2
    assert v1 == v2
    assert f1 == f2
    assert o1.clock == o2.clock
    assert o1.deps == o2.deps
    return missing, d2


def _base(text="hello world"):
    d = am.change(am.init("A"), lambda x: x.__setitem__("t", am.Text()))
    if text:
        d = am.change(d, lambda x: x["t"].insert_at(0, *text))
    return d


# ---------------------------------------------------------------------------
# host plane: seeded regression cases


def test_batch_path_engages_and_emits_coarse_diffs(span_plane):
    a = _base()
    b = am.merge(am.init("B"), a)
    b = am.change(b, lambda x: x["t"].insert_at(5, *" brave new"))
    metrics.reset()
    missing, diffs = _merge_both_ways(a, b)
    assert missing
    assert len(diffs) == 1
    assert diffs[0]["action"] == "batch"
    assert diffs[0]["type"] == "text"
    assert diffs[0]["path"] == ["t"]
    snap = metrics.snapshot()
    assert snap["sync_text_batches_merged"] == 1
    assert snap["sync_text_spans_spliced"] >= 1


def test_sequential_stream_skips_concurrency_checks(span_plane):
    """A single-writer continuation batch covers the local frontier: every
    op takes the sequential fast path."""
    a = _base()
    cont = am.change(a, lambda x: x["t"].insert_at(11, *"! and more"))
    cont = am.change(cont, lambda x: x["t"].delete_at(0, 2))
    metrics.reset()
    _merge_both_ways(a, cont)
    snap = metrics.snapshot()
    assert snap["sync_text_ops_sequential"] > 0
    assert "sync_text_ops_concurrent" not in snap


def test_concurrent_insert_at_same_position(span_plane):
    a = _base("ab")
    b = am.merge(am.init("B"), a)
    a2 = am.change(a, lambda x: x["t"].insert_at(1, *"XXX"))
    b2 = am.change(b, lambda x: x["t"].insert_at(1, *"yyy"))
    _merge_both_ways(a2, b2)
    # and full convergence through the frontend (span plane on both sides)
    m1, m2 = am.merge(a2, b2), am.merge(b2, a2)
    assert m1["t"].join() == m2["t"].join()
    assert sorted(m1["t"].join()) == sorted("abXXXyyy")


def test_range_delete_spanning_runs(span_plane):
    a = _base("")
    a = am.change(a, lambda x: x["t"].insert_at(0, *"aaa"))
    a = am.change(a, lambda x: x["t"].insert_at(1, *"bbb"))   # splits run
    b = am.merge(am.init("B"), a)
    b2 = am.change(b, lambda x: x["t"].delete_at(1, 4))  # spans both runs
    a2 = am.change(a, lambda x: x["t"].insert_at(6, *"tail"))
    _merge_both_ways(a2, b2)
    m = am.merge(a2, b2)
    assert m["t"].join() == am.merge(b2, a2)["t"].join()


def test_insert_into_middle_of_remote_run(span_plane):
    """B's run splices INTO the middle of A's concurrent run (span split
    at a non-boundary)."""
    a = _base("0123456789")
    b = am.merge(am.init("B"), a)
    a2 = am.change(a, lambda x: x["t"].insert_at(5, *"AAAA"))
    b2 = am.change(b, lambda x: x["t"].insert_at(5, *"bb"))
    _merge_both_ways(a2, b2)
    m1, m2 = am.merge(a2, b2), am.merge(b2, a2)
    assert m1["t"].join() == m2["t"].join()


def test_resurrection_concurrent_set_outlives_delete(span_plane):
    a = _base("abc")
    b = am.merge(am.init("B"), a)
    a2 = am.change(a, lambda x: x["t"].delete_at(1))
    b2 = am.change(b, lambda x: x["t"].__setitem__(1, "Q"))
    _merge_both_ways(a2, b2)
    assert am.merge(a2, b2)["t"].join() == "aQc"
    assert am.merge(b2, a2)["t"].join() == "aQc"


def test_insert_then_delete_within_batch_is_a_tombstone_run(span_plane):
    """A run fully deleted inside the same batch must not splice (the
    vis_keys-empty branch) but its tombstones must survive in the tables."""
    a = _base("xy")
    b = am.merge(am.init("B"), a)
    b2 = am.change(b, lambda x: x["t"].insert_at(1, *"tmp"))
    b2 = am.change(b2, lambda x: x["t"].delete_at(1, 3))
    missing, _ = _merge_both_ways(a, b2)
    o2, _ = a._doc.opset.add_changes(missing, text_batch=True)
    keys, _, fields = _text_state(o2)
    assert len(keys) == 2                      # nothing visible added
    assert any(k.startswith("B:") and not fields.get(k)
               for k in fields)                # tombstones recorded


def test_multiple_text_objects_in_one_batch(span_plane):
    a = am.change(am.init("A"), lambda x: (
        x.__setitem__("t1", am.Text()), x.__setitem__("t2", am.Text())))
    a = am.change(a, lambda x: x["t1"].insert_at(0, *"one"))
    a = am.change(a, lambda x: x["t2"].insert_at(0, *"two"))
    b = am.merge(am.init("B"), a)
    b2 = am.change(b, lambda x: x["t1"].insert_at(3, *"-first"))
    b2 = am.change(b2, lambda x: x["t2"].insert_at(0, *"the-"))
    missing = _missing(b2, a._doc.opset.clock)
    o2, diffs = a._doc.opset.add_changes(missing, text_batch=True)
    assert sorted(d["path"][0] for d in diffs) == ["t1", "t2"]
    m = am.merge(a, b2)
    assert m["t1"].join() == "one-first"
    assert m["t2"].join() == "the-two"


def test_ineligible_batch_falls_back_to_perop_diffs(span_plane):
    """A batch with a non-text op must keep the generic path's exact
    per-op diff records."""
    a = _base()
    b = am.merge(am.init("B"), a)
    b2 = am.change(b, lambda x: (x["t"].insert_at(0, "z"),
                                 x.__setitem__("k", 1)))
    metrics.reset()
    missing = _missing(b2, a._doc.opset.clock)
    _, diffs = a._doc.opset.add_changes(missing, text_batch=True)
    assert all(d["action"] != "batch" for d in diffs)
    assert "sync_text_batches_merged" not in metrics.snapshot()


def test_queued_changes_force_generic_path(span_plane):
    """A causally-unready change in the batch (or already queued) keeps the
    generic queueing semantics."""
    a = _base()
    b = am.merge(am.init("B"), a)
    b2 = am.change(b, lambda x: x["t"].insert_at(0, "p"))
    b3 = am.change(b2, lambda x: x["t"].insert_at(0, "q"))
    missing = _missing(b3, a._doc.opset.clock)
    assert len(missing) == 2
    # deliver out of order: seq 3 first -> must queue, not error
    o, _ = a._doc.opset.add_changes([missing[1]], text_batch=True)
    assert len(o.queue) == 1
    o, _ = o.add_changes([missing[0]], text_batch=True)
    assert not o.queue
    k, v, _ = _text_state(o)
    assert "".join(v[:2]) == "qp"


def test_duplicate_redelivery_falls_back_and_stays_idempotent(span_plane):
    a = _base()
    b = am.merge(am.init("B"), a)
    b2 = am.change(b, lambda x: x["t"].insert_at(0, *"dup"))
    missing = _missing(b2, a._doc.opset.clock)
    o1, _ = a._doc.opset.add_changes(missing, text_batch=True)
    o2, diffs = o1.add_changes(missing, text_batch=True)   # re-delivery
    assert _text_state(o1)[0] == _text_state(o2)[0]


def test_small_batches_keep_perop_diff_records():
    """With the product threshold in place, interactive-size batches keep
    their per-op edit records (cursor maintenance depends on them)."""
    a = _base()
    b = am.merge(am.init("B"), a)
    b2 = am.change(b, lambda x: x["t"].insert_at(0, "z"))
    missing = _missing(b2, a._doc.opset.clock)
    _, diffs = a._doc.opset.add_changes(missing, text_batch=True)
    assert diffs and all(d["action"] != "batch" for d in diffs)


# ---------------------------------------------------------------------------
# host plane: hypothesis driver


_instr = st.tuples(
    st.sampled_from("AB"),
    st.sampled_from(("ins", "burst", "del", "set", "pull")),
    st.integers(min_value=0, max_value=10 ** 6),   # position selector
    st.text(alphabet="abcdefgh ", min_size=1, max_size=12),
) if HAVE_HYPOTHESIS else None


def _run_divergent(instrs):
    """Execute an instruction program over two replicas; every text op is
    interpreted against current state so programs are valid by
    construction. `pull` merges A into B (keeping divergence one-sided so
    the final A<-B batch is large)."""
    a = _base("seed text ")
    reps = {"A": a, "B": am.merge(am.init("B"), a)}
    for actor, kind, pos, txt in instrs:
        d = reps[actor]
        n = len(d["t"])
        if kind in ("ins", "burst"):
            chars = txt if kind == "burst" else txt[:1]
            p = pos % (n + 1)
            d = am.change(d, lambda x, p=p, c=chars: x["t"].insert_at(
                p, *c))
        elif kind == "del" and n:
            p = pos % n
            k = min(1 + len(txt) % 5, n - p)
            d = am.change(d, lambda x, p=p, k=k: x["t"].delete_at(p, k))
        elif kind == "set" and n:
            p = pos % n
            d = am.change(d, lambda x, p=p, c=txt[0]: x["t"].__setitem__(
                p, c))
        elif kind == "pull":
            d = am.merge(d, reps["A"]) if actor == "B" else d
        reps[actor] = d
    return reps["A"], reps["B"]


if HAVE_HYPOTHESIS:

    @settings(max_examples=40, deadline=None,
              suppress_health_check=[HealthCheck.too_slow])
    @given(st.lists(_instr, min_size=1, max_size=25))
    def test_property_span_merge_equals_perop_replay(span_plane, instrs):
        a, b = _run_divergent(instrs)
        # state parity on the merge batch itself
        _merge_both_ways(a, b)
        # byte-identical convergence across replicas, both merge orders,
        # through the full frontend (span plane engaged on both sides)
        m1, m2 = am.merge(a, b), am.merge(b, a)
        assert m1["t"].join() == m2["t"].join()
        assert am.equals(m1, m2)
        # and against the per-op ground truth
        missing = _missing(b, a._doc.opset.clock)
        o_ref, _ = a._doc.opset.add_changes(missing)
        _, vals, _ = _text_state(o_ref)
        assert m1["t"].join() == "".join(str(v) for v in vals)

    # the span_plane fixture is applied manually for @given compatibility
    test_property_span_merge_equals_perop_replay = pytest.mark.usefixtures(
        "span_plane")(test_property_span_merge_equals_perop_replay)


SEEDED_PROGRAMS = [7, 23, 1031, 4242]


@pytest.mark.parametrize("seed", SEEDED_PROGRAMS)
def test_seeded_divergent_histories(span_plane, seed):
    """Deterministic regression drivers over the same instruction space as
    the hypothesis property (failures there should be frozen here)."""
    rng = random.Random(seed)
    instrs = [(rng.choice("AB"),
               rng.choice(("ins", "burst", "del", "set", "pull")),
               rng.randrange(10 ** 6),
               "".join(rng.choice("abcdefgh ") for _ in
                       range(rng.randint(1, 12))))
              for _ in range(30)]
    a, b = _run_divergent(instrs)
    _merge_both_ways(a, b)
    m1, m2 = am.merge(a, b), am.merge(b, a)
    assert m1["t"].join() == m2["t"].join()
    assert am.equals(m1, m2)


@pytest.mark.parametrize("variant", ["delete_heavy", "paste_burst"])
def test_generator_variants_merge_through_span_plane(span_plane, variant):
    """The r8 trace variants (deletion-heavy: fragmented RLE-hostile runs;
    paste-burst: long runs) both merge span-plane ≡ per-op (the old
    insert-dominated trace flattered RLE — ISSUE r8 satellite)."""
    import sys
    sys.path.insert(0, str(__import__("pathlib").Path(
        __file__).resolve().parent.parent))
    import bench
    from automerge_tpu.core.change import coerce_change
    import json as _json

    wire, seq, max_elem, nch = bench.gen_text_load_log(
        600, seed=9, variant=variant, with_state=True)
    doc = am.load(wire)
    h1, _ = bench.gen_divergent_side(seq, max_elem, nch, "A", "C", 60,
                                     seed=1)
    h2, _ = bench.gen_divergent_side(seq, max_elem, nch, "A", "B", 60,
                                     seed=2)
    from automerge_tpu.frontend.materialize import apply_changes_to_doc
    doc1 = apply_changes_to_doc(doc, doc._doc.opset,
                                [coerce_change(c) for c in h1],
                                incremental=True)
    h2c = [coerce_change(c) for c in h2]
    metrics.reset()
    span = apply_changes_to_doc(doc1, doc1._doc.opset, h2c,
                                incremental=True)
    perop = apply_changes_to_doc(doc1, doc1._doc.opset, h2c,
                                 incremental=True, text_batch=False)
    assert span["t"].join() == perop["t"].join()
    assert metrics.snapshot().get("sync_text_batches_merged") == 1
    # full state parity, not just the visible string
    k1, v1, f1 = _text_state(span._doc.opset)
    k2, v2, f2 = _text_state(perop._doc.opset)
    assert k1 == k2 and v1 == v2 and f1 == f2


# ---------------------------------------------------------------------------
# ElemList.splice_insert


def _model_splice(keys, vals, at, ins_k, ins_v):
    return keys[:at] + list(ins_k) + keys[at:], \
        vals[:at] + list(ins_v) + vals[at:]


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_splice_insert_matches_perop_inserts(seed):
    rng = random.Random(seed)
    el = ElemList()
    keys, vals = [], []
    counter = [0]

    def fresh(k):
        out = [f"e{counter[0] + i}" for i in range(k)]
        counter[0] += k
        return out

    for step in range(40):
        at = rng.randint(0, len(keys))
        k = rng.choice([1, 2, 7, CHUNK, CHUNK + 3, 2 * CHUNK + 1])
        ins_k = fresh(k)
        ins_v = [f"v{x}" for x in ins_k]
        el.splice_insert(at, ins_k, ins_v)
        keys, vals = _model_splice(keys, vals, at, ins_k, ins_v)
        assert list(el.keys) == keys
        assert list(el.values) == vals
        # the key->position index survives the re-chunking
        probe = rng.choice(keys)
        assert el.index_of(probe) == keys.index(probe)
        if keys and rng.random() < 0.3:
            i = rng.randrange(len(keys))
            el.remove_index(i)
            keys.pop(i), vals.pop(i)


def test_splice_insert_empty_and_singleton():
    el = ElemList()
    el.splice_insert(0, [], [])
    assert len(el) == 0
    el.splice_insert(0, ["a"], [1])
    assert list(el.keys) == ["a"]
    el.splice_insert(1, ["b", "c"], [2, 3])
    assert list(el.values) == [1, 2, 3]


# ---------------------------------------------------------------------------
# Text.spans() and spans_of_elems


def test_text_spans_rle_lazy_and_eager(span_plane):
    a = _base("abc")
    b = am.merge(am.init("B"), a)
    b2 = am.change(b, lambda x: x["t"].insert_at(1, *"ZZ"))
    m = am.merge(a, b2)
    lazy = m["t"].spans()
    assert "".join(s[3] for s in lazy) == m["t"].join()
    assert all(s[2] == len(s[3]) for s in lazy)
    # runs are maximal: consecutive spans never chain
    for s1, s2 in zip(lazy, lazy[1:]):
        assert not (s1[0] == s2[0] and s1[1] + s1[2] == s2[1])
    # eager-snapshot path agrees with the lazy view path
    frozen = m["t"]
    eager = am.Text(tuple(frozen), frozen.elem_ids, frozen._object_id)
    assert eager.spans() == lazy


def test_spans_of_elems_groups_consecutive_ids():
    el = ElemList(["A:1", "A:2", "A:4", "B:5", "B:6"], list("abcde"))
    assert textspans.spans_of_elems(el, None) == [
        ("A", 1, 2), ("A", 4, 1), ("B", 5, 2)]


# ---------------------------------------------------------------------------
# engine kernels: three-way parity + end-to-end order


def _random_tables(seed, n_docs=6, max_spans=50):
    rng = np.random.default_rng(seed)
    tables = []
    for _ in range(n_docs):
        n = int(rng.integers(1, max_spans))
        rows = []
        for s in range(n):
            rows.append((int(rng.integers(1, 1 << 20)),
                         int(rng.integers(0, 1 << 20)),
                         int(rng.integers(0, 60)),
                         int(rng.integers(-1, 11)),
                         int(rng.integers(0, 1 << 15)),
                         int(rng.integers(0, 64)),
                         s))
        tables.append(rows)
    return tables


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_merge_spans_three_way_parity(seed):
    from automerge_tpu.engine.span_kernels import (
        merge_spans, merge_spans_host, sort_spans, span_rank_hash_pallas)
    from automerge_tpu.engine.pack import pack_spans

    spans = pack_spans(_random_tables(seed))
    host = merge_spans_host(spans)
    dev = {k: np.asarray(v) for k, v in merge_spans(spans).items()}
    for k in ("order", "start", "total", "hash"):
        assert np.array_equal(host[k], dev[k]), k

    sorted_spans, order = sort_spans(spans)
    starts, h, total = span_rank_hash_pallas(sorted_spans, interpret=True)
    assert np.array_equal(np.asarray(h), host["hash"])
    assert np.array_equal(np.asarray(total), host["total"])
    mask = sorted_spans[:, 0, :] > 0
    want = np.take_along_axis(host["start"], order, axis=-1)
    assert np.array_equal(np.where(mask, np.asarray(starts), 0),
                          np.where(mask, want, 0))


def test_merge_spans_empty_and_padded_tables():
    from automerge_tpu.engine.span_kernels import merge_spans_host
    from automerge_tpu.engine.pack import pack_spans

    spans = pack_spans([[], [(7, 0, 3, 0, 0, 0, 0)]])
    out = merge_spans_host(spans)
    assert out["total"].tolist() == [0, 3]
    assert out["hash"][0] == 0


def test_plan_spans_and_adaptive_router():
    from automerge_tpu.engine.dispatch import merge_spans_adaptive, plan_spans

    plan = plan_spans(2, 128)
    assert plan.backend in ("host", "device")
    metrics.reset()
    p, out = merge_spans_adaptive(_random_tables(3, n_docs=2))
    assert out["total"].shape == (2,)
    snap = metrics.snapshot()
    assert snap[f"engine_span_merges{{backend={p.backend}}}"] == 1


def test_merge_table_end_to_end_reconstructs_host_merge(span_plane):
    """Structured divergence: both sides paste bursts into known gaps of a
    common document (one shared gap, so the RGA sibling priority decides).
    The kernel's merge order over the merge_table rows must reconstruct
    EXACTLY the text the host CRDT merge produces."""
    from automerge_tpu.engine.pack import pack_spans
    from automerge_tpu.engine.span_kernels import merge_spans_host

    base_text = "The quick brown fox jumps over the lazy dog"
    n = len(base_text)
    base = _base(base_text)
    # distinct side actors so elem ids never collide with the base's
    sides = {"A2": [(4, "fast "), (20, "HIGH ")],
             "B": [(4, "very "), (n, " tonight")]}
    docs = {}
    for side, side_edits in sides.items():
        d = am.merge(am.init(side), base)
        for pos, txt in sorted(side_edits, reverse=True):
            d = am.change(d, lambda x, p=pos, t=txt: x["t"].insert_at(
                p, *t))
        docs[side] = d
    merged = am.merge(docs["A2"], docs["B"])

    # region split: the base splits at every concurrent anchor position
    anchors = sorted({p for se in sides.values() for p, _ in se} - {n, 0})
    cuts = [0] + anchors + [n]
    base_spans, gap_of = [], {0: -1}
    for i, (lo, hi) in enumerate(zip(cuts, cuts[1:])):
        base_spans.append((1, lo, hi - lo))      # origin 1 = base actor
        gap_of[hi] = i
    arank = {"A2": 1, "B": 2}   # order-isomorphic to the actor id order
    origin_of = {"A2": 2, "B": 3}
    oid = merged["t"]._object_id

    # block heads: each burst consumes consecutive elem numbers from the
    # document's max_elem (43 base chars), in the side's change order
    blocks, expansion = [], {}
    for side, side_edits in sides.items():
        obj = docs[side]._doc.opset.by_object[oid]
        nxt = n + 1
        for pos, txt in sorted(side_edits, reverse=True):
            head = nxt
            nxt += len(txt)
            # the arithmetic must agree with the real insertion table
            assert f"{side}:{head}" in obj.insertion
            blocks.append((gap_of[pos], head, arank[side],
                           [(origin_of[side], head, len(txt))]))
            expansion[(origin_of[side], head)] = txt
    for o, s, v in base_spans:
        expansion[(o, s)] = base_text[s:s + v]

    rows = textspans.merge_table(base_spans, blocks)
    spans = pack_spans([rows])
    out = merge_spans_host(spans)
    assert int(out["total"][0]) == len(merged["t"])
    # expand rows in kernel merge order -> must equal the CRDT merge
    order = out["order"][0]
    text = ""
    for slot in order.tolist():
        if spans[0, 0, slot] == 0:
            continue
        key = (int(spans[0, 1, slot]), int(spans[0, 2, slot]))
        text += expansion[key]
    assert text == merged["t"].join()
    # per-span visible starts agree with the expansion offsets
    off = 0
    for slot in order.tolist():
        if spans[0, 0, slot] == 0:
            continue
        assert int(out["start"][0, slot]) == off
        off += int(spans[0, 3, slot])


# ---------------------------------------------------------------------------
# fleet convergence + auditor


def _cols(changes):
    from automerge_tpu.native.wire import changes_to_columns
    return changes_to_columns(changes)


def test_concurrent_text_fleet_converges_and_audits_clean(span_plane):
    from automerge_tpu.sync.audit import ConvergenceAuditor
    from automerge_tpu.sync.connection import Connection
    from automerge_tpu.sync.service import EngineDocSet

    sa, sb = EngineDocSet(backend="rows"), EngineDocSet(backend="rows")
    qa, qb = [], []
    ca = Connection(sa, qa.append, wire="columnar")
    cb = Connection(sb, qb.append, wire="columnar")
    ca.open()
    cb.open()

    def pump():
        for _ in range(80):
            moved = False
            while qa:
                cb.receive_msg(qa.pop(0))
                moved = True
            while qb:
                ca.receive_msg(qb.pop(0))
                moved = True
            if not moved:
                return

    rng = random.Random(99)
    docs = [f"text{d}" for d in range(4)]
    for i, did in enumerate(docs):
        base = _base(f"doc {i} common prefix ")
        sa.apply_changes(did, _missing(base, {}))
        pump()
        b = am.merge(am.init("B"), base)
        a2, b2 = base, b
        for _ in range(rng.randint(2, 5)):
            a2 = am.change(a2, lambda x: x["t"].insert_at(
                rng.randint(0, len(x["t"])), *"from-A "))
            b2 = am.change(b2, lambda x: x["t"].insert_at(
                rng.randint(0, len(x["t"])), *"from-B "))
        sa.apply_changes(did, _missing(a2, base._doc.opset.clock))
        sb.apply_changes(did, _missing(b2, base._doc.opset.clock))
        pump()

    assert sa.hashes() == sb.hashes()
    aud = ConvergenceAuditor(sa, ca, period_s=0)
    aud.audit_once()
    pump()
    assert aud.rounds_clean == 1
    assert aud.divergences == []
    # materialized state agrees byte for byte on both replicas
    for did in docs:
        assert sa.materialize(did) == sb.materialize(did)
