"""Worker process for the device-resident multihost test (VERDICT r2 #7):
each of two OS processes runs an `EngineDocSet` — documents resident in the
columnar engine, NOT host objects — syncing over TCP with BINARY columnar
frames (`wire="columnar"`, sync/frames.py), then joins a global 8-device
mesh (jax.distributed) for one SPMD reconcile and a cross-host clock-union
collective. The reference analog being scaled: DocSet + Connection
anti-entropy (src/connection.js:58-113) over a real network transport.

Usage: python tests/multihost_resident_worker.py <pid> <coord_port> <sync_port>
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

pid = int(sys.argv[1])
coord_port = sys.argv[2]
sync_port = int(sys.argv[3])

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from automerge_tpu.parallel.multihost import (global_mesh,  # noqa: E402
                                              init_multihost,
                                              reconcile_global)

init_multihost(f"127.0.0.1:{coord_port}", num_processes=2, process_id=pid)
assert jax.device_count() == 8 and jax.local_device_count() == 4

import automerge_tpu as am  # noqa: E402
from automerge_tpu.core.change import Change, Op  # noqa: E402
from automerge_tpu.core.ids import ROOT_ID  # noqa: E402
from automerge_tpu.sync.service import EngineDocSet  # noqa: E402
from automerge_tpu.sync.tcp import (TcpSyncClient, TcpSyncServer,  # noqa: E402
                                    sync_lock)

N = 8
ACTOR = f"host{pid}"
# AMTPU_MH_BACKEND=rows runs the same protocol over the docs-minor
# streaming engine (EngineDocSet backend="rows")
_backend = os.environ.get("AMTPU_MH_BACKEND", "resident")
if _backend == "sharded":
    from automerge_tpu.sync.sharded_service import ShardedEngineDocSet
    engine = ShardedEngineDocSet(n_shards=2)
else:
    engine = EngineDocSet(backend=_backend)
for i in range(N):
    if i % 2 == pid:  # each host authors half the fleet
        d = am.change(am.init(ACTOR), lambda x, i=i: am.assign(
            x, {"n": i, "xs": [i, i + 1], "owner": ACTOR}))
        engine.add_doc(f"doc{i}")
        engine.apply_changes(
            f"doc{i}", d._doc.opset.get_missing_changes({}))

# --- phase 1: DCN sync, binary columnar frames over TCP ------------------
if pid == 0:
    link = TcpSyncServer(engine, port=sync_port, wire="columnar").start()
else:
    link = None
    for _ in range(100):
        try:
            link = TcpSyncClient(engine, "127.0.0.1", sync_port,
                                 wire="columnar").start()
            break
        except OSError:
            time.sleep(0.1)
    assert link is not None, "could not reach host 0"

deadline = time.time() + 60
while time.time() < deadline:
    if (set(engine.doc_ids) >= {f"doc{i}" for i in range(N)}
            and all(engine.clock_of(f"doc{i}").get(f"host{i % 2}", 0) > 0
                    for i in range(N))):
        break
    time.sleep(0.05)
else:
    raise AssertionError(f"[p{pid}] initial columnar sync did not converge: "
                         f"{sorted(engine.doc_ids)}")

# the other host's docs really arrived as binary frames, not JSON
assert am.metrics.snapshot().get("sync_frames_received", 0) > 0, \
    f"[p{pid}] no columnar frames received"

# concurrent edits on a shared doc: both hosts write doc0.winner straight
# into the resident engine (change assembled against the engine's clock)
with sync_lock(engine):
    clk = engine.clock_of("doc0")
    ch = Change(ACTOR, clk.get(ACTOR, 0) + 1,
                {a: s for a, s in clk.items() if a != ACTOR},
                [Op("set", ROOT_ID, key="winner", value=ACTOR)])
    engine.apply_changes("doc0", [ch])

deadline = time.time() + 60
while time.time() < deadline:
    clk = engine.clock_of("doc0")
    if all(clk.get(f"host{h}", 0) > 0 for h in (0, 1)) \
            and sum(clk.values()) >= 3:
        break
    time.sleep(0.05)
else:
    raise AssertionError(f"[p{pid}] concurrent-edit sync did not converge: "
                         f"{engine.clock_of('doc0')}")
winner = engine.materialize("doc0")["data"]["winner"]
assert winner in ("host0", "host1"), f"[p{pid}] LWW winner: {winner}"

# --- phase 2: global SPMD reconcile over the joint mesh ------------------
mesh = global_mesh()
with sync_lock(engine):
    doc_changes = [engine.missing_changes(f"doc{i}", {}) for i in range(N)]
lo, hi, local_hashes = reconcile_global(doc_changes, mesh)

from automerge_tpu.engine.batchdoc import apply_batch  # noqa: E402

_, _, ref_out = apply_batch(doc_changes)
ref = np.asarray(ref_out["hash"]).astype(np.uint32)
want = ref[lo:min(hi, N)]
assert (local_hashes[:len(want)] == want).all(), \
    f"[p{pid}] shard hash mismatch"

# the resident engine's own per-doc hashes agree with the mesh reconcile
eng_hashes = engine.hashes()
for i in range(N):
    assert np.uint32(eng_hashes[f"doc{i}"]) == ref[i], \
        f"[p{pid}] resident hash != mesh hash for doc{i}"

# --- phase 3: cross-host clock-union collective --------------------------
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from automerge_tpu.parallel.collective import global_clock_union  # noqa: E402
from automerge_tpu.parallel.mesh import DOCS_AXIS  # noqa: E402

actors = sorted({c.actor for chs in doc_changes for c in chs})
rank = {a: k for k, a in enumerate(actors)}
clocks = np.zeros((N, len(actors)), np.int32)
for i in range(N):
    for a, s in engine.clock_of(f"doc{i}").items():
        clocks[i, rank[a]] = s
sh = NamedSharding(mesh, P(DOCS_AXIS))
arr = jax.make_array_from_process_local_data(
    sh, np.ascontiguousarray(clocks[lo:hi]), global_shape=clocks.shape)
union = np.asarray(global_clock_union(arr, mesh))
want_union = clocks.max(axis=0)
assert (union == want_union).all(), f"[p{pid}] union {union} != {want_union}"
assert all(union[rank[f"host{h}"]] > 0 for h in (0, 1))

if link is not None:
    link.close()
print(f"MULTIHOST-RESIDENT-OK p{pid} union={union.tolist()}", flush=True)
