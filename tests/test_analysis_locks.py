"""lock-discipline pass tests: ABBA inversion detection, blocking calls
under a lock (direct, transitive through methods, and duck-typed engine
readbacks), the cv.wait exemption, and thread-spawn hygiene — positive
and negative fixtures, plus the no-new-findings check on the repo."""

import pathlib
import textwrap

from automerge_tpu.analysis import load_project
from automerge_tpu.analysis.lock_discipline import LockDisciplinePass

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _run(tmp_path, source, rel="automerge_tpu/sync/fix.py"):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))
    return LockDisciplinePass().run(load_project(tmp_path))


def _rules(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# lock ordering


def test_abba_inversion_flagged(tmp_path):
    findings = _run(tmp_path, '''\
        import threading

        class Node:
            def __init__(self):
                self._lock = threading.Lock()
                self._log_lock = threading.Lock()

            def a_then_b(self):
                with self._lock:
                    with self._log_lock:
                        pass

            def b_then_a(self):
                with self._log_lock:
                    with self._lock:
                        pass
        ''')
    assert _rules(findings).count("lock-order") == 1
    assert "inversion" in findings[_rules(findings).index("lock-order")] \
        .message


def test_consistent_order_not_flagged(tmp_path):
    findings = _run(tmp_path, '''\
        import threading

        class Node:
            def __init__(self):
                self._lock = threading.Lock()
                self._log_lock = threading.Lock()

            def a_then_b(self):
                with self._lock:
                    with self._log_lock:
                        pass

            def also_a_then_b(self):
                with self._lock, self._log_lock:
                    pass
        ''')
    assert "lock-order" not in _rules(findings)


def test_inversion_found_through_method_call(tmp_path):
    """b_then_a never syntactically nests the withs — the inner lock is
    taken by a method it calls while holding the outer."""
    findings = _run(tmp_path, '''\
        import threading

        class Node:
            def __init__(self):
                self._lock = threading.Lock()
                self._log_lock = threading.Lock()

            def _append(self):
                with self._lock:
                    pass

            def a_then_b(self):
                with self._lock:
                    with self._log_lock:
                        pass

            def b_then_a(self):
                with self._log_lock:
                    self._append()
        ''')
    assert "lock-order" in _rules(findings)


# ---------------------------------------------------------------------------
# blocking under a lock


def test_socket_recv_under_lock_flagged(tmp_path):
    findings = _run(tmp_path, '''\
        import threading

        class Peer:
            def __init__(self, sock):
                self._lock = threading.Lock()
                self.sock = sock

            def pump(self):
                with self._lock:
                    return self.sock.recv(4096)
        ''')
    assert "block-under-lock" in _rules(findings)


def test_recv_outside_lock_not_flagged(tmp_path):
    findings = _run(tmp_path, '''\
        import threading

        class Peer:
            def __init__(self, sock):
                self._lock = threading.Lock()
                self.sock = sock

            def pump(self):
                data = self.sock.recv(4096)
                with self._lock:
                    self.buf = data
        ''')
    assert "block-under-lock" not in _rules(findings)


def test_thread_join_under_lock_flagged_str_join_not(tmp_path):
    findings = _run(tmp_path, '''\
        import threading

        class Owner:
            def __init__(self):
                self._lock = threading.Lock()
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="w")

            def stop(self):
                with self._lock:
                    self._thread.join()         # blocking under lock
                    return ", ".join(["a"])     # string join: fine
        ''')
    assert _rules(findings).count("block-under-lock") == 1


def test_cv_wait_on_held_condition_exempt_event_wait_not(tmp_path):
    findings = _run(tmp_path, '''\
        import threading

        class Monitor:
            def __init__(self):
                self._cv = threading.Condition()
                self._stop = threading.Event()
                self._lock = threading.Lock()

            def park(self):
                with self._cv:
                    self._cv.wait(timeout=1.0)   # releases _cv: fine

            def bad(self):
                with self._lock:
                    self._stop.wait(1.0)         # holds _lock: flagged
        ''')
    assert _rules(findings).count("block-under-lock") == 1


def test_device_readback_under_lock_flagged(tmp_path):
    """The r5 stall class: a duck-typed engine hash read under the
    service lock."""
    findings = _run(tmp_path, '''\
        import threading

        class Service:
            def __init__(self, engine):
                self._lock = threading.Lock()
                self._engine = engine

            def hash_table(self):
                with self._lock:
                    return self._engine.hashes()
        ''')
    assert "block-under-lock" in _rules(findings)
    msg = findings[_rules(findings).index("block-under-lock")].message
    assert "r5" in msg


def test_transitive_block_through_module_function(tmp_path):
    findings = _run(tmp_path, '''\
        import threading

        def push(sock, data):
            sock.sendall(data)

        class Peer:
            def __init__(self, sock):
                self._send_lock = threading.Lock()
                self.sock = sock

            def send(self, data):
                with self._send_lock:
                    push(self.sock, data)
        ''')
    assert "block-under-lock" in _rules(findings)


def test_super_call_reaches_base_class_footprint(tmp_path):
    """super().m() must resolve to the BASE method (the override calling
    it would be skipped by Python too) — the LockedConnection pattern:
    a lock wrapper holding its lock across the base implementation."""
    findings = _run(tmp_path, '''\
        import threading
        import time

        class Base:
            def step(self):
                time.sleep(1.0)

        class Locked(Base):
            def __init__(self):
                self._lock = threading.Lock()

            def step(self):
                with self._lock:
                    super().step()
        ''')
    assert "block-under-lock" in _rules(findings)


def test_nested_thread_target_not_attributed_to_spawner(tmp_path):
    """A closure spawned as a Thread target runs on ANOTHER thread: its
    blocking calls must not make the spawning method look blocking to
    callers that hold a lock."""
    findings = _run(tmp_path, '''\
        import threading

        class Owner:
            def __init__(self, sock):
                self._lock = threading.Lock()
                self.sock = sock

            def spawn(self):
                def worker():
                    self.sock.recv(4096)     # runs on the worker thread
                t = threading.Thread(target=worker, daemon=True, name="w")
                t.start()

            def guarded(self):
                with self._lock:
                    self.spawn()             # spawn itself never blocks
        ''')
    assert "block-under-lock" not in _rules(findings)


def test_audit_serving_readback_is_engine_read(tmp_path):
    findings = _run(tmp_path, '''\
        import threading

        class Conn:
            def __init__(self, ds):
                self._lock = threading.Lock()
                self.ds = ds

            def serve(self, msg):
                with self._lock:
                    return self.ds.audit_state()   # full hash fan-out
        ''')
    assert "block-under-lock" in _rules(findings)


# ---------------------------------------------------------------------------
# thread hygiene


def test_thread_without_daemon_or_name_flagged(tmp_path):
    findings = _run(tmp_path, '''\
        import threading

        def spawn():
            t = threading.Thread(target=print)
            t.start()
            return t
        ''')
    rules = _rules(findings)
    assert "thread-daemon" in rules
    assert "thread-name" in rules


def test_named_daemon_thread_clean(tmp_path):
    findings = _run(tmp_path, '''\
        import threading

        def spawn():
            t = threading.Thread(target=print, daemon=True, name="amtpu-x")
            t.start()
            return t
        ''')
    assert findings == []


def test_nondaemon_thread_needs_a_join(tmp_path):
    flagged = _run(tmp_path, '''\
        import threading

        def spawn():
            t = threading.Thread(target=print, daemon=False, name="x")
            t.start()
        ''')
    assert "thread-join" in _rules(flagged)


def test_nondaemon_thread_with_join_clean(tmp_path):
    findings = _run(tmp_path, '''\
        import threading

        def spawn_and_wait():
            t = threading.Thread(target=print, daemon=False, name="x")
            t.start()
            t.join()
        ''')
    assert "thread-join" not in _rules(findings)


def test_out_of_scope_modules_ignored(tmp_path):
    findings = _run(tmp_path, '''\
        import threading

        def spawn():
            threading.Thread(target=print).start()
        ''', rel="automerge_tpu/engine/fix.py")
    assert findings == []


# ---------------------------------------------------------------------------
# the real repo: everything is fixed or baselined


def test_repo_lock_findings_are_all_baselined():
    from automerge_tpu.analysis import Baseline
    from automerge_tpu.analysis.core import BASELINE_NAME, run_passes
    proj = load_project(ROOT)
    findings = run_passes(proj, [LockDisciplinePass()])
    baseline = Baseline.load(ROOT / BASELINE_NAME)
    _, new, _ = baseline.split(findings)
    assert not new, "new lock-discipline findings:\n" + "\n".join(
        f.render() for f in new)


def test_repo_tcp_threads_are_named():
    """The PR's triage fixes stay fixed: the tcp reader/accept threads
    carry amtpu- names the flight recorder can key on."""
    src = (ROOT / "automerge_tpu" / "sync" / "tcp.py").read_text()
    assert "amtpu-tcp-read" in src
    assert "amtpu-tcp-accept" in src
