"""Hypothesis fuzz for causally-stable compaction (engine/compaction.py).

Random multi-replica histories (text insert/delete, map sets, deletes,
random gossip merges) are delivered to a rows-backend EngineDocSet in a
random causally-valid global order, interleaved with random TRUE peer-clock
advertisements (clocks the replica actually held at some earlier point) and
compactions at the service-computed floor. Invariants checked at every
step, which the hand-written tests in test_compaction.py pin only for
specific topologies:

- compaction NEVER changes the convergence hash (visible-state purity);
- after every delivery checkpoint the engine hash equals the from-scratch
  oracle over exactly the delivered (causally-closed) prefix — including
  deliveries that anchor inserts at tombstones which compaction was
  entitled to keep or ghost;
- reclaim statistics are monotone (never grows ops/elems);
- the final state matches the fully-merged reference document, text
  content included.

Soundness of the harness: advertised clocks are snapshots the peer really
had, and the service floor is the Wuu-Bernstein causal floor lowered by
those adverts — so every remaining delivery conforms by construction, the
same guarantee real Connection traffic provides. Deep run:
AMTPU_FUZZ_EXAMPLES=400 python -m pytest tests/test_hypothesis_compaction.py
"""

import os

import numpy as np
import pytest

try:
    from hypothesis import HealthCheck, given, settings
    from hypothesis import strategies as st
except ImportError:  # pragma: no cover
    pytest.skip("hypothesis unavailable", allow_module_level=True)

import automerge_tpu as am
from automerge_tpu.sync.service import EngineDocSet

from tests.test_rows_service import oracle_hash

ACTORS = ("A", "B", "C")

_EXAMPLES = int(os.environ.get("AMTPU_FUZZ_EXAMPLES", "25"))

# One step of the concurrent edit program. Interpreted defensively against
# replica state so every generated program is valid by construction.
_instr = st.tuples(
    st.sampled_from(ACTORS),
    st.sampled_from(("text_ins", "text_ins", "text_del", "set", "del",
                     "merge_from")),
    st.integers(min_value=0, max_value=30),
    st.integers(min_value=0, max_value=5),
)


def _clock_of(doc):
    clk: dict[str, int] = {}
    for c in doc._doc.opset.get_missing_changes({}):
        if c.seq > clk.get(c.actor, 0):
            clk[c.actor] = c.seq
    return clk


def _run_program(instrs):
    """Execute the program over replicas; returns (merged doc, per-actor
    list of clock snapshots the replica held during its life)."""
    reps = {a: am.change(am.init(a), lambda x: x.__setitem__(
        "t", am.Text())) if a == "A" else am.init(a) for a in ACTORS}
    # everyone starts from A's text-bearing root so the object ids agree
    base = reps["A"]
    reps = {a: (base if a == "A" else am.merge(reps[a], base))
            for a in ACTORS}
    snaps = {a: [_clock_of(reps[a])] for a in ACTORS}
    for (actor, kind, pos, val) in instrs:
        d = reps[actor]
        if kind == "text_ins":
            d = am.change(d, lambda x, pos=pos, val=val: x["t"].insert_at(
                min(pos, len(x["t"])), chr(97 + (pos + val) % 26)))
        elif kind == "text_del":
            d = am.change(d, lambda x, pos=pos: (
                x["t"].delete_at(pos % len(x["t"]))
                if len(x["t"]) else x.__setitem__("noop", 1)))
        elif kind == "set":
            d = am.change(d, lambda x, pos=pos, val=val: x.__setitem__(
                f"f{val}", pos))
        elif kind == "del":
            key = f"f{val}"
            if key in d:
                d = am.change(d, lambda x, key=key: x.__delitem__(key))
            else:
                d = am.change(d, lambda x, val=val: x.__setitem__(
                    f"f{val}", -1))
        elif kind == "merge_from":
            src = ACTORS[val % len(ACTORS)]
            if src != actor:
                d = am.merge(d, reps[src])
        reps[actor] = d
        snaps[actor].append(_clock_of(d))
    merged = reps["A"]
    for a in ACTORS[1:]:
        merged = am.merge(merged, reps[a])
    return merged, snaps


@settings(max_examples=_EXAMPLES, deadline=None,
          suppress_health_check=[HealthCheck.too_slow,
                                 HealthCheck.data_too_large])
@given(st.lists(_instr, min_size=4, max_size=40), st.data())
def test_compaction_invariants_under_random_delivery(instrs, data):
    _run_fuzz_scenario(instrs, data, archive=False)


@settings(max_examples=_EXAMPLES, deadline=None,
          suppress_health_check=[HealthCheck.too_slow,
                                 HealthCheck.data_too_large])
@given(st.lists(_instr, min_size=4, max_size=40), st.data())
def test_compaction_and_log_horizon_under_random_delivery(instrs, data):
    """The same invariants with log-horizon archival in the event mix:
    row compaction (device) and log truncation (host) interleave with
    delivery and peer adverts; hash stays invariant, the delivered-prefix
    oracle parity holds, and a fresh observer reconstructs everything
    through the archive cold path at the end."""
    _run_fuzz_scenario(instrs, data, archive=True)


def _run_fuzz_scenario(instrs, data, archive: bool):
    if archive:
        import shutil
        import tempfile
        root = tempfile.mkdtemp(prefix="amtpu-fuzz-arch-")
        try:
            _run_fuzz_body(instrs, data, archive, root)
        finally:
            shutil.rmtree(root, ignore_errors=True)
    else:
        _run_fuzz_body(instrs, data, archive, None)


def _run_fuzz_body(instrs, data, archive: bool, root):
    merged, snaps = _run_program(instrs)
    all_changes = merged._doc.opset.get_missing_changes({})

    e = EngineDocSet(backend="rows",
                     **({"log_archive_dir": root} if archive else {}))
    rset = e._resident

    delivered: list = []
    delivered_clock: dict[str, int] = {}
    pending = list(all_changes)
    compactions = 0

    def ready(c):
        if c.seq != delivered_clock.get(c.actor, 0) + 1:
            return False
        return all(delivered_clock.get(a, 0) >= s
                   for a, s in (c.deps or {}).items())

    while pending:
        # deliver a random batch of causally-ready changes
        rd = [c for c in pending if ready(c)]
        assert rd, "harness bug: no ready change but pending nonempty"
        picks = data.draw(st.lists(
            st.integers(min_value=0, max_value=len(rd) - 1),
            min_size=1, max_size=min(4, len(rd)), unique=True),
            label="delivery batch")
        # everything in rd was ready at draw time and delivering one ready
        # change never un-readies another; per-actor seq order still holds
        # because only one change per actor can be ready at once
        batch = [rd[k] for k in sorted(picks)]
        for c in batch:
            e.apply_changes("doc", [c])
            delivered.append(c)
            delivered_clock[c.actor] = c.seq
            pending.remove(c)

        actions = ("none", "none", "advert", "compact", "check") \
            + (("archive",) if archive else ())
        action = data.draw(st.sampled_from(actions), label="action")
        if action == "archive" and "doc" in rset.doc_index:
            h_before = np.uint32(e.hashes()["doc"])
            e.archive_logs(["doc"])
            assert np.uint32(e.hashes()["doc"]) == h_before, \
                "archival moved the convergence hash"
        elif action == "advert":
            a = data.draw(st.sampled_from(ACTORS), label="peer")
            snap = data.draw(st.sampled_from(snaps[a]), label="snap")
            e.note_peer_clock(f"peer-{a}", "doc", snap)
        elif action == "compact" and "doc" in rset.doc_index:
            i = rset.doc_index["doc"]
            h_before = np.uint32(e.hashes()["doc"])
            floor = e._compaction_floor_locked("doc")
            stats = rset.compact({"doc": floor})["doc"]
            compactions += 1
            assert stats["ops_after"] <= stats["ops_before"]
            assert stats["elems_after"] <= stats["elems_before"]
            assert np.uint32(e.hashes()["doc"]) == h_before, \
                "compaction moved the convergence hash"
        elif action == "check" and delivered:
            assert np.uint32(e.hashes()["doc"]) == oracle_hash(delivered), \
                "delivered-prefix hash parity broke"

    # everything delivered: full parity with the merged reference doc
    assert np.uint32(e.hashes()["doc"]) == oracle_hash(all_changes)
    final = e.materialize("doc")["data"]
    assert "".join(final["t"]) == "".join(merged["t"])
    for k, v in merged.items():
        if k != "t":
            assert final[k] == v, (k, final[k], v)

    # one final compaction at the unrestricted own-clock floor must hold
    # parity too (single-user editor posture)
    i = rset.doc_index["doc"]
    h = np.uint32(e.hashes()["doc"])
    rset.compact({"doc": dict(rset.tables[i].clock)})
    assert np.uint32(e.hashes()["doc"]) == h
    assert "".join(e.materialize("doc")["data"]["t"]) == \
        "".join(merged["t"])

    if archive:
        # a brand-new observer reconstructs the full document through the
        # archive cold path (missing_changes = cold prefix + RAM tail)
        fresh = am.apply_changes(am.init("obs"),
                                 list(e.missing_changes("doc", {})))
        assert "".join(fresh["t"]) == "".join(merged["t"])
        for k, v in merged.items():
            if k != "t":
                assert fresh[k] == v, (k, fresh[k], v)
