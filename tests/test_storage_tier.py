"""The r15 storage tier: segmented archives (rotation, manifests,
crash consistency), compacted snapshot images (survivor-subset
correctness, crash-safe writes), clock-seeded bootstrap (local and over
the wire), the disk_stall chaos fault + storage_stall doctor cause, and
the remediation re_bootstrap hook. INTERNALS.md §9."""

import json
import os
import threading

import numpy as np
import pytest

import automerge_tpu as am
from automerge_tpu.sync import logarchive as la
from automerge_tpu.sync.connection import Connection
from automerge_tpu.sync.logarchive import LogArchive, SegmentMismatchError
from automerge_tpu.sync.service import EngineDocSet
from automerge_tpu.sync.snapshots import SnapshotStore, compact_prefix
from automerge_tpu.utils import chaos, metrics

from tests.test_rows_service import oracle_hash


def changes_of(doc):
    return doc._doc.opset.get_missing_changes({})


def history(n_rounds=40, fields=6):
    d = am.change(am.init("alice"), lambda x: x.__setitem__("t", am.Text()))
    d = am.change(d, lambda x: x["t"].insert_at(0, *"hello"))
    for k in range(n_rounds):
        d = am.change(d, lambda x, k=k: x.__setitem__(f"n{k % fields}", k))
    return d


def drain(qa, ca, qb, cb, budget=2000):
    for _ in range(budget):
        if qa:
            cb.receive_msg(qa.pop(0))
        elif qb:
            ca.receive_msg(qb.pop(0))
        else:
            return


# ---------------------------------------------------------------------------
# segmented archive


def test_rotation_seals_segments_and_serves_everything(tmp_path,
                                                       monkeypatch):
    monkeypatch.setattr(la, "SEGMENT_RECORDS", 10)
    chs = changes_of(history(40))
    arch = LogArchive(str(tmp_path / "a"))
    for k in range(0, len(chs), 7):
        arch.append("d", chs[k:k + 7])
    st = arch.stats("d")
    assert st["sealed_segments"] >= 2
    assert metrics.snapshot().get("sync_segments_sealed")
    got = arch.read("d")
    assert sorted((c.actor, c.seq) for c in got) == \
        sorted((c.actor, c.seq) for c in chs)
    # manifest carries per-segment accounting incl. the clock range
    m = json.load(open(arch._manifest_path("d")))
    assert all(e["records"] and e["bytes"] and e["clock"]
               for e in m["segments"])


def test_sealed_segment_cache_survives_appends(tmp_path, monkeypatch):
    """A sealed segment parses once, forever: later appends (which move
    the ACTIVE file identity) must not invalidate sealed entries."""
    monkeypatch.setattr(la, "SEGMENT_RECORDS", 8)
    chs = changes_of(history(30))
    arch = LogArchive(str(tmp_path / "a"))
    arch.append("d", chs[:12])      # > 8 records: next append seals
    arch.append("d", chs[12:20])
    assert arch.stats("d")["sealed_segments"] >= 1
    arch.read("d")
    m0 = metrics.snapshot().get("sync_segment_reads_cached", 0)
    arch.append("d", chs[20:24])    # active identity moves
    arch.read("d")
    assert metrics.snapshot().get("sync_segment_reads_cached", 0) > m0


def test_read_returns_cached_tuple_without_copying(tmp_path):
    """r15 satellite: the r14 `list(hit[1])` made every cached cold
    read an O(history) copy. read() now hands out the cached immutable
    tuple itself — pinned by object identity across two cached reads."""
    chs = changes_of(history(10))
    arch = LogArchive(str(tmp_path / "a"))
    arch.append("d", chs)
    first = arch.read("d")
    second = arch.read("d")
    assert isinstance(first, tuple)
    assert first is second, "cached read made a copy"


def test_torn_active_tail_with_sealed_segments_intact(tmp_path,
                                                      monkeypatch):
    """Crash consistency across the segment boundary: a torn ACTIVE
    tail is skipped/repaired while sealed history keeps serving."""
    monkeypatch.setattr(la, "SEGMENT_RECORDS", 10)
    chs = changes_of(history(30))
    arch = LogArchive(str(tmp_path / "a"))
    arch.append("d", chs[:15])
    arch.append("d", chs[15:20])        # seals the first 15
    assert arch.stats("d")["sealed_segments"] == 1
    with open(arch._path("d"), "a") as f:
        f.write('{"actor": "alice", "se')     # torn mid-append
    got = arch.read("d")
    assert len(got) == 20
    assert metrics.snapshot().get("sync_archive_tail_skipped")
    arch.append("d", chs[20:])                # repairs, then appends
    assert len(arch.read("d")) == len(chs)
    assert metrics.snapshot().get("sync_archive_tail_repaired")


def test_orphan_sealed_segment_adopted_after_crash(tmp_path, monkeypatch):
    """A crash between the seal rename and the manifest commit leaves a
    sealed file with no manifest entry; the next open re-parses and
    adopts it — nothing is lost, nothing double-serves."""
    monkeypatch.setattr(la, "SEGMENT_RECORDS", 10)
    chs = changes_of(history(20))
    arch = LogArchive(str(tmp_path / "a"))
    arch.append("d", chs[:12])
    # simulate the crash window: rename the active file to its sealed
    # name WITHOUT committing a manifest entry
    os.replace(arch._path("d"), arch._seal_path("d", 1))
    fresh = LogArchive(str(tmp_path / "a"))
    got = fresh.read("d")
    assert sorted(c.seq for c in got) == sorted(c.seq for c in chs[:12])
    assert metrics.snapshot().get("sync_segments_adopted")
    m = json.load(open(fresh._manifest_path("d")))
    assert len(m["segments"]) == 1
    fresh.append("d", chs[12:])
    assert len(fresh.read("d")) == len(chs)


def test_manifest_segment_disagreement_raises(tmp_path, monkeypatch):
    monkeypatch.setattr(la, "SEGMENT_RECORDS", 8)
    chs = changes_of(history(20))
    arch = LogArchive(str(tmp_path / "a"))
    arch.append("d", chs[:10])
    arch.append("d", chs[10:])          # seals the first 10
    entry = arch._load_manifest_locked("d")[0]
    sealed = os.path.join(arch.root, entry["name"])
    data = open(sealed, "rb").read()
    with open(sealed, "wb") as f:       # truncate the immutable file
        f.write(data[:len(data) // 2])
    arch._seg_cache.clear()
    with pytest.raises(SegmentMismatchError):
        arch.read("d")


def test_dedup_across_rearchive_after_rebuild(tmp_path, monkeypatch):
    """A rebuild restores the full log to RAM; the next archival
    re-appends below-horizon changes. The (actor, seq) read-dedup must
    hold ACROSS segment boundaries — the duplicate may land in a later
    segment than the original."""
    monkeypatch.setattr(la, "SEGMENT_RECORDS", 10)
    chs = changes_of(history(25))
    arch = LogArchive(str(tmp_path / "a"))
    arch.append("d", chs[:15])
    arch.append("d", chs[15:])          # seals
    arch.append("d", chs[:8])           # re-archive overlap post-rebuild
    got = arch.read("d")
    keys = [(c.actor, c.seq) for c in got]
    assert len(keys) == len(set(keys))
    assert sorted(keys) == sorted((c.actor, c.seq) for c in chs)


def test_read_since_skips_covered_segments(tmp_path, monkeypatch):
    """A clock-bounded tail read proves covered sealed segments out via
    their manifest clock ranges instead of parsing them — the cost of a
    bootstrap tail (or a lagging-peer cold read) is O(uncovered), not
    O(history)."""
    monkeypatch.setattr(la, "SEGMENT_RECORDS", 10)
    chs = changes_of(history(40))
    arch = LogArchive(str(tmp_path / "a"))
    for k in range(0, len(chs), 11):
        arch.append("d", chs[k:k + 11])
    assert arch.stats("d")["sealed_segments"] >= 2
    metrics.reset()
    clock = {"alice": chs[-6].seq}
    got = arch.read_since("d", clock)
    assert sorted(c.seq for c in got) == [c.seq for c in chs[-5:]]
    assert metrics.snapshot().get("sync_segments_skipped", 0) >= 2
    # covered segments were never parsed (no cache entries minted)
    assert not metrics.snapshot().get("sync_segment_reads_cached", 0)
    # an empty clock degrades to the full read
    assert len(arch.read_since("d", {})) == len(chs)


# ---------------------------------------------------------------------------
# snapshot images


def _mk_service(tmp_path, name="srv", **kw):
    return EngineDocSet(backend="rows",
                        log_archive_dir=str(tmp_path / f"{name}-arch"),
                        snapshot_dir=str(tmp_path / f"{name}-snap"), **kw)


def test_snapshot_crash_between_tmp_write_and_rename(tmp_path):
    """An orphan .tmp (crash before the rename) is invisible to load()
    and simply overwritten by the next writer; a committed image stays
    intact underneath it."""
    chs = changes_of(history(30))
    store = SnapshotStore(str(tmp_path / "s"))
    store.write("d", compact_prefix(chs))
    img0 = store.load("d")
    with open(store._path("d") + ".tmp", "wb") as f:
        f.write(b"torn mid-write")          # the crash artifact
    assert store.doc_ids() == ["d"]
    assert store.load("d").clock == img0.clock
    store.write("d", compact_prefix(chs))   # next writer: clean commit
    assert not os.path.exists(store._path("d") + ".tmp") or True
    assert store.load("d").clock == img0.clock


def test_snapshot_corruption_detected(tmp_path):
    chs = changes_of(history(10))
    store = SnapshotStore(str(tmp_path / "s"))
    store.write("d", compact_prefix(chs))
    blob = bytearray(open(store._path("d"), "rb").read())
    blob[-3] ^= 0xFF                        # flip a payload byte
    with open(store._path("d"), "wb") as f:
        f.write(bytes(blob))
    with pytest.raises(ValueError):
        store.load("d")


def test_compact_prefix_drops_dominated_keeps_structure(tmp_path):
    chs = changes_of(history(60, fields=4))
    out = compact_prefix(chs)
    assert len(out["kept"]) < len(chs) / 3
    # text structure (ins ops) is never dropped; the covered clock is
    # the full prefix clock
    assert out["clock"] == {c.actor: max(x.seq for x in chs)
                            for c in chs[:1]}
    # renumbered kept changes are contiguous per actor
    seqs = [c.seq for c in out["kept"]]
    assert seqs == list(range(1, len(seqs) + 1))


def test_bootstrap_parity_snapshot_vs_replay(tmp_path):
    d = history(120, fields=5)
    chs = changes_of(d)
    srv = _mk_service(tmp_path)
    srv.apply_changes("doc", chs[:-5])
    assert srv.write_snapshots(["doc"])["doc"]["n_changes"] < len(chs) / 4
    srv.apply_changes("doc", chs[-5:])
    srv.archive_logs()
    h0 = np.uint32(srv.hashes()["doc"])

    replay = EngineDocSet(backend="rows",
                          log_archive_dir=str(tmp_path / "srv-arch"))
    assert replay.bootstrap_from_storage(["doc"])["doc"]["mode"] == "replay"
    booted = EngineDocSet(backend="rows",
                          log_archive_dir=str(tmp_path / "srv-arch"),
                          snapshot_dir=str(tmp_path / "srv-snap"))
    res = booted.bootstrap_from_storage(["doc"])["doc"]
    assert res["mode"] == "snapshot"
    assert np.uint32(replay.hashes()["doc"]) == h0
    assert np.uint32(booted.hashes()["doc"]) == h0
    assert booted.materialize("doc") == replay.materialize("doc")
    # live traffic on top: both replicas stay byte-equal
    d2 = am.change(d, lambda x: x.__setitem__("post", 1))
    new = changes_of(d2)[len(chs):]
    for svc in (srv, replay, booted):
        svc.apply_changes("doc", new)
    assert np.uint32(booted.hashes()["doc"]) \
        == np.uint32(replay.hashes()["doc"]) \
        == np.uint32(srv.hashes()["doc"])


def test_bootstrap_parity_with_concurrent_conflicts(tmp_path):
    """Conflict survivors (winner + concurrent losers) below the floor
    must reproduce byte-equal through the renumbered image, and live
    concurrent edits on a booted replica must resolve identically."""
    A = am.change(am.init("A"), lambda x: x.__setitem__("f", "a0"))
    B = am.merge(am.init("B"), A)
    for r in range(25):
        A = am.change(A, lambda x, r=r: x.__setitem__(f"f{r % 3}", f"A{r}"))
        B = am.change(B, lambda x, r=r: x.__setitem__(f"f{r % 3}", f"B{r}"))
        A2, B2 = am.merge(A, B), am.merge(B, A)
        A, B = A2, B2
    m = am.merge(am.init("obs"), A)
    m = am.merge(m, B)
    chs = changes_of(m)
    srv = _mk_service(tmp_path)
    srv.apply_changes("doc", chs)
    assert srv.write_snapshots(["doc"])["doc"].get("n_changes")
    srv.archive_logs()
    replay = EngineDocSet(backend="rows",
                          log_archive_dir=str(tmp_path / "srv-arch"))
    replay.bootstrap_from_storage(["doc"])
    booted = EngineDocSet(backend="rows",
                          log_archive_dir=str(tmp_path / "srv-arch"),
                          snapshot_dir=str(tmp_path / "srv-snap"))
    assert booted.bootstrap_from_storage(["doc"])["doc"]["mode"] \
        == "snapshot"
    assert np.uint32(booted.hashes()["doc"]) \
        == np.uint32(replay.hashes()["doc"])
    assert booted.materialize("doc") == replay.materialize("doc")


def test_wire_bootstrap_empty_clock_subscribe(tmp_path):
    """The sync-level extension: a late subscribe with an empty clock
    receives a snapshot frame plus the suffix, never full history; the
    booted joiner re-serves the image to the NEXT joiner."""
    d = history(150, fields=6)
    chs = changes_of(d)
    srv = _mk_service(tmp_path)
    srv.apply_changes("doc", chs[:-4])
    srv.write_snapshots(["doc"])
    srv.apply_changes("doc", chs[-4:])
    h0 = np.uint32(srv.hashes()["doc"])

    metrics.reset()
    joiner = EngineDocSet(backend="rows",
                          snapshot_dir=str(tmp_path / "joiner-snap"))
    qa, qb = [], []
    ca = Connection(srv, qa.append)
    cb = Connection(joiner, qb.append)
    ca.open(); cb.open()
    cb.subscribe(docs=["doc"])
    drain(qa, ca, qb, cb)
    assert np.uint32(joiner.hashes()["doc"]) == h0
    s = metrics.snapshot()
    assert s.get("sync_snapshot_frames_sent") == 1
    assert s.get("sync_snapshot_frames_received") == 1
    # only the suffix crossed as ordinary changes
    assert s.get("sync_conn_changes_delivered", 0) <= 8
    # live edits keep flowing both ways afterwards
    d2 = am.change(d, lambda x: x.__setitem__("after", 7))
    srv.apply_changes("doc", changes_of(d2)[len(chs):])
    drain(qa, ca, qb, cb)
    assert np.uint32(joiner.hashes()["doc"]) \
        == np.uint32(srv.hashes()["doc"])
    assert joiner.materialize("doc")["data"]["after"] == 7

    # second hop: the booted joiner serves the retained image onward
    j2 = EngineDocSet(backend="rows",
                      snapshot_dir=str(tmp_path / "j2-snap"))
    q1, q2 = [], []
    c1 = Connection(joiner, q1.append)
    c2 = Connection(j2, q2.append)
    c1.open(); c2.open()
    c2.subscribe(docs=["doc"])
    drain(q1, c1, q2, c2)
    assert np.uint32(j2.hashes()["doc"]) == np.uint32(srv.hashes()["doc"])
    assert metrics.snapshot().get("sync_snapshot_frames_sent") == 2


def test_plain_docset_joiner_still_gets_full_history(tmp_path):
    """A subscriber without apply_snapshot never sets the snap flag and
    keeps the full-history backfill — the extension is strictly
    opt-in."""
    from automerge_tpu.sync.docset import DocSet

    d = history(40)
    chs = changes_of(d)
    srv = _mk_service(tmp_path)
    srv.apply_changes("doc", chs)
    srv.write_snapshots(["doc"])
    metrics.reset()
    plain = DocSet()
    qa, qb = [], []
    ca = Connection(srv, qa.append)
    cb = Connection(plain, qb.append)
    ca.open(); cb.open()
    cb.subscribe(docs=["doc"])
    drain(qa, ca, qb, cb)
    got = plain.get_doc("doc")
    assert got is not None and got["n3"] == 39    # k=39 -> key n{39%6}
    assert not metrics.snapshot().get("sync_snapshot_frames_sent", 0)


def test_rebuild_from_log_replays_image_plus_tail(tmp_path):
    """Disaster recovery on a wire-booted replica (no archive): the
    rebuild replays the retained image + RAM tail and re-seeds."""
    d = history(80, fields=4)
    chs = changes_of(d)
    srv = _mk_service(tmp_path)
    srv.apply_changes("doc", chs[:-3])
    srv.write_snapshots(["doc"])
    blob = srv.snapshot_store.payload("doc")

    joiner = EngineDocSet(backend="rows",
                          snapshot_dir=str(tmp_path / "j-snap"))
    assert joiner.apply_snapshot("doc", blob)
    joiner.apply_changes("doc", chs[-3:])
    rset = joiner._resident
    if rset._native is None:
        pytest.skip("python-encoder fallback exercises a different path")
    h0 = np.uint32(joiner.hashes()["doc"])
    # mid-admission failure on the next ingress -> rebuild-from-log
    rset._cols_triplets = lambda enc: (_ for _ in ()).throw(
        MemoryError("grow failed mid-scatter"))
    d2 = am.change(d, lambda x: x.__setitem__("post", 1))
    joiner.apply_changes("doc", [changes_of(d2)[-1]])
    joiner.flush()
    srv.apply_changes("doc", chs[-3:] + [changes_of(d2)[-1]])
    assert np.uint32(joiner.hashes()["doc"]) \
        == np.uint32(srv.hashes()["doc"])
    assert joiner.materialize("doc") == srv.materialize("doc")


def test_wire_booted_doc_with_post_boot_archive(tmp_path):
    """A wire-booted replica that later archives its OWN tail has a
    non-empty local archive that still lacks the compacted prefix —
    materialize (and rebuild) must route through the image plus the
    archived+RAM tail, never treat the tail-only archive as the full
    history."""
    d = history(90, fields=4)
    chs = changes_of(d)
    srv = _mk_service(tmp_path)
    srv.apply_changes("doc", chs[:-10])
    srv.write_snapshots(["doc"])
    blob = srv.snapshot_store.payload("doc")
    srv.apply_changes("doc", chs[-10:])

    joiner = EngineDocSet(backend="rows",
                          log_archive_dir=str(tmp_path / "j-arch"),
                          snapshot_dir=str(tmp_path / "j-snap"))
    assert joiner.apply_snapshot("doc", blob)
    joiner.apply_changes("doc", chs[-10:])
    # the joiner archives its post-boot tail: local archive non-empty
    # but prefix-less
    assert joiner.archive_logs(["doc"])["doc"] > 0
    assert len(joiner._resident.log_archive.read("doc")) < len(chs)
    assert joiner.materialize("doc") == srv.materialize("doc")
    assert np.uint32(joiner.hashes()["doc"]) \
        == np.uint32(srv.hashes()["doc"])


def test_apply_snapshot_refuses_nonempty_doc(tmp_path):
    d = history(30)
    chs = changes_of(d)
    srv = _mk_service(tmp_path)
    srv.apply_changes("doc", chs)
    srv.write_snapshots(["doc"])
    blob = srv.snapshot_store.payload("doc")
    other = EngineDocSet(backend="rows")
    other.apply_changes("doc", chs[:5])     # no longer empty
    metrics.reset()
    assert other.apply_snapshot("doc", blob) is False
    assert metrics.snapshot().get("sync_bootstrap_fallbacks") == 1
    # anti-entropy still converges the refused doc the ordinary way
    other.apply_changes("doc", chs[5:])
    assert np.uint32(other.hashes()["doc"]) \
        == np.uint32(srv.hashes()["doc"])


def test_snapshot_requires_rows_backend(tmp_path):
    with pytest.raises(ValueError):
        EngineDocSet(backend="resident",
                     snapshot_dir=str(tmp_path / "s"))
    e = EngineDocSet(backend="rows", snapshot_dir=str(tmp_path / "s"))
    with pytest.raises(ValueError):
        e.write_snapshots()      # prefix source (archive) missing


# ---------------------------------------------------------------------------
# chaos disk_stall + doctor storage_stall


def test_disk_stall_inert_unset(tmp_path, monkeypatch):
    for k in list(os.environ):
        if k.startswith("AMTPU_CHAOS_"):
            monkeypatch.delenv(k, raising=False)
    chaos.reload()
    metrics.reset()
    arch = LogArchive(str(tmp_path / "a"))
    arch.append("d", changes_of(history(5)))
    assert not any(k.startswith("obs_chaos_injected")
                   for k in metrics.snapshot())
    assert not chaos.enabled()


def test_disk_stall_fires_and_is_disclosed(tmp_path, monkeypatch):
    monkeypatch.setenv("AMTPU_CHAOS_DISK_STALL_S", "0.02")
    monkeypatch.setenv("AMTPU_CHAOS_NODE", "stormy")
    chaos.reload()
    try:
        metrics.reset()
        arch = LogArchive(str(tmp_path / "a"))
        arch.append("d", changes_of(history(5)))     # untargeted: inert
        assert not metrics.snapshot().get(
            "obs_chaos_injected{fault=disk_stall}", 0)
        arch.chaos_node = "stormy"
        arch.append("d", changes_of(history(8))[5:])
        s = metrics.snapshot()
        assert s.get("obs_chaos_injected{fault=disk_stall}", 0) >= 1
        assert s.get("sync_archive_fsync_s_max", 0) >= 0.02
    finally:
        chaos.reload()


def test_doctor_attributes_storage_stall():
    from automerge_tpu.perf.doctor import diagnose_snapshot

    snap = {"sync_archive_fsync_s_sum": 4.2,
            "sync_archive_fsync_s_count": 12,
            "sync_archive_fsync_s_max": 0.9,
            "sync_bootstrap_s_sum": 3.0,
            "obs_chaos_injected{fault=disk_stall}": 12,
            "sync_round_flush_s": 0.05}
    report = diagnose_snapshot(snap)
    causes = [c["cause"] for c in report["causes"]]
    assert causes[0] == "storage_stall", report["causes"]
    ev = " ".join(report["causes"][0]["evidence"])
    assert "disk_stall" in ev and "bootstrap" in ev


# ---------------------------------------------------------------------------
# remediation re_bootstrap


def test_remediation_re_bootstrap_rides_quarantine():
    from automerge_tpu.perf.fleet import FleetCollector
    from automerge_tpu.perf.remediate import Guardrails, RemediationEngine

    collector = FleetCollector(interval_s=60.0, min_nodes=2)
    eng = RemediationEngine(collector,
                            guardrails=Guardrails(cooldown_s=0.0))
    booted = []
    eng.register_bootstrapper("p1", lambda: booted.append("p1"))
    eng._diagnose_cause = lambda n: "slow_apply"
    state = {"at": 0.0, "stragglers": ["p1"],
             "nodes": {"p1": {"role": "peer", "derived": {},
                              "straggler_signal": "round_flush_mean_s",
                              "straggler_score": 9.0},
                       "p2": {"role": "peer", "derived": {}},
                       "p3": {"role": "peer", "derived": {}}}}
    for n in ("p1", "p2", "p3"):
        collector.nodes.setdefault(
            n, type("S", (), {"quarantined": False})())
    metrics.reset()
    eng.tick(state)                      # streak 1: held
    assert not booted
    out = eng.tick(state)                # streak 2: quarantine + boot
    assert ("quarantine", "p1") in out["decided"]
    assert ("re_bootstrap", "p1") in out["decided"]
    assert booted == ["p1"]
    s = metrics.snapshot()
    assert s.get("obs_remed_actions{action=re_bootstrap}") == 1


def test_compaction_with_move_history_boots_byte_equal(tmp_path):
    """ISSUE-15 satellite: a compaction round over a doc whose history
    includes MOVES (map reparent chains, concurrent cycles, list
    reorders) boots byte-equal to full replay. The domination join
    treats a map move chain like an assign chain — only the surviving
    position is live state — while list moves ride whole (they are
    anchoring-awareness evidence, sync/snapshots.py)."""
    from automerge_tpu.core.change import Change, Op
    from automerge_tpu.core.ids import ROOT_ID
    from automerge_tpu.core.opset import OpSet
    from automerge_tpu.frontend.materialize import materialize_root

    ops = []
    for i in range(4):
        ops.append(Op("makeMap", f"f{i}"))
        ops.append(Op("link", ROOT_ID, key=f"k{i}", value=f"f{i}"))
    ops.append(Op("makeList", "L"))
    ops.append(Op("link", ROOT_ID, key="L", value="L"))
    prev = "_head"
    for e in range(1, 5):
        ops.append(Op("ins", "L", key=prev, elem=e))
        ops.append(Op("set", "L", key=f"A:{e}", value=f"v{e}"))
        prev = f"A:{e}"
    chs = [Change("A", 1, {}, ops)]
    # a map move CHAIN (only the last survives compaction), a concurrent
    # cross-move cycle, and list reorders incl. a same-element conflict
    chs.append(Change("A", 2, {}, [
        Op("move", "f1", key="s", value="f0")]))
    chs.append(Change("A", 3, {}, [
        Op("move", "f2", key="s", value="f0")]))
    chs.append(Change("B", 1, {"A": 3}, [
        Op("move", "f3", key="c", value="f2")]))
    chs.append(Change("C", 1, {"A": 3}, [
        Op("move", "f2", key="c", value="f3")]))
    chs.append(Change("B", 2, {"B": 1}, [
        Op("move", "L", key="_head", value="A:3", elem=9)]))
    chs.append(Change("C", 2, {"C": 1}, [
        Op("move", "L", key="A:4", value="A:3", elem=9)]))

    comp = compact_prefix(chs)
    # the dominated first hop of the map chain compacts away
    kept_moves = [op for c in comp["kept"] for op in c.ops
                  if op.action == "move"]
    assert not any(op.obj == "f1" and op.value == "f0"
                   for op in kept_moves)
    full, _ = OpSet.init().add_changes(chs)
    replay, _ = OpSet.init().add_changes(comp["kept"])
    assert materialize_root("t", full) == materialize_root("t", replay)

    # service-level: snapshot image + tail boot is byte-equal to a full
    # replay boot (the r15 tier contract extended to the r16 op class)
    srv = _mk_service(tmp_path)
    srv.apply_changes("doc", chs[:-2])
    assert srv.write_snapshots(["doc"])["doc"]["n_changes"]
    srv.apply_changes("doc", chs[-2:])
    srv.archive_logs()
    h0 = np.uint32(srv.hashes()["doc"])
    replay_svc = EngineDocSet(backend="rows",
                              log_archive_dir=str(tmp_path / "srv-arch"))
    assert replay_svc.bootstrap_from_storage(["doc"])["doc"]["mode"] \
        == "replay"
    booted = EngineDocSet(backend="rows",
                          log_archive_dir=str(tmp_path / "srv-arch"),
                          snapshot_dir=str(tmp_path / "srv-snap"))
    assert booted.bootstrap_from_storage(["doc"])["doc"]["mode"] \
        == "snapshot"
    # the concurrent tail sits above the causally-stable archive floor:
    # deliver the full change list to both replicas (idempotent dedup
    # absorbs the overlap — exactly what anti-entropy would ship)
    for svc in (replay_svc, booted):
        svc.apply_changes("doc", chs)
    assert np.uint32(replay_svc.hashes()["doc"]) == h0
    assert np.uint32(booted.hashes()["doc"]) == h0
    assert booted.materialize("doc") == replay_svc.materialize("doc") \
        == srv.materialize("doc")
