"""EngineDocSet(backend="rows"): the sync service running on the docs-minor
streaming engine — Connection-driven columnar sync, coalesced round-frame
ingress (batch()), re-serving lagging peers from the engine's admitted log,
and dynamic document-axis growth."""

import numpy as np

import automerge_tpu as am
from automerge_tpu.engine.batchdoc import apply_batch
from automerge_tpu.sync.connection import Connection
from automerge_tpu.sync.service import EngineDocSet


def oracle_hash(changes):
    _, _, out = apply_batch([changes])
    return np.uint32(np.asarray(out["hash"])[0])


def two_replica_trace():
    a = am.change(am.init("A"),
                  lambda d: am.assign(d, {"x": 1, "tags": ["p", "q"]}))
    b = am.merge(am.init("B"), a)
    a = am.change(a, lambda d: d.__setitem__("x", 5))
    b = am.change(b, lambda d: d["tags"].append("r"))
    merged = am.merge(a, b)
    return (a._doc.opset.get_missing_changes({}),
            b._doc.opset.get_missing_changes({}),
            merged._doc.opset.get_missing_changes({}))


def drain(qa, ca, qb, cb, rounds=30):
    for _ in range(rounds):
        moved = False
        while qa:
            cb.receive_msg(qa.pop(0))
            moved = True
        while qb:
            ca.receive_msg(qb.pop(0))
            moved = True
        if not moved:
            break


def test_rows_nodes_converge_over_columnar_wire():
    chs_a, chs_b, chs_all = two_replica_trace()
    qa, qb = [], []
    ea = EngineDocSet(backend="rows")
    eb = EngineDocSet(backend="rows")
    ca = Connection(ea, qa.append, wire="columnar")
    cb = Connection(eb, qb.append, wire="columnar")
    ea.add_doc("d")
    eb.add_doc("d")
    ca.open()
    cb.open()
    ea.apply_changes("d", chs_a)
    eb.apply_changes("d", chs_b)
    drain(qa, ca, qb, cb)
    want = oracle_hash(chs_all)
    assert np.uint32(ea.hashes()["d"]) == want
    assert np.uint32(eb.hashes()["d"]) == want
    assert ea.materialize("d") == eb.materialize("d")


def test_rows_batch_coalesces_to_one_round():
    am.metrics.reset()
    e = EngineDocSet(backend="rows")
    docs = {}
    for i in range(6):
        docs[f"d{i}"] = am.change(am.init("W"), lambda d, i=i: am.assign(
            d, {"n": i}))
    with e.batch():
        for did, doc in docs.items():
            e.apply_changes(did, doc._doc.opset.get_missing_changes({}))
    snap = am.metrics.snapshot()
    # six ingresses, ONE round applied (batched or per-round is shape-
    # dependent; the coalescing itself is what this asserts)
    assert (snap.get("rows_rounds_batched", 0)
            + snap.get("rows_rounds_fallback", 0)) == 1, snap
    for did, doc in docs.items():
        want = oracle_hash(doc._doc.opset.get_missing_changes({}))
        assert np.uint32(e.hashes()[did]) == want


def test_rows_missing_changes_reserves_lagging_peer():
    chs_a, _chs_b, _ = two_replica_trace()
    e = EngineDocSet(backend="rows")
    e.add_doc("d")
    e.apply_changes("d", chs_a)
    got = e.missing_changes("d", {})
    assert {(c.actor, c.seq) for c in got} == {(c.actor, c.seq)
                                              for c in chs_a}
    # suffix query: peer already has A:1
    got2 = e.missing_changes("d", {"A": 1})
    assert all(c.seq > 1 or c.actor != "A" for c in got2)
    clk = e.clock_of("d")
    assert clk.get("A", 0) >= 2


def test_rows_document_axis_growth():
    """Adding docs past the 128-lane pad re-layouts the rows mirror; state
    stays intact and new docs reconcile correctly."""
    e = EngineDocSet(backend="rows")
    hashes_want = {}
    with e.batch():
        for i in range(130):
            d = am.change(am.init("G"), lambda x, i=i: am.assign(
                x, {"n": i, "xs": [i]}))
            chs = d._doc.opset.get_missing_changes({})
            e.apply_changes(f"d{i}", chs)
            hashes_want[f"d{i}"] = oracle_hash(chs)
    h = e.hashes()
    for did, want in hashes_want.items():
        assert np.uint32(h[did]) == want, did
    # a later edit to an early doc still lands after the growth
    clk = e.clock_of("d0")
    from automerge_tpu.core.change import Change, Op
    from automerge_tpu.core.ids import ROOT_ID
    ch = Change("G", clk["G"] + 1, {}, (Op("set", ROOT_ID, key="n",
                                           value=999),))
    e.apply_changes("d0", [ch])
    assert e.materialize("d0")["data"]["n"] == 999


def test_many_actors_grow_clock_bands_with_parity():
    """20 actors accrete onto one doc through the rows service: each new
    actor triggers rank remap and eventually actor-capacity growth (the
    clock_op band is actors-major, so cap_actors doubling re-layouts the
    row buffer). Hash parity with the oracle must hold throughout."""
    e = EngineDocSet(backend="rows")
    e.add_doc("d")
    base = am.change(am.init("actor00"), lambda d: am.assign(
        d, {"n": 0, "xs": [1]}))
    e.apply_changes("d", base._doc.opset.get_missing_changes({}))
    merged = base
    for k in range(1, 20):
        prev_clock = dict(merged._doc.opset.clock)
        mine = am.change(am.merge(am.init(f"actor{k:02d}"), merged),
                         lambda d, k=k: d.__setitem__(f"f{k % 5}", k))
        delta = mine._doc.opset.get_missing_changes(prev_clock)
        e.apply_changes("d", delta)
        merged = mine
    want = oracle_hash(merged._doc.opset.get_missing_changes({}))
    assert np.uint32(e.hashes()["d"]) == want
    assert e._resident.cap_actors >= 20
    assert e.materialize("d")["data"]["n"] == 0
