"""Convergence parity: the columnar device engine vs the Python oracle.

This is the BASELINE.json conformance gate: the batched kernel must produce
byte-identical converged state (and equal canonical hashes) for the same
change sets, regardless of delivery order.
"""

import random

import pytest

import automerge_tpu as am
from automerge_tpu.core.change import Change
from automerge_tpu.engine.batchdoc import apply_batch, decode_doc, oracle_state


def engine_state(changes):
    encs, _, out = apply_batch([changes])
    import numpy as np
    doc_out = {k: np.asarray(v)[0] for k, v in out.items()}
    return decode_doc(encs[0], doc_out)


def engine_hash(changes):
    _, _, out = apply_batch([changes])
    import numpy as np
    return int(np.asarray(out["hash"])[0])


def all_changes(doc):
    return doc._doc.opset.get_missing_changes({})


def assert_parity(doc):
    changes = all_changes(doc)
    expected = oracle_state(doc)
    actual = engine_state(changes)
    assert actual == expected, f"\nengine: {actual}\noracle: {expected}"
    # hash must be invariant under delivery-order permutation
    h1 = engine_hash(changes)
    shuffled = list(changes)
    random.Random(0).shuffle(shuffled)
    h2 = engine_hash(shuffled)
    assert h1 == h2


class TestMapParity:
    def test_flat_map(self):
        s = am.change(am.init("A"), lambda d: am.assign(d, {"x": 1, "y": "two"}))
        assert_parity(s)

    def test_overwrite(self):
        s = am.change(am.init("A"), lambda d: d.__setitem__("x", 1))
        s = am.change(s, lambda d: d.__setitem__("x", 2))
        assert_parity(s)

    def test_delete(self):
        s = am.change(am.init("A"), lambda d: am.assign(d, {"x": 1, "y": 2}))
        s = am.change(s, lambda d: d.__delitem__("x"))
        assert_parity(s)

    def test_lww_conflict(self):
        s1 = am.change(am.init("A"), lambda d: d.__setitem__("f", "a"))
        s2 = am.change(am.init("B"), lambda d: d.__setitem__("f", "b"))
        assert_parity(am.merge(s1, s2))

    def test_three_actor_conflict(self):
        docs = [am.change(am.init(a), lambda d, a=a: d.__setitem__("f", f"from {a}"))
                for a in "ABC"]
        m = am.merge(am.merge(docs[0], docs[1]), docs[2])
        assert_parity(m)

    def test_add_wins(self):
        s1 = am.change(am.init("A"), lambda d: d.__setitem__("k", "v"))
        s2 = am.merge(am.init("B"), s1)
        s1 = am.change(s1, lambda d: d.__delitem__("k"))
        s2 = am.change(s2, lambda d: d.__setitem__("k", "w"))
        assert_parity(am.merge(s1, s2))

    def test_nested_maps(self):
        s = am.change(am.init("A"), lambda d: d.__setitem__(
            "cfg", {"ui": {"theme": "dark"}, "n": 3}))
        s = am.change(s, lambda d: d["cfg"]["ui"].__setitem__("lang", "en"))
        assert_parity(s)

    def test_value_types(self):
        s = am.change(am.init("A"), lambda d: am.assign(d, {
            "i": 42, "f": 3.5, "b": True, "b2": False, "n": None, "s": "str",
            "zero": 0}))
        assert_parity(s)


class TestListParity:
    def test_simple_list(self):
        s = am.change(am.init("A"), lambda d: d.__setitem__("xs", [1, 2, 3]))
        assert_parity(s)

    def test_list_insert_middle(self):
        s = am.change(am.init("A"), lambda d: d.__setitem__("xs", ["a", "c"]))
        s = am.change(s, lambda d: d["xs"].insert_at(1, "b"))
        assert_parity(s)

    def test_list_delete(self):
        s = am.change(am.init("A"), lambda d: d.__setitem__("xs", ["a", "b", "c"]))
        s = am.change(s, lambda d: d["xs"].delete_at(1))
        assert_parity(s)

    def test_list_set_index(self):
        s = am.change(am.init("A"), lambda d: d.__setitem__("xs", ["a", "b"]))
        s = am.change(s, lambda d: d["xs"].__setitem__(0, "A"))
        assert_parity(s)

    def test_concurrent_inserts_same_position(self):
        s1 = am.change(am.init("A"), lambda d: d.__setitem__("xs", []))
        s2 = am.merge(am.init("B"), s1)
        s1 = am.change(s1, lambda d: d["xs"].extend(["a1", "a2"]))
        s2 = am.change(s2, lambda d: d["xs"].extend(["b1", "b2"]))
        assert_parity(am.merge(s1, s2))

    def test_concurrent_insert_delete(self):
        s1 = am.change(am.init("A"), lambda d: d.__setitem__("xs", ["a", "b", "c"]))
        s2 = am.merge(am.init("B"), s1)
        s1 = am.change(s1, lambda d: d["xs"].delete_at(2))
        s2 = am.change(s2, lambda d: d["xs"].insert_at(2, "mid"))
        assert_parity(am.merge(s1, s2))

    def test_tombstone_heavy(self):
        s = am.change(am.init("A"), lambda d: d.__setitem__("xs", list(range(10))))
        for _ in range(8):
            s = am.change(s, lambda d: d["xs"].delete_at(0))
        assert_parity(s)

    def test_objects_in_lists(self):
        s = am.change(am.init("A"), lambda d: d.__setitem__(
            "cards", [{"t": "one"}, {"t": "two"}]))
        s = am.change(s, lambda d: d["cards"][0].__setitem__("done", True))
        assert_parity(s)


class TestTextParity:
    def test_text(self):
        def edit(doc):
            doc["t"] = am.Text()
            doc["t"].insert_at(0, *"hello")
        s = am.change(am.init("A"), edit)
        s = am.change(s, lambda d: d["t"].delete_at(0))
        s = am.change(s, lambda d: d["t"].insert_at(2, "X"))
        assert_parity(s)

    def test_concurrent_text(self):
        def edit(doc):
            doc["t"] = am.Text()
            doc["t"].insert_at(0, *"ab")
        s1 = am.change(am.init("A"), edit)
        s2 = am.merge(am.init("B"), s1)
        s1 = am.change(s1, lambda d: d["t"].insert_at(2, *"12"))
        s2 = am.change(s2, lambda d: d["t"].insert_at(2, *"xy"))
        assert_parity(am.merge(s1, s2))


class TestBatch:
    def test_many_docs_one_invocation(self):
        docs = []
        for i in range(16):
            s = am.change(am.init(f"actor{i:02d}"),
                          lambda d, i=i: am.assign(d, {"n": i, "xs": [i, i + 1]}))
            docs.append(s)
        batches = [all_changes(d) for d in docs]
        encs, _, out = apply_batch(batches)
        import numpy as np
        for i, doc in enumerate(docs):
            doc_out = {k: np.asarray(v)[i] for k, v in out.items()}
            assert decode_doc(encs[i], doc_out) == oracle_state(doc)

    def test_cross_replica_hash_equality(self):
        s1 = am.change(am.init("A"), lambda d: d.__setitem__("xs", ["a"]))
        s2 = am.merge(am.init("B"), s1)
        s1 = am.change(s1, lambda d: d["xs"].append("b"))
        s2 = am.change(s2, lambda d: d["xs"].insert_at(0, "z"))
        m1, m2 = am.merge(s1, s2), am.merge(s2, s1)
        assert engine_hash(all_changes(m1)) == engine_hash(all_changes(m2))


class TestFuzzConvergence:
    @pytest.mark.parametrize("seed", range(6))
    def test_random_traces(self, seed):
        rng = random.Random(seed)
        actors = ["A", "B", "C"]
        docs = {a: am.init(a) for a in actors}
        # seed shared structure
        base = am.change(docs["A"], lambda d: am.assign(
            d, {"m": {}, "xs": ["x"], "k": 0}))
        docs["A"] = base
        for a in ("B", "C"):
            docs[a] = am.merge(docs[a], base)

        def random_edit(doc, rng):
            choice = rng.random()
            if choice < 0.35:
                key = rng.choice(["k", "k2", "k3"])
                return am.change(doc, lambda d: d.__setitem__(key, rng.randint(0, 9)))
            if choice < 0.5:
                return am.change(doc, lambda d: d["m"].__setitem__(
                    rng.choice(["p", "q"]), rng.randint(0, 9)))
            if choice < 0.7:
                val = f"v{rng.randint(0, 99)}"
                pos = rng.randint(0, len(doc["xs"]))
                return am.change(doc, lambda d: d["xs"].insert_at(pos, val))
            if choice < 0.85 and len(doc["xs"]) > 0:
                pos = rng.randint(0, len(doc["xs"]) - 1)
                return am.change(doc, lambda d: d["xs"].delete_at(pos))
            if len(doc["xs"]) > 0:
                pos = rng.randint(0, len(doc["xs"]) - 1)
                return am.change(doc, lambda d: d["xs"].__setitem__(
                    pos, f"s{rng.randint(0, 99)}"))
            return doc

        for _ in range(15):
            actor = rng.choice(actors)
            docs[actor] = random_edit(docs[actor], rng)
            if rng.random() < 0.3:
                other = rng.choice([a for a in actors if a != actor])
                docs[actor] = am.merge(docs[actor], docs[other])

        final = am.merge(am.merge(docs["A"], docs["B"]), docs["C"])
        assert_parity(final)


class TestDensePathParity:
    """The EXPERIMENTAL dense one-hot kernel (demoted out of the product
    dispatch in r6 — engine/experimental_dense.py) must still agree bit
    for bit with the shipped segment kernel: this parity pin is what keeps
    it eligible for a hardware A/B when a TPU window arrives."""

    def _workload(self):
        docs = []
        for i in range(4):
            s1 = am.change(am.init("A"), lambda d, i=i: am.assign(
                d, {"n": i, "tag": f"t{i % 3}"}))
            s1 = am.change(s1, lambda d: d.__setitem__("xs", ["a", "b", "c"]))
            s2 = am.merge(am.init("B"), s1)
            s1 = am.change(s1, lambda d: d["xs"].insert_at(1, "a2"))
            s2 = am.change(s2, lambda d, i=i: am.assign(d, {"n": -i, "o": "B"}))
            s2 = am.change(s2, lambda d: d["xs"].delete_at(2))
            docs.append(am.merge(s1, s2)._doc.opset.get_missing_changes({}))
        return docs

    def test_dense_matches_segment(self):
        import numpy as np

        from automerge_tpu.engine import experimental_dense as xd

        docs = self._workload()
        # product path: apply_batch routes through kernels.apply_doc
        # (segment formulation on every backend since the r6 demotion)
        _, batch, out = apply_batch(docs)
        segment = {k: np.asarray(v) for k, v in out.items()}
        max_fids = segment["present"].shape[1]
        dense = {k: np.asarray(v) for k, v in
                 xd.reconcile_dense(batch, max_fids,
                                    host_order=True).items()}
        assert set(dense) == set(segment)
        for k in dense:
            assert np.array_equal(dense[k], segment[k]), k
