"""`perf explain` — the per-doc causal convergence debugger
(automerge_tpu/perf/explain.py): cause ranking over synthetic views,
live in-process attribution of a chaos-injected doc stall, post-mortem
reads, the doctor's doc_stall join, and the CLI contract."""

import json
import subprocess
import sys
import time

import pytest

from automerge_tpu.core.change import Change, Op
from automerge_tpu.core.ids import ROOT_ID
from automerge_tpu.perf import explain
from automerge_tpu.utils import metrics

NOW = 1_000_000.0


@pytest.fixture(autouse=True)
def _clean():
    metrics.reset()
    yield
    metrics.reset()


def _view(docs):
    return {"label": "x", "tracked": len(docs), "top_k": 128,
            "exported": len(docs), "evictions": 0,
            "aggregate": {}, "redundancy": {}, "lag": {}, "docs": docs}


def _entry(admitted=0, lag=0, behind=None, behind_since=None,
           buffered=0, peers=None):
    return {"admitted": admitted, "last_admit_at": None,
            "buffered": buffered, "lag_changes": lag, "lag_s": 0.0,
            "behind_since": behind_since, "behind_peer": behind,
            "peers": peers or {}}


def _lane(**kw):
    lane = {"advert_total": 0, "advert_clock": {}, "last_advert_at": None,
            "sent": 0, "last_send_at": None, "recv_useful": 0,
            "recv_duplicate": 0, "last_recv_at": None, "bytes_sent": 0,
            "bytes_received": 0, "drops": 0}
    lane.update(kw)
    return lane


# -- cause ranking over synthetic views -------------------------------------


def test_frame_loss_at_sender_ranks_first():
    views = {
        "Y": _view({"d": _entry(lag=3, behind="W", behind_since=NOW - 2,
                                peers={"W": _lane(advert_total=3)})}),
        "W": _view({"d": _entry(admitted=3,
                                peers={"Y": _lane(drops=5, sent=0)})}),
    }
    rep = explain.explain_doc("d", views, now=NOW)
    assert rep["causes"][0]["cause"] == "doc_frame_loss"
    assert rep["causes"][0]["node"] == "W"
    assert "DROPPED 5" in rep["causes"][0]["evidence"][0]
    assert rep["frontiers"]["Y"]["lag_s"] == 2.0


def test_epoch_buffered_and_causal_queue_causes():
    views = {"Y": _view({
        "d": _entry(admitted=1, lag=2, behind="W", behind_since=NOW - 1,
                    buffered=4,
                    peers={"W": _lane(recv_useful=3)})})}
    rep = explain.explain_doc("d", views, now=NOW)
    causes = {c["cause"]: c for c in rep["causes"]}
    assert "doc_epoch_buffered" in causes
    assert causes["doc_epoch_buffered"]["node"] == "Y"
    assert "doc_causal_queue" in causes
    assert "RECEIVED 2 more" in causes["doc_causal_queue"]["evidence"][0]


def test_in_flight_vs_stalled_connection_split_on_recency():
    fresh = {
        "Y": _view({"d": _entry(lag=2, behind="W", behind_since=NOW - 1,
                                peers={"W": _lane()})}),
        "W": _view({"d": _entry(peers={
            "Y": _lane(sent=2, last_send_at=NOW - 0.5)})}),
    }
    rep = explain.explain_doc("d", fresh, now=NOW)
    assert rep["causes"][0]["cause"] == "doc_unacked_in_flight"
    assert rep["causes"][0]["node"] == "W"

    stalled = {
        "Y": _view({"d": _entry(
            lag=2, behind="W", behind_since=NOW - 30,
            peers={"W": _lane(last_advert_at=NOW - 1,
                              last_recv_at=NOW - 30)})}),
    }
    rep = explain.explain_doc("d", stalled, now=NOW)
    assert rep["causes"][0]["cause"] == "doc_connection_stalled"
    assert "still adverts" in rep["causes"][0]["evidence"][0]


def test_never_framed_is_not_replicated():
    views = {
        "Y": _view({"d": _entry(lag=2, behind="W", behind_since=NOW - 9,
                                peers={"W": _lane()})}),
        "W": _view({"d": _entry(admitted=2,
                                peers={"Y": _lane(sent=0)})}),
    }
    rep = explain.explain_doc("d", views, now=NOW)
    assert rep["causes"][0]["cause"] == "doc_not_replicated"
    assert rep["causes"][0]["node"] == "W"


def test_converged_and_unseen_docs():
    views = {"Y": _view({"d": _entry(admitted=3)})}
    rep = explain.explain_doc("d", views, now=NOW)
    assert rep["converged"] is True
    assert rep["causes"] == []
    assert "CONVERGED" in "\n".join(explain.report_lines(rep))

    rep = explain.explain_doc("ghost", views, now=NOW)
    assert rep["seen"] is False
    assert "not present" in "\n".join(explain.report_lines(rep))


def test_same_cause_same_node_rows_merge():
    views = {
        "Y": _view({"d": _entry(lag=3, behind="W", behind_since=NOW - 2,
                                peers={"W": _lane()})}),
        "Z": _view({"d": _entry(lag=2, behind="W", behind_since=NOW - 1,
                                peers={"W": _lane()})}),
        "W": _view({"d": _entry(
            admitted=3, peers={"Y": _lane(drops=4),
                               "Z": _lane(drops=4)})}),
    }
    rep = explain.explain_doc("d", views, now=NOW)
    fl = [c for c in rep["causes"] if c["cause"] == "doc_frame_loss"]
    assert len(fl) == 1, "two receivers blaming one sender merge"
    assert len(fl[0]["evidence"]) == 2


def test_hot_docs_ranking_and_lines():
    views = {
        "Y": _view({"a": _entry(lag=5, behind="W", behind_since=NOW - 3),
                    "b": _entry(lag=1, behind="W", behind_since=NOW - 1),
                    "c": _entry()}),
    }
    rows = explain.hot_docs(views, now=NOW)
    assert [r["doc"] for r in rows] == ["a", "b"]
    assert rows[0]["lag_s"] == 3.0
    lines = "\n".join(explain.hot_lines(views))
    assert "'a' @ Y: 5 change(s)" in lines
    assert explain.hot_docs({}) == []


def test_views_asof_uses_newest_stamp():
    views = {"Y": _view({"d": _entry(
        behind_since=NOW - 10,
        peers={"W": _lane(last_advert_at=NOW)})})}
    assert explain.views_asof(views) == NOW


# -- live in-process + chaos ------------------------------------------------


def _mesh_pair(monkeypatch):
    from automerge_tpu.sync.connection import Connection
    from automerge_tpu.sync.service import EngineDocSet
    from automerge_tpu.utils import chaos
    monkeypatch.setenv("AMTPU_CHAOS_STALL_DOC", "victim")
    monkeypatch.setenv("AMTPU_CHAOS_NODE", "A")
    chaos.reload()
    a, b = EngineDocSet(backend="rows"), EngineDocSet(backend="rows")
    a._chaos_node, b._chaos_node = "A", "B"
    qa, qb = [], []
    ca = Connection(a, qa.append, wire="columnar")
    cb = Connection(b, qb.append, wire="columnar")
    ca.peer_label, cb.peer_label = "B", "A"
    a.doc_ledger.label, b.doc_ledger.label = "A", "B"
    ca.open()
    cb.open()

    def drain():
        for _ in range(50):
            if not (qa or qb):
                return
            while qa:
                cb.receive_msg(qa.pop(0))
            while qb:
                ca.receive_msg(qb.pop(0))
    return a, b, drain


def test_gather_local_attributes_injected_doc_stall(monkeypatch):
    from automerge_tpu.utils import chaos
    a, b, drain = _mesh_pair(monkeypatch)
    try:
        for s in (1, 2, 3):
            a.apply_changes("victim", [Change(
                actor="x", seq=s, deps={},
                ops=[Op("set", ROOT_ID, key="k", value=s)])])
            drain()
        views = explain.gather_local()
        rep = explain.explain_doc("victim", views, now=time.time())
        top = rep["causes"][0]
        assert (top["cause"], top["node"]) == ("doc_frame_loss", "A")
        assert rep["frontiers"]["B"]["lag_changes"] == 3
    finally:
        monkeypatch.delenv("AMTPU_CHAOS_STALL_DOC")
        monkeypatch.delenv("AMTPU_CHAOS_NODE")
        chaos.reload()
        a.close()
        b.close()


# -- post-mortem + doctor join + CLI ----------------------------------------


def _stalled_snapshot():
    return {"docledger": {"nodes": {
        "Y": _view({"d": _entry(lag=3, behind="W",
                                behind_since=NOW - 2,
                                peers={"W": _lane()})}),
        "W": _view({"d": _entry(admitted=3,
                                peers={"Y": _lane(drops=5)})}),
    }}}


def test_post_mortem_views_from_dump_and_detail(tmp_path):
    dump = dict(_stalled_snapshot())
    p = tmp_path / "dump.json"
    p.write_text(json.dumps({"reason": "test", "metrics": dump}))
    sets = explain._post_mortem_view_sets(str(p))
    assert len(sets) == 1 and sets[0][0] == "test"
    views = sets[0][1]
    assert set(views) == {"Y", "W"}
    rep = explain.explain_doc("d", views)
    assert rep["causes"][0]["cause"] == "doc_frame_loss"

    # a BENCH_DETAIL yields one view set PER CONFIG, labels verbatim —
    # decorating them would break the behind_peer sender-side join
    detail = {"configs": {"12": {"metrics": dump},
                          "11": {"metrics": {}}}}
    p2 = tmp_path / "detail.json"
    p2.write_text(json.dumps(detail))
    sets = explain._post_mortem_view_sets(str(p2))
    assert [s[0] for s in sets] == ["config 12"]
    assert set(sets[0][1]) == {"Y", "W"}
    rep = explain.explain_doc("d", sets[0][1])
    assert rep["causes"][0]["cause"] == "doc_frame_loss", (
        "the sender-side join must survive the detail post-mortem path")


# -- the trace stage-breakdown band -----------------------------------------


def _texemplar(tid, doc, crit, spans, stitched=True):
    return {"tid": tid, "doc": doc, "actor": tid.split(".")[0],
            "seq": int(tid.split(".")[1]), "role": "stitched",
            "origin": "x", "stitched": stitched, "crit_s": crit,
            "spans": spans, "meta": {}}


def test_trace_stage_band_renders_waterfall_rows():
    tsecs = {"x": {"exemplars": [
        _texemplar("A.1", "d", 0.5, [["finalize", 0.0, 0.0],
                                     ["wire", 0.01, 0.1],
                                     ["visibility", 0.11, 0.39]]),
        _texemplar("A.9", "other-doc", 9.0, [["wire", 0.0, 9.0]]),
    ]}}
    lines = explain.trace_stage_lines("d", tsecs)
    text = "\n".join(lines)
    assert "stage breakdown (sampled traces; `perf trace`):" in text
    assert "trace A.1 @ x (stitched across the wire, e2e 0.5000s):" in text
    wire_row = next(line for line in lines
                    if line.strip().startswith("wire"))
    assert "20.0%" in wire_row                  # 0.1 of 0.5
    assert "other-doc" not in text              # only this doc's traces


def test_trace_stage_band_absent_without_matching_exemplar():
    assert explain.trace_stage_lines("d", {}) == []
    tsecs = {"x": {"exemplars": [
        _texemplar("A.9", "other", 1.0, [["wire", 0.0, 1.0]])]}}
    assert explain.trace_stage_lines("d", tsecs) == []
    # an exemplar with no spans disappears the same way
    tsecs = {"x": {"exemplars": [_texemplar("A.1", "d", 0.0, [])]}}
    assert explain.trace_stage_lines("d", tsecs) == []


def test_trace_stage_band_ranks_and_caps():
    tsecs = {"x": {"exemplars": [
        _texemplar(f"A.{k}", "d", float(k), [["wire", 0.0, float(k)]],
                   stitched=False)
        for k in range(1, 5)]}}
    lines = explain.trace_stage_lines("d", tsecs, limit=2)
    # header + 2 traces x (title + 1 span row) + overflow note
    assert len(lines) == 1 + 2 * 2 + 1
    assert "trace A.4" in lines[1]              # slowest e2e first
    assert "origin-local" in lines[1]
    assert "trace A.3" in lines[3]
    assert "+2 more sampled trace(s)" in lines[5]


def test_doctor_snapshot_join_emits_doc_stall():
    from automerge_tpu.perf.doctor import diagnose_snapshot
    rep = diagnose_snapshot(_stalled_snapshot(), label="t")
    causes = {c["cause"] for c in rep["causes"]}
    assert "doc_stall" in causes
    ds = next(c for c in rep["causes"] if c["cause"] == "doc_stall")
    assert any("perf explain" in ev for ev in ds["evidence"])
    assert any("'d' @ Y" in ev for ev in ds["evidence"])


def test_doctor_trace_stage_dominant_causes():
    """The doctor's trace-plane join: a stage holding >= 30% of the
    sampled critical path (visibility excluded — read-cadence bound)
    becomes its named cause; thin sections stay silent."""
    from automerge_tpu.perf.doctor import diagnose_snapshot

    def snap(stages, done=8):
        return {"traceplane": {"nodes": {"x": {
            "label": "x", "completed": done, "stages": stages,
            "critical_path": {"count": done, "p99_s": 1.0}}}}}

    hot = snap({
        "dispatch": {"count": 8, "sum_s": 0.1, "p99_s": 0.02},
        "coalesce_wait": {"count": 8, "sum_s": 2.0, "p99_s": 0.4},
        "remote_admission": {"count": 8, "sum_s": 1.8, "p99_s": 0.3},
        "visibility": {"count": 8, "sum_s": 50.0, "p99_s": 9.0},
    })
    causes = {c["cause"]: c for c in diagnose_snapshot(hot)["causes"]}
    assert "coalesce_wait_hot" in causes
    assert "remote_admission_hot" in causes
    assert "wire_serialize_hot" not in causes
    cw = causes["coalesce_wait_hot"]
    assert any("flush governor" in ev for ev in cw["evidence"])
    assert any("perf trace" in ev for ev in cw["evidence"])
    # visibility never becomes a cause even at 90%+ of wall time
    assert not any("visibility" in c for c in causes)

    # a balanced pipeline (< 30% each) and a thin sample stay silent
    quiet = snap({st: {"count": 8, "sum_s": 1.0, "p99_s": 0.1}
                  for st in ("queue_wait", "coalesce_wait", "dispatch",
                             "wire", "remote_admission")})
    assert not any(c["cause"].endswith("_hot")
                   for c in diagnose_snapshot(quiet)["causes"])
    thin = snap({"coalesce_wait": {"count": 2, "sum_s": 5.0,
                                   "p99_s": 2.0}}, done=2)
    assert not any(c["cause"].endswith("_hot")
                   for c in diagnose_snapshot(thin)["causes"])


def test_cli_explain_contract(tmp_path):
    dump = {"reason": "test", "metrics": _stalled_snapshot()}
    p = tmp_path / "dump.json"
    p.write_text(json.dumps(dump))
    env = {"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
           "HOME": "/tmp"}
    out = subprocess.run(
        [sys.executable, "-m", "automerge_tpu.perf", "explain", "d",
         "--post-mortem", str(p), "--json"],
        capture_output=True, text=True, env=env, timeout=120)
    assert out.returncode == 0, out.stderr
    rep = json.loads(out.stdout)
    assert rep["causes"][0]["cause"] == "doc_frame_loss"
    # hot-list mode (no doc), plain rendering
    out = subprocess.run(
        [sys.executable, "-m", "automerge_tpu.perf", "explain",
         "--post-mortem", str(p)],
        capture_output=True, text=True, env=env, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "hot docs" in out.stdout
    # absent file: graceful exit 0 (verify.sh stage-2 contract)
    out = subprocess.run(
        [sys.executable, "-m", "automerge_tpu.perf", "explain",
         "--post-mortem", str(tmp_path / "missing.json")],
        capture_output=True, text=True, env=env, timeout=120)
    assert out.returncode == 0
    assert "nothing to read" in out.stdout
