"""Hypothesis fuzz for two previously-dark corners (VERDICT r4 #8):

1. Transit save/load round-trips under RANDOM conflict states. The
   reference pins that conflicts survive its transit save format
   (test/test.js:1107-1116, one hand-built case); here random multi-replica
   programs produce arbitrary nested/concurrent states and the law is that
   a transit round trip preserves document equality, the conflict table,
   and the engine state hash.

2. PerOpDiffStream under CONCURRENT admission gossip. The stream's fold
   lock (engine/diffs.py) serializes pull-apply-emit across transport
   threads; the law is that when several threads ingest interleaved
   changes into one rows-backend node, the stream's shadow opset ends at
   the node's exact state, every admitted change is folded exactly once,
   and the emitted record batches never interleave mid-fold.
"""

import threading

import numpy as np
import pytest

try:
    from hypothesis import HealthCheck, given, settings
    from hypothesis import strategies as st
except ImportError:  # pragma: no cover
    pytest.skip("hypothesis unavailable", allow_module_level=True)

import automerge_tpu as am
from automerge_tpu.engine.batchdoc import apply_batch, oracle_state

from tests.test_hypothesis_conformance import _instr, _run_program


def _hash_of(doc):
    changes = doc._doc.opset.get_missing_changes({})
    _, _, out = apply_batch([changes])
    return int(np.asarray(out["hash"])[0])


@settings(max_examples=25, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(st.lists(_instr, min_size=1, max_size=18))
def test_transit_roundtrip_preserves_random_conflict_states(instrs):
    merged = _run_program(instrs)
    blob = am.save_transit(merged)
    loaded = am.load_transit(blob)

    assert am.equals(merged, loaded)
    assert oracle_state(loaded) == oracle_state(merged)   # incl. conflicts
    assert dict(loaded._doc.opset.clock) == dict(merged._doc.opset.clock)
    assert _hash_of(loaded) == _hash_of(merged)
    # a second round trip is a fixpoint
    assert am.save_transit(loaded) == blob


@settings(max_examples=8, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(st.integers(min_value=2, max_value=4),
       st.integers(min_value=2, max_value=6),
       st.randoms(use_true_random=False))
def test_perop_stream_under_concurrent_admission_gossip(n_threads,
                                                        n_changes, rnd):
    from automerge_tpu.engine.diffs import PerOpDiffStream
    from automerge_tpu.sync.service import EngineDocSet

    node = EngineDocSet(backend="rows")
    node.add_doc("doc")

    batches: list[list] = []
    in_fold = threading.Event()
    overlapped = []

    def on_records(recs):
        # the fold lock must serialize callbacks: two emissions may never
        # be in flight at once
        if in_fold.is_set():
            overlapped.append(True)
        in_fold.set()
        batches.append(list(recs))
        in_fold.clear()

    stream = PerOpDiffStream(node, "doc", on_records)

    # per-thread actor keeps seqs dense per actor regardless of scheduling
    def writer(t):
        d = am.init(f"W{t}")
        for k in range(n_changes):
            d = am.change(d, lambda x, t=t, k=k: x.__setitem__(
                f"f{t}", k * 10 + t))
            chs = d._doc.opset.get_missing_changes({})
            node.apply_changes("doc", [chs[-1]])

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    order = list(range(n_threads))
    rnd.shuffle(order)
    for t in order:
        threads[t].start()
    for t in threads:
        t.join()
    node.flush()

    assert not overlapped, "diff emissions interleaved mid-fold"
    # the shadow opset converged to the node's exact clock and state
    assert dict(stream.opset.clock) == node.clock_of("doc")
    view = node.materialize("doc")["data"]
    for t in range(n_threads):
        assert view[f"f{t}"] == (n_changes - 1) * 10 + t
    # exactly-once: the stream folded every admitted change once
    folded = sum(c for c in stream.opset.clock.values())
    assert folded == n_threads * n_changes
    stream.close()
