"""Sequential (single-replica) behavior.

Ports the semantics of /root/reference/test/test.js 'sequential use' (7-533):
change blocks, root and nested maps, lists, frozen-snapshot enforcement.
"""

import pytest

import automerge_tpu as am
from automerge_tpu.core.ids import ROOT_ID


@pytest.fixture
def s1():
    return am.init()


class TestBasics:
    def test_initially_empty_map(self, s1):
        assert s1 == {}

    def test_does_not_mutate_old_snapshots(self, s1):
        s2 = am.change(s1, lambda d: d.__setitem__("foo", "bar"))
        assert "foo" not in s1
        assert s2["foo"] == "bar"

    def test_no_conflicts_on_repeated_assignment(self, s1):
        assert s1._conflicts == {}
        s1 = am.change(s1, "change", lambda d: d.__setitem__("foo", "one"))
        assert s1._conflicts == {}
        s1 = am.change(s1, "change", lambda d: d.__setitem__("foo", "two"))
        assert s1._conflicts == {}

    def test_root_object_id(self, s1):
        assert s1._object_id == ROOT_ID


class TestChanges:
    def test_groups_several_changes(self, s1):
        def cb(doc):
            doc["first"] = "one"
            assert doc["first"] == "one"
            doc["second"] = "two"
            assert doc == {"first": "one", "second": "two"}
        s2 = am.change(s1, "change message", cb)
        assert s1 == {}
        assert s2 == {"first": "one", "second": "two"}

    def test_snapshots_are_read_only(self, s1):
        s2 = am.change(s1, lambda d: d.__setitem__("foo", "bar"))
        with pytest.raises(TypeError):
            s2["foo"] = "lemon"
        with pytest.raises(TypeError):
            del s2["foo"]
        with pytest.raises(TypeError):
            s2.update({"x": 1})
        assert s2["foo"] == "bar"

    def test_repeated_read_and_write_within_block(self, s1):
        def cb(doc):
            doc["counter"] = 1
            assert doc["counter"] == 1
            doc["counter"] += 1
            doc["counter"] += 1
            assert doc["counter"] == 3
        s2 = am.change(s1, "change message", cb)
        assert s1 == {}
        assert s2 == {"counter": 3}

    def test_no_conflicts_on_same_field_multiple_writes_in_one_change(self, s1):
        def cb(doc):
            doc["counter"] = 1
            doc["counter"] += 1
            doc["counter"] += 1
        s1 = am.change(s1, "change message", cb)
        assert s1["counter"] == 3
        assert s1._conflicts == {}

    def test_unchanged_callback_returns_same_object(self, s1):
        s2 = am.change(s1, lambda d: None)
        assert s2 is s1

    def test_writing_existing_value_is_a_noop(self, s1):
        s1 = am.change(s1, lambda d: d.__setitem__("field", 123))
        s2 = am.change(s1, lambda d: d.__setitem__("field", 123))
        assert s2 is s1

    def test_resolving_a_conflict_is_not_a_noop(self, s1):
        s2 = am.merge(am.init(), s1)
        s1 = am.change(s1, lambda d: d.__setitem__("field", 123))
        s2 = am.change(s2, lambda d: d.__setitem__("field", 321))
        s1 = am.merge(s1, s2)
        assert list(s1._conflicts.keys()) == ["field"]
        resolved = am.change(s1, lambda d: d.__setitem__("field", s1["field"]))
        assert resolved is not s1
        assert resolved == {"field": s1["field"]}
        assert resolved._conflicts == {}

    def test_sanity_checks_arguments(self, s1):
        s1 = am.change(s1, lambda d: d.__setitem__("nested", {}))
        with pytest.raises(TypeError):
            am.change({}, lambda d: None)
        with pytest.raises(TypeError):
            am.change(s1["nested"], lambda d: None)

    def test_change_message_must_be_string(self, s1):
        with pytest.raises(TypeError):
            am.change(s1, 123, lambda d: None)

    def test_attribute_style_assignment(self, s1):
        s2 = am.change(s1, lambda d: setattr(d, "foo", "bar"))
        assert s2["foo"] == "bar"
        assert s2.foo == "bar"

    def test_empty_change(self, s1):
        s1 = am.change(s1, lambda d: d.__setitem__("field", 123))
        s2 = am.empty_change(s1, "empty!")
        assert s2 is not s1
        assert s2 == s1
        history = am.get_history(s2)
        assert history[-1].change["message"] == "empty!"
        assert history[-1].change["ops"] == []


class TestRootMap:
    def test_set_root_properties(self, s1):
        def cb(doc):
            doc["first"] = "one"
            doc["second"] = "two"
        s2 = am.change(s1, cb)
        assert s2 == {"first": "one", "second": "two"}

    def test_delete_root_property(self, s1):
        s1 = am.change(s1, lambda d: am.assign(d, {"a": 1, "b": 2}))
        s2 = am.change(s1, lambda d: d.__delitem__("a"))
        assert s2 == {"b": 2}
        assert s1 == {"a": 1, "b": 2}

    def test_delete_via_delattr(self, s1):
        s1 = am.change(s1, lambda d: setattr(d, "x", 1))
        s2 = am.change(s1, lambda d: delattr(d, "x"))
        assert s2 == {}

    def test_numeric_boolean_none_values(self, s1):
        def cb(doc):
            doc["int"] = 42
            doc["float"] = 3.5
            doc["bool"] = True
            doc["none"] = None
        s2 = am.change(s1, cb)
        assert s2 == {"int": 42, "float": 3.5, "bool": True, "none": None}

    def test_key_validation(self, s1):
        with pytest.raises(TypeError):
            am.change(s1, lambda d: d.__setitem__("", 1))
        with pytest.raises(TypeError):
            am.change(s1, lambda d: d.__setitem__("_x", 1))
        with pytest.raises(TypeError):
            am.change(s1, lambda d: d.__setitem__(7, 1))

    def test_unsupported_value_types(self, s1):
        with pytest.raises(TypeError):
            am.change(s1, lambda d: d.__setitem__("f", lambda: None))
        with pytest.raises(TypeError):
            am.change(s1, lambda d: d.__setitem__("f", object()))


class TestNestedMaps:
    def test_create_nested_map(self, s1):
        s2 = am.change(s1, lambda d: d.__setitem__("nested", {}))
        assert s2 == {"nested": {}}
        assert s2["nested"]._object_id != ROOT_ID

    def test_nested_map_with_contents(self, s1):
        s2 = am.change(s1, lambda d: d.__setitem__(
            "birds", {"wrens": 3, "sparrows": 15}))
        assert s2 == {"birds": {"wrens": 3, "sparrows": 15}}
        assert s2["birds"] == {"wrens": 3, "sparrows": 15}

    def test_deeply_nested(self, s1):
        def cb(doc):
            doc["a"] = {"b": {"c": {"d": "deep"}}}
        s2 = am.change(s1, cb)
        assert s2["a"]["b"]["c"]["d"] == "deep"

    def test_mutate_nested_map_in_later_change(self, s1):
        s1 = am.change(s1, lambda d: d.__setitem__("style", {"font": "Arial"}))
        s2 = am.change(s1, lambda d: d["style"].__setitem__("size", 12))
        assert s2 == {"style": {"font": "Arial", "size": 12}}
        assert s1 == {"style": {"font": "Arial"}}

    def test_delete_key_in_nested_map(self, s1):
        s1 = am.change(s1, lambda d: d.__setitem__("style", {"font": "Arial", "size": 12}))
        s2 = am.change(s1, lambda d: d["style"].__delitem__("size"))
        assert s2 == {"style": {"font": "Arial"}}

    def test_replace_nested_object(self, s1):
        s1 = am.change(s1, lambda d: d.__setitem__("a", {"x": 1}))
        s2 = am.change(s1, lambda d: d.__setitem__("a", {"y": 2}))
        assert s2 == {"a": {"y": 2}}

    def test_structural_sharing_of_unchanged_subtrees(self, s1):
        s1 = am.change(s1, lambda d: am.assign(d, {"a": {"x": 1}, "b": {"y": 2}}))
        s2 = am.change(s1, lambda d: d["a"].__setitem__("x", 99))
        # the untouched subtree keeps its identity (incremental cache)
        assert s2["b"] is s1["b"]
        assert s2["a"] is not s1["a"]


class TestLists:
    def test_create_list(self, s1):
        s2 = am.change(s1, lambda d: d.__setitem__("noodles", []))
        assert s2 == {"noodles": []}

    def test_list_with_contents(self, s1):
        s2 = am.change(s1, lambda d: d.__setitem__("noodles", ["udon", "soba"]))
        assert s2 == {"noodles": ["udon", "soba"]}
        assert s2["noodles"][0] == "udon"
        assert s2["noodles"][1] == "soba"
        assert len(s2["noodles"]) == 2

    def test_append(self, s1):
        s1 = am.change(s1, lambda d: d.__setitem__("noodles", ["udon"]))
        s2 = am.change(s1, lambda d: d["noodles"].append("soba"))
        assert s2 == {"noodles": ["udon", "soba"]}
        assert s1 == {"noodles": ["udon"]}

    def test_insert_at(self, s1):
        s1 = am.change(s1, lambda d: d.__setitem__("noodles", ["udon", "soba"]))
        s2 = am.change(s1, lambda d: d["noodles"].insert_at(1, "ramen"))
        assert s2 == {"noodles": ["udon", "ramen", "soba"]}

    def test_insert_python_semantics(self, s1):
        s1 = am.change(s1, lambda d: d.__setitem__("xs", [1, 3]))
        s2 = am.change(s1, lambda d: d["xs"].insert(1, 2))
        assert s2 == {"xs": [1, 2, 3]}

    def test_set_list_index(self, s1):
        s1 = am.change(s1, lambda d: d.__setitem__("xs", ["a", "b"]))
        s2 = am.change(s1, lambda d: d["xs"].__setitem__(1, "B"))
        assert s2 == {"xs": ["a", "B"]}

    def test_assign_one_past_end_inserts(self, s1):
        s1 = am.change(s1, lambda d: d.__setitem__("xs", ["a"]))
        s2 = am.change(s1, lambda d: d["xs"].__setitem__(1, "b"))
        assert s2 == {"xs": ["a", "b"]}

    def test_insert_past_end_raises(self, s1):
        s1 = am.change(s1, lambda d: d.__setitem__("xs", ["a"]))
        with pytest.raises(IndexError):
            am.change(s1, lambda d: d["xs"].__setitem__(5, "x"))

    def test_delete_at(self, s1):
        s1 = am.change(s1, lambda d: d.__setitem__("xs", ["a", "b", "c"]))
        s2 = am.change(s1, lambda d: d["xs"].delete_at(1))
        assert s2 == {"xs": ["a", "c"]}

    def test_del_item(self, s1):
        s1 = am.change(s1, lambda d: d.__setitem__("xs", ["a", "b", "c"]))
        s2 = am.change(s1, lambda d: d["xs"].__delitem__(0))
        assert s2 == {"xs": ["b", "c"]}

    def test_pop_push_shift_unshift(self, s1):
        s1 = am.change(s1, lambda d: d.__setitem__("xs", ["a", "b", "c"]))

        def cb(doc):
            assert doc["xs"].pop() == "c"
            assert doc["xs"].shift() == "a"
            doc["xs"].unshift("z")
            doc["xs"].push("d", "e")
        s2 = am.change(s1, cb)
        assert s2 == {"xs": ["z", "b", "d", "e"]}

    def test_splice(self, s1):
        s1 = am.change(s1, lambda d: d.__setitem__("xs", ["a", "b", "c", "d"]))

        def cb(doc):
            deleted = doc["xs"].splice(1, 2, "X")
            assert deleted == ["b", "c"]
        s2 = am.change(s1, cb)
        assert s2 == {"xs": ["a", "X", "d"]}

    def test_fill(self, s1):
        s1 = am.change(s1, lambda d: d.__setitem__("xs", [1, 2, 3, 4]))
        s2 = am.change(s1, lambda d: d["xs"].fill(0, 1, 3))
        assert s2 == {"xs": [1, 0, 0, 4]}

    def test_nested_objects_in_lists(self, s1):
        s2 = am.change(s1, lambda d: d.__setitem__(
            "todos", [{"title": "water plants", "done": False}]))
        assert s2 == {"todos": [{"title": "water plants", "done": False}]}
        s3 = am.change(s2, lambda d: d["todos"][0].__setitem__("done", True))
        assert s3 == {"todos": [{"title": "water plants", "done": True}]}

    def test_extend(self, s1):
        s1 = am.change(s1, lambda d: d.__setitem__("xs", [1]))
        s2 = am.change(s1, lambda d: d["xs"].extend([2, 3]))
        assert s2 == {"xs": [1, 2, 3]}

    def test_list_snapshot_read_only(self, s1):
        s2 = am.change(s1, lambda d: d.__setitem__("xs", [1, 2]))
        with pytest.raises(TypeError):
            s2["xs"].append(3)
        with pytest.raises(TypeError):
            s2["xs"][0] = 9


class TestCounterlikeReadback:
    def test_reads_see_prior_writes_in_same_block(self, s1):
        def cb(doc):
            doc["list"] = []
            doc["list"].append("a")
            assert doc["list"] == ["a"]
            assert len(doc["list"]) == 1
            doc["nested"] = {"x": 1}
            assert doc["nested"]["x"] == 1
            doc["nested"]["y"] = 2
            assert doc["nested"] == {"x": 1, "y": 2}
        s2 = am.change(s1, cb)
        assert s2 == {"list": ["a"], "nested": {"x": 1, "y": 2}}
