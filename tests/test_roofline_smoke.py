"""The tunnel-recovery hook runs profile_roofline.py the first time the
chip returns; this pins its plumbing (row-buffer build, chained kernel
jit, readback) via the --interpret-smoke flag so a latent bug cannot trip
the one recovery window. The smoke fails loudly if any probe is skipped."""

import json
import os
import subprocess
import sys


def test_roofline_interpret_smoke_runs_clean():
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.dirname(
             os.path.abspath(__file__))), "profile_roofline.py"),
         "--interpret-smoke"],
        capture_output=True, text=True, timeout=540)
    assert out.returncode == 0, out.stderr[-800:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["smoke"] is True and rec["backend"] == "cpu"
    assert len(rec["probes"]) == 2
    assert all("skipped" not in p for p in rec["probes"])
