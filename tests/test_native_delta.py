"""Native (C++) vs Python delta encoder parity.

The native encoder (native/deltaenc.cpp) must produce bit-identical delta
rows, content hashes, interning ids and mirror tables to the pure-Python
`ResidentDocSet._encode_delta` — state hashes and materialized documents of
a natively-ingested docset must equal the Python-ingested one on every
workload shape.
"""

import numpy as np
import pytest

import automerge_tpu as am
from automerge_tpu.engine.resident import ResidentDocSet
from automerge_tpu.native.delta import native_delta_available
from automerge_tpu.sync.frames import changes_to_columns, decode_frame, \
    encode_frame

pytestmark = pytest.mark.skipif(not native_delta_available(),
                                reason="native toolchain unavailable")


def rich_trace():
    d = am.change(am.init("A"), lambda d: am.assign(d, {
        "i": 7, "f": 3.25, "b": True, "s": "héllo\ud800", "big": 2 ** 70,
        "null": None, "neg": -1.5, "nest": {"deep": [1, "two", False]}}))
    d = am.change(d, lambda doc: doc.__delitem__("i"))
    d = am.change(d, lambda doc: doc.__setitem__("t", am.Text()))
    d = am.change(d, "msg", lambda doc: doc["t"].insert_at(0, *"abc"))
    e = am.merge(am.init("B"), d)
    e = am.change(e, lambda doc: doc["t"].delete_at(1))
    e = am.change(e, lambda doc: doc.__setitem__("s", "overwrite"))
    m = am.merge(d, e)
    return m._doc.opset.get_missing_changes({})


def concurrent_rounds():
    """Several delta rounds with queueing-prone ordering."""
    a = am.change(am.init("A"), lambda d: d.__setitem__("x", 1))
    b = am.merge(am.init("B"), a)
    rounds = []
    for r in range(4):
        a = am.change(a, lambda d, r=r: d.__setitem__("x", 10 + r))
        b = am.change(b, lambda d, r=r: d.__setitem__("y", 20 + r))
        rounds.append(a._doc.opset.get_missing_changes({}) +
                      b._doc.opset.get_missing_changes({}))
    return rounds


class TestNativeParity:
    def test_hash_and_state_parity_single_batch(self):
        chs = rich_trace()
        nat = ResidentDocSet(["d"], native=True)
        py = ResidentDocSet(["d"], native=False)
        nat.apply_changes({"d": chs})
        py.apply_changes({"d": chs})
        assert int(nat.reconcile()[0]) == int(py.reconcile()[0])
        assert nat.materialize("d") == py.materialize("d")

    def test_mirror_tables_match(self):
        chs = rich_trace()
        nat = ResidentDocSet(["d"], native=True)
        py = ResidentDocSet(["d"], native=False)
        nat.apply_changes({"d": chs})
        py.apply_changes({"d": chs})
        tn, tp = nat.tables[0], py.tables[0]
        assert tn.objects == tp.objects
        assert tn.fields == tp.fields
        assert tn.value_list == tp.value_list
        assert (tn.n_lists, tn.max_elems) == \
            (len(tp.list_rows), max(len(s) for s in tp.elem_slots.values()))

    def test_incremental_rounds_parity(self):
        """Deltas across rounds — persistent C++ tables must stay aligned
        with the Python ones, including value/field reuse across rounds."""
        nat = ResidentDocSet(["d"], native=True)
        py = ResidentDocSet(["d"], native=False)
        seen_clock: dict = {}
        doc = am.change(am.init("A"), lambda d: d.__setitem__("xs", []))
        for r in range(5):
            doc = am.change(doc, lambda d, r=r: d["xs"].insert_at(
                len(d["xs"]), f"item{r}"))
            doc = am.change(doc, lambda d, r=r: d.__setitem__("n", r % 2))
            delta = doc._doc.opset.get_missing_changes(seen_clock)
            seen_clock = dict(doc._doc.opset.clock)
            hn = nat.apply_and_reconcile({"d": delta})
            hp = py.apply_and_reconcile({"d": delta})
            assert int(hn[0]) == int(hp[0]), f"round {r}"
        assert nat.materialize("d") == py.materialize("d")

    def test_out_of_order_queueing_parity(self):
        """Changes delivered out of causal order exercise the queue path
        (admission releasing changes from earlier frames in later calls)."""
        chs = rich_trace()
        nat = ResidentDocSet(["d"], native=True)
        py = ResidentDocSet(["d"], native=False)
        # deliver the tail first (buffers), then the head (releases)
        for rs in (chs[3:], chs[:3], chs):  # last round = duplicates
            nat.apply_changes({"d": rs})
            py.apply_changes({"d": rs})
        assert int(nat.reconcile()[0]) == int(py.reconcile()[0])
        assert nat.materialize("d") == py.materialize("d")

    def test_columns_ingress_equals_change_ingress(self):
        """apply_columns(frame) == apply_changes(changes) on the native
        path, including through a real frame byte round-trip."""
        chs = rich_trace()
        via_cols = ResidentDocSet(["d"], native=True)
        via_chs = ResidentDocSet(["d"], native=True)
        via_cols.apply_columns({"d": decode_frame(encode_frame(chs))})
        via_chs.apply_changes({"d": chs})
        assert int(via_cols.reconcile()[0]) == int(via_chs.reconcile()[0])
        assert via_cols.materialize("d") == via_chs.materialize("d")

    def test_admitted_refs_materialize(self):
        """last_admitted lazy refs rebuild the exact Change objects."""
        chs = rich_trace()
        nat = ResidentDocSet(["d"], native=True)
        nat.apply_columns({"d": changes_to_columns(chs)})
        admitted = nat.last_admitted["d"]
        assert [r.change() for r in admitted] == chs

    def test_multi_round_batches(self):
        rounds = concurrent_rounds()
        nat = ResidentDocSet(["d"], native=True)
        py = ResidentDocSet(["d"], native=False)
        for rs in rounds:
            hn = nat.apply_and_reconcile({"d": rs})
            hp = py.apply_and_reconcile({"d": rs})
            assert int(hn[0]) == int(hp[0])

    def test_multi_doc_parity(self):
        docs = {}
        for i in range(6):
            d = am.change(am.init("A"), lambda x, i=i: am.assign(
                x, {"n": i, "tag": f"t{i % 2}", "f": i / 2}))
            docs[f"d{i}"] = d._doc.opset.get_missing_changes({})
        ids = sorted(docs)
        nat = ResidentDocSet(ids, native=True)
        py = ResidentDocSet(ids, native=False)
        nat.apply_changes(docs)
        py.apply_changes(docs)
        assert np.array_equal(nat.reconcile(), py.reconcile())
